"""Gossipsub mesh behavior: control codec, graft/prune bounds, IHAVE/
IWANT recovery, O(D) egress — the properties flood-publish lacks.

reference: networking/p2p/.../gossip/config/GossipConfig.java:51-163
(D/D_low/D_high/D_lazy/heartbeat/mcache parameters).
"""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio
import random

import pytest

from teku_tpu.networking import gossip as G
from teku_tpu.node.gossip import TopicHandler, ValidationResult


class _AcceptHandler(TopicHandler):
    def __init__(self):
        self.received = []

    async def handle_message(self, data: bytes) -> ValidationResult:
        self.received.append(data)
        return ValidationResult.ACCEPT


class _FakePeer:
    """Transport-free peer: records every gossip frame sent to it."""

    def __init__(self, nid: int):
        self.node_id = bytes([nid]) * 32
        self.connected = True
        self.frames = []
        self.bytes_out = {}

    async def send_frame(self, kind: int, payload: bytes) -> None:
        self.frames.append((kind, payload))

    def close(self):
        self.connected = False


class _FakeNet:
    def __init__(self, n_peers: int):
        self.peers = [_FakePeer(i + 1) for i in range(n_peers)]
        self.on_gossip = None
        self.on_peer_disconnected = None


def _router(n_peers: int, topic="beacon_block", subscribe_peers=True):
    net = _FakeNet(n_peers)
    router = G.TcpGossipNetwork(net, rng=random.Random(42))
    handler = _AcceptHandler()
    router.subscribe(topic, handler)
    if subscribe_peers:
        for p in net.peers:
            router._peer_topics[p.node_id] = {topic}
    return net, router, handler


def _data_frames(peer):
    return [f for _, f in peer.frames if f and f[0] == G.ENV_DATA]


def _control_frames(peer):
    return [f for _, f in peer.frames if f and f[0] == G.ENV_CONTROL]


def _decoded_controls(peer):
    return [G.decode_control(f[1:]) for f in _control_frames(peer)]


def _got_graft(peer, topic):
    return any(topic in graft
               for _, graft, _, _, _ in _decoded_controls(peer))


def _got_ihave(peer):
    return [ih for _, _, _, ihave, _ in _decoded_controls(peer)
            for ih in ihave]


def test_control_codec_roundtrip():
    frame = G.encode_control(
        subs=[(True, "a"), (False, "bb")], graft=["topic_x"],
        prune=["topic_y", "z"],
        ihave=[("t", [b"\x01" * 20, b"\x02" * 20])],
        iwant=[b"\x03" * 20])
    assert frame[0] == G.ENV_CONTROL
    subs, graft, prune, ihave, iwant = G.decode_control(frame[1:])
    assert subs == [(True, "a"), (False, "bb")]
    assert graft == ["topic_x"] and prune == ["topic_y", "z"]
    assert ihave == [("t", [b"\x01" * 20, b"\x02" * 20])]
    assert iwant == [b"\x03" * 20]
    with pytest.raises(ValueError):
        G.decode_control(frame[1:-3])    # truncated


def test_spec_message_id_altair_shape():
    import hashlib
    import struct
    topic = "/eth2/abcd1234/beacon_block/ssz_snappy"
    data = b"payload"
    tb = topic.encode()
    expected = hashlib.sha256(
        b"\x01\x00\x00\x00" + struct.pack("<Q", len(tb)) + tb
        + data).digest()[:20]
    assert G.spec_msg_id(topic, data) == expected


def test_heartbeat_grafts_to_d_and_bounds_at_d_high():
    async def run():
        net, router, _ = _router(20)
        router.heartbeat()
        mesh = router._mesh["beacon_block"]
        assert len(mesh) == G.D             # grafted up from empty
        await asyncio.sleep(0)              # flush control sends
        grafted = [p for p in net.peers if _got_graft(p, "beacon_block")]
        assert len(grafted) == G.D
        # overstuffed mesh prunes down to D
        mesh.clear()
        mesh.update(net.peers[:G.D_HIGH + 3])
        router.heartbeat()
        assert len(mesh) == G.D
    asyncio.run(run())


def test_publish_egress_is_mesh_not_flood():
    async def run():
        net, router, _ = _router(20)
        router.heartbeat()                  # fill the mesh
        await router.publish("beacon_block", b"block-bytes")
        receivers = [p for p in net.peers if _data_frames(p)]
        # O(D), not O(peers): 20 connected, only the mesh gets data
        assert len(receivers) == G.D
        assert router.data_frames_sent == G.D
    asyncio.run(run())


def test_publish_falls_back_to_fanout_without_mesh():
    async def run():
        net, router, _ = _router(20)
        # no heartbeat yet → mesh empty → fanout to D topic peers
        await router.publish("beacon_block", b"x")
        receivers = [p for p in net.peers if _data_frames(p)]
        assert len(receivers) == G.D
    asyncio.run(run())


def test_forward_only_after_accept_and_mesh_only():
    async def run():
        net, router, handler = _router(20)
        router.heartbeat()
        sender = next(iter(router._mesh["beacon_block"]))
        frame = router._encode_data("beacon_block", b"msg")
        await router._on_gossip(sender, frame)
        assert handler.received == [b"msg"]
        # forwarded into the mesh minus the sender
        receivers = [p for p in net.peers if _data_frames(p)]
        assert sender not in receivers
        assert len(receivers) == G.D - 1
        # duplicate suppressed: no re-forward, handler not re-invoked
        before = router.data_frames_sent
        await router._on_gossip(sender, frame)
        assert handler.received == [b"msg"]
        assert router.data_frames_sent == before
    asyncio.run(run())


def test_heartbeat_emits_ihave_to_lazy_peers():
    async def run():
        net, router, _ = _router(20)
        router.heartbeat()
        for p in net.peers:
            p.frames.clear()
        await router.publish("beacon_block", b"recent-message")
        router.heartbeat()
        await asyncio.sleep(0)
        mesh = router._mesh["beacon_block"]
        lazy = [p for p in net.peers if p not in mesh and _got_ihave(p)]
        assert 0 < len(lazy) <= G.D_LAZY
        assert not any(_got_ihave(p) for p in mesh)
        # the IHAVE advertises the published message id
        mid = G.spec_msg_id("beacon_block", b"recent-message")
        assert any(mid in mids for _, mids in _got_ihave(lazy[0]))
    asyncio.run(run())


def test_ihave_triggers_iwant_and_serves_from_mcache():
    async def run():
        net, router, handler = _router(4)
        peer = net.peers[0]
        mid = G.spec_msg_id("beacon_block", b"missing-data")
        # peer advertises a message we don't have → we IWANT it
        await router._on_gossip(peer, G.encode_control(
            ihave=[("beacon_block", [mid])]))
        await asyncio.sleep(0)
        ctl = _control_frames(peer)
        assert ctl, "no IWANT sent"
        _, _, _, _, iwant = G.decode_control(ctl[-1][1:])
        assert iwant == [mid]
        # now the reverse: we HAVE a message, peer IWANTs it
        await router.publish("beacon_block", b"cached-data")
        cached_mid = G.spec_msg_id("beacon_block", b"cached-data")
        peer.frames.clear()
        await router._on_gossip(peer, G.encode_control(
            iwant=[cached_mid]))
        data = _data_frames(peer)
        assert len(data) == 1
        assert router.iwant_served == 1
    asyncio.run(run())


def test_unsubscribed_graft_gets_pruned_back():
    async def run():
        net, router, _ = _router(3)
        peer = net.peers[0]
        await router._on_gossip(peer, G.encode_control(
            graft=["unknown_topic"]))
        await asyncio.sleep(0)
        _, _, prune, _, _ = G.decode_control(
            _control_frames(peer)[-1][1:])
        assert prune == ["unknown_topic"]
        assert peer not in router._mesh.get("unknown_topic", set())
    asyncio.run(run())


def test_low_score_peer_refused_mesh_admission():
    async def run():
        net, router, _ = _router(3)
        peer = net.peers[0]
        # invalid deliveries drive the topic score negative (P4)
        router.scoring.on_invalid(peer.node_id, "beacon_block")
        assert router.scoring.score(peer.node_id) < G.GRAFT_SCORE_FLOOR
        await router._on_gossip(peer, G.encode_control(
            graft=["beacon_block"]))
        assert peer not in router._mesh["beacon_block"]
        # heartbeat grafting also skips it
        router.heartbeat()
        assert peer not in router._mesh["beacon_block"]
    asyncio.run(run())


def test_disconnect_cleans_mesh_and_scores_decay():
    async def run():
        net, router, _ = _router(10)
        router.heartbeat()
        gone = next(iter(router._mesh["beacon_block"]))
        await router._on_peer_gone(gone)
        assert gone not in router._mesh["beacon_block"]
        assert gone.node_id not in router._peer_topics
        # tenure ended; no counters -> score back to neutral
        assert router.scoring.score(gone.node_id) == 0.0
        # counters decay back to zero over decay passes (a node id
        # outside the network, so no mesh tenure credit interferes)
        nid = b"\xaa" * 32
        router.scoring.on_invalid(nid, "beacon_block")
        assert router.scoring.score(nid) < 0
        for _ in range(120):
            router.scoring.decay()
        assert router.scoring.score(nid) == 0.0
    asyncio.run(run())


@pytest.mark.slow
def test_sixteen_node_tcp_propagation_o_of_d_egress():
    """16 real-TCP routers, full peer graph: a published message
    reaches everyone (mesh push + IHAVE/IWANT recovery) while the
    publisher's gossip egress stays O(D), not O(peers)."""
    from teku_tpu.networking.transport import NetworkConfig, P2PNetwork

    N = 16
    TOPIC = "bench_topic"

    async def run():
        nets, routers, handlers = [], [], []
        for i in range(N):
            net = P2PNetwork(NetworkConfig(port=0), b"\x11\x22\x33\x44")
            router = G.TcpGossipNetwork(net, rng=random.Random(i))
            handler = _AcceptHandler()
            router.subscribe(TOPIC, handler)
            await net.start()
            nets.append(net)
            routers.append(router)
            handlers.append(handler)
        # announce-on-connect, as NetworkedNode wires it
        for net, router in zip(nets, routers):
            async def _hook(peer, _r=router):
                _r.announce_subscriptions(peer)
            net.on_peer_connected = _hook
        try:
            # full graph: every pair connected (worst case for flood)
            for i in range(N):
                for j in range(i + 1, N):
                    await nets[i].connect("127.0.0.1", nets[j].port)
            await asyncio.sleep(0.1)        # subscriptions propagate
            for router in routers:
                router.heartbeat()          # meshes form
            await asyncio.sleep(0.1)
            payload = b"\xab" * 2048
            await routers[0].publish(TOPIC, payload)
            # eager push floods the overlapping meshes quickly; run
            # heartbeats until IHAVE/IWANT patches any remaining gaps
            for _ in range(10):
                await asyncio.sleep(0.05)
                for router in routers:
                    router.heartbeat()
                if all(h.received for h in handlers[1:]):
                    break
            await asyncio.sleep(0.2)
            # every node except the publisher (no local loopback — same
            # semantics as the in-memory devnet bus) got the message
            got = sum(1 for h in handlers[1:] if h.received)
            assert got == N - 1, f"only {got}/{N - 1} received"
            # the publisher pushed data to its mesh only: O(D) frames,
            # where flood would have been N-1=15 with D=8
            assert routers[0].data_frames_sent <= G.D_HIGH
            from teku_tpu.networking.transport import KIND_GOSSIP
            data_egress = sum(p.bytes_out.get(KIND_GOSSIP, 0)
                              for p in nets[0].peers)
            flood_egress = len(payload) * (N - 1)
            assert data_egress < flood_egress
        finally:
            for router in routers:
                await router.stop()
            for net in nets:
                await net.stop()
    asyncio.run(run())


def test_repeat_iwant_not_served_twice_and_costs_score():
    async def run():
        net, router, _ = _router(3)
        peer = net.peers[0]
        await router.publish("beacon_block", b"amplify-me")
        mid = G.spec_msg_id("beacon_block", b"amplify-me")
        peer.frames.clear()              # drop the publish fanout frame
        await router._on_gossip(peer, G.encode_control(iwant=[mid]))
        assert len(_data_frames(peer)) == 1
        # drive the behaviour penalty past its tolerance threshold:
        # every repeat ask accrues P7, squared above the threshold
        for _ in range(40):
            await router._on_gossip(peer, G.encode_control(iwant=[mid]))
        assert len(_data_frames(peer)) == 1          # not re-served
        assert router.scoring.score(peer.node_id) < 0
    asyncio.run(run())


def test_mcache_per_topic_index_and_eviction():
    mc = G.MessageCache(history=3, gossip=2)
    mc.put(b"\x01" * 20, "a", b"da")
    mc.put(b"\x02" * 20, "b", b"db")
    assert mc.gossip_ids("a") == [b"\x01" * 20]
    assert mc.get(b"\x02" * 20) == ("b", b"db")
    mc.shift()
    mc.shift()
    assert mc.gossip_ids("a") == []       # out of the gossip windows
    assert mc.get(b"\x01" * 20) is not None   # still IWANT-servable
    mc.shift()
    assert mc.get(b"\x01" * 20) is None   # evicted from history
