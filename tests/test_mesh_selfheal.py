"""Mesh self-healing: shard-level fault isolation, device ejection,
reshape-with-RTO instead of the whole-mesh oracle cliff.

Three layers:

- host-side unit tests of the keyed fault site, the per-device health
  ledger and the MeshHealer state machine over a FAKE backend world
  (eject/reshape/readmit, veto, unattributed failures, shrink-to-zero);
- the doctor's ``mesh_degraded`` / ``mesh_flap`` findings and the
  capacity model's topology retirement;
- the CHAOS ACCEPTANCE test on the real 8-virtual-device mesh: a
  timed ``bls.mesh_shard`` fault kills one chip mid-serving; the REAL
  loader wiring (GuardedBls12381 + make_mesh_healer) must eject
  exactly that device, reshape to 4, keep serving on-device with zero
  failed in-flight verifications and verdicts bit-identical to the
  oracle, then readmit and grow back to 8 — with the whole cycle
  visible in flight events, ``bls_mesh_reshape_total``, the
  supervisor/readiness mesh snapshot, the dispatch ledger's epoch
  stamps, and a doctor finding citing the killing dispatch.

Compile budget: the acceptance test reuses the SAME committee grid
shape (16 lanes, min_bucket 8) as tests/test_mesh_grouped.py for the
8-shard kernel, and pays one small 4-shard serving shape plus the
tiny reshape-warm shape (TEKU_TPU_MESH_WARM_BATCH=1).
"""

import threading
import time

import pytest

import jax

from teku_tpu import parallel
from teku_tpu.crypto.bls import keygen
from teku_tpu.crypto.bls.pure_impl import PureBls12381
from teku_tpu.infra import capacity, dispatchledger, doctor, faults
from teku_tpu.infra import flightrecorder
from teku_tpu.infra.metrics import GLOBAL_REGISTRY, MetricsRegistry
from teku_tpu.infra.supervisor import BackendSupervisor, CircuitBreaker
from teku_tpu.parallel import selfheal
from teku_tpu.parallel.selfheal import (DeviceHealthLedger,
                                        InstallVetoError, MeshHealer)

pytest_plugins: list = []


@pytest.fixture(autouse=True)
def _restore_global_topology_filter():
    """The chaos tests drive the REAL self-heal path, which retires
    latency series on the process-global capacity model and installs
    its live-topology filter.  Left in place, the filter silently
    drops every later non-mesh test's capacity samples in the same
    process (test_msm's per-path latency-series assertions were the
    first to notice)."""
    yield
    capacity.TELEMETRY.latency.clear_topology_filter()


def _wait(predicate, timeout_s=10.0, what="condition"):
    t0 = time.monotonic()
    while not predicate():
        if time.monotonic() - t0 > timeout_s:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.02)


# --------------------------------------------------------------------------
# keyed fault site
# --------------------------------------------------------------------------

def test_keyed_faults_scope_to_named_members():
    f = faults.inject("t.keyed", faults.Raise(RuntimeError("sick"),
                                              key="dev3"))
    try:
        faults.check("t.keyed")                  # keyless call: no fire
        faults.check("t.keyed", keys=("dev1",))  # wrong member: no fire
        assert f.fired == 0
        with pytest.raises(RuntimeError):
            faults.check("t.keyed", keys=("dev1", "dev3"))
        assert f.fired == 1
    finally:
        faults.clear("t.keyed")
    # keyless faults keep firing everywhere (backward compatibility)
    f2 = faults.inject("t.keyed", faults.Raise(RuntimeError("x")))
    try:
        with pytest.raises(RuntimeError):
            faults.check("t.keyed")
        with pytest.raises(RuntimeError):
            faults.check("t.keyed", keys=("anything",))
        assert f2.fired == 2
    finally:
        faults.clear("t.keyed")


# --------------------------------------------------------------------------
# per-device health ledger
# --------------------------------------------------------------------------

def test_device_health_ledger_trip_and_readmit():
    led = DeviceHealthLedger(["d0", "d1", "d2"], trip_threshold=2)
    assert led.record_failure(1, "err") is False   # 1 < threshold
    assert led.record_failure(1, "err") is True    # trips
    led.eject(1)
    assert led.live() == [0, 2]
    assert led.ejected() == [1]
    # success resets the consecutive count for live devices
    led.record_failure(0, "blip")
    led.record_success(0)
    assert led.record_failure(0, "blip") is False
    # readmit restores and clears the streak
    assert led.readmit(1) is True
    assert led.live() == [0, 1, 2]
    assert led.record_failure(1, "err") is False
    snap = led.snapshot()
    assert snap["trip_threshold"] == 2
    assert snap["devices"][1]["ejects_total"] == 1


# --------------------------------------------------------------------------
# MeshHealer over a fake world
# --------------------------------------------------------------------------

def _fake_world(n=8, **healer_kw):
    installs: list = []
    recorder = flightrecorder.FlightRecorder(
        capacity=256, registry=MetricsRegistry())

    def probe(i):
        faults.check(selfheal.FAULT_SITE, keys=(f"fd{i}",))

    kw = dict(trip_threshold=1, probe_deadline_s=1.0, reprobe_s=0.05)
    kw.update(healer_kw)
    healer = MeshHealer(
        [f"fd{i}" for i in range(n)], probe=probe,
        make_backend=lambda live: ("backend", live) if live else None,
        install=lambda be, live, epoch: installs.append(
            (be, live, epoch)),
        recorder=recorder, **kw)
    return healer, installs, recorder


def test_healer_ejects_reshapes_and_grows_back():
    healer, installs, recorder = _fake_world()
    faults.inject(selfheal.FAULT_SITE,
                  faults.Raise(RuntimeError("sick"), key="fd3"))
    try:
        healer.on_dispatch_failure(error="dispatch died",
                                   timeout=True, trace_id="tr-kill")
        _wait(lambda: len(installs) >= 1, what="shrink install")
        be, live, epoch = installs[-1]
        # largest surviving pow-2 subset in original device order
        assert live == (0, 1, 2, 4)
        assert epoch == 1
        assert healer.last_recovery_s is not None
        events = {e["kind"]: e for e in recorder.snapshot()}
        assert events["mesh_eject"]["device"] == "fd3"
        # the triggering dispatch's trace id rides the events
        assert events["mesh_eject"]["trace_id"] == "tr-kill"
        assert events["mesh_reshape"]["direction"] == "shrink"
        assert events["mesh_reshape"]["to_devices"] == 4
        assert events["mesh_reshape"]["configured"] == 8
    finally:
        faults.clear(selfheal.FAULT_SITE)
    # fault cleared: the background reprobe readmits and grows back
    _wait(lambda: len(installs) >= 2, what="grow install")
    be, live, epoch = installs[-1]
    assert live == tuple(range(8))
    assert healer.reshapes == {"shrink": 1, "grow": 1}
    kinds = [e["kind"] for e in recorder.snapshot()]
    assert "mesh_readmit" in kinds
    healer.close()


def test_healer_unattributed_failure_does_not_eject():
    healer, installs, recorder = _fake_world()
    # no fault armed: every isolation probe passes — the collective
    # failure stays the backend breaker's problem
    healer.on_dispatch_failure(error="host-side blip")
    time.sleep(0.3)
    assert installs == []
    assert healer.live_devices == tuple(range(8))
    kinds = [e["kind"] for e in recorder.snapshot()]
    assert "mesh_heal_unattributed" in kinds
    assert "mesh_eject" not in kinds
    healer.close()


def test_healer_warm_veto_blocks_install():
    def veto(_backend, _live):
        raise InstallVetoError("wrong verdict on known input")

    healer, installs, recorder = _fake_world(warm=veto)
    faults.inject(selfheal.FAULT_SITE,
                  faults.Raise(RuntimeError("sick"), key="fd0"))
    try:
        healer.on_dispatch_failure(error="x")
        _wait(lambda: any(e["kind"] == "mesh_reshape_vetoed"
                          for e in recorder.snapshot()),
              what="veto event")
        assert installs == []          # never installed
        assert healer.live_devices == tuple(range(8))
    finally:
        faults.clear(selfheal.FAULT_SITE)
        healer.close()


def test_healer_failed_grow_rolls_readmit_back():
    """A readmitted device whose grow reshape VETOES must go back to
    EJECTED — no install happened, so exiting the reprobe loop there
    would leave the mesh silently stuck below width while the ledger
    claims recovery.  The rollback is not a new flap (eject count
    unchanged), and once the veto clears the retry grows back."""
    state = {"grow_veto": True}

    def warm(_backend, live):
        if len(live) == 8 and state["grow_veto"]:
            raise InstallVetoError("grow verdicts untrusted")

    healer, installs, recorder = _fake_world(warm=warm)
    faults.inject(selfheal.FAULT_SITE,
                  faults.Raise(RuntimeError("sick"), key="fd3"))
    try:
        healer.on_dispatch_failure(error="x")
        _wait(lambda: len(installs) >= 1, what="shrink install")
    finally:
        faults.clear(selfheal.FAULT_SITE)
    # reprobe readmits -> grow warm VETOES -> readmit rolled back
    _wait(lambda: any(e["kind"] == "mesh_reshape_vetoed"
                      for e in recorder.snapshot()), what="grow veto")
    _wait(lambda: healer.ledger.ejected() == [3], timeout_s=5.0,
          what="readmit rollback")
    assert len(installs) == 1              # the grow never installed
    assert healer.ledger.eject_count(3) == 1   # rollback != new flap
    # veto clears: the NEXT reprobe retries and the mesh recovers
    state["grow_veto"] = False
    _wait(lambda: len(installs) >= 2
          and installs[-1][1] == tuple(range(8)), what="grow retry")
    healer.close()


def test_healer_reconciles_failed_shrink_install():
    """A shrink whose INSTALL raised must be retried by the reprobe
    loop's reconcile pass: the heal path alone would strand the
    wedged full-width mesh (later sweeps find the sick device already
    ejected and report unattributed, and nothing else retries)."""
    calls = {"n": 0}
    installs: list = []
    recorder = flightrecorder.FlightRecorder(
        capacity=256, registry=MetricsRegistry())

    def probe(i):
        faults.check(selfheal.FAULT_SITE, keys=(f"fd{i}",))

    def install(be, live, epoch):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient install failure")
        installs.append((be, live, epoch))

    healer = MeshHealer(
        [f"fd{i}" for i in range(8)], probe=probe,
        make_backend=lambda live: ("backend", live) if live else None,
        install=install, trip_threshold=1, probe_deadline_s=1.0,
        reprobe_s=0.05, recorder=recorder)
    faults.inject(selfheal.FAULT_SITE,
                  faults.Raise(RuntimeError("sick"), key="fd3"))
    try:
        healer.on_dispatch_failure(error="x")
        _wait(lambda: any(i[1] == (0, 1, 2, 4) for i in installs),
              what="reconciled shrink install")
        assert healer.live_devices == (0, 1, 2, 4)
    finally:
        faults.clear(selfheal.FAULT_SITE)
        healer.close()


def test_healer_shrinks_through_one_to_zero():
    """4 -> 2 -> 1 (single-device) -> 0 (oracle last resort): the
    capacity steps down pow-2 at a time and install(None) marks the
    end of the device road."""
    healer, installs, recorder = _fake_world(n=2)
    faults.inject(selfheal.FAULT_SITE,
                  faults.Raise(RuntimeError("s0"), key="fd0"))
    try:
        healer.on_dispatch_failure(error="x")
        _wait(lambda: len(installs) >= 1, what="shrink to 1")
        assert installs[-1][0] == ("backend", (1,))
        assert installs[-1][1] == (1,)
        faults.inject(selfheal.FAULT_SITE,
                      faults.Raise(RuntimeError("s1"), key="fd1"))
        healer.on_dispatch_failure(error="y")
        _wait(lambda: len(installs) >= 2, what="shrink to 0")
        assert installs[-1][0] is None
        assert installs[-1][1] == ()
    finally:
        faults.clear(selfheal.FAULT_SITE)
        healer.close()


def test_healer_probe_deadline_catches_hangs():
    healer, installs, recorder = _fake_world(
        probe_deadline_s=0.3)
    faults.inject(selfheal.FAULT_SITE,
                  faults.Hang(5.0, key="fd2"))
    try:
        healer.on_dispatch_failure(error="wedge", timeout=True)
        _wait(lambda: len(installs) >= 1, timeout_s=5.0,
              what="hang-attributed shrink")
        assert 2 not in installs[-1][1]
        ev = [e for e in recorder.snapshot()
              if e["kind"] == "mesh_eject"][0]
        assert "deadline" in ev["probe_error"]
    finally:
        faults.clear(selfheal.FAULT_SITE)
        healer.close()


# --------------------------------------------------------------------------
# doctor findings + capacity topology retirement
# --------------------------------------------------------------------------

def test_doctor_mesh_degraded_and_flap_findings():
    events = [
        {"seq": 1, "kind": "mesh_eject", "device": "d3",
         "trace_id": "tr-kill"},
        {"seq": 2, "kind": "mesh_reshape", "direction": "shrink",
         "from_devices": 8, "to_devices": 4, "configured": 8,
         "epoch": 1, "recovery_s": 2.5, "trace_id": "tr-kill"},
        {"seq": 3, "kind": "mesh_readmit", "device": "d3"},
        {"seq": 4, "kind": "mesh_reshape", "direction": "grow",
         "from_devices": 4, "to_devices": 8, "configured": 8,
         "epoch": 2},
        {"seq": 5, "kind": "mesh_eject", "device": "d3",
         "trace_id": "tr-kill2"},
        {"seq": 6, "kind": "mesh_reshape", "direction": "shrink",
         "from_devices": 8, "to_devices": 4, "configured": 8,
         "epoch": 3, "recovery_s": 2.1, "trace_id": "tr-kill2"},
    ]
    records = [{"seq": 9, "trace_ids": ["tr-kill2"],
                "shape": "16x1@m8", "mesh": {"devices": 8}}]
    diag = doctor.diagnose(records, flight_events=events)
    by_kind = {f["kind"]: f for f in diag["findings"]}
    deg = by_kind["mesh_degraded"]
    assert deg["metrics"]["live_devices"] == 4
    assert deg["metrics"]["configured_devices"] == 8
    # the finding cites the ejection event AND the killing dispatch
    cited_kinds = {e.get("kind") for e in deg["evidence"]
                   if e["type"] == "flight_event"}
    assert "mesh_eject" in cited_kinds
    assert any(e["type"] == "dispatch" and e["seq"] == 9
               for e in deg["evidence"])
    flap = by_kind["mesh_flap"]
    assert flap["metrics"]["by_device"] == {"d3": 2}
    assert not diag["healthy"]
    # text rendering never crashes on the new finding kinds
    assert "mesh_degraded" in doctor.render_text(diag)


def test_doctor_mesh_degraded_survives_flight_ring_eviction():
    """A long-degraded mesh must stay diagnosable after its
    eject/reshape events rolled off the bounded flight ring: the
    supervisor's mesh snapshot (readiness ``backend.mesh.self_heal``)
    is the authoritative CURRENT state — same bug class PR 11 fixed
    for brownout with the admission snapshot."""
    mesh = {"devices": ["d0", "d1", "d2", "d4"], "n_devices": 4,
            "axis": "dp",
            "self_heal": {"configured": 8, "live": 4, "epoch": 3,
                          "ejected": ["d3"]}}
    diag = doctor.diagnose([], flight_events=[], mesh=mesh)
    deg = [f for f in diag["findings"] if f["kind"] == "mesh_degraded"]
    assert deg, "snapshot-only degradation missed"
    assert deg[0]["metrics"]["live_devices"] == 4
    assert deg[0]["metrics"]["configured_devices"] == 8
    # and a full-width snapshot is healthy
    mesh["self_heal"] = {"configured": 8, "live": 8, "epoch": 4,
                         "ejected": []}
    diag = doctor.diagnose([], flight_events=[], mesh=mesh)
    assert not any(f["kind"] == "mesh_degraded"
                   for f in diag["findings"])


def test_doctor_full_width_mesh_is_not_degraded():
    events = [
        {"seq": 1, "kind": "mesh_reshape", "direction": "grow",
         "from_devices": 4, "to_devices": 8, "configured": 8,
         "epoch": 2},
    ]
    diag = doctor.diagnose([], flight_events=events)
    assert "mesh_degraded" not in {f["kind"] for f in diag["findings"]}
    assert "mesh_flap" not in {f["kind"] for f in diag["findings"]}


def test_capacity_retires_dead_topology_series():
    model = capacity.ShapeLatencyModel(registry=MetricsRegistry())
    model.observe("32x1@m8", "vpu", 0.004)
    model.observe("16x1@m8", "vpu", 0.003)
    model.observe("32x1", "vpu", 0.010)
    # mesh shrank to 4: the old @m8 series and the single-device
    # series must stop informing the admission planner
    model.observe("32x1@m4", "vpu", 0.008)
    dropped = model.retire_mesh_shapes(4)
    assert dropped == 3
    assert set(model.snapshot()) == {"32x1@m4"}
    assert model.latency_for_lanes(32) == pytest.approx(0.008)
    # a LATE observe from a dispatch that completed on the old plan
    # (the hot-swap lets old-pair dispatches finish after the swap)
    # must NOT resurrect the retired series
    model.observe("32x1@m8", "vpu", 0.004)
    model.observe("32x1", "vpu", 0.010)
    assert set(model.snapshot()) == {"32x1@m4"}
    assert model.latency_for_lanes(32) == pytest.approx(0.008)
    # shrink to single-device: every mesh family goes, and the
    # single-device family records again
    assert model.retire_mesh_shapes(0) == 1
    model.observe("32x1", "vpu", 0.010)
    model.observe("32x1@m4", "vpu", 0.008)     # late m4 straggler
    assert set(model.snapshot()) == {"32x1"}
    # retired shapes freed their slot in the bounded shape set
    assert model.latency_for_lanes(16) is None


# --------------------------------------------------------------------------
# chaos acceptance: the real mesh, the real loader wiring
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def chaos_world(request):
    """One 8-virtual-device mesh provider under the REAL guarded +
    healer wiring, with keys and a committee-grid batch maker shared
    by the acceptance test (one 8-shard kernel shape, matching
    tests/test_mesh_grouped.py's grid)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (see conftest XLA_FLAGS)")
    import os

    from teku_tpu.crypto.bls.loader import (GuardedBls12381,
                                            make_mesh_healer)
    from teku_tpu.ops.provider import JaxBls12381

    prev_wb = os.environ.get("TEKU_TPU_MESH_WARM_BATCH")
    os.environ["TEKU_TPU_MESH_WARM_BATCH"] = "1"
    request.addfinalizer(lambda: (
        os.environ.pop("TEKU_TPU_MESH_WARM_BATCH", None)
        if prev_wb is None else
        os.environ.__setitem__("TEKU_TPU_MESH_WARM_BATCH", prev_wb)))

    impl = JaxBls12381(mesh=parallel.make_mesh(8), min_bucket=8)
    # deadline far above a cold XLA compile of the reshaped kernel: a
    # first-ever run pays it inside a guarded dispatch (the persistent
    # .jax_cache makes every later run hit disk), and a compile must
    # read as slow, never as a wedge
    breaker = CircuitBreaker(failure_threshold=3, deadline_s=900.0,
                             cooldown_s=60.0, name="selfheal_t",
                             registry=MetricsRegistry())
    guarded = GuardedBls12381(impl, breaker)
    # a REAL (unstarted) supervisor: its snapshot() IS the readiness
    # endpoint's "backend" body, so asserting on it proves the
    # /teku/v1/admin/readiness surface tracks the live mesh
    sup = BackendSupervisor(probe=lambda: None, install=lambda b: None,
                            name="selfheal_sup",
                            registry=MetricsRegistry())
    sup.mesh = dict(impl.mesh_info)
    healer = make_mesh_healer(
        guarded, breaker, max_batch=64, min_bucket=8, supervisor=sup,
        trip_threshold=1, probe_deadline_s=10.0, reprobe_s=0.2)
    assert healer is not None
    pure = PureBls12381()
    sks = [keygen(bytes([91 + i]) * 32) for i in range(8)]
    pks = [pure.secret_key_to_public_key(sk) for sk in sks]
    request.addfinalizer(healer.close)
    return {"impl": impl, "guarded": guarded, "breaker": breaker,
            "healer": healer, "sup": sup, "pure": pure, "sks": sks,
            "pks": pks}


_seq = [0]
_U_MAP = [0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 3, 3, 4, 5, 6, 7]


def _grid_batch(world):
    """Committee-shaped 16-lane / 8-unique grid (the
    test_mesh_grouped shape) with fresh messages per call."""
    pure, sks, pks = world["pure"], world["sks"], world["pks"]
    _seq[0] += 1
    msgs = [b"heal-%d-%d" % (_seq[0], u) for u in range(8)]
    sig_cache: dict = {}
    triples = []
    for lane in range(16):
        u = _U_MAP[lane]
        k = lane % 8
        if (k, u) not in sig_cache:
            sig_cache[(k, u)] = pure.sign(sks[k], msgs[u])
        triples.append(([pks[k]], msgs[u], sig_cache[(k, u)]))
    return triples


def _tamper_sig(world, batch, lane=2):
    """Flip one lane's signature WITHOUT changing the message set (the
    batch keeps its compiled shape)."""
    bad = list(batch)
    pure, sks = world["pure"], world["sks"]
    bad[lane] = (batch[lane][0], batch[lane][1],
                 pure.sign(sks[0], b"wrong-message"))
    return bad


def test_chaos_eject_reshape_readmit_cycle(chaos_world):
    """THE acceptance cycle: 8 -> wedge -> eject device 3 -> 4-device
    mesh keeps serving on-device, verdicts bit-identical -> readmit
    -> 8, everything observable."""
    from teku_tpu.infra import tracing

    world = chaos_world
    impl, guarded = world["impl"], world["guarded"]
    healer, sup, breaker = (world["healer"], world["sup"],
                            world["breaker"])
    mesh_gauge = GLOBAL_REGISTRY.gauge("bls_mesh_devices")
    reshape_fam = GLOBAL_REGISTRY.labeled_counter(
        "bls_mesh_reshape_total")
    flight0 = len(flightrecorder.RECORDER.snapshot())
    led0 = dispatchledger.LEDGER.recorded_total

    # ---- healthy serving at 8 devices --------------------------------
    batch = _grid_batch(world)
    assert guarded.batch_verify(batch) is True
    assert guarded.batch_verify(_tamper_sig(world, batch)) is False
    assert breaker.state == CircuitBreaker.CLOSED
    assert mesh_gauge.value == 8.0
    assert sup.snapshot()["mesh"]["n_devices"] == 8

    # ---- the wedge: device 3 goes sick -------------------------------
    sick = impl.mesh_info["devices"][3]
    shrink_before = reshape_fam.labels(direction="shrink",
                                       devices="4").value
    faults.inject(selfheal.FAULT_SITE,
                  faults.Raise(RuntimeError("chaos: shard wedged"),
                               key=sick))
    # the fault stays ARMED through the degraded-phase assertions: it
    # is keyed to the ejected device, so the shrunken collective never
    # passes its key again (serving is clean) while the background
    # reprobe keeps failing against it (the mesh HOLDS at 4 instead of
    # racing the assertions with an instant readmit)
    try:
        tr = tracing.new_trace("chaos_kill")
        with tracing.attach((tr,)):
            # the wedged dispatch: the oracle serves THIS call — the
            # in-flight verification still gets the correct verdict
            assert guarded.batch_verify(_grid_batch(world)) is True
        tracing.finish(tr)
        # the healer attributes, ejects, reshapes, AOT-warms, swaps
        _wait(lambda: guarded.device is not impl, timeout_s=600.0,
              what="reshape swap")
        _assert_degraded_phase(world, impl, sick, tr, reshape_fam,
                               shrink_before, mesh_gauge, led0,
                               flight0)
    finally:
        faults.clear(selfheal.FAULT_SITE)

    # ---- recovery: the device comes back, the mesh grows -------------
    _wait(lambda: not healer.ledger.ejected(), timeout_s=600.0,
          what="readmit")
    _wait(lambda: len(healer.live_devices) == 8, timeout_s=600.0,
          what="grow reshape")
    assert mesh_gauge.value == 8.0
    assert sup.snapshot()["mesh"]["n_devices"] == 8
    assert reshape_fam.labels(direction="grow", devices="8").value >= 1
    events = flightrecorder.RECORDER.snapshot()
    assert any(e["kind"] == "mesh_readmit" and e["device"] == sick
               for e in events)
    # and the regrown mesh serves (lazily recompiles its 8-shard
    # kernel: a fresh provider instance, same cached XLA program)
    batch = _grid_batch(world)
    assert guarded.batch_verify(batch) is True
    assert guarded.device.mesh_info["n_devices"] == 8


def _assert_degraded_phase(world, impl, sick, tr, reshape_fam,
                           shrink_before, mesh_gauge, led0, flight0):
    """Everything that must be true while the mesh is held at 4."""
    guarded, healer = world["guarded"], world["healer"]
    sup, breaker = world["sup"], world["breaker"]
    assert len(healer.live_devices) == 4
    assert healer.ledger.device_names[3] == sick
    assert healer.ledger.ejected() == [3]
    new_impl = guarded.device
    assert new_impl.mesh_info["n_devices"] == 4
    assert sick not in new_impl.mesh_info["devices"]
    assert new_impl.mesh_epoch >= 1
    batch = _grid_batch(world)
    assert guarded.batch_verify(batch) is True
    assert guarded.batch_verify(_tamper_sig(world, batch)) is False
    assert breaker.state == CircuitBreaker.CLOSED
    assert guarded.serving == "device"
    # readiness surfaces follow the LIVE mesh
    assert mesh_gauge.value == 4.0
    sup_mesh = sup.snapshot()["mesh"]
    assert sup_mesh["n_devices"] == 4
    assert sup_mesh["self_heal"]["ejected"] == [sick]
    assert reshape_fam.labels(direction="shrink",
                              devices="4").value == shrink_before + 1
    assert healer.last_recovery_s is not None
    assert GLOBAL_REGISTRY.gauge(
        "bls_mesh_recovery_seconds").value > 0
    # the dispatch ledger stamped the live device set + epoch
    mesh_recs = [r for r in dispatchledger.LEDGER.snapshot()
                 if r.get("seq", 0) > led0
                 and (r.get("mesh") or {}).get("devices") == 4]
    assert mesh_recs, "no @m4 ledger records"
    assert mesh_recs[-1]["mesh"]["epoch"] >= 1
    assert sick not in mesh_recs[-1]["mesh"]["live"]

    # ---- flight events + doctor finding ------------------------------
    events = flightrecorder.RECORDER.snapshot()[flight0:]
    by_kind = {}
    for e in events:
        by_kind.setdefault(e["kind"], []).append(e)
    assert by_kind["mesh_eject"][0]["device"] == sick
    # the eject names the dispatch that killed the chip
    assert by_kind["mesh_eject"][0]["trace_id"] == tr.trace_id
    assert by_kind["mesh_reshape"][0]["to_devices"] == 4
    diag = doctor.diagnose(
        dispatchledger.LEDGER.snapshot(), flight_events=events)
    degraded = [f for f in diag["findings"]
                if f["kind"] == "mesh_degraded"]
    assert degraded and degraded[0]["metrics"]["live_devices"] == 4
    assert any(e.get("trace_id") == tr.trace_id
               for e in degraded[0]["evidence"])
