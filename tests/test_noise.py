"""Noise XX: handshake state machine, AEAD transport framing, and the
security properties the encrypted transport exists for (reference
LibP2PNetworkBuilder.java:219 — libp2p noise upgrade)."""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio

import pytest

from teku_tpu.networking import noise as N


def _run_handshake():
    a_sk, a_pub = N.generate_static_keypair()
    b_sk, b_pub = N.generate_static_keypair()
    ini = N.XXHandshake(True, a_sk, prologue=b"p")
    res = N.XXHandshake(False, b_sk, prologue=b"p")
    res.read_message_1(ini.write_message_1())
    ini.read_message_2(res.write_message_2())
    msg3, itx, irx = ini.write_message_3()
    rtx, rrx = res.read_message_3(msg3)
    return (a_pub, b_pub, ini, res, itx, irx, rtx, rrx)


def test_xx_handshake_authenticates_both_statics():
    a_pub, b_pub, ini, res, itx, irx, rtx, rrx = _run_handshake()
    assert ini.rs == b_pub            # initiator learned responder id
    assert res.rs == a_pub            # responder learned initiator id
    assert ini.ss.h == res.ss.h       # transcripts agree
    # transport keys work both ways
    ct = itx.encrypt_with_ad(b"", b"ping")
    assert rrx.decrypt_with_ad(b"", ct) == b"ping"
    ct2 = rtx.encrypt_with_ad(b"", b"pong")
    assert irx.decrypt_with_ad(b"", ct2) == b"pong"


def test_tampered_ciphertext_rejected():
    *_, itx, irx, rtx, rrx = _run_handshake()
    ct = bytearray(itx.encrypt_with_ad(b"", b"payload"))
    ct[0] ^= 0xFF
    with pytest.raises(N.NoiseError):
        rrx.decrypt_with_ad(b"", bytes(ct))


def test_tampered_handshake_message_fails():
    a_sk, _ = N.generate_static_keypair()
    b_sk, _ = N.generate_static_keypair()
    ini = N.XXHandshake(True, a_sk)
    res = N.XXHandshake(False, b_sk)
    res.read_message_1(ini.write_message_1())
    msg2 = bytearray(res.write_message_2())
    msg2[40] ^= 0x01                  # inside the encrypted static
    with pytest.raises(N.NoiseError):
        ini.read_message_2(bytes(msg2))


def test_prologue_mismatch_fails():
    a_sk, _ = N.generate_static_keypair()
    b_sk, _ = N.generate_static_keypair()
    ini = N.XXHandshake(True, a_sk, prologue=b"one")
    res = N.XXHandshake(False, b_sk, prologue=b"two")
    res.read_message_1(ini.write_message_1())
    with pytest.raises(N.NoiseError):
        ini.read_message_2(res.write_message_2())


def test_stream_transport_roundtrip_with_chunking():
    async def run():
        a_sk, _ = N.generate_static_keypair()
        b_sk, b_pub = N.generate_static_keypair()
        server_done = asyncio.get_running_loop().create_future()

        async def serve(reader, writer):
            tx, rx, remote = await N.responder_handshake(
                reader, writer, b_sk)
            nr, nw = N.NoiseReader(reader, rx), N.NoiseWriter(writer, tx)
            got = await nr.readexactly(200_000)   # > 3 noise messages
            nw.write(got[::-1])
            await nw.drain()
            server_done.set_result(remote)
            writer.close()    # py3.12 Server.wait_closed waits on this

        server = await asyncio.start_server(serve, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        tx, rx, remote = await N.initiator_handshake(reader, writer,
                                                     a_sk)
        assert remote == b_pub
        nr, nw = N.NoiseReader(reader, rx), N.NoiseWriter(writer, tx)
        payload = bytes(range(256)) * 782 + b"xy"     # 200,194... trim
        payload = payload[:200_000]
        nw.write(payload)
        await nw.drain()
        echoed = await nr.readexactly(200_000)
        assert echoed == payload[::-1]
        await server_done
        writer.close()
        server.close()
        await server.wait_closed()
    asyncio.run(run())


def test_plaintext_peer_rejected_by_noise_node():
    """A node speaking the old cleartext framing cannot connect to an
    encrypted node — and vice versa the dial fails cleanly."""
    from teku_tpu.networking.transport import NetworkConfig, P2PNetwork

    async def run():
        secure = P2PNetwork(NetworkConfig(port=0), b"\x01\x02\x03\x04")
        plain = P2PNetwork(
            NetworkConfig(port=0, noise=False), b"\x01\x02\x03\x04")
        await secure.start()
        await plain.start()
        try:
            peer = await plain.connect("127.0.0.1", secure.port)
            await asyncio.sleep(0.1)
            assert peer is None or not peer.connected
            assert not secure.peers
            # and a secure dial of a plaintext node fails cleanly too
            peer2 = await secure.connect("127.0.0.1", plain.port)
            assert peer2 is None or not peer2.connected
        finally:
            await secure.stop()
            await plain.stop()
    asyncio.run(run())


def test_hello_id_must_match_noise_identity():
    from teku_tpu.networking.transport import NetworkConfig, P2PNetwork

    async def run():
        a = P2PNetwork(NetworkConfig(port=0), b"\x01\x02\x03\x04")
        b = P2PNetwork(NetworkConfig(port=0), b"\x01\x02\x03\x04")
        # a lies in its hello: claims an id other than its noise key
        a.node_id = b"\xee" * 32
        await a.start()
        await b.start()
        try:
            await a.connect("127.0.0.1", b.port)
            await asyncio.sleep(0.1)
            assert not b.peers            # b rejected the spoofed hello
        finally:
            await a.stop()
            await b.stop()
    asyncio.run(run())


def test_encrypted_nodes_interoperate():
    from teku_tpu.networking.transport import NetworkConfig, P2PNetwork

    async def run():
        a = P2PNetwork(NetworkConfig(port=0), b"\x01\x02\x03\x04")
        b = P2PNetwork(NetworkConfig(port=0), b"\x01\x02\x03\x04")
        await a.start()
        await b.start()
        try:
            got = []

            async def on_gossip(peer, payload):
                got.append(payload)
            b.on_gossip = on_gossip
            peer = await a.connect("127.0.0.1", b.port)
            assert peer is not None and peer.connected
            # identity = noise static key on both sides
            assert peer.node_id == b.node_id
            from teku_tpu.networking.transport import KIND_GOSSIP
            await peer.send_frame(KIND_GOSSIP, b"\x00secret-bytes")
            await asyncio.sleep(0.1)
            assert got == [b"\x00secret-bytes"]
        finally:
            await a.stop()
            await b.stop()
    asyncio.run(run())


def test_garbage_ciphertext_cleans_up_peer():
    """Post-handshake AEAD garbage must tear the peer down through the
    normal disconnect path, not kill the read loop mid-task."""
    from teku_tpu.networking.transport import NetworkConfig, P2PNetwork

    async def run():
        a = P2PNetwork(NetworkConfig(port=0), b"\x01\x02\x03\x04")
        b = P2PNetwork(NetworkConfig(port=0), b"\x01\x02\x03\x04")
        gone = []

        async def on_gone(peer):
            gone.append(peer)
        b.on_peer_disconnected = on_gone
        await a.start()
        await b.start()
        try:
            peer = await a.connect("127.0.0.1", b.port)
            assert peer is not None and peer.connected
            await asyncio.sleep(0.05)
            assert len(b.peers) == 1
            # bypass the noise writer: raw garbage noise message
            raw = peer.writer._writer
            raw.write(b"\x00\x10" + b"\xab" * 16)
            await raw.drain()
            await asyncio.sleep(0.2)
            assert not b.peers            # cleaned up, slot freed
            assert gone                   # disconnect hook fired
        finally:
            await a.stop()
            await b.stop()
    asyncio.run(run())
