"""Eth1 deposit follower: ABI codec, JSON-RPC polling against a
stubbed execution client, reorg rebuild, and deposits flowing from the
stub chain through voting to activation in a devnet.

reference: beacon/pow/.../Eth1DepositManager.java:38 (follow distance,
log fetching, contiguity validation, reorg replay).
"""

import asyncio
import dataclasses
import json

import pytest

from teku_tpu.crypto import bls
from teku_tpu.node import eth1 as E1
from teku_tpu.node.deposits import DepositProvider
from teku_tpu.spec import config as C
from teku_tpu.spec import helpers as H
from teku_tpu.spec.datastructures import DepositData, DepositMessage

CFG = C.MINIMAL


def _deposit_data(cfg, sk, amount=None):
    pk = bls.secret_to_public_key(sk)
    creds = b"\x00" + H.hash32(pk)[1:]
    amount = cfg.MAX_EFFECTIVE_BALANCE if amount is None else amount
    msg = DepositMessage(pubkey=pk, withdrawal_credentials=creds,
                         amount=amount)
    domain = H.compute_domain(C.DOMAIN_DEPOSIT, cfg.GENESIS_FORK_VERSION,
                              bytes(32))
    sig = bls.sign(sk, H.compute_signing_root(msg, domain))
    return DepositData(pubkey=pk, withdrawal_credentials=creds,
                       amount=amount, signature=sig)


class StubEth1Chain:
    """A scriptable eth1 chain served over real JSON-RPC HTTP: blocks
    with hashes/parents, deposit logs ABI-encoded exactly like the
    deposit contract's DepositEvent."""

    def __init__(self):
        self.blocks = []          # list of dicts
        self.logs = []            # (block_number, DepositData, index)
        self._server = None
        self.port = None
        self._nonce = 0           # differentiates reorged replacements
        self._mk_block(b"\x00" * 32)

    def _mk_block(self, parent_hash):
        import hashlib
        n = len(self.blocks)
        self._nonce += 1
        h = hashlib.sha256(b"blk" + n.to_bytes(8, "little")
                           + self._nonce.to_bytes(8, "little")
                           + parent_hash).digest()
        self.blocks.append({"number": n, "hash": h,
                            "parent": parent_hash,
                            "timestamp": 1700000000 + 12 * n})
        return self.blocks[-1]

    def mine(self, deposits=()):
        blk = self._mk_block(self.blocks[-1]["hash"])
        for d in deposits:
            self.logs.append((blk["number"], d, len(self.logs)))
        return blk

    def reorg(self, depth: int, deposits=()):
        """Drop the last `depth` blocks (and their logs), then mine a
        replacement carrying `deposits`."""
        cut = len(self.blocks) - depth
        self.blocks = self.blocks[:cut]
        self.logs = [(n, d, i) for n, d, i in self.logs if n < cut]
        # re-number surviving log indices contiguously
        self.logs = [(n, d, i) for i, (n, d, _) in enumerate(self.logs)]
        return self.mine(deposits)

    # -- JSON-RPC over HTTP -------------------------------------------
    async def start(self):
        self._server = await asyncio.start_server(
            self._serve, "127.0.0.1", 0)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self):
        self._server.close()
        await self._server.wait_closed()

    async def _serve(self, reader, writer):
        try:
            raw = b""
            while b"\r\n\r\n" not in raw:
                raw += await reader.read(4096)
            head, _, body = raw.partition(b"\r\n\r\n")
            length = 0
            for line in head.split(b"\r\n"):
                if line.lower().startswith(b"content-length:"):
                    length = int(line.split(b":")[1])
            while len(body) < length:
                body += await reader.read(4096)
            req = json.loads(body)
            result = self._dispatch(req["method"], req["params"])
            out = json.dumps({"jsonrpc": "2.0", "id": req["id"],
                              "result": result}).encode()
            writer.write(b"HTTP/1.1 200 OK\r\nContent-Type: "
                         b"application/json\r\nContent-Length: "
                         + str(len(out)).encode() + b"\r\n\r\n" + out)
            await writer.drain()
        finally:
            writer.close()

    def _dispatch(self, method, params):
        if method == "eth_blockNumber":
            return hex(self.blocks[-1]["number"])
        if method == "eth_getBlockByNumber":
            n = int(params[0], 16)
            if n >= len(self.blocks):
                return None
            b = self.blocks[n]
            return {"number": hex(b["number"]),
                    "hash": "0x" + b["hash"].hex(),
                    "parentHash": "0x" + b["parent"].hex(),
                    "timestamp": hex(b["timestamp"])}
        if method == "eth_getLogs":
            q = params[0]
            frm, to = int(q["fromBlock"], 16), int(q["toBlock"], 16)
            out = []
            for n, d, i in self.logs:
                if frm <= n <= to:
                    out.append({
                        "blockNumber": hex(n),
                        "blockHash": "0x" + self.blocks[n]["hash"].hex(),
                        "data": "0x" + E1.abi_encode_deposit_event(
                            d, i).hex(),
                        "topics": [E1.DEPOSIT_EVENT_TOPIC]})
            return out
        raise ValueError(method)


def test_abi_deposit_event_roundtrip():
    d = _deposit_data(CFG, 12345)
    raw = E1.abi_encode_deposit_event(d, 77)
    decoded, index = E1.abi_decode_deposit_event(raw)
    assert decoded == d and index == 77
    with pytest.raises(ValueError):
        E1.abi_decode_deposit_event(raw[:-40])


def _follower(chain, follow_distance=3):
    provider = DepositProvider(CFG)
    rpc = E1.JsonRpcEth1Provider("127.0.0.1", chain.port)
    return provider, E1.Eth1DepositFollower(
        provider, rpc, follow_distance=follow_distance)


def test_follower_tracks_deposits_behind_follow_distance():
    async def run():
        chain = StubEth1Chain()
        await chain.start()
        try:
            provider, follower = _follower(chain, follow_distance=3)
            d0, d1 = _deposit_data(CFG, 1), _deposit_data(CFG, 2)
            chain.mine([d0])              # block 1
            chain.mine([d1])              # block 2
            await follower.poll_once()
            # head=2, target=-1: nothing followed yet
            assert provider.tree.count == 0
            chain.mine()                  # 3
            chain.mine()                  # 4: target=1 → d0 visible
            await follower.poll_once()
            assert provider.tree.count == 1
            chain.mine()                  # 5: target=2 → d1 visible
            await follower.poll_once()
            assert provider.tree.count == 2
            vote = provider.eth1_data()
            assert vote.deposit_count == 2
            assert vote.block_hash == chain.blocks[2]["hash"]
        finally:
            await chain.stop()
    asyncio.run(run())


def test_follower_rebuilds_after_deep_reorg():
    async def run():
        chain = StubEth1Chain()
        await chain.start()
        try:
            provider, follower = _follower(chain, follow_distance=1)
            d_orphaned = _deposit_data(CFG, 3)
            d_canonical = _deposit_data(CFG, 4)
            chain.mine([d_orphaned])      # block 1
            chain.mine()                  # block 2
            await follower.poll_once()
            assert provider.tree.count == 1
            orphaned_root = provider.tree.root()
            # reorg deeper than the follow distance: both tip blocks
            # replaced; the orphaned deposit vanishes
            chain.reorg(2, [d_canonical])
            chain.mine()
            chain.mine()
            await follower.poll_once()    # detects hash mismatch
            await follower.poll_once()    # refollows from scratch
            assert follower.rebuilds == 1
            assert provider.tree.count == 1
            assert provider.tree.root() != orphaned_root
            assert provider._data[0] == d_canonical
        finally:
            await chain.stop()
    asyncio.run(run())


def test_non_contiguous_index_resets():
    async def run():
        chain = StubEth1Chain()
        await chain.start()
        try:
            provider, follower = _follower(chain, follow_distance=0)
            chain.mine([_deposit_data(CFG, 5)])
            await follower.poll_once()
            assert provider.tree.count == 1
            # corrupt the stub: future log claims a gapped index
            chain.logs.append((2, _deposit_data(CFG, 6), 9))
            chain.mine()
            await follower.poll_once()
            assert provider.tree.count == 0     # reset, loud not wrong
        finally:
            await chain.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_deposits_flow_from_stub_eth1_to_activation():
    """The full pipe: stub eth1 JSON-RPC → follower → deposit tree →
    eth1 voting → block inclusion with proofs → registry activation."""
    from teku_tpu.node import Devnet
    from teku_tpu.spec import Spec
    from teku_tpu.spec.genesis import interop_secret_keys

    cfg = CFG
    net = Devnet(n_nodes=1, n_validators=16, spec=Spec(cfg))
    node = net.nodes[0]

    async def run():
        chain = StubEth1Chain()
        await chain.start()
        provider, follower = _follower(chain, follow_distance=2)
        node.deposit_provider = provider
        # genesis deposits plus one newcomer land on the eth1 chain
        genesis = [_deposit_data(cfg, sk)
                   for sk in interop_secret_keys(16)]
        chain.mine(genesis)
        chain.mine([_deposit_data(cfg, 777_777)])
        for _ in range(3):
            chain.mine()
        await net.start()
        try:
            await follower.poll_once()
            assert provider.tree.count == 17
            period = cfg.EPOCHS_PER_ETH1_VOTING_PERIOD \
                * cfg.SLOTS_PER_EPOCH
            await net.run_until_slot(period // 2 + 4)
            state = node.chain.head_state()
            assert state.eth1_data.deposit_count == 17
            assert state.eth1_data.block_hash \
                == follower._followed.hash
            assert len(state.validators) == 17
            assert state.validators[16].pubkey \
                == bls.secret_to_public_key(777_777)
        finally:
            await net.stop()
            await chain.stop()
    asyncio.run(run())
