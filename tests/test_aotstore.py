"""AOT executable store: round-trip, degradation, and the serving seam.

Fast-tier gates for the compile-wall killer (`infra/aotstore.py`):

- a serialized executable must ROUND-TRIP: the first call through a
  wrapped kernel self-populates the store, and after the in-process
  memo + jit caches are dropped (a fresh process in miniature) the same
  signature is served by DESERIALIZATION — zero backend compiles — with
  bit-identical results;
- a true fresh process (subprocess, slow tier) must load the entry the
  parent wrote and agree bit-for-bit;
- corrupt blobs and identity mismatches (jax upgrade, code edit,
  different device) must degrade to a fresh compile with ONE WARN per
  complaint kind — a stale store may cost time, never correctness or a
  log flood;
- the provider's first-dispatch classifier must read a store hit as
  the third outcome, ``aot_load``.
"""

import logging
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from teku_tpu.infra import aotstore, compilecache


@pytest.fixture
def aot_dir(tmp_path, monkeypatch):
    """Point the store at a fresh dir; re-arm the one-WARN guards."""
    base = tmp_path / "aot"
    monkeypatch.setenv(aotstore.ENV_DIR, str(base))
    monkeypatch.delenv(aotstore.ENV_ON, raising=False)
    aotstore._reset_warnings()
    yield str(base)
    aotstore._reset_warnings()


def _oracle(x):
    return x * 7 + 3


def test_round_trip_bit_identical_and_classified(aot_dir):
    x = jnp.arange(8, dtype=jnp.int64)
    disp = aotstore.wrap("test:roundtrip", jax.jit(_oracle))

    before = aotstore.stats()
    first = np.asarray(disp(x))
    moved = aotstore.delta(before)
    # the serving path is self-populating: a miss compiles through the
    # explicit AOT path and SAVES, so the next process loads
    assert moved["misses"] == 1
    assert moved["saves"] == 1
    assert os.listdir(aot_dir), "miss must write the store entry"

    # a fresh process in miniature: drop the per-process memo and the
    # in-memory jit caches, then re-dispatch the same signature
    disp.reset_memo()
    jax.clear_caches()
    a_before = aotstore.stats()
    c_before = compilecache.stats()
    second = np.asarray(disp(x))
    a_moved = aotstore.delta(a_before)
    c_moved = compilecache.delta(c_before)
    assert a_moved["loads"] == 1
    assert a_moved["misses"] == 0 and a_moved["saves"] == 0
    # deserialization IS the point: no XLA backend compile fired
    assert c_moved.get("backend_compiles", 0) == 0

    oracle = _oracle(np.arange(8, dtype=np.int64))
    np.testing.assert_array_equal(first, oracle)
    np.testing.assert_array_equal(second, oracle)

    # the provider-facing classifier reads this as the third outcome
    assert compilecache.classify_first_dispatch(
        c_moved, aot=a_moved) == "aot_load"


@pytest.mark.slow
def test_fresh_process_round_trip_bit_identical(aot_dir):
    """The real thing, not the miniature: a SUBPROCESS with the same
    store dir must deserialize the parent's entry (loads==1, zero
    misses) and produce bit-identical output."""
    x = jnp.arange(16, dtype=jnp.int64)
    disp = aotstore.wrap("test:freshproc", jax.jit(_oracle))
    parent = np.asarray(disp(x))
    assert aotstore.stats()["saves"] >= 1

    script = (
        "import json, numpy as np\n"
        "import jax, jax.numpy as jnp\n"
        "from teku_tpu.infra import aotstore\n"
        "disp = aotstore.wrap('test:freshproc',"
        " jax.jit(lambda v: v * 7 + 3))\n"
        "out = disp(jnp.arange(16, dtype=jnp.int64))\n"
        "print(json.dumps({'out': np.asarray(out).tolist(),"
        " 'aot': aotstore.stats()}))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               **{aotstore.ENV_DIR: aot_dir})
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    import json
    got = json.loads(proc.stdout.strip().splitlines()[-1])
    assert got["aot"]["loads"] == 1, got["aot"]
    assert got["aot"]["misses"] == 0
    np.testing.assert_array_equal(np.asarray(got["out"]), parent)


def test_corrupt_blob_one_warn_and_fresh_compile(aot_dir, caplog):
    x = jnp.arange(4, dtype=jnp.int64)
    disp = aotstore.wrap("test:corrupt", jax.jit(_oracle))
    disp(x)
    disp2 = aotstore.wrap("test:corrupt2", jax.jit(lambda v: v - 5))
    disp2(x)
    for name in os.listdir(aot_dir):
        with open(os.path.join(aot_dir, name), "wb") as fh:
            fh.write(b"not a pickle")

    aotstore.reset_memos()
    aotstore._reset_warnings()
    with caplog.at_level(logging.WARNING,
                         logger="teku_tpu.infra.aotstore"):
        before = aotstore.stats()
        out = np.asarray(disp(x))
        out2 = np.asarray(disp2(x))
    np.testing.assert_array_equal(
        out, _oracle(np.arange(4, dtype=np.int64)))
    np.testing.assert_array_equal(
        out2, np.arange(4, dtype=np.int64) - 5)
    moved = aotstore.delta(before)
    assert moved["errors"] >= 2, "corrupt entries count as errors"
    assert moved["loads"] == 0
    warns = [r for r in caplog.records if "corrupt" in r.message]
    assert len(warns) == 1, "one WARN per complaint kind, not per blob"


def test_identity_mismatch_one_warn_and_fresh_compile(
        aot_dir, caplog):
    x = jnp.arange(4, dtype=jnp.int64)
    disp = aotstore.wrap("test:ident", jax.jit(_oracle))
    disp(x)
    # a jax upgrade in miniature: rewrite the blob's identity header
    (entry_name,) = os.listdir(aot_dir)
    path = os.path.join(aot_dir, entry_name)
    with open(path, "rb") as fh:
        entry = pickle.loads(fh.read())
    entry["identity"]["jax"] = "0.0.0-from-another-era"
    with open(path, "wb") as fh:
        fh.write(pickle.dumps(entry))

    disp.reset_memo()
    aotstore._reset_warnings()
    with caplog.at_level(logging.WARNING,
                         logger="teku_tpu.infra.aotstore"):
        before = aotstore.stats()
        out = np.asarray(disp(x))
        moved = aotstore.delta(before)
        # the mismatch degrades to a fresh compile... which re-SAVES,
        # healing the stale entry for the next process
        assert moved["loads"] == 0 and moved["errors"] >= 1
        assert moved["saves"] == 1
        disp.reset_memo()
        before = aotstore.stats()
        disp(x)
        assert aotstore.delta(before)["loads"] == 1, \
            "the re-saved entry must serve the next resolve"
    np.testing.assert_array_equal(
        out, _oracle(np.arange(4, dtype=np.int64)))
    warns = [r for r in caplog.records if "environment" in r.message]
    assert len(warns) == 1
    assert "precompile" in warns[0].message, \
        "the WARN must name the fix (re-run cli precompile)"


def test_store_off_serves_from_jit_without_counting(monkeypatch):
    monkeypatch.setenv(aotstore.ENV_ON, "0")
    assert aotstore.store_dir() is None
    disp = aotstore.wrap("test:off", jax.jit(_oracle))
    before = aotstore.stats()
    out = np.asarray(disp(jnp.arange(4, dtype=jnp.int64)))
    np.testing.assert_array_equal(
        out, _oracle(np.arange(4, dtype=np.int64)))
    assert aotstore.delta(before) == {
        "loads": 0, "misses": 0, "saves": 0, "errors": 0}


def test_shape_sig_same_for_avals_and_concrete():
    """The precompiler enumerates ShapeDtypeStructs; the serving
    wrapper sees concrete arrays.  Both must derive the SAME key or
    the store never hits."""
    concrete = (jnp.zeros((4, 6), jnp.int64),
                (jnp.zeros((4,), jnp.int32), jnp.ones((2,), bool)))
    avals = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), concrete)
    assert aotstore.shape_sig(concrete) == aotstore.shape_sig(avals)


def test_call_drift_falls_back_to_jit_with_one_warn(aot_dir, caplog):
    x = jnp.arange(4, dtype=jnp.int64)
    disp = aotstore.wrap("test:drift", jax.jit(_oracle))
    sig = aotstore.shape_sig((x,))

    def rejects(*_a):
        raise TypeError("executable/argument drift")

    disp._memo[sig] = rejects
    with caplog.at_level(logging.WARNING,
                         logger="teku_tpu.infra.aotstore"):
        out = np.asarray(disp(x))
    np.testing.assert_array_equal(
        out, _oracle(np.arange(4, dtype=np.int64)))
    # the fallback is PERMANENT for that signature
    assert disp._memo[sig] is disp._jit
    assert any("rejected" in r.message for r in caplog.records)


def test_size_cap_evicts_oldest(aot_dir, monkeypatch):
    monkeypatch.setenv(aotstore.ENV_MAX_MB, "1")
    os.makedirs(aot_dir, exist_ok=True)
    old = os.path.join(aot_dir, "old.aotx")
    new = os.path.join(aot_dir, "new.aotx")
    for path in (old, new):
        with open(path, "wb") as fh:
            fh.write(b"\0" * (700 * 1024))
    os.utime(old, (1, 1))
    aotstore._enforce_cap(aot_dir)
    assert not os.path.exists(old), "oldest entry must be evicted"
    assert os.path.exists(new)


def test_entry_key_is_filename_safe_and_stable():
    sig = (("*", "*"), (((4, 6), "int64"),))
    key = aotstore.entry_key("mesh:2:dp:ladder:vpu:deadbeef", sig)
    assert key == aotstore.entry_key(
        "mesh:2:dp:ladder:vpu:deadbeef", sig), "stable across calls"
    assert all(c.isalnum() or c in "._-" for c in key), key
    assert key != aotstore.entry_key("mesh:4:dp:ladder:vpu:deadbeef",
                                     sig)
