"""Prometheus exposition correctness + metric-naming lint.

A minimal text-format parser validates the FULL registry exposition:
HELP/TYPE pairing, label escaping, histogram bucket monotonicity — so
a malformed family breaks a fast test here instead of a scraper in
production.  The naming lint (counters end ``_total``, durations end
``_seconds``, no colliding families) runs against GLOBAL_REGISTRY after
importing the node modules, so every metric the node actually registers
is covered.
"""

import re

import pytest

from teku_tpu.infra.metrics import (Counter, Gauge, Histogram,
                                    LabeledCounter, LabeledGauge,
                                    LabeledHistogram, LATENCY_BUCKETS_S,
                                    MetricsRegistry, StateGauge)

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?'
    r' (?P<value>[^ ]+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    return (value.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_exposition(text: str):
    """Parse Prometheus text format into
    {family: {"type", "help", "samples": [(name, labels, value)]}}.
    Raises AssertionError on any structural violation."""
    families: dict = {}
    current = None
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            assert name not in families, \
                f"line {lineno}: duplicate HELP for {name}"
            families[name] = {"help": help_, "type": None, "samples": []}
            current = name
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, type_ = rest.partition(" ")
            assert name == current, \
                f"line {lineno}: TYPE {name} not paired under HELP"
            assert type_ in ("counter", "gauge", "histogram", "summary")
            assert families[name]["type"] is None, \
                f"line {lineno}: duplicate TYPE for {name}"
            families[name]["type"] = type_
            continue
        assert not line.startswith("#"), f"line {lineno}: bad comment"
        m = _SAMPLE_RE.match(line)
        assert m, f"line {lineno}: unparsable sample {line!r}"
        name = m.group("name")
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in families:
                family = name[:-len(suffix)]
        assert family in families, \
            f"line {lineno}: sample {name} outside any HELP/TYPE family"
        raw = m.group("labels") or ""
        labels = {k: _unescape(v) for k, v in _LABEL_RE.findall(raw)}
        if raw:
            # every label pair must parse (catches broken escaping)
            rebuilt = ",".join(f'{k}="{v}"'
                               for k, v in _LABEL_RE.findall(raw))
            assert rebuilt == raw, \
                f"line {lineno}: malformed labels {raw!r}"
        value = float(m.group("value")) if m.group("value") != "+Inf" \
            else float("inf")
        families[family]["samples"].append((name, labels, value))
        current = family
    for name, fam in families.items():
        assert fam["type"] is not None, f"family {name} missing TYPE"
    return families


def _histogram_checks(fam, family_name):
    """le-monotonicity + bucket/sum/count coherence per label set."""
    by_labelset: dict = {}
    for name, labels, value in fam["samples"]:
        key = tuple(sorted((k, v) for k, v in labels.items()
                           if k != "le"))
        entry = by_labelset.setdefault(
            key, {"buckets": [], "sum": None, "count": None})
        if name.endswith("_bucket"):
            le = labels["le"]
            entry["buckets"].append(
                (float("inf") if le == "+Inf" else float(le), value))
        elif name.endswith("_sum"):
            entry["sum"] = value
        elif name.endswith("_count"):
            entry["count"] = value
    assert by_labelset, f"{family_name}: no samples"
    for key, entry in by_labelset.items():
        buckets = entry["buckets"]
        assert buckets, f"{family_name}{key}: no buckets"
        les = [le for le, _ in buckets]
        counts = [c for _, c in buckets]
        assert les == sorted(les), f"{family_name}{key}: le unsorted"
        assert les[-1] == float("inf"), \
            f"{family_name}{key}: missing +Inf bucket"
        assert counts == sorted(counts), \
            f"{family_name}{key}: cumulative counts not monotone"
        assert entry["count"] == counts[-1], \
            f"{family_name}{key}: count != +Inf bucket"
        assert entry["sum"] is not None


def test_full_exposition_parses_and_validates():
    reg = MetricsRegistry()
    reg.counter("requests_total", "requests").inc(3)
    reg.gauge("depth", "queue depth").set(7)
    h = reg.histogram("sizes", "batch sizes", buckets=(1, 10, 100))
    h.observe(5)
    h.observe(5000)
    lc = reg.labeled_counter(
        "outcomes_total", "labeled outcomes",
        labelnames=("backend", "reason"))
    lc.labels(backend="device", reason="ok").inc()
    lc.labels(backend="oracle", reason='we "quoted" a\\slash\nnewline'
              ).inc(2)
    lh = reg.labeled_histogram(
        "stage_seconds", "stage durations", labelnames=("stage",))
    lh.labels(stage="device_execute").observe(0.004)
    lh.labels(stage="queue_wait").observe(11.0)   # overflows to +Inf
    sg = reg.state_gauge("backend_state", "state set",
                         states=("cold", "ready"))
    sg.set_state("ready")

    fams = parse_exposition(reg.expose())
    assert fams["requests_total"]["type"] == "counter"
    assert fams["requests_total"]["samples"][0][2] == 3.0
    assert fams["depth"]["type"] == "gauge"
    assert fams["sizes"]["type"] == "histogram"
    _histogram_checks(fams["sizes"], "sizes")
    _histogram_checks(fams["stage_seconds"], "stage_seconds")
    # label escaping round-trips through the parser
    oracle = [s for s in fams["outcomes_total"]["samples"]
              if s[1].get("backend") == "oracle"]
    assert oracle[0][1]["reason"] == 'we "quoted" a\\slash\nnewline'
    assert oracle[0][2] == 2.0
    # state set: exactly one series at 1.0
    states = fams["backend_state"]["samples"]
    assert sum(v for _, _, v in states) == 1.0
    assert [s for _, s, v in states if v == 1.0][0]["state"] == "ready"


def test_raising_gauge_supplier_does_not_break_scrape():
    reg = MetricsRegistry()
    reg.counter("alive_total", "proof of scrape").inc()

    def boom():
        raise RuntimeError("supplier died")

    reg.gauge("sick", "raising supplier", supplier=boom)
    text = reg.expose()
    fams = parse_exposition(text)
    # the scrape survives; the healthy metric is present with a value,
    # the sick gauge lost only its sample
    assert fams["alive_total"]["samples"][0][2] == 1.0
    assert fams["sick"]["samples"] == []


def test_help_lines_emitted_for_every_family():
    reg = MetricsRegistry()
    reg.counter("a_total", "help a")
    reg.histogram("b_seconds", "help b", buckets=LATENCY_BUCKETS_S)
    text = reg.expose()
    assert "# HELP a_total help a" in text
    assert "# HELP b_seconds help b" in text
    # HELP precedes TYPE for each family
    lines = text.splitlines()
    for name in ("a_total", "b_seconds"):
        help_i = lines.index(f"# HELP {name} help {name[0]}")
        type_i = next(i for i, l in enumerate(lines)
                      if l.startswith(f"# TYPE {name} "))
        assert help_i + 1 == type_i


def test_labeled_counter_label_validation():
    reg = MetricsRegistry()
    lc = reg.labeled_counter("x_total", "x", labelnames=("a", "b"))
    with pytest.raises(ValueError):
        lc.labels(a="1")              # missing label
    with pytest.raises(ValueError):
        lc.labels(a="1", b="2", c="3")  # extra label
    with pytest.raises(ValueError):
        reg.labeled_counter("x_total", "x", labelnames=("other",))
    with pytest.raises(ValueError):
        reg.counter("x_total")        # type mismatch on re-registration


# --------------------------------------------------------------------------
# Naming lint: run against the GLOBAL registry after importing the node
# modules, so every metric the node wires is checked
# --------------------------------------------------------------------------

_DURATION_HINT = re.compile(r"(duration|latency|_wait|elapsed)")
_UNIT_SUFFIXES = ("_seconds", "_ratio", "_bytes")


def test_metric_naming_lint_after_node_imports():
    import teku_tpu.crypto.bls.loader  # noqa: F401
    import teku_tpu.infra.supervisor  # noqa: F401
    import teku_tpu.infra.tracing  # noqa: F401
    import teku_tpu.node.node  # noqa: F401
    import teku_tpu.ops.provider  # noqa: F401
    import teku_tpu.services.signatures  # noqa: F401
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY

    metrics = GLOBAL_REGISTRY.metrics()
    assert metrics, "node imports registered no metrics"
    problems = []
    names = set(metrics)
    for name, m in metrics.items():
        if isinstance(m, (Counter, LabeledCounter)):
            if not name.endswith("_total"):
                problems.append(f"counter {name} must end _total")
        if isinstance(m, (Histogram, LabeledHistogram, Gauge)):
            if _DURATION_HINT.search(name) \
                    and not name.endswith("_seconds"):
                problems.append(
                    f"duration metric {name} must end _seconds")
        if isinstance(m, (Histogram, LabeledHistogram)) \
                and name.endswith("_seconds"):
            if max(m.buckets) > 100:
                problems.append(
                    f"histogram {name} is *_seconds but its buckets "
                    f"({m.buckets[:3]}…{m.buckets[-1]}) look like "
                    "unitless DEFAULT_BUCKETS — use LATENCY_BUCKETS_S")
        if isinstance(m, (Histogram, LabeledHistogram)):
            # derived series must not collide with another family
            for suffix in ("_bucket", "_sum", "_count"):
                if name + suffix in names:
                    problems.append(
                        f"{name + suffix} collides with histogram "
                        f"{name}'s derived series")
    assert not problems, "\n".join(problems)


def test_global_exposition_is_well_formed_after_node_imports():
    import teku_tpu.node.node  # noqa: F401
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY

    fams = parse_exposition(GLOBAL_REGISTRY.expose())
    assert "verify_stage_duration_seconds" in fams
    assert "bls_dispatch_padding_waste_ratio" in fams


_JIT_OUTCOMES = {"compile", "cache_load", "aot_load", "cache_hit"}


def test_dispatch_and_cache_label_contract():
    """The mont-path/compile-cache label vocabulary must not drift:
    dashboards key on `path` (vpu|mxu) and the four-way jit outcome
    (compile = fresh XLA work, cache_load = served from the persistent
    cache dir, aot_load = deserialized from the AOT executable store,
    cache_hit = in-memory jit cache)."""
    from teku_tpu.infra import compilecache  # noqa: F401 - registers
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY
    import teku_tpu.ops.provider as pv
    from teku_tpu.ops import mxu

    metrics = GLOBAL_REGISTRY.metrics()
    jit = metrics["bls_jit_dispatch_total"]
    assert isinstance(jit, LabeledCounter)
    assert tuple(jit.labelnames) == ("shape", "outcome", "path")
    cache = metrics["xla_compile_cache_total"]
    assert isinstance(cache, LabeledCounter)
    assert tuple(cache.labelnames) == ("outcome",)
    # the classifier can only emit the documented vocabulary
    for d in ({"hits": 1, "misses": 0}, {"hits": 0, "misses": 1},
              {"hits": 3, "misses": 2}, {"hits": 0, "misses": 0}):
        assert compilecache.classify_first_dispatch(d) in _JIT_OUTCOMES
    # the AOT executable store adds the fourth outcome: a first
    # dispatch served by deserialization (no compile, no cache load)
    assert compilecache.classify_first_dispatch(
        {"hits": 0, "misses": 0},
        aot={"loads": 1, "misses": 0, "saves": 0, "errors": 0}) \
        == "aot_load"
    assert "aot_load" in _JIT_OUTCOMES
    # and the path label values come from the resolver's closed set
    assert mxu.resolve() in ("vpu", "mxu")
    # provider records its engine for introspection
    assert pv  # imported above; JaxBls12381 instances carry .mont_path


def test_msm_path_family_label_contract():
    """The PR-8 MSM scalars-path families must not drift: the dispatch
    and lane counters carry exactly one `path` label whose vocabulary
    is the CLOSED {ladder, pippenger} set resolve() can emit —
    dashboards ratio pippenger lanes over total to see how much
    traffic rides the bucketed stage."""
    import teku_tpu.ops.provider  # noqa: F401 - registers families
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY
    from teku_tpu.ops import msm

    metrics = GLOBAL_REGISTRY.metrics()
    resolved_vocab = {"ladder", "pippenger"}
    for fam in ("bls_msm_dispatch_total", "bls_msm_lanes_total"):
        m = metrics[fam]
        assert isinstance(m, LabeledCounter), fam
        assert tuple(m.labelnames) == ("path",), fam
        assert fam.endswith("_total")
        # any series already recorded stays inside the closed set
        for key, _child in m._items():
            assert set(key) <= resolved_vocab, (fam, key)
    # the resolver can only emit the documented vocabulary, on every
    # input shape (incl. the sharded override and no-context auto)
    for kw in ({}, {"lanes": 4096, "rows": 16},
               {"lanes": 8, "rows": 8, "sharded": True},
               {"lanes": 0, "rows": 0}):
        assert msm.resolve(**kw) in resolved_vocab
    # and the configured vocabulary matches the CLI mirror
    from teku_tpu.cli import _MSM_PATHS
    assert tuple(msm.PATHS) == _MSM_PATHS


def test_mesh_family_label_contract():
    """The PR-10 mesh families must not drift: the sharded-dispatch
    counter carries exactly one `devices` label whose values come from
    the CLOSED pow-2 vocabulary resolve_mesh_devices can emit, the
    process gauge is `bls_mesh_devices`, and supervisors export a
    name-prefixed mesh gauge (multi-node devnets keep series
    distinct, like the admission families)."""
    import teku_tpu.ops.provider  # noqa: F401 - registers families
    from teku_tpu import parallel
    from teku_tpu.crypto.bls import loader
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY

    metrics = GLOBAL_REGISTRY.metrics()
    fam = metrics["bls_mesh_dispatch_total"]
    assert isinstance(fam, LabeledCounter)
    assert tuple(fam.labelnames) == ("devices",)
    # closed vocabulary: pow-2 device counts (the resolver only ever
    # yields pow-2 mesh sizes; bounded — label cardinality is the
    # handful of mesh sizes a fleet actually runs)
    pow2_vocab = {str(1 << i) for i in range(1, 9)}   # 2..256
    for key, _child in fam._items():
        assert set(key) <= pow2_vocab, key
    # the resolver can only emit 0 (off) or a pow-2 >= 2
    for spec, avail in (("auto", 8), ("auto", 5), ("auto", 1),
                        ("6", 8), ("100", 8), ("3", 4), ("off", 8),
                        ("garbage", 8)):
        n = parallel.resolve_mesh_devices(spec, available=avail)
        assert n == 0 or (n >= 2 and n & (n - 1) == 0), (spec, n)
    assert isinstance(metrics["bls_mesh_devices"], Gauge)
    # the supervisor-scoped gauge is name-prefixed
    reg = MetricsRegistry()
    loader.make_supervisor(registry=reg, warm=False,
                           name="lint_mesh",
                           breaker_name="lint_mesh_dev")
    assert isinstance(reg.metrics()["lint_mesh_mesh_devices"], Gauge)


def test_mesh_selfheal_family_label_contract():
    """The self-healing families must not drift: the reshape counter
    carries exactly ``{direction, devices}`` with direction from the
    closed {shrink, grow} set and devices from {0, 1} ∪ pow-2 (the
    healer's largest-surviving-pow-2 rule plus the single-device and
    oracle floors), and the recovery/ejection readouts are plain
    gauges."""
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY
    from teku_tpu.parallel import selfheal

    metrics = GLOBAL_REGISTRY.metrics()
    fam = metrics["bls_mesh_reshape_total"]
    assert isinstance(fam, LabeledCounter)
    assert tuple(fam.labelnames) == ("direction", "devices")
    assert selfheal.DIRECTIONS == ("shrink", "grow")
    devices_vocab = {"0", "1"} | {str(1 << i) for i in range(1, 9)}
    for (direction, devices), _child in fam._items():
        assert direction in selfheal.DIRECTIONS, direction
        assert devices in devices_vocab, devices
    assert isinstance(metrics["bls_mesh_recovery_seconds"], Gauge)
    assert isinstance(metrics["bls_mesh_ejected_devices"], Gauge)
    # the flight-event kinds the doctor joins on are spelled once
    # (a typo'd kind string would silently disable the findings)
    from teku_tpu.infra import doctor
    import inspect
    src = inspect.getsource(doctor._mesh_health_findings)
    for kind in ("mesh_eject", "mesh_reshape", "mesh_readmit"):
        assert kind in src


def test_h2c_dedup_and_coalesce_family_naming_lint():
    """The PR-5 dedup/cache/coalesce families must not drift: hit/miss/
    evict/dispatch counters end ``_total``, the dedup gauge is a
    unitless ``_ratio``, the shared eviction family is labeled by
    cache, and the service coalesce counter follows the service's
    ``<name>_*_total`` convention."""
    import teku_tpu.ops.h2c_cache  # noqa: F401 - registers families
    import teku_tpu.ops.provider  # noqa: F401
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY
    from teku_tpu.services.signatures import (
        AggregatingSignatureVerificationService)

    # instantiating registers the per-service families (idempotent)
    reg = MetricsRegistry()
    AggregatingSignatureVerificationService(registry=reg)
    assert isinstance(
        reg.metrics()["signature_verifications_coalesced_total"],
        Counter)

    metrics = GLOBAL_REGISTRY.metrics()
    assert {"bls_h2c_cache_hits_total", "bls_h2c_cache_misses_total",
            "bls_cache_evictions_total", "bls_h2c_dispatch_total",
            "bls_h2c_lanes_total", "bls_h2c_unique_total",
            "bls_h2c_dedup_ratio"} <= set(metrics)
    evict = metrics["bls_cache_evictions_total"]
    assert isinstance(evict, LabeledCounter)
    assert tuple(evict.labelnames) == ("cache",)
    assert isinstance(metrics["bls_h2c_dedup_ratio"], Gauge)
    problems = []
    for name, m in metrics.items():
        if not name.startswith(("bls_h2c_", "bls_cache_")):
            continue
        if isinstance(m, (Counter, LabeledCounter)) \
                and not name.endswith("_total"):
            problems.append(f"counter {name} must end _total")
        if name.endswith("_total") \
                and not isinstance(m, (Counter, LabeledCounter)):
            problems.append(f"{name} ends _total but is not a counter")
        if isinstance(m, Gauge) and not name.endswith(_UNIT_SUFFIXES):
            problems.append(
                f"gauge {name} needs a unit suffix (_ratio for the "
                "dedup/waste observables)")
    assert not problems, "\n".join(problems)
    # dedup ratio stays in [0, 1): lanes >= uniques by construction
    from teku_tpu.ops.provider import _dedup_ratio
    assert 0.0 <= _dedup_ratio() < 1.0


def test_capacity_profiler_family_naming_lint():
    """The capacity/occupancy + profiler families must not drift:
    HELP/TYPE pairing on the exposition, counters ``_total``, durations
    ``_seconds``, ratios ``_ratio`` / rates ``_per_second``, and a
    BOUNDED ``shape`` label cardinality on the device-latency model
    (pow-2 bucketing keeps the real set tiny; an adversarial shape
    storm must fold into "other", never grow the scrape)."""
    from teku_tpu.infra import capacity, profiling  # noqa: F401
    from teku_tpu.infra.capacity import ShapeLatencyModel
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY

    metrics = GLOBAL_REGISTRY.metrics()
    assert {"bls_shape_device_latency_seconds",
            "bls_arrival_rate_per_second", "bls_queue_depth",
            "bls_device_occupancy_ratio",
            "capacity_shed_rate_per_second",
            "capacity_sustainable_sigs_per_second",
            "capacity_utilization_ratio", "capacity_headroom_ratio",
            "profiler_captures_total"} <= set(metrics)
    lat = metrics["bls_shape_device_latency_seconds"]
    assert isinstance(lat, LabeledGauge)
    assert tuple(lat.labelnames) == ("shape", "path", "stat")
    arrival = metrics["bls_arrival_rate_per_second"]
    assert isinstance(arrival, LabeledGauge)
    assert tuple(arrival.labelnames) == ("source",)
    captures = metrics["profiler_captures_total"]
    assert isinstance(captures, LabeledCounter)
    assert tuple(captures.labelnames) == ("trigger",)

    problems = []
    for name, m in metrics.items():
        if not name.startswith(("capacity_", "profiler_",
                                "bls_shape_", "bls_arrival_",
                                "bls_device_occupancy")):
            continue
        if isinstance(m, (Counter, LabeledCounter)) \
                and not name.endswith("_total"):
            problems.append(f"counter {name} must end _total")
        if name.endswith("_total") \
                and not isinstance(m, (Counter, LabeledCounter)):
            problems.append(f"{name} ends _total but is not a counter")
        if _DURATION_HINT.search(name) and not name.endswith("_seconds"):
            problems.append(f"duration metric {name} must end _seconds")
        if isinstance(m, (Gauge, LabeledGauge)) \
                and not name.endswith(
                    ("_seconds", "_ratio", "_per_second", "_depth")):
            problems.append(
                f"gauge {name} needs a unit suffix (_seconds, _ratio, "
                "_per_second)")
    assert not problems, "\n".join(problems)

    # bounded `shape` cardinality: 40 distinct shapes collapse to the
    # model's cap + the "other" overflow series, on the exported gauge
    reg = MetricsRegistry()
    model = ShapeLatencyModel(max_shapes=8, registry=reg)
    for i in range(40):
        model.observe(f"{i}x{i}", "vpu", 0.001)
    gauge = reg.metrics()["bls_shape_device_latency_seconds"]
    shapes = {key[0] for key, _ in gauge._items()}
    assert len(shapes) == 9 and ShapeLatencyModel.OVERFLOW in shapes

    # the exposition stays structurally valid (HELP/TYPE pairing) with
    # every new family present
    fams = parse_exposition(GLOBAL_REGISTRY.expose())
    for fam in ("bls_shape_device_latency_seconds",
                "capacity_utilization_ratio",
                "profiler_captures_total"):
        assert fam in fams and fams[fam]["type"] is not None


def test_overload_class_family_naming_lint():
    """The PR-7 per-class/admission families must not drift: every
    ``{class}`` label value comes from the CLOSED VerifyClass enum
    (bounded cardinality — an adversary cannot grow the scrape by
    inventing classes, because the label is typed at the API), sheds
    are ``_total`` counters labeled by class, the per-class depth/age
    gauges carry unit suffixes, and the admission controller exports
    its plan/brownout gauges + edge-transition counter."""
    from teku_tpu.services.admission import (AdmissionController,
                                             CLASS_LABELS, VerifyClass)
    from teku_tpu.services.signatures import (
        AggregatingSignatureVerificationService)

    # the class label vocabulary IS the enum — closed and tiny
    assert CLASS_LABELS == ("vip", "block_import", "sync_critical",
                            "gossip", "optimistic")
    assert len(CLASS_LABELS) == len(VerifyClass)

    reg = MetricsRegistry()
    AggregatingSignatureVerificationService(registry=reg,
                                            name="lint_sigs")
    metrics = reg.metrics()
    rejected = metrics["lint_sigs_rejected_total"]
    assert isinstance(rejected, LabeledCounter)
    assert tuple(rejected.labelnames) == ("class",)
    depth = metrics["lint_sigs_class_queue_depth"]
    age = metrics["lint_sigs_class_oldest_wait_seconds"]
    assert isinstance(depth, LabeledGauge)
    assert isinstance(age, LabeledGauge)
    # bounded cardinality: the service pre-registers EXACTLY the enum's
    # series (scrape-complete from the first exposition, and nothing
    # can add a sixth class without extending the enum)
    assert {key[0] for key, _ in depth._items()} == set(CLASS_LABELS)
    assert {key[0] for key, _ in age._items()} == set(CLASS_LABELS)

    # admission controller families: name-prefixed like the service's
    # (a multi-node devnet process must not collapse every node onto
    # one shared gauge)
    reg2 = MetricsRegistry()
    from teku_tpu.infra.flightrecorder import FlightRecorder
    AdmissionController(registry=reg2, name="lint_adm",
                        recorder=FlightRecorder(registry=reg2))
    m2 = reg2.metrics()
    assert {"lint_adm_admission_batch_size",
            "lint_adm_admission_flush_deadline_seconds",
            "lint_adm_admission_brownout_level",
            "lint_adm_admission_brownout_transitions_total"} <= set(m2)
    trans = m2["lint_adm_admission_brownout_transitions_total"]
    assert isinstance(trans, LabeledCounter)
    assert tuple(trans.labelnames) == ("direction",)

    problems = []
    for name, m in {**metrics, **m2}.items():
        if not name.startswith(("lint_sigs_", "lint_adm_")):
            continue
        if isinstance(m, (Counter, LabeledCounter)) \
                and not name.endswith("_total"):
            problems.append(f"counter {name} must end _total")
        if name.endswith("_total") \
                and not isinstance(m, (Counter, LabeledCounter)):
            problems.append(f"{name} ends _total but is not a counter")
        if _DURATION_HINT.search(name) and not name.endswith("_seconds"):
            problems.append(f"duration metric {name} must end _seconds")
    assert not problems, "\n".join(problems)

    # the combined exposition stays structurally valid; the rejected
    # counter's family is DECLARED (HELP/TYPE) before any shed has
    # produced a series, so dashboards can discover it at scrape 1
    exposed = reg.expose()
    assert "# TYPE lint_sigs_rejected_total counter" in exposed
    fams = parse_exposition(exposed)
    for fam in ("lint_sigs_class_queue_depth",
                "lint_sigs_class_oldest_wait_seconds"):
        assert fam in fams and fams[fam]["type"] == "gauge"
        labels = {s[1].get("class") for s in fams[fam]["samples"]}
        assert labels == set(CLASS_LABELS)
    fams2 = parse_exposition(reg2.expose())
    assert fams2["lint_adm_admission_brownout_level"]["type"] == "gauge"


def test_queue_shed_events_carry_class_labels():
    """Flight-recorder queue_shed events must name the shed class and
    the shedding reason (the incident-report contract)."""
    import asyncio
    from teku_tpu.infra import flightrecorder
    from teku_tpu.services.admission import VerifyClass
    from teku_tpu.services.signatures import (
        AggregatingSignatureVerificationService,
        ServiceCapacityExceededError)

    async def main():
        svc = AggregatingSignatureVerificationService(
            num_workers=1, queue_capacity=1,
            registry=MetricsRegistry(), name="lint_shed")
        await svc.start()
        before = len(flightrecorder.RECORDER.snapshot())
        blocker = svc.verify([b"\xa0" + bytes(47)], b"b1", b"s")
        await asyncio.sleep(0.05)
        f1 = svc.verify([b"\xa0" + bytes(47)], b"b2", b"s",
                        cls=VerifyClass.OPTIMISTIC)
        with pytest.raises(ServiceCapacityExceededError):
            svc.verify([b"\xa0" + bytes(47)], b"b3", b"s",
                       cls=VerifyClass.OPTIMISTIC)
        for fut in (blocker, f1):
            try:
                await fut
            except Exception:
                pass
        await svc.stop()
        return flightrecorder.RECORDER.snapshot()[before:]

    events = asyncio.run(main())
    sheds = [e for e in events if e["kind"] == "queue_shed"]
    assert sheds, "no queue_shed event recorded"
    for e in sheds:
        assert e["class"] == "optimistic"
        assert e["reason"] in ("overflow", "preempted", "brownout")
        assert e["service"] == "lint_shed"
        assert "trace_id" in e


def test_slo_health_family_naming_lint():
    """The PR-3 families must not drift from the conventions: states as
    labeled/state gauges (never bare numbers encoding an enum), burn
    rates unitless gauges, durations ``_seconds``, counters
    ``_total``."""
    # importing + instantiating registers the families in the global
    # registry (idempotent: get_or_create)
    from teku_tpu.infra import flightrecorder  # noqa: F401
    from teku_tpu.infra.health import (EventLoopLagWatchdog,
                                       HealthRegistry, SloEngine)
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY
    HealthRegistry(name="lint")
    SloEngine()

    metrics = {n: m for n, m in GLOBAL_REGISTRY.metrics().items()
               if n.startswith(("slo_", "health_"))}
    assert {"slo_burn_rate", "slo_breached", "slo_breaches_total",
            "health_node_state", "health_check_state",
            "health_transitions_total"} <= set(metrics)
    problems = []
    for name, m in metrics.items():
        if isinstance(m, (Counter, LabeledCounter)) \
                and not name.endswith("_total"):
            problems.append(f"counter {name} must end _total")
        if name.endswith("_total") \
                and not isinstance(m, (Counter, LabeledCounter)):
            problems.append(f"{name} ends _total but is not a counter")
        if _DURATION_HINT.search(name) and not name.endswith("_seconds"):
            problems.append(f"duration metric {name} must end _seconds")
        # states are gauges with a `state` dimension, not enum numbers
        if name.endswith("_state"):
            if isinstance(m, StateGauge):
                pass
            elif isinstance(m, LabeledGauge) \
                    and "state" in m.labelnames:
                pass
            else:
                problems.append(
                    f"{name} must be a StateGauge or a LabeledGauge "
                    "with a 'state' label")
        # burn rates are unitless ratios: no unit suffix allowed
        if "burn_rate" in name:
            if not isinstance(m, (Gauge, LabeledGauge)):
                problems.append(f"{name} must be a gauge")
            if name.endswith(("_seconds", "_bytes", "_total")):
                problems.append(f"burn rate {name} must be unitless")
    assert not problems, "\n".join(problems)


def test_loadgen_sync_kzg_family_naming_lint():
    """The loadgen / sync-committee / kzg-source label families must
    not drift: every ``scenario`` label value comes from the CLOSED
    scenario registry, every ``kind`` from the model's closed event
    vocabulary, every ``class`` from the VerifyClass enum, and the
    well-known arrival sources are pinned strings (dashboards key on
    ``bls_arrival_rate_per_second{source="kzg"|"sync_committee"}``)."""
    from teku_tpu.crypto import kzg
    from teku_tpu.infra import capacity
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY
    from teku_tpu.loadgen import driver  # noqa: F401 - registers
    from teku_tpu.loadgen.model import EVENT_KINDS
    from teku_tpu.loadgen.scenarios import SCENARIOS
    from teku_tpu.services.admission import CLASS_LABELS, VerifyClass

    # the closed vocabularies themselves
    assert capacity.SOURCE_KZG == kzg.KZG_ARRIVAL_SOURCE == "kzg"
    assert capacity.SOURCE_SYNC_COMMITTEE == "sync_committee"
    assert kzg.kzg_verify_class() is VerifyClass.SYNC_CRITICAL
    assert len(SCENARIOS) >= 4
    assert set(EVENT_KINDS) == {"block", "block_import", "attestation",
                                "aggregate", "sync_message",
                                "sync_contribution", "blob_batch"}

    metrics = GLOBAL_REGISTRY.metrics()
    events = metrics["loadgen_events_total"]
    assert isinstance(events, LabeledCounter)
    assert tuple(events.labelnames) == ("scenario", "kind")
    sheds = metrics["loadgen_sheds_total"]
    assert isinstance(sheds, LabeledCounter)
    assert tuple(sheds.labelnames) == ("scenario", "class")
    dedup = metrics["loadgen_dedup_ratio"]
    assert isinstance(dedup, LabeledGauge)
    assert tuple(dedup.labelnames) == ("scenario",)
    # any series already recorded stays inside the closed sets
    for (scenario, kind), _c in events._items():
        assert scenario in SCENARIOS and kind in EVENT_KINDS
    for (scenario, cls), _c in sheds._items():
        assert scenario in SCENARIOS and cls in CLASS_LABELS
    for (scenario,), _c in dedup._items():
        assert scenario in SCENARIOS

    problems = []
    for name, m in metrics.items():
        if not name.startswith("loadgen_"):
            continue
        if isinstance(m, (Counter, LabeledCounter)) \
                and not name.endswith("_total"):
            problems.append(f"counter {name} must end _total")
        if name.endswith("_total") \
                and not isinstance(m, (Counter, LabeledCounter)):
            problems.append(f"{name} ends _total but is not a counter")
        if isinstance(m, (Gauge, LabeledGauge)) \
                and not name.endswith(("_ratio", "_seconds",
                                       "_per_second")):
            problems.append(f"gauge {name} needs a unit suffix")
        if _DURATION_HINT.search(name) and not name.endswith("_seconds"):
            problems.append(f"duration metric {name} must end _seconds")
    assert not problems, "\n".join(problems)

    # the combined exposition stays structurally valid with the new
    # families declared (HELP/TYPE from scrape 1)
    fams = parse_exposition(GLOBAL_REGISTRY.expose())
    for fam in ("loadgen_events_total", "loadgen_sheds_total",
                "loadgen_dedup_ratio"):
        assert fam in fams and fams[fam]["type"] is not None


def test_dispatch_ledger_family_label_contract():
    """The PR-13 dispatch-ledger families must not drift: the
    padding-waste gauge carries exactly one `stage` label from the
    CLOSED {lane, h2c} set (the lane series keeps the pre-ledger
    unlabeled gauge's semantics), the imbalance gauge is unlabeled,
    and the decision counter's three label vocabularies are all
    closed — {ladder, pippenger} x {0, pow-2 devices} x the five plan
    modes.  The ring itself is bounded memory."""
    import teku_tpu.ops.provider  # noqa: F401 - registers families
    from teku_tpu.infra import dispatchledger
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY

    metrics = GLOBAL_REGISTRY.metrics()
    waste = metrics["bls_dispatch_padding_waste_ratio"]
    assert isinstance(waste, LabeledGauge)
    assert tuple(waste.labelnames) == ("stage",)
    stages = set(dispatchledger.WASTE_STAGES)
    assert stages == {"lane", "h2c"}
    for key, _child in waste._items():
        assert set(key) <= stages, key
    # both stage series exist from scrape 1 (pre-seeded)
    assert {key[0] for key, _ in waste._items()} == stages

    assert isinstance(metrics["bls_mesh_shard_imbalance_ratio"], Gauge)

    dec = metrics["bls_dispatch_decision_total"]
    assert isinstance(dec, LabeledCounter)
    assert tuple(dec.labelnames) == ("msm_path", "mesh", "plan_mode")
    pow2_vocab = {"0"} | {str(1 << i) for i in range(1, 9)}
    for (msm_path, mesh, plan_mode), _child in dec._items():
        assert msm_path in ("ladder", "pippenger"), msm_path
        assert mesh in pow2_vocab, mesh
        assert plan_mode in dispatchledger.PLAN_MODES, plan_mode
    # the label folder can only emit the documented plan modes, on
    # arbitrary (including garbage) inputs
    for mode in (None, "latency", "throughput", "garbage", 3):
        for level in (None, 0, 1, 2, 9, "x"):
            assert dispatchledger.plan_mode_label(mode, level) \
                in dispatchledger.PLAN_MODES

    # bounded ring memory: capacity records retained, seq keeps counting
    led = dispatchledger.DispatchLedger(capacity=4,
                                        registry=MetricsRegistry())
    for _ in range(9):
        led.record({"lanes": 1,
                    "waste": {"lane": {"real": 1, "padded": 2}},
                    "msm": {"path": "ladder"}, "mesh": {"devices": 0},
                    "admission": {}})
    assert len(led.snapshot()) == 4
    assert led.recorded_total == 9

    # exposition stays structurally valid with the families declared
    fams = parse_exposition(GLOBAL_REGISTRY.expose())
    for fam in ("bls_dispatch_padding_waste_ratio",
                "bls_mesh_shard_imbalance_ratio",
                "bls_dispatch_decision_total"):
        assert fam in fams and fams[fam]["type"] is not None
