"""Fuzz harness: adversarial bytes against the SSZ decoders, the wire
codec, snappy, and the state transition.

The role of the reference's differential fuzzing entry points
(reference: fuzz/src/main/java/tech/pegasys/teku/fuzz/FuzzUtil.java:
68-88 — JNI-callable block/attestation/state mutators consumed by
beacon-fuzz): every mutated input must produce a TYPED rejection
(SszError / StateTransitionError / SnappyError / ValueError), never an
unhandled exception or a crash — the node's parsers sit on the network
edge.
"""

import random

import pytest

from teku_tpu.native.snappyc import SnappyError, uncompress
from teku_tpu.spec import config as C
from teku_tpu.spec.codec import deserialize_signed_block
from teku_tpu.spec.datastructures import SCHEMAS_MINIMAL as S
from teku_tpu.spec.builder import make_local_signer, produce_block
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.spec.transition import (state_transition,
                                      StateTransitionError)
from teku_tpu.ssz import SszError

CFG = C.MINIMAL
N_CASES = 300


def _mutations(data: bytes, rng: random.Random, n: int):
    for _ in range(n):
        kind = rng.randrange(5)
        b = bytearray(data)
        if not b:
            yield b""
            continue
        if kind == 0:      # single byte flip
            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
        elif kind == 1:    # truncate
            del b[rng.randrange(len(b)):]
        elif kind == 2:    # extend with junk
            b += rng.randbytes(rng.randrange(1, 64))
        elif kind == 3:    # corrupt an offset-table region
            pos = rng.randrange(min(len(b), 128))
            b[pos:pos + 4] = rng.randbytes(4)
        else:              # random splice
            pos = rng.randrange(len(b))
            b[pos:pos + 8] = rng.randbytes(8)
        yield bytes(b)


@pytest.fixture(scope="module")
def signed_block_bytes():
    state, sks = interop_genesis(CFG, 16)
    signed, _ = produce_block(CFG, state, 1,
                              make_local_signer(dict(enumerate(sks))))
    return S.SignedBeaconBlock.serialize(signed), state


def test_fuzz_block_decoder(signed_block_bytes):
    data, _ = signed_block_bytes
    rng = random.Random(1)
    crashes = 0
    for mutated in _mutations(data, rng, N_CASES):
        try:
            S.SignedBeaconBlock.deserialize(mutated)
        except (SszError, ValueError):
            pass                     # typed rejection: correct
        except Exception as exc:     # anything else is a parser bug
            crashes += 1
            print(type(exc).__name__, exc)
    assert crashes == 0


def test_fuzz_milestone_codec(signed_block_bytes):
    data, _ = signed_block_bytes
    rng = random.Random(2)
    for mutated in _mutations(data, rng, N_CASES):
        try:
            deserialize_signed_block(CFG, mutated)
        except (SszError, ValueError):
            pass


def test_fuzz_state_decoder(signed_block_bytes):
    _, state = signed_block_bytes
    data = S.BeaconState.serialize(state)
    rng = random.Random(3)
    for mutated in _mutations(data, rng, 60):   # states are big
        try:
            S.BeaconState.deserialize(mutated)
        except (SszError, ValueError):
            pass


def test_fuzz_attestation_decoder():
    att = S.Attestation(
        aggregation_bits=(True, False, True),
        signature=b"\x11" * 96)
    data = S.Attestation.serialize(att)
    rng = random.Random(4)
    for mutated in _mutations(data, rng, N_CASES):
        try:
            S.Attestation.deserialize(mutated)
        except (SszError, ValueError):
            pass


def test_fuzz_snappy_decoder():
    rng = random.Random(5)
    base = uncompress.__module__ and b"\x20" + rng.randbytes(40)
    for mutated in _mutations(base, rng, N_CASES):
        try:
            uncompress(mutated)
        except SnappyError:
            pass


def test_fuzz_state_transition_rejects_mutants(signed_block_bytes):
    """Decodable mutants must be REJECTED by the transition with the
    typed error, never imported and never crashing the engine."""
    data, state = signed_block_bytes
    rng = random.Random(6)
    tried = 0
    for mutated in _mutations(data, rng, 80):
        try:
            blk = S.SignedBeaconBlock.deserialize(mutated)
        except (SszError, ValueError):
            continue
        if S.SignedBeaconBlock.serialize(blk) == data:
            continue                 # survived unchanged
        if blk.message.slot > state.slot + 2 * CFG.SLOTS_PER_EPOCH:
            # the node's future-block gate fires BEFORE the transition
            # (Store.on_block: current_slot < block.slot -> reject);
            # the raw transition would walk every intervening slot
            continue
        tried += 1
        try:
            state_transition(CFG, state, blk, validate_result=True)
            raise AssertionError("mutated block was accepted!")
        except StateTransitionError:
            pass
        except AssertionError:
            raise
    assert tried >= 5                # the corpus really got exercised