"""Fuzz harness: adversarial bytes against the SSZ decoders, the wire
codec, snappy, and the state transition.

The role of the reference's differential fuzzing entry points
(reference: fuzz/src/main/java/tech/pegasys/teku/fuzz/FuzzUtil.java:
68-88 — JNI-callable block/attestation/state mutators consumed by
beacon-fuzz): every mutated input must produce a TYPED rejection
(SszError / StateTransitionError / SnappyError / ValueError), never an
unhandled exception or a crash — the node's parsers sit on the network
edge.
"""

import random

import pytest

from teku_tpu.native.snappyc import SnappyError, uncompress
from teku_tpu.spec import config as C
from teku_tpu.spec.codec import deserialize_signed_block
from teku_tpu.spec.datastructures import SCHEMAS_MINIMAL as S
from teku_tpu.spec.builder import make_local_signer, produce_block
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.spec.transition import (state_transition,
                                      StateTransitionError)
from teku_tpu.ssz import SszError

CFG = C.MINIMAL
N_CASES = 300


def _mutations(data: bytes, rng: random.Random, n: int):
    for _ in range(n):
        kind = rng.randrange(5)
        b = bytearray(data)
        if not b:
            yield b""
            continue
        if kind == 0:      # single byte flip
            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
        elif kind == 1:    # truncate
            del b[rng.randrange(len(b)):]
        elif kind == 2:    # extend with junk
            b += rng.randbytes(rng.randrange(1, 64))
        elif kind == 3:    # corrupt an offset-table region
            pos = rng.randrange(min(len(b), 128))
            b[pos:pos + 4] = rng.randbytes(4)
        else:              # random splice
            pos = rng.randrange(len(b))
            b[pos:pos + 8] = rng.randbytes(8)
        yield bytes(b)


@pytest.fixture(scope="module")
def signed_block_bytes():
    state, sks = interop_genesis(CFG, 16)
    signed, _ = produce_block(CFG, state, 1,
                              make_local_signer(dict(enumerate(sks))))
    return S.SignedBeaconBlock.serialize(signed), state


def test_fuzz_block_decoder(signed_block_bytes):
    data, _ = signed_block_bytes
    rng = random.Random(1)
    crashes = 0
    for mutated in _mutations(data, rng, N_CASES):
        try:
            S.SignedBeaconBlock.deserialize(mutated)
        except (SszError, ValueError):
            pass                     # typed rejection: correct
        except Exception as exc:     # anything else is a parser bug
            crashes += 1
            print(type(exc).__name__, exc)
    assert crashes == 0


def test_fuzz_milestone_codec(signed_block_bytes):
    data, _ = signed_block_bytes
    rng = random.Random(2)
    for mutated in _mutations(data, rng, N_CASES):
        try:
            deserialize_signed_block(CFG, mutated)
        except (SszError, ValueError):
            pass


def test_fuzz_state_decoder(signed_block_bytes):
    _, state = signed_block_bytes
    data = S.BeaconState.serialize(state)
    rng = random.Random(3)
    for mutated in _mutations(data, rng, 60):   # states are big
        try:
            S.BeaconState.deserialize(mutated)
        except (SszError, ValueError):
            pass


def test_fuzz_attestation_decoder():
    att = S.Attestation(
        aggregation_bits=(True, False, True),
        signature=b"\x11" * 96)
    data = S.Attestation.serialize(att)
    rng = random.Random(4)
    for mutated in _mutations(data, rng, N_CASES):
        try:
            S.Attestation.deserialize(mutated)
        except (SszError, ValueError):
            pass


def test_fuzz_snappy_decoder():
    rng = random.Random(5)
    base = uncompress.__module__ and b"\x20" + rng.randbytes(40)
    for mutated in _mutations(base, rng, N_CASES):
        try:
            uncompress(mutated)
        except SnappyError:
            pass


def test_fuzz_state_transition_rejects_mutants(signed_block_bytes):
    """Decodable mutants must be REJECTED by the transition with the
    typed error, never imported and never crashing the engine."""
    data, state = signed_block_bytes
    rng = random.Random(6)
    tried = 0
    for mutated in _mutations(data, rng, 80):
        try:
            blk = S.SignedBeaconBlock.deserialize(mutated)
        except (SszError, ValueError):
            continue
        if S.SignedBeaconBlock.serialize(blk) == data:
            continue                 # survived unchanged
        if blk.message.slot > state.slot + 2 * CFG.SLOTS_PER_EPOCH:
            # the node's future-block gate fires BEFORE the transition
            # (Store.on_block: current_slot < block.slot -> reject);
            # the raw transition would walk every intervening slot
            continue
        tried += 1
        try:
            state_transition(CFG, state, blk, validate_result=True)
            raise AssertionError("mutated block was accepted!")
        except StateTransitionError:
            pass
        except AssertionError:
            raise
    assert tried >= 5                # the corpus really got exercised

def test_fuzz_wire_encoding_payloads():
    """Spec ssz_snappy payload decoder: mutated uvarint prefixes and
    framing streams must raise EncodingError (or SnappyError at the
    block layer), never crash or return wrong-length data."""
    # teku_tpu.networking imports the noise transport, whose AEAD
    # primitives need the optional `cryptography` wheel
    pytest.importorskip(
        "cryptography",
        reason="networking stack needs the optional cryptography wheel")
    from teku_tpu.networking import encoding as E
    rng = random.Random(71)
    base = E.encode_payload(rng.randbytes(5000))
    for case in _mutations(base, rng, N_CASES):
        try:
            ssz, _ = E.decode_payload(case)
        except (E.EncodingError, SnappyError, ValueError):
            continue
        # survivors must honour their own length prefix
        want, _ = E.read_uvarint(case)
        assert len(ssz) == want


def test_fuzz_gossip_control_decoder():
    """Gossipsub control frames: arbitrary mutations either decode to
    well-formed lists or raise ValueError for the scoring layer."""
    pytest.importorskip(
        "cryptography",
        reason="networking stack needs the optional cryptography wheel")
    from teku_tpu.networking import gossip as G
    rng = random.Random(72)
    base = G.encode_control(
        subs=[(True, "topic_a"), (False, "topic_b")],
        graft=["topic_c"], prune=["topic_d"],
        ihave=[("topic_e", [rng.randbytes(20) for _ in range(4)])],
        iwant=[rng.randbytes(20)])[1:]
    for case in _mutations(base, rng, N_CASES):
        try:
            subs, graft, prune, ihave, iwant = G.decode_control(case)
        except ValueError:
            continue
        for mids in (mids for _, mids in ihave):
            assert all(len(m) == 20 for m in mids)
        assert all(len(m) == 20 for m in iwant)


def test_fuzz_discovery_records():
    """Signed node records: any mutation that survives decoding must
    still verify — i.e. decode() never admits a tampered record."""
    pytest.importorskip(
        "cryptography",
        reason="ed25519 identities need the optional cryptography "
               "wheel")
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey)
    from teku_tpu.networking import discv5 as D
    rng = random.Random(73)
    identity = Ed25519PrivateKey.generate()
    record = D.make_record(identity, rng.randbytes(32),
                           b"\x01\x02\x03\x04", "10.1.2.3", 9000, 9001)
    base = record.encode()
    admitted = 0
    for case in _mutations(base, rng, N_CASES):
        try:
            decoded = D.NodeRecord.decode(case)
        except (ValueError, UnicodeDecodeError):
            continue          # any OTHER exception type = harness fail
        # decode() verifies internally: surviving = untampered body
        assert decoded._signing_body() == record._signing_body()
        admitted += 1
    assert admitted <= N_CASES // 3      # extend-with-junk cases only


def test_fuzz_noise_handshake_messages():
    """Noise handshake: mutated message-2/3 bytes must surface as
    NoiseError (AEAD/shape), never as an unauthenticated success."""
    pytest.importorskip(
        "cryptography",
        reason="noise AEAD needs the optional cryptography wheel")
    from teku_tpu.networking import noise as N
    rng = random.Random(74)
    a_sk, _ = N.generate_static_keypair()
    b_sk, _ = N.generate_static_keypair()
    ini0 = N.XXHandshake(True, a_sk)
    res = N.XXHandshake(False, b_sk)
    res.read_message_1(ini0.write_message_1())
    msg2 = res.write_message_2()
    for case in _mutations(msg2, rng, N_CASES):
        if case == msg2:
            continue
        ini = N.XXHandshake(True, a_sk)
        res2 = N.XXHandshake(False, b_sk)
        res2.read_message_1(ini.write_message_1())   # fresh transcript
        try:
            ini.read_message_2(case)
        except N.NoiseError:
            continue
        # the mutated message came from a DIFFERENT handshake
        # transcript, so even byte-shape-valid cases must fail AEAD
        raise AssertionError("tampered message 2 accepted")
