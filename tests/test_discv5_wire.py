"""Spec discv5 v5.1 wire: ENR (EIP-778 published vector), RLP,
secp256k1, packet masking, WHOAREYOU handshake, message codec.

The EIP-778 example record is an INDEPENDENTLY PUBLISHED vector
(signed by the spec authors' key) — decoding, signature verification
and node-id derivation against it validate keccak256, RLP, secp256k1
and record canonicalization without a foreign client binary
(reference: the discovery library behind DiscV5Service.java speaks
this exact format).
"""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio
import secrets as _secrets

import pytest

from teku_tpu.networking import rlp, secp256k1 as EC
from teku_tpu.networking import discv5_wire as W
from teku_tpu.networking.enr import Enr, EnrError
from teku_tpu.networking.keccak import keccak256

EIP778_TEXT = (
    "enr:-IS4QHCYrYZbAKWCBRlAy5zzaDZXJBGkcnh4MHcBFZntXNFrdvJjX04jRzjz"
    "CBOonrkTfj499SZuOh8R33Ls8RRcy5wBgmlkgnY0gmlwhH8AAAGJc2VjcDI1Nmsx"
    "oQPKY0yuDUmstAHYpMa2_oxVtw0RW_QAdpzBQA8yWM0xOIN1ZHCCdl8")
EIP778_NODE_ID = ("a448f24c6d18e575453db13171562b71999873db5b286df957"
                  "af199ec94617f7")
EIP778_SECRET = int("b71c71a67e1177ad4e901695e1b4b9ee17ae16c6668d313e"
                    "ac2f96dbcda3f291", 16)


# -- primitives -------------------------------------------------------------

def test_keccak256_known_vectors():
    assert keccak256(b"").hex() == (
        "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470")
    assert keccak256(b"abc").hex() == (
        "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45")


def test_rlp_roundtrip_and_canonical():
    cases = [b"", b"\x01", b"\x7f", b"\x80", b"dog",
             [b"cat", b"dog"], [], [b"", [b"a", [b"b"]]],
             b"x" * 56, [b"y" * 60, b"z"]]
    for item in cases:
        assert rlp.decode(rlp.encode(item)) == item
    # canonical single byte: [0x81, 0x05] is invalid (must be 0x05)
    with pytest.raises(rlp.RlpError):
        rlp.decode(bytes([0x81, 0x05]))
    with pytest.raises(rlp.RlpError):
        rlp.decode(rlp.encode(b"hi") + b"\x00")   # trailing bytes


def test_secp256k1_sign_verify_ecdh():
    sk_a = 0x1234567890ABCDEF1234
    sk_b = 0xFEDCBA09876543210
    pub_a, pub_b = EC.pubkey(sk_a), EC.pubkey(sk_b)
    digest = keccak256(b"message")
    sig = EC.sign(sk_a, digest)
    assert EC.verify(pub_a, digest, sig)
    assert not EC.verify(pub_b, digest, sig)
    assert not EC.verify(pub_a, keccak256(b"other"), sig)
    # ECDH agrees in both directions and returns the compressed point
    s1 = EC.ecdh(sk_a, pub_b)
    s2 = EC.ecdh(sk_b, pub_a)
    assert s1 == s2 and len(s1) == 33 and s1[0] in (2, 3)
    # compression round trip
    assert EC.decompress(EC.compress(pub_a)) == pub_a


# -- ENR --------------------------------------------------------------------

def test_enr_eip778_published_vector():
    rec = Enr.from_text(EIP778_TEXT)
    assert rec.verify()
    assert rec.node_id.hex() == EIP778_NODE_ID
    assert rec.seq == 1
    assert rec.ip == "127.0.0.1" and rec.udp == 30303
    # the same private key reproduces the same node identity
    mine = Enr.create(EIP778_SECRET, seq=1, ip="127.0.0.1", udp=30303)
    assert mine.node_id.hex() == EIP778_NODE_ID
    assert Enr.from_text(mine.to_text()).verify()


def test_enr_rejects_tampering():
    rec = Enr.from_text(EIP778_TEXT)
    # flip the ip: signature no longer covers the content
    bad = Enr(rec.seq, dict(rec.pairs), rec.signature)
    bad.pairs[b"ip"] = bytes([10, 0, 0, 1])
    assert not bad.verify()
    with pytest.raises(EnrError):
        Enr.from_rlp(bad.to_rlp())
    # unsorted keys are rejected structurally
    raw = rlp.encode([rec.signature, rlp.encode_uint(rec.seq),
                      b"zz", b"1", b"aa", b"2"])
    with pytest.raises(EnrError):
        Enr.from_rlp(raw)


# -- packet codec -----------------------------------------------------------

def _identity(seed: int):
    sk = int.from_bytes(_secrets.token_bytes(32), "big") % EC.N or seed
    enr = Enr.create(sk, seq=1, ip="127.0.0.1", udp=9000 + seed)
    return sk, enr


def test_packet_masking_roundtrip():
    _, enr = _identity(1)
    nonce = b"\x0e" * 12
    pkt = W.encode_packet(enr.node_id, W.FLAG_MESSAGE, nonce,
                          b"\xaa" * 32, b"ciphertext")
    flag, got_nonce, authdata, ct, ad = W.decode_packet(enr.node_id,
                                                       pkt)
    assert flag == W.FLAG_MESSAGE
    assert got_nonce == nonce
    assert authdata == b"\xaa" * 32
    assert ct == b"ciphertext"
    # wrong destination cannot even parse the header
    with pytest.raises(W.WireError):
        W.decode_packet(b"\x77" * 32, pkt)


def test_message_codec_roundtrip():
    _, enr = _identity(2)
    ping = W.encode_ping(b"\x01\x02", 7)
    mtype, fields = W.decode_message(ping)
    assert mtype == W.MSG_PING and fields["enr_seq"] == 7
    pong = W.encode_pong(b"\x01\x02", 7, "10.1.2.3", 30303)
    mtype, fields = W.decode_message(pong)
    assert fields["ip"] == "10.1.2.3" and fields["port"] == 30303
    fn = W.encode_findnode(b"\x09", [256, 255, 0])
    mtype, fields = W.decode_message(fn)
    assert fields["distances"] == [256, 255, 0]
    nodes = W.encode_nodes(b"\x09", 1, [enr])
    mtype, fields = W.decode_message(nodes)
    assert fields["records"][0].node_id == enr.node_id


# -- the full handshake state machine ---------------------------------------

def test_whoareyou_handshake_and_session_messages():
    sk_a, enr_a = _identity(3)
    sk_b, enr_b = _identity(4)
    a = W.Discv5Wire(sk_a, enr_a)
    b = W.Discv5Wire(sk_b, enr_b)

    # A -> B: first contact (random-key packet carrying a PING intent)
    ping = W.encode_ping(b"\x01", enr_a.seq)
    dg1 = a.initial_packet(enr_b, ping)
    kind, challenge_dg = b.handle_datagram(dg1)
    assert kind == "whoareyou_needed"

    # B -> A: WHOAREYOU; A answers with the handshake packet
    kind, handshake_dg = a.handle_datagram(challenge_dg,
                                           peer_enr_hint=enr_b)
    assert kind == "handshake"

    # B verifies the id-signature, derives keys, reads the PING
    kind, src, mtype, fields = b.handle_datagram(handshake_dg)
    assert kind == "message" and src == enr_a.node_id
    assert mtype == W.MSG_PING and fields["request_id"] == b"\x01"

    # established sessions carry ordinary packets BOTH ways
    pong = W.encode_pong(b"\x01", enr_b.seq, "127.0.0.1", 9004)
    kind, src, mtype, fields = a.handle_datagram(
        b.message_packet(enr_a.node_id, pong))
    assert kind == "message" and mtype == W.MSG_PONG

    findnode = W.encode_findnode(b"\x02", [W.log2_distance(
        enr_a.node_id, enr_b.node_id)])
    kind, src, mtype, fields = b.handle_datagram(
        a.message_packet(enr_b.node_id, findnode))
    assert mtype == W.MSG_FINDNODE

    nodes = W.encode_nodes(b"\x02", 1, [enr_b])
    kind, src, mtype, fields = a.handle_datagram(
        b.message_packet(enr_a.node_id, nodes))
    assert mtype == W.MSG_NODES
    assert fields["records"][0].verify()
    assert fields["records"][0].node_id == enr_b.node_id


def test_handshake_rejects_forged_identity():
    """An attacker answering the WHOAREYOU with a signature from the
    WRONG key must be rejected."""
    sk_a, enr_a = _identity(5)
    sk_b, enr_b = _identity(6)
    sk_evil, enr_evil = _identity(7)
    a = W.Discv5Wire(sk_a, enr_a)
    b = W.Discv5Wire(sk_b, enr_b)
    evil = W.Discv5Wire(sk_evil, enr_a)   # claims A's record/node-id

    ping = W.encode_ping(b"\x01", enr_a.seq)
    dg1 = a.initial_packet(enr_b, ping)
    _, challenge_dg = b.handle_datagram(dg1)
    # evil intercepts the challenge addressed to A's node id: to even
    # read it, it must present A's node id; its handshake carries A's
    # record but a signature under its own key
    evil._awaiting_whoareyou = dict(a._awaiting_whoareyou)
    kind, forged = evil.handle_datagram(challenge_dg,
                                        peer_enr_hint=enr_b)
    assert kind == "handshake"
    with pytest.raises(W.WireError):
        b.handle_datagram(forged)


@pytest.mark.slow
def test_handshake_over_real_udp_sockets():
    """The same flow over actual UDP datagrams on localhost."""
    sk_a, enr_a = _identity(8)
    sk_b, enr_b = _identity(9)

    async def run():
        loop = asyncio.get_running_loop()
        inbox_a: asyncio.Queue = asyncio.Queue()
        inbox_b: asyncio.Queue = asyncio.Queue()

        class Proto(asyncio.DatagramProtocol):
            def __init__(self, inbox):
                self.inbox = inbox

            def datagram_received(self, data, addr):
                self.inbox.put_nowait((data, addr))

        ta, _ = await loop.create_datagram_endpoint(
            lambda: Proto(inbox_a), local_addr=("127.0.0.1", 0))
        tb, _ = await loop.create_datagram_endpoint(
            lambda: Proto(inbox_b), local_addr=("127.0.0.1", 0))
        addr_a = ta.get_extra_info("sockname")
        addr_b = tb.get_extra_info("sockname")
        a = W.Discv5Wire(sk_a, enr_a)
        b = W.Discv5Wire(sk_b, enr_b)
        try:
            ta.sendto(a.initial_packet(
                enr_b, W.encode_ping(b"\x07", 1)), addr_b)
            dg, src = await asyncio.wait_for(inbox_b.get(), 5)
            kind, reply = b.handle_datagram(dg)
            assert kind == "whoareyou_needed"
            tb.sendto(reply, src)
            dg, _ = await asyncio.wait_for(inbox_a.get(), 5)
            kind, reply = a.handle_datagram(dg, peer_enr_hint=enr_b)
            assert kind == "handshake"
            ta.sendto(reply, addr_b)
            dg, _ = await asyncio.wait_for(inbox_b.get(), 5)
            kind, src_id, mtype, fields = b.handle_datagram(dg)
            assert mtype == W.MSG_PING
            tb.sendto(b.message_packet(
                enr_a.node_id, W.encode_pong(
                    fields["request_id"], 1, "127.0.0.1",
                    addr_a[1])), addr_a)
            dg, _ = await asyncio.wait_for(inbox_a.get(), 5)
            kind, src_id, mtype, fields = a.handle_datagram(dg)
            assert mtype == W.MSG_PONG
            assert fields["port"] == addr_a[1]
        finally:
            ta.close()
            tb.close()

    asyncio.run(run())


@pytest.mark.slow
def test_node_identity_serves_verifiable_spec_enr():
    """/eth/v1/node/identity publishes a real EIP-778 record carrying
    the network's fork digest."""
    from teku_tpu.networking import NetworkedNode
    from teku_tpu.spec import create_spec
    from teku_tpu.spec import helpers as H

    async def run():
        spec = create_spec("minimal")
        state, _ = spec.interop_genesis(8)
        nn = NetworkedNode(spec, state)
        rec = Enr.from_text(nn.enr.to_text())
        assert rec.verify()
        digest = H.compute_fork_digest(
            spec.config.GENESIS_FORK_VERSION,
            state.genesis_validators_root)
        assert rec.get("eth2")[:4] == digest
        assert rec.get("attnets") == bytes(8)
        from teku_tpu.api import BeaconRestApi
        api = BeaconRestApi(nn.node, nn)
        out = await api._identity()
        served = Enr.from_text(out["data"]["enr"])
        assert served.node_id == rec.node_id

    asyncio.run(run())
