"""Chain database: persist a running chain, restart, resume — the
checkpoint/resume surface (reference: StoreBuilder + StorageBackedRecentChainData)."""

import pytest

from teku_tpu.spec import config as C, create_spec
from teku_tpu.spec.builder import (make_local_signer, produce_attestations,
                                   produce_block)
from teku_tpu.spec.datastructures import SCHEMAS_MINIMAL as S
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.storage import Store
from teku_tpu.storage.database import (ARCHIVE, Database,
                                       PersistentChainStorage, PRUNE)

CFG = C.MINIMAL


def _build_chain(n_slots: int):
    spec = create_spec("minimal")
    state, sks = interop_genesis(CFG, 32)
    signer = make_local_signer(dict(enumerate(sks)))
    anchor = S.BeaconBlock(slot=0, parent_root=bytes(32),
                           state_root=state.htr(), body=S.BeaconBlockBody())
    store = Store(CFG, state, anchor)
    blocks = []
    atts = []
    cur = state
    for slot in range(1, n_slots + 1):
        store.on_tick(state.genesis_time + slot * CFG.SECONDS_PER_SLOT)
        signed, post = produce_block(CFG, cur, slot, signer,
                                     attestations=atts)
        store.on_block(signed)
        atts = produce_attestations(CFG, post, slot, signed.message.htr(),
                                    signer)
        blocks.append((signed, post))
        cur = post
    return spec, store, blocks, anchor, state


@pytest.mark.slow
def test_persist_restart_resume(tmp_path):
    spec, store, blocks, anchor, genesis_state = _build_chain(
        4 * CFG.SLOTS_PER_EPOCH)
    db = Database(tmp_path / "chain.db", spec, mode=PRUNE)
    storage = PersistentChainStorage(db)
    db.save_anchor(anchor, genesis_state)
    for signed, post in blocks:
        storage.on_block_imported(signed, post)
    # finalization advances the anchor and prunes
    assert store.finalized_checkpoint.epoch >= 1
    storage.on_finalized(store, store.finalized_checkpoint)
    db.close()

    # restart: rebuild the fork-choice store from disk
    db2 = Database(tmp_path / "chain.db", spec, mode=PRUNE)
    restored = PersistentChainStorage(db2).restore_store(spec)
    assert restored is not None
    assert (restored.finalized_checkpoint.root
            == store.finalized_checkpoint.root)
    # head matches the original chain's tip
    assert restored.get_head() == store.get_head()
    tip_root = blocks[-1][0].message.htr()
    assert restored.get_head() == tip_root
    # blocks before the finalized anchor were pruned from disk
    first_root = blocks[0][0].message.htr()
    assert db2.get_block(first_root) is None
    db2.close()


def test_archive_mode_keeps_states(tmp_path):
    spec, store, blocks, anchor, genesis_state = _build_chain(3)
    db = Database(tmp_path / "arch.db", spec, mode=ARCHIVE)
    db.save_anchor(anchor, genesis_state)
    for signed, post in blocks:
        db.save_block(signed, post)
    root = blocks[1][0].message.htr()
    st = db.get_state(root)
    assert st is not None and st.htr() == blocks[1][1].htr()
    db.close()


def test_empty_database_returns_no_anchor(tmp_path):
    spec = create_spec("minimal")
    db = Database(tmp_path / "empty.db", spec)
    assert db.load_anchor() is None
    assert PersistentChainStorage(db).restore_store(spec) is None
    db.close()
