"""Chain database: persist a running chain, restart, resume — the
checkpoint/resume surface (reference: StoreBuilder + StorageBackedRecentChainData)."""

import pytest

from teku_tpu.spec import config as C, create_spec
from teku_tpu.spec.builder import (make_local_signer, produce_attestations,
                                   produce_block)
from teku_tpu.spec.datastructures import SCHEMAS_MINIMAL as S
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.storage import Store
from teku_tpu.storage.database import (ARCHIVE, Database,
                                       PersistentChainStorage, PRUNE)

CFG = C.MINIMAL


def _build_chain(n_slots: int):
    spec = create_spec("minimal")
    state, sks = interop_genesis(CFG, 32)
    signer = make_local_signer(dict(enumerate(sks)))
    anchor = S.BeaconBlock(slot=0, parent_root=bytes(32),
                           state_root=state.htr(), body=S.BeaconBlockBody())
    store = Store(CFG, state, anchor)
    blocks = []
    atts = []
    cur = state
    for slot in range(1, n_slots + 1):
        store.on_tick(state.genesis_time + slot * CFG.SECONDS_PER_SLOT)
        signed, post = produce_block(CFG, cur, slot, signer,
                                     attestations=atts)
        store.on_block(signed)
        atts = produce_attestations(CFG, post, slot, signed.message.htr(),
                                    signer)
        blocks.append((signed, post))
        cur = post
    return spec, store, blocks, anchor, state


@pytest.mark.slow
def test_persist_restart_resume(tmp_path):
    spec, store, blocks, anchor, genesis_state = _build_chain(
        4 * CFG.SLOTS_PER_EPOCH)
    db = Database(tmp_path / "chain.db", spec, mode=PRUNE)
    storage = PersistentChainStorage(db)
    db.save_anchor(anchor, genesis_state)
    for signed, post in blocks:
        storage.on_block_imported(signed, post)
    # finalization advances the anchor and prunes
    assert store.finalized_checkpoint.epoch >= 1
    storage.on_finalized(store, store.finalized_checkpoint)
    db.close()

    # restart: rebuild the fork-choice store from disk
    db2 = Database(tmp_path / "chain.db", spec, mode=PRUNE)
    restored = PersistentChainStorage(db2).restore_store(spec)
    assert restored is not None
    assert (restored.finalized_checkpoint.root
            == store.finalized_checkpoint.root)
    # head matches the original chain's tip
    assert restored.get_head() == store.get_head()
    tip_root = blocks[-1][0].message.htr()
    assert restored.get_head() == tip_root
    # blocks before the finalized anchor were pruned from disk
    first_root = blocks[0][0].message.htr()
    assert db2.get_block(first_root) is None
    db2.close()


def test_archive_mode_keeps_states(tmp_path):
    spec, store, blocks, anchor, genesis_state = _build_chain(3)
    db = Database(tmp_path / "arch.db", spec, mode=ARCHIVE,
                  state_snapshot_interval=1)     # snapshot every slot
    db.save_anchor(anchor, genesis_state)
    for signed, post in blocks:
        db.save_block(signed, post)
    root = blocks[1][0].message.htr()
    st = db.get_state(root)
    assert st is not None and st.htr() == blocks[1][1].htr()
    db.close()


@pytest.mark.slow
def test_archive_snapshots_bound_storage_and_regenerate(tmp_path):
    """Archive mode stores ~1/N full states; everything between comes
    back byte-exact by snapshot + block replay (reference
    StateCacheLoader / store regeneration)."""
    N = 8
    n_slots = 2 * CFG.SLOTS_PER_EPOCH
    spec, store, blocks, anchor, genesis_state = _build_chain(n_slots)
    db = Database(tmp_path / "arch.db", spec, mode=ARCHIVE,
                  state_snapshot_interval=N)
    storage = PersistentChainStorage(db)
    db.save_anchor(anchor, genesis_state)
    for signed, post in blocks:
        storage.on_block_imported(signed, post)
    # stored full states: snapshot slots + the anchor only
    stored = sum(1 for signed, _ in blocks
                 if db.get_state(signed.message.htr()) is not None)
    assert stored <= n_slots // N
    # every non-snapshot state regenerates exactly
    for signed, post in blocks:
        got = db.get_or_regenerate_state(signed.message.htr())
        assert got is not None
        assert got.htr() == post.htr(), signed.message.slot
    assert db.states_regenerated >= n_slots - stored
    db.close()


@pytest.mark.slow
def test_archive_restart_serves_any_historical_state_over_rest(tmp_path):
    """After a restart the hot store only holds the finalized anchor
    onward — the REST API must still serve any historical state (by
    slot) from the archive via regeneration."""
    import asyncio
    import json
    import urllib.request
    from teku_tpu.api import BeaconRestApi
    from teku_tpu.node.gossip import InMemoryGossipNetwork
    from teku_tpu.node.node import BeaconNode
    from teku_tpu.spec import Spec

    n_slots = 4 * CFG.SLOTS_PER_EPOCH
    spec, store, blocks, anchor, genesis_state = _build_chain(n_slots)
    db = Database(tmp_path / "arch.db", spec, mode=ARCHIVE,
                  state_snapshot_interval=8)
    storage = PersistentChainStorage(db)
    db.save_anchor(anchor, genesis_state)
    for signed, post in blocks:
        storage.on_block_imported(signed, post)
    assert store.finalized_checkpoint.epoch >= 1
    storage.on_finalized(store, store.finalized_checkpoint)
    db.close()

    # restart from disk
    db2 = Database(tmp_path / "arch.db", spec, mode=ARCHIVE,
                   state_snapshot_interval=8)
    restored = PersistentChainStorage(db2).restore_store(spec)
    assert restored is not None
    node = BeaconNode(Spec(CFG), genesis_state,
                      InMemoryGossipNetwork().endpoint(),
                      store=restored)

    async def run():
        api = BeaconRestApi(node, database=db2)
        await api.start()
        try:
            base = f"http://127.0.0.1:{api.port}"
            loop = asyncio.get_running_loop()

            def fetch(path):
                with urllib.request.urlopen(base + path,
                                            timeout=30) as r:
                    return json.loads(r.read())
            # historical slots BELOW the finalized anchor, none of
            # them snapshot slots — regeneration must kick in
            for slot in (3, 7, 13):
                post = next(p for s, p in blocks
                            if s.message.slot == slot)
                out = await loop.run_in_executor(
                    None, fetch, f"/eth/v1/beacon/states/{slot}/root")
                assert out["data"]["root"] == "0x" + post.htr().hex()
            assert db2.states_regenerated >= 1
        finally:
            await api.stop()
    asyncio.run(run())
    db2.close()


def test_empty_database_returns_no_anchor(tmp_path):
    spec = create_spec("minimal")
    db = Database(tmp_path / "empty.db", spec)
    assert db.load_anchor() is None
    assert PersistentChainStorage(db).restore_store(spec) is None
    db.close()


def test_prune_mode_writes_no_slot_index(tmp_path):
    """PRUNE deletes historical blocks, so it must not leave dangling
    slot-index entries the REST fallback would resolve into 500s."""
    spec, store, blocks, anchor, genesis_state = _build_chain(
        2 * CFG.SLOTS_PER_EPOCH)
    db = Database(tmp_path / "p.db", spec, mode=PRUNE)
    storage = PersistentChainStorage(db)
    db.save_anchor(anchor, genesis_state)
    for signed, post in blocks:
        storage.on_block_imported(signed, post)
    storage.on_finalized(store, store.finalized_checkpoint)
    for signed, _ in blocks:
        assert db.canonical_root_at_slot(signed.message.slot) is None
    assert db.canonical_root_at_slot(-1) is None
    db.close()
