"""Rewards REST endpoints: block reward decomposition, per-validator
attestation rewards, sync-committee rewards.

reference: data/beaconrestapi/.../handlers/v1/rewards/
(GetBlockRewards, PostAttestationRewards, PostSyncCommitteeRewards)
backed by RewardCalculator.java.
"""

import asyncio
import dataclasses
import json
import urllib.error
import urllib.request

import pytest

from teku_tpu.api import BeaconRestApi
from teku_tpu.node import Devnet
from teku_tpu.spec import config as C, Spec
from teku_tpu.spec import helpers as H


@pytest.mark.slow
def test_rewards_endpoints_on_altair_devnet():
    cfg = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0)
    spec = Spec(cfg)
    net = Devnet(n_nodes=1, n_validators=16, spec=spec)
    node = net.nodes[0]

    async def run():
        await net.start()
        api = BeaconRestApi(node)
        await api.start()
        try:
            await net.run_until_slot(3 * cfg.SLOTS_PER_EPOCH + 2)
            base = f"http://127.0.0.1:{api.port}"
            loop = asyncio.get_running_loop()

            def get(path):
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    return json.loads(r.read())

            def post(path, payload):
                req = urllib.request.Request(
                    base + path, data=json.dumps(payload).encode(),
                    method="POST",
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())

            # -- block rewards: decomposition sums to the exact
            #    proposer balance delta
            head_root = node.chain.head_root
            block = node.store.blocks[head_root]
            parent_state = node.chain.get_state(block.parent_root)
            post_state = node.chain.get_state(head_root)
            from teku_tpu.spec.transition import process_slots
            pre = parent_state
            if pre.slot < block.slot:
                pre = process_slots(cfg, pre, block.slot)
            expected_total = (post_state.balances[block.proposer_index]
                              - pre.balances[block.proposer_index])
            out = await loop.run_in_executor(
                None, get, "/eth/v1/beacon/rewards/blocks/head")
            data = out["data"]
            assert int(data["total"]) == expected_total
            assert (int(data["attestations"])
                    + int(data["sync_aggregate"])
                    + int(data["proposer_slashings"])
                    + int(data["attester_slashings"])) \
                == int(data["total"])
            assert int(data["proposer_index"]) == block.proposer_index

            # -- sync committee rewards: every committee seat reported,
            #    participants earn what absentees pay
            sync = await loop.run_in_executor(
                None, post, "/eth/v1/beacon/rewards/sync_committee/head",
                [])
            assert len(sync["data"]) == cfg.SYNC_COMMITTEE_SIZE
            rewards = [int(r["reward"]) for r in sync["data"]]
            bits = block.body.sync_aggregate.sync_committee_bits
            assert sum(1 for r in rewards if r > 0) == sum(bits)
            magnitudes = {abs(r) for r in rewards if r != 0}
            assert len(magnitudes) <= 1      # one participant_reward

            # filtered query returns only the asked validator
            only0 = await loop.run_in_executor(
                None, post, "/eth/v1/beacon/rewards/sync_committee/head",
                ["0"])
            assert all(r["validator_index"] == "0" for r in only0["data"])

            # -- attestation rewards: only SETTLED epochs (inclusion
            #    runs through epoch+1) — perfect devnet participation
            #    → actual == ideal at each tier
            # current-2 with current==3 → epoch 1 (epoch 0 is
            # degenerate: the slot-0 committee never attests)
            epoch = H.get_current_epoch(cfg, node.chain.head_state()) - 2
            att = await loop.run_in_executor(
                None, post,
                f"/eth/v1/beacon/rewards/attestations/{epoch}",
                ["0", "1"])
            totals = att["data"]["total_rewards"]
            assert [t["validator_index"] for t in totals] == ["0", "1"]
            ideal = {int(row["effective_balance"]): row
                     for row in att["data"]["ideal_rewards"]}
            for t in totals:
                vi = int(t["validator_index"])
                eb = node.chain.head_state().validators[vi] \
                    .effective_balance
                row = ideal[eb]
                for part in ("head", "target", "source"):
                    assert int(t[part]) == int(row[part]) > 0
                assert int(t["inactivity"]) == 0

            # not-yet-settled epochs (current and current-1) are 400
            current = H.get_current_epoch(cfg, node.chain.head_state())
            for unsettled in (current, current - 1):
                try:
                    await loop.run_in_executor(
                        None, post,
                        f"/eth/v1/beacon/rewards/attestations/"
                        f"{unsettled}", [])
                    raise AssertionError("expected 400")
                except urllib.error.HTTPError as exc:
                    assert exc.code == 400
            # pubkey-shaped ids are accepted per the API schema
            pk = node.chain.head_state().validators[3].pubkey
            by_pk = await loop.run_in_executor(
                None, post, "/eth/v1/beacon/rewards/sync_committee/head",
                ["0x" + pk.hex()])
            assert all(r["validator_index"] == "3"
                       for r in by_pk["data"])
        finally:
            await api.stop()
            await net.stop()
    asyncio.run(run())
