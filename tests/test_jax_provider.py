"""JaxBls12381 provider behind the facade — parity with the oracle.

Batch sizes are kept tiny (<= 4 triples) so the CPU-XLA compile cost of
each padded-size bucket is paid at most a handful of times.
"""

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.crypto.bls.pure_impl import G1_INFINITY, G2_INFINITY
from teku_tpu.ops.provider import JaxBls12381


@pytest.fixture(scope="module")
def jax_impl():
    impl = JaxBls12381()
    bls.set_implementation(impl)
    yield impl
    bls.reset_implementation()


SKS = [keygen(bytes([i]) * 32) for i in range(1, 5)]
PKS = None
MSG = b"attestation data root"


def _pks():
    global PKS
    if PKS is None:
        from teku_tpu.crypto.bls.pure_impl import PureBls12381
        p = PureBls12381()
        PKS = [p.secret_key_to_public_key(sk) for sk in SKS]
    return PKS


def test_verify_roundtrip(jax_impl):
    pk = _pks()[0]
    sig = bls.sign(SKS[0], MSG)
    assert bls.verify(pk, MSG, sig)
    assert not bls.verify(pk, b"other message", sig)
    assert not bls.verify(_pks()[1], MSG, sig)


def test_verify_garbage_inputs(jax_impl):
    pk = _pks()[0]
    sig = bls.sign(SKS[0], MSG)
    assert not bls.verify(pk[:-1], MSG, sig)       # truncated pk
    assert not bls.verify(pk, MSG, sig[:-1])       # truncated sig
    assert not bls.verify(G1_INFINITY, MSG, sig)   # infinity pk invalid
    assert not bls.verify(pk, MSG, G2_INFINITY)
    bad_sig = bytes([sig[0]]) + bytes(95)
    assert not bls.verify(pk, MSG, bad_sig)


def test_fast_aggregate_verify(jax_impl):
    sigs = [bls.sign(sk, MSG) for sk in SKS[:3]]
    agg = bls.aggregate_signatures(sigs)
    assert bls.fast_aggregate_verify(_pks()[:3], MSG, agg)
    assert not bls.fast_aggregate_verify(_pks()[:2], MSG, agg)
    assert not bls.fast_aggregate_verify(_pks()[:3], b"wrong", agg)


def test_aggregate_verify_distinct_messages(jax_impl):
    msgs = [b"m-%d" % i for i in range(3)]
    sigs = [bls.sign(sk, m) for sk, m in zip(SKS[:3], msgs)]
    agg = bls.aggregate_signatures(sigs)
    assert bls.aggregate_verify(_pks()[:3], msgs, agg)
    assert not bls.aggregate_verify(_pks()[:3], list(reversed(msgs)), agg)
    assert not bls.aggregate_verify(_pks()[:2], msgs[:2], agg)


def test_batch_verify_mixed(jax_impl):
    triples = []
    for i, sk in enumerate(SKS[:3]):
        msg = b"batch-%d" % i
        triples.append(([_pks()[i]], msg, bls.sign(sk, msg)))
    # multi-key triple (fast-aggregate semantics inside one lane)
    agg_msg = b"agg lane"
    agg_sig = bls.aggregate_signatures(
        [bls.sign(sk, agg_msg) for sk in SKS[:3]])
    triples.append((_pks()[:3], agg_msg, agg_sig))
    assert bls.batch_verify(triples)
    # one corrupted lane fails the whole batch
    bad = list(triples)
    bad[1] = (bad[1][0], b"tampered", bad[1][2])
    assert not bls.batch_verify(bad)


def test_batch_verify_infinity_sig_lane(jax_impl):
    # infinity signature with a real pubkey cannot verify
    triples = [([_pks()[0]], MSG, G2_INFINITY)]
    assert not bls.batch_verify(triples)


def test_prepare_complete_split(jax_impl):
    msg = b"split path"
    semis = [
        bls.prepare_batch_verify(([_pks()[i]], msg, bls.sign(SKS[i], msg)))
        for i in range(2)
    ]
    assert all(s is not None for s in semis)
    assert bls.complete_batch_verify(semis)
    assert bls.prepare_batch_verify(([], msg, G2_INFINITY)) is None
    assert not bls.complete_batch_verify(semis + [None])


def test_eth_wrappers(jax_impl):
    assert bls.eth_fast_aggregate_verify([], b"x", G2_INFINITY)
    with pytest.raises(ValueError):
        bls.eth_aggregate_pubkeys([])
    assert bls.public_key_is_valid(_pks()[0])
    assert not bls.public_key_is_valid(G1_INFINITY)
    assert not bls.public_key_is_valid(b"\x00" * 48)


def test_non_subgroup_signature_rejected(jax_impl):
    # an on-curve G2 point outside the subgroup must be rejected on device
    import random
    from teku_tpu.crypto.bls import curve as C, fields as F
    from teku_tpu.crypto.bls.constants import P
    rng = random.Random(5)
    while True:
        x = (rng.randrange(P), rng.randrange(P))
        rhs = F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), (4, 4))
        y = F.fq2_sqrt(rhs)
        if y is None:
            continue
        p = (x, y, F.FQ2_ONE)
        if not C.g2_in_subgroup(p):
            break
    bad_sig_bytes = C.g2_compress(p)  # compress doesn't subgroup-check
    assert not bls.verify(_pks()[0], MSG, bad_sig_bytes)
