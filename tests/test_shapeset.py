"""Shape-set registry: anti-drift pins against the dispatch path.

`ops/shapeset.py` is only useful if it CANNOT diverge from what
`provider._begin_dispatch` actually dispatches — a registry that
enumerates yesterday's buckets precompiles the wrong programs and the
compile wall comes back silently.  These tests pin the sharing:

- structurally: provider imports the shapeset module object and calls
  its bucket functions (no private copies);
- behaviorally: the policy constants equal the provider/loader knob
  defaults, and `batch_plan` reproduces the bucket decisions;
- the enumeration yields the kernel names `ops/verify.py` and
  `teku_tpu/parallel` register with the AOT store, deduplicated.
"""

import inspect

import pytest

from teku_tpu.ops import provider, shapeset
from teku_tpu.ops.provider import JaxBls12381


def test_provider_imports_shapeset_functions():
    # the module object itself is shared...
    assert provider.SS is shapeset
    # ...and every bucket decision in the dispatch path calls through
    # it: a private re-implementation is drift waiting to happen
    src = inspect.getsource(provider)
    for fn in ("SS.lane_bucket(", "SS.kmax_bucket(",
               "SS.group_rows(", "SS.group_bucket(",
               "SS.unique_bucket(", "SS.h2c_miss_bucket(",
               "SS.pk_validate_bucket(", "SS.shape_label("):
        assert fn in src, f"provider must bucket via shapeset: {fn}"


def test_policy_constants_match_provider_knob_defaults():
    impl = JaxBls12381(max_batch=8, min_bucket=4)
    assert impl._h2c_min_bucket == shapeset.H2C_MIN_BUCKET_DEFAULT
    assert impl._group_cap == shapeset.GROUP_CAP_DEFAULT


def test_service_tier_constants_match_loader_defaults():
    from teku_tpu.crypto.bls import loader
    sig = inspect.signature(loader.make_supervisor)
    assert sig.parameters["max_batch"].default \
        == shapeset.SERVICE_MAX_BATCH
    assert sig.parameters["min_bucket"].default \
        == shapeset.SERVICE_MIN_BUCKET


def test_bucket_helpers():
    assert shapeset.lane_bucket(5, 4) == 8
    assert shapeset.lane_bucket(1, 16) == 16
    assert shapeset.pk_validate_bucket(1) \
        == shapeset.PK_VALIDATE_FLOOR
    assert shapeset.pk_validate_bucket(33) == 64
    assert shapeset.h2c_miss_bucket(3, 8) == 8
    assert shapeset.h2c_miss_bucket(9, 8) == 16
    assert shapeset.shape_label(64, 2) == "64x2"
    assert shapeset.shape_label(64, 1, mesh_devices=4) == "64x1@m4"


def test_group_rows_polymorphic_over_counts_and_lane_lists():
    """The registry enumerates lane COUNTS; dispatch splits lane-index
    LISTS.  Same split rule, same row profile — or the enumerated
    group/miller shapes are not the dispatched ones."""
    counts = shapeset.group_rows([70, 3], group_cap=32)
    assert counts == [(0, 32), (0, 32), (0, 6), (1, 3)]
    lists = shapeset.group_rows([list(range(70)), [70, 71, 72]],
                                group_cap=32)
    assert [(u, len(c)) for u, c in lists] \
        == [(u, n) for u, n in counts]
    assert shapeset.group_bucket(counts) \
        == shapeset.group_bucket(lists) == 32


def test_batch_plan_all_unique_and_duplicated():
    plan = shapeset.batch_plan([1] * 12, min_bucket=4)
    assert plan["padded"] == 16 and plan["rows"] == 12
    assert plan["u_hm"] == 16
    assert plan["shape"] == "16x1"
    assert plan["h2c_bucket"] == 16, "cold boot: all rows miss"

    dup = shapeset.batch_plan([8] * 4, min_bucket=4, h2c_missing=0)
    assert dup["lanes"] == 32 and dup["rows"] == 4
    assert dup["group_bucket"] == 8
    assert dup["h2c_bucket"] == 0, "fully warm arena: no h2c program"


def test_warmup_profiles_shape():
    assert shapeset.warmup_profiles(4) == [
        ("x1", [1], None), ("x4", [1, 1, 1, 1], None)]
    profiles = shapeset.warmup_profiles(256)
    assert [name for name, _, _ in profiles] \
        == ["x1", "x256", "x256dup8"]
    name, groups, missing = profiles[2]
    assert groups == [8] * 32
    assert missing == 0, "dup8 rides the arena the x256 warm filled"


def test_serving_shapes_cover_warmup_profiles():
    shapes = shapeset.serving_shapes(max_batch=256, min_bucket=16)
    for _name, groups, missing in shapeset.warmup_profiles(256):
        plan = shapeset.batch_plan(groups, min_bucket=16,
                                   h2c_missing=missing)
        assert plan["shape"] in shapes
    assert "16x1" in shapes, "the x1 probe shape is a serving shape"


def test_enumerate_programs_names_and_dedup():
    from teku_tpu.ops import mxu
    mont = mxu.resolve()
    programs = list(shapeset.enumerate_programs(
        max_batch=8, min_bucket=4))
    kernels = [k for k, _avals, _meta in programs]
    assert f"pk_validate:{mont}" in kernels
    stages = {m["stage"] for _k, _a, m in programs}
    assert {"pk_validate", "h2c", "prepare", "miller",
            "finish"} <= stages
    # scalars comes on exactly one msm path per profile
    assert stages & {"scalars", "scalars_pip"}
    for k, _avals, meta in programs:
        if meta["stage"] not in ("pk_validate",):
            assert k.startswith("stage:"), k
            assert k.endswith(f":{mont}"), k
    # dedup: no (kernel, signature) appears twice
    from teku_tpu.infra import aotstore
    keys = [(k, aotstore.shape_sig(avals))
            for k, avals, _m in programs]
    assert len(keys) == len(set(keys))


def test_enumerate_programs_mesh_kernels():
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 virtual devices (conftest XLA_FLAGS)")
    from teku_tpu import parallel
    mesh = parallel.make_mesh(2, advertise=False)
    programs = list(shapeset.enumerate_programs(
        max_batch=8, min_bucket=4, mesh=mesh))
    mesh_progs = [(k, m) for k, _a, m in programs
                  if m["stage"] == "mesh_kernel"]
    assert mesh_progs, "mesh config must enumerate the sharded kernel"
    devices = [str(d) for d in mesh.devices.ravel()]
    for kernel, meta in mesh_progs:
        # the name the serving path registers for THIS device set —
        # a healed mesh over different devices must miss, never load
        # an executable bound to the wrong device assignment
        assert kernel == parallel.kernel_store_name(
            devices, "dp", meta["msm_path"])
    assert any(m["stage"] == "gather" for _k, _a, m in programs)
