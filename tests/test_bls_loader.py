"""BLS loader: provider selection at process start.

The node must boot on the accelerated provider (reference wires blst at
process start, Teku.java:74 + BLS.java:51-62) — and a devnet driven
end to end on the JAX provider must verify every signature through the
device kernel, which is the SURVEY §7 stage-5 success criterion.
"""

import asyncio

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import loader
from teku_tpu.crypto.bls.pure_impl import PureBls12381
from teku_tpu.ops.provider import JaxBls12381


@pytest.fixture(autouse=True)
def _restore_impl():
    yield
    bls.reset_implementation()


def test_pure_choice_installs_oracle():
    assert loader.configure("pure") == "pure"
    assert isinstance(bls.get_implementation(), PureBls12381)


def test_auto_installs_jax_on_working_backend():
    name = loader.configure("auto")
    assert name == "jax-tpu"
    assert isinstance(bls.get_implementation(), JaxBls12381)
    assert loader.current_name() == "jax-tpu"


def test_jax_choice_hard_fails_on_probe_timeout(monkeypatch):
    def wedge(max_batch, min_bucket):
        import time
        time.sleep(30)

    monkeypatch.setattr(loader, "_probe_jax", wedge)
    with pytest.raises(loader.BlsLoadError):
        loader.configure("jax", probe_timeout_s=0.2)


def test_auto_falls_back_on_probe_failure(monkeypatch):
    def boom(max_batch, min_bucket):
        raise RuntimeError("no accelerator")

    monkeypatch.setattr(loader, "_probe_jax", boom)
    assert loader.configure("auto", probe_timeout_s=5) == "pure"
    assert isinstance(bls.get_implementation(), PureBls12381)


def test_unknown_choice_rejected():
    with pytest.raises(ValueError):
        loader.configure("blst")


def test_devnet_runs_on_jax_provider():
    """End-to-end: a finalizing devnet whose gossip/import signatures
    all dispatch through the batched device kernel."""
    from teku_tpu.node import Devnet

    assert loader.configure("jax") == "jax-tpu"
    impl = bls.get_implementation()
    cfg_epochs = 3

    async def run():
        net = Devnet(n_nodes=1, n_validators=8)
        await net.start()
        try:
            last = cfg_epochs * net.spec.config.SLOTS_PER_EPOCH
            await net.run_until_slot(last)
            return net
        finally:
            await net.stop()

    net = asyncio.run(run())
    assert net.min_justified_epoch() >= 1
    # the proof the batcher fed the device: real dispatches happened
    assert impl.dispatch_count > 0
    assert impl.lanes_dispatched > 0
