"""Hash-to-G2 tests: expand_message_xmd, SSWU/isogeny, cofactor clearing."""

from teku_tpu.crypto.bls import curve as C, fields as F, hash_to_curve as H
from teku_tpu.crypto.bls.constants import DST_G2_POP, P


class TestExpandMessageXmd:
    def test_lengths(self):
        for n in (32, 64, 127, 128, 255, 256):
            out = H.expand_message_xmd(b"msg", b"DST", n)
            assert len(out) == n

    def test_deterministic_and_msg_sensitive(self):
        a = H.expand_message_xmd(b"msg", b"DST", 64)
        assert a == H.expand_message_xmd(b"msg", b"DST", 64)
        assert a != H.expand_message_xmd(b"msh", b"DST", 64)
        assert a != H.expand_message_xmd(b"msg", b"DSU", 64)

    def test_length_in_domain(self):
        # len_in_bytes is bound into b_0, so different lengths diverge fully
        a = H.expand_message_xmd(b"msg", b"DST", 32)
        b = H.expand_message_xmd(b"msg", b"DST", 64)
        assert b[:32] != a


class TestHashToField:
    def test_in_range_and_distinct(self):
        u = H.hash_to_field_fq2(b"some message", 2)
        assert len(u) == 2
        for el in u:
            assert 0 <= el[0] < P and 0 <= el[1] < P
        assert u[0] != u[1]


class TestMapToCurve:
    def test_sswu_output_on_iso_curve(self):
        for i in range(4):
            (u,) = H.hash_to_field_fq2(bytes([i]), 1)
            x, y = H.map_to_curve_sswu_g2(u)
            assert F.fq2_eq(F.fq2_sqr(y), H._gx_prime(x))

    def test_iso_output_on_e2(self):
        for i in range(4):
            (u,) = H.hash_to_field_fq2(bytes([i]), 1)
            p = H.iso_map_g2(H.map_to_curve_sswu_g2(u))
            assert C.is_on_curve(C.FQ2_OPS, C.from_affine(C.FQ2_OPS, *p))


class TestClearCofactor:
    def test_psi_matches_h_eff(self):
        for i in range(3):
            (u,) = H.hash_to_field_fq2(bytes([7 + i]), 1)
            p = C.from_affine(
                C.FQ2_OPS, *H.iso_map_g2(H.map_to_curve_sswu_g2(u)))
            fast = H.clear_cofactor_g2(p)
            slow = H.clear_cofactor_g2_slow(p)
            assert C.point_eq(C.FQ2_OPS, fast, slow)

    def test_psi_is_endomorphism(self):
        # psi(P + Q) = psi(P) + psi(Q) on the curve
        q1 = H.hash_to_g2(b"a")
        q2 = H.hash_to_g2(b"b")
        lhs = H.psi(C.point_add(C.FQ2_OPS, q1, q2))
        rhs = C.point_add(C.FQ2_OPS, H.psi(q1), H.psi(q2))
        assert C.point_eq(C.FQ2_OPS, lhs, rhs)


class TestHashToG2:
    def test_in_subgroup(self):
        for msg in (b"", b"abc", b"attestation data root"):
            p = H.hash_to_g2(msg)
            assert C.g2_in_subgroup(p)
            assert not C.is_infinity(C.FQ2_OPS, p)

    def test_deterministic_distinct(self):
        p1 = H.hash_to_g2(b"m1")
        p2 = H.hash_to_g2(b"m1")
        p3 = H.hash_to_g2(b"m2")
        assert C.point_eq(C.FQ2_OPS, p1, p2)
        assert not C.point_eq(C.FQ2_OPS, p1, p3)

    def test_dst_separation(self):
        p1 = H.hash_to_g2(b"m", DST_G2_POP)
        p2 = H.hash_to_g2(b"m", b"OTHER_DST")
        assert not C.point_eq(C.FQ2_OPS, p1, p2)
