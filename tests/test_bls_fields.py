"""Property tests for the Fq/Fq2/Fq6/Fq12 tower (pure-Python oracle).

These are the ground-truth checks everything else builds on; the JAX limb
kernels are tested against this module's functions.
"""

import random

import pytest

from teku_tpu.crypto.bls import fields as F
from teku_tpu.crypto.bls.constants import P

rng = random.Random(1234)


def rand_fq():
    return rng.randrange(P)


def rand_fq2():
    return (rand_fq(), rand_fq())


def rand_fq6():
    return (rand_fq2(), rand_fq2(), rand_fq2())


def rand_fq12():
    return (rand_fq6(), rand_fq6())


class TestFq2:
    def test_mul_commutative_associative(self):
        for _ in range(20):
            a, b, c = rand_fq2(), rand_fq2(), rand_fq2()
            assert F.fq2_eq(F.fq2_mul(a, b), F.fq2_mul(b, a))
            assert F.fq2_eq(F.fq2_mul(F.fq2_mul(a, b), c),
                            F.fq2_mul(a, F.fq2_mul(b, c)))

    def test_distributive(self):
        for _ in range(20):
            a, b, c = rand_fq2(), rand_fq2(), rand_fq2()
            assert F.fq2_eq(F.fq2_mul(a, F.fq2_add(b, c)),
                            F.fq2_add(F.fq2_mul(a, b), F.fq2_mul(a, c)))

    def test_inverse(self):
        for _ in range(20):
            a = rand_fq2()
            assert F.fq2_eq(F.fq2_mul(a, F.fq2_inv(a)), F.FQ2_ONE)

    def test_sqr_matches_mul(self):
        for _ in range(20):
            a = rand_fq2()
            assert F.fq2_eq(F.fq2_sqr(a), F.fq2_mul(a, a))

    def test_u_squared_is_minus_one(self):
        u = (0, 1)
        assert F.fq2_eq(F.fq2_sqr(u), (P - 1, 0))

    def test_sqrt_roundtrip(self):
        found = 0
        for _ in range(40):
            a = rand_fq2()
            s = F.fq2_sqrt(a)
            if s is not None:
                assert F.fq2_eq(F.fq2_sqr(s), a)
                found += 1
        assert found > 5  # about half should be squares

    def test_frobenius_is_pth_power(self):
        for _ in range(5):
            a = rand_fq2()
            assert F.fq2_eq(F.fq2_conj(a), F.fq2_pow(a, P))


class TestFq6:
    def test_ring_axioms(self):
        for _ in range(10):
            a, b, c = rand_fq6(), rand_fq6(), rand_fq6()
            assert F.fq6_eq(F.fq6_mul(a, b), F.fq6_mul(b, a))
            assert F.fq6_eq(F.fq6_mul(F.fq6_mul(a, b), c),
                            F.fq6_mul(a, F.fq6_mul(b, c)))
            assert F.fq6_eq(F.fq6_mul(a, F.fq6_add(b, c)),
                            F.fq6_add(F.fq6_mul(a, b), F.fq6_mul(a, c)))

    def test_inverse(self):
        for _ in range(10):
            a = rand_fq6()
            assert F.fq6_eq(F.fq6_mul(a, F.fq6_inv(a)), F.FQ6_ONE)

    def test_v_cubed_is_xi(self):
        v = (F.FQ2_ZERO, F.FQ2_ONE, F.FQ2_ZERO)
        v3 = F.fq6_mul(F.fq6_mul(v, v), v)
        assert F.fq6_eq(v3, (F.XI, F.FQ2_ZERO, F.FQ2_ZERO))

    def test_mul_by_v(self):
        for _ in range(10):
            a = rand_fq6()
            v = (F.FQ2_ZERO, F.FQ2_ONE, F.FQ2_ZERO)
            assert F.fq6_eq(F.fq6_mul_by_v(a), F.fq6_mul(a, v))

    def test_frobenius_is_pth_power(self):
        a = rand_fq6()
        expected = a
        # compute a^p via fq12 embedding pow is costly; use repeated mul check:
        # verify pi(a*b) = pi(a)pi(b) and pi fixes Fq instead
        b = rand_fq6()
        assert F.fq6_eq(F.fq6_frobenius(F.fq6_mul(a, b)),
                        F.fq6_mul(F.fq6_frobenius(a), F.fq6_frobenius(b)))
        one = F.FQ6_ONE
        assert F.fq6_eq(F.fq6_frobenius(one), one)


class TestFq12:
    def test_ring_axioms(self):
        for _ in range(5):
            a, b = rand_fq12(), rand_fq12()
            assert F.fq12_eq(F.fq12_mul(a, b), F.fq12_mul(b, a))

    def test_inverse(self):
        for _ in range(5):
            a = rand_fq12()
            assert F.fq12_is_one(F.fq12_mul(a, F.fq12_inv(a)))

    def test_w_squared_is_v(self):
        w = (F.FQ6_ZERO, F.FQ6_ONE)
        v12 = ((F.FQ2_ZERO, F.FQ2_ONE, F.FQ2_ZERO), F.FQ6_ZERO)
        assert F.fq12_eq(F.fq12_mul(w, w), v12)

    def test_frobenius_multiplicative_and_order(self):
        a = rand_fq12()
        b = rand_fq12()
        assert F.fq12_eq(F.fq12_frobenius(F.fq12_mul(a, b)),
                         F.fq12_mul(F.fq12_frobenius(a), F.fq12_frobenius(b)))
        # pi^12 = identity
        assert F.fq12_eq(F.fq12_frobenius(a, 12), a)
        # pi^6 = conjugation
        assert F.fq12_eq(F.fq12_frobenius(a, 6), F.fq12_conj(a))

    def test_frobenius_is_pth_power(self):
        a = rand_fq12()
        assert F.fq12_eq(F.fq12_frobenius(a), F.fq12_pow(a, P))

    def test_pow(self):
        a = rand_fq12()
        assert F.fq12_eq(F.fq12_pow(a, 5),
                         F.fq12_mul(F.fq12_mul(F.fq12_mul(F.fq12_mul(a, a), a), a), a))


def test_cyclotomic_square_matches_generic():
    """Granger-Scott squaring == generic squaring on cyclotomic elements."""
    from teku_tpu.crypto.bls import curve as C
    from teku_tpu.crypto.bls import pairing as PR

    p = C.to_affine(C.FQ_OPS, C.point_mul(C.FQ_OPS, rng.randrange(1, PR.R),
                                          C.G1_GENERATOR))
    q = C.to_affine(C.FQ2_OPS, C.point_mul(C.FQ2_OPS, rng.randrange(1, PR.R),
                                           C.G2_GENERATOR))
    f = PR.final_exponentiation(PR.miller_loop(p, q))
    assert F.fq12_eq(F.fq12_cyclo_sqr(f), F.fq12_sqr(f))
    # also holds right after the easy part (the _pow_z input domain)
    g = F.fq12_mul(F.fq12_conj(f), F.fq12_inv(f))
    g = F.fq12_mul(F.fq12_frobenius(g, 2), g)
    assert F.fq12_eq(F.fq12_cyclo_sqr(g), F.fq12_sqr(g))
