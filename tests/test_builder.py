"""Builder/MEV flow: blinding identity, bid validation, circuit
breaker, registrations."""

import asyncio
import dataclasses

import pytest

from teku_tpu import builderapi as B
from teku_tpu.crypto import bls
from teku_tpu.spec import config as C
from teku_tpu.spec.builder import make_local_signer, produce_block
from teku_tpu.spec.genesis import interop_genesis

CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0,
                          BELLATRIX_FORK_EPOCH=0, CAPELLA_FORK_EPOCH=0)


def _capella_signed_block():
    state, sks = interop_genesis(CFG, 16)
    signer = make_local_signer(dict(enumerate(sks)))
    signed, post = produce_block(CFG, state, 1, signer)
    return signed, post


def test_blinding_identity_round_trip():
    signed, _ = _capella_signed_block()
    block = signed.message
    blinded = B.blind_block(CFG, block)
    # the whole point: one signature covers both shapes
    assert blinded.htr() == block.htr()
    assert blinded.body.htr() == block.body.htr()
    _, SignedBlinded = B.blinded_schemas(CFG, block.slot)
    signed_blinded = SignedBlinded(message=blinded,
                                   signature=signed.signature)
    full = B.unblind_block(CFG, signed_blinded,
                           block.body.execution_payload)
    assert full == signed
    # a substituted payload is rejected
    tampered = block.body.execution_payload.copy_with(gas_used=1234)
    with pytest.raises(ValueError):
        B.unblind_block(CFG, signed_blinded, tampered)


def test_bid_validation():
    signed, _ = _capella_signed_block()
    payload = signed.message.body.execution_payload
    header = B._payload_to_header(payload)
    builder_sk = 777
    bid = B.sign_bid(CFG, builder_sk, B.BuilderBid(
        header=header, value=10 ** 18,
        pubkey=bls.secret_to_public_key(builder_sk)))
    assert B.validate_bid(CFG, bid, payload.parent_hash)
    # wrong parent, low value, bad signature all fail
    assert not B.validate_bid(CFG, bid, b"\x55" * 32)
    assert not B.validate_bid(CFG, bid, payload.parent_hash,
                              min_value=10 ** 19)
    forged = B.BuilderBid(header=header, value=bid.value,
                          pubkey=bid.pubkey,
                          signature=b"\xbb" * 96)
    assert not B.validate_bid(CFG, forged, payload.parent_hash)


def test_registration_sign_verify():
    sk = 4242
    reg = B.ValidatorRegistration(
        fee_recipient=b"\x01" * 20, gas_limit=30_000_000,
        timestamp=1700000000, pubkey=bls.secret_to_public_key(sk))
    signed = B.sign_registration(CFG, sk, reg)
    assert B.verify_registration(CFG, signed)
    assert not B.verify_registration(
        CFG, signed.copy_with(signature=b"\xcc" * 96))


def test_builder_flow_and_circuit_breaker():
    signed, _ = _capella_signed_block()
    payload = signed.message.body.execution_payload
    header = B._payload_to_header(payload)
    builder_sk = 777
    good_bid = B.sign_bid(CFG, builder_sk, B.BuilderBid(
        header=header, value=1,
        pubkey=bls.secret_to_public_key(builder_sk)))

    class FlakyBuilder(B.BuilderClient):
        def __init__(self):
            self.fail = False

        async def get_header(self, slot, parent_hash, pubkey):
            if self.fail:
                raise ConnectionError("relay down")
            return good_bid

        async def get_payload(self, signed_blinded_block):
            return payload

    async def run():
        builder = FlakyBuilder()
        flow = B.BuilderFlow(CFG, builder,
                             B.BuilderCircuitBreaker(fault_limit=2,
                                                     cooldown_slots=5))
        got = await flow.select_header(1, payload.parent_hash, b"")
        assert got == header
        # two faults open the circuit: local fallback (None) until the
        # cooldown passes, even after the relay recovers
        builder.fail = True
        assert await flow.select_header(2, payload.parent_hash, b"") \
            is None
        assert await flow.select_header(3, payload.parent_hash, b"") \
            is None
        builder.fail = False
        assert await flow.select_header(4, payload.parent_hash, b"") \
            is None      # circuit still open
        assert await flow.select_header(9, payload.parent_hash, b"") \
            == header    # cooldown over

        # reveal path: signed blinded block -> full signed block
        blinded = B.blind_block(CFG, signed.message)
        _, SignedBlinded = B.blinded_schemas(CFG, 1)
        sb = SignedBlinded(message=blinded, signature=signed.signature)
        full = await flow.reveal(sb)
        assert full == signed

    asyncio.run(run())
