"""Builder/MEV flow: blinding identity, bid validation, circuit
breaker, registrations."""

import asyncio
import dataclasses

import pytest

from teku_tpu import builderapi as B
from teku_tpu.crypto import bls
from teku_tpu.spec import config as C
from teku_tpu.spec.builder import make_local_signer, produce_block
from teku_tpu.spec.genesis import interop_genesis

CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0,
                          BELLATRIX_FORK_EPOCH=0, CAPELLA_FORK_EPOCH=0)


def _capella_signed_block():
    state, sks = interop_genesis(CFG, 16)
    signer = make_local_signer(dict(enumerate(sks)))
    signed, post = produce_block(CFG, state, 1, signer)
    return signed, post


def test_blinding_identity_round_trip():
    signed, _ = _capella_signed_block()
    block = signed.message
    blinded = B.blind_block(CFG, block)
    # the whole point: one signature covers both shapes
    assert blinded.htr() == block.htr()
    assert blinded.body.htr() == block.body.htr()
    _, SignedBlinded = B.blinded_schemas(CFG, block.slot)
    signed_blinded = SignedBlinded(message=blinded,
                                   signature=signed.signature)
    full = B.unblind_block(CFG, signed_blinded,
                           block.body.execution_payload)
    assert full == signed
    # a substituted payload is rejected
    tampered = block.body.execution_payload.copy_with(gas_used=1234)
    with pytest.raises(ValueError):
        B.unblind_block(CFG, signed_blinded, tampered)


def test_bid_validation():
    signed, _ = _capella_signed_block()
    payload = signed.message.body.execution_payload
    header = B._payload_to_header(payload)
    builder_sk = 777
    bid = B.sign_bid(CFG, builder_sk, B.BuilderBid(
        header=header, value=10 ** 18,
        pubkey=bls.secret_to_public_key(builder_sk)))
    assert B.validate_bid(CFG, bid, payload.parent_hash)
    # wrong parent, low value, bad signature all fail
    assert not B.validate_bid(CFG, bid, b"\x55" * 32)
    assert not B.validate_bid(CFG, bid, payload.parent_hash,
                              min_value=10 ** 19)
    forged = B.BuilderBid(header=header, value=bid.value,
                          pubkey=bid.pubkey,
                          signature=b"\xbb" * 96)
    assert not B.validate_bid(CFG, forged, payload.parent_hash)


def test_registration_sign_verify():
    sk = 4242
    reg = B.ValidatorRegistration(
        fee_recipient=b"\x01" * 20, gas_limit=30_000_000,
        timestamp=1700000000, pubkey=bls.secret_to_public_key(sk))
    signed = B.sign_registration(CFG, sk, reg)
    assert B.verify_registration(CFG, signed)
    assert not B.verify_registration(
        CFG, signed.copy_with(signature=b"\xcc" * 96))


def test_builder_flow_and_circuit_breaker():
    signed, _ = _capella_signed_block()
    payload = signed.message.body.execution_payload
    header = B._payload_to_header(payload)
    builder_sk = 777
    good_bid = B.sign_bid(CFG, builder_sk, B.BuilderBid(
        header=header, value=1,
        pubkey=bls.secret_to_public_key(builder_sk)))

    class FlakyBuilder(B.BuilderClient):
        def __init__(self):
            self.fail = False

        async def get_header(self, slot, parent_hash, pubkey):
            if self.fail:
                raise ConnectionError("relay down")
            return good_bid

        async def get_payload(self, signed_blinded_block):
            return payload

    async def run():
        builder = FlakyBuilder()
        flow = B.BuilderFlow(CFG, builder,
                             B.BuilderCircuitBreaker(fault_limit=2,
                                                     cooldown_slots=5))
        got = await flow.select_header(1, payload.parent_hash, b"")
        assert got == header
        # two faults open the circuit: local fallback (None) until the
        # cooldown passes, even after the relay recovers
        builder.fail = True
        assert await flow.select_header(2, payload.parent_hash, b"") \
            is None
        assert await flow.select_header(3, payload.parent_hash, b"") \
            is None
        builder.fail = False
        assert await flow.select_header(4, payload.parent_hash, b"") \
            is None      # circuit still open
        assert await flow.select_header(9, payload.parent_hash, b"") \
            == header    # cooldown over

        # reveal path: signed blinded block -> full signed block
        blinded = B.blind_block(CFG, signed.message)
        _, SignedBlinded = B.blinded_schemas(CFG, 1)
        sb = SignedBlinded(message=blinded, signature=signed.signature)
        full = await flow.reveal(sb)
        assert full == signed

    asyncio.run(run())


def test_bid_signing_root_is_ssz_and_covers_blob_commitments():
    """Builder-spec BuilderBid is an SSZ container; deneb+ bids bind
    blob_kzg_commitments under the builder signature (builder-specs
    deneb BuilderBid; reference SchemaDefinitionsDeneb builder bid)."""
    deneb_cfg = dataclasses.replace(CFG, DENEB_FORK_EPOCH=0)
    from teku_tpu.spec.deneb.datastructures import get_deneb_schemas
    S = get_deneb_schemas(deneb_cfg)
    header = S.ExecutionPayloadHeader()
    commitment = b"\xc5" * 48
    builder_sk = 777
    bid = B.sign_bid(deneb_cfg, builder_sk, B.BuilderBid(
        header=header, value=10 ** 18,
        pubkey=bls.secret_to_public_key(builder_sk),
        blob_kzg_commitments=(commitment,)))
    ssz_bid = bid.to_ssz(deneb_cfg)
    assert "blob_kzg_commitments" in type(ssz_bid)._ssz_fields
    assert bls.verify(bid.pubkey, bid.signing_root(deneb_cfg),
                      bid.signature)
    # dropping / swapping a commitment changes the signing root
    stripped = B.BuilderBid(header=header, value=bid.value,
                            pubkey=bid.pubkey,
                            blob_kzg_commitments=())
    assert stripped.signing_root(deneb_cfg) != bid.signing_root(deneb_cfg)
    # pre-deneb headers still sign the (header, value, pubkey) shape
    signed, _ = _capella_signed_block()
    cap_header = B._payload_to_header(
        signed.message.body.execution_payload)
    cap_bid = B.BuilderBid(header=cap_header, value=1, pubkey=b"\x01" * 48)
    assert "blob_kzg_commitments" not in type(
        cap_bid.to_ssz(CFG))._ssz_fields
    # electra bids carry execution_requests under the signature
    # (builder-specs electra BuilderBid; deneb and electra share the
    # header type, so the requests object selects the shape)
    electra_cfg = dataclasses.replace(deneb_cfg, ELECTRA_FORK_EPOCH=0)
    from teku_tpu.spec.electra.datastructures import get_electra_schemas
    SE = get_electra_schemas(electra_cfg)
    el_bid = B.BuilderBid(header=header, value=1, pubkey=bid.pubkey,
                          blob_kzg_commitments=(commitment,),
                          execution_requests=SE.ExecutionRequests())
    fields = list(type(el_bid.to_ssz(electra_cfg))._ssz_fields)
    assert fields == ["header", "blob_kzg_commitments",
                      "execution_requests", "value", "pubkey"]
    assert el_bid.signing_root(electra_cfg) != bid.signing_root(deneb_cfg)
