"""Spec engine: shuffling cross-check, interop genesis, and a full
multi-epoch chain driven through the REAL transition (block production →
state_transition with batched signature verification → justification →
finalization) on the minimal preset.

This is the TPU build's equivalent of the reference's ChainBuilder-based
transition tests (reference: ethereum/spec/src/test and
storage testFixtures ChainBuilder/ChainUpdater).
"""

import numpy as np
import pytest

from teku_tpu.spec import config as C
from teku_tpu.spec import helpers as H
from teku_tpu.spec.builder import (make_local_signer, produce_attestations,
                                   produce_block)
from teku_tpu.spec.genesis import interop_genesis, interop_secret_keys
from teku_tpu.spec.transition import (process_slots, state_transition,
                                      StateTransitionError)
from teku_tpu.crypto import bls

CFG = C.MINIMAL


# --------------------------------------------------------------------------
# Shuffling
# --------------------------------------------------------------------------

def test_shuffle_list_matches_single_index():
    seed = bytes(range(32))
    n = 100
    indices = np.arange(n, dtype=np.int64)
    shuffled = H.shuffle_list(CFG, indices, seed)
    expect = [indices[H.compute_shuffled_index(CFG, j, n, seed)]
              for j in range(n)]
    assert shuffled.tolist() == expect


def test_shuffle_is_permutation():
    seed = b"\x07" * 32
    out = H.shuffle_list(CFG, np.arange(513, dtype=np.int64), seed)
    assert sorted(out.tolist()) == list(range(513))


# --------------------------------------------------------------------------
# Genesis
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def genesis():
    state, sks = interop_genesis(CFG, 64)
    return state, sks


def test_interop_genesis_shape(genesis):
    state, sks = genesis
    assert len(state.validators) == 64
    assert len(sks) == 64
    assert all(v.activation_epoch == 0 for v in state.validators)
    assert state.genesis_validators_root != bytes(32)
    # interop keys are the standardized derivation — first key is fixed
    assert interop_secret_keys(1)[0] == sks[0]
    # every pubkey valid + distinct
    pks = [v.pubkey for v in state.validators]
    assert len(set(pks)) == 64
    assert all(bls.public_key_is_valid(pk) for pk in pks)


def test_committees_cover_all_validators(genesis):
    state, _ = genesis
    state = process_slots(CFG, state, 1)
    seen = set()
    for slot in range(CFG.SLOTS_PER_EPOCH):
        n = H.get_committee_count_per_slot(CFG, state, 0)
        for ci in range(n):
            seen.update(H.get_beacon_committee(CFG, state, slot, ci))
    assert seen == set(range(64))


def test_process_slots_rejects_rewind(genesis):
    state, _ = genesis
    state = process_slots(CFG, state, 3)
    with pytest.raises(StateTransitionError):
        process_slots(CFG, state, 2)


# --------------------------------------------------------------------------
# Full chain: produce + verify + finalize
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_chain_finalizes(genesis):
    state, sks = genesis
    signer = make_local_signer(dict(enumerate(sks)))
    atts = []
    n_epochs = 4
    for slot in range(1, n_epochs * CFG.SLOTS_PER_EPOCH + 1):
        signed, post = produce_block(CFG, state, slot, signer,
                                     attestations=atts)
        # the import path re-runs the transition WITH signature checks
        verified = state_transition(CFG, state, signed,
                                    validate_result=True)
        assert verified.htr() == post.htr(), f"state divergence at {slot}"
        head_root = signed.message.htr()
        atts = produce_attestations(CFG, post, slot, head_root, signer)
        state = post

    # perfect participation: justification within 2 epochs, finality
    # no later than epoch n-2
    assert state.current_justified_checkpoint.epoch >= n_epochs - 1
    assert state.finalized_checkpoint.epoch >= n_epochs - 2


def test_invalid_proposer_signature_rejected(genesis):
    state, sks = genesis
    signer = make_local_signer(dict(enumerate(sks)))
    signed, _ = produce_block(CFG, state, 1, signer)
    bad = signed.copy_with(signature=b"\x01" + signed.signature[1:])
    with pytest.raises(StateTransitionError):
        state_transition(CFG, state, bad, validate_result=True)


def test_wrong_state_root_rejected(genesis):
    state, sks = genesis
    signer = make_local_signer(dict(enumerate(sks)))
    signed, _ = produce_block(CFG, state, 1, signer)
    tampered_msg = signed.message.copy_with(state_root=bytes(32))
    # re-sign so only the state root is wrong
    from teku_tpu.spec import helpers as HH
    domain = HH.get_domain(CFG, state, C.DOMAIN_BEACON_PROPOSER)
    root = HH.compute_signing_root(tampered_msg, domain)
    resigned = signed.copy_with(
        message=tampered_msg,
        signature=bls.sign(sks[tampered_msg.proposer_index], root))
    with pytest.raises(StateTransitionError):
        state_transition(CFG, state, resigned, validate_result=True)
