"""tekulint: the AST-based invariant analyzer (teku_tpu/analysis).

Each checker is proven on ≥ 1 fixture true positive AND ≥ 1 clean
negative (synthetic trees under tmp_path — the analyzer never imports
what it scans, so fixtures are plain text).  Suppressions round-trip
(missing/short justification = hard error, unused entry = not clean),
the --json schema is pinned, and the tier-1 acceptance test at the
bottom runs the analyzer over THIS LIVE REPO and fails on any
unsuppressed finding — the enforcement point for "raw TEKU_TPU_*
os.environ reads outside infra/env.py are zero".

The second half regression-tests the infra/env.py degrade contract
for every knob this PR hoisted off a raw (boot-killing) read: a
garbage value degrades to the default with exactly ONE WARN instead
of raising.
"""

import json
import logging
import textwrap

import pytest

from teku_tpu.analysis import run_lint
from teku_tpu.analysis.env_knob import collect_knobs, render_knob_table
from teku_tpu.analysis.findings import SCHEMA_VERSION
from teku_tpu.analysis.runner import build_project, discover_files
from teku_tpu.analysis.suppress import SuppressionError
from teku_tpu.infra import env


# --------------------------------------------------------------------------
# fixture plumbing
# --------------------------------------------------------------------------

def make_tree(tmp_path, files, suppressions=None, readme=None):
    """Write a fixture tree; returns its root as str."""
    for relpath, source in files.items():
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    if suppressions is not None:
        (tmp_path / "lint_suppressions.json").write_text(
            json.dumps(suppressions))
    if readme is not None:
        (tmp_path / "README.md").write_text(textwrap.dedent(readme))
    return str(tmp_path)


def lint(tmp_path, files, **kw):
    return run_lint(root=make_tree(tmp_path, files, **kw))


def by_checker(report, checker):
    return [f for f in report.unsuppressed if f.checker == checker]


# --------------------------------------------------------------------------
# env-knob
# --------------------------------------------------------------------------

RAW_READS = """
    import os

    ENV_NAME = "TEKU_TPU_CONST_KNOB"

    direct = os.environ.get("TEKU_TPU_DIRECT", "5")
    via_getenv = os.getenv("TEKU_TPU_GETENV")
    via_const = os.environ.get(ENV_NAME, "x")
    subscript = os.environ["TEKU_TPU_SUBSCRIPT"]
"""

CLEAN_READS = """
    import os
    from teku_tpu.infra.env import env_int, env_str

    helper = env_int("TEKU_TPU_HELPER_KNOB", 5)
    other_ns = os.environ.get("HOME", "/")
    write = None
    os.environ["TEKU_TPU_WRITE_SEAM"] = "on"
"""


def test_env_knob_flags_raw_reads(tmp_path):
    report = lint(tmp_path, {"raw.py": RAW_READS})
    tokens = {f.token for f in by_checker(report, "env-knob")}
    assert tokens == {"TEKU_TPU_DIRECT", "TEKU_TPU_GETENV",
                      "TEKU_TPU_CONST_KNOB", "TEKU_TPU_SUBSCRIPT"}


def test_env_knob_clean_on_helper_reads_and_writes(tmp_path):
    report = lint(tmp_path, {"clean.py": CLEAN_READS})
    assert by_checker(report, "env-knob") == []


# --------------------------------------------------------------------------
# jit-purity
# --------------------------------------------------------------------------

IMPURE_KERNEL = """
    import time
    import jax

    def helper(x):
        return x + time.monotonic()

    def kernel(x):
        return helper(x) * 2

    jitted = jax.jit(kernel)
"""

PURE_KERNEL = """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def step(carry, x):
        return carry + x, None

    def kernel(x):
        total, _ = lax.scan(step, x, jnp.arange(4))
        return total

    jitted = jax.jit(kernel)

    def host_driver(x):
        import time
        t0 = time.monotonic()      # host side: NOT reachable from jit
        return jitted(x), t0
"""


def test_jit_purity_flags_clock_through_call_graph(tmp_path):
    report = lint(tmp_path, {"impure.py": IMPURE_KERNEL})
    findings = by_checker(report, "jit-purity")
    assert len(findings) == 1
    assert findings[0].token == "helper:time.monotonic"
    assert "jax.jit" in findings[0].evidence


def test_jit_purity_clean_kernel_and_host_side_effects_ok(tmp_path):
    report = lint(tmp_path, {"pure.py": PURE_KERNEL})
    assert by_checker(report, "jit-purity") == []


def test_jit_purity_decorated_method_is_an_entry(tmp_path):
    """@jax.jit on a METHOD (or nested def) must enter the walk — a
    synthetic module-level name lookup would silently drop it."""
    src = """
        import jax
        import time

        class Kernels:
            @jax.jit
            def _kernel(self, x):
                return x + time.time()
    """
    report = lint(tmp_path, {"meth.py": src})
    tokens = {f.token for f in by_checker(report, "jit-purity")}
    assert "_kernel:time.time" in tokens


def test_jit_purity_scan_body_metric_mutation(tmp_path):
    src = """
        from jax import lax
        from somewhere import METRIC

        def body(c, x):
            METRIC.labels(kind="step").inc()
            return c, x

        def run(xs):
            return lax.scan(body, 0, xs)
    """
    report = lint(tmp_path, {"scanbody.py": src})
    tokens = {f.token for f in by_checker(report, "jit-purity")}
    assert "body:METRIC.labels.inc" in tokens or \
        "body:METRIC.labels" in tokens


# --------------------------------------------------------------------------
# torn-read
# --------------------------------------------------------------------------

TORN = """
    __swap_attrs__ = ("_serving",)

    class Guarded:
        def torn(self):
            provider = self._serving[0]
            lock = self._serving[1]        # second read: torn
            return provider, lock

        def atomic(self):
            provider, lock = self._serving
            return provider, lock
"""


def test_torn_read_flags_double_read_only(tmp_path):
    report = lint(tmp_path, {"swap.py": TORN})
    findings = by_checker(report, "torn-read")
    assert [f.token for f in findings] == ["Guarded.torn:_serving"]


def test_torn_read_needs_registration(tmp_path):
    unregistered = TORN.replace('__swap_attrs__ = ("_serving",)\n', "")
    report = lint(tmp_path, {"swap.py": unregistered})
    assert by_checker(report, "torn-read") == []


# --------------------------------------------------------------------------
# metric-contract
# --------------------------------------------------------------------------

BAD_METRICS = """
    from somewhere import REG

    c = REG.counter("requests_count", "not a counter name")
    g = REG.gauge("work_done_total", "gauge claiming counter")
    h = REG.labeled_histogram("verify_ms", "latency without _seconds",
                              ("stage",))
    ok = REG.counter("requests_total", "fine")
    ok.labels(shape=f"{1}x{2}").inc()
"""

GOOD_METRICS = """
    from somewhere import REG, LATENCY_BUCKETS_S

    c = REG.counter("requests_total", "h")
    g = REG.gauge("queue_depth", "h")
    h = REG.labeled_histogram("verify_seconds", "h", ("stage",))
    h2 = REG.histogram("batch_size", "h")
    h3 = REG.histogram("wait_seconds", "h", buckets=LATENCY_BUCKETS_S)
    c.labels(kind=kind).inc()
"""


def test_metric_contract_flags_naming_and_labels(tmp_path):
    report = lint(tmp_path, {"bad.py": BAD_METRICS})
    tokens = {f.token for f in by_checker(report, "metric-contract")}
    assert "requests_count" in tokens       # counter without _total
    assert "work_done_total" in tokens      # gauge with _total
    assert "verify_ms" in tokens            # latency without _seconds
    assert "labels:shape" in tokens         # f-string label value


def test_metric_contract_clean(tmp_path):
    report = lint(tmp_path, {"good.py": GOOD_METRICS})
    assert by_checker(report, "metric-contract") == []


def test_metric_contract_sees_dict_unpacked_labels(tmp_path):
    """labels(**{"class": ...}) is the tree's reserved-word idiom —
    the open-vocabulary rule must look through the ** dict."""
    src = """
        from somewhere import REG
        c = REG.counter("sheds_total", "h")
        c.labels(**{"class": f"{cls}", "reason": reason}).inc()
    """
    report = lint(tmp_path, {"unpack.py": src})
    tokens = {f.token for f in by_checker(report, "metric-contract")}
    assert tokens == {"labels:class"}       # f-string caught, Name ok


# --------------------------------------------------------------------------
# closed-registry (needs the real module names inside the fixture tree)
# --------------------------------------------------------------------------

REGISTRY_TREE = {
    "teku_tpu/infra/faults.py": """
        SITES = frozenset({"good.site", "dead.site"})

        def check(site, keys=None):
            pass
    """,
    "teku_tpu/infra/flightrecorder.py": """
        EVENT_KINDS = frozenset({"good_kind", "dead_kind"})

        class FlightRecorder:
            def record(self, kind, **fields):
                pass

        RECORDER = FlightRecorder()

        def record(kind, **fields):
            return RECORDER.record(kind)
    """,
    "teku_tpu/user.py": """
        from .infra import faults, flightrecorder

        def work(recorder):
            faults.check("good.site")
            faults.check("rogue.site")
            flightrecorder.record("good_kind")
            recorder.record("rogue_kind")
    """,
}


def test_closed_registry_both_directions(tmp_path):
    report = lint(tmp_path, dict(REGISTRY_TREE))
    tokens = {f.token for f in by_checker(report, "closed-registry")}
    assert "rogue.site" in tokens       # used but undeclared
    assert "rogue_kind" in tokens
    assert "dead.site" in tokens        # declared but never used
    assert "dead_kind" in tokens
    assert "good.site" not in tokens    # declared + used = clean
    assert "good_kind" not in tokens


TIMELINE_TREE = {
    "teku_tpu/infra/timeline.py": """
        TRACKS = frozenset({"worker", "ghost_track"})
        PHASES = frozenset({"busy", "ghost_phase"})

        def interval(track, phase, dur_s, **fields):
            pass

        def instant(track, phase, **fields):
            pass
    """,
    "teku_tpu/user.py": """
        from .infra import timeline

        def work():
            timeline.interval("worker", "busy", 0.1)
            timeline.instant("rogue_track", "rogue_phase")
    """,
}


def test_closed_registry_timeline_tracks_and_phases(tmp_path):
    """The timeline's track/phase vocabulary is closed the same both-
    directions way as EVENT_KINDS: undeclared emits and declared-but-
    never-emitted members are both findings."""
    report = lint(tmp_path, dict(TIMELINE_TREE))
    tokens = {f.token for f in by_checker(report, "closed-registry")}
    assert "rogue_track" in tokens      # emitted but undeclared
    assert "rogue_phase" in tokens
    assert "ghost_track" in tokens      # declared but never emitted
    assert "ghost_phase" in tokens
    assert "worker" not in tokens       # declared + emitted = clean
    assert "busy" not in tokens


def test_closed_registry_missing_declaration(tmp_path):
    tree = dict(REGISTRY_TREE)
    tree["teku_tpu/infra/faults.py"] = "def check(site):\n    pass\n"
    report = lint(tmp_path, tree)
    assert any(f.token == "SITES"
               for f in by_checker(report, "closed-registry"))


# --------------------------------------------------------------------------
# dup-helper
# --------------------------------------------------------------------------

DUP_BODY = """
    def _shared_helper(value):
        total = 0
        for item in value:
            if item > 0:
                total += item * item
        return total
"""


def test_dup_helper_flags_identical_cross_module_copies(tmp_path):
    report = lint(tmp_path, {"mod_a.py": DUP_BODY,
                             "mod_b.py": DUP_BODY})
    findings = by_checker(report, "dup-helper")
    assert len(findings) == 1           # one finding per EXTRA copy
    assert findings[0].token == "_shared_helper"
    assert "mod_a.py" in findings[0].evidence


def test_dup_helper_ignores_divergent_and_tiny(tmp_path):
    divergent = DUP_BODY.replace("item * item", "item")
    tiny = "def _tiny(x):\n    return x\n"
    report = lint(tmp_path, {"mod_a.py": DUP_BODY,
                             "mod_b.py": divergent,
                             "mod_c.py": tiny, "mod_d.py": tiny})
    assert by_checker(report, "dup-helper") == []


# --------------------------------------------------------------------------
# knob-doc
# --------------------------------------------------------------------------

KNOB_CODE = """
    from teku_tpu.infra.env import env_float, env_int

    a = env_int("TEKU_TPU_DOCUMENTED", 5)
    b = env_float("TEKU_TPU_UNDOCUMENTED", 1.0)

    def deadline(cls):
        return env_float(f"TEKU_TPU_CLASS_{cls}_MS", 2.0)
"""

KNOB_README = """
    | Knob | Default |
    | --- | --- |
    | `TEKU_TPU_DOCUMENTED` | 5 |
    | `TEKU_TPU_CLASS_<CLS>_MS` | 2.0 |
    | `TEKU_TPU_STALE_ROW` | gone |
"""


def test_knob_doc_drift_both_directions(tmp_path):
    report = lint(tmp_path, {"knobs.py": KNOB_CODE},
                  readme=KNOB_README)
    tokens = {f.token for f in by_checker(report, "knob-doc")}
    assert "TEKU_TPU_UNDOCUMENTED" in tokens
    assert "TEKU_TPU_STALE_ROW" in tokens
    # exact match and <X>-pattern match are both covered
    assert "TEKU_TPU_DOCUMENTED" not in tokens
    assert not any("CLASS" in t for t in tokens)


def test_knob_registry_extraction_and_table(tmp_path):
    root = make_tree(tmp_path, {"knobs.py": KNOB_CODE})
    project, _ = build_project(root, discover_files(root))
    knobs = collect_knobs(project)
    names = {k["name"] for k in knobs}
    assert names == {"TEKU_TPU_DOCUMENTED", "TEKU_TPU_UNDOCUMENTED",
                     "TEKU_TPU_CLASS_*_MS"}
    table = render_knob_table(knobs)
    assert "| `TEKU_TPU_DOCUMENTED` | env_int | `5` |" in table


# --------------------------------------------------------------------------
# suppressions
# --------------------------------------------------------------------------

def test_suppression_round_trip(tmp_path):
    entry = {"checker": "env-knob", "match": "raw.py:TEKU_TPU_DIRECT",
             "justification": "fixture: a deliberate raw read kept "
                              "for this round-trip test"}
    report = lint(tmp_path, {"raw.py": RAW_READS},
                  suppressions={"suppressions": [entry]})
    suppressed = [f for f in report.findings if f.suppressed]
    assert len(suppressed) == 1
    assert suppressed[0].token == "TEKU_TPU_DIRECT"
    assert suppressed[0].justification == entry["justification"]
    # the other raw reads still fail the run
    assert report.unsuppressed and not report.clean


@pytest.mark.parametrize("bad_entry", [
    {"checker": "env-knob", "match": "X"},                # missing
    {"checker": "env-knob", "match": "X", "justification": ""},
    {"checker": "env-knob", "match": "X", "justification": "wontfix"},
    {"match": "X", "justification": "long enough but no checker id"},
])
def test_suppression_without_justification_is_hard_error(tmp_path,
                                                         bad_entry):
    with pytest.raises(SuppressionError):
        lint(tmp_path, {"raw.py": RAW_READS},
             suppressions={"suppressions": [bad_entry]})


def test_suppression_match_is_exact_never_a_prefix(tmp_path):
    """A justified entry must not silently WIDEN: matching is exact
    key equality, so an entry for one knob cannot absorb a future
    finding whose token merely extends it."""
    entry = {"checker": "env-knob", "match": "raw.py:TEKU_TPU_DIREC",
             "justification": "prefix of a real token: must NOT match"}
    report = lint(tmp_path, {"raw.py": RAW_READS},
                  suppressions={"suppressions": [entry]})
    assert not any(f.suppressed for f in report.findings)
    assert report.unused_suppressions == [entry]


def test_unused_suppression_is_reported_and_fails_clean(tmp_path):
    entry = {"checker": "env-knob", "match": "TEKU_TPU_NO_SUCH",
             "justification": "stale entry kept after the fix landed"}
    report = lint(tmp_path, {"clean.py": CLEAN_READS},
                  suppressions={"suppressions": [entry]})
    assert not report.unsuppressed
    assert report.unused_suppressions == [entry]
    assert not report.clean


# --------------------------------------------------------------------------
# --json schema stability
# --------------------------------------------------------------------------

def test_json_schema_is_stable(tmp_path):
    report = lint(tmp_path, {"raw.py": RAW_READS})
    doc = json.loads(json.dumps(report.to_dict()))
    assert set(doc) == {"version", "root", "files_scanned", "findings",
                        "counts", "unused_suppressions"}
    assert doc["version"] == SCHEMA_VERSION == 1
    assert set(doc["counts"]) == {"total", "unsuppressed",
                                  "suppressed", "by_checker"}
    finding = doc["findings"][0]
    assert set(finding) == {"checker", "path", "line", "message",
                            "evidence", "fix_hint", "key",
                            "suppressed"}
    assert finding["key"].startswith("env-knob:raw.py:")
    # findings sort deterministically (path, line, checker)
    ordered = [(f["path"], f["line"]) for f in doc["findings"]]
    assert ordered == sorted(ordered)


# --------------------------------------------------------------------------
# tier-1 acceptance: the LIVE tree is clean
# --------------------------------------------------------------------------

def test_live_tree_is_clean():
    """`cli lint` exits 0 over this repo: zero unsuppressed findings,
    zero stale suppressions.  This is the build-property enforcement
    of every mechanized invariant — in particular, raw TEKU_TPU_*
    os.environ/os.getenv reads outside infra/env.py are ZERO."""
    report = run_lint()
    details = "\n".join(
        f"{f.path}:{f.line} [{f.checker}] {f.message}"
        for f in report.unsuppressed)
    assert not report.unsuppressed, f"lint findings:\n{details}"
    assert not report.unused_suppressions, report.unused_suppressions
    assert report.files_scanned > 100      # the walk saw the real tree


def test_live_tree_cli_lint_json(capsys):
    """The `cli lint --json` front end over the live tree: exit 0 and
    a parseable report (the --json schema acceptance on real data)."""
    from teku_tpu.cli import main
    rc = main(["lint", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["counts"]["unsuppressed"] == 0


def test_live_knob_registry_covers_readme(capsys):
    """--knobs emits the registry table; every row's knob appears in
    the README (the drift check's forward direction, end to end)."""
    from teku_tpu.cli import main
    rc = main(["lint", "--knobs"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.startswith("| Knob | Reader | Default | Where |")
    assert "TEKU_TPU_MESH_WARM_BATCH" in out


# --------------------------------------------------------------------------
# infra/env.py: the degrade contract for every previously-raw knob
# --------------------------------------------------------------------------

# (knob, helper, default used at the real read site) for every knob
# this PR hoisted off a raw os.environ read that RAISED on garbage
# (int()/float() around the read) — the regression being pinned is
# "a typo'd unit file degrades the knob with one WARN, never the node"
HOISTED_NUMERIC_KNOBS = [
    ("TEKU_TPU_HEALTH_TICK_S", env.env_float, 5.0),
    ("TEKU_TPU_H2C_MIN_BUCKET", env.env_int, 8),
    ("TEKU_TPU_H2C_GROUP_CAP", env.env_int, 32),
    ("TEKU_TPU_BREAKER_THRESHOLD", env.env_int, 3),
    ("TEKU_TPU_DISPATCH_DEADLINE_S", env.env_float, 30.0),
    ("TEKU_TPU_BREAKER_COOLDOWN_S", env.env_float, 30.0),
    ("TEKU_TPU_BLS_PROBE_TIMEOUT_S", env.env_float, 120.0),
    ("TEKU_TPU_CAPACITY_WINDOW_S", env.env_float, 60.0),
    ("TEKU_TPU_CAPACITY_MAX_SHAPES", env.env_int, 24),
    ("TEKU_TPU_SLOW_TRACE_RING", env.env_int, 32),
    ("TEKU_TPU_FLIGHT_RECORDER_CAPACITY", env.env_int, 512),
    ("TEKU_TPU_FLIGHT_RECORDER_THROTTLE_S", env.env_float, 30.0),
    ("TEKU_TPU_REQRESP_TIMEOUT_S", env.env_float, 30.0),
    ("TEKU_TPU_XLA_CACHE_MIN_COMPILE_S", env.env_float, 2.0),
]


@pytest.mark.parametrize("name,helper,default", HOISTED_NUMERIC_KNOBS,
                         ids=[k[0] for k in HOISTED_NUMERIC_KNOBS])
def test_garbage_knob_degrades_with_one_warn(name, helper, default,
                                             monkeypatch, caplog):
    monkeypatch.setenv(name, "garbage!!")
    env._reset_warnings()
    with caplog.at_level(logging.WARNING, logger="teku_tpu.infra.env"):
        assert helper(name, default) == default     # no raise
        assert helper(name, default) == default     # second read
    warns = [r for r in caplog.records if name in r.getMessage()]
    assert len(warns) == 1, "exactly one WARN per knob per process"


def test_env_clamp_warns_once(monkeypatch, caplog):
    monkeypatch.setenv("TEKU_TPU_FLUSH_FAILSAFE_MS", "-5")
    env._reset_warnings()
    with caplog.at_level(logging.WARNING, logger="teku_tpu.infra.env"):
        assert env.env_float("TEKU_TPU_FLUSH_FAILSAFE_MS", 0.0,
                             lo=0.0) == 0.0
    assert any("clamping" in r.getMessage() for r in caplog.records)


def test_env_bool_and_choice_degrade(monkeypatch, caplog):
    env._reset_warnings()
    monkeypatch.setenv("TEKU_TPU_MESH_SELF_HEAL", "maybe")
    monkeypatch.setenv("TEKU_TPU_DEVNET_HARD_EXIT", "")
    with caplog.at_level(logging.WARNING, logger="teku_tpu.infra.env"):
        assert env.env_bool("TEKU_TPU_MESH_SELF_HEAL", True) is True
        assert env.env_choice("TEKU_TPU_X_CHOICE", "auto",
                              ("on", "off", "auto")) == "auto"
        monkeypatch.setenv("TEKU_TPU_X_CHOICE", "sideways")
        assert env.env_choice("TEKU_TPU_X_CHOICE", "auto",
                              ("on", "off", "auto")) == "auto"
    # empty string reads as unset for env_str (TEKU_TPU_X= in a unit
    # file means "default", not "empty-string mode")
    assert env.env_str("TEKU_TPU_DEVNET_HARD_EXIT", "auto") == "auto"
    assert env.env_bool("TEKU_TPU_MESH_SELF_HEAL", True) is True


def test_env_override_round_trips(monkeypatch):
    import os
    monkeypatch.setenv("TEKU_TPU_MESH_WARM_BATCH", "7")
    with env.env_override("TEKU_TPU_MESH_WARM_BATCH", "64"):
        assert os.environ["TEKU_TPU_MESH_WARM_BATCH"] == "64"
    assert os.environ["TEKU_TPU_MESH_WARM_BATCH"] == "7"
    monkeypatch.delenv("TEKU_TPU_MESH_WARM_BATCH")
    with env.env_override("TEKU_TPU_MESH_WARM_BATCH", "64"):
        assert os.environ["TEKU_TPU_MESH_WARM_BATCH"] == "64"
    assert "TEKU_TPU_MESH_WARM_BATCH" not in os.environ


def test_previously_killing_reads_now_boot(monkeypatch):
    """Functional spot checks: module-level/constructor reads that used
    to be `float(os.environ.get(...))` (boot-killing on a typo) now
    construct fine under garbage env."""
    from teku_tpu.infra.flightrecorder import FlightRecorder
    from teku_tpu.ops.h2c_cache import configured_capacity
    monkeypatch.setenv("TEKU_TPU_H2C_CACHE_CAP", "not-a-number")
    assert configured_capacity() > 0               # default, no raise
    rec = FlightRecorder(capacity=8)               # import survived
    rec.record("warmup_cache", note="env test")
    assert rec.snapshot()[-1]["kind"] == "warmup_cache"
