"""Electra: six-fork ladder, balance churn, execution requests,
pending queues, committee-bits attestations."""

import dataclasses

import pytest

from teku_tpu.crypto import bls
from teku_tpu.spec import config as C
from teku_tpu.spec import helpers as H
from teku_tpu.spec.builder import (make_local_signer, produce_attestations,
                                   produce_block)
from teku_tpu.spec.electra import block as XB
from teku_tpu.spec.electra import epoch as XE
from teku_tpu.spec.electra import helpers as EH
from teku_tpu.spec.electra.datastructures import (PendingDeposit,
                                                  get_electra_schemas)
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.spec.milestones import build_fork_schedule, SpecMilestone
from teku_tpu.spec.transition import process_slots, state_transition
from teku_tpu.spec.verifiers import SIMPLE

CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=1,
                          BELLATRIX_FORK_EPOCH=2, CAPELLA_FORK_EPOCH=3,
                          DENEB_FORK_EPOCH=4, ELECTRA_FORK_EPOCH=5)


def _electra_state(n=16):
    cfg = dataclasses.replace(CFG, ALTAIR_FORK_EPOCH=0,
                              BELLATRIX_FORK_EPOCH=0, CAPELLA_FORK_EPOCH=0,
                              DENEB_FORK_EPOCH=0, ELECTRA_FORK_EPOCH=0)
    state, sks = interop_genesis(cfg, n)
    return cfg, state, sks


def _with_compounding(state, idx, effective=None, balance=None):
    v = state.validators[idx]
    validators = list(state.validators)
    validators[idx] = v.copy_with(
        withdrawal_credentials=b"\x02" + v.withdrawal_credentials[1:11]
        + b"\x00" + b"\xaa" * 20,
        **({"effective_balance": effective} if effective else {}))
    state = state.copy_with(validators=tuple(validators))
    if balance is not None:
        balances = list(state.balances)
        balances[idx] = balance
        state = state.copy_with(balances=tuple(balances))
    return state


def test_milestone_schedule_six_forks():
    sched = build_fork_schedule(CFG)
    assert sched.milestone_at_epoch(4) is SpecMilestone.DENEB
    assert sched.milestone_at_epoch(5) is SpecMilestone.ELECTRA
    assert sched.milestone_at_epoch(10 ** 9) is SpecMilestone.ELECTRA


@pytest.mark.slow
def test_electra_ladder_finalizes():
    state, sks = interop_genesis(CFG, 32)
    signer = make_local_signer(dict(enumerate(sks)))
    S = get_electra_schemas(CFG)
    atts, cur = [], state
    for slot in range(1, 8 * CFG.SLOTS_PER_EPOCH + 1):
        signed, post = produce_block(CFG, cur, slot, signer,
                                     attestations=atts)
        verified = state_transition(CFG, cur, signed,
                                    validate_result=True)
        assert verified.htr() == post.htr(), f"divergence at slot {slot}"
        atts = produce_attestations(CFG, post, slot,
                                    signed.message.htr(), signer)
        cur = post
    assert isinstance(cur, S.BeaconState)
    assert cur.fork.current_version == CFG.ELECTRA_FORK_VERSION
    assert cur.fork.previous_version == CFG.DENEB_FORK_VERSION
    assert cur.finalized_checkpoint.epoch >= 5
    assert cur.deposit_requests_start_index \
        == C.UNSET_DEPOSIT_REQUESTS_START_INDEX


def test_electra_attestation_requires_committee_bits_shape():
    cfg, state, sks = _electra_state(n=16)
    signer = make_local_signer(dict(enumerate(sks)))
    signed, cur = produce_block(cfg, state, 1, signer)
    atts = produce_attestations(cfg, cur, 1, signed.message.htr(),
                                signer)
    assert atts and atts[0].data.index == 0
    assert sum(atts[0].committee_bits) == 1
    adv = process_slots(cfg, cur, 2)
    post = XB.process_attestation(cfg, adv, atts[0], SIMPLE)
    # attesters earned their flags
    assert post.current_epoch_participation \
        != adv.current_epoch_participation
    # nonzero data.index rejected
    bad = atts[0].copy_with(data=atts[0].data.copy_with(index=1))
    with pytest.raises(Exception):
        XB.process_attestation(cfg, adv, bad, SIMPLE)
    # committee bit must match the aggregation bits length
    wrong_bits = atts[0].copy_with(
        aggregation_bits=tuple(atts[0].aggregation_bits) + (True,))
    with pytest.raises(Exception):
        XB.process_attestation(cfg, adv, wrong_bits, SIMPLE)


def test_withdrawal_request_full_exit_and_partial():
    cfg, state, _ = _electra_state()
    state = state.copy_with(slot=(cfg.SHARD_COMMITTEE_PERIOD + 1)
                            * cfg.SLOTS_PER_EPOCH)
    S = get_electra_schemas(cfg)
    # compounding validator 3 with excess balance
    state = _with_compounding(state, 3,
                              effective=cfg.MIN_ACTIVATION_BALANCE,
                              balance=cfg.MIN_ACTIVATION_BALANCE
                              + 7 * 10 ** 9)
    v = state.validators[3]
    addr = v.withdrawal_credentials[12:]
    # partial skim of 5 gwei-billions
    req = S.WithdrawalRequest(source_address=addr,
                              validator_pubkey=v.pubkey,
                              amount=5 * 10 ** 9)
    post = XB.process_withdrawal_request(cfg, state, req)
    (w,) = post.pending_partial_withdrawals
    assert w.validator_index == 3 and w.amount == 5 * 10 ** 9
    # full exit blocked while a partial is pending
    full = S.WithdrawalRequest(source_address=addr,
                               validator_pubkey=v.pubkey,
                               amount=C.FULL_EXIT_REQUEST_AMOUNT)
    post2 = XB.process_withdrawal_request(cfg, post, full)
    assert post2.validators[3].exit_epoch == C.FAR_FUTURE_EPOCH
    # full exit on the clean state initiates a churned exit
    post3 = XB.process_withdrawal_request(cfg, state, full)
    assert post3.validators[3].exit_epoch != C.FAR_FUTURE_EPOCH
    # wrong source address is a no-op
    bad = S.WithdrawalRequest(source_address=b"\x0f" * 20,
                              validator_pubkey=v.pubkey, amount=0)
    assert XB.process_withdrawal_request(cfg, state, bad) == state


def test_partial_withdrawals_drain_through_sweep():
    cfg, state, _ = _electra_state()
    state = state.copy_with(slot=(cfg.SHARD_COMMITTEE_PERIOD + 1)
                            * cfg.SLOTS_PER_EPOCH)
    S = get_electra_schemas(cfg)
    state = _with_compounding(state, 2,
                              effective=cfg.MIN_ACTIVATION_BALANCE,
                              balance=cfg.MIN_ACTIVATION_BALANCE
                              + 9 * 10 ** 9)
    v = state.validators[2]
    req = S.WithdrawalRequest(source_address=v.withdrawal_credentials[12:],
                              validator_pubkey=v.pubkey,
                              amount=9 * 10 ** 9)
    state = XB.process_withdrawal_request(cfg, state, req)
    (pw,) = state.pending_partial_withdrawals
    # once withdrawable, the expected-withdrawals list pays it out
    state = state.copy_with(
        slot=(pw.withdrawable_epoch + 1) * cfg.SLOTS_PER_EPOCH)
    withdrawals, processed = XB.get_expected_withdrawals(cfg, state)
    assert processed == 1
    assert withdrawals[0].validator_index == 2
    assert withdrawals[0].amount == 9 * 10 ** 9
    payload = S.ExecutionPayload(withdrawals=tuple(withdrawals))
    post = XB.process_withdrawals(cfg, state, payload)
    assert post.pending_partial_withdrawals == ()
    assert post.balances[2] == cfg.MIN_ACTIVATION_BALANCE


def test_consolidation_request_switch_to_compounding():
    cfg, state, _ = _electra_state()
    S = get_electra_schemas(cfg)
    # validator 4 gets an eth1 credential first
    validators = list(state.validators)
    validators[4] = validators[4].copy_with(
        withdrawal_credentials=b"\x01" + bytes(11) + b"\xbb" * 20)
    balances = list(state.balances)
    balances[4] = cfg.MIN_ACTIVATION_BALANCE + 3 * 10 ** 9
    state = state.copy_with(validators=tuple(validators),
                            balances=tuple(balances))
    v = state.validators[4]
    req = S.ConsolidationRequest(source_address=b"\xbb" * 20,
                                 source_pubkey=v.pubkey,
                                 target_pubkey=v.pubkey)
    post = XB.process_consolidation_request(cfg, state, req)
    assert EH.has_compounding_withdrawal_credential(post.validators[4])
    # excess above MIN_ACTIVATION_BALANCE was queued as a deposit
    assert post.balances[4] == cfg.MIN_ACTIVATION_BALANCE
    (pd,) = post.pending_deposits
    assert pd.amount == 3 * 10 ** 9 and pd.pubkey == v.pubkey


def test_cross_consolidation_and_pending_processing():
    cfg, state, _ = _electra_state()
    state = state.copy_with(slot=(cfg.SHARD_COMMITTEE_PERIOD + 1)
                            * cfg.SLOTS_PER_EPOCH)
    S = get_electra_schemas(cfg)
    # boost total balance so the consolidation churn is non-trivial
    # (balance churn must exceed the 256-ETH activation/exit cap):
    # five compounding validators at 2048 ETH
    for i in (6, 7, 8, 9, 10):
        state = _with_compounding(
            state, i, effective=cfg.MAX_EFFECTIVE_BALANCE_ELECTRA,
            balance=cfg.MAX_EFFECTIVE_BALANCE_ELECTRA)
    assert EH.get_consolidation_churn_limit(cfg, state) \
        > cfg.MIN_ACTIVATION_BALANCE
    # source: eth1-credentialed validator 5; target: compounding 6
    validators = list(state.validators)
    validators[5] = validators[5].copy_with(
        withdrawal_credentials=b"\x01" + bytes(11) + b"\xcc" * 20)
    state = state.copy_with(validators=tuple(validators))
    src, tgt = state.validators[5], state.validators[6]
    req = S.ConsolidationRequest(source_address=b"\xcc" * 20,
                                 source_pubkey=src.pubkey,
                                 target_pubkey=tgt.pubkey)
    post = XB.process_consolidation_request(cfg, state, req)
    (pc,) = post.pending_consolidations
    assert (pc.source_index, pc.target_index) == (5, 6)
    exit_epoch = post.validators[5].exit_epoch
    assert exit_epoch != C.FAR_FUTURE_EPOCH
    # not withdrawable yet: pending consolidation waits
    waited = XE.process_pending_consolidations(cfg, post)
    assert len(waited.pending_consolidations) == 1
    # once the source is withdrawable, the balance moves to the target
    adv = post.copy_with(
        slot=(post.validators[5].withdrawable_epoch + 1)
        * cfg.SLOTS_PER_EPOCH)
    src_balance = adv.balances[5]
    done = XE.process_pending_consolidations(cfg, adv)
    assert done.pending_consolidations == ()
    assert done.balances[5] == src_balance - min(
        src_balance, post.validators[5].effective_balance)
    assert done.balances[6] == adv.balances[6] + min(
        src_balance, post.validators[5].effective_balance)


def test_deposit_request_and_pending_deposit_flow():
    cfg, state, sks = _electra_state()
    S = get_electra_schemas(cfg)
    # a deposit request for a brand-new key
    sk = 12345
    pk = bls.secret_to_public_key(sk)
    creds = b"\x01" + bytes(11) + b"\xdd" * 20
    amount = cfg.MIN_ACTIVATION_BALANCE
    from teku_tpu.spec.datastructures import DepositMessage
    msg = DepositMessage(pubkey=pk, withdrawal_credentials=creds,
                         amount=amount)
    domain = H.compute_domain(C.DOMAIN_DEPOSIT, cfg.GENESIS_FORK_VERSION,
                              bytes(32))
    sig = bls.sign(sk, H.compute_signing_root(msg, domain))
    req = S.DepositRequest(pubkey=pk, withdrawal_credentials=creds,
                           amount=amount, signature=sig, index=0)
    state = XB.process_deposit_request(cfg, state, req)
    assert state.deposit_requests_start_index == 0
    (pd,) = state.pending_deposits
    assert pd.slot == state.slot
    # finalize far enough and run the epoch queue: validator appears
    state = state.copy_with(
        finalized_checkpoint=state.finalized_checkpoint.copy_with(
            epoch=2),
        eth1_deposit_index=state.deposit_requests_start_index)
    n_before = len(state.validators)
    post = XE.process_pending_deposits(cfg, state)
    assert len(post.validators) == n_before + 1
    assert post.validators[-1].pubkey == pk
    assert post.balances[-1] == amount
    assert post.pending_deposits == ()
    # top-up of an existing validator skips the signature check
    top_up = PendingDeposit(pubkey=state.validators[0].pubkey,
                            withdrawal_credentials=bytes(32),
                            amount=10 ** 9, signature=b"\x00" * 96,
                            slot=0)
    state2 = state.copy_with(pending_deposits=(top_up,))
    post2 = XE.process_pending_deposits(cfg, state2)
    assert post2.balances[0] == state2.balances[0] + 10 ** 9


def test_pending_deposits_respect_finality_and_churn():
    cfg, state, _ = _electra_state()
    pd = PendingDeposit(pubkey=b"\x01" * 48,
                        withdrawal_credentials=bytes(32),
                        amount=10 ** 9, signature=b"\x00" * 96,
                        slot=10 * cfg.SLOTS_PER_EPOCH)
    state = state.copy_with(pending_deposits=(pd,))
    # not finalized yet: nothing processed
    post = XE.process_pending_deposits(cfg, state)
    assert len(post.pending_deposits) == 1
    # churn cap: huge deposits roll balance into the next epoch
    huge = PendingDeposit(pubkey=state.validators[1].pubkey,
                          withdrawal_credentials=bytes(32),
                          amount=10 * cfg.MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN_LIMIT,
                          signature=b"\x00" * 96, slot=0)
    state2 = state.copy_with(pending_deposits=(huge,),
                             finalized_checkpoint=state.
                             finalized_checkpoint.copy_with(epoch=1))
    post2 = XE.process_pending_deposits(cfg, state2)
    assert len(post2.pending_deposits) == 1      # still queued
    assert post2.deposit_balance_to_consume > 0  # churn accumulated


def test_exit_churn_schedules_by_balance():
    cfg, state, _ = _electra_state()
    limit = EH.get_activation_exit_churn_limit(cfg, state)
    state2, epoch1 = EH.compute_exit_epoch_and_update_churn(
        cfg, state, limit)
    # a second full-churn exit in the same epoch pushes one epoch out
    state3, epoch2 = EH.compute_exit_epoch_and_update_churn(
        cfg, state2, limit)
    assert epoch2 == epoch1 + 1


def test_effective_balance_cap_per_credential():
    cfg, state, _ = _electra_state()
    # compounding validator accrues above 32 ETH
    state = _with_compounding(state, 1,
                              balance=40 * 10 ** 9)
    post = XE.process_effective_balance_updates(cfg, state)
    assert post.validators[1].effective_balance == 40 * 10 ** 9
    # eth1-credentialed validator stays capped at MIN_ACTIVATION_BALANCE
    validators = list(state.validators)
    validators[9] = validators[9].copy_with(
        withdrawal_credentials=b"\x01" + bytes(11) + b"\x01" * 20)
    balances = list(state.balances)
    balances[9] = 40 * 10 ** 9
    state = state.copy_with(validators=tuple(validators),
                            balances=tuple(balances))
    post = XE.process_effective_balance_updates(cfg, state)
    assert post.validators[9].effective_balance \
        == cfg.MIN_ACTIVATION_BALANCE


def test_upgrade_queues_pre_activation_validators():
    """A deneb validator still waiting to activate crosses the fork as
    a pending deposit with zeroed balance."""
    cfg = dataclasses.replace(CFG, ALTAIR_FORK_EPOCH=0,
                              BELLATRIX_FORK_EPOCH=0, CAPELLA_FORK_EPOCH=0,
                              DENEB_FORK_EPOCH=0, ELECTRA_FORK_EPOCH=1)
    state, sks = interop_genesis(cfg, 16)
    # add a pending (not yet activated) validator pre-fork
    from teku_tpu.spec.block import get_validator_from_deposit
    newcomer = get_validator_from_deposit(
        cfg, b"\x22" * 48, b"\x00" + b"\x11" * 31,
        cfg.MAX_EFFECTIVE_BALANCE)
    state = state.copy_with(
        validators=tuple(state.validators) + (newcomer,),
        balances=tuple(state.balances) + (cfg.MAX_EFFECTIVE_BALANCE,),
        previous_epoch_participation=tuple(
            state.previous_epoch_participation) + (0,),
        current_epoch_participation=tuple(
            state.current_epoch_participation) + (0,),
        inactivity_scores=tuple(state.inactivity_scores) + (0,))
    post = process_slots(cfg, state, cfg.SLOTS_PER_EPOCH)
    S = get_electra_schemas(cfg)
    assert isinstance(post, S.BeaconState)
    assert post.balances[-1] == 0
    assert post.validators[-1].effective_balance == 0
    (pd,) = post.pending_deposits
    assert pd.pubkey == b"\x22" * 48
    assert pd.amount == cfg.MAX_EFFECTIVE_BALANCE


def test_single_attestation_normalization():
    """The electra subnet wire shape converts to the pooled one-hot
    form; wrong committee membership or nonzero index is rejected."""
    from teku_tpu.spec import Spec
    from teku_tpu.node.validators import normalize_attestation
    cfg, state, sks = _electra_state(n=16)
    spec = Spec(cfg)
    S = get_electra_schemas(cfg)
    slot, ci = 1, 0
    adv = process_slots(cfg, state, slot)
    committee = H.get_beacon_committee(cfg, adv, slot, ci)
    attester = committee[1]
    data = S.AttestationData(slot=slot, index=0,
                             beacon_block_root=b"\x01" * 32,
                             source=adv.current_justified_checkpoint,
                             target=S.Checkpoint(epoch=0,
                                                 root=b"\x02" * 32))
    single = S.SingleAttestation(committee_index=ci,
                                 attester_index=attester,
                                 data=data, signature=b"\x03" * 96)
    att = normalize_attestation(spec, adv, single)
    assert att is not None
    assert sum(att.aggregation_bits) == 1
    assert att.aggregation_bits[1]
    assert sum(att.committee_bits) == 1 and att.committee_bits[ci]
    # attester not in the claimed committee
    outsider = next(i for i in range(16) if i not in committee)
    bad = single.copy_with(attester_index=outsider)
    assert normalize_attestation(spec, adv, bad) is None
    # nonzero data.index violates the wire rule
    bad2 = single.copy_with(data=data.copy_with(index=1))
    assert normalize_attestation(spec, adv, bad2) is None


def test_electra_slashing_penalty_per_increment():
    """EIP-7251 rounds per increment FIRST (adjusted // (total//inc)),
    diverging from the altair formula whenever adjusted < total//inc
    rounds to a different quantum."""
    from teku_tpu.spec.altair import epoch as AE
    cfg, state, _ = _electra_state(16)
    epoch = H.get_current_epoch(cfg, state)
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    target = epoch + cfg.EPOCHS_PER_SLASHINGS_VECTOR // 2
    validators = list(state.validators)
    validators[0] = validators[0].copy_with(slashed=True,
                                            withdrawable_epoch=target)
    slashings = list(state.slashings)
    slashings[0] = 3 * inc   # small enough that rounding modes differ
    state = state.copy_with(validators=tuple(validators),
                            slashings=tuple(slashings))
    total = H.get_total_active_balance(cfg, state)
    adjusted = min(sum(state.slashings)
                   * cfg.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX,
                   total)
    per_increment = adjusted // (total // inc)
    eb = state.validators[0].effective_balance
    expected = per_increment * (eb // inc)
    out = XE.process_slashings(cfg, state)
    assert state.balances[0] - out.balances[0] == expected
    # and the altair formula would have charged a different amount
    old = AE.process_slashings(
        cfg, state,
        multiplier=cfg.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX)
    assert (state.balances[0] - old.balances[0]) != expected
