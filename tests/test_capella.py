"""Capella: four-fork ladder, withdrawals sweep, BLS-to-execution
changes, historical summaries."""

import dataclasses

import pytest

from teku_tpu.crypto import bls
from teku_tpu.spec import config as C
from teku_tpu.spec import helpers as H
from teku_tpu.spec.capella import block as CB
from teku_tpu.spec.capella.datastructures import (
    get_capella_schemas, payload_to_header_capella)
from teku_tpu.spec.builder import (make_local_signer, produce_attestations,
                                   produce_block)
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.spec.milestones import build_fork_schedule, SpecMilestone
from teku_tpu.spec.transition import process_slots, state_transition
from teku_tpu.spec.verifiers import SIMPLE

CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=1,
                          BELLATRIX_FORK_EPOCH=2, CAPELLA_FORK_EPOCH=3)


def test_milestone_schedule_four_forks():
    sched = build_fork_schedule(CFG)
    assert sched.milestone_at_epoch(2) is SpecMilestone.BELLATRIX
    assert sched.milestone_at_epoch(3) is SpecMilestone.CAPELLA
    assert sched.milestone_at_epoch(999) is SpecMilestone.CAPELLA


@pytest.mark.slow
def test_capella_ladder_finalizes_with_payloads():
    state, sks = interop_genesis(CFG, 32)
    signer = make_local_signer(dict(enumerate(sks)))
    S = get_capella_schemas(CFG)
    atts = []
    cur = state
    for slot in range(1, 6 * CFG.SLOTS_PER_EPOCH + 1):
        signed, post = produce_block(CFG, cur, slot, signer,
                                     attestations=atts)
        verified = state_transition(CFG, cur, signed,
                                    validate_result=True)
        assert verified.htr() == post.htr(), f"divergence at slot {slot}"
        atts = produce_attestations(CFG, post, slot,
                                    signed.message.htr(), signer)
        cur = post
    assert isinstance(cur, S.BeaconState)
    assert cur.fork.current_version == CFG.CAPELLA_FORK_VERSION
    assert cur.finalized_checkpoint.epoch >= 3
    # payload chain is live after the capella fork: one payload per
    # capella slot (slots 24..48 inclusive on this schedule)
    n_payloads = 3 * CFG.SLOTS_PER_EPOCH + 1
    assert cur.latest_execution_payload_header.block_number == n_payloads
    # sweep cursor moved (no withdrawable validators: BLS credentials)
    assert cur.next_withdrawal_index == 0
    assert cur.next_withdrawal_validator_index \
        == n_payloads * CFG.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP % 32


def _capella_state(n=16):
    cfg = dataclasses.replace(CFG, ALTAIR_FORK_EPOCH=0,
                              BELLATRIX_FORK_EPOCH=0, CAPELLA_FORK_EPOCH=0)
    state, sks = interop_genesis(cfg, n)
    return cfg, state, sks


def test_expected_withdrawals_sweep():
    cfg, state, _ = _capella_state()
    # nobody has eth1 credentials yet -> empty sweep
    assert CB.get_expected_withdrawals(cfg, state) == []
    # give validator 3 an eth1 credential and an excess balance -> skim
    validators = list(state.validators)
    validators[3] = validators[3].copy_with(
        withdrawal_credentials=b"\x01" + bytes(11) + b"\xaa" * 20)
    balances = list(state.balances)
    balances[3] = cfg.MAX_EFFECTIVE_BALANCE + 5
    state = state.copy_with(validators=tuple(validators),
                            balances=tuple(balances))
    (w,) = CB.get_expected_withdrawals(cfg, state)
    assert w.validator_index == 3 and w.amount == 5
    assert w.address == b"\xaa" * 20
    # exit validator 3 -> full withdrawal of the whole balance
    validators[3] = validators[3].copy_with(withdrawable_epoch=0)
    state = state.copy_with(validators=tuple(validators))
    (w,) = CB.get_expected_withdrawals(cfg, state)
    assert w.amount == cfg.MAX_EFFECTIVE_BALANCE + 5


def test_process_withdrawals_applies_and_advances_cursor():
    cfg, state, _ = _capella_state()
    validators = list(state.validators)
    validators[2] = validators[2].copy_with(
        withdrawal_credentials=b"\x01" + bytes(11) + b"\xbb" * 20)
    balances = list(state.balances)
    balances[2] = cfg.MAX_EFFECTIVE_BALANCE + 7
    state = state.copy_with(validators=tuple(validators),
                            balances=tuple(balances))
    S = get_capella_schemas(cfg)
    payload = S.ExecutionPayload(
        withdrawals=tuple(CB.get_expected_withdrawals(cfg, state)))
    post = CB.process_withdrawals(cfg, state, payload)
    assert post.balances[2] == cfg.MAX_EFFECTIVE_BALANCE
    assert post.next_withdrawal_index == 1
    # wrong withdrawal list rejected
    with pytest.raises(Exception):
        CB.process_withdrawals(cfg, state, S.ExecutionPayload())


def test_bls_to_execution_change():
    cfg, state, sks = _capella_state()
    S = get_capella_schemas(cfg)
    idx = 5
    pk = bls.secret_to_public_key(sks[idx])
    change = S.BLSToExecutionChange(validator_index=idx,
                                    from_bls_pubkey=pk,
                                    to_execution_address=b"\xcc" * 20)
    domain = H.compute_domain(C.DOMAIN_BLS_TO_EXECUTION_CHANGE,
                              cfg.GENESIS_FORK_VERSION,
                              state.genesis_validators_root)
    sig = bls.sign(sks[idx], H.compute_signing_root(change, domain))
    signed = S.SignedBLSToExecutionChange(message=change, signature=sig)
    post = CB.process_bls_to_execution_change(cfg, state, signed, SIMPLE)
    creds = post.validators[idx].withdrawal_credentials
    assert creds[:1] == b"\x01" and creds[12:] == b"\xcc" * 20
    # replay against the now-eth1 credential is rejected
    with pytest.raises(Exception):
        CB.process_bls_to_execution_change(cfg, post, signed, SIMPLE)
    # a signature by the wrong key is rejected
    bad = S.SignedBLSToExecutionChange(
        message=change, signature=bls.sign(sks[idx + 1], H.
                                           compute_signing_root(change,
                                                                domain)))
    with pytest.raises(Exception):
        CB.process_bls_to_execution_change(cfg, state, bad, SIMPLE)


def test_historical_summaries_replace_roots():
    """Crossing a SLOTS_PER_HISTORICAL_ROOT boundary post-capella
    appends to historical_summaries, never to historical_roots."""
    cfg, state, sks = _capella_state(n=16)
    period = cfg.SLOTS_PER_HISTORICAL_ROOT  # 64 slots on minimal
    n_roots = len(state.historical_roots)
    adv = process_slots(cfg, state, period)
    assert len(adv.historical_roots) == n_roots
    assert len(adv.historical_summaries) == 1
    s = adv.historical_summaries[0]
    assert s.block_summary_root != bytes(32)
    assert s.state_summary_root != bytes(32)


def test_capella_payload_header_has_withdrawals_root():
    S = get_capella_schemas(CFG)
    payload = S.ExecutionPayload(
        block_hash=b"\x11" * 32,
        withdrawals=(S.Withdrawal(index=0, validator_index=1,
                                  address=b"\x22" * 20, amount=9),))
    header = payload_to_header_capella(payload)
    assert header.block_hash == payload.block_hash
    assert header.withdrawals_root != bytes(32)
