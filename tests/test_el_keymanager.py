"""Execution-layer stub/JWT + key-manager REST API."""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio
import base64
import hashlib
import hmac
import json
import urllib.request

import pytest

from teku_tpu.executionlayer import (_jwt_token, ExecutionLayerStub,
                                     PayloadStatus)
from teku_tpu.validator.keymanager import KeyManagerApi
from teku_tpu.validator.keystore import encrypt


def test_execution_stub_accepts_everything():
    async def run():
        el = ExecutionLayerStub()
        st = await el.new_payload({"blockHash": "0x00"})
        assert st.status == "VALID"
        st = await el.forkchoice_updated(b"\x01" * 32, b"\x01" * 32,
                                         b"\x01" * 32)
        assert st.status == "VALID"
        assert el.new_payload_calls == 1 and el.forkchoice_calls == 1
    asyncio.run(run())


def test_engine_jwt_is_valid_hs256():
    secret = b"\x42" * 32
    token = _jwt_token(secret)
    h, p, s = token.split(".")

    def unb64(x):
        return base64.urlsafe_b64decode(x + "=" * (-len(x) % 4))
    assert json.loads(unb64(h))["alg"] == "HS256"
    assert "iat" in json.loads(unb64(p))
    expect = hmac.new(secret, f"{h}.{p}".encode(), hashlib.sha256).digest()
    assert unb64(s) == expect


def test_keymanager_import_list_delete(tmp_path):
    async def run():
        added, removed = [], []
        api = KeyManagerApi(tmp_path / "keys",
                            on_key_added=lambda pk, sk: added.append(pk),
                            on_key_removed=lambda pk: removed.append(pk))
        await api.start()
        try:
            base = f"http://127.0.0.1:{api.port}"
            loop = asyncio.get_running_loop()
            secret = bytes(range(32))
            from teku_tpu.crypto import bls
            pubkey = bls.secret_to_public_key(
                int.from_bytes(secret, "big"))
            ks = encrypt(secret, "pw", kdf="pbkdf2", pubkey=pubkey)

            def req(method, path, payload=None):
                r = urllib.request.Request(
                    base + path, method=method,
                    data=json.dumps(payload).encode() if payload else None,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(r, timeout=5) as resp:
                    return json.loads(resp.read())

            out = await loop.run_in_executor(None, req, "POST",
                                             "/eth/v1/keystores",
                                             {"keystores": [ks],
                                              "passwords": ["pw"]})
            assert out["data"][0]["status"] == "imported"
            assert added and added[0] == pubkey

            listed = await loop.run_in_executor(None, req, "GET",
                                                "/eth/v1/keystores")
            assert listed["data"][0]["validating_pubkey"] == (
                "0x" + pubkey.hex())

            out = await loop.run_in_executor(
                None, req, "DELETE", "/eth/v1/keystores",
                {"pubkeys": ["0x" + pubkey.hex()]})
            assert out["data"][0]["status"] == "deleted"
            assert removed == [pubkey]
            listed = await loop.run_in_executor(None, req, "GET",
                                                "/eth/v1/keystores")
            assert listed["data"] == []
            # wrong password import reports error, not crash
            out = await loop.run_in_executor(None, req, "POST",
                                             "/eth/v1/keystores",
                                             {"keystores": [ks],
                                              "passwords": ["wrong"]})
            assert out["data"][0]["status"] == "error"
        finally:
            await api.stop()
    asyncio.run(run())
