"""MSM-grade scalars stage: GLV + Pippenger adversarial parity suite.

The pippenger path (ops/msm.py) must be indistinguishable from the
ladder oracle at every level:

- the GLV constants: phi = [lambda] on G1, -psi^2 = [lambda] on G2,
  and the sampled-half-scalar map (k1, k2) -> k1 + k2*lambda mod r is
  nonzero/injective on the sampling range;
- kernel level: bucket MSMs over adversarial digit patterns (zero
  scalars, all-ones/max-duplicate bucket indices, infinity points,
  masked/excluded columns) match the oracle and the scalar_mul_bits
  ladder with IDENTICAL canonical() accumulator points;
- pipeline level: verify_staged_pippenger is verdict-bit-identical to
  verify_staged_grouped driven with the effective multipliers'
  255-bit bit arrays;
- provider level: batch_verify verdicts agree between the paths (and
  with the pure oracle) across committee-duplicated, all-unique,
  tampered, infinity-signature, and over-group-cap batches, on BOTH
  mont_mul engines (vpu and mxu-force with freshly traced stages).

Shapes stay tiny (4/8-lane buckets) so the CPU-XLA compiles are shared
across cases and cached persistently (conftest compile cache).
"""

import random
from contextlib import contextmanager

import numpy as np
import pytest

import jax

from teku_tpu.crypto.bls import curve as C
from teku_tpu.crypto.bls import keygen
from teku_tpu.crypto.bls.constants import P, R, X_ABS
from teku_tpu.crypto.bls.pure_impl import PureBls12381
from teku_tpu.ops import h2c
from teku_tpu.ops import limbs as fp
from teku_tpu.ops import msm
from teku_tpu.ops import mxu
from teku_tpu.ops import points as PT
from teku_tpu.ops import verify as V
from teku_tpu.ops.provider import JaxBls12381

rng = random.Random(0x88)

PURE = PureBls12381()
SKS = [keygen(bytes([120 + i]) * 32) for i in range(4)]
PKS = [PURE.secret_key_to_public_key(sk) for sk in SKS]
G2_INF_WIRE = bytes([0xC0] + [0] * 95)


def rand_g1():
    return C.point_mul(C.FQ_OPS, rng.randrange(1, R), C.G1_GENERATOR)


def rand_g2():
    return C.point_mul(C.FQ2_OPS, rng.randrange(1, R), C.G2_GENERATOR)


def stack_g1(points):
    return tuple(np.stack([fp.int_to_mont(p[i]) for p in points])
                 for i in range(3))


def stack_g2(points):
    return tuple(
        (np.stack([fp.int_to_mont(p[i][0]) for p in points]),
         np.stack([fp.int_to_mont(p[i][1]) for p in points]))
        for i in range(3))


def bits_of(scalars, nbits):
    """Host ints -> (N, nbits) MSB-first bit array (the ladder-oracle
    form scalar_mul_bits consumes for the 255-bit effective
    multipliers)."""
    out = np.zeros((len(scalars), nbits), dtype=np.int64)
    for i, s in enumerate(scalars):
        for j in range(nbits):
            out[i, nbits - 1 - j] = (int(s) >> j) & 1
    return out


def _triples(lane_msgs, tamper_lane=None, inf_sig_lane=None):
    out = []
    for i, m in enumerate(lane_msgs):
        if i == inf_sig_lane:
            out.append(([PKS[i % 4]], m, G2_INF_WIRE))
            continue
        sign_msg = b"tampered" if i == tamper_lane else m
        out.append(([PKS[i % 4]], m, PURE.sign(SKS[i % 4], sign_msg)))
    return out


@contextmanager
def fresh_stage_jits():
    """Retrace every staged program (the module-level jit table caches
    by shape only — a forced mont engine needs fresh jit objects)."""
    old = V._STAGED_JITS
    V._STAGED_JITS = None
    try:
        yield
    finally:
        V._STAGED_JITS = old


# --------------------------------------------------------------------------
# GLV constants + sampling
# --------------------------------------------------------------------------

def test_lambda_is_the_shared_eigenvalue():
    # G1: [lambda]P == phi(P) = (beta*x, y) — on a random subgroup
    # point, not just the generator the import-time assert uses
    p = C.to_affine(C.FQ_OPS, rand_g1())
    lam_p = C.to_affine(C.FQ_OPS, C.point_mul(
        C.FQ_OPS, msm.LAMBDA, (p[0], p[1], 1)))
    assert lam_p == (PT._BETA * p[0] % P, p[1])
    # G2: [lambda]Q == -psi^2(Q), via the device map
    qs = [rand_g2() for _ in range(2)]
    dev = jax.jit(msm.g2_lambda_point)(stack_g2(qs))
    for i, q in enumerate(qs):
        exp = C.point_mul(C.FQ2_OPS, msm.LAMBDA, q)
        assert C.point_eq(C.FQ2_OPS, PT.g2_from_device(dev, (i,)), exp)


def test_effective_scalar_nonzero_and_injective():
    # (0, 0) is the ONLY zero of k1 + k2*lambda on the range: for
    # k2 != 0, k2*lambda mod r = r - z^2*k2 with z^2*k2 < 2^160 << r,
    # so the sum can never cancel a k1 < 2^32
    z2 = (-msm.LAMBDA) % R          # = z^2 mod r
    assert z2 == X_ABS * X_ABS      # |z|^2 < sqrt(r): not wrapped
    assert msm.effective_scalar(0, 0) == 0
    seen = set()
    for _ in range(200):
        k1, k2 = rng.getrandbits(32), rng.getrandbits(32)
        r_eff = msm.effective_scalar(k1, k2)
        assert (r_eff != 0) or (k1 == 0 and k2 == 0)
        assert r_eff not in seen
        seen.add(r_eff)
    # the sampler nudges the one bad pair
    k1, k2 = msm.glv_sample_from_uint64(np.zeros(3, dtype=np.uint64))
    assert list(k1) == [1, 1, 1] and list(k2) == [0, 0, 0]


def test_digit_builder_is_msb_first():
    d = msm.glv_digits_np(np.array([0x12345678], dtype=np.uint64),
                          np.array([0xF0000001], dtype=np.uint64),
                          window=4)
    assert d.shape == (1, 2, 8)
    assert list(d[0, 0]) == [1, 2, 3, 4, 5, 6, 7, 8]
    assert list(d[0, 1]) == [15, 0, 0, 0, 0, 0, 0, 1]
    with pytest.raises(ValueError):
        msm.glv_digits_np(np.array([1 << 32], dtype=np.uint64),
                          np.array([0], dtype=np.uint64))


# --------------------------------------------------------------------------
# Kernel level: adversarial bucket patterns in ONE compiled shape
# --------------------------------------------------------------------------

def test_msm_rows_adversarial_grid():
    """4 rows x 4 cols, one compile: zero scalars, all-ones digits
    (every lane dropping into the same max bucket per window),
    duplicate points + duplicate bucket indices, an infinity point
    column, and excluded columns — vs the oracle."""
    pts = [[rand_g1() for _ in range(4)] for _ in range(4)]
    pts[2][1] = pts[2][0]                      # duplicate point
    pts[2][3] = C.infinity(C.FQ_OPS)           # infinity column
    k = np.array(
        [[0, 0, 0, 0],                         # zero scalars
         [0xFFFFFFFF] * 4,                     # all-ones: max dup buckets
         [7, 7, 0xABCD, 5],                    # dup digits + inf point
         [1, 0xDEAD, 2, 0xFFFF]],
        dtype=np.uint64)
    include = np.ones((4, 4), dtype=bool)
    include[3, 1] = include[3, 3] = False      # masked/absent columns
    digits = np.stack([msm.glv_digits_np(
        k[r], np.zeros(4, np.uint64))[:, 0, :] for r in range(4)])
    dev = jax.tree_util.tree_map(lambda *xs: np.stack(xs),
                                 *[stack_g1(row) for row in pts])
    out = jax.jit(
        lambda p, d, i: msm.msm_rows(PT.G1_KIT, p, d, i))(
            dev, digits, include)
    for r in range(4):
        exp = C.infinity(C.FQ_OPS)
        for c in range(4):
            if include[r, c]:
                exp = C.point_add(C.FQ_OPS, exp, C.point_mul(
                    C.FQ_OPS, int(k[r, c]), pts[r][c]))
        got = PT.g1_from_device(out, (r,))
        assert C.point_eq(C.FQ_OPS, got, exp), f"row {r}"
    # row of zero scalars must be exactly infinity (masked downstream)
    assert bool(np.asarray(PT.is_infinity(PT.G1_KIT, out))[0])


def _glv_ladder_g1(pk_dev, k1, k2):
    """The ladder-oracle G1 fold: [r_eff]P per lane via the 255-bit
    scalar_mul_bits walk (satellite: irregular widths pad, not
    demote)."""
    r_eff = [msm.effective_scalar(int(a), int(b)) for a, b in
             zip(k1, k2)]
    rb = bits_of(r_eff, 255)
    return jax.jit(lambda b, p: PT.scalar_mul_bits(PT.G1_KIT, b, p))(
        rb, pk_dev), r_eff


def test_grouped_msm_canonical_parity_vs_ladder():
    """g1_grouped_msm and g2_msm vs the ladder oracle given the SAME
    multipliers: canonical() affine accumulator limbs must be
    ARRAY-IDENTICAL (not just point-equal) — canonical() collapses any
    lazy representation drift, and every downstream stage (miller,
    finish) is deterministic in its inputs, so identical canonical
    accumulators subsume verdict bit-identity for the grouped
    pipeline.  The G1 fold is checked against BOTH the on-device
    255-bit scalar_mul_bits walk of the effective multipliers (the
    padded irregular-width fast path) and the host bigint oracle; the
    G2 fold against the host oracle (the device 255-bit G2 ladder
    would re-prove the same scalar_mul_bits contract at 3x the
    compile cost)."""
    lanes = 4
    pk_pts = [rand_g1() for _ in range(lanes)]
    sig_pts = [rand_g2() for _ in range(lanes - 1)] + [
        C.infinity(C.FQ2_OPS)]                 # an infinity sig lane
    pk_dev = stack_g1(pk_pts)
    sig_dev = stack_g2(sig_pts)
    k1 = np.array([5, 0, 0xFFFFFFFF, 0x1234], dtype=np.uint64)
    k2 = np.array([0, 3, 0xFFFFFFFF, 0xBEEF], dtype=np.uint64)
    digits = msm.glv_digits_np(k1, k2)
    # two groups of two lanes; lane 1 miller-masked out of group 0
    group_idx = np.array([[0, 1], [2, 3]], dtype=np.int32)
    group_present = np.ones((2, 2), dtype=bool)
    miller_mask = np.array([True, False, True, True])

    agg_pip = jax.jit(msm.g1_grouped_msm)(
        pk_dev, digits, group_idx, group_present, miller_mask)
    lad, r_eff = _glv_ladder_g1(pk_dev, k1, k2)
    inf = PT.infinity_like(PT.G1_KIT, lad[0])
    lad = PT._select_point(PT.G1_KIT, miller_mask, lad, inf)
    agg_lad = V.point_batch_sum(
        PT.G1_KIT, jax.tree_util.tree_map(
            lambda x: jnp_stack_rows(x, group_idx), lad))
    # canonical affine limbs: identical arrays, ladder vs pippenger
    pip_aff = V.to_affine_g1(agg_pip)
    lad_aff = V.to_affine_g1(agg_lad)
    for a, b in zip(pip_aff, lad_aff):
        assert np.array_equal(np.asarray(fp.canonical(a)),
                              np.asarray(fp.canonical(b)))
    # ... and identical to the HOST oracle's canonical limbs
    for u in range(2):
        exp = C.infinity(C.FQ_OPS)
        for lane in group_idx[u]:
            if not miller_mask[lane]:
                continue
            exp = C.point_add(C.FQ_OPS, exp, C.point_mul(
                C.FQ_OPS, r_eff[lane], pk_pts[lane]))
        ex, ey = C.to_affine(C.FQ_OPS, exp)
        assert np.array_equal(np.asarray(
            fp.canonical_plain(pip_aff[0]))[u], fp.int_to_limbs(ex))
        assert np.array_equal(np.asarray(
            fp.canonical_plain(pip_aff[1]))[u], fp.int_to_limbs(ey))
    # G2: whole-batch MSM vs the host oracle's canonical limbs
    wsig_pip = jax.jit(msm.g2_msm)(sig_dev, digits)
    exp2 = C.infinity(C.FQ2_OPS)
    for lane in range(lanes):
        exp2 = C.point_add(C.FQ2_OPS, exp2, C.point_mul(
            C.FQ2_OPS, r_eff[lane], sig_pts[lane]))
    ex2, ey2 = C.to_affine(C.FQ2_OPS, exp2)
    aff_pip = h2c.to_affine_g2(wsig_pip)
    for got, want in zip(
            (aff_pip[0][0], aff_pip[0][1], aff_pip[1][0], aff_pip[1][1]),
            (ex2[0], ex2[1], ey2[0], ey2[1])):
        assert np.array_equal(np.asarray(fp.canonical_plain(got))[0],
                              fp.int_to_limbs(want))


def jnp_stack_rows(x, group_idx):
    """Gather lanes into (G, U, ...) rows for point_batch_sum."""
    return np.moveaxis(np.asarray(x)[group_idx], 1, 0)


# --------------------------------------------------------------------------
# Provider level: committee shapes, both mont engines.  (Verdict
# bit-identity given IDENTICAL multipliers is owned by the canonical-
# accumulator test above — the stages downstream of scalars are
# deterministic in their inputs — so the provider grid checks the
# production sampling paths end to end against each other and the
# pure oracle.)
# --------------------------------------------------------------------------

def _adversarial_cases():
    return [
        ("dup4", _triples([b"msm-a"] * 4), True),
        ("unique", _triples([b"msm-u%d" % i for i in range(4)]), True),
        ("tamper", _triples([b"msm-a"] * 4, tamper_lane=2), False),
        ("inf-sig", _triples([b"msm-a"] * 3 + [b"msm-b"],
                             inf_sig_lane=3), False),
        ("pad", _triples([b"msm-p", b"msm-p", b"msm-q"]), True),
    ]


def _run_provider_cases():
    with msm.force("pippenger"):
        pip = JaxBls12381()
        pip_verdicts = {name: pip.batch_verify(t)
                        for name, t, _ in _adversarial_cases()}
        assert pip.msm_dispatches["ladder"] == 0
        assert pip.msm_dispatches["pippenger"] == len(pip_verdicts)
    with msm.force("ladder"):
        lad = JaxBls12381()
        lad_verdicts = {name: lad.batch_verify(t)
                        for name, t, _ in _adversarial_cases()}
        assert lad.msm_dispatches["pippenger"] == 0
    for name, triples, expect in _adversarial_cases():
        assert pip_verdicts[name] is lad_verdicts[name] is expect, name
        assert PURE.batch_verify(triples) is expect, name


def test_provider_verdict_parity_vpu():
    assert mxu.resolve() == "vpu"     # CPU backend resolves to vpu
    _run_provider_cases()


def test_provider_verdict_parity_mxu_force():
    """The same adversarial grid with every staged program freshly
    traced under the forced MXU mont_mul engine (the module jit table
    caches by shape, so parity on the second engine needs new jit
    objects)."""
    with mxu.force("mxu-force"), fresh_stage_jits():
        _run_provider_cases()


def test_committee_split_across_group_cap_rows(monkeypatch):
    """A committee larger than TEKU_TPU_H2C_GROUP_CAP splits across
    bucket-MSM rows sharing one H(m); verdicts must be unchanged.
    (The ladder path's cap-2 behavior is pinned by test_h2c_dedup's
    group-cap test at the same shapes — this covers the pippenger
    side.)"""
    monkeypatch.setenv("TEKU_TPU_H2C_GROUP_CAP", "2")
    with msm.force("pippenger"):
        impl = JaxBls12381()
        assert impl._group_cap == 2
        msgs = [b"msm-split"] * 5 + [b"msm-solo"]
        assert impl.batch_verify(_triples(msgs)) is True
        assert impl.batch_verify(_triples(msgs, tamper_lane=1)) is False
        assert PURE.batch_verify(_triples(msgs)) is True


def test_aggregate_verify_r1_on_pippenger():
    # randomize=False dispatches (k1, k2) = (1, 0): the distinct-
    # message aggregate equation needs r = 1 EXACTLY
    msgs = [b"msm-agg-0", b"msm-agg-1"]
    agg = PURE.aggregate_signatures(
        [PURE.sign(SKS[i], m) for i, m in enumerate(msgs)])
    with msm.force("pippenger"):
        impl = JaxBls12381()
        assert impl.aggregate_verify(PKS[:2], msgs, agg) is True
        assert impl.aggregate_verify(PKS[:2], msgs[::-1], agg) is False


# --------------------------------------------------------------------------
# Path resolution + metrics
# --------------------------------------------------------------------------

def test_resolve_auto_rules(monkeypatch):
    with msm.force("ladder"):
        assert msm.resolve(lanes=4096, rows=1) == "ladder"
    with msm.force("pippenger"):
        assert msm.resolve(lanes=1, rows=1) == "pippenger"
        # the sharded kernel always ladders (groups cross shards)
        assert msm.resolve(lanes=4096, rows=1, sharded=True) == "ladder"
    with msm.force("auto"):
        # CPU dispatch device: auto keeps the long-validated ladder
        assert msm.resolve(lanes=4096, rows=16) == "ladder"
        monkeypatch.setattr(msm, "_device_is_tpu", lambda: True)
        assert msm.resolve(lanes=256, rows=32) == "pippenger"
        assert msm.resolve(lanes=256, rows=256) == "ladder"  # dup 1
        assert msm.resolve(lanes=8, rows=2) == "ladder"      # tiny
        assert msm.resolve(lanes=None, rows=None) == "ladder"
        # crossover boundary compares the EXACT ratio: dup 1.9996
        # must stay below the 2.0 threshold even though the ledger
        # record's rounded why["dup"] reads 2.0
        path, why = msm.explain(lanes=4999, rows=2500)
        assert path == "ladder"
        assert why["dup"] == 2.0                   # rounded for record
        assert msm.resolve(lanes=5000, rows=2500) == "pippenger"
    # invalid env value degrades to auto with one warning
    monkeypatch.setenv(msm.ENV_VAR, "bogus")
    msm.set_path(None)
    assert msm.get_path() == "auto"


def test_msm_dispatch_metrics_move():
    from teku_tpu.ops import provider as pv
    before = pv._M_MSM.labels(path="pippenger").value
    lanes_before = pv._M_MSM_LANES.labels(path="pippenger").value
    with msm.force("pippenger"):
        impl = JaxBls12381()
        assert impl.batch_verify(_triples([b"msm-metric"] * 4)) is True
    assert pv._M_MSM.labels(path="pippenger").value == before + 1
    assert pv._M_MSM_LANES.labels(path="pippenger").value \
        == lanes_before + 4


def test_g2_msm_segment_merge(monkeypatch):
    """S > 1 segmented accumulation: the per-segment bucket tables
    tree-add before the reduce (bucket sums are additive across
    disjoint column sets) — forced by pinning the process seg length
    below 2N."""
    monkeypatch.setattr(msm, "_seg_cache", [2])    # 2N=8 -> S=4
    qs = [rand_g2() for _ in range(4)]
    k1 = np.array([3, 5, 7, 11], dtype=np.uint64)
    k2 = np.array([1, 0, 2, 9], dtype=np.uint64)
    digits = msm.glv_digits_np(k1, k2)
    out = jax.jit(msm.g2_msm)(stack_g2(qs), digits)  # fresh jit: S=4
    exp = C.infinity(C.FQ2_OPS)
    for i, q in enumerate(qs):
        exp = C.point_add(C.FQ2_OPS, exp, C.point_mul(
            C.FQ2_OPS,
            msm.effective_scalar(int(k1[i]), int(k2[i])), q))
    assert C.point_eq(C.FQ2_OPS, PT.g2_from_device(out, (0,)), exp)


def test_tuning_knobs_degrade_not_raise(monkeypatch):
    """A typo'd TEKU_TPU_MSM_WINDOW / TEKU_TPU_MSM_SEG must degrade to
    the default with a warning — never start failing live dispatches
    (same contract as an invalid TEKU_TPU_MSM)."""
    monkeypatch.setattr(msm, "_warned_window", [False])
    monkeypatch.setenv(msm.ENV_WINDOW, "nine")
    assert msm.window_env() == 4
    monkeypatch.setenv(msm.ENV_WINDOW, "9")        # out of 1..8
    assert msm.window_env() == 4
    monkeypatch.setenv(msm.ENV_WINDOW, "2")
    assert msm.window_env() == 2
    monkeypatch.setattr(msm, "_seg_cache", [])
    monkeypatch.setenv(msm.ENV_SEG, "31")          # not a pow-2
    assert msm._seg_len() == 32
    monkeypatch.setattr(msm, "_seg_cache", [])
    monkeypatch.setenv(msm.ENV_SEG, "8")
    assert msm._seg_len() == 8
    # the auto-crossover thresholds sit on the live dispatch path too
    monkeypatch.setattr(msm, "_device_is_tpu", lambda: True)
    monkeypatch.setenv(msm.ENV_AUTO_MIN_LANES, "thirtytwo")
    monkeypatch.setenv(msm.ENV_AUTO_MIN_DUP, "")
    with msm.force("auto"):
        assert msm.resolve(lanes=256, rows=32) == "pippenger"
    # the seg choice is process-pinned (g2_msm only runs under jit:
    # a per-call env read would silently stop mattering after the
    # first trace anyway — see msm._seg_len)
    monkeypatch.setenv(msm.ENV_SEG, "16")
    assert msm._seg_len() == 8


def test_capacity_latency_series_split_by_msm_path():
    """Under msm auto, same-padded-shape dispatches can run EITHER
    scalars program; the capacity model's per-(shape, path) latency
    series must not blend them (the admission controller plans
    batches from these p50s)."""
    from teku_tpu.infra import capacity
    with msm.force("pippenger"):
        impl = JaxBls12381()
        assert impl.batch_verify(_triples([b"msm-cap"] * 4)) is True
    snap = capacity.snapshot()["shapes"]
    paths = {p for per_shape in snap.values() for p in per_shape}
    assert any(p.endswith("+pip") for p in paths), paths
