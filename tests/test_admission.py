"""Overload control: adaptive batching, brownout hysteresis, priority
classes, shed-by-class — and the closed-loop 10x acceptance run.

Everything runs on an injected virtual clock (the controller, the
capacity telemetry and the simulated device share one), so control
decisions are deterministic without sleeps and the full 10x overload
acceptance property — p50 <= 100 ms, zero BLOCK_IMPORT sheds, sheds
ordered OPTIMISTIC >= GOSSIP, edge-triggered brownout — runs in the
fast tier."""

import asyncio

import pytest

from teku_tpu.infra import capacity as capacity_mod
from teku_tpu.infra import flightrecorder
from teku_tpu.infra.health import (HealthStatus,
                                   admission_controller_check)
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.services.admission import (AdmissionController, BatchPlan,
                                         SHEDDABLE, VerifyClass,
                                         class_deadline_s)
from teku_tpu.services.overload_sim import (DEFAULT_MIX, VirtualClock,
                                            run_overload_sim)


class FakeClock(VirtualClock):
    pass


def make_controller(clock, telemetry=None, burn=lambda: 0.0, **kw):
    reg = kw.pop("registry", MetricsRegistry())
    recorder = kw.pop("recorder",
                      flightrecorder.FlightRecorder(registry=reg))
    telemetry = telemetry or capacity_mod.CapacityTelemetry(
        registry=reg, window_s=10.0, clock=clock, recorder=recorder)
    kw.setdefault("tick_s", 0.1)
    ctl = AdmissionController(
        telemetry=telemetry, burn_getter=burn, min_bucket=8,
        max_batch=256, slo_p50_s=0.1, clock=clock, registry=reg,
        recorder=recorder, **kw)
    return ctl, telemetry, recorder


# --------------------------------------------------------------------------
# Class vocabulary
# --------------------------------------------------------------------------

def test_class_order_and_shed_set():
    """The priority order is the drain order, and only the two lowest
    classes are ever sheddable."""
    order = sorted(VerifyClass)
    assert order == [VerifyClass.VIP, VerifyClass.BLOCK_IMPORT,
                     VerifyClass.SYNC_CRITICAL, VerifyClass.GOSSIP,
                     VerifyClass.OPTIMISTIC]
    assert SHEDDABLE == (VerifyClass.OPTIMISTIC, VerifyClass.GOSSIP)
    assert VerifyClass.BLOCK_IMPORT not in SHEDDABLE
    assert VerifyClass.VIP not in SHEDDABLE
    # per-class deadlines are positive and env-overridable
    for c in VerifyClass:
        assert class_deadline_s(c) > 0


def test_class_deadline_env_override(monkeypatch):
    monkeypatch.setenv("TEKU_TPU_VERIFY_CLASS_GOSSIP_DEADLINE_MS",
                       "250")
    assert class_deadline_s(VerifyClass.GOSSIP) == pytest.approx(0.25)


# --------------------------------------------------------------------------
# Adaptive batch sizing
# --------------------------------------------------------------------------

def test_batch_size_pow2_from_depth_when_idle():
    """Latency mode (low utilization): the drain target is the
    smallest pow-2 covering the live queue depth, floored at the
    min bucket — bucket-aligned so padding waste stays low."""
    clock = FakeClock()
    ctl, tel, _ = make_controller(clock)
    tel.record_queue_depth(0)
    assert ctl.tick().batch_size == 8          # floor
    tel.record_queue_depth(37)
    clock.advance(1.0)
    assert ctl.tick().batch_size == 64         # next pow2 over 37
    tel.record_queue_depth(300)
    clock.advance(1.0)
    assert ctl.tick().batch_size == 256        # capped at max_batch


def test_batch_size_capped_by_modeled_device_latency():
    """The per-shape latency model caps the batch: the largest pow-2
    whose MODELED device time fits the per-dispatch budget (half the
    100 ms SLO by default)."""
    clock = FakeClock()
    ctl, tel, _ = make_controller(clock)
    # evidence: 256 lanes cost 258 ms, 128 cost 130 ms, 64 cost 66 ms,
    # 32 cost 34 ms (only 32 fits the 50 ms device budget)
    for lanes, cost in ((256, 0.258), (128, 0.130), (64, 0.066),
                        (32, 0.034)):
        for _ in range(3):
            t0 = clock()
            clock.advance(cost)
            tel.record_dispatch(f"{lanes}x1", "sim", lanes, t0, clock())
    tel.record_queue_depth(4000)
    # drive utilization into throughput mode: heavy offered load
    tel.record_arrival("t", 50_000)
    plan = ctl.tick()
    assert plan.batch_size == 32
    assert plan.modeled_batch_s == pytest.approx(0.034, abs=0.002)


def test_flush_deadline_only_under_pressure():
    """Workers only hold a partial batch open when utilization says
    throughput is the constraint; idle nodes dispatch immediately."""
    clock = FakeClock()
    ctl, tel, _ = make_controller(clock)
    tel.record_queue_depth(3)
    assert ctl.tick().flush_deadline_s == 0.0      # no pressure
    # pressure: modeled dispatches + demand over capacity
    for _ in range(4):
        t0 = clock()
        clock.advance(0.034)
        tel.record_dispatch("32x1", "sim", 32, t0, clock())
    tel.record_arrival("t", 20_000)
    clock.advance(0.2)
    plan = ctl.tick()
    assert plan.utilization > ctl.gather_util
    assert 0.0 < plan.flush_deadline_s <= ctl.device_budget_s * 0.5


# --------------------------------------------------------------------------
# Brownout state machine: edges + hysteresis
# --------------------------------------------------------------------------

def _pressurize(tel, clock, arrivals=50_000):
    """Dispatch evidence + offered arrivals so utilization reads >> 1."""
    for _ in range(3):
        t0 = clock()
        clock.advance(0.034)
        tel.record_dispatch("32x1", "sim", 32, t0, clock())
    tel.record_arrival("t", arrivals)


def test_brownout_enter_is_edge_triggered_and_exit_hysteretic():
    clock = FakeClock()
    ctl, tel, rec = make_controller(clock, hold_ticks=3)
    _pressurize(tel, clock)
    level = None
    for _ in range(5):               # sustained pressure, many ticks
        clock.advance(0.2)
        level = ctl.tick().brownout_level
    assert level >= 1
    enters = [e for e in rec.snapshot()
              if e["kind"] == "brownout_enter"
              and e.get("from_level") == 0]
    assert len(enters) == 1          # ONE edge despite 5 ticks
    # pressure drops below the EXIT threshold: the controller must
    # stay browned out for hold_ticks calm ticks before exiting
    clock.advance(tel.window_s + 1)  # arrival window decays to zero
    exit_events = lambda: [e for e in rec.snapshot()
                           if e["kind"] == "brownout_exit"]
    for i in range(ctl.hold_ticks - 1):
        clock.advance(0.2)
        assert ctl.tick().brownout_level >= 1, f"early exit at tick {i}"
    assert not exit_events()
    clock.advance(0.2)
    assert ctl.tick().brownout_level == 0
    assert len(exit_events()) == 1


def test_brownout_does_not_flap_on_oscillating_signal():
    """A burn rate oscillating across the ENTER threshold every tick
    produces ONE enter and zero exits (the calm ticks never reach
    hold_ticks because the calm threshold is LOWER than the enter
    threshold — hysteresis)."""
    clock = FakeClock()
    burn_values = iter([2.0, 1.0, 2.0, 1.0, 2.0, 1.0, 2.0, 1.0])
    ctl, tel, rec = make_controller(
        clock, burn=lambda: next(burn_values, 1.0), hold_ticks=3)
    for _ in range(8):
        clock.advance(0.2)
        ctl.tick()
    events = [e["kind"] for e in rec.snapshot()
              if e["kind"].startswith("brownout")]
    assert events == ["brownout_enter"]
    assert ctl.brownout_level >= 1


def test_brownout_escalates_to_level2_and_events_carry_levels():
    clock = FakeClock()
    burn_box = {"v": 1.6}            # >= burn_enter (1.5): level 1
    ctl, tel, rec = make_controller(clock, burn=lambda: burn_box["v"])
    clock.advance(0.2)
    assert ctl.tick().brownout_level == 1
    burn_box["v"] = 3.1              # >= 2x burn_enter: level 2
    clock.advance(0.2)
    plan = ctl.tick()
    assert plan.brownout_level == 2
    assert plan.sheds(VerifyClass.OPTIMISTIC)
    assert plan.sheds(VerifyClass.GOSSIP)
    assert not plan.sheds(VerifyClass.BLOCK_IMPORT)
    assert not plan.sheds(VerifyClass.SYNC_CRITICAL)
    assert not plan.sheds(VerifyClass.VIP)
    enters = [e for e in rec.snapshot()
              if e["kind"] == "brownout_enter"]
    assert [e["level"] for e in enters] == [1, 2]
    assert [e["from_level"] for e in enters] == [0, 1]


def test_brownout_deescalates_one_level_in_the_exit_enter_band():
    """Level 2 entered on a spike must step DOWN to level 1 (after a
    full hold window below the level-2 entry threshold) when load
    settles between the exit and enter thresholds — NOT stay at full
    GOSSIP shedding forever on the stale spike verdict — and must not
    fully exit while the signals are above the exit threshold."""
    clock = FakeClock()
    burn_box = {"v": 3.5}            # >= 2x burn_enter: level 2
    ctl, tel, rec = make_controller(clock, burn=lambda: burn_box["v"],
                                    hold_ticks=3)
    clock.advance(0.2)
    assert ctl.tick().brownout_level == 2
    # load settles in the band: above burn_exit (0.8), below
    # burn_enter (1.5) — justifies neither level 2 nor a full exit
    burn_box["v"] = 1.0
    for i in range(ctl.hold_ticks - 1):
        clock.advance(0.2)
        assert ctl.tick().brownout_level == 2, f"early step at {i}"
    clock.advance(0.2)
    assert ctl.tick().brownout_level == 1    # one de-escalation edge
    deesc = [e for e in rec.snapshot()
             if e["kind"] == "brownout_deescalate"]
    assert [(e["from_level"], e["level"]) for e in deesc] == [(2, 1)]
    # still in the band: level 1 is justified (target would be 0 only
    # below enter; but exit needs <= burn_exit) — holds at 1, no exit
    for _ in range(ctl.hold_ticks + 2):
        clock.advance(0.2)
        assert ctl.tick().brownout_level == 1
    assert not [e for e in rec.snapshot()
                if e["kind"] == "brownout_exit"]
    # genuinely calm: full exit after the hold window
    burn_box["v"] = 0.1
    for _ in range(ctl.hold_ticks):
        clock.advance(0.2)
        ctl.tick()
    assert ctl.brownout_level == 0
    assert len([e for e in rec.snapshot()
                if e["kind"] == "brownout_exit"]) == 1


def test_controller_health_check_reads_brownout():
    clock = FakeClock()
    ctl, tel, _ = make_controller(clock)
    check = admission_controller_check(lambda: ctl)
    assert check().status is HealthStatus.UP
    _pressurize(tel, clock)
    clock.advance(0.2)
    ctl.tick()
    res = check()
    assert res.status is HealthStatus.DEGRADED
    assert "brownout" in res.detail
    assert admission_controller_check(lambda: None)().status \
        is HealthStatus.UP


def test_snapshot_shape_for_admin_endpoint():
    clock = FakeClock()
    ctl, _, _ = make_controller(clock)
    ctl.tick()
    snap = ctl.snapshot()
    assert {"plan", "inputs", "brownout", "config", "ticks"} \
        <= set(snap)
    assert snap["plan"]["batch_size"] >= 8
    assert snap["brownout"]["level"] == 0
    assert set(snap["config"]["class_deadlines_ms"]) \
        == {c.label for c in VerifyClass}


def test_latency_for_lanes_is_conservative():
    """The controller sizes batches against the WORST matching shape
    estimate (across kmax variants and paths)."""
    clock = FakeClock()
    reg = MetricsRegistry()
    model = capacity_mod.ShapeLatencyModel(registry=reg)
    for _ in range(4):
        model.observe("64x1", "vpu", 0.020)
        model.observe("64x3", "vpu", 0.055)    # multi-key rows: slower
        model.observe("8x1", "vpu", 0.004)
    assert model.latency_for_lanes(64) == pytest.approx(0.055,
                                                        abs=0.005)
    assert model.latency_for_lanes(8) == pytest.approx(0.004,
                                                       abs=0.002)
    assert model.latency_for_lanes(128) is None


# --------------------------------------------------------------------------
# Closed-loop acceptance: 10x sustained offered load (ISSUE 7)
# --------------------------------------------------------------------------

def test_closed_loop_10x_holds_slo_and_sheds_by_class():
    """THE acceptance property: at 10x sustained offered load the
    control plane holds the 100 ms attestation-verify p50 by shedding
    OPTIMISTIC first and GOSSIP second, never BLOCK_IMPORT, with ONE
    edge-triggered brownout episode (no flapping) — all in virtual
    time on the real service + controller code paths."""
    out = asyncio.run(run_overload_sim(
        offered_x=10.0, duration_s=3.0,
        capacity_sigs_per_sec=1000.0, clock=FakeClock()))
    # the SLO holds for what was ADMITTED
    assert out["completed"] > 300
    assert out["p50_ms"] <= 100.0, out
    # shed ordering: OPTIMISTIC >= GOSSIP, protected classes never
    sheds = out["sheds"]
    assert sheds["block_import"] == 0
    assert sheds["vip"] == 0
    assert sheds["sync_critical"] == 0
    assert sheds["optimistic"] >= sheds["gossip"] > 0
    # brownout: one edge in, at most one out, no flap
    assert out["brownout"]["enters"] == 1
    assert out["brownout"]["exits"] == 1
    assert out["brownout"]["flapped"] is False
    assert out["brownout"]["final_level"] == 0   # recovered after load
    # the protected core kept express latency
    assert out["p50_ms_by_class"]["vip"] <= 50.0
    assert out["p50_ms_by_class"]["block_import"] <= 100.0
    # shed events in the flight recorder carry class labels (checked
    # via counts here; the event shape is covered in the service tests)
    assert out["shed_total"] == sum(sheds.values())


def test_closed_loop_light_load_never_browns_out():
    """At 0.3x offered load the controller must stay quiet: no
    brownout episode, nothing shed, p50 well inside the SLO."""
    out = asyncio.run(run_overload_sim(
        offered_x=0.3, duration_s=2.0,
        capacity_sigs_per_sec=1000.0, clock=FakeClock()))
    assert out["brownout"]["enters"] == 0
    assert out["shed_total"] == 0
    assert out["p50_ms"] <= 100.0
    assert out["completed"] == out["submitted"]


def test_default_mix_is_shed_ordered_and_protected_fits():
    """The bench mix's invariants: optimistic share >= gossip share
    (so admission sheds preserve the ordering) and the protected core
    at 10x stays under nominal capacity."""
    protected = sum(share for cls, share in DEFAULT_MIX.items()
                    if cls not in SHEDDABLE)
    assert protected * 10 < 1.0
    assert DEFAULT_MIX[VerifyClass.OPTIMISTIC] \
        >= DEFAULT_MIX[VerifyClass.GOSSIP]
    assert abs(sum(DEFAULT_MIX.values()) - 1.0) < 1e-9


# --------------------------------------------------------------------------
# Admin endpoint
# --------------------------------------------------------------------------

def test_admin_admission_endpoint_serves_controller_state():
    """GET /teku/v1/admin/admission serves the controller's plan,
    brownout state, inputs, and knob config plus the service's
    per-class queue view; a node without overload control answers
    503 so a dashboard never mistakes "off" for "healthy"."""
    import asyncio as aio

    from teku_tpu.api import BeaconRestApi
    from teku_tpu.infra.restapi import HttpError
    from teku_tpu.services.signatures import (
        AggregatingSignatureVerificationService)

    clock = FakeClock()
    ctl, telemetry, _ = make_controller(clock)

    class FakeNode:
        admission = ctl

    FakeNode.sig_service = AggregatingSignatureVerificationService(
        num_workers=1, registry=MetricsRegistry(),
        name="adm_endpoint", controller=ctl, telemetry=telemetry)
    api = BeaconRestApi(FakeNode())
    body = aio.run(api._admin_admission())["data"]
    controller = body["controller"]
    assert controller["plan"]["batch_size"] >= 8
    assert controller["brownout"]["level"] == 0
    assert controller["config"]["hold_ticks"] >= 1
    assert set(controller["config"]["class_deadlines_ms"]) == set(
        c.label for c in VerifyClass)
    queues = body["queues"]
    assert set(queues["classes"]) == {c.label for c in VerifyClass}
    # overload control off: explicit 503, not an empty 200
    with pytest.raises(HttpError) as err:
        aio.run(BeaconRestApi(None)._admin_admission())
    assert err.value.status == 503
