"""Curve-group kernels vs the pure-Python oracle."""

import random

import jax
import numpy as np

from teku_tpu.crypto.bls import curve as C
from teku_tpu.crypto.bls import fields as F
from teku_tpu.crypto.bls.constants import P, R
from teku_tpu.ops import limbs as fp
from teku_tpu.ops import points as PT
from teku_tpu.ops import towers as T

rng = random.Random(0x61)


def rand_g1():
    return C.point_mul(C.FQ_OPS, rng.randrange(1, R), C.G1_GENERATOR)


def rand_g2():
    return C.point_mul(C.FQ2_OPS, rng.randrange(1, R), C.G2_GENERATOR)


def stack_g1(points):
    """Oracle G1 Jacobian points -> batched device point."""
    cols = []
    for i in range(3):
        cols.append(np.stack([fp.int_to_mont(p[i]) for p in points]))
    return tuple(cols)


def stack_g2(points):
    out = []
    for i in range(3):
        out.append((np.stack([fp.int_to_mont(p[i][0]) for p in points]),
                    np.stack([fp.int_to_mont(p[i][1]) for p in points])))
    return tuple(out)


def check_eq_g1(dev, i, oracle_pt):
    got = PT.g1_from_device(dev, (i,))
    assert C.point_eq(C.FQ_OPS, got, oracle_pt)


def check_eq_g2(dev, i, oracle_pt):
    got = PT.g2_from_device(dev, (i,))
    assert C.point_eq(C.FQ2_OPS, got, oracle_pt)


def non_subgroup_g1():
    """On-curve G1 point outside the r-subgroup (h1-torsion component)."""
    while True:
        x = rng.randrange(P)
        y = F.fq_sqrt((x * x % P * x + 4) % P)
        if y is None:
            continue
        p = (x, y, 1)
        if not C.g1_in_subgroup(p):
            return p


def non_subgroup_g2():
    while True:
        x = (rng.randrange(P), rng.randrange(P))
        rhs = F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), (4, 4))
        y = F.fq2_sqrt(rhs)
        if y is None:
            continue
        p = (x, y, F.FQ2_ONE)
        if not C.g2_in_subgroup(p):
            return p


def test_g1_add_double_edge_cases():
    a, b = rand_g1(), rand_g1()
    inf = C.infinity(C.FQ_OPS)
    pairs = [(a, b), (a, a), (a, C.point_neg(C.FQ_OPS, a)), (inf, b),
             (a, inf), (inf, inf)]
    pa = stack_g1([p for p, _ in pairs])
    pb = stack_g1([q for _, q in pairs])
    out = jax.jit(lambda x, y: PT.point_add(PT.G1_KIT, x, y))(pa, pb)
    for i, (p, q) in enumerate(pairs):
        check_eq_g1(out, i, C.point_add(C.FQ_OPS, p, q))
    dbl = jax.jit(lambda x: PT.point_double(PT.G1_KIT, x))(pa)
    for i, (p, _) in enumerate(pairs):
        check_eq_g1(dbl, i, C.point_double(C.FQ_OPS, p))


def test_g2_add_double_edge_cases():
    a, b = rand_g2(), rand_g2()
    inf = C.infinity(C.FQ2_OPS)
    pairs = [(a, b), (a, a), (a, C.point_neg(C.FQ2_OPS, a)), (inf, b),
             (a, inf)]
    pa = stack_g2([p for p, _ in pairs])
    pb = stack_g2([q for _, q in pairs])
    out = jax.jit(lambda x, y: PT.point_add(PT.G2_KIT, x, y))(pa, pb)
    for i, (p, q) in enumerate(pairs):
        check_eq_g2(out, i, C.point_add(C.FQ2_OPS, p, q))


def test_scalar_mul_bits_g1():
    pts = [rand_g1() for _ in range(3)] + [C.infinity(C.FQ_OPS)]
    scalars = [rng.getrandbits(64) for _ in range(3)] + [12345]
    dev = stack_g1(pts)
    bits = PT.scalar_from_uint64(np.array(scalars, dtype=np.uint64))
    out = jax.jit(lambda b, p: PT.scalar_mul_bits(PT.G1_KIT, b, p))(bits, dev)
    for i, (p, s) in enumerate(zip(pts, scalars)):
        check_eq_g1(out, i, C.point_mul(C.FQ_OPS, s, p))


def test_scalar_mul_bits_g2():
    pts = [rand_g2() for _ in range(2)]
    scalars = [rng.getrandbits(64) for _ in range(2)]
    dev = stack_g2(pts)
    bits = PT.scalar_from_uint64(np.array(scalars, dtype=np.uint64))
    out = jax.jit(lambda b, p: PT.scalar_mul_bits(PT.G2_KIT, b, p))(bits, dev)
    for i, (p, s) in enumerate(zip(pts, scalars)):
        check_eq_g2(out, i, C.point_mul(C.FQ2_OPS, s, p))


def test_scalar_mul_static():
    p = rand_g1()
    dev = stack_g1([p])
    for e in (0, 1, 7, R - 1, R):
        out = jax.jit(
            lambda x, e=e: PT.scalar_mul_static(PT.G1_KIT, e, x))(dev)
        check_eq_g1(out, 0, C.point_mul(C.FQ_OPS, e, p))


def test_psi_is_frobenius_eigenvalue():
    # On G2, psi acts as [p]; p ≡ z (mod r) so psi(Q) == [z]Q there.
    q = rand_g2()
    dev = stack_g2([q])
    psi = jax.jit(PT.g2_psi)(dev)
    expect = C.point_mul(C.FQ2_OPS, P % R, q)
    check_eq_g2(psi, 0, expect)


def test_subgroup_checks():
    good1 = [rand_g1() for _ in range(2)] + [C.infinity(C.FQ_OPS)]
    bad1 = [non_subgroup_g1()]
    dev = stack_g1(good1 + bad1)
    got = list(np.asarray(jax.jit(PT.g1_in_subgroup)(dev)))
    assert got == [True, True, True, False]

    good2 = [rand_g2() for _ in range(2)]
    bad2 = [non_subgroup_g2()]
    dev2 = stack_g2(good2 + bad2)
    got2 = list(np.asarray(jax.jit(PT.g2_in_subgroup)(dev2)))
    assert got2 == [True, True, False]


def test_g1_decompress_device():
    pts = [rand_g1() for _ in range(4)]
    comp = [C.g1_compress(p) for p in pts]
    xs, flags = [], []
    for c in comp:
        xs.append(fp.int_to_limbs(int.from_bytes(
            bytes([c[0] & 0x1F]) + c[1:], "big")))
        flags.append(bool(c[0] & 0x20))
    ok, point = jax.jit(PT.g1_recover_y)(
        np.stack(xs), np.array(flags))
    assert all(np.asarray(ok))
    for i, p in enumerate(pts):
        check_eq_g1(point, i, p)
    # invalid x (not on curve): valid=False
    bad_x = 5
    while F.fq_sqrt((bad_x ** 3 + 4) % P) is not None:
        bad_x += 1
    ok2, _ = jax.jit(PT.g1_recover_y)(
        np.stack([fp.int_to_limbs(bad_x)]), np.array([False]))
    assert not np.asarray(ok2)[0]


def test_g2_decompress_device():
    pts = [rand_g2() for _ in range(3)]
    comp = [C.g2_compress(p) for p in pts]
    x0s, x1s, flags = [], [], []
    for c in comp:
        x1s.append(fp.int_to_limbs(int.from_bytes(
            bytes([c[0] & 0x1F]) + c[1:48], "big")))
        x0s.append(fp.int_to_limbs(int.from_bytes(c[48:96], "big")))
        flags.append(bool(c[0] & 0x20))
    ok, point = jax.jit(PT.g2_recover_y)(
        (np.stack(x0s), np.stack(x1s)), np.array(flags))
    assert all(np.asarray(ok))
    for i, p in enumerate(pts):
        check_eq_g2(point, i, p)


def test_on_curve():
    pts = [rand_g1() for _ in range(2)] + [C.infinity(C.FQ_OPS)]
    dev = stack_g1(pts)
    assert all(np.asarray(jax.jit(
        lambda p: PT.is_on_curve(PT.G1_KIT, p))(dev)))
    # corrupt one Y
    bad_y = np.array(dev[1], copy=True)
    bad_y[0] = fp.int_to_mont(12345)
    bad = (dev[0], bad_y, dev[2])
    got = np.asarray(jax.jit(lambda p: PT.is_on_curve(PT.G1_KIT, p))(bad))
    assert not got[0] and got[1] and got[2]


def test_leaf_shape_walks_tower_tuples():
    g1 = stack_g1([rand_g1(), rand_g1()])
    g2 = stack_g2([rand_g2(), rand_g2()])
    assert PT.leaf_shape(g1[0]) == (2, fp.L)
    assert PT.leaf_shape(g2[0]) == (2, fp.L)     # (c0, c1) tuple
    assert PT.leaf_shape(((g2[0],),)) == (2, fp.L)   # deeper nesting
    # infinity_like's broadcast helper rides the same leaf shape
    inf = PT.infinity_like(PT.G2_KIT, g2[0])
    assert PT.leaf_shape(inf[0]) == (2, fp.L)


def test_scalar_mul_bits_irregular_width_pads_not_demotes():
    """33-bit scalars (the GLV half-scalar worst case) must stay on
    the windowed fast path via MSB zero-padding — the old behavior
    silently demoted window -> 1 whenever nbits % window != 0."""
    # op-count pin: the padded window-4 plan beats the bit-serial
    # ladder the demotion used to fall back to
    c4 = PT.ladder_op_counts(33, 4)
    c1 = PT.ladder_op_counts(33, 1)
    assert PT.ladder_plan(33, 4) == (3, 9)
    assert c4["doubles"] == c1["doubles"] == 32
    assert c4["adds"] < c1["adds"]          # 8 gathered vs 32 serial
    assert c4["total"] < c1["total"]
    # and the padded walk is correct on BOTH groups
    scalars = [rng.getrandbits(32) | (1 << 32) for _ in range(2)]
    bits = np.zeros((2, 33), dtype=np.int64)
    for i, s in enumerate(scalars):
        for j in range(33):
            bits[i, 32 - j] = (s >> j) & 1
    p1 = [rand_g1(), rand_g1()]
    out1 = jax.jit(
        lambda b, p: PT.scalar_mul_bits(PT.G1_KIT, b, p))(
            bits, stack_g1(p1))
    p2 = [rand_g2(), rand_g2()]
    out2 = jax.jit(
        lambda b, p: PT.scalar_mul_bits(PT.G2_KIT, b, p))(
            bits, stack_g2(p2))
    for i, s in enumerate(scalars):
        check_eq_g1(out1, i, C.point_mul(C.FQ_OPS, s, p1[i]))
        check_eq_g2(out2, i, C.point_mul(C.FQ2_OPS, s, p2[i]))


def test_scalar_mul_static_dense_exponent_g1_and_g2():
    """The >16-runs dense-exponent fallback (one masked-add scan
    instead of an unrolled add per one-bit — the unrolled form once
    segfaulted CPU-XLA) had no dedicated test and never ran on G2,
    whose coordinate tuples the old hand-rolled leaf unwrapping was
    written for.  34 bits / 17 one-runs also exercises the irregular
    width (34 % 4 != 0) through the new padding path."""
    e = int("10" * 17, 2)                   # 17 runs > 16: dense path
    p = rand_g1()
    out = jax.jit(lambda x: PT.scalar_mul_static(PT.G1_KIT, e, x))(
        stack_g1([p]))
    check_eq_g1(out, 0, C.point_mul(C.FQ_OPS, e, p))
    q = rand_g2()
    out2 = jax.jit(lambda x: PT.scalar_mul_static(PT.G2_KIT, e, x))(
        stack_g2([q]))
    check_eq_g2(out2, 0, C.point_mul(C.FQ2_OPS, e, q))
