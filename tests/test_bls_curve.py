"""Tests for G1/G2 group law, subgroup checks and ZCash/ETH2 serialization."""

import random

import pytest

from teku_tpu.crypto.bls import curve as C
from teku_tpu.crypto.bls.constants import P, R

rng = random.Random(99)


def rand_g1():
    return C.point_mul(C.FQ_OPS, rng.randrange(1, R), C.G1_GENERATOR)


def rand_g2():
    return C.point_mul(C.FQ2_OPS, rng.randrange(1, R), C.G2_GENERATOR)


class TestGroupLaw:
    @pytest.mark.parametrize("ops,gen", [
        (C.FQ_OPS, C.G1_GENERATOR), (C.FQ2_OPS, C.G2_GENERATOR)])
    def test_generator_on_curve_and_order(self, ops, gen):
        assert C.is_on_curve(ops, gen)
        assert C.is_infinity(ops, C.point_mul(ops, R, gen))
        assert not C.is_infinity(ops, C.point_mul(ops, R - 1, gen))

    @pytest.mark.parametrize("ops,gen", [
        (C.FQ_OPS, C.G1_GENERATOR), (C.FQ2_OPS, C.G2_GENERATOR)])
    def test_add_commutes_and_associates(self, ops, gen):
        a = C.point_mul(ops, 7, gen)
        b = C.point_mul(ops, 11, gen)
        c = C.point_mul(ops, 13, gen)
        assert C.point_eq(ops, C.point_add(ops, a, b), C.point_add(ops, b, a))
        assert C.point_eq(ops,
                          C.point_add(ops, C.point_add(ops, a, b), c),
                          C.point_add(ops, a, C.point_add(ops, b, c)))

    @pytest.mark.parametrize("ops,gen", [
        (C.FQ_OPS, C.G1_GENERATOR), (C.FQ2_OPS, C.G2_GENERATOR)])
    def test_scalar_mul_matches_repeated_add(self, ops, gen):
        acc = C.infinity(ops)
        for k in range(1, 8):
            acc = C.point_add(ops, acc, gen)
            assert C.point_eq(ops, acc, C.point_mul(ops, k, gen))

    @pytest.mark.parametrize("ops,gen", [
        (C.FQ_OPS, C.G1_GENERATOR), (C.FQ2_OPS, C.G2_GENERATOR)])
    def test_double_equals_add_self(self, ops, gen):
        p = C.point_mul(ops, 12345, gen)
        assert C.point_eq(ops, C.point_double(ops, p), C.point_add(ops, p, p))

    @pytest.mark.parametrize("ops,gen", [
        (C.FQ_OPS, C.G1_GENERATOR), (C.FQ2_OPS, C.G2_GENERATOR)])
    def test_neg_cancels(self, ops, gen):
        p = C.point_mul(ops, 777, gen)
        assert C.is_infinity(ops, C.point_add(ops, p, C.point_neg(ops, p)))

    @pytest.mark.parametrize("ops,gen", [
        (C.FQ_OPS, C.G1_GENERATOR), (C.FQ2_OPS, C.G2_GENERATOR)])
    def test_infinity_is_identity(self, ops, gen):
        p = C.point_mul(ops, 31337, gen)
        inf = C.infinity(ops)
        assert C.point_eq(ops, C.point_add(ops, p, inf), p)
        assert C.point_eq(ops, C.point_add(ops, inf, p), p)

    def test_mul_negative_scalar(self):
        p = rand_g1()
        assert C.point_eq(C.FQ_OPS, C.point_mul(C.FQ_OPS, -5, p),
                          C.point_neg(C.FQ_OPS, C.point_mul(C.FQ_OPS, 5, p)))


class TestSerialization:
    def test_g1_roundtrip(self):
        for _ in range(8):
            p = rand_g1()
            data = C.g1_compress(p)
            assert len(data) == 48
            assert data[0] & 0x80
            assert C.point_eq(C.FQ_OPS, C.g1_decompress(data), p)

    def test_g2_roundtrip(self):
        for _ in range(8):
            p = rand_g2()
            data = C.g2_compress(p)
            assert len(data) == 96
            assert C.point_eq(C.FQ2_OPS, C.g2_decompress(data), p)

    def test_infinity_roundtrip(self):
        inf1 = bytes([0xC0] + [0] * 47)
        assert C.g1_compress(C.infinity(C.FQ_OPS)) == inf1
        assert C.is_infinity(C.FQ_OPS, C.g1_decompress(inf1))
        inf2 = bytes([0xC0] + [0] * 95)
        assert C.g2_compress(C.infinity(C.FQ2_OPS)) == inf2
        assert C.is_infinity(C.FQ2_OPS, C.g2_decompress(inf2))

    def test_known_generator_bytes(self):
        # The canonical compressed G1 generator starts 0x97f1d3... (flags|x)
        data = C.g1_compress(C.G1_GENERATOR)
        assert data.hex().startswith("97f1d3a73197d794")

    def test_rejects_bad_lengths(self):
        with pytest.raises(ValueError):
            C.g1_decompress(b"\x00" * 47)
        with pytest.raises(ValueError):
            C.g2_decompress(b"\x00" * 95)

    def test_rejects_uncompressed_flag(self):
        with pytest.raises(ValueError):
            C.g1_decompress(b"\x00" * 48)

    def test_rejects_x_out_of_range(self):
        bad = bytearray((P).to_bytes(48, "big"))
        bad[0] |= 0x80
        with pytest.raises(ValueError):
            C.g1_decompress(bytes(bad))

    def test_rejects_not_on_curve(self):
        # x with no square rhs: search deterministically
        x = 5
        from teku_tpu.crypto.bls import fields as F
        while F.fq_sqrt((x * x % P * x + 4) % P) is not None:
            x += 1
        bad = bytearray(x.to_bytes(48, "big"))
        bad[0] |= 0x80
        with pytest.raises(ValueError):
            C.g1_decompress(bytes(bad))

    def test_rejects_non_subgroup_point(self):
        # find a curve point with order != r (cofactor group): take a point
        # on curve not multiple of r by hashing x until on-curve then clearing
        from teku_tpu.crypto.bls import fields as F
        x = 1
        while True:
            rhs = (x * x % P * x + 4) % P
            y = F.fq_sqrt(rhs)
            if y is not None:
                p = C.from_affine(C.FQ_OPS, x, y)
                if not C.is_infinity(C.FQ_OPS, C.point_mul(C.FQ_OPS, R, p)):
                    break
            x += 1
        data = C.g1_compress(p)
        with pytest.raises(ValueError):
            C.g1_decompress(data)

    def test_malformed_infinity_rejected(self):
        bad = bytearray([0xC0] + [0] * 47)
        bad[20] = 1
        with pytest.raises(ValueError):
            C.g1_decompress(bytes(bad))
