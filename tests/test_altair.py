"""Altair: fork upgrade at the boundary, flag-based finality across the
fork, real sync-committee signatures, reward accounting."""

import dataclasses

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls.pure_impl import G2_INFINITY
from teku_tpu.spec import config as C
from teku_tpu.spec import helpers as H
from teku_tpu.spec.altair import helpers as AH
from teku_tpu.spec.altair.datastructures import get_altair_schemas
from teku_tpu.spec.builder import (build_unsigned_block, make_local_signer,
                                   produce_attestations, produce_block)
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.spec.milestones import SpecMilestone
from teku_tpu.spec.transition import (process_slots, state_transition,
                                      StateTransitionError)

# altair activates at epoch 1 on an otherwise-minimal config
CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=1)
N_VALIDATORS = 32


@pytest.fixture(scope="module")
def chain():
    """A chain driven from phase0 genesis THROUGH the altair fork with
    full verification, collecting states along the way."""
    state, sks = interop_genesis(CFG, N_VALIDATORS)
    signer = make_local_signer(dict(enumerate(sks)))
    atts = []
    states = {0: state}
    cur = state
    for slot in range(1, 4 * CFG.SLOTS_PER_EPOCH + 1):
        signed, post = produce_block(CFG, cur, slot, signer,
                                     attestations=atts)
        verified = state_transition(CFG, cur, signed,
                                    validate_result=True)
        assert verified.htr() == post.htr(), f"divergence at slot {slot}"
        atts = produce_attestations(CFG, post, slot,
                                    signed.message.htr(), signer)
        states[slot] = post
        cur = post
    return states, sks


def test_upgrade_happens_at_boundary(chain):
    states, _ = chain
    S = get_altair_schemas(CFG)
    pre_fork = states[CFG.SLOTS_PER_EPOCH - 1]
    post_fork = states[CFG.SLOTS_PER_EPOCH]
    assert not isinstance(pre_fork, S.BeaconState)
    assert isinstance(post_fork, S.BeaconState)
    assert post_fork.fork.current_version == CFG.ALTAIR_FORK_VERSION
    assert post_fork.fork.previous_version == CFG.GENESIS_FORK_VERSION
    assert post_fork.fork.epoch == 1
    # sync committees bootstrapped with valid aggregate keys
    assert bls.public_key_is_valid(
        post_fork.current_sync_committee.aggregate_pubkey)
    assert len(post_fork.current_sync_committee.pubkeys) == \
        CFG.SYNC_COMMITTEE_SIZE


def test_chain_finalizes_across_fork(chain):
    states, _ = chain
    tip = states[4 * CFG.SLOTS_PER_EPOCH]
    assert tip.current_justified_checkpoint.epoch >= 3
    assert tip.finalized_checkpoint.epoch >= 2
    # participation flags are being set for current epoch attesters
    assert any(p != 0 for p in tip.previous_epoch_participation)


def test_translated_participation_preserves_justification(chain):
    """The fork-boundary state translated phase0 pending attestations
    into flags — justification earned before the fork must not reset."""
    states, _ = chain
    boundary = states[CFG.SLOTS_PER_EPOCH]
    assert sum(1 for p in boundary.previous_epoch_participation
               if p != 0) > N_VALIDATORS // 2


def test_real_sync_aggregate_verifies_and_rewards(chain):
    states, sks = chain
    S = get_altair_schemas(CFG)
    slot = 4 * CFG.SLOTS_PER_EPOCH
    state = states[slot]
    pre = process_slots(CFG, state, slot + 1)
    # every committee member signs the previous block root
    root = AH.sync_committee_signing_root(CFG, pre, slot + 1)
    pk_to_sk = {bls.secret_to_public_key(sk): sk for sk in sks}
    sigs = [bls.sign(pk_to_sk[pk], root)
            for pk in pre.current_sync_committee.pubkeys]
    agg = S.SyncAggregate(
        sync_committee_bits=tuple(True for _ in sigs),
        sync_committee_signature=bls.aggregate_signatures(sigs))
    signer = make_local_signer(dict(enumerate(sks)))
    signed, post = produce_block(CFG, state, slot + 1, signer,
                                 sync_aggregate=agg)
    verified = state_transition(CFG, state, signed)
    assert verified.htr() == post.htr()
    # participants earned: total balance increased vs the empty-agg path
    _, post_empty = produce_block(CFG, state, slot + 1, signer)
    assert sum(post.balances) > sum(post_empty.balances)


def test_bad_sync_signature_rejected(chain):
    states, sks = chain
    S = get_altair_schemas(CFG)
    slot = 4 * CFG.SLOTS_PER_EPOCH
    state = states[slot]
    signer = make_local_signer(dict(enumerate(sks)))
    bad_agg = S.SyncAggregate(
        sync_committee_bits=tuple(
            i == 0 for i in range(CFG.SYNC_COMMITTEE_SIZE)),
        sync_committee_signature=bls.sign(sks[0], b"not the block root"))
    # production trusts its own inputs; the IMPORT path must reject
    signed, _ = produce_block(CFG, state, slot + 1, signer,
                              sync_aggregate=bad_agg)
    with pytest.raises(StateTransitionError):
        state_transition(CFG, state, signed, validate_result=True)


def test_empty_sync_aggregate_requires_infinity_sig(chain):
    states, sks = chain
    S = get_altair_schemas(CFG)
    slot = 4 * CFG.SLOTS_PER_EPOCH
    state = states[slot]
    signer = make_local_signer(dict(enumerate(sks)))
    # default production uses the infinity signature: valid
    signed, _ = produce_block(CFG, state, slot + 1, signer)
    assert (signed.message.body.sync_aggregate.sync_committee_signature
            == G2_INFINITY)


@pytest.mark.slow
def test_altair_devnet_with_live_sync_committee():
    """Full node loop across the fork: after altair activates, sync
    committee members sign each head over gossip and proposers include
    REAL (non-empty) sync aggregates that verify on import."""
    import asyncio
    from teku_tpu.node import Devnet
    from teku_tpu.spec import Spec

    async def run():
        net = Devnet(n_nodes=2, n_validators=16, spec=Spec(CFG))
        await net.start()
        try:
            await net.run_until_slot(3 * CFG.SLOTS_PER_EPOCH)
            assert net.heads_converged()
            head_state = net.nodes[0].chain.head_state()
            assert hasattr(head_state, "current_sync_committee")
            # at least one post-fork block carried live participation
            lively = 0
            for root, blk in net.nodes[0].store.blocks.items():
                body = getattr(blk, "body", None)
                agg = getattr(body, "sync_aggregate", None)
                if agg is not None and any(agg.sync_committee_bits):
                    lively += 1
            assert lively >= CFG.SLOTS_PER_EPOCH, (
                f"only {lively} blocks had live sync aggregates")
        finally:
            await net.stop()
    asyncio.run(run())


def test_milestone_routing_with_altair():
    from teku_tpu.spec.milestones import build_fork_schedule
    sched = build_fork_schedule(CFG)
    assert sched.milestone_at_epoch(0) is SpecMilestone.PHASE0
    assert sched.milestone_at_epoch(1) is SpecMilestone.ALTAIR
    assert sched.milestone_at_epoch(99) is SpecMilestone.ALTAIR
    # unscheduled altair stays phase0 forever
    sched0 = build_fork_schedule(C.MINIMAL)
    assert sched0.milestone_at_epoch(10 ** 6) is SpecMilestone.PHASE0