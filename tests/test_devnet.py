"""End-to-end devnet: multiple in-process nodes over loopback gossip,
every signature through each node's batching verification service,
heads converge and the chain finalizes.

The TPU build's equivalent of the reference's gossip/finalization
acceptance tests (reference: acceptance-tests/.../AttestationGossip
AcceptanceTest.java, SyncAcceptanceTest.java — there containerized,
here in-process per SURVEY §7 stage 5).
"""

import asyncio
import os
import subprocess
import sys

import pytest

from teku_tpu.node import Devnet
from teku_tpu.node.gossip import ValidationResult


def test_devnet_hard_exit_guard_scopes_correctly():
    """The clean-shutdown guard (cli._hard_exit_if_virtual_devices)
    fires ONLY in a standalone CLI process whose jax was imported
    under a forced virtual device count.  Embedders survive: pytest
    itself is the proof — these calls returning (instead of
    os._exiting the suite) IS the embedding contract, since this
    process has jax loaded under the conftest's forced 8-device
    flag.  (The positive path necessarily os._exits, so it is proven
    in the slow-tier subprocess test below.)"""
    from teku_tpu import cli

    # pytest is loaded: auto mode must refuse even with the flag set
    cli._hard_exit_if_virtual_devices(0)       # returns, no exit
    # explicit opt-out refuses everywhere
    prev = os.environ.get("TEKU_TPU_DEVNET_HARD_EXIT")
    try:
        os.environ["TEKU_TPU_DEVNET_HARD_EXIT"] = "0"
        cli._hard_exit_if_virtual_devices(0)   # returns, no exit
    finally:
        if prev is None:
            os.environ.pop("TEKU_TPU_DEVNET_HARD_EXIT", None)
        else:
            os.environ["TEKU_TPU_DEVNET_HARD_EXIT"] = prev
    # and without the forced flag there is nothing to guard against
    prev = os.environ.get("XLA_FLAGS")
    try:
        os.environ["XLA_FLAGS"] = "--xla_cpu_foo"
        os.environ["TEKU_TPU_DEVNET_HARD_EXIT"] = "1"
        cli._hard_exit_if_virtual_devices(0)   # returns, no exit
    finally:
        os.environ.pop("TEKU_TPU_DEVNET_HARD_EXIT", None)
        if prev is None:
            os.environ.pop("XLA_FLAGS", None)
        else:
            os.environ["XLA_FLAGS"] = prev


@pytest.mark.slow
def test_devnet_cli_clean_shutdown_with_forced_virtual_devices():
    """Repro + guard for the pre-existing interpreter-shutdown
    segfault/abort (noted in PR 10): ``devnet --mesh 2`` forces
    virtual host devices; once jax is imported under that flag, XLA's
    CPU client teardown can race Python finalization AFTER the devnet
    verdict printed — rc 134/139 on a clean run.  The CLI now
    hard-exits after a clean stop (flush + faulthandler disarm +
    os._exit), so the child must exit rc 0 with the verdict on stdout
    and no fatal-teardown noise on stderr.  jax is imported
    explicitly: the pure-BLS devnet itself never would, and the guard
    keys on it."""
    code = (
        "import jax\n"                      # under the forced flag
        "assert len(jax.devices()) >= 2\n"
        "import teku_tpu.cli as cli\n"
        "raise SystemExit(cli.main(["
        "'devnet', '--nodes', '1', '--validators', '4', "
        "'--epochs', '1', '--mesh', '2', '--bls-impl', 'pure']))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=600, env=env)
    assert proc.returncode in (0, 1), (proc.returncode,
                                       proc.stderr[-2000:])
    assert "devnet" in proc.stdout          # the verdict line came out
    for marker in ("Segmentation fault", "Fatal Python error",
                   "Aborted", "core dumped"):
        assert marker not in proc.stderr, proc.stderr[-2000:]


@pytest.mark.slow
def test_devnet_two_nodes_finalize():
    async def run():
        net = Devnet(n_nodes=2, n_validators=32)
        await net.start()
        try:
            epochs = 4
            await net.run_until_slot(
                epochs * net.spec.config.SLOTS_PER_EPOCH)
            assert net.heads_converged(), "nodes diverged"
            assert net.min_justified_epoch() >= epochs - 2
            assert net.min_finalized_epoch() >= epochs - 3
            assert net.min_finalized_epoch() >= 1
            # every node really verified through its batcher
            for node in net.nodes:
                batches = node.sig_service._m_batches.value
                assert batches > 0, f"{node.name} never batched"
        finally:
            await net.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_unverified_save_for_future_attestation_never_pools():
    """A garbage-signature attestation for an unknown block must not
    poison the block-production pool (its signature was never checked —
    gossip says SAVE_FOR_FUTURE before the batch verifier runs)."""
    async def run():
        net = Devnet(n_nodes=1, n_validators=16)
        await net.start()
        try:
            await net.run_until_slot(2)
            node = net.nodes[0]
            S = net.spec.schemas
            from teku_tpu.spec.datastructures import (AttestationData,
                                                      Checkpoint)
            committee = net.spec.get_beacon_committee(
                node.chain.head_state(), 2, 0)
            evil = S.Attestation(
                aggregation_bits=tuple(i == 0 for i in
                                       range(len(committee))),
                data=AttestationData(
                    slot=2, index=0,
                    beacon_block_root=b"\x66" * 32,   # unknown block
                    source=Checkpoint(epoch=0, root=bytes(32)),
                    target=Checkpoint(epoch=0, root=b"\x67" * 32)),
                signature=b"\x99" * 96)
            handler = node.gossip._handlers["beacon_attestation_0"]
            res = await handler.handle_message(S.Attestation.serialize(evil))
            assert res is ValidationResult.SAVE_FOR_FUTURE
            assert node.pool.get_aggregate(evil.data) is None, (
                "unverified attestation reached the production pool")
            # and after retries exhaust, it still never pools
            for slot in (3, 4, 5, 6):
                await node.on_slot(slot)
            assert node.pool.get_aggregate(evil.data) is None
        finally:
            await net.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_devnet_rejects_invalid_gossip_block():
    async def run():
        net = Devnet(n_nodes=2, n_validators=16)
        await net.start()
        try:
            await net.run_until_slot(3)
            a, b = net.nodes
            S = net.spec.schemas
            from teku_tpu.spec import helpers as HH
            # craft a structurally-correct slot-4 block (right proposer,
            # right parent) with a garbage signature: it must fail ONLY
            # at the signature check, i.e. be REJECTed and not imported
            await b.on_slot(4)
            pre = b.advanced_head_state(4)
            proposer = HH.get_beacon_proposer_index(net.spec.config, pre)
            hdr = pre.latest_block_header
            if hdr.state_root == bytes(32):
                hdr = hdr.copy_with(state_root=pre.htr())
            fake = S.SignedBeaconBlock(
                message=S.BeaconBlock(
                    slot=4, proposer_index=proposer,
                    parent_root=hdr.htr(), state_root=b"\x77" * 32,
                    body=S.BeaconBlockBody(eth1_data=pre.eth1_data)),
                signature=b"\x13" * 96)
            handler = b.gossip._handlers["beacon_block"]
            res = await handler.handle_message(
                S.SignedBeaconBlock.serialize(fake))
            assert res is ValidationResult.REJECT
            assert fake.message.htr() not in b.store.blocks
        finally:
            await net.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_devnet_deneb_at_genesis_finalizes():
    """Two nodes on a deneb-at-genesis network: capella payload chain +
    deneb schemas over gossip, chain still finalizes."""
    import dataclasses
    from teku_tpu.spec import config as C, Spec

    cfg = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0,
                              BELLATRIX_FORK_EPOCH=0,
                              CAPELLA_FORK_EPOCH=0, DENEB_FORK_EPOCH=0)

    async def run():
        net = Devnet(n_nodes=2, n_validators=32, spec=Spec(cfg))
        await net.start()
        try:
            epochs = 4
            await net.run_until_slot(
                epochs * cfg.SLOTS_PER_EPOCH)
            assert net.heads_converged(), "nodes diverged"
            assert net.min_justified_epoch() >= epochs - 2
            assert net.min_finalized_epoch() >= 1
            # the payload chain advanced on every node
            for node in net.nodes:
                hdr = node.chain.head_state() \
                    .latest_execution_payload_header
                assert hdr.block_number > 0
                assert hdr.excess_blob_gas == 0
        finally:
            await net.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_devnet_electra_at_genesis_finalizes():
    """Two nodes on an electra-at-genesis network: committee-bits
    attestations over gossip, electra aggregation, chain finalizes."""
    import dataclasses
    from teku_tpu.spec import config as C, Spec

    cfg = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0,
                              BELLATRIX_FORK_EPOCH=0,
                              CAPELLA_FORK_EPOCH=0, DENEB_FORK_EPOCH=0,
                              ELECTRA_FORK_EPOCH=0)

    async def run():
        net = Devnet(n_nodes=2, n_validators=32, spec=Spec(cfg))
        await net.start()
        try:
            epochs = 4
            await net.run_until_slot(epochs * cfg.SLOTS_PER_EPOCH)
            assert net.heads_converged(), "nodes diverged"
            assert net.min_justified_epoch() >= epochs - 2
            assert net.min_finalized_epoch() >= 1
            # blocks really carried electra attestation shapes
            for node in net.nodes:
                head = node.store.blocks[node.chain.head_root]
                atts = head.body.attestations
                assert atts, "head block carries no attestations"
                assert hasattr(atts[0], "committee_bits")
        finally:
            await net.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_devnet_crosses_electra_fork_live():
    """The electra fork activates mid-run: attestation containers
    change shape across the boundary and the chain keeps finalizing."""
    import dataclasses
    from teku_tpu.spec import config as C, Spec

    cfg = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0,
                              BELLATRIX_FORK_EPOCH=0,
                              CAPELLA_FORK_EPOCH=0, DENEB_FORK_EPOCH=0,
                              ELECTRA_FORK_EPOCH=2)

    async def run():
        net = Devnet(n_nodes=2, n_validators=32, spec=Spec(cfg))
        await net.start()
        try:
            epochs = 5
            await net.run_until_slot(epochs * cfg.SLOTS_PER_EPOCH)
            assert net.heads_converged(), "nodes diverged"
            assert net.min_justified_epoch() >= epochs - 2
            assert net.min_finalized_epoch() >= 2
            for node in net.nodes:
                state = node.chain.head_state()
                assert state.fork.current_version \
                    == cfg.ELECTRA_FORK_VERSION
                head = node.store.blocks[node.chain.head_root]
                atts = head.body.attestations
                assert atts and hasattr(atts[0], "committee_bits")
        finally:
            await net.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_devnet_deneb_blocks_carry_blobs_live():
    """Proposers attach real KZG commitments; sidecars gossip ahead of
    blocks and peers import through the availability gate."""
    import dataclasses
    from teku_tpu.crypto import kzg
    from teku_tpu.spec import config as C, Spec

    cfg = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0,
                              BELLATRIX_FORK_EPOCH=0,
                              CAPELLA_FORK_EPOCH=0, DENEB_FORK_EPOCH=0)
    setup = kzg.insecure_setup()
    blob = b"\x00" * (32 * cfg.FIELD_ELEMENTS_PER_BLOB)
    commitment = kzg.blob_to_kzg_commitment(blob, setup)
    proof = kzg.compute_blob_kzg_proof(blob, commitment, setup)

    async def run():
        net = Devnet(n_nodes=2, n_validators=32, spec=Spec(cfg))
        for node in net.nodes:
            node.blob_pool._setup = setup
            node.blob_source = (
                lambda slot: ([blob], (commitment,), [proof]))
        await net.start()
        try:
            epochs = 3
            await net.run_until_slot(epochs * cfg.SLOTS_PER_EPOCH)
            assert net.heads_converged(), "nodes diverged"
            assert net.min_justified_epoch() >= 1
            # every head-chain block carried the commitment, and BOTH
            # nodes' pools hold proof-verified sidecars for the head
            # (the non-proposer only imports after the gate passes)
            for node in net.nodes:
                head_root = node.chain.head_root
                head = node.store.blocks[head_root]
                assert tuple(head.body.blob_kzg_commitments) \
                    == (commitment,)
                assert node.blob_pool.check_availability(
                    head_root, [commitment]) == "available"
                wire = node.blob_pool.wire_sidecars_for(head_root)
                assert len(wire) == 1 and wire[0].index == 0
        finally:
            await net.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_sync_committee_contributions_flow():
    """Sync aggregation duty end to end: members' messages pool, a
    selection-proof-winning aggregator broadcasts a contribution, peers
    validate its three signatures, and proposers build SyncAggregates
    from contributions."""
    import dataclasses
    from teku_tpu.spec import config as C, Spec

    cfg = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0)

    async def run():
        net = Devnet(n_nodes=2, n_validators=32, spec=Spec(cfg))
        await net.start()
        try:
            epochs = 3
            await net.run_until_slot(epochs * cfg.SLOTS_PER_EPOCH)
            assert net.heads_converged()
            assert net.min_justified_epoch() >= 1
            # contributions reached BOTH nodes' pools (gossip +
            # validation worked), and head blocks carry non-trivial
            # sync aggregates
            for node in net.nodes:
                pool = node.sync_pool
                contrib_keys = [k for k in pool._msgs
                                if isinstance(k, tuple)
                                and k and k[0] == "contrib"]
                assert contrib_keys, "no contributions pooled"
                head = node.store.blocks[node.chain.head_root]
                agg = head.body.sync_aggregate
                assert sum(agg.sync_committee_bits) \
                    >= cfg.SYNC_COMMITTEE_SIZE // 2
        finally:
            await net.stop()
    asyncio.run(run())
