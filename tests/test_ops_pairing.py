"""Pairing kernel vs the pure-Python oracle (bilinearity + agreement)."""

import random

import jax
import numpy as np

from teku_tpu.crypto.bls import curve as C
from teku_tpu.crypto.bls import fields as F
from teku_tpu.crypto.bls import pairing as OP
from teku_tpu.crypto.bls.constants import R
from teku_tpu.ops import limbs as fp
from teku_tpu.ops import pairing as PR
from teku_tpu.ops import towers as T

rng = random.Random(0xA7E)


def aff_g1(k):
    return C.to_affine(C.FQ_OPS, C.point_mul(C.FQ_OPS, k, C.G1_GENERATOR))


def aff_g2(k):
    return C.to_affine(C.FQ2_OPS, C.point_mul(C.FQ2_OPS, k, C.G2_GENERATOR))


def stack_p(pts):
    """Affine oracle G1 points -> batched device (x, y)."""
    return (np.stack([fp.int_to_mont(p[0]) for p in pts]),
            np.stack([fp.int_to_mont(p[1]) for p in pts]))


def stack_q(pts):
    return tuple(
        (np.stack([fp.int_to_mont(p[i][0]) for p in pts]),
         np.stack([fp.int_to_mont(p[i][1]) for p in pts]))
        for i in range(2))


_miller = jax.jit(PR.miller_loop)
_finalexp = jax.jit(PR.final_exponentiation)


def test_miller_loop_matches_oracle():
    ks = [rng.randrange(1, R) for _ in range(3)]
    ls = [rng.randrange(1, R) for _ in range(3)]
    p = stack_p([aff_g1(k) for k in ks])
    q = stack_q([aff_g2(l) for l in ls])
    got = _miller(p, q)
    for i, (k, l) in enumerate(zip(ks, ls)):
        expect = OP.miller_loop(aff_g1(k), aff_g2(l))
        assert T.fq12_from_device(got, (i,)) == expect


def test_final_exponentiation_matches_oracle():
    k, l = rng.randrange(1, R), rng.randrange(1, R)
    ml = OP.miller_loop(aff_g1(k), aff_g2(l))
    dev = T.fq12_to_device(ml)
    dev = jax.tree_util.tree_map(lambda x: x[None], dev)
    got = _finalexp(dev)
    assert T.fq12_from_device(got, (0,)) == OP.final_exponentiation(ml)


def test_bilinearity_on_device():
    # e([a]P, [b]Q) == e(P, [ab]Q); check via ML(aP,bQ) * ML(P,-abQ) -> 1
    a, b = rng.randrange(1, R), rng.randrange(1, R)
    p1, q1 = aff_g1(a), aff_g2(b)
    p2 = aff_g1(1)
    q2_neg = C.to_affine(C.FQ2_OPS, C.point_neg(
        C.FQ2_OPS, C.point_mul(C.FQ2_OPS, a * b % R, C.G2_GENERATOR)))
    p = stack_p([p1, p2])
    q = stack_q([q1, q2_neg])
    ml = _miller(p, q)
    prod = PR.batch_product(ml)
    prod = jax.tree_util.tree_map(lambda x: x[None], prod)
    ok = np.asarray(jax.jit(PR.pairing_check)(prod))
    assert ok[0]
    # and a wrong pair does NOT verify
    q_bad = stack_q([q1, aff_g2(a * b % R)])
    ml2 = _miller(p, q_bad)
    prod2 = jax.tree_util.tree_map(
        lambda x: x[None], PR.batch_product(ml2))
    assert not np.asarray(jax.jit(PR.pairing_check)(prod2))[0]


def test_miller_mask_gives_one():
    p = stack_p([aff_g1(5), aff_g1(7)])
    q = stack_q([aff_g2(3), aff_g2(11)])
    mask = np.array([True, False])
    got = jax.jit(PR.miller_loop)(p, q, mask)
    assert T.fq12_from_device(got, (1,)) == F.FQ12_ONE
    assert T.fq12_from_device(got, (0,)) == OP.miller_loop(aff_g1(5), aff_g2(3))


def test_batch_product_odd():
    vals = [OP.miller_loop(aff_g1(i + 2), aff_g2(3)) for i in range(3)]
    dev = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *[T.fq12_to_device(v) for v in vals])
    got = PR.batch_product(dev)
    expect = F.FQ12_ONE
    for v in vals:
        expect = F.fq12_mul(expect, v)
    assert T.fq12_from_device(got, ()) == expect
