"""Persistent XLA compile cache round-trip + mont-path selection.

Fast-tier gates for the two boot-cost levers this repo leans on:

- the persistent compile cache must actually ROUND-TRIP: a first jit
  populates the dir (miss), and after the in-memory jit caches are
  dropped (a process/config reload in miniature) the same program is
  served from disk (hit) — otherwise every boot repays the multi-minute
  per-shape kernel compiles;
- `--mont-path mxu` on a CPU-only host must fall back to the vpu path
  with ONE warning instead of a slow (or failing) int8-matmul dispatch.
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from teku_tpu.infra import compilecache
from teku_tpu.ops import limbs as fp
from teku_tpu.ops import mxu


@pytest.fixture
def isolated_cache(tmp_path, monkeypatch):
    """Point the persistent cache at a fresh dir; restore after."""
    before = {
        "dir": jax.config.jax_compilation_cache_dir,
        "min_s": jax.config.jax_persistent_cache_min_compile_time_secs,
        "min_b": jax.config.jax_persistent_cache_min_entry_size_bytes,
    }
    monkeypatch.delenv(compilecache.ENV_DIR, raising=False)
    cache_dir = tmp_path / "xla_cache"
    yield str(cache_dir)
    jax.config.update("jax_compilation_cache_dir", before["dir"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      before["min_s"])
    jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                      before["min_b"])
    # rebind jax's cache object to the restored dir (it pins the dir
    # it first initialized with; configure() does the same on change)
    from jax._src import compilation_cache as _cc
    _cc.reset_cache()


def test_compile_cache_round_trips(isolated_cache):
    got = compilecache.configure(cache_dir=isolated_cache,
                                 min_compile_s=0)
    assert got == isolated_cache
    assert compilecache.cache_dir() == isolated_cache
    assert compilecache.ensure_instrumented()

    # the traced program must be unique to this test run, or a
    # previous process's cache dir... (it can't be: tmp_path is fresh)
    x = jnp.arange(64, dtype=jnp.int64)

    before = compilecache.stats()
    first = jax.jit(lambda v: (v * 3 + 1).sum())(x)
    moved = compilecache.delta(before)
    assert moved["misses"] >= 1, "first jit must MISS the fresh dir"
    import os
    assert os.listdir(isolated_cache), "miss must populate the dir"

    # a fresh process/config reload in miniature: drop the in-memory
    # jit caches, re-trace the same program, expect a DISK hit
    jax.clear_caches()
    before = compilecache.stats()
    second = jax.jit(lambda v: (v * 3 + 1).sum())(x)
    moved = compilecache.delta(before)
    assert moved["hits"] >= 1, "reload must be served from the dir"
    assert moved["misses"] == 0
    assert int(first) == int(second)
    assert compilecache.classify_first_dispatch(moved) == "cache_load"


def test_classify_first_dispatch_outcomes():
    assert compilecache.classify_first_dispatch(
        {"hits": 2, "misses": 0}) == "cache_load"
    assert compilecache.classify_first_dispatch(
        {"hits": 0, "misses": 3}) == "compile"
    # mixed (some programs loaded, some compiled) counts as compile
    assert compilecache.classify_first_dispatch(
        {"hits": 1, "misses": 1}) == "compile"
    # no persistent cache configured: first dispatch is a compile
    assert compilecache.classify_first_dispatch(
        {"hits": 0, "misses": 0}) == "compile"


def test_configure_off_disables(monkeypatch):
    prev_dir = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv(compilecache.ENV_DIR, "off")
    assert compilecache.configure() is None
    assert compilecache.cache_dir() is None
    # off actually turns the jax-side cache off, not just the report
    assert jax.config.jax_compilation_cache_dir is None
    # re-enable for the rest of the suite (conftest wired this dir)
    monkeypatch.delenv(compilecache.ENV_DIR)
    if prev_dir:
        assert compilecache.configure(cache_dir=prev_dir) == prev_dir


def test_mxu_on_cpu_falls_back_with_one_warn(caplog):
    """Explicit mxu on a non-TPU dispatch device: vpu serves, exactly
    one WARN, and the kernels still agree with the oracle."""
    assert jax.default_backend() != "tpu", "test assumes a CPU host"
    caplog.set_level(logging.WARNING, logger="teku_tpu.ops.mxu")
    prev = mxu.get_path()
    try:
        mxu.set_path("mxu")
        assert mxu.resolve() == "vpu"
        assert mxu.resolve() == "vpu"      # second resolve: no new WARN
        warns = [r for r in caplog.records
                 if "falling back to the vpu path" in r.getMessage()]
        assert len(warns) == 1
        # and the dispatching mont_mul serves the vpu result
        a = np.stack([np.asarray(fp.int_to_mont(v))
                      for v in (5, 7, 11)])
        out = np.asarray(fp.mont_mul(a, a))
        assert [fp.mont_to_int(out[i]) for i in range(3)] == \
            [25, 49, 121]
    finally:
        mxu.set_path(prev if prev != "auto" else None)


def test_auto_resolves_vpu_on_cpu():
    prev = mxu.get_path()
    try:
        mxu.set_path("auto")
        assert mxu.resolve() == ("mxu" if jax.default_backend() == "tpu"
                                 else "vpu")
        mxu.set_path("vpu")
        assert mxu.resolve() == "vpu"
        with pytest.raises(ValueError):
            mxu.set_path("simd")
    finally:
        mxu.set_path(prev if prev != "auto" else None)


def test_force_context_restores():
    prev = mxu.get_path()
    with mxu.force("mxu-force"):
        assert mxu.resolve() == "mxu"
    assert mxu.get_path() == prev
