"""Native layer: SHA-256 parity vs hashlib, KV engine semantics, and
C++↔Python on-disk format interop."""

import hashlib
import os
import secrets

import pytest

from teku_tpu.native import get_lib
from teku_tpu.native.hashtree import hash_pairs
from teku_tpu.native.kv import _PythonKv, KvStore

has_native = get_lib() is not None
needs_native = pytest.mark.skipif(not has_native,
                                  reason="no C++ toolchain")


# --------------------------------------------------------------------------
# SHA-256
# --------------------------------------------------------------------------

def test_hash_pairs_matches_hashlib():
    level = [secrets.token_bytes(32) for _ in range(64)]
    got = hash_pairs(level)
    want = [hashlib.sha256(level[2 * i] + level[2 * i + 1]).digest()
            for i in range(32)]
    assert got == want


@needs_native
def test_native_sha256_arbitrary_lengths():
    import ctypes
    lib = get_lib()
    for n in (0, 1, 55, 56, 63, 64, 65, 127, 128, 1000):
        data = secrets.token_bytes(n)
        out = ctypes.create_string_buffer(32)
        lib.teku_sha256(data, n, out)
        assert out.raw == hashlib.sha256(data).digest(), f"len {n}"


@needs_native
def test_merkleize_uses_native_and_agrees():
    from teku_tpu.ssz.hash import merkleize
    chunks = [secrets.token_bytes(32) for _ in range(33)]
    root = merkleize(chunks, 64)
    # recompute with pure hashlib
    level = chunks + [b"\x00" * 32] * 31
    while len(level) > 1:
        level = [hashlib.sha256(level[i] + level[i + 1]).digest()
                 for i in range(0, len(level), 2)]
    assert root == level[0]


# --------------------------------------------------------------------------
# KV store
# --------------------------------------------------------------------------

def _exercise(store_cls, path):
    with store_cls(path) as kv:
        kv.put(b"block/1", b"aaa")
        kv.put(b"block/2", b"bbb")
        kv.put(b"state/1", b"s" * 1000)
        kv.put(b"block/1", b"aaa2")        # overwrite
        kv.delete(b"block/2")
        kv.flush()
        assert kv.get(b"block/1") == b"aaa2"
        assert kv.get(b"block/2") is None
        assert len(kv) == 2
        assert kv.keys_with_prefix(b"block/") == [b"block/1"]
    # reopen: state survives
    with store_cls(path) as kv:
        assert kv.get(b"block/1") == b"aaa2"
        assert len(kv) == 2
        kv.compact()
        assert kv.get(b"state/1") == b"s" * 1000
        assert len(kv) == 2


def test_python_kv_semantics(tmp_path):
    _exercise(_PythonKv, tmp_path / "py.db")


@needs_native
def test_native_kv_semantics(tmp_path):
    from teku_tpu.native.kv import _NativeKv
    _exercise(_NativeKv, tmp_path / "native.db")


@needs_native
def test_cross_implementation_format(tmp_path):
    """A database written by C++ must open under Python and vice versa
    — byte-level format conformance."""
    from teku_tpu.native.kv import _NativeKv
    p = tmp_path / "cross.db"
    with _NativeKv(p) as kv:
        kv.put(b"k1", b"v1")
        kv.put(b"k2", secrets.token_bytes(500))
        kv.delete(b"k1")
        kv.put(b"k3", b"")
        kv.flush()
        native_view = {k: kv.get(k) for k in kv.keys_with_prefix()}
    with _PythonKv(p) as kv:
        assert {k: kv.get(k) for k in kv.keys_with_prefix()} == native_view
        kv.put(b"k4", b"from python")
    with _NativeKv(p) as kv:
        assert kv.get(b"k4") == b"from python"
        assert kv.get(b"k1") is None


def test_torn_tail_truncated(tmp_path):
    p = tmp_path / "torn.db"
    with _PythonKv(p) as kv:
        kv.put(b"good", b"value")
        kv.flush()
    # simulate a crash mid-append
    with open(p, "ab") as f:
        f.write(b"\x01\x05\x00\x00")       # truncated header
    with _PythonKv(p) as kv:
        assert kv.get(b"good") == b"value"
        assert len(kv) == 1
        kv.put(b"after", b"recovery")
    with _PythonKv(p) as kv:
        assert kv.get(b"after") == b"recovery"


@needs_native
def test_native_handles_python_torn_tail(tmp_path):
    from teku_tpu.native.kv import _NativeKv
    p = tmp_path / "torn2.db"
    with _PythonKv(p) as kv:
        kv.put(b"x", b"1")
        kv.flush()
    with open(p, "ab") as f:
        f.write(b"\x01\xff\xff")
    with _NativeKv(p) as kv:
        assert kv.get(b"x") == b"1"
        assert len(kv) == 1


@pytest.mark.slow
def test_kv_memory_bounded_for_large_values(tmp_path):
    """Archive-shaped workload: values (states) dominate the data; the
    engine must keep them ON DISK — the in-memory index holds only
    key -> (offset, length).  RSS must stay far below the log size,
    including across a reopen replay (which skips value bytes)."""
    import resource

    from teku_tpu.native.kv import KvStore

    path = tmp_path / "big.db"
    n, vlen = 120, 1 << 20          # ~120 MB of value data
    value = bytes(vlen)
    base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    with KvStore(path) as kv:
        for i in range(n):
            kv.put(b"state/%08d" % i, value)
        kv.flush()
        assert kv.get(b"state/%08d" % 7) == value
    grown = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss - base
    # ru_maxrss is KiB on linux; allow ~40 MB of slack for allocator
    # noise but nothing near the 120 MB of values
    assert grown < 40 * 1024, f"RSS grew {grown} KiB"
    assert path.stat().st_size > n * vlen

    base2 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    with KvStore(path) as kv:      # reopen: replay indexes, not values
        assert len(kv) == n
        assert kv.get(b"state/%08d" % 99) == value
    grown2 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss - base2
    assert grown2 < 40 * 1024, f"replay RSS grew {grown2} KiB"
