"""Checkpoint sync: a fresh node anchors at another node's finalized
checkpoint over REST and follows the chain from there."""

import asyncio

import pytest

from teku_tpu.api import BeaconRestApi
from teku_tpu.node import Devnet
from teku_tpu.node.checkpoint import (checkpoint_sync_store,
                                      fetch_checkpoint_anchor)
from teku_tpu.node.gossip import InMemoryGossipNetwork
from teku_tpu.node.node import BeaconNode


@pytest.mark.slow
def test_checkpoint_sync_anchors_and_extends():
    async def run():
        net = Devnet(n_nodes=1, n_validators=16)
        await net.start()
        api = BeaconRestApi(net.nodes[0])
        await api.start()
        try:
            cfg = net.spec.config
            await net.run_until_slot(5 * cfg.SLOTS_PER_EPOCH)
            src = net.nodes[0]
            fin = src.store.finalized_checkpoint
            assert fin.epoch >= 2
            loop = asyncio.get_running_loop()
            url = f"http://127.0.0.1:{api.port}"

            # fetch runs in a thread: urllib blocks, the server is here
            state, signed = await loop.run_in_executor(
                None, fetch_checkpoint_anchor, net.spec, url)
            assert signed.message.htr() == fin.root
            assert state.slot == signed.message.slot

            now = state.genesis_time + cfg.SECONDS_PER_SLOT * (
                src.chain.head_slot() + 1)
            store = await loop.run_in_executor(
                None, lambda: checkpoint_sync_store(net.spec, url,
                                                    now=now))
            assert store.finalized_checkpoint.root == fin.root
            # the anchored node never saw genesis, yet extends the
            # chain: replay the source's post-checkpoint blocks
            fresh = BeaconNode(net.spec, state,
                               InMemoryGossipNetwork().endpoint(),
                               store=store)
            anchor_slot = signed.message.slot
            chain = []
            root = src.chain.head_root
            while root in src.store.blocks:
                blk = src.store.blocks[root]
                if blk.slot <= anchor_slot:
                    break
                chain.append(src.store.signed_blocks[root])
                root = blk.parent_root
            assert chain, "source should have post-checkpoint blocks"
            for signed_block in reversed(chain):
                # tick the clock to the block's slot (a live node's
                # slot timer does this)
                await fresh.on_slot(signed_block.message.slot)
                assert fresh.block_manager.import_block(signed_block)
            assert fresh.chain.head_root == src.chain.head_root
        finally:
            await api.stop()
            await net.stop()

    asyncio.run(run())
