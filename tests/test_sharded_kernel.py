"""verify_kernel_sharded on the 8-virtual-device CPU mesh.

Exercises the multi-chip path (shard_map over a dp axis with all_gather
combines, teku_tpu/ops/verify.py:verify_kernel_sharded) that production
runs over ICI — the exact program the driver's dryrun_multichip checks.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

import __graft_entry__ as ge
from teku_tpu.ops import verify as V


@pytest.fixture(scope="module")
def mesh():
    devices = np.array(jax.devices()[:8])
    if devices.size < 8:
        pytest.skip("needs 8 virtual devices (see conftest XLA_FLAGS)")
    with Mesh(devices, ("dp",)) as m:
        yield m


def test_sharded_kernel_valid_batch(mesh):
    args = ge._example_batch_hm(8)
    sharded = jax.jit(V.verify_kernel_sharded(mesh, "dp"))
    ok, lane_ok = sharded(*args)
    assert bool(np.asarray(ok))
    assert np.asarray(lane_ok).all()


def test_sharded_kernel_rejects_tampered_lane(mesh):
    args = ge._example_batch_hm(8)
    # corrupt one lane's H(m) point: the whole-batch verdict must flip
    (pk_xs, pk_ys, pk_present, hm, sig_x, s_large, s_inf,
     r_bits, lane_valid) = args
    (hx0, hx1), (hy0, hy1) = hm
    hx0, hx1 = hx0.copy(), hx1.copy()
    hx0[3] = hx0[4]
    hx1[3] = hx1[4]
    hm = ((hx0, hx1), (hy0, hy1))
    sharded = jax.jit(V.verify_kernel_sharded(mesh, "dp"))
    ok, lane_ok = sharded(pk_xs, pk_ys, pk_present, hm, sig_x,
                          s_large, s_inf, r_bits, lane_valid)
    assert not bool(np.asarray(ok))
    # the lanes themselves parse fine (failure is the pairing verdict)
    assert np.asarray(lane_ok).all()
