"""Extension-field tower kernels vs the pure-Python oracle."""

import random

import jax
import numpy as np

from teku_tpu.crypto.bls import fields as F
from teku_tpu.crypto.bls.constants import P
from teku_tpu.ops import limbs as fp
from teku_tpu.ops import towers as T

rng = random.Random(0xF12)


def rand_fq2():
    return (rng.randrange(P), rng.randrange(P))


def rand_fq6():
    return tuple(rand_fq2() for _ in range(3))


def rand_fq12():
    return (rand_fq6(), rand_fq6())


def stack2(vals):
    """List of oracle Fq2 -> batched device Fq2."""
    return (np.stack([fp.int_to_mont(v[0]) for v in vals]),
            np.stack([fp.int_to_mont(v[1]) for v in vals]))


def stack6(vals):
    return tuple(stack2([v[i] for v in vals]) for i in range(3))


def stack12(vals):
    return tuple(stack6([v[i] for v in vals]) for i in range(2))


def un2(dev, n):
    return [T.fq2_from_device(dev, (i,)) for i in range(n)]


def un12(dev, n):
    return [T.fq12_from_device(dev, (i,)) for i in range(n)]


N = 6
A2 = [rand_fq2() for _ in range(N)] + [(0, 0), (1, 0), (0, 1)]
B2 = [rand_fq2() for _ in range(N)] + [(5, 7), (0, 0), (P - 1, P - 1)]
M = len(A2)


def test_fq2_ring_ops():
    a, b = stack2(A2), stack2(B2)
    add = jax.jit(T.fq2_add)(a, b)
    mul = jax.jit(T.fq2_mul)(a, b)
    sqr = jax.jit(T.fq2_sqr)(a)
    xi = jax.jit(T.fq2_mul_by_xi)(a)
    conj = jax.jit(T.fq2_conj)(a)
    assert un2(add, M) == [F.fq2_add(x, y) for x, y in zip(A2, B2)]
    assert un2(mul, M) == [F.fq2_mul(x, y) for x, y in zip(A2, B2)]
    assert un2(sqr, M) == [F.fq2_sqr(x) for x in A2]
    assert un2(xi, M) == [F.fq2_mul_by_xi(x) for x in A2]
    assert un2(conj, M) == [F.fq2_conj(x) for x in A2]


def test_fq2_inv():
    a = stack2(A2)
    inv = jax.jit(T.fq2_inv)(a)
    got = un2(inv, M)
    for x, g in zip(A2, got):
        if x == (0, 0):
            assert g == (0, 0)  # inv(0) = 0 convention
        else:
            assert g == F.fq2_inv(x)


def test_fq2_pow_and_sqrt():
    sq_vals = [F.fq2_sqr(rand_fq2()) for _ in range(4)]
    nonsq = []
    while len(nonsq) < 2:
        c = rand_fq2()
        if F.fq2_sqrt(c) is None:
            nonsq.append(c)
    vals = sq_vals + nonsq + [(0, 0)]
    a = stack2(vals)
    p3 = jax.jit(lambda x: T.fq2_pow_static(x, 65537))(a)
    assert un2(p3, len(vals)) == [F.fq2_pow(v, 65537) for v in vals]
    ok, root = jax.jit(T.fq2_sqrt)(a)
    ok = np.asarray(ok)
    roots = un2(root, len(vals))
    for i, v in enumerate(vals):
        expect = F.fq2_sqrt(v)
        if expect is None:
            assert not ok[i]
        else:
            assert ok[i]
            assert F.fq2_sqr(roots[i]) == F.fq2_sqr(expect) == (
                v[0] % P, v[1] % P)


def test_fq2_is_large():
    vals = [(1, 0), (P - 1, 0), (0, 1), (0, P - 1), ((P - 1) // 2, 0),
            ((P + 1) // 2, 0)]
    plain = (np.stack([fp.int_to_limbs(v[0]) for v in vals]),
             np.stack([fp.int_to_limbs(v[1]) for v in vals]))
    got = list(np.asarray(jax.jit(T.fq2_is_large)(plain)))
    from teku_tpu.crypto.bls.curve import _fq2_is_large
    assert got == [_fq2_is_large(v) for v in vals]


def test_fq6_ops():
    A6 = [rand_fq6() for _ in range(4)] + [F.FQ6_ZERO, F.FQ6_ONE]
    B6 = [rand_fq6() for _ in range(4)] + [F.FQ6_ONE, F.FQ6_ZERO]
    a, b = stack6(A6), stack6(B6)
    n = len(A6)
    mul = jax.jit(T.fq6_mul)(a, b)
    sqr = jax.jit(T.fq6_sqr)(a)
    inv = jax.jit(T.fq6_inv)(a)
    frob = jax.jit(T.fq6_frobenius)(a)
    got_mul = [T.fq6_from_device(mul, (i,)) for i in range(n)]
    got_sqr = [T.fq6_from_device(sqr, (i,)) for i in range(n)]
    got_inv = [T.fq6_from_device(inv, (i,)) for i in range(n)]
    got_frob = [T.fq6_from_device(frob, (i,)) for i in range(n)]
    for i in range(n):
        assert got_mul[i] == F.fq6_mul(A6[i], B6[i])
        assert got_sqr[i] == F.fq6_sqr(A6[i])
        if A6[i] != F.FQ6_ZERO:
            assert got_inv[i] == F.fq6_inv(A6[i])
        assert got_frob[i] == F.fq6_frobenius(A6[i])


def test_fq12_ops():
    A12 = [rand_fq12() for _ in range(3)] + [F.FQ12_ONE]
    B12 = [rand_fq12() for _ in range(3)] + [F.FQ12_ONE]
    a, b = stack12(A12), stack12(B12)
    n = len(A12)
    mul = jax.jit(T.fq12_mul)(a, b)
    sqr = jax.jit(T.fq12_sqr)(a)
    inv = jax.jit(T.fq12_inv)(a)
    conj = jax.jit(T.fq12_conj)(a)
    fr1 = jax.jit(lambda x: T.fq12_frobenius(x, 1))(a)
    fr2 = jax.jit(lambda x: T.fq12_frobenius(x, 2))(a)
    for i in range(n):
        assert un12(mul, n)[i] == F.fq12_mul(A12[i], B12[i])
        assert un12(sqr, n)[i] == F.fq12_sqr(A12[i])
        assert un12(inv, n)[i] == F.fq12_inv(A12[i])
        assert un12(conj, n)[i] == F.fq12_conj(A12[i])
        assert un12(fr1, n)[i] == F.fq12_frobenius(A12[i], 1)
        assert un12(fr2, n)[i] == F.fq12_frobenius(A12[i], 2)


def _cyclotomic(f):
    t = F.fq12_mul(F.fq12_conj(f), F.fq12_inv(f))
    return F.fq12_mul(F.fq12_frobenius(t, 2), t)


def test_fq12_cyclo_sqr_and_is_one():
    cyc = [_cyclotomic(rand_fq12()) for _ in range(3)] + [F.FQ12_ONE]
    a = stack12(cyc)
    n = len(cyc)
    cs = jax.jit(T.fq12_cyclo_sqr)(a)
    for i in range(n):
        assert un12(cs, n)[i] == F.fq12_sqr(cyc[i])
    ones = np.asarray(jax.jit(T.fq12_is_one)(a))
    assert list(ones) == [c == F.FQ12_ONE for c in cyc]
