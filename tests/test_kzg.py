"""KZG: roots of unity, barycentric evaluation, MSM, and end-to-end
blob commitment/proof verification on both the insecure dev setup and
the real ceremony trusted setup."""

import secrets
from pathlib import Path

import pytest

from teku_tpu.crypto import kzg
from teku_tpu.crypto.bls import curve as C
from teku_tpu.crypto.kzg import (blob_to_kzg_commitment, BYTES_PER_BLOB,
                                 compute_blob_kzg_proof, compute_challenge,
                                 evaluate_polynomial_in_evaluation_form,
                                 FIELD_ELEMENTS_PER_BLOB, g1_msm,
                                 insecure_setup, KzgError,
                                 load_trusted_setup, R, roots_of_unity,
                                 verify_blob_kzg_proof,
                                 verify_blob_kzg_proof_batch)

SETUP_PATH = Path(kzg.REFERENCE_SETUP_PATH)


def _random_blob(rng_seed: int = 1) -> bytes:
    import random
    rng = random.Random(rng_seed)
    return b"".join(
        rng.randrange(R).to_bytes(32, "big")
        for _ in range(FIELD_ELEMENTS_PER_BLOB))


def test_roots_of_unity_are_roots():
    roots = roots_of_unity()
    assert len(set(roots)) == FIELD_ELEMENTS_PER_BLOB
    for w in roots[:4] + roots[-2:]:
        assert pow(w, FIELD_ELEMENTS_PER_BLOB, R) == 1
    # the generator (index 1 bit-reversed = w^2048 = -1; the true
    # generator sits at the bit-reversal of index 1's position)
    w = pow(7, (R - 1) // FIELD_ELEMENTS_PER_BLOB, R)
    assert w in roots
    assert pow(w, FIELD_ELEMENTS_PER_BLOB // 2, R) == R - 1
    assert roots[1] == R - 1  # brp[1] = w^(n/2)


def test_barycentric_matches_direct_at_roots_and_elsewhere():
    poly = [i * 7 + 3 for i in range(FIELD_ELEMENTS_PER_BLOB)]
    roots = roots_of_unity()
    # at a root: exactly the evaluation-form value
    assert evaluate_polynomial_in_evaluation_form(poly, roots[5]) == poly[5]
    # a constant polynomial evaluates to the constant anywhere
    const = [42] * FIELD_ELEMENTS_PER_BLOB
    assert evaluate_polynomial_in_evaluation_form(const, 123456789) == 42
    # p(x) = x has evaluation form poly[i] = w_i
    identity = list(roots)
    z = 0xDEADBEEF
    assert evaluate_polynomial_in_evaluation_form(identity, z) == z


def test_msm_matches_naive():
    import random
    rng = random.Random(9)
    pts = [C.point_mul(C.FQ_OPS, rng.randrange(1, R), C.G1_GENERATOR)
           for _ in range(5)]
    scalars = [rng.randrange(R) for _ in range(5)]
    expect = (0, 1, 0)
    for p, s in zip(pts, scalars):
        expect = C.point_add(C.FQ_OPS, expect, C.point_mul(C.FQ_OPS, s, p))
    got = g1_msm(pts, scalars)
    assert C.point_eq(C.FQ_OPS, got, expect)


def test_blob_proof_roundtrip_insecure_setup():
    setup = insecure_setup()
    blob = _random_blob(2)
    commitment = blob_to_kzg_commitment(blob, setup)
    proof = compute_blob_kzg_proof(blob, commitment, setup)
    assert verify_blob_kzg_proof(blob, commitment, proof, setup)
    # tampered blob fails
    bad_blob = b"\x00" * 31 + b"\x01" + blob[32:]
    assert not verify_blob_kzg_proof(bad_blob, commitment, proof, setup)
    # tampered proof fails
    other = compute_blob_kzg_proof(bad_blob,
                                   blob_to_kzg_commitment(bad_blob, setup),
                                   setup)
    assert not verify_blob_kzg_proof(blob, commitment, other, setup)


def test_batch_and_malformed_inputs():
    setup = insecure_setup()
    blobs, commits, proofs = [], [], []
    for seed in (3, 4):
        b = _random_blob(seed)
        c = blob_to_kzg_commitment(b, setup)
        p = compute_blob_kzg_proof(b, c, setup)
        blobs.append(b), commits.append(c), proofs.append(p)
    assert verify_blob_kzg_proof_batch(blobs, commits, proofs, setup)
    assert not verify_blob_kzg_proof_batch(blobs, commits[::-1], proofs,
                                           setup)
    assert not verify_blob_kzg_proof_batch(blobs[:1], commits, proofs,
                                           setup)
    # malformed: wrong blob length, out-of-range element, bad point
    assert not verify_blob_kzg_proof(b"\x00" * 10, commits[0], proofs[0],
                                     setup)
    bad_fe = (R).to_bytes(32, "big") + blobs[0][32:]
    assert not verify_blob_kzg_proof(bad_fe, commits[0], proofs[0], setup)
    assert not verify_blob_kzg_proof(blobs[0], b"\x00" * 48, proofs[0],
                                     setup)


def test_challenge_domain_separation():
    blob = _random_blob(5)
    c1 = compute_challenge(blob, b"\xc0" * 48)
    c2 = compute_challenge(blob, b"\xc1" * 48)
    assert c1 != c2 and 0 <= c1 < R


needs_setup = pytest.mark.skipif(not SETUP_PATH.is_file(),
                                 reason="ceremony setup not present")


@needs_setup
@pytest.mark.slow
def test_real_trusted_setup_end_to_end():
    """Commitment + proof via Pippenger MSM over the REAL ceremony
    Lagrange basis, verified with the real [s]G2 — the full production
    path with no insecure shortcut."""
    setup = load_trusted_setup(SETUP_PATH)
    assert len(setup.g1_lagrange) == FIELD_ELEMENTS_PER_BLOB
    assert len(setup.g2_monomial) == 65
    blob = _random_blob(6)
    commitment = blob_to_kzg_commitment(blob, setup)
    proof = compute_blob_kzg_proof(blob, commitment, setup)
    assert verify_blob_kzg_proof(blob, commitment, proof, setup)
    bad = bytearray(blob)
    bad[40] ^= 1
    assert not verify_blob_kzg_proof(bytes(bad), commitment, proof, setup)


def test_batch_host_fallback_short_circuits_on_first_failure(
        monkeypatch):
    """The BackendUnavailable host fallback must stop at the FIRST
    failed blob: the batch verdict is already False, and a 4096-point
    pairing per remaining blob would burn host time exactly while the
    node is degraded.  Same property for the no-backend batch path."""
    setup = insecure_setup()
    blobs, commits, proofs = [], [], []
    for seed in (5, 6, 7):
        b = _random_blob(seed)
        c = blob_to_kzg_commitment(b, setup)
        p = compute_blob_kzg_proof(b, c, setup)
        blobs.append(b), commits.append(c), proofs.append(p)
    # first blob's proof is wrong; the rest are valid
    bad_proofs = [proofs[1]] + proofs[1:]

    calls = []
    real_host = kzg._verify_blob_kzg_proof_host

    def counting_host(b, c, p, s=None):
        calls.append(b[:8])
        return real_host(b, c, p, s)

    monkeypatch.setattr(kzg, "_verify_blob_kzg_proof_host",
                        counting_host)

    class SickBackend:
        name = "sick"

        def verify_blob_kzg_proof_batch(self, *a, **kw):
            raise kzg.BackendUnavailable("circuit open")

    kzg.set_backend(SickBackend())
    try:
        assert not verify_blob_kzg_proof_batch(blobs, commits,
                                               bad_proofs, setup)
        assert len(calls) == 1          # stopped at the first failure
        # a fully-valid batch still verifies every blob
        calls.clear()
        assert verify_blob_kzg_proof_batch(blobs, commits, proofs,
                                           setup)
        assert len(calls) == 3
    finally:
        kzg.set_backend(None)
    # no-backend path short-circuits identically
    calls.clear()
    assert not verify_blob_kzg_proof_batch(blobs, commits, bad_proofs,
                                           setup)
    assert len(calls) == 1


def test_kzg_arrivals_accounted_as_their_own_source():
    """Blob verification demand lands in the capacity model under
    source="kzg" (class SYNC_CRITICAL), so utilization and brownout
    see blob storms — and a failed accounting layer can never fail a
    verification."""
    from teku_tpu.infra import capacity
    from teku_tpu.infra.flightrecorder import FlightRecorder
    from teku_tpu.infra.metrics import MetricsRegistry
    from teku_tpu.services.admission import VerifyClass

    assert kzg.KZG_ARRIVAL_SOURCE == capacity.SOURCE_KZG == "kzg"
    assert kzg.kzg_verify_class() is VerifyClass.SYNC_CRITICAL

    setup = insecure_setup()
    blob = _random_blob(8)
    commitment = blob_to_kzg_commitment(blob, setup)
    proof = compute_blob_kzg_proof(blob, commitment, setup)

    reg = MetricsRegistry()
    telemetry = capacity.CapacityTelemetry(
        registry=reg, recorder=FlightRecorder(registry=reg))
    prev = capacity.swap_default(telemetry)
    try:
        assert verify_blob_kzg_proof_batch([blob], [commitment],
                                           [proof], setup)
        arrivals = telemetry.snapshot()["arrival_rate_per_second"]
        assert capacity.SOURCE_KZG in arrivals
        # single-blob verification is demand too
        assert verify_blob_kzg_proof(blob, commitment, proof, setup)
    finally:
        restored = capacity.swap_default(prev)
        assert restored is telemetry       # swap seam round-trips
