"""Device KZG (ops/kzg.py): Fr field, blob evaluation, folded pairing.

Oracle = the host KZG implementation (crypto/kzg.py), itself validated
against spec vectors in tests/test_kzg.py.  The reference's equivalent
surface is CKZG4844.java:104-122 (verifyBlobKzgProof/Batch over native
c-kzg); here the math runs on the shared JAX kernel base.
"""

import secrets

import numpy as np
import pytest

from teku_tpu.crypto import kzg as HK
from teku_tpu.crypto.bls.constants import R
from teku_tpu.ops import kzg as DK

FR = DK.FR

SETUP = HK.insecure_setup()


def _rand_fr(n, seed=1):
    rng = np.random.default_rng(seed)
    return [int.from_bytes(rng.bytes(31), "big") % R for _ in range(n)]


def _blob_from_ints(vals):
    return b"".join(v.to_bytes(32, "big") for v in vals)


def _rand_blob(seed=7):
    rng = np.random.default_rng(seed)
    return _blob_from_ints(
        [int.from_bytes(rng.bytes(31), "big") % R
         for _ in range(HK.FIELD_ELEMENTS_PER_BLOB)])


# -- Fr limb field ---------------------------------------------------------

def test_fr_roundtrip_and_mul():
    vals = _rand_fr(6)
    for v in vals:
        assert FR.limbs_to_int(FR.int_to_limbs(v)) == v
        assert FR.mont_to_int(FR.int_to_mont(v)) == v
    a = np.stack([FR.int_to_mont(v) for v in vals[:3]])
    b = np.stack([FR.int_to_mont(v) for v in vals[3:]])
    out = np.asarray(FR.mont_mul(a, b))
    for i in range(3):
        assert FR.mont_to_int(out[i]) == vals[i] * vals[3 + i] % R


def test_fr_inv_many_matches_fermat():
    vals = _rand_fr(5, seed=2) + [0]       # zero lane maps to zero
    a = np.stack([FR.int_to_mont(v) for v in vals])
    out = np.asarray(FR.inv_many(a))
    for i, v in enumerate(vals):
        expect = pow(v, R - 2, R) if v else 0
        assert FR.mont_to_int(out[i]) == expect


def test_fr_pow_static_and_canonical():
    v = _rand_fr(1, seed=3)[0]
    a = FR.int_to_mont(v)[None]
    out = np.asarray(FR.pow_static(a, 4096))
    assert FR.mont_to_int(out[0]) == pow(v, 4096, R)
    plain = np.asarray(FR.canonical_plain(a))
    assert FR.limbs_to_int(plain[0]) == v


# -- blob evaluation -------------------------------------------------------

def test_eval_blob_kernel_matches_host():
    blob = _rand_blob()
    poly = HK.blob_to_polynomial(blob)
    zs = _rand_fr(2, seed=4)
    limbs = DK.blob_bytes_to_limbs([blob, blob])
    z_mont = np.stack([FR.int_to_mont(z) for z in zs])
    out = np.asarray(DK.eval_blob_kernel(limbs, z_mont))
    for i, z in enumerate(zs):
        expect = HK.evaluate_polynomial_in_evaluation_form(poly, z)
        assert FR.limbs_to_int(out[i]) == expect


def test_eval_blob_kernel_z_at_root():
    blob = _rand_blob(seed=9)
    poly = HK.blob_to_polynomial(blob)
    z = HK.roots_of_unity()[17]
    limbs = DK.blob_bytes_to_limbs([blob])
    out = np.asarray(DK.eval_blob_kernel(
        limbs, FR.int_to_mont(z)[None]))
    assert FR.limbs_to_int(out[0]) == poly[17]


def test_blob_range_check():
    bad = _blob_from_ints([0] * (HK.FIELD_ELEMENTS_PER_BLOB - 1) + [R])
    limbs = DK.blob_bytes_to_limbs([bad])
    assert not DK.limbs_lt_modulus(limbs).all()
    good = DK.blob_bytes_to_limbs([_rand_blob()])
    assert DK.limbs_lt_modulus(good).all()


# -- folded verification ---------------------------------------------------

@pytest.fixture(scope="module")
def backend():
    return DK.JaxKzg()


def test_verify_kzg_proof_device(backend):
    blob = _rand_blob(seed=11)
    poly = HK.blob_to_polynomial(blob)
    z = _rand_fr(1, seed=5)[0]
    proof, y = HK.compute_kzg_proof_impl(poly, z, SETUP)
    commitment = HK.blob_to_kzg_commitment(blob, SETUP)
    assert backend.verify_kzg_proof(commitment, z, y, proof, SETUP)
    assert not backend.verify_kzg_proof(commitment, z, (y + 1) % R,
                                        proof, SETUP)


@pytest.mark.slow
def test_verify_blob_batch_device(backend):
    blobs = [_rand_blob(seed=20 + i) for i in range(3)]
    commitments = [HK.blob_to_kzg_commitment(b, SETUP) for b in blobs]
    proofs = [HK.compute_blob_kzg_proof(b, c, SETUP)
              for b, c in zip(blobs, commitments)]
    assert backend.verify_blob_kzg_proof_batch(
        blobs, commitments, proofs, SETUP)
    # single-item path too
    assert backend.verify_blob_kzg_proof(blobs[0], commitments[0],
                                         proofs[0], SETUP)
    # a wrong proof fails the whole batch
    assert not backend.verify_blob_kzg_proof_batch(
        blobs, commitments, [proofs[1], proofs[0], proofs[2]], SETUP)
    # malformed commitment rejects, not raises
    assert not backend.verify_blob_kzg_proof_batch(
        blobs, [b"\x00" * 48] + commitments[1:], proofs, SETUP)


@pytest.mark.slow
def test_facade_routes_to_device_backend(backend):
    """crypto/kzg.verify_blob_kzg_proof_batch dispatches through the
    installed backend (the node-facing seam)."""
    blob = _rand_blob(seed=31)
    commitment = HK.blob_to_kzg_commitment(blob, SETUP)
    proof = HK.compute_blob_kzg_proof(blob, commitment, SETUP)
    before = backend.dispatch_count
    HK.set_backend(backend)
    try:
        assert HK.verify_blob_kzg_proof_batch(
            [blob], [commitment], [proof], SETUP)
        assert backend.dispatch_count > before
        # infinity commitment (zero blob) verifies via the device too
        zero_blob = bytes(HK.BYTES_PER_BLOB)
        zc = HK.blob_to_kzg_commitment(zero_blob, SETUP)
        zp = HK.compute_blob_kzg_proof(zero_blob, zc, SETUP)
        assert HK.verify_blob_kzg_proof_batch([zero_blob], [zc], [zp],
                                              SETUP)
    finally:
        HK.set_backend(None)
