"""Node health & SLO engine (PR 3): aggregation truth table, the
event-loop-lag watchdog, SLO burn-rate math on synthetic data,
trace-correlated JSON logs, flight-recorder dump-on-trip via the
fault-injection harness, and the REST acceptance flow — breaker trip
degrades /eth/v1/node/health to 206 with an slo_*/breaker event in the
flight recorder carrying the originating trace id, and recovery
restores 200."""

import asyncio
import json
import logging
import time

import pytest

from teku_tpu.infra import faults, flightrecorder, tracing
from teku_tpu.infra.health import (CheckResult, EventLoopLagWatchdog,
                                   HealthRegistry, HealthStatus,
                                   SloEngine, SloObjective,
                                   histogram_good_total,
                                   labeled_counter_good_total,
                                   signature_service_check,
                                   supervisor_check)
from teku_tpu.infra.logs import JsonFormatter, _make_formatter
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.infra.supervisor import (BackendState, BackendSupervisor,
                                       CircuitBreaker)

UP, DEGRADED, DOWN = (HealthStatus.UP, HealthStatus.DEGRADED,
                      HealthStatus.DOWN)


def _recorder(tmp_path) -> flightrecorder.FlightRecorder:
    return flightrecorder.FlightRecorder(
        capacity=64, dump_dir=str(tmp_path),
        registry=MetricsRegistry())


# --------------------------------------------------------------------------
# Aggregation truth table + edge triggering
# --------------------------------------------------------------------------

def test_health_aggregation_truth_table(tmp_path):
    reg = MetricsRegistry()
    rec = _recorder(tmp_path)
    hr = HealthRegistry(name="t", registry=reg, recorder=rec)
    state = {"a": UP, "b": UP}
    hr.register("a", lambda: CheckResult(state["a"], "detail-a"))
    hr.register("b", lambda: state["b"])      # bare-status form
    with pytest.raises(ValueError):
        hr.register("a", lambda: CheckResult(UP))   # duplicate name

    assert hr.evaluate() is UP
    assert hr.snapshot()["status"] == "up"
    # one sick check degrades the NODE verdict
    state["a"] = DEGRADED
    assert hr.evaluate() is DEGRADED
    # DOWN dominates DEGRADED
    state["b"] = DOWN
    assert hr.evaluate() is DOWN
    snap = hr.snapshot()
    assert snap["status"] == "down"
    assert snap["checks"]["a"]["status"] == "degraded"
    assert snap["checks"]["a"]["detail"] == "detail-a"
    # recovery flips it all the way back
    state["a"] = state["b"] = UP
    assert hr.evaluate() is UP
    # a RAISING check reads as DOWN, never a crash
    hr.register("boom", lambda: 1 / 0)
    assert hr.evaluate() is DOWN
    assert "ZeroDivisionError" in hr.snapshot()["checks"]["boom"]["detail"]


def test_health_events_are_edge_triggered(tmp_path):
    reg = MetricsRegistry()
    rec = _recorder(tmp_path)
    hr = HealthRegistry(name="t", registry=reg, recorder=rec)
    state = {"s": UP}
    hr.register("a", lambda: CheckResult(state["s"]))

    hr.evaluate()
    hr.evaluate()
    # first evaluation establishing UP is not an event
    assert [e for e in rec.snapshot()
            if e["kind"] == "health_flip"] == []

    state["s"] = DEGRADED
    hr.evaluate()
    hr.evaluate()          # steady state: no second event
    hr.evaluate()
    flips = [e for e in rec.snapshot() if e["kind"] == "health_flip"]
    # exactly one flip for the check, one for the aggregate
    assert sorted(e["subject"] for e in flips) == ["a", "node"]
    assert all(e["to"] == "degraded" for e in flips)

    state["s"] = UP
    hr.evaluate()
    hr.evaluate()
    flips = [e for e in rec.snapshot() if e["kind"] == "health_flip"]
    assert len(flips) == 4     # + one recovery edge each
    assert [e["to"] for e in flips[-2:]] == ["up", "up"]
    # the transitions counter matches the edges
    assert hr._m_flips.labels(node="t", check="a").value == 2.0
    assert hr._m_flips.labels(node="t", check="node").value == 2.0


# --------------------------------------------------------------------------
# Event-loop-lag watchdog
# --------------------------------------------------------------------------

def test_event_loop_lag_watchdog_detects_blocked_loop():
    reg = MetricsRegistry()
    wd = EventLoopLagWatchdog(interval_s=0.05, degraded_s=0.2,
                              down_s=10.0, registry=reg)
    assert wd.check().status is UP          # not running yet

    async def run():
        wd.start()
        await asyncio.sleep(0.15)           # a few clean samples
        assert wd.check().status is UP
        time.sleep(0.4)                     # deliberately block the loop
        await asyncio.sleep(0.1)            # let the overshoot land
        res = wd.check()
        assert res.status is DEGRADED, res
        assert "lag" in res.detail
        # the gauge exports the same worst-recent lag
        assert wd.lag_s >= 0.2
        await wd.stop()

    asyncio.run(run())


# --------------------------------------------------------------------------
# SLO burn-rate math on synthetic data
# --------------------------------------------------------------------------

def test_slo_burn_rate_latency_objective(tmp_path):
    reg = MetricsRegistry()
    rec = _recorder(tmp_path)
    hist = reg.labeled_histogram(
        "t_stage_seconds", "t", labelnames=("stage",),
        buckets=(0.01, 0.1, 1.0))
    child = hist.labels(stage="complete")
    obj = SloObjective(
        name="verify_p50", description="p50 <= 100ms",
        target_ratio=0.5,
        sample=lambda: histogram_good_total(lambda: child, 0.1))
    eng = SloEngine([obj], registry=reg, recorder=rec)

    # window 1: 8 fast + 2 slow -> bad 0.2, budget 0.5, burn 0.4
    for _ in range(8):
        child.observe(0.005)
    for _ in range(2):
        child.observe(0.5)
    snap = eng.tick()
    assert snap["verify_p50"]["burn_rate"] == pytest.approx(0.4)
    assert not snap["verify_p50"]["breached"]

    # window 2: 2 fast + 8 slow -> bad 0.8, burn 1.6 -> BREACH (once)
    for _ in range(2):
        child.observe(0.005)
    for _ in range(8):
        child.observe(0.5)
    snap = eng.tick()
    assert snap["verify_p50"]["burn_rate"] == pytest.approx(1.6)
    assert snap["verify_p50"]["breached"]
    eng.tick()                 # no new samples: verdict held, no spam
    breaches = [e for e in rec.snapshot() if e["kind"] == "slo_breach"]
    assert len(breaches) == 1
    assert breaches[0]["objective"] == "verify_p50"
    assert eng.check().status is DEGRADED

    # window 3: all fast -> burn 0 -> edge-triggered recovery
    for _ in range(10):
        child.observe(0.005)
    snap = eng.tick()
    assert snap["verify_p50"]["burn_rate"] == 0.0
    assert not snap["verify_p50"]["breached"]
    assert [e["kind"] for e in rec.snapshot()].count("slo_recovery") == 1
    assert eng.check().status is UP


def test_slo_ratio_objective_and_trace_blame(tmp_path):
    reg = MetricsRegistry()
    rec = _recorder(tmp_path)
    fam = reg.labeled_counter("t_requests_total", "t",
                              labelnames=("backend", "reason"))
    obj = SloObjective(
        name="success_ratio", description=">= 90% ok",
        target_ratio=0.9,
        sample=lambda: labeled_counter_good_total(
            fam, lambda l: l.get("reason") == "ok"))
    eng = SloEngine([obj], registry=reg, recorder=rec)

    fam.labels(backend="device", reason="ok").inc(100)
    snap = eng.tick()
    assert snap["success_ratio"]["burn_rate"] == 0.0

    # a traced failure lands in the recorder FIRST (the breaker-trip
    # path); the subsequent untraced SLO tick must blame that trace
    rec.record("breaker_trip", trace_id="cafe-000001",
               breaker="t_device")
    fam.labels(backend="oracle", reason="fallback").inc(50)
    snap = eng.tick()
    # window: 0 ok of 50 -> bad 1.0, budget 0.1 -> burn 10
    assert snap["success_ratio"]["burn_rate"] == pytest.approx(10.0)
    breach = [e for e in rec.snapshot()
              if e["kind"] == "slo_breach"][-1]
    assert breach["trace_id"] == "cafe-000001"


def test_slo_zero_target_never_breaches(tmp_path):
    """target_ratio=0 (the device-serving default on CPU-only nodes):
    fully-bad traffic reads burn == 1.0, not a breach."""
    reg = MetricsRegistry()
    rec = _recorder(tmp_path)
    fam = reg.labeled_counter("t2_requests_total", "t",
                              labelnames=("backend", "reason"))
    obj = SloObjective(
        name="device_ratio", description="opt-in", target_ratio=0.0,
        sample=lambda: labeled_counter_good_total(
            fam, lambda l: l.get("backend") == "device"))
    eng = SloEngine([obj], registry=reg, recorder=rec)
    fam.labels(backend="oracle", reason="ok").inc(100)
    snap = eng.tick()
    assert snap["device_ratio"]["burn_rate"] == pytest.approx(1.0)
    assert not snap["device_ratio"]["breached"]


# --------------------------------------------------------------------------
# JSON log records carry the current trace id
# --------------------------------------------------------------------------

def test_json_log_records_carry_trace_id():
    fmt = JsonFormatter()
    logger = logging.getLogger("teku_tpu.test_health")

    def make(msg):
        return logger.makeRecord(logger.name, logging.WARNING, "f", 1,
                                 msg, (), None)

    with tracing.trace("json_log_verify") as tr:
        line = fmt.format(make("slow batch"))
    out = json.loads(line)
    assert out["msg"] == "slow batch"
    assert out["level"] == "WARNING"
    assert out["trace_id"] == tr.trace_id

    # outside any trace: no trace_id key, still valid JSON
    out = json.loads(fmt.format(make("untraced")))
    assert "trace_id" not in out
    # the formatter factory maps names correctly
    assert isinstance(_make_formatter("json"), JsonFormatter)
    assert not isinstance(_make_formatter("text"), JsonFormatter)


# --------------------------------------------------------------------------
# Flight recorder: ring semantics + dump-on-trip via the faults harness
# --------------------------------------------------------------------------

def test_flight_recorder_ring_and_dump(tmp_path):
    rec = _recorder(tmp_path)
    for i in range(80):              # capacity 64: oldest evicted
        rec.record("test_event", i=i)
    events = rec.snapshot()
    assert len(events) == 64
    assert events[0]["i"] == 16 and events[-1]["i"] == 79
    assert [e["i"] for e in rec.tail(3)] == [77, 78, 79]
    path = rec.dump("unit test")
    lines = [json.loads(line)
             for line in open(path).read().splitlines()]
    assert lines[0]["kind"] == "dump_header"
    assert lines[0]["reason"] == "unit test"
    assert len(lines) == 65
    rec.clear()
    assert rec.snapshot() == []
    assert rec.dump("empty") is None


@pytest.mark.faults
def test_breaker_trip_dumps_flight_recorder(tmp_path, monkeypatch):
    """A fault-injected dispatch failure trips the breaker; the GLOBAL
    recorder lands a breaker_trip event carrying the originating
    verify's trace id and auto-dumps the ring to JSONL."""
    rec = flightrecorder.RECORDER
    monkeypatch.setattr(rec, "dump_dir", str(tmp_path))
    monkeypatch.setattr(rec, "_last_dump_t", -1e9)   # defeat throttle
    reg = MetricsRegistry()
    br = CircuitBreaker(failure_threshold=1, deadline_s=2.0,
                        name="t_dump_device", registry=reg)
    faults.inject("test.dump_site",
                  faults.Raise(RuntimeError("injected dispatch fault")))
    try:
        tr = tracing.new_trace("tripping_verify")
        with tracing.attach((tr,)):
            with pytest.raises(RuntimeError):
                br.call(lambda: faults.check("test.dump_site"))
        tracing.finish(tr)
    finally:
        faults.clear("test.dump_site")
    assert br.state == CircuitBreaker.OPEN
    trip = [e for e in rec.snapshot()
            if e["kind"] == "breaker_trip"][-1]
    assert trip["breaker"] == "t_dump_device"
    assert trip["trace_id"] == tr.trace_id
    # the auto-dump wrote a JSONL file containing that same event
    files = sorted(tmp_path.glob("flight_*.jsonl"))
    assert files, "breaker trip did not dump the flight recorder"
    dumped = [json.loads(line)
              for line in files[-1].read_text().splitlines()]
    assert any(e.get("kind") == "breaker_trip"
               and e.get("trace_id") == tr.trace_id for e in dumped)
    # throttled: an immediate second trip does not write a second file
    br.record_failure()
    assert sorted(tmp_path.glob("flight_*.jsonl")) == files


# --------------------------------------------------------------------------
# Subsystem check factories
# --------------------------------------------------------------------------

def test_signature_service_check_utilization_and_stall():
    """The saturation signal is the CAPACITY MODEL's utilization (the
    same signal the brownout controller keys on), with a raw
    queue-full backstop for the pre-evidence window."""
    class FakeService:
        def __init__(self):
            self.snap = {"queue_size": 0, "capacity": 100,
                         "saturation": 0.0, "workers": 2,
                         "stalled_s": 0.0,
                         "capacity_model": {"utilization": 0.1,
                                            "headroom_ratio": 0.9}}

        def health_snapshot(self):
            return dict(self.snap)

    svc = FakeService()
    check = signature_service_check(svc, utilization_degraded=1.0,
                                    stall_down_s=30.0)
    assert check().status is UP
    # demand over sustainable capacity degrades even with a short queue
    svc.snap["capacity_model"] = {"utilization": 1.2,
                                  "headroom_ratio": 0.0}
    res = check()
    assert res.status is DEGRADED and "capacity" in res.detail
    # back under capacity, but the queue physically full: backstop
    svc.snap["capacity_model"] = {"utilization": 0.2,
                                  "headroom_ratio": 0.8}
    svc.snap.update(queue_size=97, saturation=0.97)
    assert check().status is DEGRADED
    svc.snap.update(stalled_s=45.0)
    res = check()
    assert res.status is DOWN and "stalled" in res.detail


def test_real_signature_service_health_snapshot():
    from teku_tpu.services.signatures import (
        AggregatingSignatureVerificationService)
    svc = AggregatingSignatureVerificationService(
        queue_capacity=10, registry=MetricsRegistry(),
        name="t_health_sigs")
    snap = svc.health_snapshot()
    capacity_model = snap.pop("capacity_model")
    classes = snap.pop("classes")
    assert snap == {"queue_size": 0, "capacity": 10, "saturation": 0.0,
                    "workers": 0, "stalled_s": 0.0,
                    "brownout_level": 0}
    # per-class queue view: every VerifyClass present, all idle
    from teku_tpu.services.admission import VerifyClass
    assert set(classes) == {c.label for c in VerifyClass}
    assert all(v["depth"] == 0 for v in classes.values())
    # the embedded capacity view (infra/capacity.py) rides along for
    # the SLO engine / adaptive batcher
    assert {"utilization", "headroom_ratio",
            "occupancy_ratio"} <= set(capacity_model)


def test_supervisor_check_states(tmp_path):
    assert supervisor_check(lambda: None)().status is UP

    class FakeSup:
        backend_state = "ready"
        backend_detail = ""
        breaker = None

    sup = FakeSup()
    check = supervisor_check(lambda: sup)
    assert check().status is UP
    sup.backend_state = "tripped"
    assert check().status is DEGRADED
    sup.backend_state = "degraded"
    sup.backend_detail = "bring-up abandoned: probe timeout"
    res = check()
    assert res.status is DEGRADED and "probe timeout" in res.detail
    sup.backend_state = "probing"
    assert check().status is UP         # bring-up is boot, not sickness


# --------------------------------------------------------------------------
# REST acceptance: 200 -> (trip) 206 -> (recover) 200, 503 on DOWN,
# syncing_status override, readiness + flight-recorder endpoints
# --------------------------------------------------------------------------

@pytest.mark.faults
def test_node_health_endpoint_acceptance(tmp_path, monkeypatch):
    import dataclasses
    from teku_tpu.api import BeaconRestApi
    from teku_tpu.infra.restapi import HttpError
    from teku_tpu.node.gossip import InMemoryGossipNetwork
    from teku_tpu.node.node import BeaconNode
    from teku_tpu.spec import config as C, Spec
    from teku_tpu.spec.genesis import interop_genesis

    monkeypatch.setattr(flightrecorder.RECORDER, "dump_dir",
                        str(tmp_path))
    spec = Spec(C.MINIMAL)
    state, _ = interop_genesis(C.MINIMAL, 16, 0)

    async def run():
        node = BeaconNode(spec, state,
                          InMemoryGossipNetwork().endpoint(),
                          name="t_health_node")
        api = BeaconRestApi(node)
        # healthy node: 200
        assert (await api._health())[2] == 200

        # wire a READY supervisor whose breaker we then trip with an
        # injected dispatch fault, under a root trace
        reg = MetricsRegistry()
        br = CircuitBreaker(failure_threshold=1, deadline_s=2.0,
                            cooldown_s=60.0, name="t_acc_device",
                            registry=reg)
        sup = BackendSupervisor(probe=lambda: None,
                                install=lambda b: None, breaker=br,
                                name="t_acc_backend", registry=reg)
        sup._record(BackendState.READY)
        node.supervisor = sup

        faults.inject("test.acceptance_site",
                      faults.Raise(RuntimeError("injected")))
        try:
            tr = tracing.new_trace("acceptance_verify")
            with tracing.attach((tr,)):
                with pytest.raises(RuntimeError):
                    br.call(lambda: faults.check("test.acceptance_site"))
            tracing.finish(tr)
        finally:
            faults.clear("test.acceptance_site")
        assert sup.backend_state == "tripped"

        # live HealthRegistry drives the endpoint: DEGRADED -> 206
        assert (await api._health())[2] == 206
        # syncing_status substitutes ONLY the syncing response: a
        # DEGRADED-but-synced node keeps its 206 (a ?syncing_status=200
        # LB probe must not mask real degradation) ...
        assert (await api._health(
            query={"syncing_status": "299"}))[2] == 206
        # ... while an actually-syncing node honors the override
        import types
        api_sync = BeaconRestApi(node, networked=types.SimpleNamespace(
            sync=types.SimpleNamespace(syncing=True)))
        assert (await api_sync._health())[2] == 206
        assert (await api_sync._health(
            query={"syncing_status": "299"}))[2] == 299
        with pytest.raises(HttpError) as err:
            await api._health(query={"syncing_status": "999"})
        assert err.value.status == 400
        with pytest.raises(HttpError) as err:
            await api._health(query={"syncing_status": "abc"})
        assert err.value.status == 400

        # the breaker trip recorded the originating trace id; feed an
        # SLO objective a bad window so the breach event lands too
        bad = {"good": 100.0, "total": 100.0}
        node.slo = SloEngine(
            [SloObjective(name="verify_success_ratio",
                          description=">= 99% ok", target_ratio=0.99,
                          sample=lambda: (bad["good"], bad["total"]))],
            registry=reg, recorder=node.flight_recorder)
        node.slo.tick()                     # clean baseline window
        bad["total"] = 150.0                # 50 new, all bad
        node.slo.tick()
        events = node.flight_recorder.snapshot()
        trip = [e for e in events if e["kind"] == "breaker_trip"][-1]
        breach = [e for e in events if e["kind"] == "slo_breach"][-1]
        assert trip["trace_id"] == tr.trace_id
        assert breach["objective"] == "verify_success_ratio"
        assert breach["trace_id"] == tr.trace_id   # originating trace

        # readiness names the hurting subsystems
        ready = await api._admin_readiness()
        assert ready["status"] == "degraded"
        assert ready["checks"]["backend"]["status"] == "degraded"
        assert ready["slo"]["verify_success_ratio"]["breached"]
        assert ready["backend"]["state"] == "tripped"

        # flight-recorder endpoint serves the ring (and tails)
        fr = await api._admin_flight_recorder(query={"last": "5"})
        assert 0 < len(fr["data"]) <= 5

        # recovery: breaker re-closes -> supervisor READY -> slo
        # window recovers -> 200 again
        br.record_success()
        assert sup.backend_state == "ready"
        bad["good"] = bad["total"] = 1150.0   # 1000 new, all good
        node.slo.tick()
        assert (await api._health())[2] == 200

        # a DOWN check on the live registry is a 503
        forced = {"s": DOWN}
        node.health.register("forced",
                             lambda: CheckResult(forced["s"], "test"))
        assert (await api._health())[2] == 503
        forced["s"] = UP
        assert (await api._health())[2] == 200

    asyncio.run(run())
