"""Discovery peer exchange, subnet management, doppelganger detection,
milestone routing."""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio

import pytest

from teku_tpu.networking import NetworkedNode
from teku_tpu.networking.discovery import DiscoveryService
from teku_tpu.networking.subnets import AttestationSubnetManager
from teku_tpu.spec import config as C, create_spec, Spec
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.spec.milestones import SpecMilestone
from teku_tpu.validator.doppelganger import (DoppelgangerDetected,
                                             DoppelgangerDetector)


def test_discovery_learns_peers_transitively():
    """A knows B, B knows C; discovery connects A to C."""
    async def run():
        spec = create_spec("minimal")
        state, _ = interop_genesis(spec.config, 8)
        a, b, c = (NetworkedNode(spec, state, name=n) for n in "abc")
        for n in (a, b, c):
            await n.start()
        discos = []
        try:
            for n in (a, b, c):
                d = DiscoveryService(n.net, target_peers=5)
                d.install()
                discos.append(d)
            await a.connect(b)
            await b.connect(c)
            assert len(a.net.peers) == 1
            await discos[0]._round()       # one discovery sweep on A
            await asyncio.sleep(0.05)
            ports = {p.listen_port for p in a.net.peers}
            assert c.net.port in ports, "A did not learn C from B"
        finally:
            for n in (a, b, c):
                await n.stop()
    asyncio.run(run())


def test_subnet_manager_windows_and_persistent():
    mgr = AttestationSubnetManager(C.MINIMAL, b"\x05" * 32)
    persistent = mgr.persistent_subnets()
    assert persistent and all(
        0 <= s < C.MINIMAL.ATTESTATION_SUBNET_COUNT for s in persistent)
    # same node id -> same persistent subnets (deterministic)
    assert persistent == AttestationSubnetManager(
        C.MINIMAL, b"\x05" * 32).persistent_subnets()
    mgr.subscribe_for_duty(subnet=7, until_slot=10)
    assert 7 in mgr.on_slot(10)
    assert 7 not in mgr.on_slot(11) or 7 in persistent


def test_doppelganger_detects_and_clears():
    hits = []
    det = DoppelgangerDetector([3, 4], detection_epochs=2,
                               on_detected=hits.append)
    det.begin(current_epoch=10)
    assert not det.on_epoch(10)
    det.observe_attesters([1, 2])          # others are fine
    with pytest.raises(DoppelgangerDetected):
        det.observe_attesters([2, 3])      # our index 3 seen!
    assert hits == [3]
    assert not det.on_epoch(12)            # never clears after detection

    ok = DoppelgangerDetector([5], detection_epochs=2)
    ok.begin(10)
    ok.observe_attesters([1, 2])
    assert not ok.on_epoch(11)
    assert ok.on_epoch(12)                 # clean window -> cleared


def test_milestone_routing():
    spec = Spec(C.MINIMAL)
    assert spec.milestone_at_slot(0) is SpecMilestone.PHASE0
    assert spec.milestone_at_slot(10 ** 6) is SpecMilestone.PHASE0
    v = spec.at_slot(5)
    assert v.fork_version == C.MINIMAL.GENESIS_FORK_VERSION
    assert spec.fork_schedule.fork_at_epoch(3)[2] == 0
    assert SpecMilestone.DENEB.is_at_least(SpecMilestone.ALTAIR)
    assert not SpecMilestone.PHASE0.is_at_least(SpecMilestone.ALTAIR)
