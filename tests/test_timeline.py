"""The unified causal timeline (infra/timeline.py + infra/clock.py +
infra/schema.py): one clock spine, three exports.

Pinned here: schema v1 (the envelope shared by doctor and the
timeline), the three-way join by trace id, Perfetto trace-event
validity, the gap-free span-tree invariant on a REAL in-process
dispatch, the TEKU_TPU_TIMELINE=0 instrumentation-free path, the
self-measured overhead, the doctor's host_prep_serial/overlap_stall
analyzers, and one-WARN degradation for every new knob."""

import asyncio
import logging

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.infra import clock, doctor, env, schema, timeline, tracing
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.services.signatures import (
    AggregatingSignatureVerificationService)

SKS = [keygen(bytes([60 + i]) * 32) for i in range(4)]
PKS = [bls.secret_to_public_key(sk) for sk in SKS]


# --------------------------------------------------------------------------
# schema v1 — ONE versioning helper for doctor + timeline
# --------------------------------------------------------------------------

def test_schema_v1_is_pinned():
    assert schema.VERSIONS == {"doctor": 1, "timeline": 1,
                               "perfetto": 1}
    env_ = schema.envelope("timeline", {"body": 1})
    assert env_["schema"] == "timeline" and env_["version"] == 1
    assert env_["body"] == 1
    with pytest.raises(KeyError):
        schema.envelope("unknown", {})


def test_doctor_and_timeline_share_the_envelope():
    diag = doctor.diagnose([])
    assert diag["schema"] == "doctor" and diag["version"] == 1
    joined = timeline.join("t-x")
    assert joined["schema"] == "timeline" and joined["version"] == 1


def test_clock_spine_anchor_round_trips():
    t_wall, t_mono = clock.now()
    assert abs(clock.wall_of(t_mono) - t_wall) < 0.05
    assert abs(clock.mono_of(t_wall) - t_mono) < 0.05
    rec = clock.stamp({})
    assert set(rec) >= {"t_wall", "t_mono"}
    anchor = clock.anchor_dict()
    assert set(anchor) == {"t_wall", "t_mono"}


# --------------------------------------------------------------------------
# attribution metrics (pure interval arithmetic)
# --------------------------------------------------------------------------

def _ev(phase, t_mono, dur_s=0.0, **kw):
    return {"seq": 0, "track": "worker", "phase": phase,
            "t_mono": t_mono, "dur_s": dur_s, "trace_id": "", **kw}


def test_attribution_overlap_and_serial_shares():
    events = [
        _ev("queue_nonempty", 10.0, 1.0),
        _ev("busy", 10.0, 0.4),         # 0.4 of 1.0 nonempty covered
        _ev("host_prep", 10.5, 0.2),    # fully outside busy → serial
    ]
    out = timeline.attribution(events, 10.0, 12.0,
                               stage_sums={"queue_wait": 1.0,
                                           "complete": 4.0},
                               compile_s=0.5)
    assert out["overlap_efficiency"] == pytest.approx(0.4)
    assert out["host_prep_serial_share"] == pytest.approx(0.1)
    assert out["queue_wait_share"] == pytest.approx(0.25)
    assert out["compile_wall_share"] == pytest.approx(0.25)
    assert out["events"] == 3


def test_attribution_missing_inputs_come_back_none():
    out = timeline.attribution([], 0.0, 1.0)
    assert out["overlap_efficiency"] is None
    assert out["host_prep_serial_share"] is None
    assert out["queue_wait_share"] is None
    assert out["compile_wall_share"] is None


def test_stalls_are_nonempty_minus_busy():
    events = [_ev("queue_nonempty", 5.0, 2.0),
              _ev("busy", 5.5, 0.5)]
    gaps = timeline.stalls(events)
    assert gaps == [(5.0, 5.5), (6.0, 7.0)]


# --------------------------------------------------------------------------
# the three-way join
# --------------------------------------------------------------------------

def _trace_dict(trace_id="t-join", t_mono=100.0, total_ms=10.0,
                stages=None):
    return {"trace_id": trace_id, "name": "verify", "labels": {},
            "t_wall": clock.wall_of(t_mono), "t_mono": t_mono,
            "total_ms": total_ms, "stages": stages or []}


def test_join_filters_every_ring_by_trace_id():
    traces = [_trace_dict("t-join"), _trace_dict("t-other")]
    records = [{"seq": 1, "trace_ids": ["t-join"], "t_mono": 100.0},
               {"seq": 2, "trace_ids": ["t-other"]}]
    flight = [{"seq": 7, "kind": "slo_breach", "trace_id": "t-join",
               "t_mono": 100.2},
              {"seq": 8, "kind": "slo_breach", "trace_id": "zzz"}]
    ring = [_ev("busy", 100.0, 0.01, trace_id="t-join"),
            _ev("busy", 100.0, 0.01, trace_id="t-other")]
    out = timeline.join("t-join", traces, records, flight, ring)
    assert out["trace_id"] == "t-join"
    assert out["tree"]["trace_id"] == "t-join"
    assert [r["seq"] for r in out["records"]] == [1]
    assert [e["seq"] for e in out["flight"]] == [7]
    assert len(out["ring"]) == 1
    assert set(out["anchor"]) == {"t_wall", "t_mono"}
    # unknown trace id: honest empty join, not an error
    missing = timeline.join("t-none", traces, records, flight, ring)
    assert missing["tree"] is None and missing["records"] == []


# --------------------------------------------------------------------------
# span trees: gap-free by construction
# --------------------------------------------------------------------------

def _assert_gap_free(node):
    """Every node's children tile it EXACTLY: contiguous starts, and
    the last child ends at the parent's end."""
    children = node["children"]
    if not children:
        return
    cursor = node["t_mono"]
    for child in children:
        assert abs(child["t_mono"] - cursor) <= 2e-6, \
            f"hole before {child['phase']} in {node['phase']}"
        cursor = child["t_mono"] + child["dur_ms"] / 1e3
    parent_end = node["t_mono"] + node["dur_ms"] / 1e3
    assert abs(cursor - parent_end) <= 2e-6 + timeline.RESOLUTION_S
    for child in children:
        _assert_gap_free(child)


def test_span_tree_nests_fills_and_tiles():
    tr = _trace_dict(total_ms=10.0, stages=[
        {"stage": "dispatch", "ms": 6.0, "t_mono": 100.002},
        {"stage": "host_prep", "ms": 2.0, "t_mono": 100.003},
        # starts 0.02 ms before host_prep's end: a sub-resolution
        # seam that must SNAP, not synthesize a filler node
        {"stage": "device_sync", "ms": 2.5, "t_mono": 100.00498},
    ])
    tree = timeline.span_tree(tr)
    phases = [c["phase"] for c in tree["children"]]
    # the pre-dispatch hole and the post-dispatch tail are explicit
    assert phases == ["unattributed", "dispatch", "unattributed"]
    dispatch = tree["children"][1]
    assert [c["phase"] for c in dispatch["children"]] == [
        "unattributed", "host_prep", "device_sync", "unattributed"]
    _assert_gap_free(tree)


def test_span_tree_on_a_real_in_process_dispatch():
    """End-to-end: a verification through the aggregating service
    (pure-python provider) yields a trace whose span tree is gap-free
    and whose dispatch actually hit the timeline ring."""
    prev_tracing = tracing.enabled()
    prev_timeline = timeline.enabled()
    tracing.set_enabled(True)
    timeline.set_enabled(True)
    mark = timeline.RING.mark()

    async def main():
        svc = AggregatingSignatureVerificationService(
            num_workers=1, registry=MetricsRegistry())
        await svc.start()
        tr = tracing.new_trace("verify_test")
        msg = b"timeline-e2e"
        sig = bls.sign(SKS[0], msg)
        with tracing.attach((tr,)):
            ok = await svc.verify([PKS[0]], msg, sig)
        tracing.finish(tr)
        await svc.stop()
        return ok, tr

    try:
        ok, tr = asyncio.run(main())
    finally:
        tracing.set_enabled(prev_tracing)
        timeline.set_enabled(prev_timeline)
    assert ok
    doc = tr.to_dict()
    assert doc["t_mono"] > 0
    stages = {s["stage"] for s in doc["stages"]}
    assert "dispatch" in stages
    assert all("t_mono" in s for s in doc["stages"])
    tree = timeline.span_tree(doc)
    assert tree["children"], "no spans nested under the trace"
    _assert_gap_free(tree)
    # the service's queue instrumentation reached the shared ring
    ring = timeline.RING.snapshot(since_seq=mark)
    assert any(e["phase"] == "queue_nonempty" for e in ring)


# --------------------------------------------------------------------------
# Perfetto export
# --------------------------------------------------------------------------

def test_perfetto_events_validate_and_declare_tracks():
    traces = [_trace_dict(stages=[
        {"stage": "dispatch", "ms": 8.0, "t_mono": 100.001},
        {"stage": "device_sync", "ms": 3.0, "t_mono": 100.004}])]
    records = [{"seq": 3, "trace_ids": ["t-join"], "t_mono": 100.0,
                "shape": "256x2", "admission": {"plan": {
                    "mode": "steady"}},
                "compile": {"outcome": "cache_hit",
                            "enqueue_s": 0.004},
                "device": {"sync_s": 0.003}}]
    flight = [{"seq": 9, "kind": "brownout_enter",
               "trace_id": "t-join", "t_mono": 100.001}]
    ring = [_ev("coalesce", 100.002, trace_id="t-join"),
            _ev("busy", 100.003, 0.004, trace_id="t-join",
                track="device")]
    events = timeline.perfetto(traces, records, flight, ring)
    tracks = {e["args"]["name"] for e in events
              if e["ph"] == "M" and e["name"] == "thread_name"}
    assert tracks == timeline.TRACKS and len(tracks) >= 4
    for e in events:
        assert e["ph"] in ("M", "X", "i", "b", "e")
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert e["pid"] == 1 and isinstance(e["tid"], int)
        assert "cat" in e and "name" in e
        if e["ph"] == "X":
            assert e["dur"] >= 0
        if e["ph"] == "i":
            assert e["s"] == "t"
    # async arrows: coalesce and overlap pairs are id-matched b/e
    for cat in ("coalesce", "overlap"):
        pairs = [e for e in events if e["ph"] in ("b", "e")
                 and e["cat"] == cat]
        assert pairs and len(pairs) % 2 == 0
        by_id = {}
        for e in pairs:
            by_id.setdefault(e["id"], []).append(e["ph"])
        assert all(sorted(v) == ["b", "e"] for v in by_id.values())
    body = [e for e in events if e["ph"] != "M"]
    assert body == sorted(body, key=lambda e: (e["ts"], e["tid"]))


# --------------------------------------------------------------------------
# disabled mode + self-measurement
# --------------------------------------------------------------------------

def test_timeline_disabled_is_instrumentation_free():
    prev = timeline.enabled()
    mark = timeline.RING.mark()
    try:
        timeline.set_enabled(False)
        assert timeline.interval("worker", "host_prep", 0.01) is None
        assert timeline.instant("worker", "coalesce") is None
        assert timeline.RING.snapshot(since_seq=mark) == []
        timeline.set_enabled(True)
        ev = timeline.interval("worker", "host_prep", 0.01)
        assert ev is not None and ev["seq"] > mark
    finally:
        timeline.set_enabled(prev)


def test_measure_overhead_is_scratch_and_bounded():
    mark = timeline.RING.mark()
    out = timeline.measure_overhead(n=500)
    assert out["events"] == 500
    assert out["per_event_us"] > 0
    # the self-measurement must not pollute the live ring
    assert timeline.RING.snapshot(since_seq=mark) == []
    # sanity ceiling: a stamp is a dict build + deque append; even on
    # a loaded 1-core box it stays far under a millisecond
    assert out["per_event_us"] < 1000


def test_ring_is_bounded_and_markable():
    ring = timeline.TimelineRing(capacity=4)
    for i in range(10):
        ring.append({"t_mono": float(i), "trace_id": "t",
                     "phase": "busy", "track": "device",
                     "dur_s": 0.0})
    snap = ring.snapshot()
    assert len(snap) == 4 and snap[-1]["seq"] == 10
    assert ring.snapshot(last=2)[0]["seq"] == 9
    assert ring.snapshot(since_seq=8)[0]["seq"] == 9
    assert ring.snapshot(trace_id="nope") == []


# --------------------------------------------------------------------------
# doctor analyzers
# --------------------------------------------------------------------------

def _tl(traces=None, events=None):
    return {"traces": traces or [], "events": events or []}


def test_doctor_host_prep_serial_cites_the_worst_dispatch():
    rec = {"seq": 11, "trace_ids": ["t-hp"], "shape": "256x2",
           "lanes": 256, "t_mono": 100.0}
    tr = _trace_dict("t-hp", total_ms=100.0, stages=[
        {"stage": "host_prep", "ms": 60.0, "t_mono": 100.001}])
    diag = doctor.diagnose([rec], timeline=_tl(traces=[tr]))
    f = next(x for x in diag["findings"]
             if x["kind"] == "host_prep_serial")
    assert f["evidence"][0]["seq"] == 11
    assert f["evidence"][0]["trace_id"] == "t-hp"
    assert f["metrics"]["share"] == pytest.approx(0.6)
    assert f["metrics"]["lanes"] == 256
    # small batches never trip it: host_prep dominating a 1-lane
    # verify is expected, not a finding
    small = dict(rec, lanes=4)
    diag = doctor.diagnose([small], timeline=_tl(traces=[tr]))
    assert not [x for x in diag["findings"]
                if x["kind"] == "host_prep_serial"]


def test_doctor_overlap_stall_cites_the_gap():
    events = [_ev("queue_nonempty", 100.0, 1.0),
              _ev("busy", 100.0, 0.3, track="device")]
    rec = {"seq": 21, "trace_ids": ["t-st"], "shape": "256x2",
           "t_mono": 100.5}
    diag = doctor.diagnose([rec], timeline=_tl(events=events))
    f = next(x for x in diag["findings"]
             if x["kind"] == "overlap_stall")
    assert f["metrics"]["stall_share"] == pytest.approx(0.7)
    assert f["metrics"]["worst_gap"]["dur_s"] == pytest.approx(0.7)
    assert f["evidence"][0]["seq"] == 21
    assert diag["inputs"]["timeline"] is True
    # a well-overlapped window is quiet
    good = [_ev("queue_nonempty", 100.0, 1.0),
            _ev("busy", 100.0, 0.95, track="device")]
    diag = doctor.diagnose([], timeline=_tl(events=good))
    assert not [x for x in diag["findings"]
                if x["kind"] == "overlap_stall"]


# --------------------------------------------------------------------------
# knob hygiene: garbage degrades with ONE WARN, never a boot failure
# --------------------------------------------------------------------------

def test_garbage_timeline_knobs_degrade_with_one_warn(monkeypatch,
                                                      caplog):
    monkeypatch.setenv("TEKU_TPU_TIMELINE", "sideways")
    monkeypatch.setenv("TEKU_TPU_TIMELINE_RING", "garbage!!")
    env._reset_warnings()
    with caplog.at_level(logging.WARNING, logger="teku_tpu.infra.env"):
        assert env.env_bool("TEKU_TPU_TIMELINE", True) is True
        ring = timeline.TimelineRing()
        assert ring.capacity == 4096        # default survived
        timeline.TimelineRing()             # second read: no new WARN
    for knob in ("TEKU_TPU_TIMELINE", "TEKU_TPU_TIMELINE_RING"):
        warns = [r for r in caplog.records
                 if r.getMessage().startswith(knob + " ")]
        assert len(warns) == 1, knob


def test_garbage_doctor_knobs_degrade_with_one_warn(monkeypatch,
                                                    caplog):
    monkeypatch.setenv("TEKU_TPU_DOCTOR_HOST_PREP_SHARE", "garbage!!")
    monkeypatch.setenv("TEKU_TPU_DOCTOR_OVERLAP_STALL", "2.5")
    env._reset_warnings()
    with caplog.at_level(logging.WARNING, logger="teku_tpu.infra.env"):
        diag = doctor.diagnose([], timeline=_tl())
        doctor.diagnose([], timeline=_tl())
    assert diag["healthy"]
    for knob in ("TEKU_TPU_DOCTOR_HOST_PREP_SHARE",
                 "TEKU_TPU_DOCTOR_OVERLAP_STALL"):
        warns = [r for r in caplog.records if knob in r.getMessage()]
        assert len(warns) == 1, knob
