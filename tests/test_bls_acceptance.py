"""The BLS acceptance gate: all ten official suite types, run against
BOTH providers (pure oracle + JAX kernel) with cross-provider parity.

Mirrors the reference's eth2 BLS reference-test matrix (reference:
eth-reference-tests/src/referenceTest/java/tech/pegasys/teku/reference/
phase0/bls/BlsTests.java:23-36 — verify, batch_verify, aggregate,
aggregate_verify, sign, fast_aggregate_verify, eth_aggregate_pubkeys,
eth_fast_aggregate_verify, deserialization_G1, deserialization_G2).
The official vector archives are downloaded at build time upstream and
are not available offline, so the cases here are CONSTRUCTED to cover
the same edge surface: the deserialization suites systematically build
malformed/non-curve/non-subgroup/infinity encodings, which is exactly
what targets the device decompression path (ops/points.py
g1/g2_recover_y).
"""

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import curve as C
from teku_tpu.crypto.bls import fields as F
from teku_tpu.crypto.bls import keygen
from teku_tpu.crypto.bls.constants import P, R
from teku_tpu.crypto.bls.pure_impl import (G1_INFINITY, G2_INFINITY,
                                           PureBls12381)
from teku_tpu.ops.provider import JaxBls12381

PURE = PureBls12381()
JAX_IMPL = JaxBls12381()

SKS = [keygen(bytes([i + 1]) * 32) for i in range(8)]
PKS = [PURE.secret_key_to_public_key(sk) for sk in SKS]
MSGS = [b"acceptance-%d" % i for i in range(8)]
SIGS = [PURE.sign(sk, m) for sk, m in zip(SKS, MSGS)]

both = pytest.mark.parametrize("impl", [PURE, JAX_IMPL],
                               ids=["pure", "jax"])


# -- suite 1: sign ---------------------------------------------------------

def test_sign_cross_provider_parity():
    for sk, m in zip(SKS[:3], MSGS[:3]):
        assert JAX_IMPL.sign(sk, m) == PURE.sign(sk, m)
    with pytest.raises(ValueError):
        PURE.sign(0, b"m")          # zero key prohibited
    with pytest.raises(ValueError):
        PURE.sign(R, b"m")          # key == r prohibited


# -- suite 2: verify -------------------------------------------------------

@both
def test_verify_suite(impl):
    assert impl.verify(PKS[0], MSGS[0], SIGS[0])
    assert not impl.verify(PKS[0], MSGS[1], SIGS[0])      # wrong msg
    assert not impl.verify(PKS[1], MSGS[0], SIGS[0])      # wrong key
    assert not impl.verify(PKS[0], MSGS[0], SIGS[1])      # wrong sig
    assert not impl.verify(G1_INFINITY, MSGS[0], SIGS[0])
    assert not impl.verify(PKS[0], MSGS[0], G2_INFINITY)
    assert not impl.verify(PKS[0][:-1], MSGS[0], SIGS[0])
    assert not impl.verify(PKS[0], MSGS[0], SIGS[0][:-1])


# -- suite 3: aggregate ----------------------------------------------------

@both
def test_aggregate_suite(impl):
    agg = impl.aggregate_signatures(SIGS[:3])
    assert agg == PURE.aggregate_signatures(SIGS[:3])
    with pytest.raises(ValueError):
        impl.aggregate_signatures([])


# -- suite 4: aggregate_verify --------------------------------------------

@both
def test_aggregate_verify_suite(impl):
    agg = PURE.aggregate_signatures(SIGS[:3])
    assert impl.aggregate_verify(PKS[:3], MSGS[:3], agg)
    assert not impl.aggregate_verify(PKS[:3], list(reversed(MSGS[:3])),
                                     agg)
    assert not impl.aggregate_verify(PKS[:2], MSGS[:2], agg)
    assert not impl.aggregate_verify([], [], agg)
    # infinity pubkey poisoning
    assert not impl.aggregate_verify([PKS[0], G1_INFINITY],
                                     MSGS[:2], agg)


# -- suite 5: fast_aggregate_verify ---------------------------------------

@both
def test_fast_aggregate_verify_suite(impl):
    sigs = [PURE.sign(sk, b"same message") for sk in SKS]
    agg = PURE.aggregate_signatures(sigs)
    assert impl.fast_aggregate_verify(PKS, b"same message", agg)
    assert not impl.fast_aggregate_verify(PKS[:-1], b"same message", agg)
    assert not impl.fast_aggregate_verify(PKS, b"other", agg)
    assert not impl.fast_aggregate_verify([], b"same message", agg)
    assert not impl.fast_aggregate_verify([G1_INFINITY] + PKS[1:],
                                          b"same message", agg)


@pytest.mark.slow
def test_fast_aggregate_verify_512_keys():
    """The sync-committee shape (BASELINE measurement config 3)."""
    import random
    rng = random.Random(1)
    sks = [keygen(rng.randbytes(32)) for _ in range(512)]
    pks = [PURE.secret_key_to_public_key(sk) for sk in sks]
    msg = b"sync committee root"
    agg = PURE.aggregate_signatures([PURE.sign(sk, msg) for sk in sks])
    assert JAX_IMPL.fast_aggregate_verify(pks, msg, agg)
    assert not JAX_IMPL.fast_aggregate_verify(pks, b"wrong", agg)


# -- suite 6: batch_verify -------------------------------------------------

@both
def test_batch_verify_suite(impl):
    triples = [([PKS[i]], MSGS[i], SIGS[i]) for i in range(4)]
    assert impl.batch_verify(triples)
    bad = list(triples)
    bad[2] = ([PKS[2]], b"tampered", SIGS[2])
    assert not impl.batch_verify(bad)


# -- suite 7: eth_aggregate_pubkeys ---------------------------------------

def test_eth_aggregate_pubkeys_suite():
    agg = bls.eth_aggregate_pubkeys(PKS[:3])
    assert bls.public_key_is_valid(agg)
    with pytest.raises(ValueError):
        bls.eth_aggregate_pubkeys([])
    with pytest.raises(ValueError):
        bls.eth_aggregate_pubkeys([G1_INFINITY])
    with pytest.raises(ValueError):
        bls.eth_aggregate_pubkeys([PKS[0], b"\x00" * 48])


# -- suite 8: eth_fast_aggregate_verify -----------------------------------

def test_eth_fast_aggregate_verify_suite():
    assert bls.eth_fast_aggregate_verify([], b"x", G2_INFINITY)
    assert not bls.eth_fast_aggregate_verify([], b"x", SIGS[0])
    sigs = [PURE.sign(sk, b"m") for sk in SKS[:2]]
    agg = PURE.aggregate_signatures(sigs)
    assert bls.eth_fast_aggregate_verify(PKS[:2], b"m", agg)


# -- suites 9+10: deserialization edge vectors ----------------------------

def _g1_vectors():
    """(bytes, expect_valid) targeting every decompression branch."""
    good = PKS[0]
    x = int.from_bytes(good, "big") & ((1 << 381) - 1)
    cases = [
        (good, True),
        (b"", False),
        (good[:-1], False),                       # 47 bytes
        (good + b"\x00", False),                  # 49 bytes
        (b"\x00" * 48, False),                    # no flags
        # canonical infinity DECODES but KeyValidate rejects the
        # identity pubkey (IETF BLS KeyValidate; the reference's
        # deserialization_G1 infinity cases land the same way through
        # BlstPublicKey's validation)
        (b"\xc0" + b"\x00" * 47, False),
        (b"\xc0" + b"\x01" + b"\x00" * 46, False),  # infinity w/ data
        (b"\x80" + b"\x00" * 47, False),          # inf flag w/o comp
        (bytes([good[0] & 0x3F]) + good[1:], False),  # comp bit clear
        # infinity flag set on a non-infinity encoding
        (bytes([good[0] | 0x40]) + good[1:], False),
        # x >= p
        (bytes([0x80 | 0x20]) + (P).to_bytes(48, "big")[1:], False),
    ]
    # non-curve x: find x with no y^2 solution
    from teku_tpu.crypto.bls import fields as FF
    xx = 5
    while True:
        rhs = (pow(xx, 3, P) + 4) % P
        if pow(rhs, (P - 1) // 2, P) != 1:
            break
        xx += 1
    bad_x = bytearray(xx.to_bytes(48, "big"))
    bad_x[0] |= 0x80
    cases.append((bytes(bad_x), False))
    # on-curve but NON-SUBGROUP point
    xx = 3
    while True:
        rhs = (pow(xx, 3, P) + 4) % P
        if pow(rhs, (P - 1) // 2, P) == 1:
            y = pow(rhs, (P + 1) // 4, P)
            pt = (xx, y, 1)
            if not C.g1_in_subgroup(pt):
                cases.append((C.g1_compress(pt), False))
                break
        xx += 1
    return cases


def _g2_vectors():
    good = SIGS[0]
    cases = [
        (good, True),
        (good[:-1], False),
        (b"\x00" * 96, False),
        (b"\xc0" + b"\x00" * 95, True),           # canonical infinity
        (b"\xc0" + b"\x00" * 94 + b"\x01", False),
        (bytes([good[0] & 0x3F]) + good[1:], False),
        # x_c1 >= p
        (bytes([0x80 | 0x1F]) + b"\xff" * 47 + b"\x00" * 48, False),
    ]
    # on-curve non-subgroup G2 point
    import random
    rng = random.Random(7)
    while True:
        x = (rng.randrange(P), rng.randrange(P))
        rhs = F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), (4, 4))
        y = F.fq2_sqrt(rhs)
        if y is None:
            continue
        pt = (x, y, F.FQ2_ONE)
        if not C.g2_in_subgroup(pt):
            cases.append((C.g2_compress(pt), False))
            break
    return cases


def test_deserialization_g1_pure_and_jax_agree():
    for data, expect in _g1_vectors():
        assert PURE.public_key_is_valid(data) == expect, data.hex()
        assert JAX_IMPL.public_key_is_valid(data) == expect, (
            f"jax disagrees on {data.hex()}")


def test_deserialization_g2_pure_and_jax_agree():
    for data, expect in _g2_vectors():
        assert PURE.signature_is_valid(data) == expect, data.hex()
        # the device path: a bad signature must fail verify, a good one
        # must at least parse (wrong-key verify returns False cleanly)
        verdict = JAX_IMPL.verify(PKS[0], b"probe", data)
        if not expect:
            assert verdict is False
