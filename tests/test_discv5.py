"""UDP discovery: signed records, routing table, PING/FINDNODE walk,
and the dial feed into the TCP layer.

reference: networking/p2p/.../discovery/discv5/DiscV5Service.java:57.
"""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio
import secrets

import pytest

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey)

from teku_tpu.networking import discv5 as D

FORK = b"\xaa\xbb\xcc\xdd"


def _record(seq=1, fork=FORK, **kw):
    identity = Ed25519PrivateKey.generate()
    return identity, D.make_record(
        identity, noise_pub=b"\x01" * 32, fork_digest=fork,
        ip="127.0.0.1", udp_port=kw.get("udp_port", 9),
        tcp_port=kw.get("tcp_port", 10), seq=seq)


def test_record_roundtrip_and_tamper_rejected():
    identity, record = _record()
    raw = record.encode()
    decoded = D.NodeRecord.decode(raw)
    assert decoded == record
    assert decoded.node_id == record.node_id
    tampered = bytearray(raw)
    tampered[76] ^= 1                 # flip a port bit
    with pytest.raises(ValueError):
        D.NodeRecord.decode(bytes(tampered))
    # forged signature over modified content also fails
    other, _ = _record()
    forged = record.__dict__ | {"signature": other.sign(b"junk" * 16)}
    with pytest.raises(ValueError):
        D.NodeRecord(**forged).verify()


def test_routing_table_seq_and_bucket_rules():
    _, own = _record()
    table = D.RoutingTable(own.node_id, k=2)
    identity, rec = _record(seq=1)
    assert table.add(rec)
    assert not table.add(rec)                 # same seq: no-op
    newer = D.make_record(identity, rec.noise_pub, rec.fork_digest,
                          rec.ip, rec.udp_port, 99, seq=2)
    assert table.add(newer)                   # seq bump updates
    assert table._by_id[rec.node_id].tcp_port == 99
    assert not table.add(own.__class__(**own.__dict__))  # self
    # closest() orders by XOR distance
    for _ in range(6):
        table.add(_record()[1])
    target = secrets.token_bytes(32)
    ordered = table.closest(target)
    dists = [D._distance(r.node_id, target) for r in ordered]
    assert dists == sorted(dists)


def test_three_node_walk_discovers_transitively():
    """A knows only B; C is known only to B.  One lookup makes A learn
    C via FINDNODE/NODES, and the dial feed fires."""
    async def run():
        found = []
        a = D.UdpDiscoveryService(fork_digest=FORK, tcp_port=1001,
                                  on_discovered=found.append)
        b = D.UdpDiscoveryService(fork_digest=FORK, tcp_port=1002)
        c = D.UdpDiscoveryService(fork_digest=FORK, tcp_port=1003)
        await a.start()
        await b.start()
        await c.start()
        try:
            # seed: C pings B (B learns C); A pings B
            assert await c.ping(("127.0.0.1", b.port)) is not None
            assert await a.bootstrap([("127.0.0.1", b.port)]) == 1
            await a.lookup(secrets.token_bytes(32))
            ids = {r.node_id for r in a.table.records()}
            assert b.record.node_id in ids
            assert c.record.node_id in ids
            # the dial feed carries the tcp endpoint + noise identity
            assert any(r.tcp_port == 1003 for r in found)
            # B reciprocally learned A from the FINDNODE it served
            assert a.record.node_id in {r.node_id
                                        for r in b.table.records()}
        finally:
            await a.stop()
            await b.stop()
            await c.stop()
    asyncio.run(run())


def test_wrong_fork_records_never_enter_the_table():
    async def run():
        a = D.UdpDiscoveryService(fork_digest=FORK)
        b = D.UdpDiscoveryService(fork_digest=b"\x00\x00\x00\x00")
        await a.start()
        await b.start()
        try:
            assert await b.ping(("127.0.0.1", a.port)) is None
            assert len(a.table) == 0
            assert len(b.table) == 0
        finally:
            await a.stop()
            await b.stop()
    asyncio.run(run())


def test_liveness_round_evicts_dead_nodes():
    async def run():
        a = D.UdpDiscoveryService(fork_digest=FORK)
        b = D.UdpDiscoveryService(fork_digest=FORK)
        await a.start()
        await b.start()
        assert await a.ping(("127.0.0.1", b.port)) is not None
        assert len(a.table) == 1
        await b.stop()                # b goes dark
        await a._liveness_round()
        assert len(a.table) == 0
        await a.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_networked_nodes_find_each_other_over_udp():
    """Two full nodes with only a UDP bootnode address end up
    TCP-connected (noise + hello) without any explicit dial."""
    from teku_tpu.networking import NetworkedNode
    from teku_tpu.spec import create_spec
    from teku_tpu.spec.genesis import interop_genesis

    async def run():
        spec = create_spec("minimal")
        state, _ = interop_genesis(spec.config, 8)
        a = NetworkedNode(spec, state, name="a", udp_discovery_port=0)
        await a.start()
        b = NetworkedNode(spec, state, name="b", udp_discovery_port=0,
                          bootnodes=[f"127.0.0.1:{a.discv5.port}"])
        await b.start()
        try:
            for _ in range(60):
                if a.net.peers and b.net.peers:
                    break
                await asyncio.sleep(0.1)
            assert a.net.peers and b.net.peers
            assert a.net.peers[0].node_id == b.net.node_id
        finally:
            await b.stop()
            await a.stop()
    asyncio.run(run())
