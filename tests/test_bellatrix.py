"""Bellatrix: full fork ladder phase0→altair→bellatrix under full
verification, execution-payload processing, merge transition."""

import dataclasses

import pytest

from teku_tpu.spec import config as C
from teku_tpu.spec import helpers as H
from teku_tpu.spec.bellatrix import block as BB
from teku_tpu.spec.bellatrix.datastructures import (
    get_bellatrix_schemas, payload_to_header)
from teku_tpu.spec.builder import (make_local_signer, produce_attestations,
                                   produce_block)
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.spec.milestones import build_fork_schedule, SpecMilestone
from teku_tpu.spec.transition import process_slots, state_transition
from teku_tpu.spec.verifiers import SIMPLE

CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=1,
                          BELLATRIX_FORK_EPOCH=2)


@pytest.mark.slow
def test_full_fork_ladder_finalizes():
    state, sks = interop_genesis(CFG, 32)
    signer = make_local_signer(dict(enumerate(sks)))
    atts = []
    cur = state
    S = get_bellatrix_schemas(CFG)
    for slot in range(1, 5 * CFG.SLOTS_PER_EPOCH + 1):
        signed, post = produce_block(CFG, cur, slot, signer,
                                     attestations=atts)
        verified = state_transition(CFG, cur, signed,
                                    validate_result=True)
        assert verified.htr() == post.htr(), f"divergence at slot {slot}"
        atts = produce_attestations(CFG, post, slot,
                                    signed.message.htr(), signer)
        cur = post
    assert isinstance(cur, S.BeaconState)
    assert cur.fork.current_version == CFG.BELLATRIX_FORK_VERSION
    assert cur.fork.previous_version == CFG.ALTAIR_FORK_VERSION
    assert cur.finalized_checkpoint.epoch >= 3
    # pre-merge: empty payload header throughout
    assert not BB.is_merge_transition_complete(cur)


def test_milestone_schedule_three_forks():
    sched = build_fork_schedule(CFG)
    assert sched.milestone_at_epoch(0) is SpecMilestone.PHASE0
    assert sched.milestone_at_epoch(1) is SpecMilestone.ALTAIR
    assert sched.milestone_at_epoch(2) is SpecMilestone.BELLATRIX
    assert sched.milestone_at_epoch(500) is SpecMilestone.BELLATRIX


def test_payload_header_roundtrip():
    S = get_bellatrix_schemas(CFG)
    payload = S.ExecutionPayload(
        parent_hash=b"\x01" * 32, block_hash=b"\x02" * 32,
        block_number=7, gas_limit=30_000_000, timestamp=12,
        transactions=(b"\xaa\xbb", b"\xcc" * 40))
    header = payload_to_header(payload)
    assert header.block_hash == payload.block_hash
    assert header.block_number == 7
    # transactions_root is the list HTR, not zero
    assert header.transactions_root != bytes(32)


@pytest.mark.slow
def test_merge_transition_block_processes():
    """A first real payload (correct randao/timestamp) flips the merge
    to complete via the execution-engine seam."""
    state, sks = interop_genesis(CFG, 32)
    signer = make_local_signer(dict(enumerate(sks)))
    cur = state
    atts = []
    for slot in range(1, 2 * CFG.SLOTS_PER_EPOCH + 1):
        signed, cur = produce_block(CFG, cur, slot, signer,
                                    attestations=atts)
        atts = produce_attestations(CFG, cur, slot,
                                    signed.message.htr(), signer)
    assert not BB.is_merge_transition_complete(cur)
    S = get_bellatrix_schemas(CFG)
    slot = cur.slot + 1
    pre = process_slots(CFG, cur, slot)
    payload = S.ExecutionPayload(
        parent_hash=b"\x00" * 32,
        prev_randao=H.get_randao_mix(CFG, pre,
                                     H.get_current_epoch(CFG, pre)),
        timestamp=BB.compute_timestamp_at_slot(CFG, pre, slot),
        block_hash=b"\xEE" * 32,
        block_number=1)
    post = BB.process_execution_payload(CFG, pre, type(
        "B", (), {"execution_payload": payload})(), BB.ACCEPT_ALL_ENGINE)
    assert BB.is_merge_transition_complete(post)
    assert (post.latest_execution_payload_header.block_hash
            == b"\xEE" * 32)
    # wrong randao rejected
    bad = payload.copy_with(prev_randao=b"\x13" * 32)
    with pytest.raises(Exception):
        BB.process_execution_payload(CFG, pre, type(
            "B", (), {"execution_payload": bad})(), BB.ACCEPT_ALL_ENGINE)