"""Deposit lifecycle: execution-chain deposit → merkle tree → block
inclusion with proof → registry entry → activation."""

import dataclasses

import pytest

from teku_tpu.crypto import bls
from teku_tpu.node.deposits import DepositProvider, DepositTree
from teku_tpu.spec import config as C
from teku_tpu.spec import helpers as H
from teku_tpu.spec.builder import make_local_signer, produce_attestations, \
    produce_block
from teku_tpu.spec.datastructures import DepositData, DepositMessage
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.spec.transition import state_transition

CFG = C.MINIMAL


def _deposit_data(cfg, sk, amount=None):
    pk = bls.secret_to_public_key(sk)
    creds = b"\x00" + H.hash32(pk)[1:]
    amount = cfg.MAX_EFFECTIVE_BALANCE if amount is None else amount
    msg = DepositMessage(pubkey=pk, withdrawal_credentials=creds,
                         amount=amount)
    domain = H.compute_domain(C.DOMAIN_DEPOSIT, cfg.GENESIS_FORK_VERSION,
                              bytes(32))
    sig = bls.sign(sk, H.compute_signing_root(msg, domain))
    return DepositData(pubkey=pk, withdrawal_credentials=creds,
                       amount=amount, signature=sig)


def test_deposit_tree_proofs_verify():
    cfg = CFG
    tree = DepositTree()
    datas = [_deposit_data(cfg, 1000 + i) for i in range(5)]
    for d in datas:
        tree.push(d)
    root = tree.root()
    for i, d in enumerate(datas):
        assert H.is_valid_merkle_branch(
            d.htr(), tree.proof(i), cfg.DEPOSIT_CONTRACT_TREE_DEPTH + 1,
            i, root), f"proof {i} failed"
    # proofs bind to the index and the leaf
    assert not H.is_valid_merkle_branch(
        datas[0].htr(), tree.proof(0),
        cfg.DEPOSIT_CONTRACT_TREE_DEPTH + 1, 1, root)


@pytest.mark.slow
def test_new_deposit_joins_and_activates():
    cfg = dataclasses.replace(CFG, SHARD_COMMITTEE_PERIOD=4)
    state, sks = interop_genesis(cfg, 16)
    signer = make_local_signer(dict(enumerate(sks)))
    provider = DepositProvider(cfg)
    # genesis deposits enter the tree so indices line up
    for sk in sks:
        provider.on_deposit(_deposit_data(cfg, sk))
    newcomer_sk = 999_999
    provider.on_deposit(_deposit_data(cfg, newcomer_sk))
    # the chain learns the new deposit root via eth1_data (the voting
    # period is compressed to "already agreed" for the test)
    state = state.copy_with(eth1_data=provider.eth1_data())
    assert state.eth1_data.deposit_count == 17

    deposits = provider.get_deposits_for_block(state)
    assert len(deposits) == 1
    signed, post = produce_block(cfg, state, 1, signer,
                                 deposits=deposits)
    verified = state_transition(cfg, state, signed, validate_result=True)
    assert verified.htr() == post.htr()
    assert len(post.validators) == 17
    newcomer_pk = bls.secret_to_public_key(newcomer_sk)
    assert post.validators[16].pubkey == newcomer_pk
    assert post.balances[16] == cfg.MAX_EFFECTIVE_BALANCE
    assert post.eth1_deposit_index == 17
    # a block OMITTING the due deposit is invalid
    import teku_tpu.spec.block  # noqa
    with pytest.raises(Exception):
        bad, _ = produce_block(cfg, state, 1, signer, deposits=())

    # run ~3 epochs: the newcomer becomes eligible and activates
    cur = state
    atts = []
    for slot in range(1, 4 * cfg.SLOTS_PER_EPOCH + 1):
        dep = provider.get_deposits_for_block(cur)
        signed, cur = produce_block(cfg, cur, slot, signer,
                                    attestations=atts, deposits=dep)
        atts = produce_attestations(cfg, cur, slot,
                                    signed.message.htr(), signer)
    v = cur.validators[16]
    assert v.activation_eligibility_epoch < C.FAR_FUTURE_EPOCH
    assert v.activation_epoch < C.FAR_FUTURE_EPOCH


@pytest.mark.slow
def test_eth1_voting_adopts_new_deposits_on_devnet():
    """End to end without manual eth1_data injection: proposers VOTE
    the provider's deposit root; once a majority of the voting period
    agrees, deposits flow and the newcomer joins the registry."""
    import asyncio
    from teku_tpu.node import Devnet
    from teku_tpu.spec import Spec

    cfg = CFG
    net = Devnet(n_nodes=1, n_validators=16, spec=Spec(cfg))
    node = net.nodes[0]
    provider = DepositProvider(cfg)
    from teku_tpu.spec.genesis import interop_secret_keys
    for sk in interop_secret_keys(16):
        provider.on_deposit(_deposit_data(cfg, sk))
    provider.on_deposit(_deposit_data(cfg, 777_777))
    node.deposit_provider = provider

    async def run():
        await net.start()
        try:
            period = cfg.EPOCHS_PER_ETH1_VOTING_PERIOD \
                * cfg.SLOTS_PER_EPOCH
            await net.run_until_slot(period // 2 + 4)
            state = node.chain.head_state()
            # the vote carried: eth1_data switched to the new root
            assert state.eth1_data.deposit_count == 17
            assert len(state.validators) == 17
            assert state.validators[16].pubkey \
                == bls.secret_to_public_key(777_777)
        finally:
            await net.stop()
    asyncio.run(run())


def test_proofs_snapshot_at_committed_count():
    """A deposit arriving AFTER the committed eth1_data must not break
    the proofs for deposits the state already expects."""
    cfg = CFG
    tree = DepositTree()
    datas = [_deposit_data(cfg, 2000 + i) for i in range(6)]
    for d in datas[:4]:
        tree.push(d)
    committed_root = tree.root()          # snapshot at 4
    for d in datas[4:]:
        tree.push(d)                      # tree grows to 6
    assert tree.count == 6
    # proof for index 3 against the 4-leaf snapshot still verifies
    proof = tree.proof(3, count=4)
    assert H.is_valid_merkle_branch(
        datas[3].htr(), proof, cfg.DEPOSIT_CONTRACT_TREE_DEPTH + 1, 3,
        committed_root)
    # the live-tree proof would NOT (different count mix-in)
    live = tree.proof(3)
    assert not H.is_valid_merkle_branch(
        datas[3].htr(), live, cfg.DEPOSIT_CONTRACT_TREE_DEPTH + 1, 3,
        committed_root)
    # snapshot must bound the index
    import pytest as _pytest
    with _pytest.raises(IndexError):
        tree.proof(5, count=4)
