"""tools/bench_diff.py — the bench regression gate — plus bench.py's
rolling BENCH_TRAJECTORY.json (append-only per run id)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402
from tools import bench_diff  # noqa: E402

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
BASE = os.path.join(FIXTURES, "bench_base.json")
REGRESSED = os.path.join(FIXTURES, "bench_regressed.json")
BENCH_R05 = os.path.join(os.path.dirname(__file__), "..",
                         "BENCH_r05.json")


def _by_metric(out):
    return {c["metric"]: c for c in out["checks"]}


def test_regressed_fixture_is_flagged():
    out = bench_diff.compare(bench_diff.load_result(BASE),
                             bench_diff.load_result(REGRESSED))
    assert out["verdict"] == "regression"
    checks = _by_metric(out)
    # 46.1 -> 31.8 sigs/sec is far past the 10% tolerance
    assert checks["sigs_per_sec"]["status"] == "regression"
    # the guilty stage is named, not just the headline
    assert checks["stage_p50_ms.device_sync"]["status"] == "regression"
    # host_prep barely moved: not flagged
    assert checks["stage_p50_ms.host_prep"]["status"] == "ok"
    # shape 256 was cache-loaded in base but recompiled in new
    cache = checks["compile_cache_serving"]
    assert cache["status"] == "regression" and cache["new"] == ["256"]
    # dedup gates: 8x speedup fell under 1.5, warm pass dispatched h2c
    assert checks["dedup_speedup_8x"]["status"] == "regression"
    assert checks["warm_h2c_dispatches"]["status"] == "regression"
    # overload gates: p50 at 10x blew the 100 ms SLO, BLOCK_IMPORT got
    # shed, sheds inverted (gossip > optimistic), brownout flapped
    assert checks["overload_p50_ms"]["status"] == "regression"
    assert checks["overload_block_import_sheds"]["status"] \
        == "regression"
    assert checks["overload_shed_order"]["status"] == "regression"
    assert checks["overload_brownout_stable"]["status"] == "regression"


def test_base_vs_itself_passes():
    base = bench_diff.load_result(BASE)
    out = bench_diff.compare(base, base)
    assert out["verdict"] == "pass"
    assert out["regressions"] == 0
    checks = _by_metric(out)
    assert checks["sigs_per_sec"]["ratio"] == 1.0
    # the overload acceptance gates pass on the healthy fixture
    assert checks["overload_p50_ms"]["status"] == "ok"
    assert checks["overload_block_import_sheds"]["status"] == "ok"
    assert checks["overload_shed_order"]["status"] == "ok"
    assert checks["overload_brownout_stable"]["status"] == "ok"


def test_overload_gates_absent_are_skipped_and_threshold_overrides():
    """A run without the overload phase skips the gates (budget-starved
    rounds must not fail); the p50 gate threshold is operator-tunable
    via --threshold overload_p50_ms_max=N."""
    base = bench_diff.load_result(BASE)
    stripped = {k: v for k, v in base.items() if k != "overload"}
    out = bench_diff.compare(base, stripped)
    checks = _by_metric(out)
    for gate in ("overload_p50_ms", "overload_block_import_sheds",
                 "overload_shed_order", "overload_brownout_stable"):
        assert checks[gate]["status"] == "skipped"
    # tighten the SLO gate below the fixture's measured 49 ms: flags
    out = bench_diff.compare(base, base,
                             {"overload_p50_ms_max": 40.0})
    assert _by_metric(out)["overload_p50_ms"]["status"] == "regression"


def test_overlap_efficiency_gate_flags_skips_and_overrides():
    """The timeline PR gate: the latency burst's device-busy share of
    queue-nonempty time (bench stamps it from infra/timeline.py's
    attribution) must stay >= the floor; results that predate the
    timeline ring or ran with TEKU_TPU_TIMELINE=0 carry no value and
    skip; the floor defaults to 0.0 (the CPU reference box measures
    ~0 — drain-then-dispatch never overlaps) and is raised per
    deployment where enqueue genuinely overlaps device execution."""
    base = bench_diff.load_result(BASE)
    reg = bench_diff.load_result(REGRESSED)
    assert _by_metric(bench_diff.compare(base, base))[
        "overlap_efficiency"]["status"] == "ok"
    # default floor is vacuous: even the regressed fixture passes
    assert _by_metric(bench_diff.compare(base, reg))[
        "overlap_efficiency"]["status"] == "ok"
    # a deployment floor flags it
    assert _by_metric(bench_diff.compare(
        base, reg, {"overlap_efficiency_min": 0.3}))[
        "overlap_efficiency"]["status"] == "regression"
    stripped = {k: v for k, v in base.items()
                if k != "overlap_efficiency"}
    assert _by_metric(bench_diff.compare(base, stripped))[
        "overlap_efficiency"]["status"] == "skipped"
    out = bench_diff.compare(base, base,
                             {"overlap_efficiency_min": 0.9})
    assert _by_metric(out)["overlap_efficiency"]["status"] \
        == "regression"


def test_msm_gate_flags_skips_and_overrides():
    """The PR-8 MSM gate: pippenger's scalars-stage p50 must beat the
    ladder >= 1.3x at every measured batch >= 256; absent evidence
    (pre-MSM results, budget-starved runs) skips, sub-256 batches are
    informational only, and the threshold is operator-tunable."""
    doc = {"msm": {"dup": 8, "window": 4,
                   "256": {"scalars": {"ladder_p50_ms": 100.0,
                                       "pippenger_p50_ms": 90.0,
                                       "speedup": 1.11}}}}
    out = bench_diff.compare({}, doc)
    assert out["verdict"] == "regression"
    assert _by_metric(out)["msm_scalars_speedup_256"]["status"] \
        == "regression"
    doc["msm"]["256"]["scalars"]["speedup"] = 1.45
    out = bench_diff.compare({}, doc)
    assert _by_metric(out)["msm_scalars_speedup_256"]["status"] == "ok"
    # a 4096 entry gets its own gate; an errored batch entry skips
    doc["msm"]["4096"] = {"error": "TimeoutError: budget"}
    out = bench_diff.compare({}, doc)
    checks = _by_metric(out)
    assert checks["msm_scalars_speedup_4096"]["status"] == "skipped"
    assert out["verdict"] == "pass"
    # no msm evidence at all -> no msm checks (older results compare)
    assert not any(c["metric"].startswith("msm_")
                   for c in bench_diff.compare({}, {})["checks"])
    # sub-256 batches are not gated (the crossover is shape-dependent)
    tiny = {"msm": {"64": {"scalars": {"speedup": 0.5}}}}
    assert bench_diff.compare({}, tiny)["verdict"] == "pass"
    # operator override loosens the gate
    doc["msm"]["256"]["scalars"]["speedup"] = 1.11
    out = bench_diff.compare({}, doc,
                             {"msm_scalars_speedup_min": 1.0})
    assert _by_metric(out)["msm_scalars_speedup_256"]["status"] == "ok"


def test_current_bench_r05_vs_itself_passes():
    """The acceptance gate: the checked-in BENCH_r05 (driver envelope
    with a `parsed` key, budget-starved phases missing) must compare
    clean against itself — absent metrics are skipped, never failed."""
    r05 = bench_diff.load_result(BENCH_R05)
    assert r05["metric"] == "bls_verify_sigs_per_sec"   # unwrapped
    out = bench_diff.compare(r05, r05)
    assert out["verdict"] == "pass"
    checks = _by_metric(out)
    # r05 predates the dedup-sweep/latency_stages evidence: skipped
    assert checks["dedup_speedup_8x"]["status"] == "skipped"
    assert checks["sigs_per_sec"]["status"] == "ok"


def test_threshold_override_changes_verdict():
    base = bench_diff.load_result(BASE)
    slower = dict(base)
    slower["value"] = base["value"] * 0.85        # -15%
    assert bench_diff.compare(base, slower)["verdict"] == "regression"
    out = bench_diff.compare(base, slower,
                             {"sigs_per_sec": 0.2, "p50_ms": 10.0,
                              "p99_ms": 10.0, "stage_p50_ms": 10.0})
    assert _by_metric(out)["sigs_per_sec"]["status"] == "ok"


def test_cli_exit_codes_and_json(tmp_path, capsys):
    assert bench_diff.main([BASE, BASE]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["verdict"] == "pass"
    assert bench_diff.main([BASE, REGRESSED, "--quiet"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["verdict"] == "regression"
    assert "sigs_per_sec" in out["failed"]
    # IO errors are a distinct exit code with a JSON error line
    assert bench_diff.main([BASE, str(tmp_path / "missing.json")]) == 2
    assert json.loads(capsys.readouterr().out)["verdict"] == "error"


# --------------------------------------------------------------------------
# BENCH_TRAJECTORY.json: rolling, append-only per run id
# --------------------------------------------------------------------------

def _result(value=46.1):
    return {"value": value, "best_batch": 256, "device": "cpu",
            "p50_ms": 5210.4, "p99_ms": 7102.9,
            "latency_stages": {
                "device_sync": {"p50_ms": 4801.0, "n": 500}},
            "detail": {"256": {"cache_load_s": 207.3}},
            "h2c_dedup": {"factors": {"8": {"speedup_vs_1x": 1.57}},
                          "warm": {"h2c_dispatches": 0}},
            "capacity": {"occupancy_ratio": 0.91}}


def test_trajectory_appends_and_refuses_same_run_id(tmp_path):
    path = str(tmp_path / "BENCH_TRAJECTORY.json")
    assert bench.append_trajectory(_result(46.1), path=path,
                                   run_id="r06") == "appended"
    assert bench.append_trajectory(_result(50.0), path=path,
                                   run_id="r07") == "appended"
    # the same run id must NOT rewrite history the gate already cited
    assert bench.append_trajectory(_result(99.9), path=path,
                                   run_id="r06") == "duplicate_run_id"
    doc = json.load(open(path))
    assert [e["run_id"] for e in doc["entries"]] == ["r06", "r07"]
    entry = doc["entries"][0]
    assert entry["sigs_per_sec"] == 46.1
    assert entry["stage_p50_ms"]["device_sync"] == 4801.0
    assert entry["cache_load_s"] == 207.3 and entry["compile_s"] == 0.0
    assert entry["dedup_speedup_8x"] == 1.57
    assert entry["warm_h2c_dispatches"] == 0


def test_trajectory_is_bounded_and_comparable(tmp_path):
    path = str(tmp_path / "BENCH_TRAJECTORY.json")
    for i in range(7):
        assert bench.append_trajectory(
            _result(40.0 + i), path=path, run_id=f"r{i:02d}",
            max_entries=5) == "appended"
    doc = json.load(open(path))
    assert len(doc["entries"]) == 5
    assert doc["entries"][-1]["run_id"] == "r06"
    # trajectory entries feed straight back into the diff gate
    out = bench_diff.compare(doc["entries"][0], doc["entries"][-1])
    assert _by_metric(out)["sigs_per_sec"]["status"] == "ok"


def test_trajectory_corrupt_file_aborts_without_overwrite(tmp_path):
    """An EXISTING but unreadable trajectory must abort the append —
    silently restarting history would overwrite the record a
    regression gate already cited.  A missing file (first run) still
    starts fresh."""
    path = tmp_path / "BENCH_TRAJECTORY.json"
    path.write_text("not json{{{")
    out = bench.append_trajectory(_result(), path=str(path),
                                  run_id="r01")
    assert out.startswith("error:")
    assert path.read_text() == "not json{{{"    # untouched
    missing = tmp_path / "fresh" / "BENCH_TRAJECTORY.json"
    missing.parent.mkdir()
    assert bench.append_trajectory(_result(), path=str(missing),
                                   run_id="r01") == "appended"
    assert len(json.load(open(missing))["entries"]) == 1


def test_mainnet_gates_on_fixtures():
    """The loadgen acceptance gates: BLOCK_IMPORT/VIP sheds == 0 under
    EVERY scenario, the critical p50 bound on production shapes only
    (adversarial floods are exempt from the latency gate, not the
    shed gate), and the dedup-ratio floor on committee-shaped mixes."""
    base = bench_diff.load_result(BASE)
    out = bench_diff.compare(base, base)
    checks = _by_metric(out)
    assert checks["mainnet_block_import_sheds.steady_state"][
        "status"] == "ok"
    assert checks["mainnet_vip_p50_ms.steady_state"]["status"] == "ok"
    assert checks["mainnet_dedup_ratio.steady_state"]["status"] == "ok"
    # adversarial scenarios carry no latency gate but keep the shed one
    assert checks["mainnet_block_import_sheds.invalid_sig_flood"][
        "status"] == "ok"
    assert "mainnet_vip_p50_ms.invalid_sig_flood" not in checks
    # non-committee-shaped mixes carry no dedup floor
    assert "mainnet_dedup_ratio.dup_collapse" not in checks

    reg = bench_diff.load_result(REGRESSED)
    out = bench_diff.compare(base, reg)
    checks = _by_metric(out)
    assert out["verdict"] == "regression"
    # block import was shed under the storm: the invariant gate fires
    assert checks["mainnet_block_import_sheds.epoch_boundary_storm"][
        "status"] == "regression"
    # vip p50 blown on a production shape
    assert checks["mainnet_vip_p50_ms.steady_state"]["status"] \
        == "regression"
    # a committee-shaped mix lost its duplication
    assert checks["mainnet_dedup_ratio.blob_storm"]["status"] \
        == "regression"


def test_mainnet_gates_absent_are_skipped_and_thresholds():
    """Runs without the mainnet phase (pre-loadgen results) compare
    clean; the p50 bound and dedup floor are operator-tunable."""
    base = bench_diff.load_result(BASE)
    stripped = {k: v for k, v in base.items() if k != "mainnet"}
    out = bench_diff.compare(base, stripped)
    assert not any(c["metric"].startswith("mainnet_")
                   for c in out["checks"])
    assert out["verdict"] == "pass"
    # tighten the critical p50 bound under the storm's measured 228 ms
    out = bench_diff.compare(base, base,
                             {"mainnet_critical_p50_ms_max": 100.0})
    checks = _by_metric(out)
    assert checks["mainnet_vip_p50_ms.epoch_boundary_storm"][
        "status"] == "regression"
    # raise the dedup floor past the fixtures' 0.30
    out = bench_diff.compare(base, base,
                             {"mainnet_dedup_ratio_min": 0.5})
    assert _by_metric(out)["mainnet_dedup_ratio.steady_state"][
        "status"] == "regression"


def test_mesh_gates_on_fixtures():
    """The PR-10 mesh acceptance gates: the device-count sweep must be
    monotonic, and on real parallel hardware (series == "measured")
    the efficiency at the max device count must hold >= 0.7x linear."""
    base = bench_diff.load_result(BASE)
    out = bench_diff.compare(base, base)
    checks = _by_metric(out)
    assert checks["mesh_monotonic"]["status"] == "ok"
    assert checks["mesh_scaling_efficiency"]["status"] == "ok"

    reg = bench_diff.load_result(REGRESSED)
    out = bench_diff.compare(base, reg)
    checks = _by_metric(out)
    assert out["verdict"] == "regression"
    assert checks["mesh_monotonic"]["status"] == "regression"
    assert checks["mesh_scaling_efficiency"]["status"] == "regression"


def test_mesh_gates_skip_when_missing_or_virtual():
    """Skip-if-missing like every phase gate; on a serialized-virtual
    sweep (one host, forced device count) the efficiency gate skips —
    the per-device projection's Amdahl saturation is expected there —
    while monotonicity of the projection is still gated.  The
    threshold is operator-tunable."""
    base = bench_diff.load_result(BASE)
    stripped = {k: v for k, v in base.items() if k != "mesh"}
    out = bench_diff.compare(base, stripped)
    checks = _by_metric(out)
    assert checks["mesh_monotonic"]["status"] == "skipped"
    assert checks["mesh_scaling_efficiency"]["status"] == "skipped"
    assert out["verdict"] == "pass"

    virtual = dict(base)
    virtual["mesh"] = dict(base["mesh"],
                           series="projected_serialized_virtual",
                           scaling_efficiency_at_max=0.35)
    out = bench_diff.compare(base, virtual)
    checks = _by_metric(out)
    assert checks["mesh_scaling_efficiency"]["status"] == "skipped"
    assert checks["mesh_monotonic"]["status"] == "ok"
    # a non-monotonic virtual projection still fails
    virtual["mesh"] = dict(virtual["mesh"], monotonic=False)
    out = bench_diff.compare(base, virtual)
    assert _by_metric(out)["mesh_monotonic"]["status"] == "regression"
    # operator override tightens the measured gate past the fixture
    out = bench_diff.compare(base, base,
                             {"mesh_efficiency_min": 0.9})
    assert _by_metric(out)["mesh_scaling_efficiency"]["status"] \
        == "regression"
    # trajectory entries carry the FLATTENED mesh fields: the gates
    # read them with the standard fallback, like every other phase
    flat = {"mesh_monotonic": True, "mesh_series": "measured",
            "mesh_scaling_efficiency": 0.8}
    checks = _by_metric(bench_diff.compare({}, flat))
    assert checks["mesh_monotonic"]["status"] == "ok"
    assert checks["mesh_scaling_efficiency"]["status"] == "ok"
    flat["mesh_series"] = "projected_serialized_virtual"
    assert _by_metric(bench_diff.compare({}, flat))[
        "mesh_scaling_efficiency"]["status"] == "skipped"


def test_chaos_gates_on_fixtures():
    """The mesh self-healing acceptance gates: zero wrong verdicts
    through eject/reshape/readmit, full grow-back, and (on measured
    series) recovery <= mesh_recovery_s_max — in BOTH the bench chaos
    phase and the loadgen chaos_device_loss scenario."""
    base = bench_diff.load_result(BASE)
    out = bench_diff.compare(base, base)
    checks = _by_metric(out)
    assert checks["chaos_wrong_verdicts"]["status"] == "ok"
    assert checks["chaos_recovered"]["status"] == "ok"
    assert checks["chaos_recovery_s"]["status"] == "ok"
    assert checks["mainnet_chaos_wrong_verdicts"]["status"] == "ok"
    assert checks["mainnet_chaos_recovered"]["status"] == "ok"
    # the chaos scenario also rides the per-scenario protected-class
    # shed gate like every other traffic shape
    assert checks["mainnet_block_import_sheds.chaos_device_loss"][
        "status"] == "ok"

    reg = bench_diff.load_result(REGRESSED)
    out = bench_diff.compare(base, reg)
    checks = _by_metric(out)
    assert out["verdict"] == "regression"
    assert checks["chaos_wrong_verdicts"]["status"] == "regression"
    assert checks["chaos_recovered"]["status"] == "regression"
    assert checks["chaos_recovery_s"]["status"] == "regression"
    assert checks["mainnet_chaos_wrong_verdicts"]["status"] \
        == "regression"
    assert checks["mainnet_chaos_recovered"]["status"] == "regression"


def test_chaos_gates_skip_when_missing_or_virtual():
    """Skip-if-missing (budget-starved runs drop the phase) and
    skip-on-virtual for the recovery-time gate: serialized virtual
    devices pay XLA compile wall time that means nothing, so only the
    correctness gates (wrong verdicts, recovered) apply there.  The
    RTO threshold is operator-tunable."""
    base = bench_diff.load_result(BASE)
    stripped = {k: v for k, v in base.items() if k != "chaos"}
    stripped["mainnet"] = {
        "scenarios": {k: v for k, v
                      in base["mainnet"]["scenarios"].items()
                      if k != "chaos_device_loss"}}
    out = bench_diff.compare(base, stripped)
    checks = _by_metric(out)
    for m in ("chaos_wrong_verdicts", "chaos_recovered",
              "chaos_recovery_s"):
        assert checks[m]["status"] == "skipped", m
    # the loadgen-chaos gates follow the per-scenario precedent:
    # absent scenario => no mainnet_* checks at all
    assert "mainnet_chaos_wrong_verdicts" not in checks
    assert "mainnet_chaos_recovered" not in checks
    # a skipped bench phase leaves a "skipped: ..." STRING, not a dict
    stringy = dict(stripped, chaos="skipped: needs >= 4 devices")
    out = bench_diff.compare(base, stringy)
    assert _by_metric(out)["chaos_wrong_verdicts"]["status"] \
        == "skipped"

    virtual = dict(base)
    virtual["chaos"] = dict(base["chaos"], series="virtual",
                            recovery_s=240.0)
    out = bench_diff.compare(base, virtual)
    checks = _by_metric(out)
    assert checks["chaos_recovery_s"]["status"] == "skipped"
    assert checks["chaos_wrong_verdicts"]["status"] == "ok"
    # a virtual run that flips a verdict still fails
    virtual["chaos"] = dict(virtual["chaos"], wrong_verdicts=1)
    assert _by_metric(bench_diff.compare(base, virtual))[
        "chaos_wrong_verdicts"]["status"] == "regression"
    # operator override tightens the measured RTO gate
    out = bench_diff.compare(base, base,
                             {"mesh_recovery_s_max": 5.0})
    assert _by_metric(out)["chaos_recovery_s"]["status"] \
        == "regression"
    # trajectory entries carry the flattened chaos fields
    flat = {"chaos_recovery_s": 8.0, "chaos_wrong_verdicts": 0,
            "chaos_series": "measured", "chaos_recovered": True}
    checks = _by_metric(bench_diff.compare({}, flat))
    assert checks["chaos_wrong_verdicts"]["status"] == "ok"
    assert checks["chaos_recovered"]["status"] == "ok"
    assert checks["chaos_recovery_s"]["status"] == "ok"


def test_coldstart_gates_on_fixtures():
    """The AOT executable-store acceptance gates: a boot from a
    populated store must perform ZERO kernel-grade fresh XLA compiles
    and reach READY >= coldstart_speedup_min (3x) faster than the
    empty-store cold boot that pays the compile wall."""
    base = bench_diff.load_result(BASE)
    out = bench_diff.compare(base, base)
    checks = _by_metric(out)
    assert checks["coldstart_warm_store_compiles"]["status"] == "ok"
    assert checks["coldstart_speedup"]["status"] == "ok"

    reg = bench_diff.load_result(REGRESSED)
    out = bench_diff.compare(base, reg)
    checks = _by_metric(out)
    assert out["verdict"] == "regression"
    # the regressed fixture recompiled 3 kernels warm and only hit 2x
    assert checks["coldstart_warm_store_compiles"]["status"] \
        == "regression"
    assert checks["coldstart_speedup"]["status"] == "regression"


def test_coldstart_gates_skip_when_missing_and_threshold():
    """Skip-if-missing: the coldstart phase is opt-in
    (BENCH_COLDSTART=1 — it pays a full compile wall on purpose), so
    results without the block must compare clean.  The speedup floor
    is operator-tunable."""
    base = bench_diff.load_result(BASE)
    stripped = {k: v for k, v in base.items() if k != "coldstart"}
    checks = _by_metric(bench_diff.compare(base, stripped))
    assert checks["coldstart_warm_store_compiles"]["status"] \
        == "skipped"
    assert checks["coldstart_speedup"]["status"] == "skipped"
    # tighten the floor past the healthy fixture's measured 29.1x
    out = bench_diff.compare(
        base, base, thresholds={"coldstart_speedup_min": 50.0})
    checks = _by_metric(out)
    assert checks["coldstart_speedup"]["status"] == "regression"
    assert checks["coldstart_warm_store_compiles"]["status"] == "ok"


def test_ledger_gates_on_fixtures():
    """The PR-13 dispatch-ledger gates: per bench phase, lane-bucket
    padding waste must stay <= padding_waste_max (0.5) and the mesh
    shard makespan ratio <= mesh_imbalance_max (1.5)."""
    base = bench_diff.load_result(BASE)
    out = bench_diff.compare(base, base)
    checks = _by_metric(out)
    assert checks["ledger_padding_waste.latency"]["status"] == "ok"
    assert checks["ledger_padding_waste.mesh"]["status"] == "ok"
    assert checks["ledger_mesh_imbalance.mesh"]["status"] == "ok"
    # a phase without mesh dispatches skips its imbalance gate
    assert checks["ledger_mesh_imbalance.latency"]["status"] \
        == "skipped"

    reg = bench_diff.load_result(REGRESSED)
    out = bench_diff.compare(base, reg)
    checks = _by_metric(out)
    assert out["verdict"] == "regression"
    # the seeded regressions: latency-phase lane waste 0.61 > 0.5,
    # mesh-phase makespan 1.82 > 1.5
    assert checks["ledger_padding_waste.latency"]["status"] \
        == "regression"
    assert checks["ledger_mesh_imbalance.mesh"]["status"] \
        == "regression"
    assert checks["ledger_padding_waste.mesh"]["status"] == "ok"


def test_ledger_gates_skip_when_missing_and_thresholds():
    """Skip-if-missing (pre-ledger results and budget-starved runs
    carry no `ledger` block); thresholds are operator-tunable."""
    base = bench_diff.load_result(BASE)
    stripped = {k: v for k, v in base.items() if k != "ledger"}
    out = bench_diff.compare(base, stripped)
    assert not any(c["metric"].startswith("ledger_")
                   for c in out["checks"])
    assert out["verdict"] == "pass"
    # a phase that PINNED its dispatch bucket for compile budget
    # (bench latency phase) skips the waste gate: the waste measures
    # the pin, not the production planner
    pinned = json.loads(json.dumps(base))
    pinned["ledger"]["latency"]["padding_waste"]["lane"] = 0.73
    pinned["ledger"]["latency"]["pinned_min_bucket"] = 256
    out = bench_diff.compare(base, pinned)
    assert _by_metric(out)["ledger_padding_waste.latency"]["status"] \
        == "skipped"
    assert out["verdict"] == "pass"
    # tighten the waste gate below the healthy fixture's 0.0312: flags
    out = bench_diff.compare(base, base,
                             {"padding_waste_max": 0.01})
    assert _by_metric(out)["ledger_padding_waste.latency"]["status"] \
        == "regression"
    # loosen the imbalance gate past the regressed fixture's 1.82
    reg = bench_diff.load_result(REGRESSED)
    out = bench_diff.compare(base, reg,
                             {"mesh_imbalance_max": 2.0})
    assert _by_metric(out)["ledger_mesh_imbalance.mesh"]["status"] \
        == "ok"


def test_phase_focused_run_zero_value_skips_relative_gates():
    """A control-plane-focused run (BENCH_THROUGHPUT=0) reports
    value=0.0 — that is 'phase did not run', never a measured
    collapse, so the relative gates skip instead of failing."""
    base = bench_diff.load_result(BASE)
    focused = dict(base)
    focused["value"] = 0.0
    out = bench_diff.compare(base, focused)
    assert _by_metric(out)["sigs_per_sec"]["status"] == "skipped"
    assert out["verdict"] == "pass"


def test_current_bench_r09_mainnet_evidence_gates_clean():
    """The checked-in mainnet-focused BENCH_r09 run: >= 4 scenarios
    including the adversarial flood and the epoch-boundary storm, all
    mainnet gates green against the r08 base."""
    r08 = os.path.join(os.path.dirname(__file__), "..",
                       "BENCH_r08.json")
    r09 = os.path.join(os.path.dirname(__file__), "..",
                       "BENCH_r09.json")
    if not (os.path.exists(r08) and os.path.exists(r09)):
        pytest.skip("checked-in bench results not present")
    new = bench_diff.load_result(r09)
    scen = new["mainnet"]["scenarios"]
    assert len([v for v in scen.values() if isinstance(v, dict)
                and "by_class" in v]) >= 4
    assert "invalid_sig_flood" in scen
    assert "epoch_boundary_storm" in scen
    assert scen["invalid_sig_flood"]["bisect_dispatches"] > 0
    assert scen["epoch_boundary_storm"]["brownout"]["enters"] >= 1
    out = bench_diff.compare(bench_diff.load_result(r08), new)
    assert out["verdict"] == "pass"
    mainnet_checks = [c for c in out["checks"]
                      if c["metric"].startswith("mainnet_")]
    assert mainnet_checks
    assert all(c["status"] in ("ok", "skipped")
               for c in mainnet_checks)
