"""Peer reputation book: graded adjustments, disconnect floor,
time-bounded bans, and the transport admission gate (reference:
networking/p2p/.../reputation/DefaultReputationManager.java).
"""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio

import pytest

from teku_tpu.networking import transport as T
from teku_tpu.networking.reputation import (Adjustment,
                                            ReputationManager)

NID = b"\x42" * 32


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_adjust_clamps_and_disconnects_at_floor():
    rep = ReputationManager(time_fn=_Clock())
    for _ in range(100):
        assert not rep.adjust(NID, Adjustment.LARGE_REWARD)
    assert rep.score(NID) == 150.0          # clamped at MAX_SCORE
    # the floor is an absolute score, so even a maxed-out peer can
    # fall: 30 large penalties from +150
    hit = False
    for _ in range(60):
        if rep.adjust(NID, Adjustment.LARGE_PENALTY):
            hit = True
            break
    assert hit
    assert not rep.is_connect_allowed(NID)   # banned


def test_ban_expires_and_forgives():
    clock = _Clock()
    rep = ReputationManager(time_fn=clock, ban_period_s=100.0)
    while not rep.adjust(NID, -50.0):
        pass
    assert not rep.is_connect_allowed(NID)
    clock.t += 99.0
    assert not rep.is_connect_allowed(NID)
    clock.t += 2.0
    assert rep.is_connect_allowed(NID)
    assert rep.score(NID) == 0.0             # forgiven with the ban


def test_ban_worthy_goodbye_codes():
    clock = _Clock()
    rep = ReputationManager(time_fn=clock)
    rep.report_received_goodbye(NID, 1)      # clean shutdown: no ban
    assert rep.is_connect_allowed(NID)
    rep.report_received_goodbye(NID, 3)      # fault: ban
    assert not rep.is_connect_allowed(NID)
    other = b"\x43" * 32
    rep.report_initiated_disconnect(other, 128)
    assert not rep.is_connect_allowed(other)
    # transient conditions never ban: shutdown (1), too-many-peers (129)
    third = b"\x44" * 32
    rep.report_received_goodbye(third, 129)
    assert rep.is_connect_allowed(third)


@pytest.mark.slow
def test_banned_peer_refused_at_transport():
    """Real TCP: node A bans node B's id; B's dial completes the
    handshake but is refused admission with a goodbye."""
    async def run():
        a = T.P2PNetwork(T.NetworkConfig(noise=False), b"\x00" * 4,
                         node_id=b"\x0a" * 32)
        b = T.P2PNetwork(T.NetworkConfig(noise=False), b"\x00" * 4,
                         node_id=b"\x0b" * 32)
        await a.start()
        await b.start()
        try:
            a.reputation.report_initiated_disconnect(b.node_id, 3)
            peer = await b.connect("127.0.0.1", a.port)
            # give A's accept path a beat to refuse
            await asyncio.sleep(0.2)
            assert a.peers == []
            assert peer is None or not peer.connected
        finally:
            await a.stop()
            await b.stop()
    asyncio.run(run())
