"""Backend supervisor: bring-up state machine, hot-swap, breaker.

The acceptance criterion of the supervision issue, asserted end to end
with injected faults and NO accelerator: a slow-ramp backend (init far
longer than the old probe deadline) must not stall boot — the node
serves the oracle immediately, the supervisor reaches READY in the
background, the facade hot-swaps with zero failed in-flight
verifications, and an injected dispatch-hang afterwards trips the
breaker back to the oracle, all visible as metric/heartbeat state
transitions.
"""

import asyncio
import time

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen, loader
from teku_tpu.crypto.bls.pure_impl import PureBls12381
from teku_tpu.infra import faults
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.infra.supervisor import (BackendState, BackendSupervisor,
                                       CircuitBreaker, CircuitOpenError,
                                       DispatchTimeoutError)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.clear()
    bls.reset_implementation()


class FakeDevice(PureBls12381):
    """'Device' provider: oracle math behind the `bls.dispatch` fault
    site, so hang/raise/wrong-result injection hits it exactly like the
    real JaxBls12381._dispatch."""

    name = "fake-device"

    def __init__(self):
        super().__init__()
        self.dispatch_count = 0

    def _site(self):
        self.dispatch_count += 1
        faults.check("bls.dispatch")

    def fast_aggregate_verify(self, pks, msg, sig):
        self._site()
        return faults.transform(
            "bls.dispatch", super().fast_aggregate_verify(pks, msg, sig))

    def batch_verify(self, triples):
        self._site()
        return faults.transform(
            "bls.dispatch", super().batch_verify(triples))

    def verify(self, pk, msg, sig):
        self._site()
        return faults.transform(
            "bls.dispatch", super().verify(pk, msg, sig))

    def public_key_is_valid(self, pk):
        self._site()
        return super().public_key_is_valid(pk)


def make_fake_supervisor(registry=None, *, ramp_s=0.0, breaker=None,
                         fail_times=0, with_reprobe=False, **kw):
    """Supervisor over FakeDevice with a SlowRamp/Raise-able probe."""
    registry = registry or MetricsRegistry()
    # default deadline is generous: pure-oracle batch dispatches in
    # these tests take tens of ms and must never trip spuriously
    breaker = breaker or CircuitBreaker(
        failure_threshold=2, deadline_s=2.0, cooldown_s=0.2,
        name="t", registry=registry)
    if ramp_s:
        faults.inject("backend.init", faults.SlowRamp(ramp_s))
    if fail_times:
        faults.inject("backend.init", faults.Raise(
            RuntimeError("tunnel wedged"), times=fail_times))
    installed = {}

    def probe():
        return FakeDevice()

    def install(backend):
        installed["impl"] = backend
        bls.set_implementation(
            loader.GuardedBls12381(backend, breaker))

    def reprobe():
        if not installed["impl"].fast_aggregate_verify([PK], MSG, SIG):
            raise RuntimeError("reprobe wrong verdict")

    kw.setdefault("probe_attempts_per_round", 2)
    kw.setdefault("probe_base_delay_s", 0.01)
    kw.setdefault("round_delay_s", 0.01)
    return BackendSupervisor(
        probe=probe, install=install,
        reprobe=reprobe if with_reprobe else None,
        uninstall=bls.reset_implementation, breaker=breaker,
        name="t", registry=registry, **kw), registry


SK = keygen(b"\x07" * 32)
PK = bls.secret_to_public_key(SK)
MSG = b"supervised"
SIG = bls.sign(SK, MSG)


# --------------------------------------------------------------------------
# state machine
# --------------------------------------------------------------------------

def test_slow_ramp_boots_oracle_then_hot_swaps():
    """Init slower than the OLD probe deadline: boot is instant on the
    oracle, READY arrives in the background, facade hot-swaps."""
    async def main():
        sup, reg = make_fake_supervisor(ramp_s=0.3)
        old_probe_deadline = 0.05          # the legacy blocking budget
        t0 = time.monotonic()
        await sup.start()
        boot_s = time.monotonic() - t0
        assert boot_s < old_probe_deadline  # start() never blocks
        # the node is serving NOW, on the oracle
        assert isinstance(bls.get_implementation(), PureBls12381)
        assert bls.verify(PK, MSG, SIG)
        assert sup.backend_state in ("cold", "probing", "warming",
                                     "ready")
        assert await sup.wait_ready(5.0)
        impl = bls.get_implementation()
        assert isinstance(impl, loader.GuardedBls12381)
        assert impl.name == "fake-device"
        assert bls.verify(PK, MSG, SIG)     # now via the device
        states = [s for s, _ in sup.transitions]
        assert states == ["cold", "probing", "warming", "ready"]
        # transitions carry timestamps and the metrics agree
        assert all(t > 0 for _, t in sup.transitions)
        assert reg.state_gauge("t_state").state == "ready"
        assert 'state="ready"} 1.0' in reg.expose()
        await sup.stop()
    asyncio.run(main())


def test_probe_failures_back_off_then_succeed():
    async def main():
        # 3 raise-faults > one 2-attempt round: forces a round of
        # backoff before the probe lands
        sup, reg = make_fake_supervisor(fail_times=3)
        await sup.start()
        assert await sup.wait_ready(5.0)
        assert reg.counter("t_probe_failures_total").value >= 1
        assert faults.fired_count("backend.init") == 3
        await sup.stop()
    asyncio.run(main())


def test_non_retryable_probe_degrades():
    async def main():
        registry = MetricsRegistry()

        def probe():
            raise ImportError("no accelerator plugin in this image")

        sup = BackendSupervisor(
            probe=probe, install=lambda b: None, name="t",
            registry=registry, probe_attempts_per_round=2,
            probe_base_delay_s=0.01, round_delay_s=0.01)
        await sup.start()
        for _ in range(200):
            if sup.backend_state == "degraded":
                break
            await asyncio.sleep(0.02)
        assert sup.backend_state == "degraded"
        assert "abandoned" in sup.backend_detail
        # the oracle still serves: DEGRADED costs speed, not liveness
        assert bls.verify(PK, MSG, SIG)
        await sup.stop()
    asyncio.run(main())


def test_warmup_veto_degrades_instead_of_installing():
    """A device that returns a wrong verdict on a KNOWN-good input
    during warmup must never be hot-swapped in: correctness over
    speed, so the supervisor goes DEGRADED on the oracle."""
    from teku_tpu.infra.supervisor import WarmupVetoError

    async def main():
        def warmup(backend):
            raise WarmupVetoError("warmup batch did not verify")

        sup = BackendSupervisor(
            probe=FakeDevice, warmup=warmup,
            install=lambda b: bls.set_implementation(b),
            name="t", registry=MetricsRegistry(),
            probe_base_delay_s=0.01, round_delay_s=0.01)
        await sup.start()
        for _ in range(200):
            if sup.backend_state == "degraded":
                break
            await asyncio.sleep(0.02)
        assert sup.backend_state == "degraded"
        assert "veto" in sup.backend_detail
        # the untrusted device was NOT installed
        assert isinstance(bls.get_implementation(), PureBls12381)
        assert not sup._ready_event.is_set()
        await sup.stop()
    asyncio.run(main())


def test_warmup_ordinary_failure_still_installs():
    """A non-veto warmup hiccup (e.g. compile error) installs anyway:
    the first real batch compiles lazily."""
    async def main():
        def warmup(backend):
            raise RuntimeError("compile hiccup")

        breaker = CircuitBreaker(name="t", registry=MetricsRegistry())
        sup = BackendSupervisor(
            probe=FakeDevice, warmup=warmup,
            install=lambda b: bls.set_implementation(
                loader.GuardedBls12381(b, breaker)),
            name="t", registry=MetricsRegistry(),
            probe_base_delay_s=0.01, round_delay_s=0.01)
        await sup.start()
        assert await sup.wait_ready(5.0)
        assert isinstance(bls.get_implementation(),
                          loader.GuardedBls12381)
        await sup.stop()
    asyncio.run(main())


def test_kzg_error_does_not_trip_breaker():
    """Malformed-input KzgErrors from the device backend are verdicts:
    they propagate but never count toward the trip threshold."""
    from teku_tpu.crypto import kzg

    class VerdictKzg:
        name = "verdict"

        def g1_lincomb(self, setup, scalars):
            raise kzg.KzgError("scalar count must match basis size")

    br = CircuitBreaker(failure_threshold=1, deadline_s=1.0,
                        cooldown_s=60.0, name="vk",
                        registry=MetricsRegistry())
    guarded = loader.GuardedKzgBackend(VerdictKzg(), br)
    for _ in range(3):
        with pytest.raises(kzg.KzgError):
            guarded.g1_lincomb(None, [])
    assert br.state == CircuitBreaker.CLOSED   # never tripped


def test_max_rounds_degrades():
    async def main():
        sup, _ = make_fake_supervisor(fail_times=100, max_rounds=2)
        await sup.start()
        for _ in range(200):
            if sup.backend_state == "degraded":
                break
            await asyncio.sleep(0.02)
        assert sup.backend_state == "degraded"
        await sup.stop()
    asyncio.run(main())


# --------------------------------------------------------------------------
# hot-swap under concurrent load
# --------------------------------------------------------------------------

def test_hot_swap_zero_failed_inflight_verifications():
    """Continuous verification traffic across the oracle→device swap:
    every single verdict stays correct."""
    from teku_tpu.infra.metrics import MetricsRegistry as MR
    from teku_tpu.services.signatures import (
        AggregatingSignatureVerificationService)

    async def main():
        sup, _ = make_fake_supervisor(ramp_s=0.1)
        svc = AggregatingSignatureVerificationService(
            num_workers=2, registry=MR())
        await svc.start()
        await sup.start()
        results = []
        bad_sig = bls.sign(SK, b"other-message")
        # traffic spans the swap: supervisor goes READY ~0.1s in
        for burst in range(12):
            futs = [svc.verify([PK], MSG, SIG) for _ in range(6)]
            with_bad = burst % 5 == 0
            if with_bad:
                futs.append(svc.verify([PK], MSG, bad_sig))
            got = await asyncio.gather(*futs)
            results.append((with_bad, got))
            await asyncio.sleep(0.01)
        assert await sup.wait_ready(5.0)
        for with_bad, got in results:
            assert got[:6] == [True] * 6     # zero failed verifications
            if with_bad:
                assert got[6] is False       # bad sig still rejected
        # the device actually served part of the traffic
        assert sup.backend.dispatch_count > 0
        await svc.stop()
        await sup.stop()
    asyncio.run(main())


# --------------------------------------------------------------------------
# circuit breaker
# --------------------------------------------------------------------------

def test_breaker_trips_on_consecutive_failures_and_recloses():
    reg = MetricsRegistry()
    br = CircuitBreaker(failure_threshold=2, deadline_s=1.0,
                        cooldown_s=0.1, name="cb", registry=reg)

    def boom():
        raise RuntimeError("device fault")

    for _ in range(2):
        with pytest.raises(RuntimeError):
            br.call(boom)
    assert br.state == CircuitBreaker.OPEN
    assert reg.counter("cb_circuit_trips_total").value == 1
    # open: dispatch refused without touching the device
    with pytest.raises(CircuitOpenError):
        br.call(lambda: True)
    time.sleep(0.15)
    # half-open probe succeeds -> re-closed
    assert br.call(lambda: "ok") == "ok"
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_half_open_failure_reopens_with_longer_cooldown():
    br = CircuitBreaker(failure_threshold=1, deadline_s=1.0,
                        cooldown_s=0.1, name="cb2",
                        registry=MetricsRegistry())
    with pytest.raises(RuntimeError):
        br.call(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    assert br.state == CircuitBreaker.OPEN
    first_open_until = br._open_until
    time.sleep(0.12)
    with pytest.raises(RuntimeError):      # half-open probe fails
        br.call(lambda: (_ for _ in ()).throw(RuntimeError("y")))
    assert br.state == CircuitBreaker.OPEN
    # cooldown doubled: second window is longer than the first
    assert br._open_until - br._clock() > 0.15
    assert br._open_until > first_open_until


def test_breaker_deadline_counts_as_failure():
    br = CircuitBreaker(failure_threshold=1, deadline_s=0.05,
                        cooldown_s=10.0, name="cb3",
                        registry=MetricsRegistry())
    with pytest.raises(DispatchTimeoutError):
        br.call(time.sleep, 0.5)
    assert br.state == CircuitBreaker.OPEN


def test_guarded_bls_falls_back_to_oracle_per_call():
    """A raising device never corrupts a verdict: the SAME call is
    re-served by the oracle."""
    reg = MetricsRegistry()
    br = CircuitBreaker(failure_threshold=3, deadline_s=1.0,
                        cooldown_s=60.0, name="g", registry=reg)
    device = FakeDevice()
    guarded = loader.GuardedBls12381(device, br)
    faults.inject("bls.dispatch", faults.Raise(
        RuntimeError("device fault"), times=1))
    assert guarded.verify(PK, MSG, SIG) is True     # oracle served it
    assert guarded.verify(PK, MSG, SIG) is True     # device again
    assert br.state == CircuitBreaker.CLOSED


def test_dispatch_hang_trips_breaker_back_to_oracle_then_recloses():
    """The acceptance scenario's second half: after READY, an injected
    dispatch hang trips the breaker; verdicts keep flowing from the
    oracle (TRIPPED state), and once the fault clears the half-open
    probe re-closes the circuit back to READY."""
    async def main():
        reg = MetricsRegistry()
        br = CircuitBreaker(failure_threshold=2, deadline_s=0.5,
                            cooldown_s=0.2, name="t", registry=reg)
        sup, _ = make_fake_supervisor(registry=reg, breaker=br)
        await sup.start()
        assert await sup.wait_ready(5.0)
        impl = bls.get_implementation()
        # hang longer than the 0.5s per-dispatch deadline, every time
        faults.inject("bls.dispatch", faults.Hang(1.0))
        for _ in range(2):                 # threshold=2 -> trip
            assert await asyncio.to_thread(
                bls.verify, PK, MSG, SIG)  # correct, via oracle
        assert impl.breaker.state == CircuitBreaker.OPEN
        assert impl.serving == "oracle"
        assert sup.backend_state == "tripped"
        assert "tripped" in [s for s, _ in sup.transitions]
        # while open: no device calls, instant oracle service
        n_before = sup.backend.dispatch_count
        assert bls.verify(PK, MSG, SIG)
        assert sup.backend.dispatch_count == n_before
        # clear the fault; after cooldown a half-open probe re-closes.
        # Orphaned hang threads may still hold the device lock for a
        # while (by design: a busy device reads as busy), so retry
        # until they drain
        faults.clear("bls.dispatch")
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            assert await asyncio.to_thread(bls.verify, PK, MSG, SIG)
            if impl.breaker.state == CircuitBreaker.CLOSED:
                break
            await asyncio.sleep(0.3)
        assert impl.breaker.state == CircuitBreaker.CLOSED
        assert sup.backend_state == "ready"
        snap = sup.snapshot()
        assert snap["circuit"] == "closed"
        assert [t["state"] for t in snap["transitions"]][-2:] == \
            ["tripped", "ready"]
        await sup.stop()
    asyncio.run(main())


# --------------------------------------------------------------------------
# lifecycle / wiring
# --------------------------------------------------------------------------

def test_node_owns_supervisor_lifecycle():
    """BeaconNode.do_start starts the supervisor, do_stop stops it and
    restores the oracle."""
    from teku_tpu.node import Devnet

    async def main():
        sup, _ = make_fake_supervisor()
        net = Devnet(n_nodes=1, n_validators=8)
        net.nodes[0].supervisor = sup
        await net.start()
        assert sup.is_running
        assert await sup.wait_ready(5.0)
        assert isinstance(bls.get_implementation(),
                          loader.GuardedBls12381)
        await net.run_slot(1)
        await net.stop()
        assert not sup.is_running
        # uninstall restored the oracle
        assert isinstance(bls.get_implementation(), PureBls12381)
    asyncio.run(main())


def test_stop_before_ready_cancels_cleanly():
    async def main():
        sup, _ = make_fake_supervisor(ramp_s=0.6)
        await sup.start()
        await asyncio.sleep(0.05)
        await sup.stop()                   # mid-probe cancel
        assert sup.backend_state in ("probing", "cold")
        assert isinstance(bls.get_implementation(), PureBls12381)
    asyncio.run(main())


def test_probe_reserved_keeps_live_traffic_off_half_open():
    """With a supervisor-owned reprobe, a live call arriving after the
    cooldown must NOT be drafted as the half-open probe."""
    br = CircuitBreaker(failure_threshold=1, deadline_s=1.0,
                        cooldown_s=0.05, name="pr",
                        registry=MetricsRegistry())
    br.probe_reserved = True
    with pytest.raises(RuntimeError):
        br.call(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    assert br.state == CircuitBreaker.OPEN
    time.sleep(0.08)
    # cooldown elapsed: a live (non-probe) call is still refused...
    with pytest.raises(CircuitOpenError):
        br.call(lambda: True)
    assert br.state == CircuitBreaker.OPEN
    # ...and only the probe call may re-close
    assert br.call(lambda: "ok", probe=True) == "ok"
    assert br.state == CircuitBreaker.CLOSED


def test_kzg_dispatch_faults_feed_the_breaker():
    """Hang/raise injection at kzg.dispatch runs INSIDE the guarded
    call: deadlines contain hangs and raises count toward the trip."""
    from teku_tpu.crypto import kzg

    class IdleKzg:
        name = "idle"

        def g1_lincomb(self, setup, scalars):
            return b"\x00" * 48

    br = CircuitBreaker(failure_threshold=2, deadline_s=0.2,
                        cooldown_s=60.0, name="kd",
                        registry=MetricsRegistry())
    guarded = loader.GuardedKzgBackend(IdleKzg(), br)
    faults.inject("kzg.dispatch", faults.Raise(RuntimeError("boom")))
    with pytest.raises(kzg.BackendUnavailable):
        guarded.g1_lincomb(None, [])
    faults.clear("kzg.dispatch")
    faults.inject("kzg.dispatch", faults.Hang(1.0, times=1))
    with pytest.raises(kzg.BackendUnavailable):   # deadline, not 1.0s
        t0 = time.monotonic()
        guarded.g1_lincomb(None, [])
    assert time.monotonic() - t0 < 0.8
    assert br.state == CircuitBreaker.OPEN        # 2 failures tripped


def test_background_reprobe_recloses_without_live_traffic():
    """After a trip, the SUPERVISOR's synthetic reprobe re-closes the
    circuit — no live verification pays the probe's deadline wait."""
    async def main():
        reg = MetricsRegistry()
        br = CircuitBreaker(failure_threshold=1, deadline_s=0.3,
                            cooldown_s=0.2, name="t", registry=reg)
        sup, _ = make_fake_supervisor(registry=reg, breaker=br,
                                      with_reprobe=True)
        await sup.start()
        assert await sup.wait_ready(5.0)
        faults.inject("bls.dispatch", faults.Hang(1.0, times=1))
        assert await asyncio.to_thread(bls.verify, PK, MSG, SIG)
        assert sup.backend_state == "tripped"
        # NO further traffic: the background reprobe must recover alone
        for _ in range(100):
            if sup.backend_state == "ready":
                break
            await asyncio.sleep(0.05)
        assert sup.backend_state == "ready"
        assert br.state == CircuitBreaker.CLOSED
        await sup.stop()
    asyncio.run(main())


def test_complete_batch_verify_across_hot_swap():
    """A prepare/complete pair split across the oracle→device swap
    completes on the implementation family it started with."""
    semi = bls.prepare_batch_verify(([PK], MSG, SIG))     # oracle semi
    bad_semi = bls.prepare_batch_verify(([PK], b"x", SIG))
    br = CircuitBreaker(failure_threshold=2, deadline_s=2.0,
                        cooldown_s=0.2, name="x",
                        registry=MetricsRegistry())
    guarded = loader.GuardedBls12381(FakeDevice(), br)
    bls.set_implementation(guarded)                       # hot-swap
    assert bls.complete_batch_verify([semi]) is True
    assert bls.complete_batch_verify([bad_semi]) is False
    # and mixed old/new semis in one completion
    new_semi = bls.prepare_batch_verify(([PK], MSG, SIG))
    assert bls.complete_batch_verify([semi, new_semi]) is True


def test_configure_supervised_boots_pure():
    assert loader.configure("supervised") == "pure"
    assert isinstance(bls.get_implementation(), PureBls12381)


@pytest.mark.slow
def test_supervised_bringup_real_jax_provider():
    """End-to-end on the real device provider (CPU backend): probe,
    warmup compile, hot-swap, and a guarded verification that actually
    dispatches the staged kernel."""
    async def main():
        sup = loader.make_supervisor(registry=MetricsRegistry(),
                                     probe_base_delay_s=0.1,
                                     round_delay_s=0.1)
        await sup.start()
        assert await sup.wait_ready(1200.0)
        impl = bls.get_implementation()
        assert isinstance(impl, loader.GuardedBls12381)
        assert impl.name == "jax-tpu"
        # generous deadline: a cold staged compile is minutes on CPU
        impl.breaker.deadline_s = 900.0
        assert await asyncio.to_thread(bls.verify, PK, MSG, SIG)
        assert not bls.verify(PK, b"other", SIG)
        assert sup.backend[0].dispatch_count > 0
        await sup.stop()
    asyncio.run(main())


def test_guarded_kzg_backend_unavailable_falls_through():
    """A tripped device KZG backend must cost latency, not verdicts:
    the facade falls through to the host path."""
    from teku_tpu.crypto import kzg

    class BoomKzg:
        name = "boom"

        def verify_blob_kzg_proof_batch(self, *a):
            raise RuntimeError("device fault")

        def g1_lincomb(self, *a):
            raise RuntimeError("device fault")

        def verify_blob_kzg_proof(self, *a):
            raise RuntimeError("device fault")

    br = CircuitBreaker(failure_threshold=1, deadline_s=1.0,
                        cooldown_s=60.0, name="gk",
                        registry=MetricsRegistry())
    kzg.set_backend(loader.GuardedKzgBackend(BoomKzg(), br))
    try:
        setup = kzg.insecure_setup()
        # nonzero polynomial: keeps commitment/proof off the infinity
        # point so the host pairing path is exercised for real
        blob = ((7).to_bytes(32, "big")
                + b"\x00" * (kzg.BYTES_PER_BLOB - 32))
        commitment = kzg.blob_to_kzg_commitment(blob, setup)
        proof = kzg.compute_blob_kzg_proof(blob, commitment, setup)
        # device raises -> breaker opens -> host path still verifies
        assert kzg.verify_blob_kzg_proof_batch(
            [blob], [commitment], [proof], setup)
        assert br.state == CircuitBreaker.OPEN
    finally:
        kzg.set_backend(None)
