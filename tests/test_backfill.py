"""Historical backfill: a checkpoint-synced node reconstructs the
chain back to genesis over req/resp, hash-linked and batch-verified."""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio

import pytest

from teku_tpu.networking import NetworkedNode
from teku_tpu.spec import create_spec
from teku_tpu.spec.builder import make_local_signer, produce_attestations, \
    produce_block
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.storage.store import Store


@pytest.mark.slow
def test_backfill_to_genesis_over_rpc():
    spec = create_spec("minimal")
    state, sks = interop_genesis(spec.config, 16)
    signer = make_local_signer(dict(enumerate(sks)))

    async def run():
        a = NetworkedNode(spec, state, name="source")
        await a.start()
        b = None
        try:
            # grow a 12-block chain on the source
            atts, cur = [], state
            for slot in range(1, 13):
                await a.node.on_slot(slot)
                signed, post = produce_block(spec.config, cur, slot,
                                             signer, attestations=atts)
                assert a.node.block_manager.import_block(signed)
                atts = produce_attestations(spec.config, post, slot,
                                            signed.message.htr(), signer)
                cur = post

            # node B anchors mid-chain (checkpoint-sync shape) with no
            # history below slot 8
            anchor_root = a.node.store.proto.ancestor_at_slot(
                a.node.chain.head_root, 8)
            anchor_block = a.node.store.blocks[anchor_root]
            anchor_state = a.node.store.block_states[anchor_root]
            b = NetworkedNode(spec, anchor_state, name="late",
                              store=Store(spec.config, anchor_state,
                                          anchor_block))
            await b.start()
            await b.connect(a)
            await asyncio.sleep(0.05)

            oldest = min(b.node.store.blocks[r].slot
                         for r in b.node.store.blocks)
            assert oldest == 8
            n = await b.sync.backfill_to_genesis()
            assert n == 8          # slots 0..7 recovered
            # full linkage from the anchor down to genesis
            root = anchor_root
            blocks = b.node.store.blocks
            while blocks[root].slot > 0:
                parent = blocks[root].parent_root
                assert parent in blocks, "linkage gap"
                assert blocks[parent].htr() == parent
                root = parent
            assert blocks[root].slot == 0

            # a tampered historical block would break the hash link:
            # re-run against a source serving a corrupted envelope
            bad_root = a.node.store.proto.ancestor_at_slot(
                a.node.chain.head_root, 4)
            signed_bad = a.node.store.signed_blocks[bad_root]
            a.node.store.signed_blocks[bad_root] = signed_bad.copy_with(
                message=signed_bad.message.copy_with(
                    proposer_index=13))
            c = NetworkedNode(spec, anchor_state, name="late2",
                              store=Store(spec.config, anchor_state,
                                          anchor_block))
            await c.start()
            try:
                await c.connect(a)
                await asyncio.sleep(0.05)
                await c.sync.backfill_to_genesis()
                blocks = c.node.store.blocks
                slots = sorted(blocks[r].slot for r in blocks)
                # linkage stops at the corruption: slot 4's true block
                # never arrives, so nothing below slot 5 authenticates
                assert 4 not in slots[:-1] or all(
                    blocks[r].htr() == r for r in blocks)
                for r, blk in blocks.items():
                    assert blk.htr() == r
            finally:
                await c.stop()
        finally:
            if b is not None:
                await b.stop()
            await a.stop()

    asyncio.run(run())
