"""Pairing property tests: bilinearity, non-degeneracy, multi-pairing."""

import random

from teku_tpu.crypto.bls import curve as C, fields as F, pairing as PR
from teku_tpu.crypto.bls.constants import R

rng = random.Random(7)

G1_AFF = C.to_affine(C.FQ_OPS, C.G1_GENERATOR)
G2_AFF = C.to_affine(C.FQ2_OPS, C.G2_GENERATOR)
E_GG = PR.pairing(G1_AFF, G2_AFF)


def g1(k):
    return C.to_affine(C.FQ_OPS, C.point_mul(C.FQ_OPS, k, C.G1_GENERATOR))


def g2(k):
    return C.to_affine(C.FQ2_OPS, C.point_mul(C.FQ2_OPS, k, C.G2_GENERATOR))


class TestPairing:
    def test_non_degenerate(self):
        assert not F.fq12_is_one(E_GG)

    def test_output_in_gt(self):
        # e(G1, G2)^r == 1: output has order dividing r
        assert F.fq12_is_one(F.fq12_pow(E_GG, R))

    def test_bilinear_in_g1(self):
        a = rng.randrange(2, 10 ** 6)
        assert F.fq12_eq(PR.pairing(g1(a), G2_AFF), F.fq12_pow(E_GG, a))

    def test_bilinear_in_g2(self):
        b = rng.randrange(2, 10 ** 6)
        assert F.fq12_eq(PR.pairing(G1_AFF, g2(b)), F.fq12_pow(E_GG, b))

    def test_bilinear_joint(self):
        a = rng.randrange(2, 10 ** 6)
        b = rng.randrange(2, 10 ** 6)
        assert F.fq12_eq(PR.pairing(g1(a), g2(b)),
                         F.fq12_pow(E_GG, (a * b) % R))

    def test_additive_in_g1(self):
        # e(P1 + P2, Q) = e(P1,Q) e(P2,Q)
        p1, p2 = 111, 222
        lhs = PR.pairing(g1(p1 + p2), G2_AFF)
        rhs = F.fq12_mul(PR.pairing(g1(p1), G2_AFF), PR.pairing(g1(p2), G2_AFF))
        assert F.fq12_eq(lhs, rhs)

    def test_infinity_pairs_to_one(self):
        assert F.fq12_is_one(PR.pairing(None, G2_AFF))
        assert F.fq12_is_one(PR.pairing(G1_AFF, None))

    def test_multi_pairing_cancellation(self):
        # e(aG1, G2) * e(-aG1, G2) == 1
        a = 314159
        neg = C.to_affine(
            C.FQ_OPS, C.point_neg(C.FQ_OPS, C.point_mul(C.FQ_OPS, a, C.G1_GENERATOR)))
        result = PR.multi_pairing([(g1(a), G2_AFF), (neg, G2_AFF)])
        assert F.fq12_is_one(result)

    def test_multi_pairing_verify_equation(self):
        # The BLS verify equation: e(pk, H) * e(-G1, sig) == 1 where
        # pk = sk*G1 and sig = sk*H for any H in G2.
        sk = 987654321
        h = g2(424242)  # stand-in for a hashed message point
        pk = g1(sk)
        sig = C.to_affine(
            C.FQ2_OPS,
            C.point_mul(C.FQ2_OPS, sk, C.from_affine(C.FQ2_OPS, *h)))
        neg_g1 = C.to_affine(C.FQ_OPS, C.point_neg(C.FQ_OPS, C.G1_GENERATOR))
        assert F.fq12_is_one(PR.multi_pairing([(pk, h), (neg_g1, sig)]))


def test_twist_miller_matches_untwist_oracle():
    """Production twist-coordinate Miller loop == clarity-first untwist loop.

    The twist loop (Jacobian on E'/Fq2 with sparse line mults) is the
    algorithm the JAX kernel mirrors; the untwist loop is its independent
    oracle.  They must agree up to final exponentiation.
    """
    for _ in range(2):
        p = g1(rng.randrange(1, R))
        q = g2(rng.randrange(1, R))
        fast = PR.final_exponentiation(PR.miller_loop(p, q))
        slow = PR.final_exponentiation(PR.miller_loop_untwist(p, q))
        assert F.fq12_eq(fast, slow)
    # infinity handling is identical
    assert PR.miller_loop(None, q) == F.FQ12_ONE
    assert PR.miller_loop(p, None) == F.FQ12_ONE
