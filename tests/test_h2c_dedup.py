"""Dedup-aware verify pipeline: unique-message h2c, gather/scatter
exactness, the device-resident H(m) cache, and grouped-Miller parity.

Committee gossip signs the same AttestationData many times, so the
provider (teku_tpu/ops/provider.py) hashes-to-curve the batch's UNIQUE
messages only and folds each message's lanes into one Miller loop via
pairing bilinearity (ops/verify.py:stage_group).  These tests pin the
contract edges: all-duplicate / all-unique / duplicate-across-the-
padding-boundary batches, bit-exact gather/scatter, warm-cache batches
making ZERO h2c dispatches with verdicts identical to the cold path
(on BOTH mont_mul paths), and a poisoned cache entry never flipping a
verdict (the hit is re-verified by key, `h2c.cache` fault site).

Batch shapes stay tiny (lane buckets 4/8/16, unique bucket 8) so the
CPU-XLA compiles are shared with the other provider tests and cached
persistently.
"""

import numpy as np
import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.crypto.bls.pure_impl import PureBls12381
from teku_tpu.infra import faults
from teku_tpu.ops import h2c_cache as HC
from teku_tpu.ops import mxu
from teku_tpu.ops import verify as V
from teku_tpu.ops.provider import JaxBls12381

PURE = PureBls12381()
SKS = [keygen(bytes([80 + i]) * 32) for i in range(6)]
PKS = [PURE.secret_key_to_public_key(sk) for sk in SKS]


@pytest.fixture(scope="module")
def impl():
    impl = JaxBls12381()
    bls.set_implementation(impl)
    yield impl
    bls.reset_implementation()


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.clear()


def _triples(lane_msgs, tamper_lane=None):
    """One single-key triple per lane; lanes sharing a message model a
    committee (distinct signers, same AttestationData)."""
    out = []
    for i, m in enumerate(lane_msgs):
        sign_msg = b"tampered" if i == tamper_lane else m
        out.append(([PKS[i % 6]], m, PURE.sign(SKS[i % 6], sign_msg)))
    return out


def _oracle_verdict(triples):
    return PURE.batch_verify(triples)


# --------------------------------------------------------------------------
# unique-index gather/scatter shapes
# --------------------------------------------------------------------------

def test_all_lanes_duplicate(impl):
    msgs = [b"dup-all"] * 4
    triples = _triples(msgs)
    d0 = impl.h2c_dispatch_count
    assert impl.batch_verify(triples) is True
    # one batch, one unique message -> exactly one h2c dispatch
    assert impl.h2c_dispatch_count == d0 + 1
    # and a bad signer among the duplicates still fails the batch
    assert impl.batch_verify(_triples(msgs, tamper_lane=2)) is False


def test_all_unique(impl):
    msgs = [b"uniq-%d" % i for i in range(4)]
    triples = _triples(msgs)
    assert impl.batch_verify(triples) is True
    assert impl.batch_verify(_triples(msgs, tamper_lane=1)) is False
    assert _oracle_verdict(triples) is True


def test_duplicate_across_padding_boundary(impl):
    # 3 real lanes pad to the 4-lane bucket; the duplicate pair spans
    # the last real lane, adjacent to the padding lanes (which map to
    # group row 0 — their contribution must stay masked)
    msgs = [b"pb-a", b"pb-b", b"pb-a"]
    triples = _triples(msgs)
    assert impl.batch_verify(triples) is True
    # tamper the duplicate that sits AT the padding boundary
    assert impl.batch_verify(_triples(msgs, tamper_lane=2)) is False
    assert _oracle_verdict(triples) is True


def test_multi_key_lanes_share_message(impl):
    # aggregate lanes (fast-aggregate semantics) over one message:
    # grouping must fold the in-kernel key aggregates too
    m = b"committee-agg"
    agg = PURE.aggregate_signatures(
        [PURE.sign(sk, m) for sk in SKS[:3]])
    triples = [
        (PKS[:3], m, agg),
        ([PKS[3]], m, PURE.sign(SKS[3], m)),
        ([PKS[4]], b"other", PURE.sign(SKS[4], b"other")),
    ]
    assert impl.batch_verify(triples) is True
    bad = list(triples)
    bad[0] = (PKS[:2], m, agg)      # wrong key set for the aggregate
    assert impl.batch_verify(bad) is False


# --------------------------------------------------------------------------
# gather/scatter exactness + grouped-vs-per-lane parity
# --------------------------------------------------------------------------

def test_gather_scatter_bit_exact():
    import __graft_entry__ as ge
    (pk_xs, pk_ys, pk_present, u0, u1, group_idx, group_present,
     sig_x, s_large, s_inf, r_bits, lane_valid) = ge._example_batch(4)
    jits = V.staged_jits()
    hm_uniq = jits["h2c"](u0, u1)
    # lane_map derived from the group index
    n = pk_xs.shape[0]
    lane_map = np.zeros(n, dtype=np.int32)
    for u in range(group_idx.shape[0]):
        for g in range(group_idx.shape[1]):
            if group_present[u, g]:
                lane_map[group_idx[u, g]] = u
    hm_lanes = jits["gather"](hm_uniq, lane_map)
    # the gather is a pure scatter of rows: bit-identical limbs
    (ux, uxi), (uy, uyi) = (np.asarray(a) for a in hm_uniq[0]), \
                           (np.asarray(a) for a in hm_uniq[1])
    (lx, lxi), (ly, lyi) = hm_lanes
    assert np.array_equal(np.asarray(lx), np.asarray(ux)[lane_map])
    assert np.array_equal(np.asarray(lxi), np.asarray(uxi)[lane_map])
    assert np.array_equal(np.asarray(ly), np.asarray(uy)[lane_map])
    assert np.array_equal(np.asarray(lyi), np.asarray(uyi)[lane_map])
    # per-lane (gathered hm) and grouped pipelines agree on the verdict
    ok_lane, lane_ok1 = V.verify_staged_hm(
        pk_xs, pk_ys, pk_present, hm_lanes, sig_x, s_large, s_inf,
        r_bits, lane_valid)
    ok_grp, lane_ok2 = V.verify_staged_grouped(
        pk_xs, pk_ys, pk_present, hm_uniq, group_idx, group_present,
        sig_x, s_large, s_inf, r_bits, lane_valid)
    assert bool(np.asarray(ok_lane)) is bool(np.asarray(ok_grp)) is True
    assert np.array_equal(np.asarray(lane_ok1), np.asarray(lane_ok2))


# --------------------------------------------------------------------------
# device-resident H(m) cache: warm batches make ZERO h2c dispatches
# --------------------------------------------------------------------------

def _warm_cold_parity(impl):
    msgs = [b"warm-a", b"warm-b"] * 2
    good = _triples(msgs)
    bad = _triples(msgs, tamper_lane=3)
    cold_good = impl.batch_verify(good)
    d0 = impl.h2c_dispatch_count
    warm_good = impl.batch_verify(good)     # fully warm: same messages
    warm_bad = impl.batch_verify(bad)
    assert impl.h2c_dispatch_count == d0, \
        "fully-warm batches must make zero h2c dispatches"
    assert (cold_good, warm_good, warm_bad) == (True, True, False)
    st = impl._h2c_cache.stats()
    assert st["hits"] > 0


def test_warm_cache_zero_h2c_dispatch_vpu(impl):
    assert impl.mont_path == "vpu"     # CPU backend resolves to vpu
    _warm_cold_parity(impl)


def test_warm_cache_parity_mxu_force():
    """The cache-warm path on the MXU mont_mul engine: cold h2c output
    and warm arena round trip must be BIT-IDENTICAL limb arrays.

    Point-level bit-identity subsumes verdict identity (the downstream
    stages are deterministic in their inputs), so this gates the
    warm-vs-cold contract on the mxu path while compiling only the h2c
    stage under the forced engine — the full-pipeline warm/cold gate
    runs on the vpu path above, and cross-engine full-pipeline parity
    is owned by tests/test_ops_limbs.py's bit-identical mont_mul
    contract."""
    import hashlib
    import jax
    with mxu.force("mxu-force"):
        # a FRESH jit object retraces stage_h2c under the forced
        # engine even at an already-seen shape
        h2c_mxu = jax.jit(V.stage_h2c)
        impl = JaxBls12381()
        assert impl.mont_path == "mxu"
        msgs = [b"mxu-warm-a", b"mxu-warm-b"]
        u0, u1 = impl._uniq_draws(msgs, 8)
        cold = h2c_mxu(u0, u1)
        cache = HC.H2cPointCache(capacity=8)
        digests = [hashlib.sha256(m).digest() for m in msgs]
        cache.insert(digests, cold)
        slots = [cache.lookup(dg) for dg in digests]
        assert None not in slots            # warm: zero h2c recomputes
        warm = cache.gather(np.asarray(slots))
        (cx0, cx1), (cy0, cy1) = cold
        (wx0, wx1), (wy0, wy1) = warm
        for c, w in ((cx0, wx0), (cx1, wx1), (cy0, wy0), (cy1, wy1)):
            assert np.array_equal(np.asarray(c)[:2], np.asarray(w))


def test_cache_disabled_still_dedups(monkeypatch):
    monkeypatch.setenv(HC.ENV_CAP, "0")
    impl = JaxBls12381()
    assert not impl._h2c_cache.enabled
    msgs = [b"nocache-x"] * 3 + [b"nocache-y"]
    d0 = impl.h2c_dispatch_count
    assert impl.batch_verify(_triples(msgs)) is True
    assert impl.h2c_dispatch_count == d0 + 1   # one dispatch, 2 uniques
    # no cache: the repeat pays h2c again
    d1 = impl.h2c_dispatch_count
    assert impl.batch_verify(_triples(msgs)) is True
    assert impl.h2c_dispatch_count == d1 + 1


def test_oversized_committee_splits_across_group_rows(monkeypatch):
    # a committee larger than the group cap splits across Miller rows
    # (bounded (U, G) matrix); the rows share one H(m) point and the
    # verdict is unchanged
    monkeypatch.setenv("TEKU_TPU_H2C_GROUP_CAP", "2")
    impl = JaxBls12381()
    assert impl._group_cap == 2
    msgs = [b"split-big"] * 3 + [b"split-one"]
    d0 = impl.h2c_dispatch_count
    assert impl.batch_verify(_triples(msgs)) is True
    assert impl.h2c_dispatch_count == d0 + 1     # still ONE h2c dispatch
    assert impl.batch_verify(_triples(msgs, tamper_lane=1)) is False


def test_more_uniques_than_capacity_bypasses_cache(monkeypatch):
    # a cold batch carrying more unique messages than the WHOLE arena
    # holds would recycle slots assigned earlier in the same insert
    # (duplicate scatter indices -> wrong points served); the provider
    # bypasses the cache for such batches and insert() rejects them
    monkeypatch.setenv(HC.ENV_CAP, "2")
    impl = JaxBls12381()
    assert impl._h2c_cache.capacity == 2
    msgs = [b"overcap-%d" % i for i in range(4)]   # 4 uniques > cap 2
    d0 = impl.h2c_dispatch_count
    assert impl.batch_verify(_triples(msgs)) is True
    assert impl.h2c_dispatch_count == d0 + 1       # one bypass dispatch
    assert len(impl._h2c_cache) == 0               # arena untouched
    assert impl.batch_verify(_triples(msgs, tamper_lane=2)) is False
    with pytest.raises(ValueError):
        impl._h2c_cache.insert([bytes([i]) * 32 for i in range(3)],
                               None)


def test_cache_lru_eviction_bound(impl):
    cache = HC.H2cPointCache(capacity=4)
    jits = V.staged_jits()
    rows = jits["h2c"](
        *impl._uniq_draws([b"ev-%d" % i for i in range(6)], 8))
    digests = [bytes([i]) * 32 for i in range(6)]
    cache.insert(digests[:4], rows)
    assert len(cache) == 4 and cache.evictions == 0
    cache.insert(digests[4:], rows)     # 2 more -> 2 LRU evictions
    assert len(cache) == 4 and cache.evictions == 2
    assert cache.lookup(digests[0]) is None      # LRU victim gone
    assert cache.lookup(digests[5]) is not None


# --------------------------------------------------------------------------
# fault injection: a poisoned cache entry must not flip a verdict
# --------------------------------------------------------------------------

@pytest.mark.faults
def test_poisoned_cache_entry_does_not_flip_verdict(impl):
    msgs = [b"poison-a", b"poison-b"] * 2
    good = _triples(msgs)
    assert impl.batch_verify(good) is True          # warm the cache
    st0 = impl._h2c_cache.stats()
    # poison every lookup of the next batch: resolved slots point at
    # the WRONG arena rows — the digest re-verification must catch it,
    # drop the entry, and recompute instead of trusting the hit
    faults.inject("h2c.cache",
                  faults.WrongResult(value=impl._h2c_cache.capacity - 1,
                                     times=2))
    d0 = impl.h2c_dispatch_count
    assert impl.batch_verify(good) is True, \
        "poisoned H(m) cache entry flipped a verdict"
    st1 = impl._h2c_cache.stats()
    assert st1["misses"] > st0["misses"]     # poison detected as miss
    assert impl.h2c_dispatch_count > d0      # recomputed, not trusted
    faults.clear("h2c.cache")
    # the recomputed entries are clean again: warm + zero dispatches
    d1 = impl.h2c_dispatch_count
    assert impl.batch_verify(good) is True
    assert impl.h2c_dispatch_count == d1


# --------------------------------------------------------------------------
# dedup metrics
# --------------------------------------------------------------------------

def test_dedup_metrics_track_lanes_and_uniques(impl):
    from teku_tpu.ops import provider as pv
    lanes0 = pv._M_H2C_LANES.value
    uniq0 = pv._M_H2C_UNIQUE.value
    assert impl.batch_verify(_triples([b"metric-m"] * 4)) is True
    assert pv._M_H2C_LANES.value == lanes0 + 4
    assert pv._M_H2C_UNIQUE.value == uniq0 + 1
    assert 0.0 <= pv._dedup_ratio() < 1.0
