"""Fork choice: unit behavior + the REFERENCE'S OWN scenario fixtures.

The reference ships 32 fork-choice scenario files (official test
format: genesis + slot/block/attestation steps + head checks) with real
minimal-preset SSZ objects (/root/reference/fork-choice-tests/src/
integration-test/resources/, runner ForkChoiceIntegrationTest.java).
Running them against our Store/ProtoArray checks head selection, block
admission, attestation validation and signature handling end to end
against independently-produced expectations.
"""

from pathlib import Path

import pytest
import yaml

from teku_tpu.crypto import bls
from teku_tpu.spec import config as C
from teku_tpu.spec.datastructures import SCHEMAS_MINIMAL as S
from teku_tpu.storage import ForkChoiceError, ProtoArray, Store

from .test_ssz import _attestation_from_yaml, _block_from_yaml, _h

RES = Path("/root/reference/fork-choice-tests/src/integration-test/"
           "resources")
CACHE = RES / "cache"
CFG = C.MINIMAL

needs_fixtures = pytest.mark.skipif(
    not RES.is_dir(), reason="reference fixtures not present")


# --------------------------------------------------------------------------
# ProtoArray unit behavior
# --------------------------------------------------------------------------

def _root(i: int) -> bytes:
    return bytes([i]) * 32


def test_protoarray_heaviest_branch_wins():
    p = ProtoArray()
    p.on_block(0, _root(1), _root(0), 0, 0)
    p.on_block(1, _root(2), _root(1), 0, 0)   # branch A
    p.on_block(1, _root(3), _root(1), 0, 0)   # branch B
    # two validators vote A, one votes B
    p.process_attestation(0, _root(2), 1)
    p.process_attestation(1, _root(2), 1)
    p.process_attestation(2, _root(3), 1)
    head = p.find_head(_root(1), 0, 0, [10, 10, 10], 1)
    assert head == _root(2)
    # votes move to B with higher target epoch
    p.process_attestation(0, _root(3), 2)
    p.process_attestation(1, _root(3), 2)
    head = p.find_head(_root(1), 0, 0, [10, 10, 10], 2)
    assert head == _root(3)


def test_protoarray_proposer_boost_transient():
    p = ProtoArray()
    p.on_block(0, _root(1), _root(0), 0, 0)
    p.on_block(1, _root(2), _root(1), 0, 0)
    p.on_block(1, _root(3), _root(1), 0, 0)
    p.process_attestation(0, _root(2), 1)
    p.set_proposer_boost(_root(3), 100)
    assert p.find_head(_root(1), 0, 0, [10], 1) == _root(3)
    p.clear_proposer_boost()
    assert p.find_head(_root(1), 0, 0, [10], 1) == _root(2)


def test_protoarray_equal_weight_tiebreak_is_stable():
    p = ProtoArray()
    p.on_block(0, _root(1), _root(0), 0, 0)
    p.on_block(1, _root(4), _root(1), 0, 0)
    p.on_block(1, _root(9), _root(1), 0, 0)
    # no votes: higher root wins (byte compare), deterministically
    h1 = p.find_head(_root(1), 0, 0, [], 1)
    h2 = p.find_head(_root(1), 0, 0, [], 1)
    assert h1 == h2 == _root(9)


# --------------------------------------------------------------------------
# Scenario runner (official fork-choice test format)
# --------------------------------------------------------------------------

def _load_block(step_val):
    if isinstance(step_val, str):
        return S.SignedBeaconBlock.deserialize(
            (CACHE / step_val).read_bytes())
    return _block_from_yaml(step_val)


def _load_attestation(step_val):
    if isinstance(step_val, str):
        return S.Attestation.deserialize((CACHE / step_val).read_bytes())
    return _attestation_from_yaml(step_val)


def _genesis_store(state) -> Store:
    anchor = S.BeaconBlock(
        slot=state.slot, parent_root=bytes(32),
        state_root=state.htr(), body=S.BeaconBlockBody())
    return Store(CFG, state, anchor)


def run_scenario(path: Path):
    """Replays a scenario with the node-side pending semantics the
    reference runner exercises (statetransition AttestationManager /
    BlockManager pools): future blocks and unknown-block attestations
    are queued and retried, never dropped."""
    doc = yaml.safe_load(path.read_text())
    bls_required = doc.get("meta", {}).get("bls_setting", 1) == 1
    old_disabled = bls.verification_disabled
    bls.verification_disabled = not bls_required
    pending_blocks: list = []
    pending_atts: list = []

    def try_block(blk) -> bool:
        try:
            store.on_block(blk)
            return True
        except ForkChoiceError as exc:
            if "future" in str(exc) or "unknown parent" in str(exc):
                pending_blocks.append(blk)
            return False

    def try_attestation(att) -> bool:
        try:
            store.on_attestation(att)
            return True
        except ForkChoiceError as exc:
            if "unknown" in str(exc) or "future" in str(exc):
                pending_atts.append(att)
            return False

    def drain_pending():
        progress = True
        while progress:
            progress = False
            for blk in pending_blocks[:]:
                pending_blocks.remove(blk)
                if try_block(blk):
                    progress = True
            for att in pending_atts[:]:
                pending_atts.remove(att)
                if try_attestation(att):
                    progress = True

    try:
        state = S.BeaconState.deserialize(
            (CACHE / doc["genesis"]).read_bytes())
        store = _genesis_store(state)
        for step in doc["steps"]:
            if "slot" in step and "checks" not in step:
                target = state.genesis_time + step["slot"] * CFG.SECONDS_PER_SLOT
                store.on_tick(target)
                drain_pending()
            elif "block" in step:
                try_block(_load_block(step["block"]))
                drain_pending()
            elif "attestation" in step:
                try_attestation(_load_attestation(step["attestation"]))
            elif "checks" in step:
                checks = step["checks"]
                if "head" in checks:
                    assert store.get_head() == _h(checks["head"]), (
                        f"{path.name}: head mismatch at step {step}")
                if "block_in_store" in checks:
                    assert _h(checks["block_in_store"]) in store.blocks, (
                        f"{path.name}: missing block")
                if "block_not_in_store" in checks:
                    assert (_h(checks["block_not_in_store"])
                            not in store.blocks), (
                        f"{path.name}: block should be rejected")
                if "justified_checkpoint_epoch" in checks:
                    assert (store.justified_checkpoint.epoch
                            == checks["justified_checkpoint_epoch"]), (
                        f"{path.name}: justified epoch")
    finally:
        bls.verification_disabled = old_disabled


def _scenarios():
    out = []
    for group in ("valid_block", "invalid_block", "valid_attestation",
                  "invalid_attestation"):
        for f in sorted((RES / group).glob("*.yaml")):
            out.append(pytest.param(f, id=f"{group}/{f.stem}"))
    return out


@needs_fixtures
@pytest.mark.slow
@pytest.mark.parametrize("path", _scenarios())
def test_reference_scenario(path):
    run_scenario(path)
