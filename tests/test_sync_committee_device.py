"""Sync-committee signature sets on the batched device provider.

The second device verb (ROADMAP 4): contribution/sync-message
verification rides the batched JAX provider end-to-end — including the
multi-pubkey fast-aggregate lane over the shared sync root — with
parity pinned against the per-signature pure oracle, and the demand
accounted under its own ``sync_committee`` arrival source."""

import asyncio
import dataclasses

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.crypto.bls.pure_impl import PureBls12381
from teku_tpu.infra.capacity import (CapacityTelemetry, SOURCE_KZG,
                                     SOURCE_SYNC_COMMITTEE)
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.services.admission import VerifyClass
from teku_tpu.services.signatures import (
    AggregatingSignatureVerificationService)

jax = pytest.importorskip("jax")


def _sync_set(tamper: bool = False):
    """A synthetic sync-committee signature set in the production
    shape: selection proof + envelope (single-key lanes) and the
    aggregated contribution over the shared sync root (one multi-key
    fast-aggregate lane)."""
    oracle = PureBls12381()
    agg_sk = keygen(b"\x51" * 32)
    agg_pk = oracle.secret_key_to_public_key(agg_sk)
    sel_root = b"sel-root".ljust(32, b"\x01")
    env_root = b"env-root".ljust(32, b"\x02")
    sync_root = b"sync-root".ljust(32, b"\x03")
    member_sks = [keygen(bytes([0x60 + i]) * 32) for i in range(4)]
    member_pks = [oracle.secret_key_to_public_key(sk)
                  for sk in member_sks]
    contribution_sig = oracle.aggregate_signatures(
        [oracle.sign(sk, sync_root) for sk in member_sks])
    env_sig = oracle.sign(agg_sk, env_root)
    if tamper:
        env_sig = oracle.sign(agg_sk, b"wrong-root".ljust(32, b"\x04"))
    return [
        ([agg_pk], sel_root, oracle.sign(agg_sk, sel_root)),
        ([agg_pk], env_root, env_sig),
        (member_pks, sync_root, contribution_sig),
    ]


def _oracle_verdict(triples) -> bool:
    """The per-signature oracle path: each lane as one independent
    fast-aggregate verify on the pure implementation."""
    oracle = PureBls12381()
    return all(oracle.fast_aggregate_verify(pks, msg, sig)
               for pks, msg, sig in triples)


@pytest.fixture(scope="module")
def provider():
    from teku_tpu.ops.provider import JaxBls12381
    return JaxBls12381(max_batch=8, min_bucket=8)


def test_sync_set_device_oracle_parity(provider):
    """The acceptance pin: a sync-committee signature set verifies
    through the batched device provider with the SAME verdict as the
    per-signature oracle path — valid and tampered."""
    good = _sync_set()
    assert _oracle_verdict(good) is True
    assert provider.batch_verify(good) is True
    bad = _sync_set(tamper=True)
    assert _oracle_verdict(bad) is False
    assert provider.batch_verify(bad) is False


def test_contribution_signature_set_shape():
    """The shared triple-set definition (spec/altair/helpers) produces
    exactly the three lanes the validator batches, with participants
    filtered by the aggregation bits — and end-to-end, the set it
    builds against a REAL altair state verifies on the device provider
    and the oracle alike."""
    from teku_tpu.spec import config as C
    from teku_tpu.spec import helpers as H
    from teku_tpu.spec.altair import helpers as AH
    from teku_tpu.spec.altair.datastructures import get_altair_schemas
    from teku_tpu.spec.genesis import interop_genesis

    cfg = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0)
    state, sks = interop_genesis(cfg, 8)
    assert hasattr(state, "current_sync_committee")
    S = get_altair_schemas(cfg)
    pk_to_sk = {bls.secret_to_public_key(sk): sk for sk in sks}
    sub = 1
    positions, pubkeys = AH.sync_subcommittee_members(cfg, state, sub)
    slot = 1
    root = b"\x07" * 32
    bits = tuple(i % 2 == 0 for i in range(len(pubkeys)))
    sync_root = AH.sync_message_signing_root(cfg, state, slot, root)
    contribution = S.SyncCommitteeContribution(
        slot=slot, beacon_block_root=root, subcommittee_index=sub,
        aggregation_bits=bits,
        signature=bls.aggregate_signatures(
            [bls.sign(pk_to_sk[pk], sync_root)
             for pk, b in zip(pubkeys, bits) if b]))
    aggregator_index = 3
    agg_sk = sks[aggregator_index]
    msg = S.ContributionAndProof(
        aggregator_index=aggregator_index, contribution=contribution,
        selection_proof=bls.sign(
            agg_sk, AH.sync_selection_proof_signing_root(
                cfg, state, slot, sub)))
    signed = S.SignedContributionAndProof(
        message=msg, signature=bls.sign(
            agg_sk, AH.contribution_and_proof_signing_root(cfg, state,
                                                           msg)))

    triples = AH.contribution_signature_set(cfg, state, signed, pubkeys)
    assert len(triples) == 3
    sel, env, contrib = triples
    assert sel[0] == env[0] == [state.validators[
        aggregator_index].pubkey]
    assert contrib[0] == [pk for pk, b in zip(pubkeys, bits) if b]
    assert contrib[1] == sync_root
    # the whole set verifies per-signature on the oracle
    assert _oracle_verdict(triples) is True
    # no participants -> None (the validator REJECTs)
    empty = S.SignedContributionAndProof(
        message=S.ContributionAndProof(
            aggregator_index=aggregator_index,
            contribution=contribution.copy_with(
                aggregation_bits=tuple(False for _ in bits)),
            selection_proof=msg.selection_proof),
        signature=signed.signature)
    assert AH.contribution_signature_set(cfg, state, empty,
                                         pubkeys) is None


def test_contribution_set_parity_on_device(provider):
    """The real-state contribution set from the helper verifies
    identically through the batched provider."""
    from teku_tpu.spec import config as C
    from teku_tpu.spec.altair import helpers as AH
    from teku_tpu.spec.altair.datastructures import get_altair_schemas
    from teku_tpu.spec.genesis import interop_genesis

    cfg = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0)
    state, sks = interop_genesis(cfg, 8)
    S = get_altair_schemas(cfg)
    pk_to_sk = {bls.secret_to_public_key(sk): sk for sk in sks}
    positions, pubkeys = AH.sync_subcommittee_members(cfg, state, 0)
    slot, root = 2, b"\x09" * 32
    bits = tuple(True for _ in pubkeys)
    sync_root = AH.sync_message_signing_root(cfg, state, slot, root)
    contribution = S.SyncCommitteeContribution(
        slot=slot, beacon_block_root=root, subcommittee_index=0,
        aggregation_bits=bits,
        signature=bls.aggregate_signatures(
            [bls.sign(pk_to_sk[pk], sync_root) for pk in pubkeys]))
    msg = S.ContributionAndProof(
        aggregator_index=0, contribution=contribution,
        selection_proof=bls.sign(
            sks[0], AH.sync_selection_proof_signing_root(
                cfg, state, slot, 0)))
    signed = S.SignedContributionAndProof(
        message=msg, signature=bls.sign(
            sks[0], AH.contribution_and_proof_signing_root(cfg, state,
                                                           msg)))
    triples = AH.contribution_signature_set(cfg, state, signed, pubkeys)
    assert _oracle_verdict(triples) is True
    assert provider.batch_verify(triples) is True
    # one flipped participant bit breaks the aggregate lane everywhere
    tampered = [triples[0], triples[1],
                (triples[2][0][:-1], triples[2][1], triples[2][2])]
    assert _oracle_verdict(tampered) is False
    assert provider.batch_verify(tampered) is False


def test_sync_committee_arrival_source_accounting():
    """A verification submitted with source="sync_committee" lands in
    the capacity model as its OWN demand stream, separate from the
    service's default source."""

    async def main():
        registry = MetricsRegistry()
        telemetry = CapacityTelemetry(registry=registry)
        svc = AggregatingSignatureVerificationService(
            num_workers=1, registry=registry, name="sync_acct",
            telemetry=telemetry)
        await svc.start()
        f1 = svc.verify([b"\xa0" + bytes(47)], b"m1", b"s1",
                        cls=VerifyClass.GOSSIP)
        f2 = svc.verify([b"\xa0" + bytes(47)], b"m2", b"s2",
                        cls=VerifyClass.SYNC_CRITICAL,
                        source=SOURCE_SYNC_COMMITTEE)
        for f in (f1, f2):
            try:
                await f
            except Exception:
                pass
        await svc.stop()
        return telemetry.snapshot()["arrival_rate_per_second"]

    arrivals = asyncio.run(main())
    assert SOURCE_SYNC_COMMITTEE in arrivals
    assert "sync_acct" in arrivals
    assert SOURCE_KZG == "kzg" and SOURCE_SYNC_COMMITTEE \
        == "sync_committee"


def test_contribution_validator_class_is_sync_critical():
    from teku_tpu.node.validators import ContributionValidator
    assert ContributionValidator.verify_cls \
        is VerifyClass.SYNC_CRITICAL
