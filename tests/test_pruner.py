"""Storage pruners: blob DA-window pruning, optional history
retention, epoch-throttled passes (reference: storage/.../server/
pruner/BlobSidecarPruner.java, BlockPruner.java, StatePruner.java).
"""

from teku_tpu.node.blobs import BlobSidecar
from teku_tpu.spec import config as C, create_spec
from teku_tpu.spec.builder import make_local_signer, produce_block
from teku_tpu.spec.datastructures import SCHEMAS_MINIMAL as S
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.storage.database import Database
from teku_tpu.storage.pruner import StoragePruner

CFG = C.MINIMAL


def _db(tmp_path, mode="archive"):
    return Database(tmp_path / "db", create_spec("minimal"), mode=mode)


def _sc(root, slot, index, tag=b"\x00"):
    return BlobSidecar(index=index, blob=b"", kzg_commitment=tag * 48,
                       kzg_proof=b"\x00" * 48, block_root=root,
                       slot=slot)


def test_blob_sidecars_roundtrip_and_prune(tmp_path):
    db = _db(tmp_path)
    r1, r2 = b"\x01" * 32, b"\x02" * 32
    db.save_blob_sidecars(r1, [_sc(r1, 8, i) for i in range(2)])
    db.save_blob_sidecars(r2, [_sc(r2, 64, 0, tag=b"\x11")])
    assert len(db.get_blob_sidecars(r1)) == 2
    assert len(db.get_blob_sidecars(r2)) == 1
    # round-trip preserves wire bytes
    raw = db.get_blob_sidecars(r2)[0]
    assert BlobSidecar.deserialize(raw).kzg_commitment == b"\x11" * 48
    removed = db.prune_blob_sidecars(cutoff_slot=32)
    assert removed == 2
    assert db.get_blob_sidecars(r1) == []
    assert len(db.get_blob_sidecars(r2)) == 1
    db.close()


def test_pruner_runs_once_per_epoch_and_uses_da_window(tmp_path):
    db = _db(tmp_path)
    root = b"\x03" * 32
    db.save_blob_sidecars(root, [_sc(root, 0, 0)])
    pruner = StoragePruner(db, CFG, blob_retention_epochs=2)
    spe = CFG.SLOTS_PER_EPOCH
    pruner.on_slot(1 * spe)           # cutoff would be negative: no-op
    assert pruner.blobs_pruned_total == 0
    pruner.on_slot(3 * spe)           # cutoff = (3-2)*spe > 0: prunes
    assert pruner.blobs_pruned_total == 1
    before = pruner.blobs_pruned_total
    pruner.on_slot(3 * spe + 1)       # mid-epoch: throttled
    assert pruner.blobs_pruned_total == before
    db.close()


def test_history_retention_prunes_blocks_and_states(tmp_path):
    """A rolling-window node: finalized history past the retention is
    dropped; the anchor and recent history survive."""
    db = _db(tmp_path)
    state, sks = interop_genesis(CFG, 32)
    signer = make_local_signer(dict(enumerate(sks)))
    anchor = S.BeaconBlock(slot=0, parent_root=bytes(32),
                           state_root=state.htr(),
                           body=S.BeaconBlockBody())
    db.save_anchor(anchor, state)
    cur = state
    last_root = None
    for slot in range(1, 6):
        signed, post = produce_block(CFG, cur, slot, signer)
        db.save_block(signed, post)
        last_root = signed.message.htr()
        db._kv.put(b"sl/" + slot.to_bytes(8, "big"), last_root)
        db._kv.put(b"st/" + last_root, type(post).serialize(post))
        cur = post
    blocks, states = db.prune_finalized_history(cutoff_slot=4)
    assert blocks == 3 and states == 3
    # anchor + recent blocks intact
    assert db.load_anchor() is not None
    assert db.canonical_root_at_slot(1) is None
    assert db.canonical_root_at_slot(5) is not None
    assert db.get_block(last_root) is not None
    db.close()
