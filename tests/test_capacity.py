"""Capacity & occupancy telemetry: windowed rate estimators, the
per-shape device-latency model, true device-time attribution under
async overlap, the admin capacity endpoint, and triggered profiler
capture."""

import asyncio
import time

import numpy as np
import pytest

from teku_tpu.crypto import bls
from teku_tpu.infra import capacity, profiling, tracing
from teku_tpu.infra.capacity import (CapacityTelemetry,
                                     DeviceOccupancyTracker,
                                     QueueDepthSeries, RateEstimator,
                                     ShapeLatencyModel)
from teku_tpu.infra.flightrecorder import FlightRecorder
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.services.signatures import (
    AggregatingSignatureVerificationService)


class FakeClock:
    """Injectable monotonic clock: tests advance time explicitly, so
    windowed-decay behavior is deterministic without sleeps."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _tracing_reset():
    tracing.set_enabled(True)
    yield
    tracing.set_enabled(True)
    tracing.clear_slow_traces()


# --------------------------------------------------------------------------
# Rate estimator
# --------------------------------------------------------------------------

def test_rate_estimator_empty_window_is_zero():
    est = RateEstimator(window_s=10.0, clock=FakeClock())
    assert est.rate() == 0.0
    assert est.total() == 0.0


def test_rate_estimator_windowed_decay_under_bursty_arrivals():
    clock = FakeClock()
    est = RateEstimator(window_s=10.0, buckets=10, clock=clock)
    # a burst of 100 events lands in one instant
    for _ in range(100):
        est.record()
    assert est.rate() == pytest.approx(10.0)        # 100 / 10s window
    # half a window later the burst still counts in full...
    clock.advance(5.0)
    assert est.rate() == pytest.approx(10.0)
    # ...and exactly one window later it has decayed out wholesale
    clock.advance(6.0)
    assert est.rate() == 0.0
    # steady trickle after the burst: only the windowed events count
    for _ in range(5):
        est.record(2.0)
        clock.advance(1.0)
    assert est.total() == pytest.approx(10.0)
    assert est.rate() == pytest.approx(1.0)


def test_rate_estimator_memory_is_bounded_by_bucket_count():
    clock = FakeClock()
    est = RateEstimator(window_s=10.0, buckets=10, clock=clock)
    for _ in range(10_000):
        est.record()
        clock.advance(0.001)
    assert len(est._buckets) <= 11


def test_queue_depth_series_tracks_current_and_history():
    series = QueueDepthSeries(capacity=4)
    for depth in (1, 5, 3, 7, 2):
        series.record(depth)
    assert series.current == 2
    snap = series.snapshot()
    assert [s["depth"] for s in snap] == [5, 3, 7, 2]   # bounded ring
    assert all("t_wall" in s for s in snap)


# --------------------------------------------------------------------------
# Per-shape latency model
# --------------------------------------------------------------------------

def test_shape_latency_model_ewma_and_percentiles():
    model = ShapeLatencyModel(alpha=0.5, window=64,
                              registry=MetricsRegistry())
    for v in (0.010, 0.010, 0.010, 0.010, 0.100):
        model.observe("256x1", "vpu", v)
    snap = model.snapshot()["256x1"]["vpu"]
    assert snap["samples"] == 5
    assert snap["p50_s"] == pytest.approx(0.010)
    assert snap["p95_s"] == pytest.approx(0.100)
    # alpha=0.5 EWMA after 4x10ms then one 100ms: (10+100)/2-ish
    assert 0.03 < snap["ewma_s"] < 0.07
    assert model.latency_s("256x1", "vpu") == snap["p50_s"]
    assert model.latency_s("999x9", "vpu") is None


def test_shape_latency_model_bounds_label_cardinality():
    reg = MetricsRegistry()
    model = ShapeLatencyModel(max_shapes=4, registry=reg)
    for i in range(10):
        model.observe(f"{2 ** i}x1", "vpu", 0.001 * (i + 1))
    shapes = set(model.snapshot())
    # 4 real shapes + the overflow bucket, never 10
    assert len(shapes) == 5
    assert ShapeLatencyModel.OVERFLOW in shapes
    # the exported gauge family carries the same bounded vocabulary
    gauge = reg.metrics()["bls_shape_device_latency_seconds"]
    label_shapes = {key[0] for key, _ in gauge._items()}
    assert label_shapes == shapes
    # overflow absorbed the 6 extra shapes' samples
    overflow = model.snapshot()[ShapeLatencyModel.OVERFLOW]["vpu"]
    assert overflow["samples"] == 6


# --------------------------------------------------------------------------
# Occupancy under overlap
# --------------------------------------------------------------------------

def test_occupancy_tracker_clamps_overlapping_dispatches():
    clock = FakeClock()
    occ = DeviceOccupancyTracker(window_s=10.0, clock=clock)
    # dispatch A: device busy 1.0 → 3.0
    assert occ.record(1.0, 3.0) == pytest.approx(2.0)
    # dispatch B was ENQUEUED at 2.0 while A executed; its true device
    # time starts only when A's program finished (3.0) — the wall
    # interval overlaps, the device time must not double-count
    assert occ.record(2.0, 4.5) == pytest.approx(1.5)
    assert occ.busy_seconds() == pytest.approx(3.5)
    assert occ.occupancy() == pytest.approx(0.35)
    # an interval fully covered by prior busy time contributes zero
    assert occ.record(3.0, 4.0) == 0.0


def test_occupancy_is_capped_at_one():
    clock = FakeClock()
    occ = DeviceOccupancyTracker(window_s=2.0, clock=clock)
    occ.record(0.0, 10.0)
    assert occ.occupancy() == 1.0


# --------------------------------------------------------------------------
# Combined capacity model
# --------------------------------------------------------------------------

def _telemetry(clock=None, recorder=None):
    return CapacityTelemetry(registry=MetricsRegistry(),
                             window_s=10.0,
                             clock=clock or FakeClock(),
                             recorder=recorder or FlightRecorder(
                                 registry=MetricsRegistry()))


def test_capacity_utilization_and_headroom_math():
    clock = FakeClock()
    tel = _telemetry(clock)
    # demand: 200 triples over the 10s window = 20/s
    tel.record_arrival("gossip", 120)
    tel.record_arrival("api", 80)
    # supply evidence: 256 lanes verified in 2.56s of device time
    # → 100 sigs/sec sustainable
    tel.record_dispatch("256x1", "vpu", 256, enqueue_end=1.0,
                        sync_end=3.56)
    assert tel.demand_sigs_per_second() == pytest.approx(20.0)
    assert tel.sustainable_sigs_per_second() == pytest.approx(100.0)
    assert tel.utilization() == pytest.approx(0.2)
    assert tel.headroom() == pytest.approx(0.8)
    snap = tel.snapshot()
    assert snap["arrival_rate_per_second"] == {"gossip": 12.0,
                                               "api": 8.0}
    assert snap["derived"]["headroom_sigs_per_second"] \
        == pytest.approx(80.0)
    assert snap["shapes"]["256x1"]["vpu"]["samples"] == 1


def test_capacity_utilization_falls_back_to_occupancy():
    tel = _telemetry()
    # no dispatch evidence at all: utilization must not divide by zero
    tel.record_arrival("gossip", 50)
    assert tel.sustainable_sigs_per_second() == 0.0
    assert tel.utilization() == tel.occupancy.occupancy() == 0.0


def test_headroom_exhausted_event_is_edge_triggered_with_trace_id():
    clock = FakeClock()
    rec = FlightRecorder(registry=MetricsRegistry())
    tel = _telemetry(clock, rec)
    # capacity 10 sigs/sec (10 lanes in 1s device time), demand 40/s
    tel.record_dispatch("8x1", "vpu", 10, enqueue_end=0.0, sync_end=1.0)
    tel.record_arrival("gossip", 400)
    tr = tracing.new_trace("overloaded_verify")
    with tracing.attach((tr,)):
        snap = tel.refresh()
    tracing.finish(tr)
    assert snap["derived"]["utilization"] > 1.0
    assert snap["derived"]["headroom_exhausted"] is True
    events = [e for e in rec.snapshot()
              if e["kind"] == "capacity_headroom_exhausted"]
    assert len(events) == 1
    assert events[0]["trace_id"] == tr.trace_id
    assert events[0]["demand_sigs_per_second"] > \
        events[0]["capacity_sigs_per_second"]
    # still exhausted: NO second event (edge, not level)
    tel.refresh()
    assert len([e for e in rec.snapshot()
                if e["kind"] == "capacity_headroom_exhausted"]) == 1
    # demand decays out of the window → one recovery event
    clock.advance(11.0)
    tel.record_dispatch("8x1", "vpu", 10, enqueue_end=clock.t,
                        sync_end=clock.t + 1.0)
    tel.refresh()
    kinds = [e["kind"] for e in rec.snapshot()]
    assert kinds.count("capacity_headroom_recovered") == 1


# --------------------------------------------------------------------------
# Attribution split: device_sync excludes host-prep overlap
# --------------------------------------------------------------------------

class _RealHandleImpl:
    """BLS impl whose async begin returns the provider's REAL
    _DispatchHandle over already-materialized numpy verdict arrays —
    the genuine device_sync span + capacity feed run without a device
    dispatch."""

    def __init__(self, host_prep_s: float = 0.05):
        self.host_prep_s = host_prep_s
        self.begins = 0

    def begin_batch_verify(self, triples):
        from teku_tpu.ops.provider import _DispatchHandle
        self.begins += 1
        with tracing.span("host_prep"):
            time.sleep(self.host_prep_s)
        n = len(triples)
        traces = tracing.current_traces()
        t_enq_end = time.perf_counter()
        tracing.record_stage("device_enqueue", 0.0, traces)
        return _DispatchHandle(
            np.True_, np.ones(max(n, 1), dtype=bool), n, traces,
            shape=f"{n}x1", path="vpu", t_enq_end=t_enq_end)

    def batch_verify(self, triples):
        return True

    def fast_aggregate_verify(self, pks, msg, sig):
        return True


def test_device_sync_excludes_host_prep_overlap(monkeypatch):
    """The PERF.md:229 caveat, fixed end-to-end: under
    TEKU_TPU_ASYNC_OVERLAP=1 the worker host_preps batch N+1 between
    batch N's enqueue and its sync.  The old combined device span
    started at enqueue and absorbed that host-prep time; the new
    device_sync span covers ONLY the blocking wait, so its p50 must
    sit far below the deliberately slow host_prep."""
    monkeypatch.setenv("TEKU_TPU_ASYNC_OVERLAP", "1")
    impl = _RealHandleImpl(host_prep_s=0.05)
    traces = []

    async def main():
        bls.set_implementation(impl)
        try:
            svc = AggregatingSignatureVerificationService(
                num_workers=1, max_batch_size=1,
                registry=MetricsRegistry(), name="cap_overlap")
            assert svc.overlap is True          # read from the env
            await svc.start()
            futs = []
            for i in range(6):
                tr = tracing.new_trace("overlap_verify")
                traces.append(tr)
                with tracing.attach((tr,)):
                    futs.append(svc.verify(
                        [b"\xa0" + bytes(47)], b"m%d" % i, b"sig"))
            assert all(await asyncio.gather(*futs))
            await svc.stop()
        finally:
            bls.reset_implementation()
        for tr in traces:
            tracing.finish(tr)

    asyncio.run(main())
    assert impl.begins >= 2, "overlap path never engaged"
    syncs, preps = [], []
    for tr in traces:
        for stage, dur in tr.stages:
            if stage == "device_sync":
                syncs.append(dur)
            elif stage == "host_prep":
                preps.append(dur)
    assert syncs and preps
    p50 = sorted(syncs)[len(syncs) // 2]
    # host_prep really was slow (the overlap work existed)...
    assert sorted(preps)[len(preps) // 2] >= 0.04
    # ...and device_sync did NOT absorb it (the old combined span
    # would have measured >= host_prep_s here)
    assert p50 < 0.025, f"device_sync p50 {p50:.3f}s includes overlap"


def test_dispatch_handle_feeds_capacity_shapes():
    """result() routes the overlap-corrected interval into the global
    capacity telemetry keyed by {shape, path}."""
    from teku_tpu.ops.provider import _DispatchHandle
    before = capacity.TELEMETRY.latency.snapshot().get(
        "16x2", {}).get("vpu", {}).get("samples", 0)
    h = _DispatchHandle(np.True_, np.ones(16, dtype=bool), 16, (),
                        shape="16x2", path="vpu",
                        t_enq_end=time.perf_counter())
    assert h.result() is True
    assert h.result() is True     # idempotent: records once
    after = capacity.TELEMETRY.latency.snapshot()["16x2"]["vpu"]
    assert after["samples"] == before + 1


# --------------------------------------------------------------------------
# Service + endpoint integration
# --------------------------------------------------------------------------

def test_admin_capacity_endpoint_serves_live_dispatch_model():
    """Service-level acceptance: live dispatches through the batching
    service land in the per-shape latency model, and the admin
    endpoint serves them with the utilization/headroom derivation."""
    from teku_tpu.api import BeaconRestApi

    impl = _RealHandleImpl(host_prep_s=0.0)

    async def main():
        bls.set_implementation(impl)
        try:
            svc = AggregatingSignatureVerificationService(
                num_workers=1, registry=MetricsRegistry(),
                name="cap_endpoint", overlap=True)
            await svc.start()
            futs = [svc.verify([b"\xa0" + bytes(47)], b"c%d" % i,
                               b"sig") for i in range(4)]
            assert all(await asyncio.gather(*futs))
            snap = svc.health_snapshot()
            await svc.stop()
        finally:
            bls.reset_implementation()
        api = BeaconRestApi(None)
        return snap, (await api._admin_capacity())["data"]

    snap, data = asyncio.run(main())
    # the service's health snapshot embeds the derived capacity view
    model = snap["capacity_model"]
    assert {"arrival_rate_per_second", "capacity_sigs_per_second",
            "utilization", "headroom_ratio",
            "occupancy_ratio"} <= set(model)
    assert model["arrival_rate_per_second"] > 0
    # the endpoint serves the full detail: this service's arrivals,
    # the per-shape model fed by its dispatch handles, and the
    # derived signals
    assert data["arrival_rate_per_second"]["cap_endpoint"] > 0
    shapes = {(s, p) for s, paths in data["shapes"].items()
              for p in paths}
    assert any(p == "vpu" for _, p in shapes)
    derived = data["derived"]
    assert derived["capacity_sigs_per_second"] >= 0
    assert 0.0 <= derived["headroom_ratio"] <= 1.0
    assert "headroom_exhausted" in derived
    assert data["queue_depth"]["series"]


# --------------------------------------------------------------------------
# Profiler capture
# --------------------------------------------------------------------------

class _FakeProfilerBackend:
    def __init__(self, fail_start: bool = False):
        self.fail_start = fail_start
        self.calls = []

    def start(self, log_dir):
        if self.fail_start:
            raise RuntimeError("no profiler here")
        self.calls.append(("start", log_dir))

    def stop(self):
        self.calls.append(("stop",))


def _controller(tmp_path, clock, backend=None, rec=None, **kw):
    return profiling.ProfilerController(
        backend=backend or _FakeProfilerBackend(),
        out_dir=str(tmp_path), clock=clock,
        registry=MetricsRegistry(),
        recorder=rec or FlightRecorder(registry=MetricsRegistry()),
        cooldown_s=60.0, auto_duration_s=2.0, burn_threshold=1.0,
        **kw)


def test_profiler_manual_start_stop_records_flight_events(tmp_path):
    clock = FakeClock()
    rec = FlightRecorder(registry=MetricsRegistry())
    ctl = _controller(tmp_path, clock, rec=rec)
    tr = tracing.new_trace("profiled_verify")
    with tracing.attach((tr,)):
        out = ctl.start()
    tracing.finish(tr)
    assert out["trigger"] == "manual" and "path" in out
    assert ctl.status()["active"] is True
    # a second start while active is refused, not stacked
    assert "error" in ctl.start()
    clock.advance(3.0)
    done = ctl.stop()
    assert done["duration_s"] == pytest.approx(3.0)
    assert ctl.status()["active"] is False
    assert ctl.status()["last"]["path"] == out["path"]
    assert "error" in ctl.stop()              # nothing active anymore
    kinds = [(e["kind"], e.get("trace_id")) for e in rec.snapshot()]
    assert ("profiler_capture_start", tr.trace_id) in kinds
    assert any(k == "profiler_capture_stop" for k, _ in kinds)


def test_profiler_burn_trigger_cooldown_and_auto_stop(tmp_path):
    clock = FakeClock()
    rec = FlightRecorder(registry=MetricsRegistry())
    ctl = _controller(tmp_path, clock, rec=rec)
    # below threshold / wrong objective: no capture
    assert not ctl.maybe_trigger("attestation_verify_p50", 0.9)
    assert not ctl.maybe_trigger("verify_success_ratio", 99.0)
    # burning: one auto capture starts...
    assert ctl.maybe_trigger("attestation_verify_p50", 5.0)
    assert ctl.status()["capture"]["trigger"] == "burn_rate"
    # ...the tick's poll stops it after auto_duration_s...
    clock.advance(1.0)
    ctl.poll()
    assert ctl.status()["active"] is True
    clock.advance(1.5)
    ctl.poll({"attestation_verify_p50": {"burn_rate": 5.0}})
    assert ctl.status()["active"] is False
    # ...and the cooldown suppresses a re-trigger (even via poll)
    ctl.poll({"attestation_verify_p50": {"burn_rate": 5.0}})
    assert ctl.status()["active"] is False
    # past the cooldown the trigger arms again
    clock.advance(61.0)
    assert ctl.maybe_trigger("attestation_verify_p50", 5.0)
    starts = [e for e in rec.snapshot()
              if e["kind"] == "profiler_capture_start"]
    assert len(starts) == 2
    assert all(e["trigger"] == "burn_rate" for e in starts)


def test_profiler_start_failure_degrades_cleanly(tmp_path):
    clock = FakeClock()
    rec = FlightRecorder(registry=MetricsRegistry())
    ctl = _controller(tmp_path, clock, rec=rec,
                      backend=_FakeProfilerBackend(fail_start=True))
    out = ctl.start()
    assert "error" in out
    assert ctl.status()["active"] is False
    assert any(e["kind"] == "profiler_capture_error"
               for e in rec.snapshot())


def test_admin_profile_endpoint(tmp_path, monkeypatch):
    from teku_tpu.api import BeaconRestApi
    from teku_tpu.infra.restapi import HttpError

    clock = FakeClock()
    ctl = _controller(tmp_path, clock)
    monkeypatch.setattr(profiling, "CONTROLLER", ctl)
    api = BeaconRestApi(None)

    async def main():
        status = (await api._admin_profile())["data"]
        assert status["active"] is False
        started = (await api._admin_profile(
            query={"start": "1", "duration_s": "2"}))["data"]
        assert started["trigger"] == "manual"
        assert started["stop_after_s"] == 2.0
        with pytest.raises(HttpError):
            await api._admin_profile(query={"start": "1",
                                            "duration_s": "nope"})
        stopped = (await api._admin_profile(query={"stop": "1"}))["data"]
        assert stopped["path"] == started["path"]
        assert (await api._admin_profile())["data"]["active"] is False

    asyncio.run(main())
