"""Test configuration.

Tests run on the XLA CPU backend with 8 virtual devices so multi-chip
sharding paths (jax.sharding.Mesh over ICI in production) are exercised
without TPU hardware, per the project's multi-chip test strategy.

The override must be a hard set, not setdefault: the agent environment
ships JAX_PLATFORMS=axon (a remote single-tenant TPU tunnel), and letting
tests default onto it turns every eager op into a network RPC — and wedges
the tunnel for the real benchmark runs.  jax may already be imported by
the interpreter's sitecustomize, so the config is also forced via
jax.config for the already-imported module.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
