"""Test configuration.

Tests run on the XLA CPU backend with 8 virtual devices so multi-chip
sharding paths (jax.sharding.Mesh over ICI in production) are exercised
without TPU hardware, per the project's multi-chip test strategy.
Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
