"""Test configuration.

Tests run on the XLA CPU backend with 8 virtual devices so multi-chip
sharding paths (jax.sharding.Mesh over ICI in production) are exercised
without TPU hardware, per the project's multi-chip test strategy.

The override must be a hard set, not setdefault: the agent environment
ships JAX_PLATFORMS=axon (a remote single-tenant TPU tunnel), and letting
tests default onto it turns every eager op into a network RPC — and wedges
the tunnel for the real benchmark runs.  jax may already be imported by
the interpreter's sitecustomize, so the config is also forced via
jax.config for the already-imported module.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the heavy pairing-kernel compiles are
# identical across runs, so pay them once per machine, not per pytest
# invocation.  (The cache key includes platform/flags, so the 8-device
# CPU programs never leak into TPU runs.)
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
try:
    jax.config.update("jax_compilation_cache_dir",
                      os.path.abspath(_cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except Exception:  # pragma: no cover - older jax without these knobs
    pass
