"""External signer (Web3Signer-style) + multi-BN failover.

reference: validator/client/.../signer/ExternalSigner.java:68,
validator/remote/.../FailoverValidatorApiHandler.java:69.
"""

import asyncio
import dataclasses
import json
import threading

import pytest

from teku_tpu.crypto import bls
from teku_tpu.node.gossip import InMemoryGossipNetwork
from teku_tpu.node.node import BeaconNode
from teku_tpu.spec import config as C
from teku_tpu.spec import Spec
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.validator import (BeaconNodeValidatorApi, ExternalSigner,
                                FailoverError, FailoverValidatorApi,
                                LocalSigner, SigningError,
                                SlashingProtectedSigner, ValidatorClient)
from teku_tpu.validator.slashing_protection import SlashingProtector


class StubWeb3Signer:
    """A Web3Signer lookalike over plain HTTP (threaded, so the VC's
    blocking urllib calls don't deadlock the test's event loop):
    POST /api/v1/eth2/sign/{pubkey}, GET /upcheck, GET publicKeys."""

    def __init__(self, secret_keys):
        self.by_pubkey = {bls.secret_to_public_key(sk): sk
                          for sk in secret_keys}
        self.requests = []
        self.refuse = False
        self.corrupt = False
        import http.server

        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/upcheck":
                    self._json(200, "OK")
                elif self.path == "/api/v1/eth2/publicKeys":
                    self._json(200, ["0x" + pk.hex()
                                     for pk in stub.by_pubkey])
                else:
                    self._json(404, {})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                pubkey = bytes.fromhex(
                    self.path.rsplit("/0x", 1)[1])
                stub.requests.append((req.get("type"), pubkey))
                if stub.refuse:
                    self._json(412, {"error": "slashing"})
                    return
                sk = stub.by_pubkey.get(pubkey)
                if sk is None:
                    self._json(404, {})
                    return
                root = bytes.fromhex(req["signingRoot"][2:])
                sig = bls.sign(sk, root)
                if stub.corrupt:
                    sig = b"\x0c" + sig[1:]
                self._json(200, {"signature": "0x" + sig.hex()})

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()


CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0)


def test_external_signer_signs_same_roots_as_local():
    spec = Spec(CFG)
    state, sks = interop_genesis(CFG, 16)
    stub = StubWeb3Signer(sks)
    try:
        pubkeys = {i: bls.secret_to_public_key(sk)
                   for i, sk in enumerate(sks)}
        ext = ExternalSigner(f"http://127.0.0.1:{stub.port}", pubkeys)
        local = LocalSigner(dict(enumerate(sks)))
        assert ext.upcheck()
        assert set(ext.public_keys()) == set(pubkeys.values())
        # randao + attestation + selection proof match local exactly
        assert ext.sign_randao_reveal(CFG, state, 0, 3) \
            == local.sign_randao_reveal(CFG, state, 0, 3)
        from teku_tpu.spec.datastructures import (AttestationData,
                                                  Checkpoint)
        data = AttestationData(
            slot=1, index=0, beacon_block_root=b"\x01" * 32,
            source=Checkpoint(epoch=0, root=b"\x02" * 32),
            target=Checkpoint(epoch=0, root=b"\x03" * 32))
        assert ext.sign_attestation_data(CFG, state, data, 5) \
            == local.sign_attestation_data(CFG, state, data, 5)
        assert ext.sign_selection_proof(CFG, state, 7, 2) \
            == local.sign_selection_proof(CFG, state, 7, 2)
        assert ("ATTESTATION", pubkeys[5]) in stub.requests
    finally:
        stub.stop()


def test_external_signer_error_paths():
    spec = Spec(CFG)
    state, sks = interop_genesis(CFG, 4)
    stub = StubWeb3Signer(sks[:2])      # holds only keys 0,1
    try:
        pubkeys = {i: bls.secret_to_public_key(sk)
                   for i, sk in enumerate(sks)}
        ext = ExternalSigner(f"http://127.0.0.1:{stub.port}", pubkeys)
        with pytest.raises(SigningError):     # key not held → 404
            ext.sign_randao_reveal(CFG, state, 0, 3)
        stub.refuse = True                    # 412 slashing refusal
        with pytest.raises(SigningError):
            ext.sign_randao_reveal(CFG, state, 0, 0)
        stub.refuse = False
        stub.corrupt = True                   # bad signature detected
        with pytest.raises(SigningError):
            ext.sign_randao_reveal(CFG, state, 0, 0)
        # unreachable signer
        dead = ExternalSigner("http://127.0.0.1:1", pubkeys,
                              timeout=0.5)
        with pytest.raises(SigningError):
            dead.sign_randao_reveal(CFG, state, 0, 0)
        assert not dead.upcheck()
    finally:
        stub.stop()


class _FlakyChannel:
    """Wraps a real channel; raises on everything while down."""

    def __init__(self, inner):
        self.inner = inner
        self.down = False
        self.calls = 0

    def __getattr__(self, name):
        real = getattr(self.inner, name)
        if not callable(real):
            return real

        if asyncio.iscoroutinefunction(real):
            async def wrapper(*a, **kw):
                self.calls += 1
                if self.down:
                    raise ConnectionError("beacon node down")
                return await real(*a, **kw)
            return wrapper

        def wrapper(*a, **kw):
            self.calls += 1
            if self.down:
                raise ConnectionError("beacon node down")
            return real(*a, **kw)
        return wrapper


@pytest.mark.slow
def test_vc_survives_primary_bn_death_mid_epoch():
    """Two beacon nodes on a devnet; the VC drives duties through a
    failover channel and its external signer.  The primary dies
    mid-epoch; duties continue via the secondary and the chain still
    advances with blocks from the externally-signed VC."""
    spec = Spec(CFG)
    state, sks = interop_genesis(CFG, 8)
    stub = StubWeb3Signer(sks)

    async def run():
        net = InMemoryGossipNetwork()
        node_a = BeaconNode(spec, state, net.endpoint(), name="a")
        node_b = BeaconNode(spec, state, net.endpoint(), name="b")
        await node_a.start()
        await node_b.start()
        primary = _FlakyChannel(BeaconNodeValidatorApi(node_a))
        secondary = BeaconNodeValidatorApi(node_b)
        failover = FailoverValidatorApi([primary, secondary])
        pubkeys = {i: bls.secret_to_public_key(sk)
                   for i, sk in enumerate(sks)}
        # verify=False here: response verification has its own unit
        # test, and the oracle BLS re-check would double this devnet's
        # runtime on one core
        signer = SlashingProtectedSigner(
            ExternalSigner(f"http://127.0.0.1:{stub.port}", pubkeys,
                           verify=False),
            SlashingProtector())
        client = ValidatorClient(spec, failover, signer,
                                 list(range(8)))
        last = CFG.SLOTS_PER_EPOCH
        half = last // 2
        # phases run on THIS loop (the channels are in-process and the
        # stub signer serves from its own thread, so the VC's blocking
        # HTTP never deadlocks the node's services)
        for slot in range(1, last + 1):
            if slot == half:
                primary.down = True      # primary dies mid-epoch
            await node_a.on_slot(slot)
            await node_b.on_slot(slot)
            await client.on_slot_start(slot)
            await client.on_attestation_due(slot)
            await client.on_aggregation_due(slot)
        assert failover.failovers >= 1
        assert client.blocks_proposed >= last - 2
        # the secondary's chain kept growing after the primary died
        assert node_b.chain.head_slot() >= last - 1
        # every signature came from the external signer
        assert len(stub.requests) > last
        await node_a.stop()
        await node_b.stop()
    asyncio.run(run())


def test_failover_exhaustion_raises():
    class _Chan:
        def head_root(self):
            raise ConnectionError("down")
    fo = FailoverValidatorApi([_Chan(), _Chan()])
    with pytest.raises(FailoverError):
        fo.head_root()
