"""Deneb: five-fork ladder, blob commitments + sidecar inclusion
proofs, EIP-7044 pinned exit domains, EIP-7045 extended inclusion."""

import dataclasses

import pytest

from teku_tpu.crypto import bls, kzg
from teku_tpu.spec import config as C
from teku_tpu.spec import helpers as H
from teku_tpu.spec.altair.block import process_attestation
from teku_tpu.spec.builder import (make_local_signer, produce_attestations,
                                   produce_block)
from teku_tpu.spec.deneb import block as DB
from teku_tpu.spec.deneb.datastructures import (
    compute_commitment_inclusion_proof, get_deneb_schemas,
    kzg_commitment_inclusion_proof_depth, make_blob_sidecars,
    payload_to_header_deneb, verify_commitment_inclusion_proof)
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.spec.milestones import build_fork_schedule, SpecMilestone
from teku_tpu.spec.transition import process_slots, state_transition
from teku_tpu.spec.verifiers import SIMPLE

CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=1,
                          BELLATRIX_FORK_EPOCH=2, CAPELLA_FORK_EPOCH=3,
                          DENEB_FORK_EPOCH=4)


def _deneb_state(n=16):
    cfg = dataclasses.replace(CFG, ALTAIR_FORK_EPOCH=0,
                              BELLATRIX_FORK_EPOCH=0, CAPELLA_FORK_EPOCH=0,
                              DENEB_FORK_EPOCH=0)
    state, sks = interop_genesis(cfg, n)
    return cfg, state, sks


def test_milestone_schedule_five_forks():
    sched = build_fork_schedule(CFG)
    assert sched.milestone_at_epoch(3) is SpecMilestone.CAPELLA
    assert sched.milestone_at_epoch(4) is SpecMilestone.DENEB
    assert sched.milestone_at_epoch(10 ** 6) is SpecMilestone.DENEB


@pytest.mark.slow
def test_deneb_ladder_finalizes():
    state, sks = interop_genesis(CFG, 32)
    signer = make_local_signer(dict(enumerate(sks)))
    S = get_deneb_schemas(CFG)
    atts, cur = [], state
    for slot in range(1, 7 * CFG.SLOTS_PER_EPOCH + 1):
        signed, post = produce_block(CFG, cur, slot, signer,
                                     attestations=atts)
        verified = state_transition(CFG, cur, signed,
                                    validate_result=True)
        assert verified.htr() == post.htr(), f"divergence at slot {slot}"
        atts = produce_attestations(CFG, post, slot,
                                    signed.message.htr(), signer)
        cur = post
    assert isinstance(cur, S.BeaconState)
    assert cur.fork.current_version == CFG.DENEB_FORK_VERSION
    assert cur.fork.previous_version == CFG.CAPELLA_FORK_VERSION
    assert cur.finalized_checkpoint.epoch >= 4
    hdr = cur.latest_execution_payload_header
    assert hdr.excess_blob_gas == 0 and hdr.blob_gas_used == 0
    assert hdr.block_number > 0


def test_versioned_hash():
    vh = DB.kzg_commitment_to_versioned_hash(b"\x07" * 48)
    assert len(vh) == 32 and vh[:1] == b"\x01"
    assert vh[1:] == H.hash32(b"\x07" * 48)[1:]


def test_eip7045_extended_attestation_inclusion():
    """An attestation older than one epoch (but with a previous-epoch
    target) is valid deneb-style and invalid capella-style."""
    cfg, state, sks = _deneb_state(n=16)
    signer = make_local_signer(dict(enumerate(sks)))
    atts, cur = [], state
    att_slot = cfg.SLOTS_PER_EPOCH  # first slot of epoch 1
    for slot in range(1, att_slot + 1):
        signed, cur = produce_block(cfg, cur, slot, signer,
                                    attestations=atts)
        atts = []
    old_atts = produce_attestations(cfg, cur, att_slot,
                                    cur.latest_block_header.copy_with(
                                        state_root=cur.htr()).htr(),
                                    signer)
    # advance deep into epoch 2: > att_slot + SLOTS_PER_EPOCH
    target_slot = 2 * cfg.SLOTS_PER_EPOCH + 6
    adv = process_slots(cfg, cur, target_slot)
    assert target_slot > att_slot + cfg.SLOTS_PER_EPOCH
    att = old_atts[0]
    post = process_attestation(cfg, adv, att, SIMPLE,
                               enforce_upper_window=False)
    assert post is not adv  # accepted, participation applied
    with pytest.raises(Exception):
        process_attestation(cfg, adv, att, SIMPLE,
                            enforce_upper_window=True)


def test_eip7044_exit_domain_pinned_to_capella():
    cfg, state, sks = _deneb_state(n=16)
    # age the validators enough to exit
    state = state.copy_with(slot=(cfg.SHARD_COMMITTEE_PERIOD + 1)
                            * cfg.SLOTS_PER_EPOCH)
    S = get_deneb_schemas(cfg)
    idx = 2
    exit_msg = S.VoluntaryExit(epoch=0, validator_index=idx)
    capella_domain = H.compute_domain(C.DOMAIN_VOLUNTARY_EXIT,
                                      cfg.CAPELLA_FORK_VERSION,
                                      state.genesis_validators_root)
    good = S.SignedVoluntaryExit(
        message=exit_msg,
        signature=bls.sign(sks[idx], H.compute_signing_root(
            exit_msg, capella_domain)))
    from teku_tpu.spec.block import process_voluntary_exit
    post = process_voluntary_exit(cfg, state, good, SIMPLE,
                                  exit_fork_version=cfg.CAPELLA_FORK_VERSION)
    assert post.validators[idx].exit_epoch != C.FAR_FUTURE_EPOCH
    # signed over the CURRENT (deneb) fork domain -> rejected under the pin
    deneb_domain = H.get_domain(cfg, state, C.DOMAIN_VOLUNTARY_EXIT, 0)
    assert deneb_domain != capella_domain
    bad = S.SignedVoluntaryExit(
        message=exit_msg,
        signature=bls.sign(sks[idx], H.compute_signing_root(
            exit_msg, deneb_domain)))
    with pytest.raises(Exception):
        process_voluntary_exit(cfg, state, bad, SIMPLE,
                               exit_fork_version=cfg.CAPELLA_FORK_VERSION)


def test_commitment_inclusion_proof_roundtrip():
    cfg, state, sks = _deneb_state()
    S = get_deneb_schemas(cfg)
    depth = kzg_commitment_inclusion_proof_depth(cfg)
    assert depth == 5 + 1 + 4  # minimal: 32-limit subtree + mix + body
    commitments = tuple(bytes([i]) * 48 for i in range(3))
    body = S.BeaconBlockBody(blob_kzg_commitments=commitments)
    block = S.BeaconBlock(slot=5, proposer_index=1,
                          parent_root=b"\x01" * 32,
                          state_root=b"\x02" * 32, body=body)
    signed = S.SignedBeaconBlock(message=block, signature=b"\x03" * 96)
    blobs = [bytes(32 * cfg.FIELD_ELEMENTS_PER_BLOB)] * 3
    proofs = [bytes(48)] * 3
    sidecars = make_blob_sidecars(cfg, signed, blobs, proofs)
    assert len(sidecars) == 3
    for sc in sidecars:
        assert verify_commitment_inclusion_proof(cfg, sc)
    # tampering with the commitment, index, or proof breaks it
    sc = sidecars[1]
    assert not verify_commitment_inclusion_proof(
        cfg, sc.copy_with(kzg_commitment=b"\xff" * 48))
    assert not verify_commitment_inclusion_proof(
        cfg, sc.copy_with(index=2))
    branch = list(sc.kzg_commitment_inclusion_proof)
    branch[0] = b"\x00" * 32
    assert not verify_commitment_inclusion_proof(
        cfg, sc.copy_with(kzg_commitment_inclusion_proof=tuple(branch)))


def test_mainnet_inclusion_proof_depth_is_17():
    assert kzg_commitment_inclusion_proof_depth(C.MAINNET) == 17


def test_blob_commitment_cap_enforced():
    cfg, state, sks = _deneb_state()
    S = get_deneb_schemas(cfg)
    pre = process_slots(cfg, state, 1)
    too_many = tuple(bytes([i]) * 48
                     for i in range(cfg.MAX_BLOBS_PER_BLOCK + 1))
    body = S.BeaconBlockBody(blob_kzg_commitments=too_many)
    with pytest.raises(Exception):
        DB.process_execution_payload(cfg, pre, body)


def test_deneb_payload_header_carries_blob_gas():
    S = get_deneb_schemas(CFG)
    p = S.ExecutionPayload(blob_gas_used=7, excess_blob_gas=9,
                           block_hash=b"\x0a" * 32)
    h = payload_to_header_deneb(p)
    assert h.blob_gas_used == 7 and h.excess_blob_gas == 9
    assert h.block_hash == p.block_hash


def test_fork_at_genesis_has_equal_versions():
    """Spec: genesis states of later-fork configs set previous ==
    current (no prior fork existed on chain)."""
    cfg, state, _ = _deneb_state()
    assert state.fork.current_version == cfg.DENEB_FORK_VERSION
    assert state.fork.previous_version == cfg.DENEB_FORK_VERSION


def test_sidecar_gossip_rejects_wrong_proposer():
    from teku_tpu.crypto import kzg
    from teku_tpu.node.blobs import validate_spec_sidecar
    from teku_tpu.spec.deneb.datastructures import make_blob_sidecars
    cfg, state, sks = _deneb_state()
    S = get_deneb_schemas(cfg)
    setup = kzg.insecure_setup()
    blob = b"\x00" * (32 * cfg.FIELD_ELEMENTS_PER_BLOB)
    commitment = kzg.blob_to_kzg_commitment(blob, setup)
    proof = kzg.compute_blob_kzg_proof(blob, commitment, setup)
    slot = 1
    expected = H.get_beacon_proposer_index(cfg, state, slot=slot)
    body = S.BeaconBlockBody(blob_kzg_commitments=(commitment,))

    def signed_block_by(index):
        block = S.BeaconBlock(slot=slot, proposer_index=index,
                              parent_root=b"\x01" * 32,
                              state_root=b"\x02" * 32, body=body)
        header = type(state.latest_block_header)(
            slot=slot, proposer_index=index,
            parent_root=block.parent_root, state_root=block.state_root,
            body_root=body.htr())
        domain = H.get_domain(cfg, state, C.DOMAIN_BEACON_PROPOSER, 0)
        sig = bls.sign(sks[index], H.compute_signing_root(header, domain))
        return S.SignedBeaconBlock(message=block, signature=sig)

    good = make_blob_sidecars(cfg, signed_block_by(expected),
                              [blob], [proof])[0]
    assert validate_spec_sidecar(cfg, good, state=state,
                                 setup=setup) == "accept"
    wrong = (expected + 1) % len(state.validators)
    forged = make_blob_sidecars(cfg, signed_block_by(wrong),
                                [blob], [proof])[0]
    assert validate_spec_sidecar(cfg, forged, state=state,
                                 setup=setup) == "reject"
