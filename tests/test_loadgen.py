"""Mainnet-shape load generator: seeded determinism, spec-derived
distribution sanity, and the adversarial scenarios driving the REAL
service's bisect/coalescing/brownout machinery under a virtual clock."""

import functools

import pytest

from teku_tpu.loadgen import driver, model, scenarios
from teku_tpu.loadgen.model import (EVENT_KINDS, INVALID_SIG_PREFIX,
                                    TrafficModel, committee_size,
                                    committees_per_slot, generate_events,
                                    stream_stats, subnet_for)
from teku_tpu.services.admission import CLASS_LABELS, VerifyClass


@functools.lru_cache(maxsize=None)
def run(name, slots=1, seed=3):
    """One cached driver run per (scenario, slots): several tests read
    different properties of the same replay."""
    return driver.run_scenario(name, seed=seed, slots=slots)


# --------------------------------------------------------------------------
# Traffic model: determinism + spec-derived shape
# --------------------------------------------------------------------------

def _fingerprint(events):
    return [(round(e.t, 6), e.kind, e.cls, e.triples, e.blobs)
            for e in events]


def test_seeded_determinism():
    """Same (model, seed, slots) -> bit-identical event stream; a
    different seed genuinely reshuffles."""
    m = TrafficModel()
    a = generate_events(m, seed=7, slots=1)
    b = generate_events(m, seed=7, slots=1)
    assert _fingerprint(a) == _fingerprint(b)
    c = generate_events(m, seed=8, slots=1)
    assert _fingerprint(a) != _fingerprint(c)
    # determinism survives stats aggregation too (no dict-order leaks)
    assert stream_stats(a) == stream_stats(b)


def test_spec_derived_committee_structure():
    """Committee count/size and the subnet mapping follow the spec
    derivations for a 1M-validator network: 64 committees per slot on
    64 subnets, ~490-member committees."""
    v = 1_000_000
    assert committees_per_slot(v) == 64
    assert committee_size(v) == v // 32 // 64 == 488
    # the subnet map covers all 64 subnets across one slot's committees
    assert {subnet_for(v, 1000, c) for c in range(64)} \
        == set(range(64))
    # smaller networks derive smaller structures (devnet scale)
    assert committees_per_slot(8192) == 2
    assert committee_size(8192) == 128


def test_duplication_curve_matches_validator_count():
    """The attestation duplication curve IS the committee size: every
    participating member of a committee signs the same AttestationData,
    so mean lanes-per-unique-message tracks committee_size *
    participation (* redelivery)."""
    m = TrafficModel()
    stats = stream_stats(generate_events(m, seed=5, slots=1))
    expected = committee_size(m.validators) * m.participation \
        * (1 + m.redelivery)
    assert stats["attestation_dup_mean"] == pytest.approx(expected,
                                                          rel=0.15)
    assert stats["attestation_dup_max"] <= committee_size(m.validators) \
        * (1 + 6 * m.redelivery)
    # the whole-stream dedup ratio is committee-shaped (well over half
    # the lanes are duplicates of an already-seen message)
    assert stats["dedup_ratio"] > 0.5
    # event kinds stay inside the closed vocabulary
    assert set(stats["by_kind"]) == set(EVENT_KINDS)
    assert all(k in EVENT_KINDS for k in stats["by_kind"])


def test_dup_collapse_kills_the_curve():
    m = TrafficModel(dup_collapse=True)
    stats = stream_stats(generate_events(m, seed=5, slots=1))
    assert stats["attestation_dup_mean"] <= 1.5   # redelivery only
    assert stats["dedup_ratio"] < 0.2


def test_invalid_rate_marks_signatures():
    m = TrafficModel(invalid_rate=0.5)
    events = generate_events(m, seed=5, slots=1)
    bad = sum(1 for e in events for _pks, _m, sig in e.triples
              if sig.startswith(INVALID_SIG_PREFIX))
    total = sum(len(e.triples) for e in events)
    assert 0.2 < bad / total < 0.7
    # the model never forges the protected classes' signatures
    assert all(e.valid for e in events if e.cls is VerifyClass.VIP)


# --------------------------------------------------------------------------
# Scenario registry: the closed vocabulary
# --------------------------------------------------------------------------

def test_scenario_registry_closed_and_complete():
    assert set(scenarios.DEFAULT_SWEEP) == set(scenarios.SCENARIOS)
    assert len(scenarios.SCENARIOS) >= 4
    names = set(scenarios.SCENARIOS)
    assert "invalid_sig_flood" in names        # adversarial (bisect)
    assert "epoch_boundary_storm" in names     # the storm shape
    adversarial = {n for n, s in scenarios.SCENARIOS.items()
                   if s.adversarial}
    assert adversarial >= {"invalid_sig_flood", "equivocation_replay",
                           "dup_collapse"}
    for name, sc in scenarios.SCENARIOS.items():
        assert sc.name == name
        # declared class mixes come from the closed enum vocabulary
        assert set(sc.classes) <= set(CLASS_LABELS)
        assert sc.description
    with pytest.raises(KeyError):
        scenarios.get("no_such_scenario")


# --------------------------------------------------------------------------
# Driver: the real service under each scenario
# --------------------------------------------------------------------------

def test_steady_state_report_shape_and_protected_classes():
    rep = run("steady_state")
    assert rep["completed_triples"] > 1000
    assert rep["sigs_per_sec"] > 0
    assert set(rep["by_class"]) == set(CLASS_LABELS)
    # the declared class mix was actually submitted
    for cls in scenarios.get("steady_state").classes:
        assert rep["by_class"][cls]["submitted"] > 0
    # protected classes are never shed, on any scenario — pinned here
    # for steady state, in the bench gate for all
    assert rep["sheds"]["block_import"] == 0
    assert rep["sheds"]["vip"] == 0
    # committee shape survives to the device: dedup ratio well over
    # the bench gate's floor
    assert rep["dedup_ratio"] >= 0.25
    # sync-committee demand is attributed to its own arrival source
    assert "sync_committee" in rep["arrival_sources"]


def test_invalid_sig_flood_drives_bisect():
    """The adversarial acceptance pin: a forged-signature flood must
    produce failed batches that the service isolates via its bisect
    recursion — *_dispatch_total{kind=bisect} > 0 — while the
    protected classes stay unshed."""
    rep = run("invalid_sig_flood")
    assert rep["bisect_dispatches"] > 0
    assert rep["dispatches"].get("first_try", 0) > 0
    assert rep["failed_verdicts"] > 0
    assert rep["sheds"]["block_import"] == 0
    assert rep["sheds"]["vip"] == 0


def test_equivocation_replay_exercises_coalescing():
    rep = run("equivocation_replay")
    # identical in-flight triples coalesced onto shared lanes (some
    # replicas claim a higher class, exercising promotion)
    assert rep["coalesced"] > 50
    assert rep["failed_verdicts"] == 0


def test_dup_collapse_starves_dedup():
    rep = run("dup_collapse")
    assert rep["dedup_ratio"] < 0.1
    assert rep["completed_triples"] > 500


def test_epoch_boundary_storm_brownout_and_shed_by_class():
    """The storm overloads the modeled device: brownout must ENTER,
    shed only the sheddable classes, and exit after the storm."""
    rep = run("epoch_boundary_storm", slots=2)
    assert rep["brownout"]["enters"] >= 1
    assert rep["brownout"]["final_level"] == 0      # exited after
    assert rep["sheds"]["optimistic"] + rep["sheds"]["gossip"] > 0
    assert rep["sheds"]["block_import"] == 0
    assert rep["sheds"]["vip"] == 0
    assert rep["sheds"]["sync_critical"] == 0
    # the OPTIMISTIC deferred-revalidation burst was part of the mix
    assert rep["by_class"]["optimistic"]["submitted"] > 0


def test_blob_storm_accounts_kzg_demand():
    """Blob batches dispatch through the REAL crypto/kzg facade: the
    model backend serves them and the demand lands in the capacity
    model under source="kzg"."""
    rep = run("blob_storm")
    assert rep["kzg"]["batches"] > 0
    assert rep["kzg"]["blobs"] >= rep["kzg"]["batches"]
    assert rep["kzg"]["source_accounted"]
    assert "kzg" in rep["arrival_sources"]


def test_run_scenarios_summary_and_metrics():
    """The sweep summary the bench gate reads, plus the loadgen_*
    metric families (closed scenario/kind/class label vocabularies)."""
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY
    out = driver.run_scenarios(["steady_state", "dup_collapse"],
                               seed=3, slots=1)
    assert set(out["scenarios"]) == {"steady_state", "dup_collapse"}
    summary = out["summary"]
    assert summary["scenarios_run"] == 2
    assert summary["block_import_sheds_worst"] == 0
    assert summary["critical_p50_ms_worst"] >= 0
    # dedup floor ignores the non-committee-shaped dup_collapse
    assert summary["committee_dedup_ratio_min"] >= 0.25
    metrics = GLOBAL_REGISTRY.metrics()
    events = metrics["loadgen_events_total"]
    for (scenario, kind), child in events._items():
        assert scenario in scenarios.SCENARIOS
        assert kind in EVENT_KINDS
        assert child.value > 0
    sheds = metrics["loadgen_sheds_total"]
    for (scenario, cls), _child in sheds._items():
        assert scenario in scenarios.SCENARIOS
        assert cls in CLASS_LABELS


def test_block_import_p50_not_inflated_by_wall_clock():
    """Regression pin for the r10/r11 block-import p50 inflation
    (~3.6 s vs 50 ms): the driver used to advance the VIRTUAL clock
    while a dispatch crossed the asyncio.to_thread boundary, so on a
    1-core box every GIL switch interval (~5 ms) of wall scheduling
    turned into seconds of virtual latency charged to whatever was in
    flight.  The clock now holds while ``svc.inflight_dispatches``
    is nonzero (same gate in services/overload_sim.py), making
    virtual latency what the model says it is — queue wait + modeled
    device time — on any core count.  The bench gate's production
    bound is 300 ms; steady-state block import models out well under
    100 ms."""
    rep = run("steady_state")
    assert rep["by_class"]["block_import"]["p50_ms"] <= 100.0
    assert rep["by_class"]["vip"]["p50_ms"] <= 100.0
    # and the overall p50 is model-scale, not scheduler-scale
    assert rep["p50_ms"] <= 500.0


def test_chaos_device_loss_heals_and_protects():
    """The loadgen chaos schedule drives the REAL supervisor
    machinery: a timed bls.mesh_shard wedge on the 8-device model
    mesh must eject exactly the sick device, reshape to 4, keep
    serving (protected classes never shed, zero wrong verdicts), and
    grow back to 8 once the schedule clears the fault."""
    rep = run("chaos_device_loss", slots=2)
    ch = rep["chaos"]
    # the schedule fired both actions
    assert [c["action"] for c in ch["schedule"]] == ["wedge", "clear"]
    assert ch["ejects"] >= 1
    assert ch["readmits"] >= 1
    assert ch["reshapes"]["shrink"] >= 1
    assert ch["reshapes"]["grow"] >= 1
    assert ch["recovery_s"] is not None
    assert ch["recovered"] is True
    assert ch["mesh"]["live"] == 8
    assert ch["mesh"]["configured"] == 8
    # zero wrong verdicts through the whole cycle (no invalid sigs in
    # this mix: any failed verdict would have been wrong)
    assert ch["wrong_verdicts"] == 0
    assert rep["failed_verdicts"] == 0
    # protected classes never shed during device loss
    assert rep["sheds"]["block_import"] == 0
    assert rep["sheds"]["vip"] == 0
    # the mesh (not the oracle cliff) served the overwhelming share
    served = ch["served"]
    assert served.get("device:ok", 0) > 10 * (
        served.get("oracle:fallback", 0)
        + served.get("oracle:breaker_open", 0))
    # eject/reshape/readmit are all visible in the event timeline
    kinds = [e["kind"] for e in ch["events"]]
    assert "mesh_eject" in kinds
    assert "mesh_reshape" in kinds
    assert "mesh_readmit" in kinds
    eject = next(e for e in ch["events"] if e["kind"] == "mesh_eject")
    assert eject["device"] == "vdev3"
    # committee shape survives device loss (the bench dedup gate)
    assert rep["dedup_ratio"] >= 0.2


def test_driver_verdicts_deterministic():
    """Same scenario/seed/slots -> the same verdict-level evidence.
    (Batch boundaries can shift marginally via the flush-hold's
    real-time failsafe, so latency percentiles are not pinned —
    verdicts, shed counts and the stream itself are.)"""
    a = driver.run_scenario("steady_state", seed=11, slots=1)
    b = driver.run_scenario("steady_state", seed=11, slots=1)
    for key in ("completed_triples", "failed_verdicts", "sheds",
                "stream"):
        assert a[key] == b[key], key
