"""BLS facade tests mirroring the reference's BLS vector suites.

Covers the 10 eth2 BLS reference-test categories (sign, verify, aggregate,
aggregate_verify, fast_aggregate_verify, batch_verify, eth_aggregate_pubkeys,
eth_fast_aggregate_verify, deserialization_G1, deserialization_G2 — see
reference eth-reference-tests/.../BlsTests.java:23-36) using self-generated
vectors validated by the pure oracle's property tests, since the official
vector tarballs are not available offline.
"""

import pytest

from teku_tpu.crypto import bls as BLS
from teku_tpu.crypto.bls.pure_impl import G1_INFINITY, G2_INFINITY


@pytest.fixture(scope="module")
def keys():
    sks = [BLS.keygen(bytes([i]) * 32) for i in range(1, 6)]
    pks = [BLS.secret_to_public_key(sk) for sk in sks]
    return sks, pks


MSG = b"\x12" * 32


class TestSignVerify:
    def test_roundtrip(self, keys):
        sks, pks = keys
        sig = BLS.sign(sks[0], MSG)
        assert len(sig) == 96
        assert BLS.verify(pks[0], MSG, sig)

    def test_wrong_message_fails(self, keys):
        sks, pks = keys
        sig = BLS.sign(sks[0], MSG)
        assert not BLS.verify(pks[0], b"\x13" * 32, sig)

    def test_wrong_key_fails(self, keys):
        sks, pks = keys
        sig = BLS.sign(sks[0], MSG)
        assert not BLS.verify(pks[1], MSG, sig)

    def test_sign_deterministic(self, keys):
        sks, _ = keys
        assert BLS.sign(sks[0], MSG) == BLS.sign(sks[0], MSG)

    def test_zero_key_sign_prohibited(self):
        with pytest.raises(ValueError):
            BLS.sign(0, MSG)

    def test_infinity_pubkey_rejected(self, keys):
        sks, _ = keys
        sig = BLS.sign(sks[0], MSG)
        assert not BLS.verify(G1_INFINITY, MSG, sig)
        assert not BLS.verify(G1_INFINITY, MSG, G2_INFINITY)

    def test_garbage_inputs_fail(self, keys):
        _, pks = keys
        assert not BLS.verify(pks[0], MSG, b"\x01" * 96)
        assert not BLS.verify(b"\x01" * 48, MSG, BLS.sign(1, MSG))


class TestAggregate:
    def test_aggregate_same_message(self, keys):
        sks, pks = keys
        sigs = [BLS.sign(sk, MSG) for sk in sks]
        agg = BLS.aggregate_signatures(sigs)
        assert BLS.fast_aggregate_verify(pks, MSG, agg)

    def test_subset_fails(self, keys):
        sks, pks = keys
        sigs = [BLS.sign(sk, MSG) for sk in sks[:3]]
        agg = BLS.aggregate_signatures(sigs)
        assert not BLS.fast_aggregate_verify(pks, MSG, agg)

    def test_aggregate_empty_raises(self):
        with pytest.raises(ValueError):
            BLS.aggregate_signatures([])

    def test_aggregate_verify_distinct_messages(self, keys):
        sks, pks = keys
        msgs = [bytes([i]) * 32 for i in range(len(sks))]
        agg = BLS.aggregate_signatures(
            [BLS.sign(sk, m) for sk, m in zip(sks, msgs)])
        assert BLS.aggregate_verify(pks, msgs, agg)
        assert not BLS.aggregate_verify(pks, list(reversed(msgs)), agg)

    def test_aggregate_verify_empty_fails(self):
        assert not BLS.aggregate_verify([], [], G2_INFINITY)

    def test_eth_aggregate_pubkeys(self, keys):
        _, pks = keys
        agg = BLS.eth_aggregate_pubkeys(pks)
        assert len(agg) == 48
        with pytest.raises(ValueError):
            BLS.eth_aggregate_pubkeys([])
        with pytest.raises(ValueError):
            BLS.eth_aggregate_pubkeys([G1_INFINITY])

    def test_eth_fast_aggregate_verify_empty_infinity(self):
        # deneb rule: no participants + infinity signature is valid
        assert BLS.eth_fast_aggregate_verify([], MSG, G2_INFINITY)
        assert not BLS.eth_fast_aggregate_verify([], MSG, b"\x01" * 96)

    def test_fast_aggregate_verify_empty_fails(self):
        assert not BLS.fast_aggregate_verify([], MSG, G2_INFINITY)


class TestBatchVerify:
    def test_batch_of_valid(self, keys):
        sks, pks = keys
        msgs = [bytes([40 + i]) * 32 for i in range(len(sks))]
        triples = [([pk], m, BLS.sign(sk, m))
                   for sk, pk, m in zip(sks, pks, msgs)]
        # plus one aggregate triple
        agg_sig = BLS.aggregate_signatures([BLS.sign(sk, MSG) for sk in sks])
        triples.append((pks, MSG, agg_sig))
        assert BLS.batch_verify(triples)

    def test_batch_detects_single_bad(self, keys):
        sks, pks = keys
        msgs = [bytes([50 + i]) * 32 for i in range(len(sks))]
        triples = [([pk], m, BLS.sign(sk, m))
                   for sk, pk, m in zip(sks, pks, msgs)]
        triples[2] = (triples[2][0], b"\x66" * 32, triples[2][2])
        assert not BLS.batch_verify(triples)

    def test_empty_batch_is_true(self):
        assert BLS.batch_verify([])

    def test_single_triple_uses_direct_path(self, keys):
        sks, pks = keys
        sig = BLS.sign(sks[0], MSG)
        assert BLS.batch_verify([([pks[0]], MSG, sig)])

    def test_prepare_complete_split(self, keys):
        sks, pks = keys
        msgs = [bytes([60 + i]) * 32 for i in range(3)]
        semis = [BLS.prepare_batch_verify(([pks[i]], msgs[i], BLS.sign(sks[i], msgs[i])))
                 for i in range(3)]
        assert all(s is not None for s in semis)
        assert BLS.complete_batch_verify(semis)
        # invalid triple -> None -> batch fails
        bad = BLS.prepare_batch_verify(([b"\x01" * 48], MSG, b"\x02" * 96))
        assert bad is None
        assert not BLS.complete_batch_verify(semis + [bad])


class TestKillSwitch:
    def test_verification_disabled(self, keys):
        _, pks = keys
        BLS.verification_disabled = True
        try:
            assert BLS.verify(pks[0], MSG, b"\x01" * 96)
        finally:
            BLS.verification_disabled = False


class TestDeserialization:
    """deserialization_G1 / deserialization_G2 vector categories."""

    def test_valid_pubkey(self, keys):
        _, pks = keys
        assert BLS.public_key_is_valid(pks[0])

    def test_infinity_pubkey_invalid(self):
        assert not BLS.public_key_is_valid(G1_INFINITY)

    def test_infinity_signature_valid_point(self):
        assert BLS.signature_is_valid(G2_INFINITY)

    def test_bad_encodings(self):
        assert not BLS.public_key_is_valid(b"\x00" * 48)
        assert not BLS.public_key_is_valid(b"\xff" * 48)
        assert not BLS.signature_is_valid(b"\x00" * 96)
        assert not BLS.signature_is_valid(b"\xff" * 96)
