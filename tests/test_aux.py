"""Aux subsystems: operation pools, weak subjectivity, step timers."""

import dataclasses
import logging

import pytest

from teku_tpu.infra.perf import StepTimer
from teku_tpu.node.oppool import make_operation_pools
from teku_tpu.spec import config as C
from teku_tpu.spec import helpers as H
from teku_tpu.spec.config import DOMAIN_VOLUNTARY_EXIT
from teku_tpu.spec.datastructures import (SignedVoluntaryExit,
                                          VoluntaryExit)
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.spec.transition import process_slots
from teku_tpu.spec.weak_subjectivity import (
    compute_weak_subjectivity_period, WeakSubjectivityValidator)
from teku_tpu.crypto import bls

# exits allowed immediately for pool tests
CFG = dataclasses.replace(C.MINIMAL, SHARD_COMMITTEE_PERIOD=0)


def _signed_exit(state, sks, index, epoch=0):
    msg = VoluntaryExit(epoch=epoch, validator_index=index)
    domain = H.get_domain(CFG, state, DOMAIN_VOLUNTARY_EXIT, epoch)
    root = H.compute_signing_root(msg, domain)
    return SignedVoluntaryExit(message=msg,
                               signature=bls.sign(sks[index], root))


def test_voluntary_exit_pool_validates_dedupes_and_includes():
    state, sks = interop_genesis(CFG, 16)
    state = process_slots(CFG, state, 1)
    pools = make_operation_pools(CFG)
    pool = pools["voluntary_exits"]

    good = _signed_exit(state, sks, 3)
    assert pool.add(state, good)
    assert not pool.add(state, good)                 # dedupe
    # bad signature rejected on entry
    bad = _signed_exit(state, sks, 4).copy_with(signature=b"\x09" * 96)
    assert not pool.add(state, bad)
    # unknown validator rejected
    assert not pool.add(state, _signed_exit(
        state, dict(enumerate(sks)) | {99: sks[0]}, 99))
    assert len(pool) == 1
    assert pool.get_for_block(16, state) == [good]
    # once included, pruned
    pool.on_included([good])
    assert len(pool) == 0


def test_exit_flows_into_produced_block():
    """Pool → block production → state transition end to end."""
    from teku_tpu.spec.builder import make_local_signer, produce_block
    from teku_tpu.spec.transition import state_transition
    state, sks = interop_genesis(CFG, 16)
    signer = make_local_signer(dict(enumerate(sks)))
    pools = make_operation_pools(CFG)
    pre = process_slots(CFG, state, 1)
    exit_op = _signed_exit(pre, sks, 5)
    assert pools["voluntary_exits"].add(pre, exit_op)
    signed, post = produce_block(
        CFG, state, 1, signer,
        voluntary_exits=pools["voluntary_exits"].get_for_block(16, pre))
    verified = state_transition(CFG, state, signed)
    assert verified.validators[5].exit_epoch != C.FAR_FUTURE_EPOCH


@pytest.mark.slow
def test_exit_gossips_between_nodes_and_lands_in_block():
    """Exit enters node A via the pool API, gossips to node B, and is
    included by whichever proposer builds next."""
    import asyncio
    from teku_tpu.node import Devnet
    from teku_tpu.node.gossip import VOLUNTARY_EXIT_TOPIC
    from teku_tpu.spec import Spec

    async def run():
        net = Devnet(n_nodes=2, n_validators=16, spec=Spec(CFG))
        await net.start()
        try:
            await net.run_until_slot(2)
            a, b = net.nodes
            state = a.chain.head_state()
            sks = [s for s in
                   __import__("teku_tpu.spec.genesis",
                              fromlist=["interop_secret_keys"]
                              ).interop_secret_keys(16)]
            exit_op = _signed_exit(state, sks, 7)
            assert a.operation_pools["voluntary_exits"].add(state, exit_op)
            await a.gossip.publish(
                VOLUNTARY_EXIT_TOPIC,
                type(exit_op).serialize(exit_op))
            assert len(b.operation_pools["voluntary_exits"]) == 1
            await net.run_until_slot(4, first_slot=3)
            head_state = a.chain.head_state()
            assert head_state.validators[7].exit_epoch != C.FAR_FUTURE_EPOCH
        finally:
            await net.stop()
    asyncio.run(run())


def test_weak_subjectivity_period_and_validator():
    state, _ = interop_genesis(C.MINIMAL, 64)
    period = compute_weak_subjectivity_period(C.MINIMAL, state)
    assert period >= C.MINIMAL.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    v = WeakSubjectivityValidator(C.MINIMAL)
    assert v.is_within_period(state, period // 2)
    assert not v.is_within_period(state, period + 1)
    with pytest.raises(ValueError):
        v.validate_anchor(state, period + 100)
    v.validate_anchor(state, 1)          # fresh anchor passes


def test_step_timer_logs_only_over_threshold(caplog):
    t = StepTimer("fast op", threshold_ms=10_000)
    t.mark("a")
    assert t.complete() is not None
    with caplog.at_level(logging.WARNING, logger="teku_tpu.perf"):
        slow = StepTimer("slow op", threshold_ms=0.0)
        slow.mark("stage1")
        total = slow.complete()
        assert total is not None
    assert any("slow op" in r.message for r in caplog.records)
    assert StepTimer("off", enabled=False).complete() is None
