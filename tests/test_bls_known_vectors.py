"""Known-answer tests pinned to externally published RFC 9380 vectors.

These constants are the published IETF RFC 9380 test vectors (Appendix K.1
expand_message_xmd SHA-256, Appendix J.10.1 BLS12381G2_XMD:SHA-256_SSWU_RO_),
cross-checked against the RFC at the time this file was created.  They pin
the wire-format conventions (sgn0, c1-first ordering, isogeny constants,
DST handling) so a consistent internal flip that survives the round-trip
property tests still fails here — guarding interop with blst-based clients.
"""

from teku_tpu.crypto.bls import curve as C, hash_to_curve as H

EXPANDER_DST = b"QUUX-V01-CS02-with-expander-SHA256-128"

# RFC 9380 K.1 (SHA-256, len_in_bytes = 0x20)
K1_VECTORS = {
    b"": "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235",
    b"abc": "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615",
}

H2C_DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"

# RFC 9380 J.10.1: affine output point (x = x0 + x1*u, y = y0 + y1*u)
J101_VECTORS = {
    b"": (
        0x0141EBFBDCA40EB85B87142E130AB689C673CF60F1A3E98D69335266F30D9B8D4AC44C1038E9DCDD5393FAF5C41FB78A,
        0x05CB8437535E20ECFFAEF7752BADDF98034139C38452458BAEEFAB379BA13DFF5BF5DD71B72418717047F5B0F37DA03D,
        0x0503921D7F6A12805E72940B963C0CF3471C7B2A524950CA195D11062EE75EC076DAF2D4BC358C4B190C0C98064FDD92,
        0x12424AC32561493F3FE3C260708A12B7C620E7BE00099A974E259DDC7D1F6395C3C811CDD19F1E8DBF3E9ECFDCBAB8D6,
    ),
    b"abc": (
        0x02C2D18E033B960562AAE3CAB37A27CE00D80CCD5BA4B7FE0E7A210245129DBEC7780CCC7954725F4168AFF2787776E6,
        0x139CDDBCCDC5E91B9623EFD38C49F81A6F83F175E80B06FC374DE9EB4B41DFE4CA3A230ED250FBE3A2ACF73A41177FD8,
        0x1787327B68159716A37440985269CF584BCB1E621D3A7202BE6EA05C4CFE244AEB197642555A0645FB87BF7466B2BA48,
        0x00AA65DAE3C8D732D10ECD2C50F8A1BAF3001578F71C694E03866E9F3D49AC1E1CE70DD94A733534F106D4CEC0EDDD16,
    ),
}


def test_expand_message_xmd_rfc_k1():
    for msg, expected in K1_VECTORS.items():
        assert H.expand_message_xmd(msg, EXPANDER_DST, 0x20).hex() == expected


def test_hash_to_curve_g2_rfc_j101():
    for msg, (x0, x1, y0, y1) in J101_VECTORS.items():
        p = C.to_affine(C.FQ2_OPS, H.hash_to_g2(msg, H2C_DST))
        assert p[0] == (x0, x1), f"x mismatch for {msg!r}"
        assert p[1] == (y0, y1), f"y mismatch for {msg!r}"
