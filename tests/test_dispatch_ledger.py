"""Dispatch decision ledger: per-dispatch cost attribution (PR 13).

The acceptance surface: real mixed dup/tamper/mesh batches through the
REAL provider must land structured records whose waste/dedup/
imbalance/compile fields are pinned against the provider's own
counters; ``?trace_id=`` lookup from a slow-trace ring entry must
return the matching record; the admission annotations must survive
``asyncio.to_thread`` into the worker-thread dispatch; and the doctor
engine must rank findings that cite ledger records by trace id.

Compile budget: the device tests reuse EXACTLY the kernel shapes
tests/test_mesh_grouped.py uses (16-lane kmax-1 grid, min_bucket 8;
the 8-shard mesh layout) so the staged programs compile once per
process and load from the persistent cache across runs.
"""

import asyncio
import threading

import pytest

import jax

from teku_tpu import parallel
from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.crypto.bls.pure_impl import PureBls12381
from teku_tpu.infra import dispatchledger, doctor, tracing
from teku_tpu.infra.flightrecorder import FlightRecorder
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.ops import provider as PV
from teku_tpu.ops.provider import JaxBls12381
from teku_tpu.services.admission import BatchPlan, VerifyClass
from teku_tpu.services.signatures import (
    AggregatingSignatureVerificationService)

pytest_plugins: list = []


# --------------------------------------------------------------------------
# host-only: ring, annotations, summarize, doctor engine
# --------------------------------------------------------------------------

def test_ring_is_bounded_and_counts_all_records():
    led = dispatchledger.DispatchLedger(capacity=4,
                                        registry=MetricsRegistry())
    for i in range(11):
        led.record({"lanes": 1,
                    "waste": {"lane": {"real": 3, "padded": 4}}})
    assert len(led.snapshot()) == 4
    assert led.recorded_total == 11
    assert led.snapshot()[-1]["seq"] == 11
    # cumulative waste survives ring eviction: 11 * (3 real / 4 padded)
    assert led.snapshot(last=2)[0]["seq"] == 10
    led.clear()
    assert led.snapshot() == []
    assert led.recorded_total == 11     # seq is monotonic, not reset


def test_annotations_propagate_into_worker_threads():
    """The service's plan annotations must reach open_record() even
    when the dispatch runs on a worker thread (asyncio.to_thread
    copies the ContextVar context)."""
    got = {}

    def dispatch_thread():
        got.update(dispatchledger.open_record(shape="t")["admission"])

    with dispatchledger.annotate(plan_mode="throughput",
                                 brownout_level=1,
                                 classes={"gossip": 3}):
        # plain threads do NOT inherit context; copy like to_thread
        import contextvars
        ctx = contextvars.copy_context()
        t = threading.Thread(target=lambda: ctx.run(dispatch_thread))
        t.start()
        t.join()
    assert got["plan_mode"] == "throughput"
    assert got["brownout_level"] == 1
    assert got["classes"] == {"gossip": 3}
    # outside the block the annotations are gone
    assert dispatchledger.open_record(shape="t")["admission"] == {}


def test_plan_mode_label_closed_vocabulary():
    for mode in (None, "latency", "throughput", "garbage", 7):
        for level in (None, 0, 1, 2, 3, "x"):
            label = dispatchledger.plan_mode_label(mode, level)
            assert label in dispatchledger.PLAN_MODES
    assert dispatchledger.plan_mode_label("latency", 0) == "latency"
    assert dispatchledger.plan_mode_label("throughput", 2) \
        == "brownout2"
    assert dispatchledger.plan_mode_label(None, 0) == "none"


def test_summarize_waste_imbalance_and_decisions():
    recs = [
        {"seq": 1, "lanes": 48, "unique_messages": 6,
         "waste": {"lane": {"real": 48, "padded": 64},
                   "h2c": {"real": 6, "padded": 8}},
         "h2c": {"cache_hits": 2, "cache_misses": 4},
         "msm": {"path": "pippenger"},
         "mesh": {"devices": 8, "makespan_ratio": 1.8},
         "admission": {"plan_mode": "throughput",
                       "brownout_level": 0},
         "compile": {"outcome": "compile", "enqueue_s": 41.0}},
        {"seq": 2, "lanes": 16, "unique_messages": 16,
         "waste": {"lane": {"real": 16, "padded": 16},
                   "h2c": {"real": 16, "padded": 16}},
         "h2c": {"cache_hits": 16, "cache_misses": 0},
         "msm": {"path": "ladder"}, "mesh": {"devices": 0},
         "admission": {}, "compile": {"outcome": "cache_hit"}},
    ]
    s = dispatchledger.summarize(recs)
    assert s["records"] == 2
    assert s["padding_waste"]["lane"] == round(16 / 80, 4)
    assert s["padding_waste_by_lane_bucket"]["64"] == 0.25
    assert s["dedup_ratio"] == round((64 - 22) / 64, 4)
    assert s["decisions"] == {"ladder|0|none": 1,
                              "pippenger|8|throughput": 1}
    assert s["compile"] == {"cache_hit": 1, "compile": 1}
    assert s["compile_s"] == 41.0
    assert s["mesh_imbalance"]["max"] == 1.8
    assert s["h2c_cache"] == {"hits": 18, "misses": 4}
    # since_seq filters (the bench per-phase delta)
    assert dispatchledger.summarize(recs, since_seq=1)["records"] == 1


def test_doctor_ranks_findings_and_cites_records():
    records = [
        {"seq": 7, "shape": "512x8", "lanes": 300,
         "trace_ids": ["aa-000007"],
         "unique_messages": 300,
         "waste": {"lane": {"real": 300, "padded": 512},
                   "h2c": {"real": 300, "padded": 512}},
         "h2c": {"cache_hits": 0, "cache_misses": 300},
         "msm": {"path": "ladder",
                 "why": {"rule": "auto: dispatch device is not a TPU",
                         "tpu": False, "dup": 4.0,
                         "auto_min_dup": 2.0}},
         "mesh": {"devices": 0},
         "admission": {},
         "compile": {"outcome": "compile", "enqueue_s": 41.0}},
        {"seq": 8, "shape": "64x1@m8", "lanes": 40,
         "trace_ids": ["aa-000008"],
         "unique_messages": 10,
         "waste": {"lane": {"real": 40, "padded": 64},
                   "h2c": {"real": 10, "padded": 16}},
         "h2c": {"cache_hits": 10, "cache_misses": 0},
         "msm": {"path": "ladder", "why": {"rule": "explicitly "
                                           "configured"}},
         "mesh": {"devices": 8, "makespan_ratio": 1.8,
                  "shard_lanes": [5, 5, 5, 9, 4, 4, 4, 4]},
         "admission": {},
         "compile": {"outcome": "cache_hit"}},
    ]
    flight = [{"seq": 3, "kind": "slo_breach",
               "objective": "attestation_verify_p50",
               "burn_rate": 2.4, "trace_id": "aa-000007"},
              {"seq": 4, "kind": "config_demotion",
               "subsystem": "mesh", "requested": 6, "resolved": 4,
               "trace_id": ""}]
    diagnosis = doctor.diagnose(records, flight_events=flight)
    findings = diagnosis["findings"]
    assert findings, "doctor found nothing on a loaded scenario"
    sev = [f["severity"] for f in findings]
    assert sev == sorted(sev, reverse=True)
    assert [f["rank"] for f in findings] == list(
        range(1, len(findings) + 1))
    kinds = {f["kind"] for f in findings}
    assert {"compile_latency", "mesh_shard_imbalance",
            "slo_breach", "config_demotion"} <= kinds
    by_kind = {f["kind"]: f for f in findings}
    compile_f = by_kind["compile_latency"]
    assert "cold compile of shape 512x8" in compile_f["title"]
    assert "41.0 s" in compile_f["title"]
    # every compile citation names a dispatch record by seq + trace id
    ev = compile_f["evidence"][0]
    assert ev == {"type": "dispatch", "seq": 7,
                  "trace_id": "aa-000007", "shape": "512x8"}
    imb = by_kind["mesh_shard_imbalance"]
    assert "shard 3 makespan 1.80x mean" in imb["title"]
    assert imb["evidence"][0]["seq"] == 8
    # the SLO breach finding links the flight event's trace id back to
    # the ledger record that served that verification
    breach = by_kind["slo_breach"]
    cited = {(e["type"], e.get("seq")) for e in breach["evidence"]}
    assert ("flight_event", 3) in cited
    assert ("dispatch", 7) in cited
    assert not diagnosis["healthy"]
    # the human rendering carries the citations verbatim
    text = doctor.render_text(diagnosis)
    assert "aa-000007" in text and "512x8" in text
    # a clean ledger renders healthy
    assert doctor.diagnose([])["healthy"]


def _compile_rec(seq, shape, outcome, enqueue_s=30.0):
    return {"seq": seq, "shape": shape, "lanes": 8,
            "trace_ids": [f"bb-{seq:06d}"],
            "unique_messages": 8,
            "waste": {"lane": {"real": 8, "padded": 8},
                      "h2c": {"real": 8, "padded": 8}},
            "h2c": {"cache_hits": 8, "cache_misses": 0},
            "msm": {"path": "ladder"}, "mesh": {"devices": 0},
            "admission": {},
            "compile": {"outcome": outcome, "enqueue_s": enqueue_s}}


def test_doctor_cold_compile_on_hot_path_finding():
    """A serving dispatch that paid a FRESH compile for a shape the
    shapeset registry covers gets its own ranked finding naming the
    fix (`cli precompile` -> AOT store), citing dispatch seq + trace
    id per the PR-11 evidence contract.  Shapes OUTSIDE the registry
    (operator ran an exotic batch) and non-compile outcomes
    (aot_load, cache_load) must NOT fire it."""
    records = [
        # covered: 256x1 is the default service-tier primary bucket
        _compile_rec(11, "256x1", "compile", 314.0),
        _compile_rec(12, "256x1", "compile", 2.0),
        # covered shape but served by the AOT store: not a finding
        _compile_rec(13, "16x1", "aot_load", 0.4),
        # NOT covered (kmax 8 is outside the default serving set)
        _compile_rec(14, "512x8", "compile", 41.0),
    ]
    diagnosis = doctor.diagnose(records)
    cold = [f for f in diagnosis["findings"]
            if f["kind"] == "cold_compile_on_hot_path"]
    assert len(cold) == 1, cold
    f = cold[0]
    assert "256x1" in f["title"]
    assert f["metrics"]["dispatches"] == 2
    assert f["metrics"]["total_s"] == 316.0
    assert "precompile" in f["detail"], "the finding must name the fix"
    # evidence cites the dispatch records: seq + trace id
    cited = {(e["seq"], e["trace_id"]) for e in f["evidence"]}
    assert cited == {(11, "bb-000011"), (12, "bb-000012")}
    # severity puts an avoidable 316 s compile wall above the generic
    # compile_latency finding for the same records
    generic = [x for x in diagnosis["findings"]
               if x["kind"] == "compile_latency"]
    assert generic and f["severity"] > generic[0]["severity"]


def test_flush_failsafe_env_knob_and_evidence():
    """TEKU_TPU_FLUSH_FAILSAFE_MS bounds the WALL time a worker may
    hold a batch open when the service clock stalls (the r10 loadgen
    3.6 s block-import p50); a firing increments the counter and
    records a flight-recorder event."""
    class _FakeImpl:
        def batch_verify(self, triples):
            return True

        def fast_aggregate_verify(self, pks, msg, sig):
            # the facade's batch path verifies single-triple batches
            # through this seam
            return True

    class _HeldController:
        brownout_level = 0

        def plan(self):
            # a 5 s (virtual) fill hold: with the service clock frozen
            # it would hold a worker for 5 REAL seconds without the
            # failsafe
            return BatchPlan(batch_size=64, flush_deadline_s=5.0,
                             brownout_level=0, mode="throughput")

    async def main():
        reg = MetricsRegistry()
        rec = FlightRecorder(registry=MetricsRegistry())
        svc = AggregatingSignatureVerificationService(
            num_workers=1, registry=reg, name="failsafe_t",
            overlap=False, controller=_HeldController(),
            recorder=rec, clock=lambda: 0.0)   # frozen service clock
        await svc.start()
        fut = svc.verify([b"pk"], b"m", b"sig",
                         cls=VerifyClass.GOSSIP)
        ok = await asyncio.wait_for(fut, timeout=5.0)
        await svc.stop()
        return ok, reg, rec

    impl = _FakeImpl()
    bls.set_implementation(impl)
    import os
    os.environ["TEKU_TPU_FLUSH_FAILSAFE_MS"] = "25"
    try:
        ok, reg, rec = asyncio.run(main())
    finally:
        del os.environ["TEKU_TPU_FLUSH_FAILSAFE_MS"]
        bls.reset_implementation()
    assert ok is True
    assert reg.counter("failsafe_t_flush_failsafe_total").value >= 1
    events = [e for e in rec.snapshot()
              if e["kind"] == "flush_failsafe"]
    assert events, "failsafe firing must land in the flight recorder"
    assert events[0]["failsafe_ms"] == 25.0
    assert events[0]["flush_deadline_ms"] == 5000.0


# --------------------------------------------------------------------------
# device: records pinned against provider counters
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def keys():
    pure = PureBls12381()
    sks = [keygen(bytes([61 + i]) * 32) for i in range(8)]
    pks = [pure.secret_key_to_public_key(sk) for sk in sks]
    return pure, sks, pks


@pytest.fixture(scope="module")
def single_impl():
    return JaxBls12381(min_bucket=8)


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (see conftest XLA_FLAGS)")
    m = parallel.make_mesh(8)
    with m:
        yield m


@pytest.fixture(scope="module")
def mesh_impl(mesh8):
    return JaxBls12381(mesh=mesh8, min_bucket=8)


_seq = [0]

# the test_mesh_grouped lane->message grid: two dup-4 committees, two
# dup-2 pairs, four singles = 16 lanes over 8 unique messages (ONE
# compiled shape shared with that module's device tests)
_U_MAP = [0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 3, 3, 4, 5, 6, 7]


def _grid_batch(pure, sks, pks, tag=None):
    if tag is None:
        _seq[0] += 1
        tag = b"ledger-%d" % _seq[0]
    msgs = [tag + b"-%d" % u for u in range(8)]
    sig_cache: dict = {}
    triples = []
    for lane in range(16):
        u, k = _U_MAP[lane], lane % 8
        if (k, u) not in sig_cache:
            sig_cache[(k, u)] = pure.sign(sks[k], msgs[u])
        triples.append(([pks[k]], msgs[u], sig_cache[(k, u)]))
    return triples


def _last_record():
    recs = dispatchledger.LEDGER.snapshot()
    assert recs, "no ledger records"
    return recs[-1]


def test_record_fields_pinned_against_provider_counters(single_impl,
                                                        keys):
    """One real mixed-duplication batch: the record's lanes/padded/
    unique/h2c/dedup/compile/verdict fields must equal the provider's
    own counter deltas, and a warm re-dispatch must flip the h2c
    fields to all-hits/zero-bucket."""
    pure, sks, pks = keys
    triples = _grid_batch(pure, sks, pks)
    before = (PV._M_LANES_REAL.value, PV._M_LANES_PADDED.value,
              PV._M_H2C_UNIQUE.value, single_impl.h2c_dispatch_count,
              dispatchledger.LEDGER.recorded_total)
    assert single_impl.batch_verify(triples)
    rec = _last_record()
    # exactly ONE record per batch dispatch (the h2c sub-dispatch does
    # not open its own record)
    assert dispatchledger.LEDGER.recorded_total == before[4] + 1
    # lanes real/padded == the provider counter deltas
    assert rec["lanes"] == PV._M_LANES_REAL.value - before[0] == 16
    assert rec["waste"]["lane"]["real"] == 16
    assert rec["waste"]["lane"]["padded"] \
        == PV._M_LANES_PADDED.value - before[1] == 16
    # unique messages == the dedup counter delta; ratio matches
    assert rec["unique_messages"] \
        == PV._M_H2C_UNIQUE.value - before[2] == 8
    assert rec["dedup_ratio"] == round((16 - 8) / 16, 4) == 0.5
    # cold batch: 8 fresh messages missed the arena, ONE h2c dispatch
    assert rec["h2c"]["cache_misses"] == 8
    assert rec["h2c"]["cache_hits"] == 0
    assert single_impl.h2c_dispatch_count - before[3] == 1
    assert rec["h2c"]["dispatch_bucket"] >= 8
    assert rec["compile"]["outcome"] in ("compile", "cache_load",
                                         "cache_hit")
    assert rec["compile"]["enqueue_s"] >= 0
    assert rec["verdict"] is True
    assert rec["device"]["sync_s"] >= 0
    assert rec["mesh"]["devices"] == 0
    assert rec["msm"]["path"] in ("ladder", "pippenger")
    assert rec["msm"]["why"]["rule"]
    # warm re-dispatch of the SAME batch: the arena serves every row
    h2c_before = single_impl.h2c_dispatch_count
    assert single_impl.batch_verify(triples)
    warm = _last_record()
    assert warm["h2c"] == {"cache_hits": 8, "cache_misses": 0,
                           "dispatch_bucket": 0}
    assert single_impl.h2c_dispatch_count == h2c_before
    assert warm["compile"]["outcome"] == "cache_hit"


def test_tampered_batch_records_false_verdict(single_impl, keys):
    pure, sks, pks = keys
    triples = _grid_batch(pure, sks, pks)
    triples[10] = (triples[10][0], b"tampered", triples[10][2])
    assert not single_impl.batch_verify(triples)
    rec = _last_record()
    assert rec["verdict"] is False
    # the tamper created a 9th unique message
    assert rec["unique_messages"] == 9


def test_mesh_record_carries_shard_plan_and_imbalance(mesh_impl,
                                                      keys):
    pure, sks, pks = keys
    from teku_tpu.infra.metrics import GLOBAL_REGISTRY
    assert mesh_impl.batch_verify(_grid_batch(pure, sks, pks))
    rec = _last_record()
    assert rec["mesh"]["devices"] == 8
    assert rec["shape"].endswith("@m8")
    # whole-row sharding: the per-shard REAL lane loads sum to the
    # batch and the makespan ratio is max/mean
    lanes = rec["mesh"]["shard_lanes"]
    assert len(lanes) == 8 and sum(lanes) == 16
    expect = max(lanes) / (sum(lanes) / 8)
    assert rec["mesh"]["makespan_ratio"] == round(expect, 4)
    assert rec["mesh"]["makespan_ratio"] >= 1.0
    assert sum(rec["mesh"]["shard_rows"]) == 8
    # the gauge tracks the most recent mesh dispatch
    gauge = GLOBAL_REGISTRY.gauge("bls_mesh_shard_imbalance_ratio")
    assert gauge.value == rec["mesh"]["makespan_ratio"]
    # the decision counter carries the mesh label
    dec = GLOBAL_REGISTRY.labeled_counter("bls_dispatch_decision_total")
    assert any(key[1] == "8" for key, _ in dec._items())


def test_trace_id_lookup_joins_slow_traces_and_endpoint(single_impl,
                                                        keys):
    """The acceptance join: a slow-trace ring entry's trace id must
    look up the exact ledger record that served it, both through the
    ledger API and GET /teku/v1/admin/dispatches?trace_id=."""
    from teku_tpu.api import BeaconRestApi
    pure, sks, pks = keys
    tracing.clear_slow_traces()
    with tracing.trace("ledger_accept") as tr:
        assert single_impl.batch_verify(_grid_batch(pure, sks, pks))
    trace_id = tr.trace_id
    slow_ids = {t["trace_id"] for t in tracing.slow_traces()}
    assert trace_id in slow_ids
    # ledger-side lookup
    matches = dispatchledger.LEDGER.snapshot(trace_id=trace_id)
    assert len(matches) == 1
    assert trace_id in matches[0]["trace_ids"]
    # endpoint-side lookup (+ slow filter + tail + summary envelope)
    api = BeaconRestApi(None)

    async def drive():
        by_trace = (await api._admin_dispatches(
            query={"trace_id": trace_id}))["data"]
        slow = (await api._admin_dispatches(
            query={"slow": "1"}))["data"]
        tail = (await api._admin_dispatches(
            query={"last": "1"}))["data"]
        return by_trace, slow, tail

    by_trace, slow, tail = asyncio.run(drive())
    assert len(by_trace["records"]) == 1
    assert by_trace["records"][0]["seq"] == matches[0]["seq"]
    assert by_trace["summary"]["records"] == 1
    assert any(r["seq"] == matches[0]["seq"]
               for r in slow["records"])
    assert len(tail["records"]) == 1
    assert tail["capacity"] == dispatchledger.LEDGER.capacity
    # the doctor over the live ledger: every dispatch citation's
    # trace id resolves back to a real ledger record
    diagnosis = doctor.diagnose(dispatchledger.LEDGER.snapshot())
    all_ids = {tid for r in dispatchledger.LEDGER.snapshot()
               for tid in r.get("trace_ids", [])}
    for f in diagnosis["findings"]:
        for ev in f["evidence"]:
            if ev.get("type") == "dispatch" and ev.get("trace_id"):
                assert ev["trace_id"] in all_ids
    text = doctor.render_text(diagnosis)
    assert "dispatch record" in text


def test_service_annotations_land_in_records(single_impl, keys):
    """End-to-end plan propagation: a service drain under a live
    controller stamps plan_mode/class-mix into the record the REAL
    provider writes (the asyncio.to_thread context copy)."""
    pure, sks, pks = keys

    class FixedController:
        brownout_level = 0

        def plan(self):
            return BatchPlan(batch_size=16, flush_deadline_s=0.0,
                             brownout_level=0, mode="latency")

    async def main():
        bls.set_implementation(single_impl)
        try:
            svc = AggregatingSignatureVerificationService(
                num_workers=1, registry=MetricsRegistry(),
                name="ledger_ann", controller=FixedController())
            await svc.start()
            triples = _grid_batch(pure, sks, pks)
            futs = [svc.verify(*t) for t in triples[:4]]
            assert all(await asyncio.gather(*futs))
            await svc.stop()
        finally:
            bls.reset_implementation()

    mark = dispatchledger.LEDGER.recorded_total
    asyncio.run(main())
    recs = [r for r in dispatchledger.LEDGER.snapshot()
            if r["seq"] > mark]
    assert recs
    ann = recs[-1]["admission"]
    assert ann["plan_mode"] == "latency"
    assert ann["brownout_level"] == 0
    assert ann["service"] == "ledger_ann"
    assert sum(ann["classes"].values()) >= 1
    assert set(ann["classes"]) <= {c.label for c in VerifyClass}


# --------------------------------------------------------------------------
# review hardening: idempotent publication, eviction flag, live brownout
# --------------------------------------------------------------------------

def test_sync_error_retry_publishes_record_once():
    """A raising sync publishes the record (verdict null); a retry
    that succeeds must UPDATE that record in place — a second
    record() would double-count its waste/decision metrics and give
    one trace id two ring entries."""
    import time

    import numpy as np

    from teku_tpu.ops.provider import _DispatchHandle

    class _FlakyLaneOk:
        def __init__(self):
            self.calls = 0

        def __array__(self, *a, **k):
            self.calls += 1
            if self.calls == 1:
                raise RuntimeError("wedged sync")
            return np.ones(4, dtype=bool)

    led = dispatchledger.LEDGER
    base = led.recorded_total
    rec = dispatchledger.open_record(
        shape="4x1", trace_ids=["retry-1"],
        waste={"lane": {"real": 3, "padded": 4}})
    handle = _DispatchHandle(
        np.asarray(True), _FlakyLaneOk(), 4, (), "4x1", "vpu",
        time.perf_counter(), rec=rec)
    with pytest.raises(RuntimeError):
        handle.result()
    assert led.recorded_total == base + 1
    wedged = led.snapshot(trace_id="retry-1")[-1]
    assert wedged["device"]["sync_error"] is True
    assert wedged["verdict"] is None
    assert handle.result() is True          # retry succeeds
    assert led.recorded_total == base + 1   # same ring entry, updated
    retried = led.snapshot(trace_id="retry-1")
    assert len(retried) == 1
    assert retried[-1]["verdict"] is True
    assert "busy_s" in retried[-1]["device"]


def test_summary_flags_records_evicted_from_the_ring():
    """A phase window that outgrew the bounded ring must say so —
    bench_diff gates on the per-phase summary and silent truncation
    would read as full coverage."""
    led = dispatchledger.DispatchLedger(
        capacity=4, registry=MetricsRegistry())
    for _ in range(6):
        led.record({"lanes": 4, "unique_messages": 4})
    s = led.summary()
    assert s["records"] == 4
    assert s["evicted"] == 2
    fresh = led.summary(since_seq=4)
    assert fresh["records"] == 2
    assert "evicted" not in fresh


def test_doctor_reports_active_brownout_from_admission_snapshot():
    """The flight ring shows brownout TRANSITIONS; the admission
    snapshot says what is true NOW (the enter event can roll off the
    bounded ring while the brownout is still on)."""
    diagnosis = doctor.diagnose([], admission={
        "plan": {"batch_size": 256, "mode": "throughput"},
        "inputs": {"utilization": 0.95, "burn_rate": 2.4,
                   "queue_depth": 512},
        "brownout": {"level": 1, "shedding": ["optimistic"],
                     "enters": 1, "exits": 0}})
    assert not diagnosis["healthy"]
    by_kind = {f["kind"]: f for f in diagnosis["findings"]}
    f = by_kind["brownout_active"]
    assert "optimistic" in f["title"]
    assert f["metrics"]["level"] == 1
    assert f["metrics"]["plan"]["batch_size"] == 256
    # a calm controller raises nothing
    assert doctor.diagnose([], admission={
        "brownout": {"level": 0}})["healthy"]


def test_dispatch_annotations_carry_the_governing_plan():
    """The record must stamp the plan the batch was ASSEMBLED under:
    re-fetching controller.plan() at dispatch time could tick a
    brownout edge mid-flight and stamp a mode the batch was never
    admitted under.  Without a governing plan (bisect re-dispatch)
    the fallback is a passive last_plan() read — never plan()."""

    class _TickingController:
        def __init__(self):
            self.plan_calls = 0

        def plan(self):
            self.plan_calls += 1
            return BatchPlan(batch_size=256, flush_deadline_s=0.0,
                             brownout_level=1, mode="throughput")

        def last_plan(self):
            return BatchPlan(batch_size=64, flush_deadline_s=0.0,
                             brownout_level=0, mode="latency")

    ctrl = _TickingController()
    # constructed but never start()ed: no worker loop runs, so the
    # only plan()/last_plan() calls are the ones under test
    svc = AggregatingSignatureVerificationService(
        num_workers=1, registry=MetricsRegistry(),
        name="govplan", controller=ctrl)
    task = type("T", (), {"cls": VerifyClass.GOSSIP})()
    governing = BatchPlan(batch_size=32, flush_deadline_s=0.0,
                          brownout_level=0, mode="latency")
    ann = svc._dispatch_annotations([task], governing)
    assert ann["plan_mode"] == "latency"
    assert ann["plan_batch_size"] == 32
    assert ctrl.plan_calls == 0
    fallback = svc._dispatch_annotations([task], None)
    assert fallback["plan_batch_size"] == 64   # last_plan(), no tick
    assert ctrl.plan_calls == 0


def test_doctor_slo_findings_consume_the_real_snapshot_shape():
    """SloEngine.snapshot() is a mapping keyed by objective name (the
    readiness endpoint serves it verbatim) — the analyzer must emit a
    slo_burn finding from that shape, not a phantom 'objectives'
    list."""
    diagnosis = doctor.diagnose([], slo={
        "attestation_verify_p50": {
            "description": "p50 end-to-end verify latency <= 100ms",
            "target_ratio": 0.9, "burn_rate": 5.0,
            "breached": True, "windows": 12},
        "verify_error_rate": {
            "description": "verify errors", "target_ratio": 0.999,
            "burn_rate": 0.2, "breached": False, "windows": 12}})
    burns = [f for f in diagnosis["findings"]
             if f["kind"] == "slo_burn"]
    assert len(burns) == 1
    assert burns[0]["metrics"]["objective"] == "attestation_verify_p50"
    assert burns[0]["metrics"]["burn_rate"] == 5.0
    assert not diagnosis["healthy"]
