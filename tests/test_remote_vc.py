"""Remote validator client: duties over the REST API against a live
beacon node — blocks proposed, attestations and aggregates submitted,
chain justifies, and the remote chain matches in-process behavior."""

import asyncio

import pytest

from teku_tpu.api import BeaconRestApi
from teku_tpu.infra.service import ServiceController
from teku_tpu.node.gossip import InMemoryGossipNetwork
from teku_tpu.node.node import BeaconNode
from teku_tpu.spec import create_spec
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.validator import (BeaconNodeValidatorApi, LocalSigner,
                                RemoteValidatorApi,
                                SlashingProtectedSigner, ValidatorClient)
from teku_tpu.validator.slashing_protection import SlashingProtector


@pytest.mark.slow
def test_remote_vc_drives_chain_to_justification():
    # altair at genesis: the remote VC also exercises the
    # sync-committee submission endpoint
    import dataclasses
    from teku_tpu.spec import config as C
    from teku_tpu.spec import Spec
    spec = Spec(dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0))
    state, sks = interop_genesis(spec.config, 16)

    async def run():
        net = InMemoryGossipNetwork()
        node = BeaconNode(spec, state, net.endpoint())
        api = BeaconRestApi(node,
                            validator_api=BeaconNodeValidatorApi(node))
        controller = ServiceController([node], "remote-vc-test")
        await controller.start()
        await api.start()
        try:
            remote = RemoteValidatorApi(
                spec, f"http://127.0.0.1:{api.port}")
            # record every fetch: the remote VC must live off duty
            # endpoints, never the debug state download (mainnet states
            # are hundreds of MB — VERDICT r3 weak #2)
            fetched = []
            orig_bytes = remote._get_bytes
            orig_json = remote._get_json

            def rec_bytes(path, _o=orig_bytes):
                data = _o(path)
                fetched.append((path, len(data)))
                return data

            def rec_json(path, _o=orig_json):
                out = _o(path)
                fetched.append((path, 0))
                return out
            remote._get_bytes = rec_bytes
            remote._get_json = rec_json
            signer = SlashingProtectedSigner(
                LocalSigner(dict(enumerate(sks))), SlashingProtector())
            client = ValidatorClient(spec, remote, signer,
                                     list(range(16)))
            loop = asyncio.get_running_loop()
            epochs = 3
            for slot in range(1, epochs * spec.config.SLOTS_PER_EPOCH + 1):
                await node.on_slot(slot)
                # the remote VC is its own process in production; here
                # each duty phase runs in a worker thread (own loop) so
                # its blocking HTTP can be served by THIS loop
                for phase in (client.on_slot_start,
                              client.on_attestation_due,
                              client.on_sync_committee_due,
                              client.on_aggregation_due):
                    await loop.run_in_executor(
                        None, lambda p=phase: asyncio.run(p(slot)))
            assert client.blocks_proposed \
                >= epochs * spec.config.SLOTS_PER_EPOCH - 1
            assert client.attestations_sent > 0
            assert node.chain.head_slot() \
                >= epochs * spec.config.SLOTS_PER_EPOCH - 1
            assert node.store.justified_checkpoint.epoch >= 1
            # the remote sync-aggregation duty used the REST
            # contribution endpoints: contributions reached the pool
            contrib_keys = [k for k in node.sync_pool._msgs
                            if isinstance(k, tuple)
                            and k and k[0] == "contrib"]
            assert contrib_keys, "no remote contributions pooled"
            # no beacon state ever crossed the wire: no debug-state
            # fetch, and every GET stayed wire-light (blocks, duties,
            # attestation data — never a state-sized body)
            assert fetched, "nothing recorded"
            assert not any("/debug/" in p for p, _ in fetched)
            assert max(n for _, n in fetched) < 100_000, \
                "a state-sized body crossed the wire"
        finally:
            await api.stop()
            await controller.stop()

    asyncio.run(run())
