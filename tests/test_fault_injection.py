"""Fault-injection harness semantics + the failure paths it unlocks.

Covers the harness itself (site registry, budgets, check/transform
split), wrong-result bisection in the batching service at the issue's
250-task scale, overflow-shed observability, and the reqresp
retry-on-transient-failure path.
"""

import asyncio
import logging

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.infra import faults
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.services.signatures import (
    AggregatingSignatureVerificationService, ServiceCapacityExceededError)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.clear()
    bls.reset_implementation()


SKS = [keygen(bytes([60 + i]) * 32) for i in range(4)]
PKS = [bls.secret_to_public_key(sk) for sk in SKS]


# --------------------------------------------------------------------------
# harness semantics
# --------------------------------------------------------------------------

def test_inactive_harness_is_free():
    assert not faults.active()
    faults.check("anywhere")                     # no-op
    assert faults.transform("anywhere", True) is True


def test_times_budget_and_clear():
    f = faults.inject("s", faults.Raise(ValueError("x"), times=2))
    for _ in range(2):
        with pytest.raises(ValueError):
            faults.check("s")
    faults.check("s")                            # budget spent
    assert f.fired == 2
    assert faults.fired_count("s") == 2
    faults.clear("s")
    assert not faults.active()


def test_sites_are_independent():
    faults.inject("a", faults.Raise(ValueError("a")))
    faults.check("b")                            # different site: clean
    with pytest.raises(ValueError):
        faults.check("a")


def test_wrong_result_only_consumed_by_transform():
    f = faults.inject("s", faults.WrongResult(times=1))
    faults.check("s")                            # must NOT spend it
    assert f.fired == 0
    assert faults.transform("s", True) is False  # inverted
    assert faults.transform("s", True) is True   # budget spent


def test_hang_fault_blocks_for_duration():
    import time
    faults.inject("s", faults.Hang(0.05, times=1))
    t0 = time.monotonic()
    faults.check("s")
    assert time.monotonic() - t0 >= 0.05


def test_overflow_fault_raises_queuefull():
    faults.inject("s", faults.Overflow(times=1))
    with pytest.raises(asyncio.QueueFull):
        faults.check("s")


def test_callable_exception_factory():
    faults.inject("s", faults.Raise(lambda: RuntimeError("fresh")))
    with pytest.raises(RuntimeError):
        faults.check("s")
    with pytest.raises(RuntimeError):            # fresh instance each time
        faults.check("s")


# --------------------------------------------------------------------------
# facade / provider sites
# --------------------------------------------------------------------------

def test_facade_batch_verify_wrong_result_site():
    sk, pk = SKS[0], PKS[0]
    sig = bls.sign(sk, b"m")
    faults.inject("bls.batch_verify", faults.WrongResult(times=1))
    assert bls.batch_verify([([pk], b"m", sig)]) is False  # corrupted
    assert bls.batch_verify([([pk], b"m", sig)]) is True   # clean again


def test_spec_verifier_site():
    from teku_tpu.spec.verifiers import BatchSignatureVerifier, SIMPLE

    sig = bls.sign(SKS[0], b"m")
    faults.inject("verifiers.dispatch", faults.WrongResult(times=1))
    assert SIMPLE.verify([PKS[0]], b"m", sig) is False
    v = BatchSignatureVerifier()
    assert v.verify([PKS[0]], b"m", sig)        # optimistic record
    assert v.batch_verify() is True             # fault budget spent


# --------------------------------------------------------------------------
# bisect-on-fail under injected wrong results (satellite)
# --------------------------------------------------------------------------

def run(coro):
    return asyncio.run(coro)


def make_service(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return AggregatingSignatureVerificationService(**kw)


class StubBls:
    """Constant-time provider for batch-shape tests: a triple verifies
    iff its signature equals the stub tag for (pks, msg).  Bisection at
    the 250-task scale is a SERVICE-layer property; real pairing math
    at that scale belongs to the slow tier."""

    name = "stub"

    @staticmethod
    def tag(pks, msg):
        return (b"sig:" + msg + b":" + b"".join(pk[:2] for pk in pks)
                )[:96].ljust(96, b"\x00")

    def fast_aggregate_verify(self, pks, msg, sig):
        return sig == self.tag(pks, msg)

    def batch_verify(self, triples):
        return all(self.fast_aggregate_verify(pks, m, s)
                   for pks, m, s in triples)


def test_bisect_isolates_single_bad_triple_in_250_batch():
    """One genuinely-bad triple inside a full 250-task batch: bisection
    must fail exactly that task and pass the other 249."""
    async def main():
        stub = StubBls()
        bls.set_implementation(stub)
        svc = make_service(num_workers=1, max_batch_size=250,
                           split_threshold=4)
        await svc.start()
        futs = []
        bad_index = 137
        n = 250
        for i in range(n):
            m = b"m-%d" % i
            pks = [PKS[i % 4]]
            sig = (stub.tag(pks, m) if i != bad_index
                   else stub.tag(pks, b"tampered"))
            futs.append(svc.verify(pks, m, sig))
        got = await asyncio.gather(*futs)
        await svc.stop()
        assert got[bad_index] is False
        assert all(got[:bad_index]) and all(got[bad_index + 1:])
    run(main())


def test_bisect_survives_spurious_wrong_result_fault():
    """A WrongResult fault on the FIRST whole-batch dispatch (a flaky
    device reporting False for a good batch): bisection re-verifies and
    every honest task still resolves True — wrong results cost retries,
    never verdicts."""
    async def main():
        svc = make_service(num_workers=1, split_threshold=4)
        await svc.start()
        faults.inject("bls.batch_verify",
                      faults.WrongResult(times=1))
        futs = []
        for i in range(8):
            m = b"flaky-%d" % i
            futs.append(svc.verify([PKS[i % 4]],
                                   m, bls.sign(SKS[i % 4], m)))
        got = await asyncio.gather(*futs)
        await svc.stop()
        assert got == [True] * 8
        assert faults.fired_count("bls.batch_verify") == 1
    run(main())


def test_atomic_multi_sig_task_fails_as_unit_under_fault():
    """A multi-triple task (e.g. SignedAggregateAndProof's three
    signatures) is atomic through bisection: one bad triple fails the
    WHOLE task, neighbours unaffected."""
    async def main():
        svc = make_service(num_workers=1, split_threshold=2)
        await svc.start()
        m1, m2, m3 = b"sel", b"agg", b"proof"
        good_multi = [([PKS[0]], m1, bls.sign(SKS[0], m1)),
                      ([PKS[1]], m2, bls.sign(SKS[1], m2))]
        bad_multi = [([PKS[2]], m3, bls.sign(SKS[2], m3)),
                     ([PKS[3]], m1, bls.sign(SKS[3], m2))]  # wrong msg
        f1 = svc.verify_multi(good_multi)
        f2 = svc.verify_multi(bad_multi)
        f3 = svc.verify([PKS[0]], m2, bls.sign(SKS[0], m2))
        got = await asyncio.gather(f1, f2, f3)
        await svc.stop()
        assert got == [True, False, True]
    run(main())


# --------------------------------------------------------------------------
# overflow shedding observability (satellite)
# --------------------------------------------------------------------------

def test_overflow_shed_counts_and_warns(caplog):
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        faults.inject("sigservice.enqueue", faults.Overflow(times=1))
        sig = bls.sign(SKS[0], b"shed")
        with caplog.at_level(logging.WARNING,
                             logger="teku_tpu.services.signatures"):
            with pytest.raises(ServiceCapacityExceededError):
                svc.verify([PKS[0]], b"shed", sig)
        await svc.stop()
        # sheds carry the priority class (gossip is the default)
        rejected = reg.metrics()[
            "signature_verifications_rejected_total"].labels(
            **{"class": "gossip"}).value
        assert rejected == 1
        assert any("shedding" in r.getMessage()
                   for r in caplog.records)
        assert ('signature_verifications_rejected_total'
                '{class="gossip"} 1') in reg.expose()
    run(main())


def test_real_queue_overflow_also_counted():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, queue_capacity=2, registry=reg)
        await svc.start()
        # distinct messages: identical pending triples would coalesce
        # onto one queued task instead of filling the queue
        msgs = [b"ovf-%d" % i for i in range(52)]
        sigs = [bls.sign(SKS[0], m) for m in msgs]
        futs = [svc.verify([PKS[0]], msgs[i], sigs[i]) for i in range(2)]
        with pytest.raises(ServiceCapacityExceededError):
            for i in range(2, 52):
                futs.append(svc.verify([PKS[0]], msgs[i], sigs[i]))
        await asyncio.gather(*futs)
        await svc.stop()
        assert reg.metrics()[
            "signature_verifications_rejected_total"].labels(
            **{"class": "gossip"}).value >= 1
    run(main())


# --------------------------------------------------------------------------
# reqresp retry on transient failures (satellite)
# --------------------------------------------------------------------------

def _import_reqresp():
    """Import the RPC module even where the optional `cryptography`
    dependency (noise transport) is absent: the retry/timeout logic
    under test is pure asyncio and must stay testable in minimal
    containers."""
    try:
        from teku_tpu.networking import reqresp
        return reqresp
    except ModuleNotFoundError:
        import importlib
        import os
        import sys
        import types
        import teku_tpu
        if "teku_tpu.networking" not in sys.modules:
            pkg = types.ModuleType("teku_tpu.networking")
            pkg.__path__ = [os.path.join(
                os.path.dirname(teku_tpu.__file__), "networking")]
            sys.modules["teku_tpu.networking"] = pkg
        return importlib.import_module("teku_tpu.networking.reqresp")


class _FlakyPeer:
    """Peer whose request times out `fail` times, then succeeds."""

    def __init__(self, fail: int, payload: bytes):
        self.fail = fail
        self.calls = 0
        self.payload = payload
        self.timeouts = []

    async def request(self, method, body, timeout=10.0):
        self.calls += 1
        self.timeouts.append(timeout)
        if self.calls <= self.fail:
            raise asyncio.TimeoutError()
        return self.payload


def _make_rpc(**kw):
    BeaconRpc = _import_reqresp().BeaconRpc

    class _Net:
        on_request = None

    return BeaconRpc(_Net(), node=None, **kw)


def test_reqresp_retries_transient_timeouts():
    _import_reqresp()
    import importlib
    E = importlib.import_module("teku_tpu.networking.encoding")

    async def main():
        rpc = _make_rpc(request_timeout_s=5.0, request_attempts=3)
        payload = E.encode_response_chunk(b"chunk")
        peer = _FlakyPeer(fail=2, payload=payload)
        resp = await rpc._fetch(peer, "any", b"")
        assert resp == payload
        assert peer.calls == 3
        # the configurable timeout reached the transport on every try
        assert peer.timeouts == [5.0] * 3
    asyncio.run(main())


def test_reqresp_bounded_attempts_then_fails():
    async def main():
        rpc = _make_rpc(request_timeout_s=1.0, request_attempts=2)
        peer = _FlakyPeer(fail=99, payload=b"")
        with pytest.raises(RuntimeError):
            await rpc._fetch(peer, "any", b"")
        assert peer.calls == 2
    asyncio.run(main())


def test_reqresp_malformed_response_not_retried():
    """A malformed blocks_by_range response is peer misbehaviour, not a
    transient fault: it must raise WITHOUT burning retry attempts."""
    async def main():
        rpc = _make_rpc(request_timeout_s=1.0, request_attempts=3)
        peer = _FlakyPeer(fail=0, payload=b"\xff\xffgarbage")
        with pytest.raises(ConnectionError):
            await rpc.blocks_by_range(peer, 0, 4)
        assert peer.calls == 1                  # no retries on garbage
    asyncio.run(main())


def test_reqresp_timeout_env_default(monkeypatch):
    monkeypatch.setenv("TEKU_TPU_REQRESP_TIMEOUT_S", "7.5")
    rpc = _make_rpc()
    assert rpc.request_timeout_s == 7.5
