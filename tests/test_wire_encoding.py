"""Spec ssz_snappy wire encoding: uvarint, CRC32C, snappy framing
format, response chunking — frame-level conformance vectors.

reference: networking/eth2/.../rpc/core/encodings/ (LengthPrefixed
Encoding, SnappyFrameDecoder) — the same byte shapes the spec mandates
for req/resp streams.
"""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import pytest

from teku_tpu.networking import encoding as E


# -- uvarint ---------------------------------------------------------------

def test_uvarint_vectors():
    # protobuf varint test vectors
    cases = [(0, b"\x00"), (1, b"\x01"), (127, b"\x7f"),
             (128, b"\x80\x01"), (300, b"\xac\x02"),
             (16384, b"\x80\x80\x01"), (2 ** 32, b"\x80\x80\x80\x80\x10")]
    for value, wire in cases:
        assert E.write_uvarint(value) == wire
        got, pos = E.read_uvarint(wire)
        assert got == value and pos == len(wire)


def test_uvarint_truncated_and_oversized():
    with pytest.raises(E.EncodingError):
        E.read_uvarint(b"\x80")          # continuation bit, no next byte
    with pytest.raises(E.EncodingError):
        E.read_uvarint(b"\xff" * 11)     # > 10 bytes


# -- CRC32C ----------------------------------------------------------------

def test_crc32c_known_vector():
    # RFC 3720 test vector: crc32c("123456789") = 0xE3069283
    assert E.crc32c(b"123456789") == 0xE3069283
    assert E.crc32c(b"") == 0
    # python fallback agrees with whatever implementation is active
    assert E._crc32c_py(b"123456789") == 0xE3069283


def test_masked_crc_matches_snappy_mask_definition():
    c = E.crc32c(b"abc")
    expected = (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF
    assert E.masked_crc32c(b"abc") == expected


# -- framing format --------------------------------------------------------

def test_frame_stream_identifier_prefix():
    out = E.frame_compress(b"hello world")
    assert out.startswith(b"\xff\x06\x00\x00sNaPpY")


def test_frame_roundtrip_small_and_multi_chunk():
    for payload in (b"", b"x", b"hello" * 100,
                    bytes(range(256)) * 600):     # >64KiB → 3 chunks
        assert E.frame_uncompress(E.frame_compress(payload)) == payload


def test_frame_checksum_corruption_detected():
    out = bytearray(E.frame_compress(b"payload under test" * 10))
    out[-1] ^= 0xFF                       # flip a data byte
    with pytest.raises(E.EncodingError):
        E.frame_uncompress(bytes(out))


def test_frame_rejects_missing_identifier():
    with pytest.raises(E.EncodingError):
        E.frame_uncompress(b"\x01\x08\x00\x00AAAAAAAA")


# -- request/response payload shapes ---------------------------------------

def test_payload_roundtrip_and_length_prefix_enforced():
    ssz = b"\x2a" * 1000
    wire = E.encode_payload(ssz)
    # prefix is the UNCOMPRESSED length as uvarint
    want, pos = E.read_uvarint(wire)
    assert want == 1000
    got, end = E.decode_payload(wire)
    assert got == ssz and end == len(wire)
    # lying length prefix is rejected
    forged = E.write_uvarint(999) + wire[pos:]
    with pytest.raises(E.EncodingError):
        E.decode_payload(forged)


def test_payload_over_limit_rejected():
    wire = E.encode_payload(b"abc")
    with pytest.raises(E.EncodingError):
        E.decode_payload(E.write_uvarint(E.MAX_PAYLOAD + 1) + wire[1:])


def test_response_chunks_roundtrip_with_result_codes():
    chunks = [b"first-ssz", b"second" * 50, b""]
    body = b"".join(E.encode_response_chunk(c) for c in chunks)
    parsed = E.decode_response(body)
    assert [ssz for _, ssz in parsed] == chunks
    assert all(result == E.RESULT_SUCCESS for result, _ in parsed)
    err = E.encode_response_chunk(b"nope", result=E.RESULT_SERVER_ERROR)
    parsed = E.decode_response(err)
    assert parsed == [(E.RESULT_SERVER_ERROR, b"nope")]


def test_multiple_payloads_back_to_back_consume_exact_bytes():
    a = E.encode_payload(b"A" * 70000)   # multi-chunk stream
    b = E.encode_payload(b"BB")
    ssz_a, pos = E.decode_payload(a + b)
    assert ssz_a == b"A" * 70000
    ssz_b, end = E.decode_payload(a + b, pos)
    assert ssz_b == b"BB" and end == len(a + b)
