"""Batching service semantics: drain, bisect-on-fail, overflow, grouping.

Runs against the pure-Python provider (fast enough at these sizes and
identical semantics through the SPI; the TPU provider is exercised by
tests/test_jax_provider.py)."""

import asyncio

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.services.signatures import (
    AggregatingSignatureVerificationService, ServiceCapacityExceededError)

SKS = [keygen(bytes([40 + i]) * 32) for i in range(4)]
PKS = [bls.secret_to_public_key(sk) for sk in SKS]


def run(coro):
    return asyncio.run(coro)


def make_service(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return AggregatingSignatureVerificationService(**kw)


def test_basic_verify_and_metrics():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        msg = b"single"
        sig = bls.sign(SKS[0], msg)
        ok = await svc.verify([PKS[0]], msg, sig)
        bad = await svc.verify([PKS[0]], b"other", sig)
        await svc.stop()
        assert ok and not bad
        assert reg.counter("signature_verifications_task_count_total").value >= 2
        assert reg.counter("signature_verifications_batch_count_total").value >= 2
        assert "signature_verifications_batch_size_bucket" in reg.expose()
    run(main())


def test_batching_drains_queue():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        futs = []
        msgs = [b"drain-%d" % i for i in range(6)]
        for i, m in enumerate(msgs):
            futs.append(svc.verify([PKS[i % 4]], m, bls.sign(SKS[i % 4], m)))
        results = await asyncio.gather(*futs)
        await svc.stop()
        assert all(results)
        # fewer batches than tasks proves the drain actually batched
        assert (reg.counter("signature_verifications_batch_count_total").value
                < len(msgs))
    run(main())


def test_bad_signature_isolated_by_bisect():
    async def main():
        svc = make_service(num_workers=1, split_threshold=2)
        await svc.start()
        futs = []
        for i in range(5):
            m = b"bisect-%d" % i
            sig = bls.sign(SKS[i % 4], m)
            if i == 2:
                m = b"tampered"
            futs.append(svc.verify([PKS[i % 4]], m, sig))
        results = await asyncio.gather(*futs)
        await svc.stop()
        assert results == [True, True, False, True, True]
    run(main())


def test_multi_triple_task_atomic():
    async def main():
        svc = make_service(num_workers=1)
        await svc.start()
        m1, m2 = b"proof", b"aggregate"
        good = [([PKS[0]], m1, bls.sign(SKS[0], m1)),
                ([PKS[1]], m2, bls.sign(SKS[1], m2))]
        bad = [([PKS[0]], m1, bls.sign(SKS[0], m1)),
               ([PKS[1]], b"wrong", bls.sign(SKS[1], m2))]
        ok = await svc.verify_multi(good)
        not_ok = await svc.verify_multi(bad)
        await svc.stop()
        assert ok and not not_ok  # one bad sig fails the whole task
    run(main())


def test_queue_overflow():
    async def main():
        svc = make_service(num_workers=1, queue_capacity=2)
        await svc.start()
        # DISTINCT messages: identical pending triples would coalesce
        # onto one queued task and never overflow the queue
        msgs = [b"overflow-%d" % i for i in range(52)]
        sigs = [bls.sign(SKS[0], m) for m in msgs]
        futs = [svc.verify([PKS[0]], msgs[i], sigs[i]) for i in range(2)]
        with pytest.raises(ServiceCapacityExceededError):
            for i in range(2, 52):
                futs.append(svc.verify([PKS[0]], msgs[i], sigs[i]))
        await asyncio.gather(*futs)
        await svc.stop()
    run(main())


def test_identical_inflight_triples_coalesce():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        msg = b"coalesce"
        sig = bls.sign(SKS[0], msg)
        bad_sig = bls.sign(SKS[0], b"wrong message")
        # gossip re-delivery: the same triple submitted 5x while queued
        futs = [svc.verify([PKS[0]], msg, sig) for _ in range(5)]
        bad = [svc.verify([PKS[0]], msg, bad_sig) for _ in range(3)]
        results = await asyncio.gather(*futs)
        bad_results = await asyncio.gather(*bad)
        assert results == [True] * 5       # verdict fans out to waiters
        assert bad_results == [False] * 3
        coalesced = reg.counter(
            "signature_verifications_coalesced_total").value
        assert coalesced == 4 + 2
        # pending map drains once verdicts land
        assert not svc._pending
        # a RE-submission after completion is a fresh task (the dedup
        # is in-flight only — a later identical request must re-verify)
        assert await svc.verify([PKS[0]], msg, sig) is True
        await svc.stop()
    run(main())


def test_multi_triple_tasks_coalesce_by_full_key():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        m1, m2 = b"agg-1", b"agg-2"
        task = [([PKS[0]], m1, bls.sign(SKS[0], m1)),
                ([PKS[1]], m2, bls.sign(SKS[1], m2))]
        f1 = svc.verify_multi(task)
        f2 = svc.verify_multi(list(task))      # identical -> coalesces
        f3 = svc.verify_multi(task[:1])        # different key: own task
        assert await asyncio.gather(f1, f2, f3) == [True, True, True]
        assert reg.counter(
            "signature_verifications_coalesced_total").value == 1
        await svc.stop()
    run(main())


def test_cancelled_primary_promotes_live_waiter():
    async def main():
        svc = make_service(num_workers=1)
        await svc.start()
        msg = b"promote"
        sig = bls.sign(SKS[0], msg)
        f1 = svc.verify([PKS[0]], msg, sig)
        f2 = svc.verify([PKS[0]], msg, sig)  # coalesce onto f1's task
        f3 = svc.verify([PKS[0]], msg, sig)
        # the original submitter bails while the task is still queued:
        # the waiters' callers still want the verdict — the first live
        # waiter is promoted to primary, nobody else gets cancelled
        f1.cancel()
        assert await asyncio.gather(f2, f3) == [True, True]
        assert f1.cancelled()
        assert not svc._pending
        await svc.stop()
    run(main())


class _AsyncHandle:
    def __init__(self, verdict):
        self._verdict = verdict

    def result(self):
        return self._verdict


class _AsyncFakeImpl:
    """Minimal BLS impl exposing the async begin seam: records the
    call interleaving so the overlap test can prove begin(N+1) runs
    BEFORE result(N) is read."""

    def __init__(self):
        self.calls = []

    def _verdict(self, triples):
        return all(sig == b"good" for _pks, _msg, sig in triples)

    def begin_batch_verify(self, triples):
        self.calls.append(("begin", len(triples)))
        verdict = self._verdict(triples)

        class H(_AsyncHandle):
            def result(h):
                self.calls.append(("result", len(triples)))
                return verdict

        return H(verdict)

    def batch_verify(self, triples):
        self.calls.append(("sync", len(triples)))
        return self._verdict(triples)

    def fast_aggregate_verify(self, pks, msg, sig):
        self.calls.append(("sync", 1))
        return sig == b"good"


def test_async_overlap_begins_next_batch_before_retiring_previous():
    async def main():
        impl = _AsyncFakeImpl()
        bls.set_implementation(impl)
        try:
            svc = make_service(num_workers=1, overlap=True)
            await svc.start()
            futs = [svc.verify([PKS[i % 4]], b"msg-%d" % i, b"good")
                    for i in range(6)]
            assert all(await asyncio.gather(*futs))
            await svc.stop()
        finally:
            bls.reset_implementation()
        begins = [c for c in impl.calls if c[0] == "begin"]
        assert begins, "async seam never engaged"
        # if more than one batch formed, the worker must have begun a
        # later batch before reading an earlier batch's result
        if len(begins) > 1:
            first_result = impl.calls.index(("result", begins[0][1]))
            second_begin = impl.calls.index(begins[1])
            assert second_begin < first_result
    run(main())


def test_async_overlap_failure_still_bisects():
    async def main():
        impl = _AsyncFakeImpl()
        bls.set_implementation(impl)
        try:
            svc = make_service(num_workers=1, overlap=True,
                               split_threshold=2)
            await svc.start()
            futs = []
            for i in range(5):
                sig = b"bad" if i == 2 else b"good"
                futs.append(svc.verify([PKS[i % 4]], b"bis-%d" % i, sig))
            results = await asyncio.gather(*futs)
            await svc.stop()
        finally:
            bls.reset_implementation()
        assert results == [True, True, False, True, True]
    run(main())


def test_overlap_disabled_stays_sync():
    async def main():
        impl = _AsyncFakeImpl()
        bls.set_implementation(impl)
        try:
            svc = make_service(num_workers=1, overlap=False)
            await svc.start()
            assert await svc.verify([PKS[0]], b"m", b"good")
            await svc.stop()
        finally:
            bls.reset_implementation()
        assert all(c[0] == "sync" for c in impl.calls)
    run(main())


def test_not_started_raises():
    async def main():
        svc = make_service()
        with pytest.raises(RuntimeError):
            svc.verify([PKS[0]], b"x", b"y" * 96)
    run(main())


# --------------------------------------------------------------------------
# Priority classes: strict-priority drain, VIP lane, shed-by-class,
# coalescing promotion (ISSUE 7)
# --------------------------------------------------------------------------

from teku_tpu.services.admission import VerifyClass  # noqa: E402


class _OrderRecordingImpl(_AsyncFakeImpl):
    """Records the message order batches are dispatched in (the facade
    routes single-triple batches through fast_aggregate_verify, so
    both seams record).  The FIRST dispatch blocks on a gate so a test
    can pile classed tasks up behind a busy worker deterministically."""

    def __init__(self, gate_first: bool = False):
        super().__init__()
        import threading
        self.batches = []
        self.gate = threading.Event()
        self._gates_left = 1 if gate_first else 0

    def _record(self, triples):
        if self._gates_left:
            self._gates_left -= 1
            self.gate.wait(10)
        self.batches.append([msg for _pks, msg, _sig in triples])
        return self._verdict(triples)

    def batch_verify(self, triples):
        return self._record(triples)

    def fast_aggregate_verify(self, pks, msg, sig):
        return self._record([(pks, msg, sig)])


def test_strict_priority_drain_order():
    """With every class queued while the worker is busy, the next
    batch drains VIP > BLOCK_IMPORT > SYNC_CRITICAL > GOSSIP >
    OPTIMISTIC — and the VIP dispatch carries no lower-class lanes."""
    async def main():
        impl = _OrderRecordingImpl(gate_first=True)
        bls.set_implementation(impl)
        try:
            svc = make_service(num_workers=1, overlap=False)
            await svc.start()
            # the gated first dispatch occupies the single worker
            # while the classed tasks pile up behind it
            futs = [svc.verify([PKS[0]], b"blocker", b"good")]
            await asyncio.sleep(0.05)       # worker inside the gate
            order = [(VerifyClass.OPTIMISTIC, b"opt"),
                     (VerifyClass.GOSSIP, b"gossip"),
                     (VerifyClass.SYNC_CRITICAL, b"sync"),
                     (VerifyClass.BLOCK_IMPORT, b"block"),
                     (VerifyClass.VIP, b"vip")]
            for cls, msg in order:          # submitted WORST first
                futs.append(svc.verify([PKS[0]], msg, b"good",
                                       cls=cls))
            impl.gate.set()
            assert all(await asyncio.gather(*futs))
            await svc.stop()
        finally:
            bls.reset_implementation()
        # first batch: the blocker alone.  The VIP task dispatches in
        # its own batch (bypass), then the rest in priority order.
        assert impl.batches[0] == [b"blocker"]
        assert impl.batches[1] == [b"vip"]
        flat = [m for b in impl.batches[2:] for m in b]
        assert flat == [b"block", b"sync", b"gossip", b"opt"]
    run(main())


def test_vip_is_single_signature_only():
    async def main():
        svc = make_service(num_workers=1)
        await svc.start()
        m1, m2 = b"v1", b"v2"
        with pytest.raises(ValueError):
            svc.verify_multi(
                [([PKS[0]], m1, bls.sign(SKS[0], m1)),
                 ([PKS[1]], m2, bls.sign(SKS[1], m2))],
                cls=VerifyClass.VIP)
        await svc.stop()
    run(main())


def test_full_queue_evicts_lower_class_for_higher_arrival():
    """Shed-by-class at the bound: a BLOCK_IMPORT arrival on a full
    queue evicts a queued OPTIMISTIC task (never the reverse), the
    victim's future fails with the capacity error, and both the
    labeled counter and the flight-recorder event name the class."""
    async def main():
        from teku_tpu.infra import flightrecorder
        from teku_tpu.infra.metrics import MetricsRegistry
        from teku_tpu.services.signatures import (
            ServiceCapacityExceededError)
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, queue_capacity=2,
                           registry=reg)
        await svc.start()
        blocker = svc.verify([PKS[0]], b"blk", b"x")   # worker takes it
        await asyncio.sleep(0.05)                       # worker busy
        opt = svc.verify([PKS[0]], b"opt-victim", b"x",
                         cls=VerifyClass.OPTIMISTIC)
        gos = svc.verify([PKS[0]], b"gos", b"x",
                         cls=VerifyClass.GOSSIP)
        # queue now full (2): a BLOCK_IMPORT arrival evicts the
        # OPTIMISTIC task
        ring_before = len(flightrecorder.RECORDER.snapshot())
        blk = svc.verify([PKS[1]], b"import", b"x",
                         cls=VerifyClass.BLOCK_IMPORT)
        with pytest.raises(ServiceCapacityExceededError):
            await opt
        # an OPTIMISTIC arrival on the still-full queue cannot evict
        # anyone (nothing queued ranks below it) -> rejected outright
        with pytest.raises(ServiceCapacityExceededError):
            svc.verify([PKS[0]], b"opt-2", b"x",
                       cls=VerifyClass.OPTIMISTIC)
        for fut in (blocker, gos, blk):
            with pytest.raises(Exception):
                # fake signatures: verdicts are False, not errors —
                # consume them; only the verdicts matter elsewhere
                if not await fut:
                    raise RuntimeError("expected-false")
        await svc.stop()
        rejected = reg.metrics()[
            "signature_verifications_rejected_total"]
        assert rejected.labels(**{"class": "optimistic"}).value == 2
        assert rejected.labels(**{"class": "block_import"}).value == 0
        sheds = [e for e in flightrecorder.RECORDER.snapshot()
                 [ring_before:] if e["kind"] == "queue_shed"]
        assert {e["class"] for e in sheds} == {"optimistic"}
        assert {e["reason"] for e in sheds} == {"preempted",
                                                "overflow"}
    run(main())


def test_coalesced_higher_class_waiter_promotes_task():
    """Satellite: a VIP duplicate of a queued GOSSIP verify promotes
    the shared lane — it drains ahead of higher-priority-by-default
    traffic queued after it."""
    async def main():
        impl = _OrderRecordingImpl(gate_first=True)
        bls.set_implementation(impl)
        try:
            svc = make_service(num_workers=1, overlap=False)
            await svc.start()
            blocker = svc.verify([PKS[0]], b"blocker", b"good")
            await asyncio.sleep(0.05)       # worker inside the gate
            shared = svc.verify([PKS[0]], b"shared", b"good",
                                cls=VerifyClass.GOSSIP)
            ahead = svc.verify([PKS[0]], b"sync", b"good",
                               cls=VerifyClass.SYNC_CRITICAL)
            # the duplicate arrives with VIP urgency: the SHARED lane
            # must inherit it (one lane, highest waiter's class)
            dup = svc.verify([PKS[0]], b"shared", b"good",
                             cls=VerifyClass.VIP)
            impl.gate.set()
            assert all(await asyncio.gather(blocker, shared, ahead,
                                            dup))
            await svc.stop()
        finally:
            bls.reset_implementation()
        # the promoted task dispatched as the VIP express batch,
        # BEFORE the sync-critical task that outranked its old class
        assert impl.batches[1] == [b"shared"]
        assert impl.batches[2] == [b"sync"]
    run(main())


def test_cancelled_vip_primary_does_not_strand_gossip_waiters():
    """Satellite: the VIP submitter bails while coalesced GOSSIP
    waiters still want the verdict — the first live waiter is
    promoted to primary, every waiter resolves, and the task's
    effective class falls back to the survivors' (GOSSIP), releasing
    the express lane."""
    async def main():
        impl = _OrderRecordingImpl(gate_first=True)
        bls.set_implementation(impl)
        try:
            svc = make_service(num_workers=1, overlap=False)
            await svc.start()
            blocker = svc.verify([PKS[0]], b"blocker", b"good")
            await asyncio.sleep(0.05)       # worker inside the gate
            vip = svc.verify([PKS[0]], b"shared", b"good",
                             cls=VerifyClass.VIP)
            w1 = svc.verify([PKS[0]], b"shared", b"good",
                            cls=VerifyClass.GOSSIP)
            w2 = svc.verify([PKS[0]], b"shared", b"good",
                            cls=VerifyClass.GOSSIP)
            vip.cancel()
            impl.gate.set()
            assert await asyncio.gather(blocker, w1, w2) \
                == [True, True, True]
            assert vip.cancelled()
            assert not svc._pending
            await svc.stop()
        finally:
            bls.reset_implementation()
        # the demoted task no longer rides the VIP express batch: it
        # dispatched as an ordinary (non-solo or solo-by-idle) batch
        # AND nobody was stranded (gathers above resolved)
        assert any(b"shared" in b for b in impl.batches)
    run(main())


def test_per_class_depth_metrics_and_queue_snapshot():
    async def main():
        from teku_tpu.infra.metrics import MetricsRegistry
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        blocker = svc.verify([PKS[0]], b"blocker", b"x")
        await asyncio.sleep(0.05)
        futs = [svc.verify([PKS[0]], b"g%d" % i, b"x",
                           cls=VerifyClass.GOSSIP) for i in range(3)]
        futs.append(svc.verify([PKS[0]], b"o1", b"x",
                               cls=VerifyClass.OPTIMISTIC))
        snap = svc.queue_snapshot()
        assert snap["classes"]["gossip"]["depth"] == 3
        assert snap["classes"]["optimistic"]["depth"] == 1
        assert snap["classes"]["vip"]["depth"] == 0
        assert snap["total"] == 4
        depth = reg.metrics()[
            "signature_verifications_class_queue_depth"]
        assert depth.labels(**{"class": "gossip"}).value == 3
        await asyncio.gather(blocker, *futs)
        await svc.stop()
        assert svc.queue_snapshot()["total"] == 0
    run(main())


def test_brownout_sheds_queued_optimistic_and_rejects_arrivals():
    """A controller-declared brownout trims queued OPTIMISTIC tasks
    (class-labeled shed events) and rejects new OPTIMISTIC arrivals
    at admission, while GOSSIP flows at level 1."""
    async def main():
        from teku_tpu.services.admission import BatchPlan
        from teku_tpu.services.signatures import (
            ServiceCapacityExceededError)

        class FixedController:
            brownout_level = 1

            def plan(self):
                return BatchPlan(batch_size=64, flush_deadline_s=0.0,
                                 brownout_level=1)

        svc = make_service(num_workers=1,
                           controller=FixedController())
        await svc.start()
        blocker = svc.verify([PKS[0]], b"blocker", b"x")
        await asyncio.sleep(0.05)
        # admission control: OPTIMISTIC rejected outright
        with pytest.raises(ServiceCapacityExceededError):
            svc.verify([PKS[0]], b"o", b"x",
                       cls=VerifyClass.OPTIMISTIC)
        # GOSSIP still admitted at level 1
        g = svc.verify([PKS[0]], b"g", b"x", cls=VerifyClass.GOSSIP)
        assert (await asyncio.gather(blocker, g)) == [False, False]
        await svc.stop()
    run(main())
