"""Batching service semantics: drain, bisect-on-fail, overflow, grouping.

Runs against the pure-Python provider (fast enough at these sizes and
identical semantics through the SPI; the TPU provider is exercised by
tests/test_jax_provider.py)."""

import asyncio

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.services.signatures import (
    AggregatingSignatureVerificationService, ServiceCapacityExceededError)

SKS = [keygen(bytes([40 + i]) * 32) for i in range(4)]
PKS = [bls.secret_to_public_key(sk) for sk in SKS]


def run(coro):
    return asyncio.run(coro)


def make_service(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return AggregatingSignatureVerificationService(**kw)


def test_basic_verify_and_metrics():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        msg = b"single"
        sig = bls.sign(SKS[0], msg)
        ok = await svc.verify([PKS[0]], msg, sig)
        bad = await svc.verify([PKS[0]], b"other", sig)
        await svc.stop()
        assert ok and not bad
        assert reg.counter("signature_verifications_task_count_total").value >= 2
        assert reg.counter("signature_verifications_batch_count_total").value >= 2
        assert "signature_verifications_batch_size_bucket" in reg.expose()
    run(main())


def test_batching_drains_queue():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        futs = []
        msgs = [b"drain-%d" % i for i in range(6)]
        for i, m in enumerate(msgs):
            futs.append(svc.verify([PKS[i % 4]], m, bls.sign(SKS[i % 4], m)))
        results = await asyncio.gather(*futs)
        await svc.stop()
        assert all(results)
        # fewer batches than tasks proves the drain actually batched
        assert (reg.counter("signature_verifications_batch_count_total").value
                < len(msgs))
    run(main())


def test_bad_signature_isolated_by_bisect():
    async def main():
        svc = make_service(num_workers=1, split_threshold=2)
        await svc.start()
        futs = []
        for i in range(5):
            m = b"bisect-%d" % i
            sig = bls.sign(SKS[i % 4], m)
            if i == 2:
                m = b"tampered"
            futs.append(svc.verify([PKS[i % 4]], m, sig))
        results = await asyncio.gather(*futs)
        await svc.stop()
        assert results == [True, True, False, True, True]
    run(main())


def test_multi_triple_task_atomic():
    async def main():
        svc = make_service(num_workers=1)
        await svc.start()
        m1, m2 = b"proof", b"aggregate"
        good = [([PKS[0]], m1, bls.sign(SKS[0], m1)),
                ([PKS[1]], m2, bls.sign(SKS[1], m2))]
        bad = [([PKS[0]], m1, bls.sign(SKS[0], m1)),
               ([PKS[1]], b"wrong", bls.sign(SKS[1], m2))]
        ok = await svc.verify_multi(good)
        not_ok = await svc.verify_multi(bad)
        await svc.stop()
        assert ok and not not_ok  # one bad sig fails the whole task
    run(main())


def test_queue_overflow():
    async def main():
        svc = make_service(num_workers=1, queue_capacity=2)
        await svc.start()
        # DISTINCT messages: identical pending triples would coalesce
        # onto one queued task and never overflow the queue
        msgs = [b"overflow-%d" % i for i in range(52)]
        sigs = [bls.sign(SKS[0], m) for m in msgs]
        futs = [svc.verify([PKS[0]], msgs[i], sigs[i]) for i in range(2)]
        with pytest.raises(ServiceCapacityExceededError):
            for i in range(2, 52):
                futs.append(svc.verify([PKS[0]], msgs[i], sigs[i]))
        await asyncio.gather(*futs)
        await svc.stop()
    run(main())


def test_identical_inflight_triples_coalesce():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        msg = b"coalesce"
        sig = bls.sign(SKS[0], msg)
        bad_sig = bls.sign(SKS[0], b"wrong message")
        # gossip re-delivery: the same triple submitted 5x while queued
        futs = [svc.verify([PKS[0]], msg, sig) for _ in range(5)]
        bad = [svc.verify([PKS[0]], msg, bad_sig) for _ in range(3)]
        results = await asyncio.gather(*futs)
        bad_results = await asyncio.gather(*bad)
        assert results == [True] * 5       # verdict fans out to waiters
        assert bad_results == [False] * 3
        coalesced = reg.counter(
            "signature_verifications_coalesced_total").value
        assert coalesced == 4 + 2
        # pending map drains once verdicts land
        assert not svc._pending
        # a RE-submission after completion is a fresh task (the dedup
        # is in-flight only — a later identical request must re-verify)
        assert await svc.verify([PKS[0]], msg, sig) is True
        await svc.stop()
    run(main())


def test_multi_triple_tasks_coalesce_by_full_key():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        m1, m2 = b"agg-1", b"agg-2"
        task = [([PKS[0]], m1, bls.sign(SKS[0], m1)),
                ([PKS[1]], m2, bls.sign(SKS[1], m2))]
        f1 = svc.verify_multi(task)
        f2 = svc.verify_multi(list(task))      # identical -> coalesces
        f3 = svc.verify_multi(task[:1])        # different key: own task
        assert await asyncio.gather(f1, f2, f3) == [True, True, True]
        assert reg.counter(
            "signature_verifications_coalesced_total").value == 1
        await svc.stop()
    run(main())


def test_cancelled_primary_promotes_live_waiter():
    async def main():
        svc = make_service(num_workers=1)
        await svc.start()
        msg = b"promote"
        sig = bls.sign(SKS[0], msg)
        f1 = svc.verify([PKS[0]], msg, sig)
        f2 = svc.verify([PKS[0]], msg, sig)  # coalesce onto f1's task
        f3 = svc.verify([PKS[0]], msg, sig)
        # the original submitter bails while the task is still queued:
        # the waiters' callers still want the verdict — the first live
        # waiter is promoted to primary, nobody else gets cancelled
        f1.cancel()
        assert await asyncio.gather(f2, f3) == [True, True]
        assert f1.cancelled()
        assert not svc._pending
        await svc.stop()
    run(main())


class _AsyncHandle:
    def __init__(self, verdict):
        self._verdict = verdict

    def result(self):
        return self._verdict


class _AsyncFakeImpl:
    """Minimal BLS impl exposing the async begin seam: records the
    call interleaving so the overlap test can prove begin(N+1) runs
    BEFORE result(N) is read."""

    def __init__(self):
        self.calls = []

    def _verdict(self, triples):
        return all(sig == b"good" for _pks, _msg, sig in triples)

    def begin_batch_verify(self, triples):
        self.calls.append(("begin", len(triples)))
        verdict = self._verdict(triples)

        class H(_AsyncHandle):
            def result(h):
                self.calls.append(("result", len(triples)))
                return verdict

        return H(verdict)

    def batch_verify(self, triples):
        self.calls.append(("sync", len(triples)))
        return self._verdict(triples)

    def fast_aggregate_verify(self, pks, msg, sig):
        self.calls.append(("sync", 1))
        return sig == b"good"


def test_async_overlap_begins_next_batch_before_retiring_previous():
    async def main():
        impl = _AsyncFakeImpl()
        bls.set_implementation(impl)
        try:
            svc = make_service(num_workers=1, overlap=True)
            await svc.start()
            futs = [svc.verify([PKS[i % 4]], b"msg-%d" % i, b"good")
                    for i in range(6)]
            assert all(await asyncio.gather(*futs))
            await svc.stop()
        finally:
            bls.reset_implementation()
        begins = [c for c in impl.calls if c[0] == "begin"]
        assert begins, "async seam never engaged"
        # if more than one batch formed, the worker must have begun a
        # later batch before reading an earlier batch's result
        if len(begins) > 1:
            first_result = impl.calls.index(("result", begins[0][1]))
            second_begin = impl.calls.index(begins[1])
            assert second_begin < first_result
    run(main())


def test_async_overlap_failure_still_bisects():
    async def main():
        impl = _AsyncFakeImpl()
        bls.set_implementation(impl)
        try:
            svc = make_service(num_workers=1, overlap=True,
                               split_threshold=2)
            await svc.start()
            futs = []
            for i in range(5):
                sig = b"bad" if i == 2 else b"good"
                futs.append(svc.verify([PKS[i % 4]], b"bis-%d" % i, sig))
            results = await asyncio.gather(*futs)
            await svc.stop()
        finally:
            bls.reset_implementation()
        assert results == [True, True, False, True, True]
    run(main())


def test_overlap_disabled_stays_sync():
    async def main():
        impl = _AsyncFakeImpl()
        bls.set_implementation(impl)
        try:
            svc = make_service(num_workers=1, overlap=False)
            await svc.start()
            assert await svc.verify([PKS[0]], b"m", b"good")
            await svc.stop()
        finally:
            bls.reset_implementation()
        assert all(c[0] == "sync" for c in impl.calls)
    run(main())


def test_not_started_raises():
    async def main():
        svc = make_service()
        with pytest.raises(RuntimeError):
            svc.verify([PKS[0]], b"x", b"y" * 96)
    run(main())
