"""Batching service semantics: drain, bisect-on-fail, overflow, grouping.

Runs against the pure-Python provider (fast enough at these sizes and
identical semantics through the SPI; the TPU provider is exercised by
tests/test_jax_provider.py)."""

import asyncio

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.infra.metrics import MetricsRegistry
from teku_tpu.services.signatures import (
    AggregatingSignatureVerificationService, ServiceCapacityExceededError)

SKS = [keygen(bytes([40 + i]) * 32) for i in range(4)]
PKS = [bls.secret_to_public_key(sk) for sk in SKS]


def run(coro):
    return asyncio.run(coro)


def make_service(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return AggregatingSignatureVerificationService(**kw)


def test_basic_verify_and_metrics():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        msg = b"single"
        sig = bls.sign(SKS[0], msg)
        ok = await svc.verify([PKS[0]], msg, sig)
        bad = await svc.verify([PKS[0]], b"other", sig)
        await svc.stop()
        assert ok and not bad
        assert reg.counter("signature_verifications_task_count_total").value >= 2
        assert reg.counter("signature_verifications_batch_count_total").value >= 2
        assert "signature_verifications_batch_size_bucket" in reg.expose()
    run(main())


def test_batching_drains_queue():
    async def main():
        reg = MetricsRegistry()
        svc = make_service(num_workers=1, registry=reg)
        await svc.start()
        futs = []
        msgs = [b"drain-%d" % i for i in range(6)]
        for i, m in enumerate(msgs):
            futs.append(svc.verify([PKS[i % 4]], m, bls.sign(SKS[i % 4], m)))
        results = await asyncio.gather(*futs)
        await svc.stop()
        assert all(results)
        # fewer batches than tasks proves the drain actually batched
        assert (reg.counter("signature_verifications_batch_count_total").value
                < len(msgs))
    run(main())


def test_bad_signature_isolated_by_bisect():
    async def main():
        svc = make_service(num_workers=1, split_threshold=2)
        await svc.start()
        futs = []
        for i in range(5):
            m = b"bisect-%d" % i
            sig = bls.sign(SKS[i % 4], m)
            if i == 2:
                m = b"tampered"
            futs.append(svc.verify([PKS[i % 4]], m, sig))
        results = await asyncio.gather(*futs)
        await svc.stop()
        assert results == [True, True, False, True, True]
    run(main())


def test_multi_triple_task_atomic():
    async def main():
        svc = make_service(num_workers=1)
        await svc.start()
        m1, m2 = b"proof", b"aggregate"
        good = [([PKS[0]], m1, bls.sign(SKS[0], m1)),
                ([PKS[1]], m2, bls.sign(SKS[1], m2))]
        bad = [([PKS[0]], m1, bls.sign(SKS[0], m1)),
               ([PKS[1]], b"wrong", bls.sign(SKS[1], m2))]
        ok = await svc.verify_multi(good)
        not_ok = await svc.verify_multi(bad)
        await svc.stop()
        assert ok and not not_ok  # one bad sig fails the whole task
    run(main())


def test_queue_overflow():
    async def main():
        svc = make_service(num_workers=1, queue_capacity=2)
        await svc.start()
        msg = b"overflow"
        sig = bls.sign(SKS[0], msg)
        # stall the worker by flooding faster than it can drain
        futs = [svc.verify([PKS[0]], msg, sig) for _ in range(2)]
        with pytest.raises(ServiceCapacityExceededError):
            for _ in range(50):
                futs.append(svc.verify([PKS[0]], msg, sig))
        await asyncio.gather(*futs)
        await svc.stop()
    run(main())


def test_not_started_raises():
    async def main():
        svc = make_service()
        with pytest.raises(RuntimeError):
            svc.verify([PKS[0]], b"x", b"y" * 96)
    run(main())
