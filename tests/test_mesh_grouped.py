"""Group-aligned mesh sharding: the production multi-chip verify path.

The dedup-aware pipeline sharded across the 8-virtual-device CPU mesh
(production: ICI): whole message groups per shard, per-device partial
combines, bit-identical verdicts vs the single-device grouped pipeline
and the pure oracle — plus the host-side shard planner, mesh-spec
resolution/demotion, dedup-counter parity, the mesh fault site tripping
the breaker to oracle fallback, and the mesh observability surfaces.

Compile budget: every device test in the fast tier shares ONE sharded
kernel shape (32 lanes x kmax 1, 8 rows x group 4 over 8 shards) and
ONE single-device staged shape set; the pippenger-sharded and mxu-force
re-traces are extra full-pipeline compiles and live in the slow tier.
"""

import logging

import numpy as np
import pytest

import jax

from teku_tpu import parallel
from teku_tpu.crypto.bls import keygen
from teku_tpu.crypto.bls.pure_impl import PureBls12381
from teku_tpu.infra import capacity, faults
from teku_tpu.infra.metrics import GLOBAL_REGISTRY, MetricsRegistry
from teku_tpu.infra.supervisor import (CircuitBreaker)
from teku_tpu.ops import msm
from teku_tpu.ops import provider as PV
from teku_tpu.ops.provider import JaxBls12381

_G2_INF = bytes([0xC0] + [0] * 95)

pytest_plugins: list = []


# --------------------------------------------------------------------------
# host-side: shard planner + mesh-spec resolution (no device work)
# --------------------------------------------------------------------------

def test_plan_group_shards_keeps_rows_whole():
    # rows of lane-index lists with mixed sizes over 4 shards
    rows = [(0, [0, 1, 2, 3, 4]), (1, [5, 6]), (2, [7]), (3, [8, 9])]
    plan = parallel.plan_group_shards(rows, 10, 4, min_lanes=1)
    assert plan.n_shards == 4
    # pow-2 per-shard shapes, identical across shards
    assert plan.lanes_per_shard & (plan.lanes_per_shard - 1) == 0
    assert plan.rows_per_shard & (plan.rows_per_shard - 1) == 0
    assert plan.padded == 4 * plan.lanes_per_shard
    # lane_pos is injective and every row's lanes land in ONE shard
    assert len(set(plan.lane_pos.tolist())) == 10
    placed = [r for r in plan.row_layout if r >= 0]
    assert sorted(placed) == [0, 1, 2, 3]       # every row placed once
    for pos, r in enumerate(plan.row_layout):
        if r < 0:
            continue
        shard = pos // plan.rows_per_shard
        lo = shard * plan.lanes_per_shard
        hi = lo + plan.lanes_per_shard
        for lane in rows[r][1]:
            assert lo <= plan.lane_pos[lane] < hi, (pos, r, lane)


def test_plan_group_shards_balances_lanes():
    # 8 equal rows over 4 shards: 2 rows / 8 lanes per shard, no slack
    rows = [(u, list(range(u * 4, u * 4 + 4))) for u in range(8)]
    plan = parallel.plan_group_shards(rows, 32, 4)
    assert plan.lanes_per_shard == 8
    assert plan.rows_per_shard == 2
    assert plan.padded == 32                    # zero padding waste


def test_plan_respects_min_floors():
    plan = parallel.plan_group_shards([(0, [0])], 1, 2,
                                      min_lanes=4, min_rows=2)
    assert plan.lanes_per_shard == 4
    assert plan.rows_per_shard == 2


def test_resolve_mesh_devices_rules(caplog):
    r = parallel.resolve_mesh_devices
    assert r(None) == 0
    assert r("off") == 0
    assert r("0") == 0
    assert r("1", available=8) == 0             # mesh of 1 = no mesh
    assert r("auto", available=8) == 8
    assert r("auto", available=5) == 4          # largest pow-2 <=
    assert r("auto", available=1) == 0
    assert r("8", available=8) == 8
    # non-pow-2 / over-sized N DEMOTES with a warning, never raises
    parallel._warned_demotion[0] = False
    with caplog.at_level(logging.WARNING):
        assert r("6", available=8) == 4
    assert any("demoting" in rec.message for rec in caplog.records)
    assert r("100", available=8) == 8
    # garbage spec disables the mesh instead of failing boot
    assert r("many", available=8) == 0


def test_sharded_verifiers_still_raise_on_non_pow2():
    # construction keeps the hard contract; the CLI/loader resolve
    # first (resolve_mesh_devices only ever yields pow-2 or 0)
    class FakeMesh:
        axis_names = ("dp",)
        shape = {"dp": 3}
        devices = np.empty((3,), dtype=object)
    with pytest.raises(ValueError):
        parallel.GroupShardedVerifier(FakeMesh())


def test_cli_validate_mesh():
    from teku_tpu import cli
    assert cli._validate_mesh("off") == "off"
    assert cli._validate_mesh("auto") == "auto"
    assert cli._validate_mesh("4") == "4"
    # YAML parses bare off/on/no/yes as booleans before this layer:
    # the boolean spellings must normalize, never fail node boot
    assert cli._validate_mesh("false") == "off"
    assert cli._validate_mesh("no") == "off"
    assert cli._validate_mesh("0") == "off"
    assert cli._validate_mesh("true") == "auto"
    assert cli._validate_mesh("on") == "auto"
    with pytest.raises(SystemExit):
        cli._validate_mesh("zero")
    with pytest.raises(SystemExit):
        cli._validate_mesh("-2")


def test_configure_kernel_sets_mesh_env(monkeypatch):
    import os

    from teku_tpu import cli

    # _configure_kernel writes these straight to os.environ; restore
    # the process env by hand after the test
    saved = {var: os.environ.get(var)
             for var in ("TEKU_TPU_MESH", "TEKU_TPU_MONT_MUL",
                         "TEKU_TPU_MSM")}

    class Args:
        mont_path = None
        msm_path = None
        mesh = "auto"
    try:
        mont, msm_choice, mesh = cli._configure_kernel(Args(), {})
        assert mesh == "auto"
        assert os.environ["TEKU_TPU_MESH"] == "auto"
        # numeric N forces virtual host devices ONLY if the flag is
        # absent
        monkeypatch.setenv("XLA_FLAGS", "--xla_foo")
        Args.mesh = "4"
        assert cli._configure_kernel(Args(), {})[2] == "4"
        assert "xla_force_host_platform_device_count=4" \
            in os.environ["XLA_FLAGS"]
        # already-forced flag (the test env itself) is left untouched
        monkeypatch.setenv(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        cli._configure_kernel(Args(), {})
        assert os.environ["XLA_FLAGS"] == \
            "--xla_force_host_platform_device_count=8"
    finally:
        for var, value in saved.items():
            if value is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = value


# --------------------------------------------------------------------------
# device fixtures: ONE mesh, ONE provider pair, ONE sharded shape
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (see conftest XLA_FLAGS)")
    m = parallel.make_mesh(8)
    with m:
        yield m


@pytest.fixture(scope="module")
def keys():
    pure = PureBls12381()
    sks = [keygen(bytes([31 + i]) * 32) for i in range(8)]
    pks = [pure.secret_key_to_public_key(sk) for sk in sks]
    return pure, sks, pks


@pytest.fixture(scope="module")
def mesh_impl(mesh8):
    return JaxBls12381(mesh=mesh8, min_bucket=8)


@pytest.fixture(scope="module")
def single_impl():
    return JaxBls12381(min_bucket=8)


_seq = [0]


# lane -> unique-message map: two dup-4 committees, two dup-2 pairs,
# four singles = 16 lanes over 8 unique messages, so ONE kernel shape
# (group bucket 4, 8 rows, 4 lanes/shard over 8 shards) covers the dup
# AND unique grid axes — and its 13-lane prefix keeps the same shape
# for the padding case
_U_MAP = [0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 3, 3, 4, 5, 6, 7]


def _grid_batch(pure, sks, pks, tag=None, n_lanes=16):
    """Committee-shaped mixed-duplication batch (see _U_MAP).  Fresh
    messages per call (tag) keep the H(m) caches cold for counter
    tests."""
    if tag is None:
        _seq[0] += 1
        tag = b"grid-%d" % _seq[0]
    msgs = [tag + b"-%d" % u for u in range(8)]
    triples = []
    sig_cache: dict = {}
    for lane in range(n_lanes):
        u = _U_MAP[lane]
        k = lane % 8
        if (k, u) not in sig_cache:
            sig_cache[(k, u)] = pure.sign(sks[k], msgs[u])
        triples.append(([pks[k]], msgs[u], sig_cache[(k, u)]))
    return triples


def test_mesh_self_description(mesh8, mesh_impl):
    # make_mesh logged + exported the device set (satellite: no more
    # silent first-N): the gauge and describe() agree with the mesh
    desc = parallel.describe_mesh()
    assert desc["n_devices"] == 8
    assert len(desc["devices"]) == 8
    gauge = GLOBAL_REGISTRY.gauge("bls_mesh_devices")
    assert gauge.value == 8.0
    assert mesh_impl.mesh_info["n_devices"] == 8
    assert mesh_impl.mesh_info["devices"] == desc["devices"]


def test_grouped_sharded_parity_grid(mesh_impl, single_impl, keys):
    """Verdict parity: mesh vs single-device grouped vs pure oracle on
    the dup-4 / unique / tamper / infinity-sig / padding grid.  Every
    case reuses ONE compiled sharded shape (see module docstring)."""
    pure, sks, pks = keys
    base = _grid_batch(pure, sks, pks)

    tampered = list(base)
    tampered[10] = (base[10][0], b"tampered-msg", base[10][2])

    tampered_dup = list(base)                 # corrupt a dup-4 lane
    tampered_dup[2] = (base[2][0], base[2][1],
                       pure.sign(sks[0], b"wrong"))

    inf_sig = list(base)
    inf_sig[12] = (base[12][0], base[12][1], _G2_INF)

    padded = _grid_batch(pure, sks, pks)[:13]   # non-pow-2 lane count

    cases = {"valid": base, "tamper_msg": tampered,
             "tamper_sig_in_committee": tampered_dup,
             "infinity_sig": inf_sig, "padding_13": padded}
    for name, triples in cases.items():
        want = pure.batch_verify(triples)
        got_single = single_impl.batch_verify(triples)
        got_mesh = mesh_impl.batch_verify(triples)
        assert got_single == want, f"{name}: single vs oracle"
        assert got_mesh == want, f"{name}: mesh vs oracle"
    assert mesh_impl.dispatch_count >= len(cases)
    # the mesh dispatch counter carries the closed devices label
    fam = GLOBAL_REGISTRY.labeled_counter("bls_mesh_dispatch_total")
    assert fam.labels(devices="8").value >= len(cases)


def test_sharded_dedup_counters_match_single_device(
        mesh_impl, single_impl, keys):
    """Satellite: sharded dispatch must not double-count dedup metrics.
    The same batch through the single-device and mesh providers
    reports IDENTICAL bls_h2c_lanes/unique/dispatch deltas (the mesh
    layout pads lanes/rows, but the dedup accounting is canonical)."""
    pure, sks, pks = keys

    def deltas(impl, triples):
        before = (PV._M_H2C_LANES.value, PV._M_H2C_UNIQUE.value,
                  PV._M_H2C_DISPATCH.value, impl.h2c_dispatch_count)
        assert impl.batch_verify(triples)
        return (PV._M_H2C_LANES.value - before[0],
                PV._M_H2C_UNIQUE.value - before[1],
                PV._M_H2C_DISPATCH.value - before[2],
                impl.h2c_dispatch_count - before[3])

    # FRESH messages for each provider: both pay exactly one cold h2c
    d_single = deltas(single_impl, _grid_batch(pure, sks, pks))
    d_mesh = deltas(mesh_impl, _grid_batch(pure, sks, pks))
    assert d_single == d_mesh == (16, 8, 1, 1)
    # warm re-dispatch through the mesh: dedup still counted once,
    # ZERO h2c dispatches (the arena serves the whole batch)
    warm = _grid_batch(pure, sks, pks)
    deltas(mesh_impl, warm)
    assert deltas(mesh_impl, warm)[2:] == (0, 0)


def test_mesh_latency_model_feeds_admission(mesh_impl, keys):
    """The capacity model's per-shape series carries the mesh-shaped
    dispatches (distinct `@mN` family) and latency_for_lanes still
    prefix-matches them — the admission controller's batch planner
    sees N-chip device latencies."""
    pure, sks, pks = keys
    assert mesh_impl.batch_verify(_grid_batch(pure, sks, pks))
    shapes = capacity.TELEMETRY.latency.snapshot()
    mesh_shapes = [s for s in shapes if s.endswith("@m8")]
    assert mesh_shapes, f"no mesh-labeled shapes in {list(shapes)}"
    lanes = int(mesh_shapes[0].split("x")[0])
    assert capacity.TELEMETRY.latency.latency_for_lanes(lanes)


def test_mesh_shard_hang_trips_breaker_zero_failed(mesh_impl, keys):
    """Satellite: one wedged shard (the bls.mesh_shard fault site)
    wedges the whole mesh dispatch; the breaker trips the mesh backend
    to oracle fallback and every in-flight verification still returns
    the correct verdict."""
    from teku_tpu.crypto.bls.loader import GuardedBls12381
    pure, sks, pks = keys
    br = CircuitBreaker(failure_threshold=1, deadline_s=10.0,
                        cooldown_s=60.0, name="mesh_t",
                        registry=MetricsRegistry())
    guarded = GuardedBls12381(mesh_impl, br)
    batch = _grid_batch(pure, sks, pks)
    # warm the exact dispatch shape OUTSIDE the breaker so the guarded
    # calls below measure the hang, not compile/box noise
    assert mesh_impl.batch_verify(batch)
    assert br.state == CircuitBreaker.CLOSED
    faults.inject("bls.mesh_shard", faults.Hang(12.0, times=1))
    try:
        # the wedged-shard dispatch overruns the deadline: the oracle
        # serves THIS call (correct verdict, zero failed in-flight)
        # and the breaker trips the whole mesh backend
        assert guarded.batch_verify(batch) is True
        assert br.state == CircuitBreaker.OPEN
        assert guarded.serving == "oracle"
        # while open: instant oracle service, still correct
        assert guarded.batch_verify(batch) is True
        bad = list(batch)
        bad[3] = (batch[3][0], b"mesh-tampered", batch[3][2])
        assert guarded.batch_verify(bad) is False
    finally:
        faults.clear("bls.mesh_shard")


def test_supervisor_snapshot_and_gauge_carry_mesh():
    """make_supervisor exports the name-prefixed mesh gauge and the
    readiness snapshot self-describes an installed mesh backend."""
    import asyncio

    from teku_tpu.crypto.bls import loader

    async def main():
        reg = MetricsRegistry()
        sup = loader.make_supervisor(registry=reg, warm=False,
                                     name="mesh_snap",
                                     breaker_name="mesh_snap_dev")
        gauge = reg.gauge("mesh_snap_mesh_devices")
        assert gauge.value == 0.0
        sup.mesh = {"devices": ["d0", "d1"], "n_devices": 2,
                    "axis": "dp"}
        assert gauge.value == 2.0
        assert sup.snapshot()["mesh"]["n_devices"] == 2
    asyncio.run(main())


# --------------------------------------------------------------------------
# slow tier: extra full-pipeline re-traces (pippenger mesh, mxu-force)
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_pippenger_sharded_parity(mesh_impl, single_impl, keys):
    """The mesh kernel is NOT ladder-only: forced pippenger compiles
    the GLV+Pippenger sharded program and the verdict grid matches the
    ladder mesh, the single-device pippenger path and the oracle."""
    pure, sks, pks = keys
    base = _grid_batch(pure, sks, pks)
    bad = list(base)
    bad[5] = (base[5][0], b"pip-tampered", base[5][2])
    with msm.force("pippenger"):
        for triples, want in ((base, True), (bad, False)):
            assert pure.batch_verify(triples) == want
            assert single_impl.batch_verify(triples) == want
            assert mesh_impl.batch_verify(triples) == want
    assert mesh_impl.msm_dispatches["pippenger"] >= 2


@pytest.mark.slow
def test_grouped_sharded_parity_grid_mxu_force(mesh8, keys):
    """The parity grid again under TEKU_TPU_MONT_MUL=mxu-force: the
    int8 digit-split engine re-traces the whole sharded pipeline and
    the verdicts stay bit-identical to the oracle."""
    from teku_tpu.ops import mxu
    pure, sks, pks = keys
    with mxu.force("mxu-force"):
        impl = JaxBls12381(mesh=mesh8, min_bucket=8)
        base = _grid_batch(pure, sks, pks)
        bad = list(base)
        bad[9] = (base[9][0], b"mxu-tampered", base[9][2])
        assert impl.batch_verify(base) is True
        assert impl.batch_verify(bad) is False
