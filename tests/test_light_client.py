"""Light-client sync protocol: bootstrap verification, finality
updates over real devnet sync aggregates, proof soundness."""

import asyncio
import dataclasses

import pytest

from teku_tpu.node import Devnet
from teku_tpu.spec import config as C, Spec
from teku_tpu.spec.altair.light_client import (
    LightClientError, block_to_header, create_bootstrap, create_update,
    finality_branch, initialize_light_client_store,
    process_light_client_update, sync_committee_branch,
    verify_merkle_proof)
from teku_tpu.spec.genesis import interop_genesis

ALTAIR_CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0)


def test_state_proofs_verify_and_bind():
    state, _ = interop_genesis(ALTAIR_CFG, 16)
    root = state.htr()
    branch, gindex = sync_committee_branch(state, "current")
    leaf = state.current_sync_committee.htr()
    assert verify_merkle_proof(leaf, branch, gindex, root)
    # a tampered leaf or branch fails
    assert not verify_merkle_proof(b"\x01" * 32, branch, gindex, root)
    bad = list(branch)
    bad[0] = b"\x00" * 32
    assert not verify_merkle_proof(leaf, bad, gindex, root)
    fb, fg = finality_branch(state)
    assert verify_merkle_proof(state.finalized_checkpoint.root, fb, fg,
                               root)


def test_electra_state_proofs_use_deeper_tree():
    cfg = dataclasses.replace(ALTAIR_CFG, BELLATRIX_FORK_EPOCH=0,
                              CAPELLA_FORK_EPOCH=0, DENEB_FORK_EPOCH=0,
                              ELECTRA_FORK_EPOCH=0)
    state, _ = interop_genesis(cfg, 16)
    branch, gindex = sync_committee_branch(state, "current")
    # electra's 37-field state needs a depth-6 branch (gindex 86,
    # the reference's CURRENT_SYNC_COMMITTEE_GINDEX_ELECTRA)
    assert len(branch) == 6
    assert gindex == 86
    assert verify_merkle_proof(state.current_sync_committee.htr(),
                               branch, gindex, state.htr())


@pytest.mark.slow
def test_light_client_follows_devnet_finality():
    async def run():
        net = Devnet(n_nodes=2, n_validators=32, spec=Spec(ALTAIR_CFG))
        await net.start()
        try:
            cfg = ALTAIR_CFG
            # cross the epoch-4 boundary (in-state finality lands
            # there) plus two slots so a CHILD aggregate signs a
            # finality-bearing attested header
            await net.run_until_slot(4 * cfg.SLOTS_PER_EPOCH + 2)
            node = net.nodes[0]
            store = node.store
            anchor_root = min(store.blocks,
                              key=lambda r: store.blocks[r].slot)
            anchor_block = store.blocks[anchor_root]
            anchor_state = store.block_states[anchor_root]

            bootstrap = create_bootstrap(cfg, anchor_state, anchor_block)
            lc = initialize_light_client_store(
                cfg, anchor_block.htr(), bootstrap)
            assert lc.finalized_header.slot == anchor_block.slot
            # wrong trusted root rejected
            with pytest.raises(LightClientError):
                initialize_light_client_store(cfg, b"\x13" * 32,
                                              bootstrap)

            # find a block whose sync aggregate signs its parent
            root = node.chain.head_root
            update = None
            while root in store.blocks:
                blk = store.blocks[root]
                parent = blk.parent_root
                agg = blk.body.sync_aggregate
                if (parent in store.blocks
                        and store.blocks[parent].slot == blk.slot - 1
                        and sum(agg.sync_committee_bits)
                        * 3 >= len(agg.sync_committee_bits) * 2):
                    attested_block = store.blocks[parent]
                    attested_state = store.block_states[parent]
                    fin_root = attested_state.finalized_checkpoint.root
                    if fin_root in store.blocks:
                        update = create_update(
                            cfg, attested_state, attested_block,
                            block_to_header(store.blocks[fin_root]),
                            agg, blk.slot)
                        break
                root = parent
            assert update is not None, "no usable sync aggregate found"

            lc = process_light_client_update(
                cfg, lc, update,
                anchor_state.genesis_validators_root)
            assert lc.optimistic_header.htr() \
                == update.attested_header.htr()
            assert lc.finalized_header.htr() \
                == update.finalized_header.htr()
            assert lc.finalized_header.slot > anchor_block.slot
            assert lc.next_sync_committee is not None

            # a flipped signature bit must be rejected
            bad_agg = update.sync_aggregate.copy_with(
                sync_committee_signature=b"\xaa" * 96)
            bad = dataclasses.replace(update, sync_aggregate=bad_agg)
            with pytest.raises(LightClientError):
                process_light_client_update(
                    cfg, lc, bad, anchor_state.genesis_validators_root)

            # the REST surface serves both light-client shapes
            import json
            import urllib.request
            from teku_tpu.api import BeaconRestApi
            api = BeaconRestApi(node)
            await api.start()
            try:
                loop = asyncio.get_running_loop()

                def fetch(path):
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{api.port}{path}",
                            timeout=5) as r:
                        return json.loads(r.read())

                boot = await loop.run_in_executor(
                    None, fetch,
                    "/eth/v1/beacon/light_client/bootstrap/0x"
                    + anchor_block.htr().hex())
                assert len(boot["data"]["current_sync_committee"]
                           ["pubkeys"]) == cfg.SYNC_COMMITTEE_SIZE
                fin = await loop.run_in_executor(
                    None, fetch,
                    "/eth/v1/beacon/light_client/finality_update")
                assert int(fin["data"]["signature_slot"]) > 0
                assert fin["data"]["finality_branch"]
            finally:
                await api.stop()
        finally:
            await net.stop()

    asyncio.run(run())


def test_forged_gindex_proof_rejected():
    """A proof that verifies at a SERVER-chosen tree position must not
    fool the verifier: the gindex is pinned from the fork schedule."""
    from teku_tpu.spec.altair.light_client import (
        _state_field_roots, create_bootstrap,
        initialize_light_client_store)
    from teku_tpu.ssz import merkle_branch
    state, _ = interop_genesis(ALTAIR_CFG, 16)
    block_fields = dict(slot=0, proposer_index=0,
                        parent_root=bytes(32), state_root=state.htr())
    from teku_tpu.spec.datastructures import get_schemas
    S = get_schemas(ALTAIR_CFG)
    # an honest bootstrap initializes fine
    from teku_tpu.spec.altair.datastructures import get_altair_schemas
    A = get_altair_schemas(ALTAIR_CFG)
    block = A.BeaconBlock(slot=0, proposer_index=0,
                          parent_root=bytes(32), state_root=state.htr(),
                          body=A.BeaconBlockBody())
    boot = create_bootstrap(ALTAIR_CFG, state, block)
    initialize_light_client_store(ALTAIR_CFG, block.htr(), boot)
    # forge: prove NEXT committee at its true (different) position and
    # claim it as current — the pinned gindex makes this fail even
    # though the branch itself is a valid merkle path
    roots = _state_field_roots(state)
    fields = list(type(state)._ssz_fields)
    next_idx = fields.index("next_sync_committee")
    forged = dataclasses.replace(
        boot,
        current_sync_committee=state.next_sync_committee,
        current_sync_committee_branch=merkle_branch(roots, next_idx),
        current_sync_committee_gindex=(1 << 5) + next_idx)
    # (identical committees at genesis would mask the forgery: make
    # them differ first)
    if state.current_sync_committee == state.next_sync_committee:
        from teku_tpu.spec.altair.light_client import verify_merkle_proof
        # the branch DOES verify at the attacker's position...
        assert verify_merkle_proof(
            state.next_sync_committee.htr(),
            forged.current_sync_committee_branch,
            forged.current_sync_committee_gindex, state.htr())
        # ...but the verifier checks at the PINNED position with the
        # attacker's branch, which cannot also verify there unless the
        # two fields are byte-identical AND the branches collide —
        # exercise with a tampered leaf to prove the pin engages
        forged = dataclasses.replace(
            forged, current_sync_committee=state.current_sync_committee
            .copy_with(aggregate_pubkey=b"\xaa" * 48))
    with pytest.raises(LightClientError):
        initialize_light_client_store(ALTAIR_CFG, block.htr(), forged)
