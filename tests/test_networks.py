"""Network configuration bundles (spec/networks.py).

Fork digests are asserted against the PUBLICLY KNOWN mainnet constants
(the values every consensus client advertises on its gossip topics) —
the same check the reference encodes in its bundled network configs
(ethereum/networks/src/main/resources/, Eth2NetworkConfiguration.java).
"""

import pytest

from teku_tpu.spec import create_spec
from teku_tpu.spec import helpers as H
from teku_tpu.spec.networks import BUNDLES, get_bundle


def test_mainnet_fork_digests_match_known_constants():
    b = get_bundle("mainnet")
    gvr = b.genesis_validators_root
    cfg = b.config
    # genesis (phase0) fork digest on mainnet gossip: 0xb5303f2a
    assert H.compute_fork_digest(cfg.GENESIS_FORK_VERSION,
                                 gvr).hex() == "b5303f2a"
    # capella: 0xbba4da96; deneb: 0x6a95a1a9 (public topic constants)
    assert H.compute_fork_digest(cfg.CAPELLA_FORK_VERSION,
                                 gvr).hex() == "bba4da96"
    assert H.compute_fork_digest(cfg.DENEB_FORK_VERSION,
                                 gvr).hex() == "6a95a1a9"


def test_mainnet_fork_schedule():
    cfg = get_bundle("mainnet").config
    assert cfg.ALTAIR_FORK_EPOCH == 74240
    assert cfg.BELLATRIX_FORK_EPOCH == 144896
    assert cfg.CAPELLA_FORK_EPOCH == 194048
    assert cfg.DENEB_FORK_EPOCH == 269568
    assert cfg.ELECTRA_FORK_EPOCH == 364032
    spec = create_spec("mainnet")
    # milestone routing uses the real schedule
    assert spec.milestone_at_slot(0).name == "PHASE0"
    assert spec.milestone_at_slot(194048 * 32).name == "CAPELLA"
    assert spec.milestone_at_slot(364032 * 32).name == "ELECTRA"


@pytest.mark.parametrize("name", ["sepolia", "holesky", "gnosis"])
def test_testnet_bundles_are_coherent(name):
    b = get_bundle(name)
    cfg = b.config
    # fork versions are distinct and network-scoped
    versions = [cfg.GENESIS_FORK_VERSION, cfg.ALTAIR_FORK_VERSION,
                cfg.BELLATRIX_FORK_VERSION, cfg.CAPELLA_FORK_VERSION,
                cfg.DENEB_FORK_VERSION]
    assert len(set(versions)) == len(versions)
    # schedule is monotone
    epochs = [cfg.ALTAIR_FORK_EPOCH, cfg.BELLATRIX_FORK_EPOCH,
              cfg.CAPELLA_FORK_EPOCH, cfg.DENEB_FORK_EPOCH]
    assert epochs == sorted(epochs)
    assert b.deposit_contract is not None \
        and len(b.deposit_contract) == 20
    assert b.genesis_validators_root is not None \
        and len(b.genesis_validators_root) == 32
    assert b.checkpoint_sync_urls
    # create_spec resolves the bundle
    spec = create_spec(name)
    assert spec.config.config_name == name


def test_sepolia_identity():
    cfg = get_bundle("sepolia").config
    assert cfg.DEPOSIT_CHAIN_ID == 11155111
    assert cfg.GENESIS_FORK_VERSION == bytes.fromhex("90000069")
    assert cfg.ELECTRA_FORK_EPOCH == 222464


def test_holesky_identity():
    cfg = get_bundle("holesky").config
    assert cfg.DEPOSIT_CHAIN_ID == 17000
    assert cfg.ALTAIR_FORK_EPOCH == 0 and cfg.BELLATRIX_FORK_EPOCH == 0
    assert cfg.EJECTION_BALANCE == 28 * 10 ** 9


def test_gnosis_identity():
    cfg = get_bundle("gnosis").config
    assert cfg.SECONDS_PER_SLOT == 5 and cfg.SLOTS_PER_EPOCH == 16
    assert cfg.DEPOSIT_CHAIN_ID == 100
    assert cfg.preset_name == "gnosis"


def test_unknown_network_rejected():
    with pytest.raises(ValueError):
        get_bundle("nosuchnet")
    assert "minimal" in BUNDLES and "mainnet-preset" in BUNDLES
