"""bench.py bring-up hardening: the probe must fail FAST and loudly.

Round 3 post-mortem: three in-process jax.devices() probes hung ~25
minutes each before the CPU fallback fired, eating the driver's whole
budget with zero evidence.  The probe now runs in a kill-able
subprocess with a hard deadline, and every phase transition appends to
a heartbeat file (reference keeps its benchmarks honest the same way —
JMH timeouts in eth-benchmark-tests/.../BLSBenchmark.java).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402


def test_probe_kills_hung_backend_within_deadline():
    t0 = time.time()
    platform, why = bench._probe_backend(
        1.5, code="import time\ntime.sleep(600)\n")
    elapsed = time.time() - t0
    assert platform is None
    assert "timeout" in why
    assert elapsed < 30          # seconds, not round 3's 25 minutes


def test_probe_reports_crash_and_garbage():
    platform, why = bench._probe_backend(
        30, code="import sys\nsys.exit(3)\n")
    assert platform is None and "rc=3" in why
    platform, why = bench._probe_backend(
        30, code="print('not json')\n")
    assert platform is None and "garbage" in why


def test_probe_parses_healthy_backend():
    code = ("import json\n"
            "print(json.dumps({'platform': 'tpu', "
            "'device': 'TPU_0(process=0,(0,0,0,0))'}))\n")
    platform, device = bench._probe_backend(30, code=code)
    assert platform == "tpu"
    assert device.startswith("TPU_0")


def test_heartbeat_file_records_stages(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_HEARTBEAT_PATH",
                        str(tmp_path / "hb.json"))
    bench._beat("unit_stage", batch=7)
    bench._beat("unit_stage_2")
    lines = (tmp_path / "hb.json").read_text().strip().splitlines()
    assert len(lines) == 2
    first = json.loads(lines[0])
    assert first["stage"] == "unit_stage" and first["batch"] == 7


def test_watchdog_arm_disarm_bookkeeping():
    wd = bench._Watchdog()
    wd.arm(3600, "never fires in-test")
    assert wd._deadline is not None and wd._label.startswith("never")
    wd.disarm()
    assert wd._deadline is None
