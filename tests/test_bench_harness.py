"""bench.py bring-up hardening: the probe must fail FAST and loudly.

Round 3 post-mortem: three in-process jax.devices() probes hung ~25
minutes each before the CPU fallback fired, eating the driver's whole
budget with zero evidence.  The probe now runs in a kill-able
subprocess with a hard deadline, and every phase transition appends to
a heartbeat file (reference keeps its benchmarks honest the same way —
JMH timeouts in eth-benchmark-tests/.../BLSBenchmark.java).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402


def test_probe_kills_hung_backend_within_deadline():
    t0 = time.time()
    platform, why, _err = bench._probe_backend(
        1.5, code="import time\ntime.sleep(600)\n")
    elapsed = time.time() - t0
    assert platform is None
    assert "timeout" in why
    assert elapsed < 30          # seconds, not round 3's 25 minutes


def test_probe_reports_crash_and_garbage():
    platform, why, err = bench._probe_backend(
        30, code="import sys\nsys.stderr.write('boom trace')\n"
                 "sys.exit(3)\n")
    assert platform is None and "rc=3" in why
    assert "boom trace" in err   # child stderr is evidence, not lost
    platform, why, _err = bench._probe_backend(
        30, code="print('not json')\n")
    assert platform is None and "garbage" in why


def test_probe_parses_healthy_backend():
    code = ("import json\n"
            "print(json.dumps({'platform': 'tpu', "
            "'device': 'TPU_0(process=0,(0,0,0,0))'}))\n")
    platform, device, _err = bench._probe_backend(30, code=code)
    assert platform == "tpu"
    assert device.startswith("TPU_0")


def test_probe_retries_until_success(monkeypatch):
    """Round 4 gave up after ONE probe; the retry loop must try again
    within budget and report each failure's stderr to the heartbeat."""
    calls = []

    def fake_probe(timeout_s, code=None):
        calls.append(timeout_s)
        if len(calls) < 2:
            return None, "probe timeout after 1s", "tunnel stderr tail"
        return "tpu", "TPU_0", ""

    monkeypatch.setattr(bench, "_probe_backend", fake_probe)
    monkeypatch.setenv("BENCH_PROBE_TIMEOUT_S", "1")
    monkeypatch.setenv("BENCH_PROBE_ATTEMPTS", "3")
    platform, device = bench._probe_with_retries(time.time() + 3600)
    assert platform == "tpu" and device == "TPU_0"
    assert len(calls) == 2


def test_probe_retries_respect_budget(monkeypatch):
    """With <90s remaining no further probe attempt may start."""
    calls = []

    def fake_probe(timeout_s, code=None):
        calls.append(timeout_s)
        return None, "probe timeout", ""

    monkeypatch.setattr(bench, "_probe_backend", fake_probe)
    monkeypatch.setenv("BENCH_PROBE_ATTEMPTS", "5")
    platform, why = bench._probe_with_retries(time.time() + 60)
    assert platform is None
    assert calls == []           # budget already too thin to probe


def test_heartbeat_file_records_stages(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_HEARTBEAT_PATH",
                        str(tmp_path / "hb.json"))
    bench._beat("unit_stage", batch=7)
    bench._beat("unit_stage_2")
    lines = (tmp_path / "hb.json").read_text().strip().splitlines()
    assert len(lines) == 2
    first = json.loads(lines[0])
    assert first["stage"] == "unit_stage" and first["batch"] == 7


def test_watchdog_arm_disarm_bookkeeping():
    wd = bench._Watchdog()
    wd.arm(3600, "never fires in-test")
    assert wd._deadline is not None and wd._label.startswith("never")
    wd.disarm()
    assert wd._deadline is None
