"""Real-socket networking: handshake, gossip over TCP, req/resp block
serving, and a fresh node syncing to an advanced chain — the reference's
Eth2P2PNetworkFactory-style loopback integration tests."""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio

import pytest

from teku_tpu.networking import NetworkedNode
from teku_tpu.spec import create_spec
from teku_tpu.spec.genesis import interop_genesis
from teku_tpu.validator import (BeaconNodeValidatorApi, LocalSigner,
                                SlashingProtectedSigner, ValidatorClient)
from teku_tpu.validator.slashing_protection import SlashingProtector

N_VALIDATORS = 16


def _make_pair():
    spec = create_spec("minimal")
    state, sks = interop_genesis(spec.config, N_VALIDATORS)
    a = NetworkedNode(spec, state, name="alpha")
    b = NetworkedNode(spec, state, name="beta")
    return spec, state, sks, a, b


def _client(spec, nn, keys):
    signer = SlashingProtectedSigner(LocalSigner(keys), SlashingProtector())
    return ValidatorClient(spec, BeaconNodeValidatorApi(nn.node), signer,
                           sorted(keys))


async def _run_slots(spec, nodes, clients, first, last):
    for slot in range(first, last + 1):
        for nn in nodes:
            await nn.node.on_slot(slot)
        for c in clients:
            await c.on_slot_start(slot)
        # real sockets: remote validation runs in the peers' read loops,
        # so give the wire a beat between duty phases (production has a
        # third of a slot here)
        await asyncio.sleep(0.02)
        for c in clients:
            await c.on_attestation_due(slot)
        for c in clients:
            await c.on_aggregation_due(slot)
        await asyncio.sleep(0.02)


@pytest.mark.slow
def test_gossip_over_tcp_converges():
    async def run():
        spec, state, sks, a, b = _make_pair()
        await a.start()
        await b.start()
        try:
            peer = await a.connect(b)
            assert peer is not None and peer.connected
            assert peer.status is not None          # status exchanged
            keys_a = {i: sks[i] for i in range(0, N_VALIDATORS, 2)}
            keys_b = {i: sks[i] for i in range(1, N_VALIDATORS, 2)}
            clients = [_client(spec, a, keys_a), _client(spec, b, keys_b)]
            await _run_slots(spec, [a, b], clients,
                             1, 2 * spec.config.SLOTS_PER_EPOCH)
            assert a.node.chain.head_root == b.node.chain.head_root
            assert a.node.chain.head_slot() == 2 * spec.config.SLOTS_PER_EPOCH
            # both proposers contributed over the wire
            assert all(c.blocks_proposed > 0 for c in clients)
        finally:
            await a.stop()
            await b.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_fresh_node_syncs_by_range():
    async def run():
        spec, state, sks, a, b = _make_pair()
        await a.start()
        try:
            # node A advances alone for 1.5 epochs
            client = _client(spec, a, dict(enumerate(sks)))
            await _run_slots(spec, [a], [client], 1, 12)
            assert a.node.chain.head_slot() == 12
            # fresh node B joins and syncs via blocks_by_range
            await b.start()
            for slot in range(1, 13):
                await b.node.on_slot(slot)      # clock catches up only
            await b.connect(a)
            await b.sync.run_until_synced()
            assert b.node.chain.head_slot() == 12
            assert b.node.chain.head_root == a.node.chain.head_root
            assert b.sync.blocks_imported == 12
        finally:
            await a.stop()
            await b.stop()
    asyncio.run(run())


def test_wrong_fork_digest_rejected():
    async def run():
        spec = create_spec("minimal")
        state1, _ = interop_genesis(spec.config, 8, genesis_time=1578009600)
        state2, _ = interop_genesis(spec.config, 8, genesis_time=1578009999)
        a = NetworkedNode(spec, state1)
        b = NetworkedNode(spec, state2)
        # different genesis time -> same fork version but the devnet
        # digest derives from validators root; force distinct digests
        b.net.fork_digest = b"\xde\xad\xbe\xef"
        await a.start()
        await b.start()
        try:
            peer = await a.connect(b)
            await asyncio.sleep(0.05)
            assert peer is None or not peer.connected
            assert not any(p.connected for p in a.net.peers)
        finally:
            await a.stop()
            await b.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_multipeer_sync_survives_garbage_and_silent_peers():
    """The best-claiming peer serves garbage, another claims much and
    serves nothing: the node must back both off and still reach the
    honest head (reference BatchSync + SyncStallDetector)."""
    from teku_tpu.networking import encoding as E
    from teku_tpu.networking.reqresp import BeaconRpc
    from teku_tpu.spec.datastructures import Status

    async def run():
        spec, state, sks, honest, fresh = _make_pair()
        evil = NetworkedNode(spec, state, name="evil")
        silent = NetworkedNode(spec, state, name="silent")
        await honest.start()
        try:
            client = _client(spec, honest, dict(enumerate(sks)))
            await _run_slots(spec, [honest], [client], 1, 12)
            assert honest.node.chain.head_slot() == 12

            # evil claims slot 50 and serves junk block batches
            await evil.start()
            real_status = evil.rpc._local_status()
            evil.rpc._local_status = lambda: Status(
                fork_digest=real_status.fork_digest,
                finalized_root=b"\xee" * 32, finalized_epoch=5,
                head_root=b"\xee" * 32, head_slot=50)
            junk = E.encode_response_chunk(b"\xff" * 120)

            async def evil_handler(peer, method, body,
                                   _orig=evil.net.on_request):
                if method == "beacon_blocks_by_range":
                    return junk
                return await _orig(peer, method, body)
            evil.net.on_request = evil_handler

            # silent claims slot 40 and times out every block request
            await silent.start()
            real2 = silent.rpc._local_status()
            silent.rpc._local_status = lambda: Status(
                fork_digest=real2.fork_digest,
                finalized_root=b"\xaa" * 32, finalized_epoch=4,
                head_root=b"\xaa" * 32, head_slot=40)

            async def silent_handler(peer, method, body,
                                     _orig=silent.net.on_request):
                if method == "beacon_blocks_by_range":
                    await asyncio.sleep(3600)
                return await _orig(peer, method, body)
            silent.net.on_request = silent_handler

            await fresh.start()
            for slot in range(1, 13):
                await fresh.node.on_slot(slot)
            await fresh.connect(evil)
            await fresh.connect(silent)
            await fresh.connect(honest)
            # short client timeout so the silent peer costs seconds
            orig = BeaconRpc.blocks_by_range

            async def fast_timeout(self, peer, start, count):
                resp = await peer.request(
                    "beacon_blocks_by_range",
                    E.encode_payload(
                        __import__("struct").pack("<QQ", start, count)),
                    timeout=1.0)
                from teku_tpu.networking.reqresp import _unpack_chunks
                chunks = _unpack_chunks(resp)
                if chunks is None:
                    raise ConnectionError("bad response")
                from teku_tpu.spec.codec import deserialize_signed_block
                return [deserialize_signed_block(self.node.spec.config, c)
                        for c in chunks]
            BeaconRpc.blocks_by_range = fast_timeout
            try:
                await fresh.sync.run_until_synced()
            finally:
                BeaconRpc.blocks_by_range = orig
            assert fresh.node.chain.head_slot() == 12
            assert fresh.node.chain.head_root == \
                honest.node.chain.head_root
            # the liars were detected and backed off
            assert fresh.sync.stalls_detected >= 1 or \
                len(fresh.sync._backoff) >= 1
        finally:
            await honest.stop()
            await evil.stop()
            await silent.stop()
            await fresh.stop()
    asyncio.run(run())
