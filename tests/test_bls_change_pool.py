"""bls_to_execution_changes: pool, gossip, block packing, REST family.

The VERDICT done-criterion scenario: on a capella devnet a submitted
bls-change enters the pool (entry-validated, the reference's
SignedBlsToExecutionChangeValidator semantics), is packed into a
proposal, executes on-chain (credentials flip to 0x01), and is pruned
from the pool (reference: statetransition/OperationPool.java +
handlers/v1/beacon/PostBlsToExecutionChanges).
"""

import asyncio
import dataclasses
import json
import urllib.error
import urllib.request

import pytest

from teku_tpu.api import BeaconRestApi
from teku_tpu.crypto import bls
from teku_tpu.node import Devnet
from teku_tpu.spec import config as C, Spec
from teku_tpu.spec import helpers as H
from teku_tpu.spec.capella.datastructures import get_capella_schemas

CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0,
                          BELLATRIX_FORK_EPOCH=0, CAPELLA_FORK_EPOCH=0)


def _signed_change(cfg, state, sks, idx, address=b"\xcc" * 20):
    S = get_capella_schemas(cfg)
    change = S.BLSToExecutionChange(
        validator_index=idx,
        from_bls_pubkey=bls.secret_to_public_key(sks[idx]),
        to_execution_address=address)
    domain = H.compute_domain(C.DOMAIN_BLS_TO_EXECUTION_CHANGE,
                              cfg.GENESIS_FORK_VERSION,
                              state.genesis_validators_root)
    sig = bls.sign(sks[idx], H.compute_signing_root(change, domain))
    return S.SignedBLSToExecutionChange(message=change, signature=sig)


@pytest.mark.slow
def test_bls_change_lands_in_block_via_rest():
    spec = Spec(CFG)
    net = Devnet(n_nodes=1, n_validators=16, spec=spec)
    node = net.nodes[0]
    state = net.genesis_state
    # the interop keys are deterministic — rebuild the signer's view
    from teku_tpu.spec.genesis import interop_secret_keys
    sks = interop_secret_keys(16)
    signed = _signed_change(CFG, state, sks, idx=5)

    async def run():
        await net.start()
        api = BeaconRestApi(node)
        await api.start()
        try:
            base = f"http://127.0.0.1:{api.port}"
            loop = asyncio.get_running_loop()

            def _post(path, payload):
                req = urllib.request.Request(
                    base + path, data=json.dumps(payload).encode(),
                    method="POST",
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())

            def _get(path):
                with urllib.request.urlopen(base + path,
                                            timeout=10) as r:
                    return json.loads(r.read())

            async def post(path, payload):
                return await loop.run_in_executor(None, _post, path,
                                                  payload)

            async def get(path):
                return await loop.run_in_executor(None, _get, path)

            payload = [{
                "message": {
                    "validator_index": "5",
                    "from_bls_pubkey":
                        "0x" + bls.secret_to_public_key(sks[5]).hex(),
                    "to_execution_address": "0x" + "cc" * 20},
                "signature": "0x" + bytes(signed.signature).hex()}]
            await post("/eth/v1/beacon/pool/bls_to_execution_changes",
                       payload)
            pool = node.operation_pools["bls_to_execution_changes"]
            assert len(pool) == 1
            listed = await get(
                "/eth/v1/beacon/pool/bls_to_execution_changes")
            assert listed["data"][0]["message"]["validator_index"] == "5"
            # duplicate submission is a 400
            with pytest.raises(urllib.error.HTTPError):
                await post(
                    "/eth/v1/beacon/pool/bls_to_execution_changes",
                    payload)
            # run a few slots: the next proposal must pack + execute it
            await net.run_until_slot(4)
            head = node.chain.head_state()
            creds = head.validators[5].withdrawal_credentials
            assert creds[:1] == b"\x01" and creds[12:] == b"\xcc" * 20
            assert len(pool) == 0          # pruned on inclusion
            # it rode in an actual block body
            found = any(
                len(node.store.blocks[root].body
                    .bls_to_execution_changes) > 0
                for root in node.store.blocks
                if hasattr(node.store.blocks[root].body,
                           "bls_to_execution_changes"))
            assert found
        finally:
            await api.stop()
            await net.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_pool_rest_family_and_balances():
    spec = Spec(CFG)
    net = Devnet(n_nodes=1, n_validators=16, spec=spec)
    node = net.nodes[0]

    async def run():
        await net.start()
        api = BeaconRestApi(node)
        await api.start()
        try:
            await net.run_until_slot(2)
            base = f"http://127.0.0.1:{api.port}"
            loop = asyncio.get_running_loop()

            def _get(path):
                with urllib.request.urlopen(base + path,
                                            timeout=10) as r:
                    return json.loads(r.read())

            async def get(path):
                return await loop.run_in_executor(None, _get, path)

            # empty pools serve empty lists
            for name in ("attester_slashings", "proposer_slashings",
                         "voluntary_exits"):
                empty = await get(f"/eth/v1/beacon/pool/{name}")
                assert empty["data"] == []
            # v2 pool family: versioned envelope
            for name in ("attester_slashings", "proposer_slashings"):
                v2 = await get(f"/eth/v2/beacon/pool/{name}")
                assert v2["data"] == []
                assert v2["version"] in (
                    "phase0", "altair", "bellatrix", "capella",
                    "deneb", "electra")
            # balances: full + filtered
            bal = await get(
                "/eth/v1/beacon/states/head/validator_balances")
            assert len(bal["data"]) == 16
            one = await get(
                "/eth/v1/beacon/states/head/validator_balances?id=3")
            assert one["data"][0]["index"] == "3"
            assert int(one["data"][0]["balance"]) > 0
            # block root + attestations + peer count
            root = (await get("/eth/v1/beacon/blocks/head/root")
                    )["data"]["root"]
            assert root.startswith("0x") and len(root) == 66
            atts = await get("/eth/v1/beacon/blocks/head/attestations")
            assert isinstance(atts["data"], list)
            pc = (await get("/eth/v1/node/peer_count"))["data"]
            assert pc["connected"] == "0"
            # expected withdrawals on a capella state
            w = await get(
                "/eth/v1/beacon/states/head/expected_withdrawals")
            assert isinstance(w["data"], list)
        finally:
            await api.stop()
            await net.stop()

    asyncio.run(run())
