"""fp381 limb arithmetic vs the pure-Python oracle.

Layer validation for BOTH mont_mul engines: the VPU pad-and-sum path
and the MXU int8 digit-split matmul path (ops/mxu.py) run against the
same oracle, including adversarial operands at the documented
``units(a) * units(b) <= 64`` lazy-reduction contract edge, plus a
cross-path parity gate asserting bit-identical ``canonical()`` images.
"""

import random

import numpy as np
import pytest

from teku_tpu.crypto.bls.constants import P, R
from teku_tpu.ops import limbs as fp
from teku_tpu.ops import modfield, mxu

rng = random.Random(0xB15)

PATH_KERNELS = {
    "vpu": (fp.mont_mul_vpu, fp.mont_sqr_vpu),
    "mxu": (fp.mont_mul_mxu, fp.mont_sqr_mxu),
}


def rand_fq():
    return rng.randrange(P)


EDGE = [0, 1, 2, P - 1, P - 2, (P - 1) // 2, fp.R_MOD_P, P - fp.R_MOD_P]


def batch_mont(values):
    return np.stack([fp.int_to_mont(v) for v in values])


def unbatch(arr):
    return [fp.mont_to_int(np.asarray(arr)[i]) for i in range(arr.shape[0])]


def test_limb_roundtrip():
    for v in EDGE + [rand_fq() for _ in range(20)]:
        assert fp.limbs_to_int(fp.int_to_limbs(v)) == v
        assert fp.mont_to_int(fp.int_to_mont(v)) == v


def test_add_sub_neg():
    a_vals = EDGE + [rand_fq() for _ in range(24)]
    b_vals = list(reversed(EDGE)) + [rand_fq() for _ in range(24)]
    a, b = batch_mont(a_vals), batch_mont(b_vals)
    assert unbatch(fp.add(a, b)) == [(x + y) % P for x, y in zip(a_vals, b_vals)]
    assert unbatch(fp.sub(a, b)) == [(x - y) % P for x, y in zip(a_vals, b_vals)]
    assert unbatch(fp.neg(a)) == [(-x) % P for x in a_vals]


def test_mont_mul_sqr():
    a_vals = EDGE + [rand_fq() for _ in range(24)]
    b_vals = list(reversed(EDGE)) + [rand_fq() for _ in range(24)]
    a, b = batch_mont(a_vals), batch_mont(b_vals)
    assert unbatch(fp.mont_mul(a, b)) == [x * y % P for x, y in zip(a_vals, b_vals)]
    assert unbatch(fp.mont_sqr(a)) == [x * x % P for x in a_vals]


def test_mul_broadcast():
    # (4,1,L) x (3,L) -> (4,3,L)
    a_vals = [rand_fq() for _ in range(4)]
    b_vals = [rand_fq() for _ in range(3)]
    a = batch_mont(a_vals)[:, None, :]
    b = batch_mont(b_vals)
    out = np.asarray(fp.mont_mul(a, b))
    assert out.shape == (4, 3, fp.L)
    for i in range(4):
        for j in range(3):
            assert fp.mont_to_int(out[i, j]) == a_vals[i] * b_vals[j] % P


def test_to_from_mont_device():
    vals = EDGE + [rand_fq() for _ in range(8)]
    plain = np.stack([fp.int_to_limbs(v) for v in vals])
    m = fp.to_mont(plain)
    back = np.asarray(fp.from_mont(m))
    assert [fp.limbs_to_int(back[i]) for i in range(len(vals))] == vals


def test_is_zero_eq_select():
    a = batch_mont([0, 1, P - 1, 0])
    b = batch_mont([0, 1, 1, 5])
    assert list(np.asarray(fp.is_zero(a))) == [True, False, False, True]
    assert list(np.asarray(fp.eq(a, b))) == [True, True, False, False]
    sel = fp.select(fp.eq(a, b), a, b)
    assert unbatch(sel) == [0, 1, 1, 5]


def test_mul_small():
    a_vals = [rand_fq() for _ in range(6)] + [P - 1]
    a = batch_mont(a_vals)
    for k in (0, 1, 2, 3, 8):
        assert unbatch(fp.mul_small(a, k)) == [v * k % P for v in a_vals]


def test_pow_static_and_inv():
    a_vals = [rand_fq() for _ in range(4)] + [1, P - 1]
    a = batch_mont(a_vals)
    for e in (1, 2, 3, 65537, (P - 1) // 2):
        assert unbatch(fp.pow_static(a, e)) == [pow(v, e, P) for v in a_vals]
    got = unbatch(fp.inv(a))
    assert got == [pow(v, -1, P) for v in a_vals]
    # inv(0) = 0 convention
    z = batch_mont([0])
    assert unbatch(fp.inv(z)) == [0]


def test_sqrt_candidate():
    for _ in range(6):
        r = rand_fq()
        sq = r * r % P
        cand = fp.mont_to_int(np.asarray(fp.sqrt_candidate(batch_mont([sq]))[0]))
        assert cand in (r, P - r)


# --------------------------------------------------------------------------
# Adversarial operand bounds at the lazy-reduction contract edge, on
# BOTH multiplier paths (units(a) * units(b) <= 64; ops/limbs.py)
# --------------------------------------------------------------------------

def _lazy_operand(n_units: int, sign_rng):
    """A signed sum of n_units Montgomery units: (lazy_limbs, value)."""
    acc = np.zeros(fp.L, dtype=np.int64)
    value = 0
    for _ in range(n_units):
        v = rand_fq()
        s = sign_rng.choice((1, -1))
        acc = acc + s * np.asarray(fp.int_to_mont(v), dtype=np.int64)
        value = (value + s * v) % P
    return acc, value


@pytest.mark.parametrize("path", sorted(PATH_KERNELS))
@pytest.mark.parametrize("ua,ub", [(1, 64), (2, 32), (4, 16), (8, 8),
                                   (16, 4), (64, 1)])
def test_mont_mul_lazy_contract_edge(path, ua, ub):
    """Signed lazy sums at every (ua, ub) split of the ua*ub = 64
    contract edge must reduce to the oracle product on both paths."""
    mont_mul, _ = PATH_KERNELS[path]
    sign_rng = random.Random(ua * 1000 + ub)
    lanes = 4
    la, lb, expect = [], [], []
    for _ in range(lanes):
        a, va = _lazy_operand(ua, sign_rng)
        b, vb = _lazy_operand(ub, sign_rng)
        la.append(a)
        lb.append(b)
        expect.append(va * vb % P)
    out = np.asarray(mont_mul(np.stack(la), np.stack(lb)))
    got = [fp.mont_to_int(out[i]) for i in range(lanes)]
    assert got == expect


@pytest.mark.parametrize("path", sorted(PATH_KERNELS))
def test_mont_mul_top_limb_magnitude(path):
    """Operands whose compressed top limb sits near the +-2^22 unit
    bound (and beyond, at the 64-unit lazy bound) stay exact: the MXU
    digit split must carry the top limb's sign and overflow."""
    mont_mul, mont_sqr = PATH_KERNELS[path]
    top = fp.W * (fp.L - 1)                      # bit 364
    cases = []
    for top_mag in ((1 << 22) - 1, (1 << 21) + 1):
        v = ((top_mag << top) + rng.randrange(1 << top)) % P
        cases.append(v)
    # maximal canonical value: top limb at its largest canonical size
    cases += [P - 1, P - 2]
    a = np.stack([np.asarray(fp.int_to_mont(v), dtype=np.int64)
                  for v in cases])
    # drive the top limb NEGATIVE and large via signed-sum lazies:
    # a (1 unit) x neg (32 units) hits ua*ub = 32; mont_sqr uses an
    # 8-unit operand so the squared contract 8*8 = 64 sits AT the edge
    neg = np.stack([-32 * row for row in a])
    out = np.asarray(mont_mul(a, neg))
    for i, v in enumerate(cases):
        assert fp.mont_to_int(out[i]) == (v * (-32 * v)) % P
    sq = np.asarray(mont_sqr(np.stack([-8 * row for row in a])))
    for i, v in enumerate(cases):
        assert fp.mont_to_int(sq[i]) == (8 * v) ** 2 % P


def test_cross_path_parity_bit_identical():
    """vpu and mxu mont_mul/mont_sqr must produce BIT-IDENTICAL
    canonical() images on shared random vectors — the gate for
    swapping the engine under the live kernels."""
    prng = random.Random(0xA11CE)
    lanes = 32
    a = np.stack([np.asarray(fp.int_to_mont(prng.randrange(P)))
                  for _ in range(lanes)])
    b = np.stack([np.asarray(fp.int_to_mont(prng.randrange(P)))
                  for _ in range(lanes)])
    # plus lazy signed sums (units 2 and 4), like real call sites feed
    lazy_a = a - np.roll(a, 1, axis=0)
    lazy_b = b + np.roll(b, 3, axis=0) - np.roll(a, 5, axis=0) + a
    for x, y in ((a, b), (lazy_a, lazy_b), (lazy_b, lazy_a)):
        vpu = np.asarray(fp.canonical(fp.mont_mul_vpu(x, y)))
        mxu_ = np.asarray(fp.canonical(fp.mont_mul_mxu(x, y)))
        assert (vpu == mxu_).all()
    sq_v = np.asarray(fp.canonical(fp.mont_sqr_vpu(lazy_b)))
    sq_m = np.asarray(fp.canonical(fp.mont_sqr_mxu(lazy_b)))
    assert (sq_v == sq_m).all()


def test_dispatch_follows_path_config():
    """fp.mont_mul routes by the process-global config: forced mxu and
    forced vpu must agree bit-for-bit (trace-time dispatch).  Both
    ends are pinned so an ambient TEKU_TPU_MONT_MUL doesn't leak in."""
    a = batch_mont([rand_fq() for _ in range(4)])
    b = batch_mont([rand_fq() for _ in range(4)])
    with mxu.force("vpu"):
        assert mxu.resolve() == "vpu"
        base = np.asarray(fp.mont_mul(a, b))
    with mxu.force("mxu-force"):
        assert mxu.resolve() == "mxu"
        forced = np.asarray(fp.mont_mul(a, b))
    assert (np.asarray(fp.canonical(base))
            == np.asarray(fp.canonical(forced))).all()


def test_generic_field_cross_path_parity():
    """modfield.make_field carries both engines too (Fr for KZG): the
    scalar field's 10-limb digit split needs 5 digit planes — cover it
    against the bigint oracle and across paths."""
    FR = modfield.make_field(R, "fr")
    prng = random.Random(0xF2)
    xs = [0, 1, R - 1, R - 2] + [prng.randrange(R) for _ in range(12)]
    ys = list(reversed(xs))
    a = np.stack([np.asarray(FR.int_to_mont(v)) for v in xs])
    b = np.stack([np.asarray(FR.int_to_mont(v)) for v in ys])
    lazy_a = a - np.roll(b, 2, axis=0)
    va = [(x - y2) % R for x, y2 in zip(xs, np.roll(ys, 2).tolist())]
    out_v = np.asarray(FR.mont_mul_vpu(lazy_a, b))
    out_m = np.asarray(FR.mont_mul_mxu(lazy_a, b))
    for i in range(len(xs)):
        assert FR.mont_to_int(out_v[i]) == va[i] * ys[i] % R
        assert FR.mont_to_int(out_m[i]) == va[i] * ys[i] % R
    assert (np.asarray(FR.canonical(out_v))
            == np.asarray(FR.canonical(out_m))).all()
