"""fp381 limb arithmetic vs the pure-Python oracle."""

import random

import numpy as np
import pytest

from teku_tpu.crypto.bls.constants import P
from teku_tpu.ops import limbs as fp

rng = random.Random(0xB15)


def rand_fq():
    return rng.randrange(P)


EDGE = [0, 1, 2, P - 1, P - 2, (P - 1) // 2, fp.R_MOD_P, P - fp.R_MOD_P]


def batch_mont(values):
    return np.stack([fp.int_to_mont(v) for v in values])


def unbatch(arr):
    return [fp.mont_to_int(np.asarray(arr)[i]) for i in range(arr.shape[0])]


def test_limb_roundtrip():
    for v in EDGE + [rand_fq() for _ in range(20)]:
        assert fp.limbs_to_int(fp.int_to_limbs(v)) == v
        assert fp.mont_to_int(fp.int_to_mont(v)) == v


def test_add_sub_neg():
    a_vals = EDGE + [rand_fq() for _ in range(24)]
    b_vals = list(reversed(EDGE)) + [rand_fq() for _ in range(24)]
    a, b = batch_mont(a_vals), batch_mont(b_vals)
    assert unbatch(fp.add(a, b)) == [(x + y) % P for x, y in zip(a_vals, b_vals)]
    assert unbatch(fp.sub(a, b)) == [(x - y) % P for x, y in zip(a_vals, b_vals)]
    assert unbatch(fp.neg(a)) == [(-x) % P for x in a_vals]


def test_mont_mul_sqr():
    a_vals = EDGE + [rand_fq() for _ in range(24)]
    b_vals = list(reversed(EDGE)) + [rand_fq() for _ in range(24)]
    a, b = batch_mont(a_vals), batch_mont(b_vals)
    assert unbatch(fp.mont_mul(a, b)) == [x * y % P for x, y in zip(a_vals, b_vals)]
    assert unbatch(fp.mont_sqr(a)) == [x * x % P for x in a_vals]


def test_mul_broadcast():
    # (4,1,L) x (3,L) -> (4,3,L)
    a_vals = [rand_fq() for _ in range(4)]
    b_vals = [rand_fq() for _ in range(3)]
    a = batch_mont(a_vals)[:, None, :]
    b = batch_mont(b_vals)
    out = np.asarray(fp.mont_mul(a, b))
    assert out.shape == (4, 3, fp.L)
    for i in range(4):
        for j in range(3):
            assert fp.mont_to_int(out[i, j]) == a_vals[i] * b_vals[j] % P


def test_to_from_mont_device():
    vals = EDGE + [rand_fq() for _ in range(8)]
    plain = np.stack([fp.int_to_limbs(v) for v in vals])
    m = fp.to_mont(plain)
    back = np.asarray(fp.from_mont(m))
    assert [fp.limbs_to_int(back[i]) for i in range(len(vals))] == vals


def test_is_zero_eq_select():
    a = batch_mont([0, 1, P - 1, 0])
    b = batch_mont([0, 1, 1, 5])
    assert list(np.asarray(fp.is_zero(a))) == [True, False, False, True]
    assert list(np.asarray(fp.eq(a, b))) == [True, True, False, False]
    sel = fp.select(fp.eq(a, b), a, b)
    assert unbatch(sel) == [0, 1, 1, 5]


def test_mul_small():
    a_vals = [rand_fq() for _ in range(6)] + [P - 1]
    a = batch_mont(a_vals)
    for k in (0, 1, 2, 3, 8):
        assert unbatch(fp.mul_small(a, k)) == [v * k % P for v in a_vals]


def test_pow_static_and_inv():
    a_vals = [rand_fq() for _ in range(4)] + [1, P - 1]
    a = batch_mont(a_vals)
    for e in (1, 2, 3, 65537, (P - 1) // 2):
        assert unbatch(fp.pow_static(a, e)) == [pow(v, e, P) for v in a_vals]
    got = unbatch(fp.inv(a))
    assert got == [pow(v, -1, P) for v in a_vals]
    # inv(0) = 0 convention
    z = batch_mont([0])
    assert unbatch(fp.inv(z)) == [0]


def test_sqrt_candidate():
    for _ in range(6):
        r = rand_fq()
        sq = r * r % P
        cand = fp.mont_to_int(np.asarray(fp.sqrt_candidate(batch_mont([sq]))[0]))
        assert cand in (r, P - r)
