"""Official vector gate: when TEKU_TPU_VECTORS points at the real
archives (ethereum/bls12-381-tests + consensus-spec-tests), every
discovered case runs; without it these parametrize to skips.

The loader itself is validated against a hand-built miniature archive
with the official layout, so the gate flips on automatically the
moment real archives are present (VERDICT r3 weak #5).
"""

import json
import os
from pathlib import Path

import pytest

from teku_tpu.spec import reference_tests as RT

_ROOT = RT.vectors_root()


def _bls_cases():
    if _ROOT is None:
        return []
    return [pytest.param(suite, name, case,
                         id=f"{suite}::{name}")
            for suite, name, case in RT.iter_bls_cases(_ROOT)]


def _consensus_cases(runner):
    if _ROOT is None:
        return []
    return [pytest.param(fork, handler, case_dir,
                         id=f"{fork}::{handler}::{case_dir.name}")
            for fork, handler, case_dir
            in RT.iter_consensus_cases(_ROOT, runner)]


@pytest.mark.skipif(_ROOT is None,
                    reason="TEKU_TPU_VECTORS not set")
@pytest.mark.parametrize("suite,name,case", _bls_cases())
def test_official_bls(suite, name, case):
    result = RT.run_bls_case(suite, case)
    if result is None:
        pytest.skip(f"unsupported suite {suite}")
    assert result, f"{suite}/{name} diverged from the official vector"


@pytest.mark.skipif(_ROOT is None,
                    reason="TEKU_TPU_VECTORS not set")
@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("epoch_processing"))
def test_official_epoch_processing(fork, handler, case_dir):
    result = RT.run_epoch_processing_case("minimal", fork, handler,
                                          case_dir)
    if result is None:
        pytest.skip(f"unsupported handler {handler}")
    assert result


@pytest.mark.skipif(_ROOT is None,
                    reason="TEKU_TPU_VECTORS not set")
@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("operations"))
def test_official_operations(fork, handler, case_dir):
    result = RT.run_operations_case("minimal", fork, handler, case_dir)
    if result is None:
        pytest.skip(f"unsupported handler {handler}")
    assert result


@pytest.mark.skipif(_ROOT is None,
                    reason="TEKU_TPU_VECTORS not set")
@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("sanity"))
def test_official_sanity(fork, handler, case_dir):
    if handler == "slots":
        assert RT.run_sanity_slots_case("minimal", fork, case_dir)
    elif handler == "blocks":
        assert RT.run_sanity_blocks_case("minimal", fork, case_dir)
    else:
        pytest.skip(handler)


@pytest.mark.skipif(_ROOT is None,
                    reason="TEKU_TPU_VECTORS not set")
@pytest.mark.parametrize("fork,type_name,case_dir",
                         _consensus_cases("ssz_static"))
def test_official_ssz_static(fork, type_name, case_dir):
    result = RT.run_ssz_static_case("minimal", fork, type_name,
                                    case_dir)
    if result is None:
        pytest.skip(f"no schema for {type_name}")
    assert result


# ---------------------------------------------------------------------------
# Loader mechanics, proven against a hand-built miniature archive with
# the official layout — runs offline, always.
# ---------------------------------------------------------------------------

def _write_snappy(path: Path, ssz: bytes) -> None:
    from teku_tpu.native import snappyc
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_bytes(snappyc.compress(ssz))


def _build_mini_archive(root: Path) -> dict:
    """Official directory shapes, contents generated with our own
    implementations (the loader's MECHANICS are under test: layout
    walking, snappy/yaml/json decoding, dispatch, verdicts)."""
    from teku_tpu.crypto import bls
    from teku_tpu.spec import perf as P
    from teku_tpu.spec.altair import epoch as AE
    from teku_tpu.spec.datastructures import Checkpoint
    from teku_tpu.spec.transition import process_slots

    counts = {}
    # BLS: one passing verify vector, one expected-failure, a sign case
    sk = 4242
    pk = bls.secret_to_public_key(sk)
    msg = b"\x11" * 32
    sig = bls.sign(sk, msg)
    bls_dir = root / "bls"
    (bls_dir / "verify").mkdir(parents=True)
    (bls_dir / "verify" / "verify_valid.json").write_text(json.dumps({
        "input": {"pubkey": "0x" + pk.hex(),
                  "message": "0x" + msg.hex(),
                  "signature": "0x" + sig.hex()},
        "output": True}))
    (bls_dir / "verify" / "verify_wrong_msg.json").write_text(
        json.dumps({
            "input": {"pubkey": "0x" + pk.hex(),
                      "message": "0x" + (b"\x22" * 32).hex(),
                      "signature": "0x" + sig.hex()},
            "output": False}))
    (bls_dir / "sign").mkdir(parents=True)
    (bls_dir / "sign" / "sign_case.json").write_text(json.dumps({
        "input": {"privkey": "0x" + sk.to_bytes(32, "big").hex(),
                  "message": "0x" + msg.hex()},
        "output": "0x" + sig.hex()}))
    counts["bls"] = 3

    # epoch_processing: altair slashings_reset (pre/post)
    cfg = RT.fork_config("minimal", "altair")
    state = P.make_synthetic_altair_state(cfg, 8)
    import teku_tpu.spec.epoch as E0
    post = E0.process_slashings_reset(cfg, state)
    case = (root / "tests" / "minimal" / "altair" / "epoch_processing"
            / "slashings_reset" / "pyspec_tests" / "slashings_reset_0")
    S = RT.schemas_for(cfg, "altair")
    _write_snappy(case / "pre.ssz_snappy", S.BeaconState.serialize(state))
    _write_snappy(case / "post.ssz_snappy", S.BeaconState.serialize(post))
    counts["epoch"] = 1

    # sanity/slots: advance 3 empty slots
    post_slots = process_slots(cfg, state, state.slot + 3)
    case = (root / "tests" / "minimal" / "altair" / "sanity" / "slots"
            / "pyspec_tests" / "slots_3")
    _write_snappy(case / "pre.ssz_snappy", S.BeaconState.serialize(state))
    (case / "slots.yaml").write_text("3\n")
    _write_snappy(case / "post.ssz_snappy",
                  S.BeaconState.serialize(post_slots))
    counts["sanity"] = 1

    # operations/voluntary_exit (phase0): exercises the verifier
    # injection — process_voluntary_exit takes a SignatureVerifier
    from teku_tpu.spec import block as B0
    from teku_tpu.spec import helpers as H
    from teku_tpu.spec.config import DOMAIN_VOLUNTARY_EXIT
    from teku_tpu.spec.datastructures import (SignedVoluntaryExit,
                                              VoluntaryExit)
    from teku_tpu.spec.genesis import interop_genesis
    from teku_tpu.spec.verifiers import SIMPLE
    p0_cfg = RT.fork_config("minimal", "phase0")
    exit_state, sks = interop_genesis(p0_cfg, 8)
    # the validator must have served SHARD_COMMITTEE_PERIOD epochs
    exit_state = process_slots(
        p0_cfg, exit_state,
        p0_cfg.SHARD_COMMITTEE_PERIOD * p0_cfg.SLOTS_PER_EPOCH + 1)
    epoch = p0_cfg.SHARD_COMMITTEE_PERIOD
    msg = VoluntaryExit(epoch=epoch, validator_index=2)
    domain = H.get_domain(p0_cfg, exit_state, DOMAIN_VOLUNTARY_EXIT,
                          epoch)
    signed_exit = SignedVoluntaryExit(
        message=msg,
        signature=__import__("teku_tpu.crypto.bls",
                             fromlist=["sign"]).sign(
            sks[2], H.compute_signing_root(msg, domain)))
    post_exit = B0.process_voluntary_exit(p0_cfg, exit_state,
                                          signed_exit, SIMPLE)
    S0 = RT.schemas_for(p0_cfg, "phase0")
    case = (root / "tests" / "minimal" / "phase0" / "operations"
            / "voluntary_exit" / "pyspec_tests" / "exit_0")
    _write_snappy(case / "pre.ssz_snappy",
                  S0.BeaconState.serialize(exit_state))
    _write_snappy(case / "voluntary_exit.ssz_snappy",
                  SignedVoluntaryExit.serialize(signed_exit))
    _write_snappy(case / "post.ssz_snappy",
                  S0.BeaconState.serialize(post_exit))
    # and an invalid twin: bad signature, no post file
    bad_case = (root / "tests" / "minimal" / "phase0" / "operations"
                / "voluntary_exit" / "pyspec_tests" / "exit_bad_sig")
    bad = SignedVoluntaryExit(message=msg, signature=b"\x0b" * 96)
    _write_snappy(bad_case / "pre.ssz_snappy",
                  S0.BeaconState.serialize(exit_state))
    _write_snappy(bad_case / "voluntary_exit.ssz_snappy",
                  SignedVoluntaryExit.serialize(bad))
    counts["operations"] = 2

    # ssz_static: a Checkpoint with roots.yaml
    cp = Checkpoint(epoch=7, root=b"\x5a" * 32)
    case = (root / "tests" / "minimal" / "phase0" / "ssz_static"
            / "Checkpoint" / "ssz_random" / "case_0")
    _write_snappy(case / "serialized.ssz_snappy",
                  Checkpoint.serialize(cp))
    (case / "roots.yaml").write_text(
        f"{{root: '0x{cp.htr().hex()}'}}\n")
    counts["ssz"] = 1
    return counts


def test_loader_against_miniature_official_archive(tmp_path):
    counts = _build_mini_archive(tmp_path)

    bls_cases = list(RT.iter_bls_cases(tmp_path))
    assert len(bls_cases) == counts["bls"]
    for suite, name, case in bls_cases:
        assert RT.run_bls_case(suite, case) is True, (suite, name)

    epoch_cases = list(RT.iter_consensus_cases(tmp_path,
                                               "epoch_processing"))
    assert len(epoch_cases) == counts["epoch"]
    for fork, handler, case_dir in epoch_cases:
        assert RT.run_epoch_processing_case("minimal", fork, handler,
                                            case_dir) is True

    ops = list(RT.iter_consensus_cases(tmp_path, "operations"))
    assert len(ops) == counts["operations"]
    for fork, handler, case_dir in ops:
        assert RT.run_operations_case("minimal", fork, handler,
                                      case_dir) is True, case_dir.name

    sanity = list(RT.iter_consensus_cases(tmp_path, "sanity"))
    assert len(sanity) == counts["sanity"]
    for fork, handler, case_dir in sanity:
        assert handler == "slots"
        assert RT.run_sanity_slots_case("minimal", fork, case_dir)

    ssz = list(RT.iter_consensus_cases(tmp_path, "ssz_static"))
    assert len(ssz) == counts["ssz"]
    for fork, type_name, case_dir in ssz:
        assert RT.run_ssz_static_case("minimal", fork, type_name,
                                      case_dir) is True


def test_loader_flags_divergence(tmp_path):
    """A corrupted expected value must FAIL, not skip: the gate's
    verdicts are real."""
    from teku_tpu.spec.datastructures import Checkpoint
    cp = Checkpoint(epoch=7, root=b"\x5a" * 32)
    case = (tmp_path / "tests" / "minimal" / "phase0" / "ssz_static"
            / "Checkpoint" / "ssz_random" / "case_0")
    _write_snappy(case / "serialized.ssz_snappy",
                  Checkpoint.serialize(cp))
    (case / "roots.yaml").write_text(
        "{root: '0x" + "ab" * 32 + "'}\n")
    assert RT.run_ssz_static_case("minimal", "phase0", "Checkpoint",
                                  case) is False
    # and a BLS vector claiming a wrong output fails too
    bad = {"input": {"pubkey": "0x" + "11" * 48,
                     "message": "0x" + "22" * 32,
                     "signature": "0x" + "33" * 96},
           "output": True}
    assert RT.run_bls_case("verify", bad) is False
