"""Official vector gate.

When TEKU_TPU_VECTORS points at the real archives
(ethereum/bls12-381-tests + consensus-spec-tests), every discovered
case runs against the corresponding runner.  WITHOUT the env var the
gate still runs — against the constructed official-format archive
(tests/vector_archive.py), so every runner executes real cases in
offline CI instead of skipping (VERDICT r4: the official-vector gate
never fired).

Loader mechanics (case counts, verdict flipping) are additionally
asserted against a fresh archive build in a tmp dir.
"""

import atexit
import json
import shutil
import tempfile
from pathlib import Path

import pytest

from teku_tpu.spec import reference_tests as RT

from . import vector_archive as VA

_ROOT = RT.vectors_root()
_CONSTRUCTED = _ROOT is None
_KZG_SETUP = None
if _CONSTRUCTED:
    _ROOT = Path(tempfile.mkdtemp(prefix="teku_tpu_vectors_"))
    atexit.register(shutil.rmtree, _ROOT, True)
    _COUNTS = VA.build(_ROOT)
    _KZG_SETUP = VA.INSECURE_SETUP
elif (_ROOT / "INSECURE_KZG_SETUP").exists():
    _KZG_SETUP = VA.INSECURE_SETUP


def _bls_cases():
    return [pytest.param(suite, name, case, id=f"{suite}::{name}")
            for suite, name, case in RT.iter_bls_cases(_ROOT)]


def _consensus_cases(runner, preset="minimal"):
    return [pytest.param(fork, handler, case_dir,
                         id=f"{fork}::{handler}::{case_dir.name}")
            for fork, handler, case_dir
            in RT.iter_consensus_cases(_ROOT, runner, preset=preset)]


@pytest.mark.parametrize("suite,name,case", _bls_cases())
def test_official_bls(suite, name, case):
    result = RT.run_bls_case(suite, case)
    if result is None:
        pytest.skip(f"unsupported suite {suite}")
    assert result, f"{suite}/{name} diverged from the official vector"


@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("epoch_processing"))
def test_official_epoch_processing(fork, handler, case_dir):
    result = RT.run_epoch_processing_case("minimal", fork, handler,
                                          case_dir)
    if result is None:
        pytest.skip(f"unsupported handler {handler}")
    assert result


@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("operations"))
def test_official_operations(fork, handler, case_dir):
    result = RT.run_operations_case("minimal", fork, handler, case_dir)
    if result is None:
        pytest.skip(f"unsupported handler {handler}")
    assert result


@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("sanity"))
def test_official_sanity(fork, handler, case_dir):
    if handler == "slots":
        assert RT.run_sanity_slots_case("minimal", fork, case_dir)
    elif handler == "blocks":
        assert RT.run_sanity_blocks_case("minimal", fork, case_dir)
    else:
        pytest.skip(handler)


@pytest.mark.parametrize("fork,type_name,case_dir",
                         _consensus_cases("ssz_static"))
def test_official_ssz_static(fork, type_name, case_dir):
    result = RT.run_ssz_static_case("minimal", fork, type_name,
                                    case_dir)
    if result is None:
        pytest.skip(f"no schema for {type_name}")
    assert result


@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("shuffling"))
def test_official_shuffling(fork, handler, case_dir):
    assert RT.run_shuffling_case("minimal", fork, case_dir)


@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("rewards"))
def test_official_rewards(fork, handler, case_dir):
    result = RT.run_rewards_case("minimal", fork, case_dir)
    if result is None:
        pytest.skip(f"rewards runner does not cover {fork}")
    assert result


@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("fork"))
def test_official_fork_upgrade(fork, handler, case_dir):
    result = RT.run_fork_upgrade_case("minimal", fork, case_dir)
    if result is None:
        pytest.skip(f"no upgrade handler for {fork}")
    assert result


@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("transition"))
def test_official_transition(fork, handler, case_dir):
    result = RT.run_transition_case("minimal", fork, case_dir)
    if result is None:
        pytest.skip(f"transition runner does not cover {fork}")
    assert result


@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("fork_choice"))
def test_official_fork_choice(fork, handler, case_dir):
    result = RT.run_fork_choice_case("minimal", fork, case_dir)
    if result is None:
        pytest.skip("case uses steps this build does not model")
    assert result


def _kzg_cases():
    out = []
    for _fork, handler, case_dir in RT.iter_consensus_cases(
            _ROOT, "kzg", preset="general"):
        data = case_dir / "data.yaml"
        if data.exists():
            out.append(pytest.param(
                handler, data, id=f"{handler}::{case_dir.name}"))
    return out


@pytest.mark.parametrize("handler,data_path", _kzg_cases())
def test_official_kzg(handler, data_path):
    import yaml
    case = yaml.safe_load(data_path.read_text())
    result = RT.run_kzg_case(handler, case, setup=_KZG_SETUP)
    if result is None:
        pytest.skip(f"unsupported kzg handler {handler}")
    assert result


@pytest.mark.parametrize("fork,handler,case_dir",
                         _consensus_cases("light_client"))
def test_official_merkle_proof(fork, handler, case_dir):
    if handler != "single_merkle_proof" \
            or not (case_dir / "proof.yaml").exists():
        pytest.skip(f"light_client handler {handler} not a merkle "
                    "proof case")
    result = RT.run_merkle_proof_case("minimal", fork, case_dir)
    if result is None:
        pytest.skip(f"no schema for {case_dir.parent.name}")
    assert result


# ---------------------------------------------------------------------------
# Loader mechanics: exact case counts + verdicts flip on divergence,
# against a fresh archive build.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_loader_against_fresh_archive(tmp_path):
    counts = VA.build(tmp_path)

    bls_cases = list(RT.iter_bls_cases(tmp_path))
    assert len(bls_cases) == counts["bls"]
    for suite, name, case in bls_cases:
        assert RT.run_bls_case(suite, case) is True, (suite, name)

    expect = {
        "epoch_processing": ("epoch", RT.run_epoch_processing_case),
        "operations": ("operations", RT.run_operations_case),
    }
    for runner, (key, fn) in expect.items():
        cases = list(RT.iter_consensus_cases(tmp_path, runner))
        assert len(cases) == counts[key]
        for fork, handler, case_dir in cases:
            assert fn("minimal", fork, handler, case_dir) is True, \
                (runner, case_dir.name)

    simple = {
        "sanity": ("sanity", RT.run_sanity_slots_case),
        "shuffling": ("shuffling", RT.run_shuffling_case),
        "rewards": ("rewards", RT.run_rewards_case),
        "fork": ("fork", RT.run_fork_upgrade_case),
        "transition": ("transition", RT.run_transition_case),
        "fork_choice": ("fork_choice", RT.run_fork_choice_case),
    }
    for runner, (key, fn) in simple.items():
        cases = list(RT.iter_consensus_cases(tmp_path, runner))
        assert len(cases) == counts[key], runner
        for fork, _handler, case_dir in cases:
            assert fn("minimal", fork, case_dir) is True, \
                (runner, case_dir.name)

    ssz = list(RT.iter_consensus_cases(tmp_path, "ssz_static"))
    assert len(ssz) == counts["ssz"]
    for fork, type_name, case_dir in ssz:
        assert RT.run_ssz_static_case("minimal", fork, type_name,
                                      case_dir) is True

    kzg_cases = list(RT.iter_consensus_cases(tmp_path, "kzg",
                                             preset="general"))
    assert len(kzg_cases) == counts["kzg"]
    import yaml
    for _fork, handler, case_dir in kzg_cases:
        case = yaml.safe_load((case_dir / "data.yaml").read_text())
        assert RT.run_kzg_case(handler, case,
                               setup=VA.INSECURE_SETUP) is True, \
            (handler, case_dir.name)

    lc = list(RT.iter_consensus_cases(tmp_path, "light_client"))
    assert len(lc) == counts["merkle"]
    for fork, _handler, case_dir in lc:
        assert RT.run_merkle_proof_case("minimal", fork,
                                        case_dir) is True


@pytest.mark.slow
def test_verdicts_flip_on_divergence(tmp_path):
    """Corrupted expectations must FAIL, not skip: the gate's verdicts
    are real for every runner family."""
    from teku_tpu.spec.datastructures import Checkpoint
    cp = Checkpoint(epoch=7, root=b"\x5a" * 32)
    case = (tmp_path / "tests" / "minimal" / "phase0" / "ssz_static"
            / "Checkpoint" / "ssz_random" / "case_0")
    VA.write_snappy(case / "serialized.ssz_snappy",
                    Checkpoint.serialize(cp))
    (case / "roots.yaml").write_text("{root: '0x" + "ab" * 32 + "'}\n")
    assert RT.run_ssz_static_case("minimal", "phase0", "Checkpoint",
                                  case) is False
    bad = {"input": {"pubkey": "0x" + "11" * 48,
                     "message": "0x" + "22" * 32,
                     "signature": "0x" + "33" * 96},
           "output": True}
    assert RT.run_bls_case("verify", bad) is False
    # fork-choice: corrupt the expected head root after a valid build
    VA.build_fork_choice_case(tmp_path)
    case_dir = (tmp_path / "tests" / "minimal" / "phase0"
                / "fork_choice" / "on_block" / "pyspec_tests"
                / "case_0")
    steps = json.loads((case_dir / "steps.yaml").read_text())
    steps[-1]["checks"]["head"]["root"] = "0x" + "ee" * 32
    (case_dir / "steps.yaml").write_text(json.dumps(steps))
    assert RT.run_fork_choice_case("minimal", "phase0",
                                   case_dir) is False
    # shuffling: corrupt one mapping entry
    VA.build_shuffling_rewards_fork(tmp_path)
    shuf = (tmp_path / "tests" / "minimal" / "phase0" / "shuffling"
            / "core" / "shuffle" / "shuffle_case_0")
    data = json.loads((shuf / "mapping.yaml").read_text())
    data["mapping"][0] = (data["mapping"][0] + 1) % data["count"]
    (shuf / "mapping.yaml").write_text(json.dumps(data))
    assert RT.run_shuffling_case("minimal", "phase0", shuf) is False
