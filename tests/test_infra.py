"""Infra: event channels, async primitives, service lifecycle."""

import asyncio

import pytest

from teku_tpu.infra.aio import (finish, OrderedTaskQueue, RepeatingTask,
                                retry_with_backoff, ThrottlingTaskQueue)
from teku_tpu.infra.events import EventChannels, SlotEventsChannel
from teku_tpu.infra.service import Service, ServiceController, ServiceState


def test_event_channel_fanout_and_isolation():
    chans = EventChannels()
    seen = []

    class Good:
        def on_slot(self, slot):
            seen.append(slot)

    class Bad:
        def on_slot(self, slot):
            raise RuntimeError("boom")

    chans.subscribe(SlotEventsChannel, Bad())
    chans.subscribe(SlotEventsChannel, Good())
    chans.subscribe(SlotEventsChannel, Good())
    chans.publisher(SlotEventsChannel).on_slot(7)
    # the failing subscriber must not break the others
    assert seen == [7, 7]


def test_event_channel_unknown_event_rejected():
    chans = EventChannels()
    with pytest.raises(AttributeError):
        chans.publisher(SlotEventsChannel).on_bogus


def test_throttling_queue_bounds_concurrency():
    async def run():
        q = ThrottlingTaskQueue(2)
        active = 0
        peak = 0

        async def job():
            nonlocal active, peak
            active += 1
            peak = max(peak, active)
            await asyncio.sleep(0.01)
            active -= 1

        await asyncio.gather(*(q.run(job) for _ in range(8)))
        return peak
    assert asyncio.run(run()) == 2


def test_ordered_queue_serializes_and_asserts_ownership():
    async def run():
        q = OrderedTaskQueue()
        order = []

        async def job(i):
            q.check_in_queue()
            order.append(("start", i))
            await asyncio.sleep(0.005)
            order.append(("end", i))

        await asyncio.gather(*(q.run(lambda i=i: job(i)) for i in range(3)))
        # no interleaving: every start is immediately followed by its end
        for j in range(0, len(order), 2):
            assert order[j][0] == "start" and order[j + 1][0] == "end"
            assert order[j][1] == order[j + 1][1]
        with pytest.raises(AssertionError):
            q.check_in_queue()
    asyncio.run(run())


def test_retry_with_backoff():
    async def run():
        calls = {"n": 0}

        async def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ValueError("nope")
            return "ok"

        out = await retry_with_backoff(flaky, attempts=4,
                                       base_delay_s=0.001)
        assert out == "ok" and calls["n"] == 3

        async def always_fails():
            raise ValueError("always")
        with pytest.raises(RuntimeError):
            await retry_with_backoff(always_fails, attempts=2,
                                     base_delay_s=0.001)
    asyncio.run(run())


def test_service_lifecycle_and_controller_order():
    log = []

    class Svc(Service):
        def __init__(self, name):
            super().__init__(name)

        async def do_start(self):
            log.append(("start", self.name))

        async def do_stop(self):
            log.append(("stop", self.name))

    async def run():
        a, b = Svc("a"), Svc("b")
        ctl = ServiceController([a, b])
        await ctl.start()
        assert a.is_running and b.is_running
        with pytest.raises(RuntimeError):
            await a.start()     # double start forbidden
        await ctl.stop()
        assert log == [("start", "a"), ("start", "b"),
                       ("stop", "b"), ("stop", "a")]
    asyncio.run(run())


def test_controller_unwinds_on_start_failure():
    log = []

    class Svc(Service):
        async def do_start(self):
            log.append(("start", self.name))

        async def do_stop(self):
            log.append(("stop", self.name))

    class Broken(Service):
        async def do_start(self):
            raise RuntimeError("cannot start")

    async def run():
        a = Svc("a")
        ctl = ServiceController([a, Broken("x"), Svc("c")])
        with pytest.raises(RuntimeError):
            await ctl.start()
        assert log == [("start", "a"), ("stop", "a")]
    asyncio.run(run())


def test_finish_logs_but_does_not_raise():
    async def run():
        async def fails():
            raise ValueError("boom")
        t = finish(fails(), "background thing")
        await asyncio.sleep(0.01)
        assert t.done() and t.exception() is not None
    asyncio.run(run())


def test_repeating_task_ticks_and_stops():
    async def run():
        ticks = []

        async def tick():
            ticks.append(1)

        rt = RepeatingTask(0.005, tick)
        rt.start()
        await asyncio.sleep(0.03)
        await rt.stop()
        n = len(ticks)
        assert n >= 3
        await asyncio.sleep(0.02)
        assert len(ticks) == n   # no ticks after stop
    asyncio.run(run())
