"""SSZ engine: unit semantics + round-trip against REAL reference
fixtures.

The reference repo ships raw SSZ-encoded minimal-preset phase0 blocks
and attestations with YAML value companions
(/root/reference/fork-choice-tests/src/integration-test/resources/cache/).
Serializing the YAML values must reproduce the SSZ bytes exactly, and
each block's parent_root must equal the hash-tree-root of the previous
block's header — an end-to-end external check of both serialization and
merkleization.
"""

import os
from pathlib import Path

import pytest
import yaml

from teku_tpu.spec.datastructures import SCHEMAS_MINIMAL as S
from teku_tpu.ssz import (Bitlist, Bitvector, boolean, Bytes32, Container,
                          List, merkleize, mix_in_length, SszError, uint8,
                          uint16, uint64, Union, Vector, zero_hash)

CACHE = Path("/root/reference/fork-choice-tests/src/integration-test/"
             "resources/cache")


# --------------------------------------------------------------------------
# Unit semantics
# --------------------------------------------------------------------------

def test_uint_roundtrip_and_bounds():
    assert uint64.serialize(1) == b"\x01" + b"\x00" * 7
    assert uint64.deserialize(b"\xff" * 8) == 2 ** 64 - 1
    with pytest.raises(SszError):
        uint8.serialize(256)
    with pytest.raises(SszError):
        uint16.deserialize(b"\x00")  # wrong width


def test_uint_htr_is_padded_le():
    assert uint64.hash_tree_root(5) == (5).to_bytes(8, "little") + b"\x00" * 24


def test_boolean_strictness():
    with pytest.raises(SszError):
        boolean.deserialize(b"\x02")


def test_vector_of_uint64_htr_packs():
    v = Vector(uint64, 4)
    ser = v.serialize((1, 2, 3, 4))
    assert len(ser) == 32
    assert v.hash_tree_root((1, 2, 3, 4)) == ser  # single chunk, no hash


def test_list_htr_mixes_length():
    l4 = List(uint64, 4)
    root = merkleize([b"".join(
        u.to_bytes(8, "little") for u in (1, 2, 3, 4))], 1)
    assert l4.hash_tree_root((1, 2, 3, 4)) == mix_in_length(root, 4)
    assert l4.hash_tree_root(()) == mix_in_length(zero_hash(0), 0)


def test_bitlist_delimiter():
    b = Bitlist(8)
    assert b.serialize(()) == b"\x01"
    assert b.serialize((True,) * 3) == b"\x0f"
    assert b.deserialize(b"\x0f") == (True,) * 3
    with pytest.raises(SszError):
        b.deserialize(b"\x00")      # missing delimiter
    with pytest.raises(SszError):
        Bitlist(2).deserialize(b"\x0f")  # over limit


def test_bitvector_padding_bits_rejected():
    with pytest.raises(SszError):
        Bitvector(3).deserialize(b"\x0f")


def test_union_roundtrip():
    u = Union(None, uint64)
    assert u.deserialize(u.serialize((1, 7))) == (1, 7)
    assert u.serialize((0, None)) == b"\x00"


def test_container_offsets_strict():
    class VarC(Container):
        a: uint64
        b: List(uint64, 8)
        c: uint64

    v = VarC(a=1, b=(9, 10), c=2)
    data = VarC.serialize(v)
    assert VarC.deserialize(data) == v
    # corrupt the offset: must be rejected, not mis-parsed
    bad = bytearray(data)
    bad[8] = 0xFF
    with pytest.raises(SszError):
        VarC.deserialize(bytes(bad))


def test_container_immutability_and_copy():
    cp = S.Checkpoint(epoch=3, root=b"\x11" * 32)
    with pytest.raises(AttributeError):
        cp.epoch = 4
    cp2 = cp.copy_with(epoch=4)
    assert cp.epoch == 3 and cp2.epoch == 4 and cp2.root == cp.root


def test_htr_memoized_per_instance():
    cp = S.Checkpoint(epoch=3, root=b"\x11" * 32)
    r1 = cp.htr()
    assert cp.htr() is r1  # cached object, not recomputed


# --------------------------------------------------------------------------
# Reference fixtures (real serialized minimal-preset phase0 objects)
# --------------------------------------------------------------------------

def _h(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


def _attestation_from_yaml(d) -> "Container":
    def chk(c):
        return S.Checkpoint(epoch=c["epoch"], root=_h(c["root"]))
    bits_bytes = _h(d["aggregation_bits"])
    bits = S.Attestation._ssz_fields["aggregation_bits"].deserialize(
        bits_bytes)
    return S.Attestation(
        aggregation_bits=bits,
        data=S.AttestationData(
            slot=d["data"]["slot"], index=d["data"]["index"],
            beacon_block_root=_h(d["data"]["beacon_block_root"]),
            source=chk(d["data"]["source"]),
            target=chk(d["data"]["target"])),
        signature=_h(d["signature"]))


def _block_from_yaml(d) -> "Container":
    m = d["message"]
    b = m["body"]
    body = S.BeaconBlockBody(
        randao_reveal=_h(b["randao_reveal"]),
        eth1_data=S.Eth1Data(
            deposit_root=_h(b["eth1_data"]["deposit_root"]),
            deposit_count=b["eth1_data"]["deposit_count"],
            block_hash=_h(b["eth1_data"]["block_hash"])),
        graffiti=_h(b["graffiti"]),
        proposer_slashings=(),
        attester_slashings=(),
        attestations=tuple(_attestation_from_yaml(a)
                           for a in b["attestations"]),
        deposits=(),
        voluntary_exits=())
    assert not b["proposer_slashings"] and not b["deposits"]
    block = S.BeaconBlock(
        slot=m["slot"], proposer_index=m["proposer_index"],
        parent_root=_h(m["parent_root"]), state_root=_h(m["state_root"]),
        body=body)
    return S.SignedBeaconBlock(message=block, signature=_h(d["signature"]))


needs_fixtures = pytest.mark.skipif(
    not CACHE.is_dir(), reason="reference fixtures not present")


@needs_fixtures
def test_attestation_fixtures_roundtrip():
    n = 0
    for ssz_path in sorted(CACHE.glob("attestation_*.ssz")):
        data = ssz_path.read_bytes()
        with open(ssz_path.with_suffix(".yaml")) as f:
            val = _attestation_from_yaml(yaml.safe_load(f))
        assert S.Attestation.serialize(val) == data, ssz_path.name
        assert S.Attestation.deserialize(data) == val
        n += 1
    assert n >= 10


@needs_fixtures
def test_block_fixtures_roundtrip():
    n = 0
    for ssz_path in sorted(CACHE.glob("block_*.ssz")):
        data = ssz_path.read_bytes()
        with open(ssz_path.with_suffix(".yaml")) as f:
            val = _block_from_yaml(yaml.safe_load(f))
        assert S.SignedBeaconBlock.serialize(val) == data, ssz_path.name
        assert S.SignedBeaconBlock.deserialize(data) == val
        n += 1
    assert n >= 10


@needs_fixtures
def test_block_parent_roots_match_header_htr():
    """block[i].parent_root must equal HTR of block[j]'s header for some
    ancestor j — an external validation of hash_tree_root."""
    blocks = {}
    for ssz_path in CACHE.glob("block_*.ssz"):
        blk = S.SignedBeaconBlock.deserialize(ssz_path.read_bytes()).message
        header = S.BeaconBlockHeader(
            slot=blk.slot, proposer_index=blk.proposer_index,
            parent_root=blk.parent_root, state_root=blk.state_root,
            body_root=blk.body.htr())
        blocks[header.htr()] = blk
    linked = sum(1 for blk in blocks.values()
                 if blk.parent_root in blocks)
    # the cache holds several fork branches and not every parent, but a
    # large majority of parent_roots must resolve to a computed header
    # HTR — each link is an exact 32-byte match, so even one link is
    # strong evidence and dozens are conclusive
    assert linked >= len(blocks) * 2 // 3
    assert len(blocks) >= 10
