"""Blob sidecar pool + KZG availability gate."""

import random

import pytest

from teku_tpu.crypto import kzg
from teku_tpu.node.blobs import (AvailabilityResult, BlobSidecar,
                                 BlobSidecarPool, MAX_BLOBS_PER_BLOCK)

SETUP = kzg.insecure_setup()


def _blob(seed):
    rng = random.Random(seed)
    return b"".join(rng.randrange(kzg.R).to_bytes(32, "big")
                    for _ in range(kzg.FIELD_ELEMENTS_PER_BLOB))


def _sidecar(block_root, index, seed, tamper=False):
    blob = _blob(seed)
    commitment = kzg.blob_to_kzg_commitment(blob, SETUP)
    proof = kzg.compute_blob_kzg_proof(blob, commitment, SETUP)
    if tamper:
        proof = b"\xc0" + proof[1:]
    return BlobSidecar(index=index, blob=blob, kzg_commitment=commitment,
                       kzg_proof=proof, block_root=block_root,
                       slot=7), commitment


def test_collect_and_availability():
    pool = BlobSidecarPool(SETUP)
    root = b"\x01" * 32
    s0, c0 = _sidecar(root, 0, 1)
    s1, c1 = _sidecar(root, 1, 2)
    assert pool.check_availability(root, [c0, c1]) == \
        AvailabilityResult.PENDING
    assert pool.add_sidecar(s0)
    assert not pool.add_sidecar(s0)                 # dedupe per index
    assert pool.check_availability(root, [c0, c1]) == \
        AvailabilityResult.PENDING                   # one still missing
    assert pool.add_sidecar(s1)
    assert pool.check_availability(root, [c0, c1]) == \
        AvailabilityResult.AVAILABLE
    assert [s.index for s in pool.sidecars_for(root)] == [0, 1]
    # no commitments == trivially available (pre-deneb blocks)
    assert pool.check_availability(b"\x09" * 32, []) == \
        AvailabilityResult.AVAILABLE


def test_bad_proof_rejected_at_entry_and_cannot_brick_the_block():
    pool = BlobSidecarPool(SETUP)
    root = b"\x02" * 32
    bad, c0 = _sidecar(root, 0, 3, tamper=True)
    assert not pool.add_sidecar(bad)       # proof checked at the door
    assert pool.check_availability(root, [c0]) == \
        AvailabilityResult.PENDING
    # the honest sidecar still lands (no first-wins shadowing)
    good, _ = _sidecar(root, 0, 3)
    assert pool.add_sidecar(good)
    assert pool.check_availability(root, [c0]) == \
        AvailabilityResult.AVAILABLE


def test_commitment_mismatch_stays_pending():
    """A valid sidecar for a DIFFERENT commitment must not satisfy (or
    poison) the block's slot — without its real blob the block is
    simply not yet available."""
    pool = BlobSidecarPool(SETUP)
    root = b"\x03" * 32
    s0, _ = _sidecar(root, 0, 4)
    pool.add_sidecar(s0)
    other_commitment = kzg.blob_to_kzg_commitment(_blob(99), SETUP)
    assert pool.check_availability(root, [other_commitment]) == \
        AvailabilityResult.PENDING


def test_prune_clears_verdicts():
    pool = BlobSidecarPool(SETUP)
    root = b"\x05" * 32
    s0, c0 = _sidecar(root, 0, 6)
    pool.add_sidecar(s0)
    assert pool.check_availability(root, [c0]) == \
        AvailabilityResult.AVAILABLE
    pool.prune_block(root)
    assert pool.check_availability(root, [c0]) == \
        AvailabilityResult.PENDING


def test_malformed_sidecars_rejected():
    pool = BlobSidecarPool(SETUP)
    root = b"\x04" * 32
    s, _ = _sidecar(root, 0, 5)
    assert not pool.add_sidecar(s.copy_with(index=MAX_BLOBS_PER_BLOCK))
    assert not pool.add_sidecar(s.copy_with(blob=b"\x00" * 100))