"""Blob sidecar pool + KZG availability gate."""

import random

import pytest

from teku_tpu.crypto import kzg
from teku_tpu.node.blobs import (AvailabilityResult, BlobSidecar,
                                 BlobSidecarPool, MAX_BLOBS_PER_BLOCK)

SETUP = kzg.insecure_setup()


def _blob(seed):
    rng = random.Random(seed)
    return b"".join(rng.randrange(kzg.R).to_bytes(32, "big")
                    for _ in range(kzg.FIELD_ELEMENTS_PER_BLOB))


def _sidecar(block_root, index, seed, tamper=False):
    blob = _blob(seed)
    commitment = kzg.blob_to_kzg_commitment(blob, SETUP)
    proof = kzg.compute_blob_kzg_proof(blob, commitment, SETUP)
    if tamper:
        proof = b"\xc0" + proof[1:]
    return BlobSidecar(index=index, blob=blob, kzg_commitment=commitment,
                       kzg_proof=proof, block_root=block_root,
                       slot=7), commitment


def test_collect_and_availability():
    pool = BlobSidecarPool(SETUP)
    root = b"\x01" * 32
    s0, c0 = _sidecar(root, 0, 1)
    s1, c1 = _sidecar(root, 1, 2)
    assert pool.check_availability(root, [c0, c1]) == \
        AvailabilityResult.PENDING
    assert pool.add_sidecar(s0)
    assert not pool.add_sidecar(s0)                 # dedupe per index
    assert pool.check_availability(root, [c0, c1]) == \
        AvailabilityResult.PENDING                   # one still missing
    assert pool.add_sidecar(s1)
    assert pool.check_availability(root, [c0, c1]) == \
        AvailabilityResult.AVAILABLE
    assert [s.index for s in pool.sidecars_for(root)] == [0, 1]
    # no commitments == trivially available (pre-deneb blocks)
    assert pool.check_availability(b"\x09" * 32, []) == \
        AvailabilityResult.AVAILABLE


def test_bad_proof_rejected_at_entry_and_cannot_brick_the_block():
    pool = BlobSidecarPool(SETUP)
    root = b"\x02" * 32
    bad, c0 = _sidecar(root, 0, 3, tamper=True)
    assert not pool.add_sidecar(bad)       # proof checked at the door
    assert pool.check_availability(root, [c0]) == \
        AvailabilityResult.PENDING
    # the honest sidecar still lands (no first-wins shadowing)
    good, _ = _sidecar(root, 0, 3)
    assert pool.add_sidecar(good)
    assert pool.check_availability(root, [c0]) == \
        AvailabilityResult.AVAILABLE


def test_commitment_mismatch_stays_pending():
    """A valid sidecar for a DIFFERENT commitment must not satisfy (or
    poison) the block's slot — without its real blob the block is
    simply not yet available."""
    pool = BlobSidecarPool(SETUP)
    root = b"\x03" * 32
    s0, _ = _sidecar(root, 0, 4)
    pool.add_sidecar(s0)
    other_commitment = kzg.blob_to_kzg_commitment(_blob(99), SETUP)
    assert pool.check_availability(root, [other_commitment]) == \
        AvailabilityResult.PENDING


def test_prune_clears_verdicts():
    pool = BlobSidecarPool(SETUP)
    root = b"\x05" * 32
    s0, c0 = _sidecar(root, 0, 6)
    pool.add_sidecar(s0)
    assert pool.check_availability(root, [c0]) == \
        AvailabilityResult.AVAILABLE
    pool.prune_block(root)
    assert pool.check_availability(root, [c0]) == \
        AvailabilityResult.PENDING


def test_malformed_sidecars_rejected():
    pool = BlobSidecarPool(SETUP)
    root = b"\x04" * 32
    s, _ = _sidecar(root, 0, 5)
    assert not pool.add_sidecar(s.copy_with(index=MAX_BLOBS_PER_BLOCK))
    assert not pool.add_sidecar(s.copy_with(blob=b"\x00" * 100))

# ---- deneb wire-format sidecars (inclusion proof + gossip validation) ----

def _wire_sidecars(cfg, seeds):
    """A deneb signed block carrying len(seeds) real commitments, plus
    its wire sidecars."""
    from teku_tpu.spec.deneb.datastructures import (get_deneb_schemas,
                                                    make_blob_sidecars)
    S = get_deneb_schemas(cfg)
    blobs = [_blob(s) for s in seeds]
    commitments = [kzg.blob_to_kzg_commitment(b, SETUP) for b in blobs]
    proofs = [kzg.compute_blob_kzg_proof(b, c, SETUP)
              for b, c in zip(blobs, commitments)]
    body = S.BeaconBlockBody(blob_kzg_commitments=tuple(commitments))
    block = S.BeaconBlock(slot=9, proposer_index=0,
                          parent_root=b"\x04" * 32,
                          state_root=b"\x05" * 32, body=body)
    signed = S.SignedBeaconBlock(message=block, signature=b"\x06" * 96)
    return signed, make_blob_sidecars(cfg, signed, blobs, proofs)


def test_spec_sidecar_validation_and_pool():
    import dataclasses
    from teku_tpu.spec import config as C
    from teku_tpu.node.blobs import validate_spec_sidecar
    cfg = C.MINIMAL
    signed, sidecars = _wire_sidecars(cfg, [11, 12])
    seen = set()
    assert validate_spec_sidecar(cfg, sidecars[0], setup=SETUP,
                                 seen=seen) == "accept"
    # replays are IGNOREd, not rejected
    assert validate_spec_sidecar(cfg, sidecars[0], setup=SETUP,
                                 seen=seen) == "ignore"
    # bad inclusion proof -> reject
    bad = sidecars[1].copy_with(kzg_commitment=b"\xee" * 48)
    assert validate_spec_sidecar(cfg, bad, setup=SETUP) == "reject"
    # index out of bounds -> reject
    oob = sidecars[1].copy_with(index=cfg.MAX_BLOBS_PER_BLOCK)
    assert validate_spec_sidecar(cfg, oob, setup=SETUP) == "reject"

    pool = BlobSidecarPool(SETUP)
    for sc in sidecars:
        assert pool.add_spec_sidecar(cfg, sc)
    root = signed.message.htr()
    body = signed.message.body
    assert pool.check_availability(
        root, list(body.blob_kzg_commitments)) == \
        AvailabilityResult.AVAILABLE
    wire = pool.wire_sidecars_for(root)
    assert [w.index for w in wire] == [0, 1]
    assert wire[0] == sidecars[0]


def test_blob_sidecars_rpc_serving():
    """BeaconRpc serves deneb sidecars from the pool by root and range."""
    import asyncio
    import types
    # teku_tpu.networking imports the noise transport, whose AEAD
    # primitives need the optional `cryptography` wheel
    pytest.importorskip(
        "cryptography",
        reason="networking stack needs the optional cryptography wheel")
    from teku_tpu.spec import config as C
    from teku_tpu.networking import reqresp as rr

    cfg = C.MINIMAL
    signed, sidecars = _wire_sidecars(cfg, [21, 22])
    root = signed.message.htr()
    pool = BlobSidecarPool(SETUP)
    for sc in sidecars:
        assert pool.add_spec_sidecar(cfg, sc)

    block = signed.message
    store = types.SimpleNamespace(blocks={root: block},
                                  signed_blocks={root: signed})
    chain = types.SimpleNamespace(head_root=root)
    spec = types.SimpleNamespace(config=cfg)
    node = types.SimpleNamespace(store=store, chain=chain, spec=spec,
                                 blob_pool=pool)
    net = types.SimpleNamespace(on_request=None)
    rpc = rr.BeaconRpc(net, node)
    peer = types.SimpleNamespace()

    from teku_tpu.networking import encoding as E
    from teku_tpu.spec.deneb.datastructures import get_deneb_schemas
    schema = get_deneb_schemas(cfg).BlobSidecar

    async def run():
        body = E.encode_payload(root + (1).to_bytes(8, "little"))
        resp = await net.on_request(peer, rr.BLOB_SIDECARS_BY_ROOT, body)
        chunks = rr._unpack_chunks(resp)
        assert len(chunks) == 1
        assert schema.deserialize(chunks[0]) == sidecars[1]

        import struct
        body = E.encode_payload(struct.pack("<QQ", 0, 32))
        resp = await net.on_request(peer, rr.BLOB_SIDECARS_BY_RANGE, body)
        chunks = rr._unpack_chunks(resp)
        assert [schema.deserialize(c).index for c in chunks] == [0, 1]

    asyncio.run(run())


def test_block_import_gated_on_blob_availability():
    """A deneb block with commitments parks until every sidecar is in
    the pool (reference ForkChoiceBlobSidecarsAvailabilityChecker)."""
    import asyncio
    import dataclasses
    from teku_tpu.spec import config as C
    from teku_tpu.spec import Spec
    from teku_tpu.spec.genesis import interop_genesis
    from teku_tpu.node.node import BeaconNode
    from teku_tpu.node.gossip import InMemoryGossipNetwork

    cfg = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0,
                              BELLATRIX_FORK_EPOCH=0, CAPELLA_FORK_EPOCH=0,
                              DENEB_FORK_EPOCH=0)
    spec = Spec(cfg)
    state, sks = interop_genesis(cfg, 16)
    net = InMemoryGossipNetwork()
    node = BeaconNode(spec, state, net.endpoint())
    node.blob_pool._setup = SETUP

    S = spec.at_slot(0).schemas
    signed, sidecars = _wire_sidecars(cfg, [31])
    # re-root the block onto the node's head so only availability gates
    block = signed.message.copy_with(parent_root=node.chain.head_root,
                                     slot=0)
    signed = S.SignedBeaconBlock(message=block,
                                 signature=signed.signature)
    root = block.htr()
    bm = node.block_manager
    assert not bm.import_block(signed)
    assert root in bm._awaiting_blobs      # parked, not dropped
    # sidecars arrive (rebuilt against the re-rooted block)
    from teku_tpu.spec.deneb.datastructures import make_blob_sidecars
    blob = _blob(31)
    commitment = kzg.blob_to_kzg_commitment(blob, SETUP)
    proof = kzg.compute_blob_kzg_proof(blob, commitment, SETUP)
    for sc in make_blob_sidecars(cfg, signed, [blob], [proof]):
        assert node.blob_pool.add_spec_sidecar(cfg, sc)
    bm.retry_pending_blobs()
    # unparked: availability passed (the import itself then fails on
    # the junk payload, which is the transition's job, not the gate's)
    assert root not in bm._awaiting_blobs


def test_da_gate_skipped_outside_retention_window():
    """Blocks in epochs older than MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS
    import without sidecars — peers prune wire sidecars, so gating
    historical blocks would wedge any deep sync (spec is_data_available
    horizon, epoch-granular like the reference's availability check)."""
    import dataclasses
    from teku_tpu.spec import config as C
    from teku_tpu.spec import Spec
    from teku_tpu.spec.genesis import interop_genesis
    from teku_tpu.node.node import BeaconNode
    from teku_tpu.node.gossip import InMemoryGossipNetwork

    cfg = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0,
                              BELLATRIX_FORK_EPOCH=0, CAPELLA_FORK_EPOCH=0,
                              DENEB_FORK_EPOCH=0,
                              MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS=2)
    spec = Spec(cfg)
    state, sks = interop_genesis(cfg, 16)
    net = InMemoryGossipNetwork()
    node = BeaconNode(spec, state, net.endpoint())
    node.blob_pool._setup = SETUP
    bm = node.block_manager

    S = spec.at_slot(0).schemas
    signed, _ = _wire_sidecars(cfg, [31])
    block = signed.message.copy_with(parent_root=node.chain.head_root,
                                     slot=0)
    signed = S.SignedBeaconBlock(message=block,
                                 signature=signed.signature)
    root = block.htr()
    # boundary epoch (epoch 0 + window >= current epoch): still gated
    window_epochs = cfg.MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS
    boundary = window_epochs * cfg.SLOTS_PER_EPOCH
    node.chain.store.on_tick(state.genesis_time
                             + boundary * cfg.SECONDS_PER_SLOT)
    assert bm._within_da_window(0)
    assert not bm.import_block(signed)
    assert root in bm._awaiting_blobs        # parked on availability
    bm._awaiting_blobs.pop(root)
    bm._n_pending -= 1
    # one epoch past the boundary: the gate is skipped entirely — the
    # block reaches the transition (which rejects its junk payload)
    # instead of parking for sidecars that no peer still serves
    node.chain.store.on_tick(
        state.genesis_time
        + (boundary + cfg.SLOTS_PER_EPOCH) * cfg.SECONDS_PER_SLOT)
    assert not bm._within_da_window(0)
    assert not bm.import_block(signed)
    assert root not in bm._awaiting_blobs    # not parked: gate skipped
