"""Device hash-to-G2 vs the oracle (which carries RFC 9380 vectors)."""

import jax
import numpy as np

from teku_tpu.crypto.bls import curve as C
from teku_tpu.crypto.bls import hash_to_curve as OH
from teku_tpu.ops import h2c
from teku_tpu.ops import points as PT
from teku_tpu.ops import towers as T

MSGS = [b"", b"abc", b"hello world", b"\x00" * 32, b"q" * 100]


def test_map_to_curve_matches_oracle():
    us = []
    for m in MSGS:
        us.extend(OH.hash_to_field_fq2(m, 2))
    dev = (np.stack([np.asarray(T.fq2_const(u)[0]) for u in us]),
           np.stack([np.asarray(T.fq2_const(u)[1]) for u in us]))
    x, y = jax.jit(h2c.map_to_curve_sswu)(dev)
    for i, u in enumerate(us):
        ex, ey = OH.map_to_curve_sswu_g2(u)
        assert T.fq2_from_device(x, (i,)) == ex
        assert T.fq2_from_device(y, (i,)) == ey


def test_full_hash_to_g2_matches_oracle():
    u0, u1 = h2c.messages_to_fields(MSGS)
    out = jax.jit(h2c.hash_to_g2_device)(u0, u1)
    for i, m in enumerate(MSGS):
        got = PT.g2_from_device(out, (i,))
        expect = OH.hash_to_g2(m)
        assert C.point_eq(C.FQ2_OPS, got, expect)
