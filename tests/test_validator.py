"""Validator stack: EIP-2335 keystores against the reference's own test
vectors, slashing protection semantics, duty-signing client wiring."""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio
import json
from pathlib import Path

import pytest

from teku_tpu.validator.keystore import (decrypt, encrypt, KeystoreError,
                                         load_directory)
from teku_tpu.validator.signer import (LocalSigner, SigningError,
                                       SlashingProtectedSigner)
from teku_tpu.validator.slashing_protection import (SigningRecord,
                                                    SlashingProtector)

VECTORS = Path("/root/reference/infrastructure/bls-keystore/src/test/"
               "resources/tech/pegasys/teku/bls/keystore")

# EIP-2335 official test password (mathematical-fraktur "testpassword" +
# U+1F511) and secret, as pinned by the reference's KeyStoreTest.java:48-50
EIP2335_PASSWORD = ("\U0001D531\U0001D522\U0001D530\U0001D531\U0001D52D"
                    "\U0001D51E\U0001D530\U0001D530\U0001D534\U0001D52C"
                    "\U0001D52F\U0001D521\U0001F511")
EIP2335_SECRET = bytes.fromhex(
    "000000000019d6689c085ae165831e934ff763ae46a2a6c172b3f1b60a8ce26f")

needs_vectors = pytest.mark.skipif(not VECTORS.is_dir(),
                                   reason="reference vectors not present")


@needs_vectors
def test_pbkdf2_official_vector():
    ks = json.loads((VECTORS / "pbkdf2TestVector.json").read_text())
    assert decrypt(ks, EIP2335_PASSWORD) == EIP2335_SECRET


@needs_vectors
@pytest.mark.slow
def test_scrypt_official_vector():
    ks = json.loads((VECTORS / "scryptTestVector.json").read_text())
    assert decrypt(ks, EIP2335_PASSWORD) == EIP2335_SECRET


@needs_vectors
def test_wrong_password_rejected():
    ks = json.loads((VECTORS / "pbkdf2TestVector.json").read_text())
    with pytest.raises(KeystoreError, match="checksum"):
        decrypt(ks, "wrong password")


@needs_vectors
def test_unsupported_variants_rejected():
    for name in ("unsupportedChecksumFunction.json",
                 "unsupportedCipherFunction.json",
                 "unsupportedKdfFunction.json",
                 "unsupportedPBKDF2Prf.json",
                 "v3TestVector.json"):
        ks = json.loads((VECTORS / name).read_text())
        with pytest.raises((KeystoreError, KeyError)):
            decrypt(ks, EIP2335_PASSWORD)


def test_encrypt_roundtrip_pbkdf2():
    secret = bytes(range(32))
    ks = encrypt(secret, "hunter2 🔐", kdf="pbkdf2")
    assert decrypt(ks, "hunter2 🔐") == secret
    with pytest.raises(KeystoreError):
        decrypt(ks, "hunter3")


def test_load_directory(tmp_path):
    keys = tmp_path / "keys"
    pws = tmp_path / "passwords"
    keys.mkdir(), pws.mkdir()
    secret = b"\x01" * 32
    ks = encrypt(secret, "pw", kdf="pbkdf2", pubkey=b"\xaa" * 48)
    (keys / "v1.json").write_text(json.dumps(ks))
    (pws / "v1.txt").write_text("pw\n")
    loaded = load_directory(keys, pws)
    assert loaded == {b"\xaa" * 48: int.from_bytes(secret, "big")}


# --------------------------------------------------------------------------
# Slashing protection
# --------------------------------------------------------------------------

def test_signing_record_rules():
    r = SigningRecord()
    assert r.may_sign_attestation(0, 1)
    r = SigningRecord(block_slot=5, source_epoch=2, target_epoch=3)
    assert not r.may_sign_block(5)          # same slot = double proposal
    assert r.may_sign_block(6)
    assert not r.may_sign_attestation(1, 4)  # source regression = surround
    assert not r.may_sign_attestation(2, 3)  # same target = double vote
    assert not r.may_sign_attestation(4, 3)  # source > target
    assert r.may_sign_attestation(2, 4)


def test_protector_persists(tmp_path):
    pk = b"\xbb" * 48
    p1 = SlashingProtector(tmp_path)
    assert p1.may_sign_block(pk, 10)
    assert p1.may_sign_attestation(pk, 1, 2)
    # reload from disk: records survive a restart
    p2 = SlashingProtector(tmp_path)
    assert not p2.may_sign_block(pk, 10)
    assert not p2.may_sign_attestation(pk, 1, 2)
    assert p2.may_sign_block(pk, 11)


def test_interchange_roundtrip(tmp_path):
    gvr = b"\x11" * 32
    p1 = SlashingProtector()
    pk = b"\xcc" * 48
    p1.may_sign_block(pk, 42)
    p1.may_sign_attestation(pk, 5, 6)
    doc = p1.export_interchange(gvr)
    assert doc["metadata"]["interchange_format_version"] == "5"
    p2 = SlashingProtector()
    assert p2.import_interchange(doc, gvr) == 1
    assert not p2.may_sign_block(pk, 42)
    with pytest.raises(ValueError):
        p2.import_interchange(doc, b"\x22" * 32)


# --------------------------------------------------------------------------
# Slashing-protected signer refuses conflicting duties
# --------------------------------------------------------------------------

def test_protected_signer_refuses_double_attestation():
    from teku_tpu.spec import config as C
    from teku_tpu.spec.genesis import interop_genesis
    from teku_tpu.spec.datastructures import AttestationData, Checkpoint
    cfg = C.MINIMAL
    state, sks = interop_genesis(cfg, 4)
    signer = SlashingProtectedSigner(
        LocalSigner({0: sks[0]}), SlashingProtector())
    data = AttestationData(
        slot=8, index=0, beacon_block_root=b"\x01" * 32,
        source=Checkpoint(epoch=0, root=bytes(32)),
        target=Checkpoint(epoch=1, root=b"\x02" * 32))
    sig = signer.sign_attestation_data(cfg, state, data, 0)
    assert len(sig) == 96
    # same target epoch, different data: must refuse
    data2 = data.copy_with(beacon_block_root=b"\x03" * 32)
    with pytest.raises(SigningError):
        signer.sign_attestation_data(cfg, state, data2, 0)
