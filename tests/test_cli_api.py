"""CLI subcommands + REST API served over real HTTP."""

import asyncio
import json
import urllib.request

import pytest

from teku_tpu.cli import main
from teku_tpu.spec import create_spec


def test_genesis_and_transition_roundtrip(tmp_path):
    gen = tmp_path / "genesis.ssz"
    assert main(["genesis", "--validators", "16", "--out", str(gen)]) == 0
    spec = create_spec("minimal")
    state = spec.schemas.BeaconState.deserialize(gen.read_bytes())
    assert len(state.validators) == 16

    # build one block offline and run the transition subcommand over it
    from teku_tpu.spec.builder import make_local_signer, produce_block
    from teku_tpu.spec.genesis import interop_genesis
    st, sks = interop_genesis(spec.config, 16)
    signed, post = produce_block(
        spec.config, st, 1, make_local_signer(dict(enumerate(sks))))
    blk = tmp_path / "block1.ssz"
    blk.write_bytes(spec.schemas.SignedBeaconBlock.serialize(signed))
    out = tmp_path / "post.ssz"
    assert main(["transition", "--pre", str(gen), "--post", str(out),
                 str(blk)]) == 0
    result = spec.schemas.BeaconState.deserialize(out.read_bytes())
    assert result.htr() == post.htr()


def test_transition_rejects_bad_block(tmp_path, capsys):
    gen = tmp_path / "g.ssz"
    main(["genesis", "--validators", "16", "--out", str(gen)])
    spec = create_spec("minimal")
    from teku_tpu.spec.builder import make_local_signer, produce_block
    from teku_tpu.spec.genesis import interop_genesis
    st, sks = interop_genesis(spec.config, 16)
    signed, _ = produce_block(
        spec.config, st, 1, make_local_signer(dict(enumerate(sks))))
    bad = signed.copy_with(signature=b"\x11" + signed.signature[1:])
    blk = tmp_path / "bad.ssz"
    blk.write_bytes(spec.schemas.SignedBeaconBlock.serialize(bad))
    assert main(["transition", "--pre", str(gen),
                 "--post", str(tmp_path / "p.ssz"), str(blk)]) == 1


def test_slashing_protection_interchange(tmp_path):
    d1, d2 = tmp_path / "a", tmp_path / "b"
    from teku_tpu.validator.slashing_protection import SlashingProtector
    p = SlashingProtector(d1)
    p.may_sign_block(b"\xaa" * 48, 5)
    f = tmp_path / "interchange.json"
    assert main(["slashing-protection", "export", "--data-dir", str(d1),
                 "--file", str(f)]) == 0
    assert main(["slashing-protection", "import", "--data-dir", str(d2),
                 "--file", str(f)]) == 0
    p2 = SlashingProtector(d2)
    assert not p2.may_sign_block(b"\xaa" * 48, 5)


@pytest.mark.slow
def test_devnet_subcommand_finalizes():
    assert main(["devnet", "--nodes", "2", "--validators", "16",
                 "--epochs", "4"]) == 0


@pytest.mark.slow
def test_rest_api_over_http():
    from teku_tpu.api import BeaconRestApi
    from teku_tpu.node import Devnet
    from teku_tpu.validator import BeaconNodeValidatorApi

    async def run():
        net = Devnet(n_nodes=1, n_validators=16)
        await net.start()
        api = BeaconRestApi(
            net.nodes[0],
            validator_api=BeaconNodeValidatorApi(net.nodes[0]))
        await api.start()
        try:
            await net.run_until_slot(net.spec.config.SLOTS_PER_EPOCH + 2)

            def fetch(path):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{api.port}{path}",
                        timeout=5) as r:
                    body = r.read()
                    if r.headers.get_content_type() == "application/json":
                        return json.loads(body)
                    return body

            # run blocking urllib in a thread so the server can serve
            loop = asyncio.get_running_loop()
            health = await loop.run_in_executor(
                None, fetch, "/eth/v1/node/health")
            assert health == {}
            genesis = await loop.run_in_executor(
                None, fetch, "/eth/v1/beacon/genesis")
            assert genesis["data"]["genesis_validators_root"].startswith(
                "0x")
            syncing = await loop.run_in_executor(
                None, fetch, "/eth/v1/node/syncing")
            assert syncing["data"]["is_syncing"] is False
            header = await loop.run_in_executor(
                None, fetch, "/eth/v1/beacon/headers/head")
            assert int(header["data"]["header"]["message"]["slot"]) >= 1
            fin = await loop.run_in_executor(
                None, fetch,
                "/eth/v1/beacon/states/head/finality_checkpoints")
            assert "finalized" in fin["data"]
            duties = await loop.run_in_executor(
                None, fetch, "/eth/v1/validator/duties/proposer/1")
            assert len(duties["data"]) == net.spec.config.SLOTS_PER_EPOCH
            metrics = await loop.run_in_executor(None, fetch, "/metrics")
            assert b"signature_verifications" in metrics
            vals = await loop.run_in_executor(
                None, fetch, "/eth/v1/beacon/states/head/validators")
            assert len(vals["data"]) == 16
            # 404 mapping
            try:
                await loop.run_in_executor(
                    None, fetch, "/eth/v1/beacon/headers/0x" + "ab" * 32)
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as exc:
                assert exc.code == 404
        finally:
            await api.stop()
            await net.stop()
    asyncio.run(run())


@pytest.mark.slow
def test_events_sse_stream():
    """/eth/v1/events streams head/block events as the chain advances."""
    from teku_tpu.api import BeaconRestApi
    from teku_tpu.node import Devnet

    async def run():
        net = Devnet(n_nodes=1, n_validators=16)
        await net.start()
        api = BeaconRestApi(net.nodes[0])
        await api.start()
        try:
            import socket
            loop = asyncio.get_running_loop()
            lines = []

            def reader():
                s = socket.create_connection(
                    ("127.0.0.1", api.port), timeout=10)
                s.sendall(b"GET /eth/v1/events?topics=head,block "
                          b"HTTP/1.1\r\nHost: x\r\n\r\n")
                buf = b""
                s.settimeout(10)
                try:
                    while buf.count(b"\n\n") < 5:
                        chunk = s.recv(4096)
                        if not chunk:
                            break
                        buf += chunk
                except socket.timeout:
                    pass
                finally:
                    s.close()
                lines.extend(buf.decode(errors="replace").splitlines())

            task = loop.run_in_executor(None, reader)
            await asyncio.sleep(0.2)       # let the GET register
            await net.run_until_slot(3)
            await task
            events = [l for l in lines if l.startswith("event: ")]
            datas = [l for l in lines if l.startswith("data: ")]
            assert any("head" in e for e in events), lines[:10]
            assert any("block" in e for e in events)
            head = json.loads(next(
                d for e, d in zip(events, datas) if "head" in e)[6:])
            assert int(head["slot"]) >= 1
            assert head["block"].startswith("0x")
        finally:
            await api.stop()
            await net.stop()
    asyncio.run(run())


def test_duty_and_committee_endpoints():
    """The endpoints a remote VC lives off (reference handlers/v1/
    validator/PostSyncDuties.java:43, PostValidatorLiveness.java,
    v1/beacon/GetStateCommittees.java, v1/config/GetForkSchedule)."""
    import dataclasses
    from teku_tpu.api import BeaconRestApi
    from teku_tpu.node.gossip import InMemoryGossipNetwork
    from teku_tpu.node.node import BeaconNode
    from teku_tpu.spec import config as C, Spec
    from teku_tpu.spec.genesis import interop_genesis
    from teku_tpu.validator import BeaconNodeValidatorApi

    spec = Spec(dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0))
    state, sks = interop_genesis(spec.config, 16)

    async def run():
        net = InMemoryGossipNetwork()
        node = BeaconNode(spec, state, net.endpoint())
        api = BeaconRestApi(node,
                            validator_api=BeaconNodeValidatorApi(node))
        await api.start()
        try:
            base = f"http://127.0.0.1:{api.port}"
            loop = asyncio.get_running_loop()

            def post(path, payload):
                req = urllib.request.Request(
                    base + path, data=json.dumps(payload).encode(),
                    method="POST",
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5) as r:
                    return json.loads(r.read())

            def get(path):
                with urllib.request.urlopen(base + path, timeout=5) as r:
                    return json.loads(r.read())

            sync = await loop.run_in_executor(
                None, post, "/eth/v1/validator/duties/sync/0",
                [str(i) for i in range(16)])
            # minimal preset: committee of 32 seats over 16 validators —
            # everyone sits somewhere, positions are seat indices
            assert len(sync["data"]) == 16
            seats = sum(len(d["validator_sync_committee_indices"])
                        for d in sync["data"])
            assert seats == spec.config.SYNC_COMMITTEE_SIZE

            committees = await loop.run_in_executor(
                None, get, "/eth/v1/beacon/states/head/committees")
            assert committees["data"]
            one = committees["data"][0]
            assert {"index", "slot", "validators"} <= set(one)
            filtered = await loop.run_in_executor(
                None, get,
                f"/eth/v1/beacon/states/head/committees"
                f"?slot={one['slot']}&index={one['index']}")
            assert filtered["data"] == [one]

            sc = await loop.run_in_executor(
                None, get, "/eth/v1/beacon/states/head/sync_committees")
            assert len(sc["data"]["validators"]) == \
                spec.config.SYNC_COMMITTEE_SIZE

            live = await loop.run_in_executor(
                None, post, "/eth/v1/validator/liveness/0",
                ["0", "1"])
            assert [d["index"] for d in live["data"]] == ["0", "1"]
            assert all(d["is_live"] is False for d in live["data"])

            forks = await loop.run_in_executor(
                None, get, "/eth/v1/config/fork_schedule")
            assert forks["data"][0]["epoch"] == "0"
            assert forks["data"][0]["current_version"].startswith("0x")
        finally:
            await api.stop()
    asyncio.run(run())


def test_voluntary_exit_subcommand():
    """`voluntary-exit` signs with the interop key and lands in the
    node's exit pool through the REST pool endpoint (reference
    cli/subcommand/VoluntaryExitCommand.java)."""
    import dataclasses
    import types
    from teku_tpu.api import BeaconRestApi
    from teku_tpu.cli import cmd_voluntary_exit
    from teku_tpu.node.gossip import InMemoryGossipNetwork
    from teku_tpu.node.node import BeaconNode
    from teku_tpu.spec import config as C, Spec
    from teku_tpu.spec.genesis import interop_genesis
    from teku_tpu.spec.transition import process_slots

    # exits need SHARD_COMMITTEE_PERIOD epochs of service
    cfg = dataclasses.replace(C.MINIMAL, SHARD_COMMITTEE_PERIOD=0)
    spec = Spec(cfg)
    state, sks = interop_genesis(cfg, 16)
    state = process_slots(cfg, state, 1)

    async def run():
        node = BeaconNode(spec, state, InMemoryGossipNetwork().endpoint())
        api = BeaconRestApi(node)
        await api.start()
        try:
            loop = asyncio.get_running_loop()
            args = types.SimpleNamespace(
                network="minimal",
                beacon_node=f"http://127.0.0.1:{api.port}",
                validator_index=3, epoch=0, interop_total=16)
            rc = await loop.run_in_executor(
                None, cmd_voluntary_exit, args)
            assert rc == 0
            pool = node.operation_pools["voluntary_exits"]
            ops = pool.get_for_block(16, node.chain.head_state())
            assert any(op.message.validator_index == 3 for op in ops)
            # resubmission is a duplicate → nonzero exit code
            rc2 = await loop.run_in_executor(
                None, cmd_voluntary_exit, args)
            assert rc2 == 1
        finally:
            await api.stop()
    asyncio.run(run())


def test_voluntary_exit_subcommand_error_paths():
    import types
    from teku_tpu.cli import cmd_voluntary_exit
    # index out of the interop keyset → usage error, no traceback
    args = types.SimpleNamespace(network="minimal",
                                 beacon_node="http://127.0.0.1:1",
                                 validator_index=100, epoch=0,
                                 interop_total=16)
    assert cmd_voluntary_exit(args) == 2
    args.validator_index = -1
    assert cmd_voluntary_exit(args) == 2
    # unreachable node → clean exit code, no traceback
    args.validator_index = 3
    assert cmd_voluntary_exit(args) == 1


def test_validator_subscription_and_registration_endpoints():
    """The remaining VC-facing POST endpoints: committee/sync
    subscriptions, proposer preparation, builder registrations
    (reference handlers/v1/validator/Post*)."""
    import time
    # NetworkedNode pulls in the noise transport, whose AEAD
    # primitives need the optional `cryptography` wheel
    pytest.importorskip(
        "cryptography",
        reason="networking stack needs the optional cryptography wheel")
    from teku_tpu import builderapi as B
    from teku_tpu.api import BeaconRestApi
    from teku_tpu.crypto import bls
    from teku_tpu.networking import NetworkedNode
    from teku_tpu.spec import config as C, Spec
    from teku_tpu.spec.genesis import interop_genesis

    cfg = C.MINIMAL
    spec = Spec(cfg)
    state, sks = interop_genesis(cfg, 8)

    async def run():
        nn = NetworkedNode(spec, state, name="subtest")
        await nn.start()
        api = BeaconRestApi(nn.node, nn)
        await api.start()
        try:
            base = f"http://127.0.0.1:{api.port}"
            loop = asyncio.get_running_loop()

            def post(path, payload):
                req = urllib.request.Request(
                    base + path, data=json.dumps(payload).encode(),
                    method="POST",
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5) as r:
                    return json.loads(r.read() or b"{}")

            out = await loop.run_in_executor(
                None, post,
                "/eth/v1/validator/beacon_committee_subscriptions",
                [{"validator_index": "1", "committee_index": "0",
                  "committees_at_slot": "1", "slot": "5",
                  "is_aggregator": True}])
            assert out["data"]["accepted"] == "1"
            assert nn.subnets._until              # duty recorded

            await loop.run_in_executor(
                None, post,
                "/eth/v1/validator/sync_committee_subscriptions",
                [{"validator_index": "1",
                  "sync_committee_indices": ["0"],
                  "until_epoch": "2"}])

            await loop.run_in_executor(
                None, post, "/eth/v1/validator/prepare_beacon_proposer",
                [{"validator_index": "2",
                  "fee_recipient": "0x" + "ab" * 20}])
            assert nn.node.proposer_preparations[2] == b"\xab" * 20

            # a SIGNED registration round-trips verification
            sk = 4242
            reg = B.ValidatorRegistration(
                fee_recipient=b"\x11" * 20, gas_limit=30_000_000,
                timestamp=int(time.time()),
                pubkey=bls.secret_to_public_key(sk))
            signed = B.sign_registration(cfg, sk, reg)
            await loop.run_in_executor(
                None, post, "/eth/v1/validator/register_validator",
                [{"message": {
                    "fee_recipient": "0x" + reg.fee_recipient.hex(),
                    "gas_limit": str(reg.gas_limit),
                    "timestamp": str(reg.timestamp),
                    "pubkey": "0x" + bytes(reg.pubkey).hex()},
                  "signature": "0x" + signed.signature.hex()}])
            assert bytes(reg.pubkey) in nn.node.validator_registrations
            # a forged signature is a 400
            try:
                await loop.run_in_executor(
                    None, post, "/eth/v1/validator/register_validator",
                    [{"message": {
                        "fee_recipient": "0x" + reg.fee_recipient.hex(),
                        "gas_limit": str(reg.gas_limit),
                        "timestamp": str(reg.timestamp),
                        "pubkey": "0x" + bytes(reg.pubkey).hex()},
                      "signature": "0x" + ("11" * 96)}])
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as exc:
                assert exc.code == 400
        finally:
            await api.stop()
            await nn.stop()
    asyncio.run(run())


def test_debug_and_admin_subcommands(tmp_path, capsys):
    gen = tmp_path / "g.ssz"
    assert main(["genesis", "--validators", "8", "--out", str(gen)]) == 0
    capsys.readouterr()
    assert main(["debug", "pretty-print", "state", str(gen)]) == 0
    out = capsys.readouterr().out
    assert "BeaconState:" in out and "genesis_time" in out
    assert main(["admin", "weak-subjectivity", "--state", str(gen),
                 "--current-epoch", "10"]) == 0
    out = capsys.readouterr().out
    assert "weak subjectivity period" in out
    # far beyond the period: exit code 2 signals "outside"
    assert main(["admin", "weak-subjectivity", "--state", str(gen),
                 "--current-epoch", "99999"]) == 2


def test_migrate_database_between_modes(tmp_path, capsys):
    """archive -> prune drops snapshots/index; prune -> archive
    rebuilds the slot index from the persisted chain."""
    from teku_tpu.spec import config as C
    from teku_tpu.spec.builder import make_local_signer, produce_block
    from teku_tpu.spec.datastructures import SCHEMAS_MINIMAL as S
    from teku_tpu.spec.genesis import interop_genesis
    from teku_tpu.storage.database import Database

    cfg = C.MINIMAL
    spec = create_spec("minimal")
    data_dir = tmp_path / "node"
    data_dir.mkdir()
    db = Database(data_dir / "chain.db", spec, mode="archive",
                  state_snapshot_interval=1)
    state, sks = interop_genesis(cfg, 16)
    signer = make_local_signer(dict(enumerate(sks)))
    anchor = S.BeaconBlock(slot=0, parent_root=bytes(32),
                           state_root=state.htr(),
                           body=S.BeaconBlockBody())
    db.save_anchor(anchor, state)
    cur, roots = state, []
    for slot in range(1, 4):
        signed, post = produce_block(cfg, cur, slot, signer)
        db.save_block(signed, post)
        roots.append(signed.message.htr())
        cur = post
    db.close()
    assert main(["migrate-database", "--data-dir", str(data_dir),
                 "--to", "prune"]) == 0
    assert "migrated to prune" in capsys.readouterr().out
    db = Database(data_dir / "chain.db", spec, mode="prune")
    # anchor state survives, per-block snapshots are gone
    assert db.get_state(anchor.htr()) is not None
    assert db.get_state(roots[-1]) is None
    assert db.get_block(roots[-1]) is not None
    db.close()
    assert main(["migrate-database", "--data-dir", str(data_dir),
                 "--to", "archive"]) == 0
    assert "migrated to archive" in capsys.readouterr().out
