"""Differential tests: vectorized epoch hot loops vs the scalar spec
implementations — exact integer equality on a messy registry (slashed,
exited, partially-participating, leaking validators)."""

import dataclasses
import random

from teku_tpu.spec import config as C
from teku_tpu.spec import helpers as H
from teku_tpu.spec import perf as P
from teku_tpu.spec import vectorized as V
from teku_tpu.spec.altair import epoch as AE
from teku_tpu.spec import epoch as E0

CFG = P.perf_config(C.MINIMAL)
N = 600


def _messy_state(leaking=False, seed=7):
    rng = random.Random(seed)
    epoch = 5
    state = P.make_synthetic_altair_state(CFG, N, epoch=epoch,
                                          participation_rate=0.0,
                                          seed=seed)
    validators = list(state.validators)
    participation = []
    scores = []
    for i in range(N):
        flags = 0
        for f in range(3):
            if rng.random() < 0.8:
                flags |= 1 << f
        participation.append(flags)
        scores.append(rng.randrange(0, 50))
        if rng.random() < 0.05:       # slashed, pending withdrawal
            validators[i] = validators[i].copy_with(
                slashed=True,
                withdrawable_epoch=epoch
                + CFG.EPOCHS_PER_SLASHINGS_VECTOR // 2)
        elif rng.random() < 0.05:     # exited
            validators[i] = validators[i].copy_with(
                exit_epoch=epoch - 1, withdrawable_epoch=epoch + 1)
    slashings = list(state.slashings)
    slashings[0] = 7 * CFG.EFFECTIVE_BALANCE_INCREMENT
    # near-zero balances make the per-delta-list clamp ordering
    # observable (a net-sum clamp diverges exactly there)
    balances = list(state.balances)
    for i in range(0, N, 9):
        balances[i] = rng.randrange(0, 200_000)
    state = state.copy_with(
        balances=tuple(balances),
        validators=tuple(validators),
        previous_epoch_participation=tuple(participation),
        current_epoch_participation=tuple(
            reversed(participation)),
        inactivity_scores=tuple(scores),
        slashings=tuple(slashings))
    if leaking:
        # finality far behind → is_in_inactivity_leak
        state = state.copy_with(
            finalized_checkpoint=state.finalized_checkpoint.copy_with(
                epoch=0),
            justification_bits=(False, False, False, False))
    return state


def _scalar(fn, *args, **kw):
    """Run `fn` with vectorization forced off."""
    saved = V.VECTOR_THRESHOLD
    V.VECTOR_THRESHOLD = 10 ** 9
    try:
        return fn(*args, **kw)
    finally:
        V.VECTOR_THRESHOLD = saved


def test_rewards_and_penalties_exact_match():
    for leaking in (False, True):
        state = _messy_state(leaking=leaking)
        scalar = _scalar(AE.process_rewards_and_penalties, CFG, state)
        vec = V.process_rewards_and_penalties(CFG, state)
        assert scalar.balances == vec.balances


def test_rewards_with_bellatrix_quotient_match():
    state = _messy_state()
    q = CFG.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
    scalar = _scalar(AE.process_rewards_and_penalties, CFG, state,
                     inactivity_quotient=q)
    vec = V.process_rewards_and_penalties(CFG, state,
                                          inactivity_quotient=q)
    assert scalar.balances == vec.balances


def test_inactivity_updates_exact_match():
    for leaking in (False, True):
        state = _messy_state(leaking=leaking, seed=11)
        scalar = _scalar(AE.process_inactivity_updates, CFG, state)
        vec = V.process_inactivity_updates(CFG, state)
        assert scalar.inactivity_scores == vec.inactivity_scores


def test_effective_balance_updates_exact_match():
    state = _messy_state(seed=13)
    # skew balances so hysteresis moves a subset
    rng = random.Random(3)
    balances = [b + rng.randrange(-3 * 10 ** 9, 3 * 10 ** 9)
                for b in state.balances]
    state = state.copy_with(balances=tuple(balances))
    scalar = _scalar(E0.process_effective_balance_updates, CFG, state)
    vec = V.process_effective_balance_updates(CFG, state)
    assert scalar.validators == vec.validators


def test_justification_balances_match():
    state = _messy_state(seed=17)
    from teku_tpu.spec.altair import helpers as AH
    from teku_tpu.spec.config import TIMELY_TARGET_FLAG_INDEX
    prev = AH.get_unslashed_participating_indices(
        CFG, state, TIMELY_TARGET_FLAG_INDEX,
        H.get_previous_epoch(CFG, state))
    cur = AH.get_unslashed_participating_indices(
        CFG, state, TIMELY_TARGET_FLAG_INDEX,
        H.get_current_epoch(CFG, state))
    want = (H.get_total_balance(CFG, state, prev),
            H.get_total_balance(CFG, state, cur))
    assert V.target_participation_balances(CFG, state) == want


def test_full_epoch_matches_scalar_end_to_end():
    state = _messy_state(seed=23)
    scalar = _scalar(AE.process_epoch, CFG, state)
    vec = AE.process_epoch(CFG, state)      # dispatches (N >= 256)
    assert scalar.balances == vec.balances
    assert scalar.inactivity_scores == vec.inactivity_scores
    assert scalar.validators == vec.validators
    assert scalar.htr() == vec.htr()


def test_overflow_risk_falls_back_to_scalar():
    state = _messy_state(seed=29)
    state = state.copy_with(inactivity_scores=tuple(
        2 ** 55 for _ in range(N)))
    import pytest
    with pytest.raises(V.OverflowRisk):
        V.process_rewards_and_penalties(CFG, state)
    # the dispatching wrapper survives via the big-int path
    out = AE.process_rewards_and_penalties(CFG, state)
    assert len(out.balances) == N


def test_registry_updates_exact_match():
    from teku_tpu.spec.config import FAR_FUTURE_EPOCH
    rng = random.Random(41)
    state = _messy_state(seed=41)
    validators = list(state.validators)
    for i in range(N):
        r = rng.random()
        if r < 0.1:      # fresh deposit: waiting to enter the queue
            validators[i] = validators[i].copy_with(
                activation_eligibility_epoch=FAR_FUTURE_EPOCH,
                activation_epoch=FAR_FUTURE_EPOCH)
        elif r < 0.2:    # queued: eligibility finalized, not yet active
            validators[i] = validators[i].copy_with(
                activation_eligibility_epoch=rng.randrange(0, 3),
                activation_epoch=FAR_FUTURE_EPOCH)
        elif r < 0.25:   # ejectable
            validators[i] = validators[i].copy_with(
                effective_balance=CFG.EJECTION_BALANCE)
    state = state.copy_with(validators=tuple(validators))
    scalar = _scalar(E0.process_registry_updates, CFG, state)
    vec = V.process_registry_updates(CFG, state)
    assert scalar.validators == vec.validators
    assert scalar.htr() == vec.htr()
    # deneb's explicit activation cap routes through the same path
    scalar2 = _scalar(E0.process_registry_updates, CFG, state,
                      activation_limit=3)
    vec2 = V.process_registry_updates(CFG, state, activation_limit=3)
    assert scalar2.validators == vec2.validators


def test_slashings_exact_match_all_modes():
    state = _messy_state(seed=43)
    from teku_tpu.spec.electra import epoch as XE
    for mult in (CFG.PROPORTIONAL_SLASHING_MULTIPLIER,
                 CFG.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR,
                 CFG.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX):
        scalar = _scalar(AE.process_slashings, CFG, state,
                         multiplier=mult)
        vec = V.process_slashings(CFG, state, mult)
        assert scalar.balances == vec.balances
    scalar_e = _scalar(XE.process_slashings, CFG, state)
    vec_e = V.process_slashings(
        CFG, state, CFG.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX,
        per_increment=True)
    assert scalar_e.balances == vec_e.balances


def test_electra_effective_balance_updates_exact_match():
    """The electra path caps per credential (compounding 2048 ETH vs
    0x01 creds 32 ETH) via max_eb_fn — its own differential test."""
    from teku_tpu.spec.electra import epoch as XE
    from teku_tpu.spec.electra import helpers as EH
    ecfg = dataclasses.replace(
        CFG, BELLATRIX_FORK_EPOCH=0, CAPELLA_FORK_EPOCH=0,
        DENEB_FORK_EPOCH=0, ELECTRA_FORK_EPOCH=0)
    rng = random.Random(51)
    state = _messy_state(seed=51)
    validators = list(state.validators)
    balances = []
    for i in range(N):
        creds = (b"\x02" if rng.random() < 0.5 else b"\x01") + bytes(31)
        validators[i] = validators[i].copy_with(
            withdrawal_credentials=creds)
        # balances straddling both caps, forcing hysteresis both ways
        balances.append(rng.randrange(10 ** 9,
                                      ecfg.MAX_EFFECTIVE_BALANCE_ELECTRA
                                      + 5 * 10 ** 9))
    state = state.copy_with(validators=tuple(validators),
                            balances=tuple(balances))
    scalar = _scalar(XE.process_effective_balance_updates, ecfg, state)
    vec = V.process_effective_balance_updates(
        ecfg, state, max_eb_fn=EH.get_max_effective_balance)
    assert scalar.validators == vec.validators


def test_uint64_range_values_fall_back_without_crashing():
    """uint64-representable extremes (>= 2^63) must degrade to the
    scalar big-int path, not crash the numpy one."""
    state = _messy_state(seed=53)
    huge = 2 ** 63 + 5
    state = state.copy_with(inactivity_scores=tuple(
        huge for _ in range(N)))
    out = AE.process_inactivity_updates(CFG, state)   # no crash
    assert len(out.inactivity_scores) == N
    out2 = AE.process_rewards_and_penalties(CFG, state)
    assert len(out2.balances) == N


# -- electra / capella additions (round 5) ---------------------------------

def _electra_state(seed=11):
    cfg = P.perf_config_electra()
    rng = random.Random(seed)
    state = P.make_synthetic_electra_state(cfg, N, epoch=5, seed=seed)
    validators = list(state.validators)
    for i in range(N):
        r = rng.random()
        if r < 0.08:     # fully-withdrawable: exited + matured
            validators[i] = validators[i].copy_with(
                exit_epoch=1, withdrawable_epoch=2)
        elif r < 0.12:   # BLS credential: invisible to the sweep
            validators[i] = validators[i].copy_with(
                withdrawal_credentials=b"\x00"
                + validators[i].withdrawal_credentials[1:])
        elif r < 0.2:    # fresh deposit awaiting eligibility
            validators[i] = validators[i].copy_with(
                activation_eligibility_epoch=C.FAR_FUTURE_EPOCH,
                activation_epoch=C.FAR_FUTURE_EPOCH)
        elif r < 0.28:   # finalized-eligible, not yet active
            validators[i] = validators[i].copy_with(
                activation_eligibility_epoch=rng.randrange(0, 3),
                activation_epoch=C.FAR_FUTURE_EPOCH)
        elif r < 0.32:   # ejectable
            validators[i] = validators[i].copy_with(
                effective_balance=cfg.EJECTION_BALANCE)
    return cfg, state.copy_with(validators=tuple(validators))


def test_capella_sweep_exact_match():
    from teku_tpu.spec.capella import block as CB
    cfg, state = _electra_state(seed=12)
    for cursor in (0, N - 7):   # wrap-around window too
        s = state.copy_with(next_withdrawal_validator_index=cursor,
                            next_withdrawal_index=40)
        scalar = _scalar(CB.get_expected_withdrawals, cfg, s)
        vec = CB.get_expected_withdrawals(cfg, s)
        assert scalar == vec
        assert len(vec) > 0     # the scenario actually exercises hits


def test_electra_sweep_exact_match_with_partials():
    from teku_tpu.spec.electra import block as EB
    from teku_tpu.spec.electra.datastructures import get_electra_schemas
    cfg, state = _electra_state(seed=13)
    S = get_electra_schemas(cfg)
    # a couple of matured pending partials, one against a sweep hit
    partials = (
        S.PendingPartialWithdrawal(validator_index=3,
                                   amount=10 ** 9,
                                   withdrawable_epoch=1),
        S.PendingPartialWithdrawal(validator_index=9,
                                   amount=2 * 10 ** 9,
                                   withdrawable_epoch=2),
    )
    state = state.copy_with(pending_partial_withdrawals=partials,
                            next_withdrawal_validator_index=0)
    scalar = _scalar(EB.get_expected_withdrawals, cfg, state)
    vec = EB.get_expected_withdrawals(cfg, state)
    assert scalar == vec
    assert len(vec[0]) > 0


def test_electra_registry_updates_exact_match():
    from teku_tpu.spec.electra import epoch as EE
    cfg, state = _electra_state(seed=14)
    scalar = _scalar(EE.process_registry_updates, cfg, state)
    vec = EE.process_registry_updates(cfg, state)
    assert scalar.validators == vec.validators
    assert scalar.htr() == vec.htr()


def test_electra_full_epoch_matches_scalar():
    from teku_tpu.spec.electra import epoch as EE
    cfg, state = _electra_state(seed=15)
    scalar = _scalar(EE.process_epoch, cfg, state)
    vec = EE.process_epoch(cfg, state)
    assert scalar.htr() == vec.htr()
