"""Debug + light-client-range + peer REST endpoints (round-5 REST
parity tail; reference: handlers/v1/debug/GetForkChoice,
handlers/v1/beacon/GetLightClientUpdatesByRange,
handlers/v1/node/GetPeerById)."""

import asyncio
import dataclasses

import pytest

from teku_tpu.api import BeaconRestApi
from teku_tpu.infra.restapi import HttpError
from teku_tpu.node import Devnet
from teku_tpu.spec import config as C, Spec

CFG = dataclasses.replace(C.MINIMAL, ALTAIR_FORK_EPOCH=0)


@pytest.mark.slow
def test_debug_fork_choice_lc_updates_and_peer():
    net = Devnet(n_nodes=1, n_validators=16, spec=Spec(CFG))
    node = net.nodes[0]

    async def run():
        await net.start()
        try:
            await net.run_until_slot(2 * CFG.SLOTS_PER_EPOCH)
            api = BeaconRestApi(node)
            fc = await api._debug_fork_choice()
            assert len(fc["fork_choice_nodes"]) \
                == 2 * CFG.SLOTS_PER_EPOCH + 1   # anchor + every block
            head = node.chain.head_root
            assert any(n["block_root"] == "0x" + head.hex()
                       for n in fc["fork_choice_nodes"])
            assert all(int(n["weight"]) >= 0
                       for n in fc["fork_choice_nodes"])
            # light-client updates by range: one update for period 0
            ups = await api._lc_updates(query={"start_period": "0",
                                               "count": "4"})
            assert len(ups) == 1
            data = ups[0]["data"]
            assert int(data["signature_slot"]) > 0
            assert data["sync_aggregate"][
                "sync_committee_bits"].startswith("0x")
            # malformed range is a 400, not a 500
            with pytest.raises(HttpError) as err:
                await api._lc_updates(query={"start_period": "x"})
            assert err.value.status == 400
            # unknown peer is a 404
            with pytest.raises(HttpError) as err:
                await api._peer_by_id("00" * 32)
            assert err.value.status == 404
        finally:
            await net.stop()

    asyncio.run(run())
