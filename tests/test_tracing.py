"""Hot-path tracing: spans, trace attribution across threads/tasks,
slow-trace ring, disabled mode, service + endpoint integration."""

import asyncio
import threading
import time

import pytest

from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.infra import tracing
from teku_tpu.infra.metrics import GLOBAL_REGISTRY, MetricsRegistry
from teku_tpu.services.signatures import (
    AggregatingSignatureVerificationService)


@pytest.fixture(autouse=True)
def _tracing_reset():
    tracing.set_enabled(True)
    tracing.clear_slow_traces()
    tracing.set_sampler(None)
    yield
    tracing.set_enabled(True)
    tracing.clear_slow_traces()
    tracing.set_sampler(None)


def test_span_records_stage_and_trace():
    with tracing.trace("t", kind="unit") as tr:
        with tracing.span("host_prep"):
            pass
    assert tr.complete
    assert [s for s, _ in tr.stages] == ["host_prep"]
    assert all(d >= 0 for _, d in tr.stages)
    assert tr.labels == {"kind": "unit"}


def test_worker_thread_and_asyncio_task_land_in_same_trace():
    """The batch pipeline's exact shape: the root span opens in an
    asyncio task, one stage is recorded in the task, another inside a
    worker thread via asyncio.to_thread (contextvar copy), and a third
    from a RAW thread given the trace handle explicitly."""
    async def run():
        with tracing.trace("gossip_verify", topic="attestation") as tr:
            with tracing.span("assembly"):
                await asyncio.sleep(0)

            def thread_stage():
                with tracing.span("device_sync"):
                    time.sleep(0.001)

            await asyncio.to_thread(thread_stage)

            # raw threads drop contextvars: the explicit-handle form
            def raw_thread():
                tracing.record_stage("queue_wait", 0.002, (tr,))
            t = threading.Thread(target=raw_thread)
            t.start()
            t.join()
        return tr

    tr = asyncio.run(run())
    stages = dict(tr.stages)
    assert set(stages) == {"assembly", "device_sync", "queue_wait"}
    assert stages["device_sync"] >= 0.001
    assert tr.complete and tr.total_s >= 0.001


def test_attach_binds_many_traces_per_dispatch():
    a = tracing.new_trace("a")
    b = tracing.new_trace("b")
    with tracing.attach((a, None, b)):
        with tracing.span("dispatch"):
            pass
    assert [s for s, _ in a.stages] == ["dispatch"]
    assert [s for s, _ in b.stages] == ["dispatch"]


def test_slow_ring_keeps_the_slowest():
    tracing.clear_slow_traces()
    for i in range(50):
        tr = tracing.new_trace("t", i=str(i))
        # monotonic fake durations via a real (tiny) sleep would be
        # slow; instead fudge t_start backwards
        tr.t_start -= i * 0.001
        tracing.finish(tr)
    dump = tracing.slow_traces()
    assert len(dump) <= 32
    totals = [t["total_ms"] for t in dump]
    assert totals == sorted(totals, reverse=True)
    # the slowest synthetic trace survived, the fastest did not
    assert dump[0]["labels"]["i"] == "49"
    assert all(t["labels"]["i"] != "0" for t in dump)


def test_disabled_mode_is_noop():
    tracing.set_enabled(False)
    hist = GLOBAL_REGISTRY.labeled_histogram(
        "verify_stage_duration_seconds", labelnames=("stage",))
    before = hist.labels(stage="complete").snapshot()[2]
    assert tracing.new_trace("x") is None
    with tracing.trace("x") as tr:
        assert tr is None
        assert tracing.current_trace() is None
        with tracing.span("dispatch"):
            pass
    tracing.finish(None)   # tolerated
    assert tracing.slow_traces() == []
    after = hist.labels(stage="complete").snapshot()[2]
    assert after == before


def test_sampler_sees_completed_traces():
    seen = []
    tracing.set_sampler(seen.append)
    with tracing.trace("t"):
        pass
    assert len(seen) == 1 and seen[0].complete


SKS = [keygen(bytes([60 + i]) * 32) for i in range(2)]
PKS = [bls.secret_to_public_key(sk) for sk in SKS]


def test_service_attributes_stages_to_caller_trace():
    """End-to-end through the batching service on the pure provider:
    the caller's root trace collects queue_wait, assembly and dispatch,
    and their sum approximates the end-to-end total."""
    async def main():
        svc = AggregatingSignatureVerificationService(
            num_workers=1, registry=MetricsRegistry(), name="tr_svc")
        await svc.start()
        msg = b"traced"
        sig = bls.sign(SKS[0], msg)
        with tracing.trace("gossip_verify", topic="attestation") as tr:
            ok = await svc.verify([PKS[0]], msg, sig)
        await svc.stop()
        return ok, tr

    ok, tr = asyncio.run(main())
    assert ok
    stages = dict(tr.stages)
    assert {"queue_wait", "assembly", "dispatch"} <= set(stages)
    attributed = (stages["queue_wait"] + stages["assembly"]
                  + stages["dispatch"])
    # attribution covers the bulk of the end-to-end time (the remainder
    # is event-loop scheduling of the future resolution)
    assert attributed <= tr.total_s
    assert attributed >= 0.5 * tr.total_s
    # the trace also made it into the slow ring
    assert any(t["name"] == "gossip_verify"
               for t in tracing.slow_traces())


def test_service_batch_latency_and_bisect_metrics():
    """Satellite: batch latency histogram + first_try/bisect split."""
    async def main():
        reg = MetricsRegistry()
        svc = AggregatingSignatureVerificationService(
            num_workers=1, registry=reg, split_threshold=2,
            name="bisect_svc")
        await svc.start()
        good = [(f"m{i}".encode()) for i in range(3)]
        futs = [svc.verify([PKS[0]], m, bls.sign(SKS[0], m))
                for m in good]
        # one bad task forces the failure path → bisect recursion
        futs.append(svc.verify([PKS[0]], b"bad", bls.sign(SKS[1],
                                                          b"bad")))
        results = await asyncio.gather(*futs)
        await svc.stop()
        return reg, results

    reg, results = asyncio.run(main())
    assert results[:3] == [True, True, True] and results[3] is False
    hist = reg.histogram("bisect_svc_batch_duration_seconds")
    assert hist.count >= 1
    dispatches = reg.labeled_counter("bisect_svc_dispatch_total")
    assert dispatches.labels(kind="first_try").value >= 1
    assert dispatches.labels(kind="bisect").value >= 1


def test_admin_traces_endpoint():
    from teku_tpu.api import BeaconRestApi

    async def main():
        with tracing.trace("gossip_verify", topic="attestation"):
            pass
        api = BeaconRestApi(None)
        out = await api._admin_traces()
        assert out["tracing_enabled"] is True
        assert out["data"] and out["data"][0]["name"] == "gossip_verify"
        assert "total_ms" in out["data"][0]
        # ?clear=1 empties the ring after the read
        out = await api._admin_traces(query={"clear": "1"})
        assert out["data"]
        out = await api._admin_traces()
        assert out["data"] == []

    asyncio.run(main())
