"""Gossipsub v1.1 peer-scoring model: per-topic weighted components
with decaying counters (reference: networking/eth2/.../gossip/config/
GossipScoringConfigurator.java builds the same parameter families).
"""

import pytest

# the p2p/keystore stack imports the optional `cryptography`
# module at package import time; absent it, skip cleanly
# instead of erroring collection (tier-1 must report zero
# collection errors)
pytest.importorskip("cryptography")


import asyncio
import random

from teku_tpu.networking import gossip as G
from teku_tpu.networking.scoring import (GossipScoring, PeerScoreParams,
                                         TopicScoreParams,
                                         eth2_topic_params)

PEER = b"\x01" * 32
TOPIC = "beacon_block"


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _scoring(**kw):
    clock = _Clock()
    tp = kw.pop("topic_params", None) or (lambda t: TopicScoreParams())
    s = GossipScoring(params=PeerScoreParams(**kw), topic_params=tp,
                      time_fn=clock)
    return s, clock


def test_time_in_mesh_rewards_and_caps():
    # P3 off so long mesh tenure with no deliveries isolates P1
    s, clock = _scoring(topic_params=lambda t: TopicScoreParams(
        mesh_delivery_weight=0.0))
    s.on_graft(PEER, TOPIC)
    assert s.score(PEER) == 0.0
    clock.t += 24.0                       # two quanta
    tp = s.topic_params(TOPIC)
    expect = tp.topic_weight * tp.time_in_mesh_weight * 2.0
    assert abs(s.score(PEER) - expect) < 1e-9
    clock.t += 10_000_000.0               # way past the cap
    capped = tp.topic_weight * tp.time_in_mesh_weight \
        * tp.time_in_mesh_cap
    assert abs(s.score(PEER) - capped) < 1e-9


def test_first_deliveries_count_and_cap():
    s, _ = _scoring()
    tp = s.topic_params(TOPIC)
    for _ in range(int(tp.first_message_cap) + 25):
        s.on_first_delivery(PEER, TOPIC)
    expect = tp.topic_weight * tp.first_message_weight \
        * tp.first_message_cap
    assert abs(s.score(PEER) - expect) < 1e-9


def test_invalid_penalty_is_squared_and_beats_linear_credit():
    """The r4-scalar attack: alternate valid and invalid traffic.
    Squared P4 with capped P2 must drive the score down."""
    s, _ = _scoring()
    for _ in range(60):
        s.on_first_delivery(PEER, TOPIC)
        s.on_invalid(PEER, TOPIC)
    assert s.score(PEER) < 0


def test_mesh_delivery_deficit_activates_after_window():
    s, clock = _scoring()
    s.on_graft(PEER, TOPIC)
    tp = s.topic_params(TOPIC)
    # inside the activation window: no deficit penalty yet
    clock.t += tp.mesh_delivery_activation_s / 2
    assert s.score(PEER) >= 0
    # past the window with zero deliveries: squared deficit applies
    clock.t += tp.mesh_delivery_activation_s
    deficit = tp.mesh_delivery_threshold
    expect_p3 = tp.mesh_delivery_weight * deficit * deficit
    assert s.score(PEER) < 0
    assert s.score(PEER) <= tp.topic_weight * expect_p3 / 2
    # meeting the duty clears the penalty
    for _ in range(int(tp.mesh_delivery_threshold)):
        s.on_duplicate_delivery(PEER, TOPIC)
    assert s.score(PEER) >= 0


def test_prune_resets_mesh_counters():
    s, clock = _scoring()
    s.on_graft(PEER, TOPIC)
    clock.t += 120.0
    s.on_prune(PEER, TOPIC)
    # no longer in mesh: neither P1 credit nor P3 deficit
    assert s.score(PEER) == 0.0


def test_behaviour_penalty_squared_above_threshold():
    s, _ = _scoring()
    thr = s.params.behaviour_penalty_threshold
    s.add_behaviour_penalty(PEER, thr)     # exactly at tolerance
    assert s.score(PEER) == 0.0
    s.add_behaviour_penalty(PEER, 2.0)
    expect = s.params.behaviour_penalty_weight * 4.0
    assert abs(s.score(PEER) - expect) < 1e-9


def test_positive_topic_sum_capped_but_penalties_uncapped():
    s, _ = _scoring(topic_score_cap=5.0)
    for _ in range(1000):
        s.on_first_delivery(PEER, TOPIC)
    assert s.score(PEER) == 5.0
    s.add_behaviour_penalty(PEER, s.params.behaviour_penalty_threshold
                            + 10.0)
    assert s.score(PEER) < 5.0 - 100.0 * abs(
        s.params.behaviour_penalty_weight) / 2


def test_decay_forgives_and_garbage_collects():
    s, _ = _scoring()
    s.on_invalid(PEER, TOPIC)
    s.add_behaviour_penalty(PEER, 10.0)
    assert s.score(PEER) < 0
    for _ in range(200):
        s.decay()
    assert s.score(PEER) == 0.0
    assert PEER not in s._peers            # record GC'd


def test_eth2_topic_families():
    att = eth2_topic_params("beacon_attestation_7")
    blk = eth2_topic_params("beacon_block")
    exi = eth2_topic_params("voluntary_exit")
    assert att.topic_weight < blk.topic_weight
    assert exi.mesh_delivery_weight == 0.0   # no mesh duty for rare ops
    assert att.invalid_message_weight < blk.invalid_message_weight


def test_router_graylists_on_repeated_invalid_messages():
    """End-to-end through the router: REJECT-heavy traffic drives the
    peer below the graylist threshold and the router closes it."""
    from teku_tpu.node.gossip import TopicHandler, ValidationResult

    class _RejectHandler(TopicHandler):
        async def handle_message(self, data):
            return ValidationResult.REJECT

    class _FakePeer:
        def __init__(self):
            self.node_id = b"\x07" * 32
            self.connected = True

        async def send_frame(self, kind, payload):
            pass

        def close(self):
            self.connected = False

    class _FakeNet:
        def __init__(self, peer):
            self.peers = [peer]
            self.on_gossip = None
            self.on_peer_disconnected = None

    async def run():
        peer = _FakePeer()
        router = G.TcpGossipNetwork(_FakeNet(peer),
                                    rng=random.Random(1))
        router.subscribe("beacon_block", _RejectHandler())
        i = 0
        while peer.connected and i < 200:
            await router._on_gossip(
                peer, router._encode_data("beacon_block",
                                          b"junk-%d" % i))
            i += 1
        assert not peer.connected          # graylisted and closed
        assert router.scoring.score(peer.node_id) \
            <= router.scoring.params.graylist_threshold
    asyncio.run(run())


def test_reconnect_does_not_wash_score():
    """Review regression: a penalized peer that drops and redials
    keeps its negative counters (retainScore)."""
    s, _ = _scoring()
    s.on_invalid(PEER, TOPIC)
    before = s.score(PEER)
    assert before < 0
    s.on_disconnect(PEER)
    assert s.score(PEER) == before           # counters retained
    s.on_graft(PEER, TOPIC)                  # "reconnected" + grafted
    assert s.score(PEER) <= before           # still carrying the sin


def test_eviction_backoff_prevents_same_heartbeat_regraft():
    """Review regression: a P3-deficit eviction must not re-graft the
    same peer in the same (or next) heartbeat pass."""
    async def run():
        net, router, _ = _fresh_router(3)
        router.heartbeat()                    # initial grafting
        victim = next(iter(router._mesh[TOPIC]))
        # P4 invalid: drives score below zero without touching P3
        router.scoring.on_invalid(victim.node_id, TOPIC)
        assert router.scoring.score(victim.node_id) < 0
        router.heartbeat()                    # evicts with backoff
        assert victim not in router._mesh[TOPIC]
        # even after the P4 counter decays back to zero, the backoff
        # still holds the peer out of the refill
        for _ in range(100):
            router.scoring.decay()
        assert router.scoring.score(victim.node_id) == 0.0
        router.heartbeat()
        assert victim not in router._mesh[TOPIC]
        # once the backoff expires it may rejoin
        router._heartbeats += G.PRUNE_BACKOFF_HEARTBEATS
        router.heartbeat()
        assert victim in router._mesh[TOPIC]
    asyncio.run(run())


def test_graft_during_backoff_costs_behaviour_score():
    async def run():
        net, router, _ = _fresh_router(2)
        peer = net.peers[0]
        await router._on_gossip(peer, G.encode_control(
            prune=[TOPIC]))                   # peer prunes us: backoff
        before = router.scoring._peers.get(peer.node_id)
        before_bp = before.behaviour_penalty if before else 0.0
        await router._on_gossip(peer, G.encode_control(
            graft=[TOPIC]))                   # rude re-graft
        assert peer not in router._mesh[TOPIC]
        rec = router.scoring._peers[peer.node_id]
        assert rec.behaviour_penalty > before_bp
    asyncio.run(run())


def test_duplicate_credit_only_inside_delivery_window():
    """Review regression: replaying one stale message must not farm
    P3 mesh-delivery credit forever."""
    async def run():
        net, router, _ = _fresh_router(2)
        peer = net.peers[0]
        router._mesh_add(TOPIC, peer)
        frame = router._encode_data(TOPIC, b"the-message")[1:]
        await router._on_data(peer, frame)    # first: validated
        await router._on_data(peer, frame)    # dup inside window
        rec = router.scoring._peers[peer.node_id]
        in_window = rec.topics[TOPIC].mesh_deliveries
        assert in_window >= 2.0
        # expire the window; replays no longer credit
        for _ in range(G.DELIVERY_WINDOW_HEARTBEATS + 1):
            router.heartbeat()
        for _ in range(10):
            await router._on_data(peer, frame)
        after = router.scoring._peers[peer.node_id] \
            .topics[TOPIC].mesh_deliveries
        assert after <= in_window * 1.0 + 1e-9   # no new credit
    asyncio.run(run())


def _fresh_router(n_peers):
    """Tiny fake-net router (mirrors test_gossipsub's harness)."""
    from teku_tpu.node.gossip import TopicHandler, ValidationResult

    class _Accept(TopicHandler):
        async def handle_message(self, data):
            return ValidationResult.ACCEPT

    class _FakePeer:
        def __init__(self, nid):
            self.node_id = bytes([nid]) * 32
            self.connected = True

        async def send_frame(self, kind, payload):
            pass

        def close(self):
            self.connected = False

    class _FakeNet:
        def __init__(self, n):
            self.peers = [_FakePeer(i + 1) for i in range(n)]
            self.on_gossip = None
            self.on_peer_disconnected = None

    net = _FakeNet(n_peers)
    router = G.TcpGossipNetwork(net, rng=random.Random(7))
    handler = _Accept()
    router.subscribe(TOPIC, handler)
    for p in net.peers:
        router._peer_topics[p.node_id] = {TOPIC}
    return net, router, handler
