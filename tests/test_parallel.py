"""teku_tpu.parallel: mesh construction + sharded provider dispatch.

The multi-chip story end to end: JaxBls12381(mesh=...) routes its
batched dispatches through the shard_map kernel over the 8-virtual-
device CPU mesh (production: ICI), and the verdicts match the
single-chip provider.
"""

import numpy as np
import pytest

import jax

from teku_tpu import parallel
from teku_tpu.crypto import bls
from teku_tpu.crypto.bls import keygen
from teku_tpu.crypto.bls.pure_impl import PureBls12381
from teku_tpu.ops.provider import JaxBls12381


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (see conftest XLA_FLAGS)")
    m = parallel.make_mesh(8)
    with m:
        yield m


def test_make_mesh_validates_device_count():
    with pytest.raises(ValueError):
        parallel.make_mesh(10 ** 6)


def test_sharded_verifier_bucket_rule(mesh):
    v = parallel.ShardedVerifier(mesh, min_bucket=4)
    assert v.n_devices == 8
    assert v.min_bucket == 8          # raised to the mesh size


@pytest.mark.slow
def test_sharded_provider_matches_single_chip(mesh):
    pure = PureBls12381()
    sks = [keygen(bytes([i + 1]) * 32) for i in range(8)]
    pks = [pure.secret_key_to_public_key(sk) for sk in sks]
    msgs = [b"shard-%d" % i for i in range(8)]
    sigs = [pure.sign(sk, m) for sk, m in zip(sks, msgs)]
    triples = [([pk], m, s) for pk, m, s in zip(pks, msgs, sigs)]

    impl = JaxBls12381(mesh=mesh)
    assert impl._sharded is not None
    assert impl.batch_verify(triples)
    bad = list(triples)
    bad[3] = ([pks[3]], b"tampered", sigs[3])
    assert not impl.batch_verify(bad)
