"""bench_diff — the bench regression gate.

Compares any two bench result JSONs (``BENCH_r*.json`` — raw bench
output or the driver wrapper with a ``parsed`` key — or one entry of
``BENCH_TRAJECTORY.json``) metric by metric with per-metric thresholds
and emits a machine-readable verdict:

    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json
    python tools/bench_diff.py base.json new.json \
        --threshold sigs_per_sec=0.2 --quiet

Exit code 0 = pass, 1 = regression, 2 = usage/IO error.  The JSON
verdict on stdout is the contract future perf PRs (ROADMAP items 1, 2,
5) cite as their regression gate:

    {"verdict": "pass" | "regression",
     "regressions": <n>,
     "checks": [{"metric", "base", "new", "ratio", "threshold",
                 "direction", "status": "ok"|"regression"|"skipped"},
                ...]}

Checked metrics (a metric missing on either side is ``skipped``, never
a failure — budget-starved runs drop phases):

- ``sigs_per_sec`` (higher is better): flag when new < base·(1-thr);
- ``p50_ms`` / ``p99_ms`` and every per-stage p50 in
  ``latency_stages`` (lower is better): flag when new > base·(1+thr);
- compile-cache accounting: a shape the base run served as
  ``cache_load_s`` that the new run paid as ``compile_s`` again means
  the persistent cache stopped serving (absolute check);
- dedup gates (absolute): ``h2c_dedup`` 8x speedup ≥ 1.5 and the
  fully-warm pass's ``h2c_dispatches == 0`` — the PR-5 acceptance
  properties must not silently rot;
- overload gates (absolute, on the closed-loop ``overload.at_max``
  run): p50 under max offered load ≤ ``overload_p50_ms_max`` (default
  the 100 ms SLO), ZERO BLOCK_IMPORT sheds, shed counts ordered
  OPTIMISTIC ≥ GOSSIP, and an unflapped brownout (one enter edge, at
  most one exit) — the PR-7 acceptance properties;
- mainnet gates (absolute, per loadgen scenario in ``mainnet``):
  BLOCK_IMPORT/VIP sheds == 0 under EVERY traffic shape, vip/
  block_import p50 ≤ ``mainnet_critical_p50_ms_max`` on production
  (non-adversarial) shapes, and dedup ratio ≥
  ``mainnet_dedup_ratio_min`` on committee-shaped mixes;
- mesh gates (absolute, on the device-count sweep in ``mesh``): the
  scaling series must be monotonic in device count, and on real
  parallel hardware (``series == "measured"``) efficiency at the max
  count ≥ ``mesh_efficiency_min`` × linear (serialized-virtual runs
  report efficiency but only monotonicity is gated);
- ledger gates (absolute, per bench phase under ``ledger``): the
  dispatch decision ledger's lane-bucket padding waste ≤
  ``padding_waste_max`` and mesh shard makespan ratio ≤
  ``mesh_imbalance_max`` on every phase that emitted them
  (skip-if-missing);
- chaos gates (mesh self-healing, absolute, skip-if-missing): zero
  wrong verdicts through eject/reshape/readmit and full grow-back in
  BOTH the bench ``chaos`` phase and the loadgen ``chaos_device_loss``
  scenario, plus recovery ≤ ``mesh_recovery_s_max`` on measured
  (real-hardware) series — virtual serialized runs report recovery
  time but are compile-dominated, so the wall gate skips them.
"""

import argparse
import json
import sys
from typing import Dict, Optional

# fractional tolerance per relative metric; absolute gates are coded
DEFAULT_THRESHOLDS: Dict[str, float] = {
    "sigs_per_sec": 0.10,
    "p50_ms": 0.25,
    "p99_ms": 0.30,
    "stage_p50_ms": 0.30,
    "dedup_speedup_8x_min": 1.5,
    "overload_p50_ms_max": 100.0,
    "msm_scalars_speedup_min": 1.3,
    "mainnet_critical_p50_ms_max": 300.0,
    # committee-shaped floor: steady mixes measure ~0.34, the boundary
    # storm ~0.24 (brownout sheds duplicated gossip before dispatch);
    # adversarial dup-collapse sits at ~0.03
    "mainnet_dedup_ratio_min": 0.2,
    # mesh scaling at the max device count must keep >= 0.7x linear —
    # enforced on MEASURED series (real parallel hardware) only; the
    # serialized-virtual projection reports efficiency but its Amdahl
    # saturation (replicated finish) is expected, so it is not gated
    "mesh_efficiency_min": 0.7,
    # dispatch-ledger gates (per bench phase that emitted a ledger
    # summary): pow-2 lane-bucket padding waste must stay bounded, and
    # the mesh shard makespan (max shard lane load / mean) must stay
    # near balanced — both direct throughput observables the ledger
    # (infra/dispatchledger.py) now records per dispatch
    "padding_waste_max": 0.5,
    "mesh_imbalance_max": 1.5,
    # mesh self-healing recovery-time objective: losing a chip must
    # cost eject + replan + AOT warm of the smaller shapes, bounded —
    # gated on MEASURED (real parallel hardware) series only; virtual
    # serialized runs pay compile wall time that means nothing
    "mesh_recovery_s_max": 60.0,
    # causal-timeline gate (timeline PR): while the latency burst's
    # queue holds work, the device must be executing a dispatch at
    # least this share of the time — the direct measurement of the
    # async-overlap machinery doing its job.  Skip-if-missing: absent
    # when TEKU_TPU_TIMELINE=0 or the result predates the ring.
    # Default 0.0 (vacuous): the CPU reference box MEASURES ~0 —
    # the service drains the queue into one batch and only then
    # dispatches, so the queue is empty again before the device gets
    # busy (BENCH_r18 latency phase: 0.0002).  Raise to ~0.3 on real
    # parallel hardware where enqueue overlaps device execution.
    "overlap_efficiency_min": 0.0,
    # cold-start gate (AOT executable store PR): booting with a
    # populated store must reach READY at least this many times faster
    # than an empty-store cold boot that pays the full compile wall.
    # Skip-if-missing: absent when BENCH_COLDSTART=0 (the default —
    # the phase pays one full compile wall on purpose).
    "coldstart_speedup_min": 3.0,
}


def load_result(path: str) -> dict:
    """Read a bench result, unwrapping the driver's ``{"parsed": ...}``
    envelope when present."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a bench result object")
    return doc


def _get(doc: dict, *path):
    for key in path:
        if not isinstance(doc, dict):
            return None
        doc = doc.get(key)
    return doc


def _stage_p50s(doc: dict) -> Dict[str, float]:
    stages = doc.get("latency_stages") or {}
    out = {}
    for stage, v in stages.items():
        if isinstance(v, dict) and isinstance(
                v.get("p50_ms"), (int, float)):
            out[stage] = float(v["p50_ms"])
    # trajectory entries carry the flattened form
    for stage, v in (doc.get("stage_p50_ms") or {}).items():
        if isinstance(v, (int, float)):
            out.setdefault(stage, float(v))
    return out


def _check(checks: list, metric: str, base, new, threshold: float,
           direction: str) -> None:
    """direction: "higher" = higher is better, "lower" = lower is
    better.  None/zero on either side = skipped (no evidence): every
    relative metric here is strictly positive when measured, so a 0
    means the phase did not run (budget-starved or phase-focused
    runs), not a measured collapse."""
    entry = {"metric": metric, "base": base, "new": new,
             "threshold": threshold, "direction": direction}
    if not isinstance(base, (int, float)) \
            or not isinstance(new, (int, float)) or base <= 0 \
            or new <= 0:
        entry["status"] = "skipped"
        checks.append(entry)
        return
    ratio = new / base
    entry["ratio"] = round(ratio, 4)
    if direction == "higher":
        regressed = ratio < 1.0 - threshold
    else:
        regressed = ratio > 1.0 + threshold
    entry["status"] = "regression" if regressed else "ok"
    checks.append(entry)


def _check_absolute(checks: list, metric: str, value, predicate,
                    detail: str) -> None:
    entry = {"metric": metric, "new": value, "direction": "absolute",
             "detail": detail}
    if value is None:
        entry["status"] = "skipped"
    else:
        entry["status"] = "ok" if predicate(value) else "regression"
    checks.append(entry)


def compare(base: dict, new: dict,
            thresholds: Optional[Dict[str, float]] = None) -> dict:
    thr = dict(DEFAULT_THRESHOLDS)
    thr.update(thresholds or {})
    checks: list = []

    _check(checks, "sigs_per_sec",
           base.get("value", base.get("sigs_per_sec")),
           new.get("value", new.get("sigs_per_sec")),
           thr["sigs_per_sec"], "higher")
    _check(checks, "p50_ms", base.get("p50_ms"), new.get("p50_ms"),
           thr["p50_ms"], "lower")
    _check(checks, "p99_ms", base.get("p99_ms"), new.get("p99_ms"),
           thr["p99_ms"], "lower")

    base_stages, new_stages = _stage_p50s(base), _stage_p50s(new)
    for stage in sorted(set(base_stages) & set(new_stages)):
        _check(checks, f"stage_p50_ms.{stage}", base_stages[stage],
               new_stages[stage], thr["stage_p50_ms"], "lower")

    # compile-cache accounting: a shape the base loaded from the
    # persistent cache must not recompile fresh in the new run
    recompiled = []
    base_detail = base.get("detail") or {}
    new_detail = new.get("detail") or {}
    for shape, bv in base_detail.items():
        nv = new_detail.get(shape)
        if isinstance(bv, dict) and isinstance(nv, dict) \
                and "cache_load_s" in bv and "compile_s" in nv:
            recompiled.append(shape)
    _check_absolute(
        checks, "compile_cache_serving",
        recompiled if (base_detail and new_detail) else None,
        lambda shapes: not shapes,
        "shapes the base run cache-loaded but the new run recompiled")

    # dedup gates (PR-5 acceptance properties, absolute)
    f8 = _get(new, "h2c_dedup", "factors", "8") or {}
    _check_absolute(
        checks, "dedup_speedup_8x",
        f8.get("speedup_vs_1x", new.get("dedup_speedup_8x")),
        lambda v: v >= thr["dedup_speedup_8x_min"],
        f"8x-duplication speedup must stay >= "
        f"{thr['dedup_speedup_8x_min']}")
    warm = _get(new, "h2c_dedup", "warm") or {}
    _check_absolute(
        checks, "warm_h2c_dispatches",
        warm.get("h2c_dispatches", new.get("warm_h2c_dispatches")),
        lambda v: v == 0,
        "a fully-warm H(m) cache must dispatch zero h2c")

    # MSM gates (PR-8 acceptance property, absolute): the bucketed
    # pippenger scalars stage must beat the ladder on the stage-
    # profile p50 at every measured batch >= 256 (committee dup
    # shape; skip-if-missing like the dedup gates)
    for batch, entry in sorted((_get(new, "msm") or {}).items()):
        if not isinstance(entry, dict) or not batch.isdigit() \
                or int(batch) < 256:
            continue
        _check_absolute(
            checks, f"msm_scalars_speedup_{batch}",
            _get(entry, "scalars", "speedup"),
            lambda v: v >= thr["msm_scalars_speedup_min"],
            f"pippenger scalars-stage p50 must beat the ladder by >= "
            f"{thr['msm_scalars_speedup_min']}x at batch {batch}")

    # overload gates (PR-7 acceptance properties, absolute): the
    # closed-loop phase's max-offered-load run must hold the SLO by
    # shedding the right classes, never block import, without flapping
    at_max = _get(new, "overload", "at_max") or {}
    _check_absolute(
        checks, "overload_p50_ms",
        at_max.get("p50_ms", new.get("overload_p50_ms")),
        lambda v: v <= thr["overload_p50_ms_max"],
        f"p50 under max offered load must stay <= "
        f"{thr['overload_p50_ms_max']} ms")
    sheds = at_max.get("sheds") or {}
    _check_absolute(
        checks, "overload_block_import_sheds",
        sheds.get("block_import",
                  new.get("overload_block_import_sheds")),
        lambda v: v == 0,
        "BLOCK_IMPORT must never be shed under overload")
    _check_absolute(
        checks, "overload_shed_order",
        ((sheds.get("optimistic"), sheds.get("gossip"))
         if sheds else None),
        lambda v: v[0] is not None and v[1] is not None
        and v[0] >= v[1],
        "shed counts must be ordered OPTIMISTIC >= GOSSIP")
    brownout = at_max.get("brownout") or {}
    _check_absolute(
        checks, "overload_brownout_stable",
        brownout.get("flapped") if brownout else None,
        lambda v: v is False,
        "brownout must be edge-triggered: one enter, at most one "
        "exit, no flapping")

    # causal-timeline gate (timeline PR, absolute, skip-if-missing):
    # device-busy ∩ queue-nonempty over queue-nonempty during the
    # latency burst — overlap collapsing means host work serialized
    # ahead of the device again
    _check_absolute(
        checks, "overlap_efficiency",
        new.get("overlap_efficiency"),
        lambda v: v >= thr["overlap_efficiency_min"],
        f"device-busy share of queue-nonempty time must stay >= "
        f"{thr['overlap_efficiency_min']}")

    # mesh gates (PR-10 acceptance properties, absolute, skip-if-
    # missing): the device-count sweep's scaling series must rise
    # monotonically with chips, and on real parallel hardware the
    # efficiency at the max count must stay >= mesh_efficiency_min of
    # linear.  A virtual (serialized single-host) run reports
    # efficiency but only the monotonicity of its per-device
    # projection is gated — its wall time physically cannot drop.
    mesh_block = _get(new, "mesh") or {}
    _check_absolute(
        checks, "mesh_monotonic",
        mesh_block.get("monotonic", new.get("mesh_monotonic")),
        lambda v: v is True,
        "mesh sigs/sec must rise monotonically with device count")
    mesh_series = mesh_block.get("series", new.get("mesh_series"))
    mesh_eff = mesh_block.get("scaling_efficiency_at_max",
                              new.get("mesh_scaling_efficiency"))
    _check_absolute(
        checks, "mesh_scaling_efficiency",
        mesh_eff if mesh_series == "measured" else None,
        lambda v: v >= thr["mesh_efficiency_min"],
        f"scaling efficiency at the max device count must stay >= "
        f"{thr['mesh_efficiency_min']}x linear on real hardware")

    # mainnet gates (loadgen acceptance properties, absolute, per
    # scenario): protected classes are NEVER shed under any traffic
    # shape, the critical-class p50 bound holds on every production
    # (non-adversarial) shape, and committee-shaped mixes keep the
    # dedup ratio the unique-message pipeline's wins depend on
    for name, rep in sorted((_get(new, "mainnet", "scenarios")
                             or {}).items()):
        if not isinstance(rep, dict) or "by_class" not in rep:
            continue
        sheds = rep.get("sheds") or {}
        _check_absolute(
            checks, f"mainnet_block_import_sheds.{name}",
            (sheds.get("block_import"), sheds.get("vip")),
            lambda v: v[0] == 0 and v[1] == 0,
            "BLOCK_IMPORT/VIP must never be shed, under every "
            "scenario")
        if not rep.get("adversarial"):
            for cls in ("vip", "block_import"):
                _check_absolute(
                    checks, f"mainnet_{cls}_p50_ms.{name}",
                    _get(rep, "by_class", cls, "p50_ms"),
                    lambda v: v <= thr["mainnet_critical_p50_ms_max"],
                    f"{cls} p50 must stay <= "
                    f"{thr['mainnet_critical_p50_ms_max']} ms on "
                    "production shapes")
        if rep.get("committee_shaped"):
            _check_absolute(
                checks, f"mainnet_dedup_ratio.{name}",
                rep.get("dedup_ratio"),
                lambda v: v >= thr["mainnet_dedup_ratio_min"],
                f"committee-shaped mixes must keep dedup ratio >= "
                f"{thr['mainnet_dedup_ratio_min']}")

    # chaos gates (mesh self-healing acceptance, absolute,
    # skip-if-missing): device loss must NEVER flip a verdict, the
    # mesh must grow back to full width once the fault clears, and on
    # real hardware the eject->reshape->serving recovery must beat the
    # recovery-time objective (virtual serialized runs report the time
    # but their wall clock is compile-dominated and not gated)
    chaos = _get(new, "chaos") if isinstance(_get(new, "chaos"), dict) \
        else {}
    _check_absolute(
        checks, "chaos_wrong_verdicts",
        chaos.get("wrong_verdicts", new.get("chaos_wrong_verdicts")),
        lambda v: v == 0,
        "device loss must never flip a verdict (zero wrong verdicts "
        "through eject/reshape/readmit)")
    _check_absolute(
        checks, "chaos_recovered",
        chaos.get("recovered", new.get("chaos_recovered")),
        lambda v: v is True,
        "the mesh must readmit the recovered device and grow back to "
        "its configured width")
    chaos_series = chaos.get("series", new.get("chaos_series"))
    _check_absolute(
        checks, "chaos_recovery_s",
        (chaos.get("recovery_s", new.get("chaos_recovery_s"))
         if chaos_series == "measured" else None),
        lambda v: v <= thr["mesh_recovery_s_max"],
        f"eject->reshape->on-device-serving recovery must stay <= "
        f"{thr['mesh_recovery_s_max']} s on real hardware")
    # the loadgen chaos scenario (REAL supervisor machinery under
    # traffic): zero wrong verdicts and full recovery; its
    # protected-class shed gate already rides the per-scenario
    # mainnet loop above (sheds==0 under EVERY scenario, chaos
    # included).  Emitted only when the scenario ran — pre-loadgen
    # results must compare with no mainnet_* checks at all (the
    # per-scenario precedent above)
    mchaos = _get(new, "mainnet", "scenarios", "chaos_device_loss",
                  "chaos")
    if isinstance(mchaos, dict):
        _check_absolute(
            checks, "mainnet_chaos_wrong_verdicts",
            mchaos.get("wrong_verdicts"),
            lambda v: v == 0,
            "loadgen device loss must never flip a verdict")
        _check_absolute(
            checks, "mainnet_chaos_recovered",
            mchaos.get("recovered"),
            lambda v: v is True,
            "the loadgen chaos mesh must readmit and grow back")

    # cold-start gates (absolute, skip-if-missing): the coldstart
    # phase boots the supervisor three times in fresh subprocesses —
    # empty store, XLA cache only, populated AOT store.  With the AOT
    # store warm, boot must deserialize executables instead of
    # compiling: zero kernel-grade fresh XLA compiles (micro-op jnp
    # compiles under TEKU_TPU_KERNEL_COMPILE_MIN_S don't count), and
    # time-to-READY at least coldstart_speedup_min times better than
    # the empty-store boot
    cold = _get(new, "coldstart") \
        if isinstance(_get(new, "coldstart"), dict) else {}
    _check_absolute(
        checks, "coldstart_warm_store_compiles",
        cold.get("warm_store_kernel_compiles"),
        lambda v: v == 0,
        "a populated AOT store must boot to READY with zero "
        "kernel-grade fresh XLA compiles")
    _check_absolute(
        checks, "coldstart_speedup",
        cold.get("speedup_vs_empty"),
        lambda v: v >= thr["coldstart_speedup_min"],
        f"warm-store boot must be >= "
        f"{thr['coldstart_speedup_min']}x faster to READY than "
        f"the empty-store cold boot")

    # ledger gates (absolute, per phase, skip-if-missing): each bench
    # phase's dispatch-ledger summary must keep padding waste and mesh
    # shard imbalance inside the bounds — a regression here means the
    # batch/shard planners started dispatching dead work even if the
    # headline sigs/sec survived
    for phase, led in sorted((new.get("ledger") or {}).items()):
        if not isinstance(led, dict):
            continue
        waste = (led.get("padding_waste") or {}).get("lane")
        if led.get("pinned_min_bucket"):
            # the phase pinned its dispatch bucket for compile budget
            # (bench latency phase): the waste measures the pin, not
            # the production batch planner — skip, don't fail
            waste = None
        _check_absolute(
            checks, f"ledger_padding_waste.{phase}", waste,
            lambda v: v <= thr["padding_waste_max"],
            f"lane-bucket padding waste must stay <= "
            f"{thr['padding_waste_max']}")
        _check_absolute(
            checks, f"ledger_mesh_imbalance.{phase}",
            (led.get("mesh_imbalance") or {}).get("max"),
            lambda v: v <= thr["mesh_imbalance_max"],
            f"mesh shard makespan ratio must stay <= "
            f"{thr['mesh_imbalance_max']}")

    regressions = [c for c in checks if c["status"] == "regression"]
    return {"verdict": "regression" if regressions else "pass",
            "regressions": len(regressions),
            "checks": checks,
            "thresholds": thr}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_diff",
        description="compare two bench result JSONs; exit 1 on "
                    "regression")
    ap.add_argument("base", help="baseline BENCH_*.json")
    ap.add_argument("new", help="candidate BENCH_*.json")
    ap.add_argument("--threshold", action="append", default=[],
                    metavar="NAME=FRACTION",
                    help="override a threshold, e.g. sigs_per_sec=0.2")
    ap.add_argument("--quiet", action="store_true",
                    help="print only the one-line verdict, not the "
                         "full check list")
    args = ap.parse_args(argv)
    overrides: Dict[str, float] = {}
    for spec in args.threshold:
        name, _, value = spec.partition("=")
        if not value:
            ap.error(f"--threshold {spec!r}: expected NAME=FRACTION")
        try:
            overrides[name] = float(value)
        except ValueError:
            ap.error(f"--threshold {spec!r}: {value!r} is not a number")
    try:
        base = load_result(args.base)
        new = load_result(args.new)
    except (OSError, ValueError) as exc:
        print(json.dumps({"verdict": "error", "error": str(exc)}))
        return 2
    out = compare(base, new, overrides)
    if args.quiet:
        out = {"verdict": out["verdict"],
               "regressions": out["regressions"],
               "failed": [c["metric"] for c in out["checks"]
                          if c["status"] == "regression"]}
    print(json.dumps(out, indent=1))
    return 1 if out["verdict"] == "regression" else 0


if __name__ == "__main__":
    sys.exit(main())
