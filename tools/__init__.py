"""Operator/CI tooling that lives beside the repo, not inside the
node package: bench comparison (bench_diff) and friends."""
