"""CI gate: run tekulint over the repo and fail on any unsuppressed
finding.

The standard verify flow runs this (alongside the tier-1 pytest
acceptance test `tests/test_analysis.py::test_live_tree_is_clean`,
which embeds the same call):

    python tools/lint_gate.py [--json]

Exit codes: 0 clean, 1 unsuppressed findings or stale suppression
entries, 2 invalid suppression file.  `--json` prints the
machine-readable report for archival next to BENCH_*.json.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    from teku_tpu.analysis import run_lint
    from teku_tpu.analysis.suppress import SuppressionError

    argv = sys.argv[1:] if argv is None else argv
    try:
        report = run_lint()
    except SuppressionError as exc:
        print(f"lint_gate: {exc}", file=sys.stderr)
        return 2
    if "--json" in argv:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        print(report.render_text())
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
