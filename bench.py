"""North-star benchmark: BLS signatures verified per second per chip.

Measures the batched verification kernel (teku_tpu/ops/verify.py) on the
real device at the BASELINE.md batch sizes (1 / 64 / 512 / 4096), end to
end per dispatch: host arrays in, verdict out, device synchronized; plus
a bursty-arrival latency phase (BASELINE.md measurement config 5)
reporting attestation-verify p50/p99 through the batching service.

Prints ONE JSON line:
  {"metric": "bls_verify_sigs_per_sec", "value": <best>, "unit":
   "sigs/sec/chip", "vs_baseline": <value / 50_000>, "p50_ms": ...,
   ...detail...}

Hardened bring-up (round 2: rc=1, no JSON; round 3: in-process
jax.devices() probes hung ~25 min EACH before the fallback fired):
- backend init is probed in a kill-able SUBPROCESS with a hard deadline
  (BENCH_PROBE_TIMEOUT_S, default 60s); on timeout/failure the process
  falls back to CPU immediately so a JSON line ALWAYS comes out
  (flagged via "device"/"fallback");
- a watchdog thread force-emits the JSON and exits if any armed phase
  wedges inside the TPU runtime where signal handlers cannot run;
- every phase transition appends to BENCH_HEARTBEAT.json and stderr so
  even a SIGKILL leaves evidence of where time went;
- every phase is fenced: a failure records an "error" field for that
  phase instead of crashing the process;
- a wall-clock budget (BENCH_BUDGET_S) gates each extra compile.

vs_baseline is against the project target (>= 50k attestation sigs/sec
on one TPU v5e-1, BASELINE.md; the reference's CPU blst does ~1-2k
verifies/sec/core).  The reference measures the same surface with JMH
(reference: eth-benchmark-tests/src/jmh/java/tech/pegasys/teku/
benchmarks/BLSBenchmark.java:37-80 and ethereum/statetransition/src/jmh/
.../AggregatingSignatureVerificationServiceBenchmark.java).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import traceback

import numpy as np

OUT = {
    "metric": "bls_verify_sigs_per_sec",
    "value": 0.0,
    "unit": "sigs/sec/chip",
    "vs_baseline": 0.0,
}

_HEARTBEAT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_HEARTBEAT.json")

_emitted = False


def _emit():
    global _emitted
    if _emitted:
        return
    _emitted = True
    try:
        # even a signal/watchdog exit carries the health verdict
        _final_health()
    except Exception:
        pass
    print(json.dumps(OUT))
    sys.stdout.flush()


def _beat(stage: str, **extra) -> None:
    """Progress evidence that survives ANY exit: a heartbeat file beside
    the repo root plus a stderr JSON line (stdout stays reserved for the
    ONE result line the driver parses).  Round 3 lost 80 minutes of
    wall clock with zero evidence of where; this makes every phase
    transition observable post-mortem."""
    beat = {"stage": stage, "t": round(time.time(), 1), **extra,
            "out_so_far": {k: OUT[k] for k in
                           ("value", "device", "fallback", "error")
                           if k in OUT}}
    line = json.dumps(beat)
    try:
        with open(_HEARTBEAT_PATH, "a") as fh:
            fh.write(line + "\n")
    except OSError:
        pass
    print(line, file=sys.stderr)
    sys.stderr.flush()


def _ledger_mark() -> int:
    """Dispatch-ledger high-water mark (record seq) at a phase start."""
    try:
        from teku_tpu.infra import dispatchledger
        return dispatchledger.LEDGER.recorded_total
    except Exception:
        return 0


def _ledger_phase_summary(phase: str, since: int, **extra) -> None:
    """Per-phase dispatch-ledger summary into OUT["ledger"][phase]:
    padding-waste per stage bucket (and per lane bucket), dedup ratio,
    mesh shard imbalance, and the decision/compile histograms — so the
    perf trajectory records WHY each phase performed as it did, not
    just how fast it went (tools/bench_diff.py gates on the waste and
    imbalance ratios).  ``extra`` annotates the summary — e.g.
    ``pinned_min_bucket`` when the phase deliberately pins the
    dispatch bucket for compile budget (waste then reflects the pin,
    not the planner, and the diff gate skips it)."""
    try:
        from teku_tpu.infra import dispatchledger
        summary = dispatchledger.LEDGER.summary(since_seq=since)
        if summary.get("records"):
            summary.update(extra)
            OUT.setdefault("ledger", {})[phase] = summary
    except Exception:
        pass


def _on_term(signum, frame):  # pragma: no cover - signal path
    """An external timeout (driver harness) must still get the JSON
    line: a TPU-side compile can block past any soft deadline, and
    round 3's first probe died JSON-less exactly this way."""
    OUT["error"] = OUT.get("error", f"killed by signal {signum} "
                                    "(budget exceeded mid-compile)")
    _emit()
    os._exit(1)


class _Watchdog:
    """A hung TPU runtime call blocks the main thread inside C, where
    Python signal handlers cannot run — round 3's jax.devices() probes
    hung ~25 minutes EACH.  This daemon thread force-emits the JSON and
    exits the process when an armed phase overruns its deadline."""

    def __init__(self):
        self._deadline = None
        self._label = ""
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def arm(self, seconds: float, label: str) -> None:
        self._label = label
        self._deadline = time.time() + seconds

    def disarm(self) -> None:
        self._deadline = None

    def _run(self):  # pragma: no cover - failure path
        while True:
            time.sleep(1.0)
            d = self._deadline
            if d is not None and time.time() > d:
                OUT["error"] = (f"watchdog: {self._label} exceeded "
                                "deadline (backend hang)")
                _beat("watchdog_fired", label=self._label)
                _emit()
                os._exit(1)


# initialized by main(): importing this module (tests do) must not
# install process-wide signal handlers or spawn the watchdog thread
WD = None


def _arm_process_guards() -> None:
    global WD
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    if WD is None:
        WD = _Watchdog()


_BACKEND_STATES: list = []


def _backend_state(state: str, **extra) -> None:
    """Record a supervisor-style backend state transition (COLD →
    PROBING → READY/DEGRADED) with a timestamp, into BOTH the heartbeat
    stream and the final JSON — so BENCH_*.json shows WHY this run
    served the backend it served (`infra/supervisor.py:BackendState`
    names; the node's supervisor emits the same vocabulary)."""
    _BACKEND_STATES.append({"state": state, "t": round(time.time(), 1),
                            **extra})
    OUT["backend_states"] = _BACKEND_STATES
    try:
        # same ring the node uses: breaker trips / sheds from the
        # latency phase interleave with bring-up in one timeline
        from teku_tpu.infra import flightrecorder
        flightrecorder.record("backend_state", supervisor="bench",
                              state=state, **extra)
    except Exception:
        pass
    _beat("backend_state", state=state, **extra)


def _final_health() -> None:
    """A last health snapshot + the flight-recorder tail into the
    result JSON and heartbeat, so a degraded run (e.g. 'tpu init
    failed: probe timeout' falling back to TFRT_CPU_0, BENCH_r05.json)
    explains itself without log archaeology."""
    status, detail = "up", ""
    if OUT.get("fallback"):
        status, detail = "degraded", OUT["fallback"]
    if OUT.get("error"):
        status, detail = "down", OUT["error"]
    OUT["health"] = {
        "status": status, "detail": detail,
        "device": OUT.get("device", "unknown"),
        "last_backend_state": (_BACKEND_STATES[-1]["state"]
                               if _BACKEND_STATES else "unknown")}
    try:
        from teku_tpu.infra import flightrecorder
        OUT["flight_recorder"] = flightrecorder.RECORDER.tail(20)
    except Exception:
        pass
    _beat("final_health", health=OUT["health"],
          flight_recorder_events=len(OUT.get("flight_recorder", [])))


_PROBE_CODE = ("import jax, json, sys\n"
               "d = jax.devices()[0]\n"
               "print(json.dumps({'platform': d.platform, "
               "'device': str(d)}))\n")


def _probe_backend(timeout_s: float, code: str = _PROBE_CODE):
    """Ask a SUBPROCESS what jax.devices() says, with a hard deadline.

    The probe owns the hang risk: if the axon tunnel is wedged the child
    is killed at timeout_s and this process never touches the TPU
    runtime — round 3 lost 3 x ~25 min to in-process probes that could
    not be interrupted.  Returns (platform, device_str, stderr_tail);
    platform is None on failure."""
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            start_new_session=True, text=True)
    except OSError as exc:
        return None, f"probe spawn failed: {exc}", ""
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            proc.kill()
        out, err = proc.communicate()
        return (None, f"probe timeout after {timeout_s:.0f}s",
                (err or "")[-800:])
    if proc.returncode != 0:
        tail = (err or "").strip().splitlines()[-1:] or ["no stderr"]
        return (None, f"probe rc={proc.returncode}: {tail[0][:200]}",
                (err or "")[-800:])
    try:
        info = json.loads(out.strip().splitlines()[-1])
        return info["platform"], info["device"], (err or "")[-400:]
    except (ValueError, KeyError, IndexError):
        return None, f"probe emitted garbage: {out[:120]!r}", (
            err or "")[-800:]


def _probe_with_retries(deadline):
    """Round 4 gave up after ONE 60s probe and benchmarked the CPU for
    25 minutes; a tunnel that needs a longer first handshake (or one
    retry) deserves more than one chance.  Retry with backoff, always
    budget-aware, and leave each attempt's stderr in the heartbeat so
    the NEXT failure is diagnosable."""
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
    attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
    last_detail = "no probe attempts made"
    for i in range(attempts):
        remaining = deadline - time.time()
        if remaining < 90:
            last_detail += " (probe budget exhausted)"
            break
        t0 = time.time()
        timeout_s = min(probe_timeout, max(remaining - 60, 60))
        _beat("probe_start", attempt=i + 1, timeout_s=timeout_s)
        platform, detail, err_tail = _probe_backend(timeout_s)
        OUT["probe_s"] = OUT.get("probe_s", 0) + round(time.time() - t0, 1)
        if platform is not None:
            _beat("probe_ok", attempt=i + 1, platform=platform,
                  device=detail)
            return platform, detail
        last_detail = detail
        _beat("probe_failed", attempt=i + 1, why=detail,
              child_stderr=err_tail)
        if i + 1 < attempts:
            time.sleep(min(10 * (i + 1), deadline - time.time() - 60, 30)
                       if deadline - time.time() > 120 else 0)
    return None, last_detail


def _init_device(deadline):
    """Bring up a JAX backend without ever letting a wedged TPU tunnel
    eat the budget: subprocess probes with hard deadlines first (with
    retries), CPU fallback on exhaustion, watchdog on the in-process
    init that follows a successful probe."""
    _backend_state("probing")
    platform, detail = _probe_with_retries(deadline)
    if platform is None:
        # fast-fail to CPU: the env var must be set BEFORE jax imports
        os.environ["JAX_PLATFORMS"] = "cpu"
        OUT["fallback"] = f"tpu init failed: {detail}"
        _backend_state("degraded", why=detail)
    if platform in (None, "cpu") \
            and os.environ.get("BENCH_MESH", "1") != "0" \
            and os.environ.get("BENCH_THROUGHPUT", "1") != "0":
        # the mesh phase needs devices to shard over: on the CPU
        # (fallback) backend, force virtual host devices BEFORE jax
        # imports (serialized on one core — the phase reports the
        # per-device projection alongside measured wall rates).
        # Gated exactly like the phase itself (BENCH_THROUGHPUT=0
        # control-plane runs must keep their baseline topology)
        from teku_tpu.infra.env import ensure_virtual_devices
        n = int(os.environ.get("BENCH_MESH_FORCE_DEVICES", "8"))
        if ensure_virtual_devices(n):
            _beat("mesh_virtual_devices_forced", n=n)

    # the probe proved (or disproved) the backend in a disposable
    # process; the in-process init after a good probe should be quick,
    # but the tunnel can still wedge between the two — watchdog it
    WD.arm(240, "in-process backend init")
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    # persistent compile cache: repeat bench invocations skip the
    # multi-minute per-bucket XLA compiles (one definition in
    # infra/compilecache, shared with the CLI and driver entry hooks);
    # hit/miss counters feed the compile vs cache_load accounting below
    from teku_tpu.infra import compilecache
    cache_dir = compilecache.configure()
    compilecache.ensure_instrumented()
    OUT["compile_cache"] = {"dir": cache_dir}
    devs = jax.devices()
    WD.disarm()
    OUT["device"] = str(devs[0])
    if platform is not None:
        _backend_state("ready", device=OUT["device"])
    _beat("device_ready", device=OUT["device"])
    return jax


def _throughput_phase(jax, deadline, batches, detail):
    """Batches are tried IN ORDER and each fresh compile is gated on
    the remaining budget: TPU-XLA compiles of the full kernel run tens
    of minutes cold (hash-to-G2 alone is ~8 min), so one measured
    number at the primary shape beats four JSON-less timeouts.  The
    persistent compile cache makes warm reruns cheap.  `detail` is the
    shared accumulator across calls (main() runs this phase twice:
    primary shape first, the rest only after p50/epoch landed)."""
    import __graft_entry__ as ge
    from teku_tpu.infra import compilecache
    from teku_tpu.ops import verify as V

    kernel = V.verify_staged     # staged bounded compiles, not one
                                 # monolith (dedup-aware: h2c + miller
                                 # run at unique-message width)
    best = float(OUT.get("value") or 0.0)
    best_batch = OUT.get("best_batch")
    compiled_once = any(
        isinstance(v, dict) and ("compile_s" in v or "cache_load_s" in v)
        for v in detail.values())
    for n in batches:
        remaining = deadline - time.time()
        # a cold compile needs a wide margin; after one shape compiled
        # (cache siblings share most of the work server-side) be braver
        need = 120 if compiled_once else 600
        if remaining < need and detail:
            detail[str(n)] = "skipped: budget"
            continue
        try:
            args = ge._example_batch(n)
            stage_s = {}

            def _on_stage(nm, s, _n=n, _st=stage_s):
                _st[nm] = round(s, 1)
                _beat("stage_done", batch=_n, stage_name=nm,
                      s=round(s, 1))

            # stage-by-stage warm/compile, watchdogged: each of the five
            # staged programs must land within the phase's own margin
            _beat("compile_start", batch=n)
            WD.arm(max(remaining, need) + 120, f"compile batch {n}")
            cache_before = compilecache.stats()
            t0 = time.time()
            ok, lane_ok = kernel(*args, on_stage=_on_stage)
            ok = bool(np.asarray(ok))
            WD.disarm()
            compile_s = time.time() - t0
            compiled_once = True
            # compile_s vs cache_load_s: a post-cache (warm-boot) run
            # must not report disk loads as "compile" time — the two
            # differ by orders of magnitude and drivers compare them
            moved = compilecache.delta(cache_before)
            kind = ("cache_load_s"
                    if compilecache.classify_first_dispatch(moved)
                    == "cache_load" else "compile_s")
            entry = {kind: round(compile_s, 1),
                     "cache_hits": moved["hits"],
                     "cache_misses": moved["misses"],
                     "stage_s": stage_s}
            detail[str(n)] = entry
            if not (ok and np.asarray(lane_ok).all()):
                entry["error"] = "batch did not verify"
                continue
            iters = max(1, min(30, int(200 / max(n / 64, 1))))
            WD.arm(max(deadline - time.time(), 60) + 120,
                   f"measure batch {n}")
            t0 = time.time()
            for _ in range(iters):
                ok, lane_ok = kernel(*args)
            jax.block_until_ready((ok, lane_ok))
            WD.disarm()
            dt = (time.time() - t0) / iters
            rate = n / dt
            entry["sigs_per_sec"] = round(rate, 1)
            entry["dispatch_ms"] = round(dt * 1e3, 2)
            _beat("batch_measured", batch=n,
                  sigs_per_sec=entry["sigs_per_sec"])
            if rate > best:
                best, best_batch = rate, n
            # keep the headline current so even a SIGTERM mid-phase
            # reports the best number measured so far
            OUT["detail"] = detail
            OUT["best_batch"] = best_batch
            OUT["value"] = round(best, 1)
            OUT["vs_baseline"] = round(best / 50_000, 4)
        except Exception as exc:
            detail[str(n)] = {"error": f"{type(exc).__name__}: {exc}"}
    OUT["detail"] = detail
    OUT["best_batch"] = best_batch
    OUT["value"] = round(best, 1)
    OUT["vs_baseline"] = round(best / 50_000, 4)


def _latency_phase(jax, deadline):
    """Slot-burst replay through AggregatingSignatureVerificationService:
    Poisson-bursty single-attestation tasks, p50/p99 task latency PLUS
    per-stage attribution (queue_wait / assembly / dispatch / host_prep /
    device_enqueue / device_sync / complete p50/p95/p99) from the tracing
    layer — so a
    future p50 regression in BENCH_*.json names its guilty stage."""
    import asyncio
    import secrets
    from collections import defaultdict

    from teku_tpu.crypto import bls
    from teku_tpu.crypto.bls import keygen
    from teku_tpu.infra import tracing
    from teku_tpu.ops.provider import JaxBls12381
    from teku_tpu.services.signatures import (
        AggregatingSignatureVerificationService)

    trace_on = os.environ.get("BENCH_TRACING", "1") != "0"
    tracing.set_enabled(trace_on)
    OUT["tracing"] = "on" if trace_on else "off"
    stage_samples: dict = defaultdict(list)

    def _sampler(tr):
        # raw per-trace samples beat histogram-bucket percentiles:
        # dedupe repeated stage entries (bisect retries) by summing
        per_stage: dict = defaultdict(float)
        for stage, dur in tr.stages:
            per_stage[stage] += dur
        for stage, dur in per_stage.items():
            stage_samples[stage].append(dur)
        stage_samples["complete"].append(tr.total_s)

    if trace_on:
        tracing.set_sampler(_sampler)

    led0 = _ledger_mark()
    # min_bucket=256 pins EVERY service dispatch to the one 256-lane
    # shape the throughput phase already compiled — no extra kernel
    # compiles in this phase (only the small pubkey-validation program)
    impl = JaxBls12381(max_batch=256, min_bucket=256)
    bls.set_implementation(impl)
    try:
        sks = [keygen(bytes([i + 1]) * 32) for i in range(16)]
        pks = [impl.secret_key_to_public_key(sk) for sk in sks]
        msgs = [b"att-%d" % i for i in range(16)]
        sigs = [impl.sign(sk, m) for sk, m in zip(sks, msgs)]
        # one warm dispatch (256-lane bucket + pk validation compile);
        # same compile vs cache_load split as the throughput phase so
        # a post-cache run doesn't report a misleading "warm_compile_s"
        from teku_tpu.infra import compilecache
        triples = [([pks[i % 16]], msgs[i % 16], sigs[i % 16])
                   for i in range(256)]
        cache_before = compilecache.stats()
        t0 = time.time()
        if not impl.batch_verify(triples):
            raise RuntimeError("warmup batch failed")
        warm_s = round(time.time() - t0, 1)
        moved = compilecache.delta(cache_before)
        if (moved["hits"] or moved["misses"]) and \
                compilecache.classify_first_dispatch(moved) == "cache_load":
            OUT["warm_cache_load_s"] = warm_s
        else:
            OUT["warm_compile_s"] = warm_s

        lat: list = []

        async def run():
            svc = AggregatingSignatureVerificationService(
                num_workers=2, max_batch_size=256)
            await svc.start()
            rng = np.random.default_rng(3)
            # one slot-boundary burst: ~500 attestations arriving in
            # ~200ms (BASELINE config 5 scaled to bench budget)
            n_msgs = 500
            pending = []
            for i in range(n_msgs):
                j = i % 16
                t_submit = time.perf_counter()
                # one root trace per attestation, submit → verdict
                # (the service + provider attribute their stages to it)
                tr = tracing.new_trace("bench_verify")
                with tracing.attach((tr,)):
                    fut = svc.verify([pks[j]], msgs[j], sigs[j])
                pending.append((t_submit, fut, tr))
                await asyncio.sleep(float(rng.exponential(0.0004)))
            for t_submit, fut, tr in pending:
                okv = await fut
                tracing.finish(tr)
                assert okv
                lat.append(time.perf_counter() - t_submit)
            await svc.stop()

        from teku_tpu.infra import timeline
        ring0 = timeline.RING.mark()
        t_tl0 = time.perf_counter()
        asyncio.run(run())
        t_tl1 = time.perf_counter()
        lat_ms = np.asarray(sorted(lat)) * 1e3
        OUT["p50_ms"] = round(float(np.percentile(lat_ms, 50)), 2)
        OUT["p99_ms"] = round(float(np.percentile(lat_ms, 99)), 2)
        OUT["latency_tasks"] = len(lat_ms)
        if stage_samples:
            stages = {}
            for stage, samples in sorted(stage_samples.items()):
                arr = np.asarray(samples) * 1e3
                stages[stage] = {
                    "p50_ms": round(float(np.percentile(arr, 50)), 3),
                    "p95_ms": round(float(np.percentile(arr, 95)), 3),
                    "p99_ms": round(float(np.percentile(arr, 99)), 3),
                    "n": len(samples)}
            OUT["latency_stages"] = stages
            # attribution coverage: the named stages' p50s should
            # account for the end-to-end p50 (driver checks ±20%).
            # device time is enqueue + sync since the attribution
            # split (device_sync excludes host-prep overlap, so the
            # sum no longer double-counts under TEKU_TPU_ASYNC_OVERLAP)
            attributed = sum(
                stages[s]["p50_ms"] for s in
                ("queue_wait", "assembly", "host_prep",
                 "device_enqueue", "device_sync")
                if s in stages)
            OUT["latency_p50_attributed_ms"] = round(attributed, 3)
        # causal-timeline attribution over the burst window: what share
        # of wall the device actually worked while the queue held tasks
        # (overlap_efficiency) and how much host_prep stayed serial
        # outside device-busy (host_prep_serial_share) — None when the
        # ring is off, and tools/bench_diff.py skips its gate then
        from teku_tpu.infra import dispatchledger
        tl_events = timeline.RING.snapshot(since_seq=ring0)
        attr = timeline.attribution(
            tl_events, t_tl0, t_tl1,
            stage_sums={s: sum(v) for s, v in stage_samples.items()},
            compile_s=dispatchledger.LEDGER.summary(
                since_seq=led0).get("compile_s"))
        OUT["attribution"] = attr
        OUT["overlap_efficiency"] = attr.get("overlap_efficiency")
        OUT["host_prep_serial_share"] = attr.get(
            "host_prep_serial_share")
        # the instrumentation measures itself: ring-append cost times
        # the events this phase actually emitted, as a share of the
        # burst wall (the ≤2% budget the timeline PR promises)
        ovh = timeline.measure_overhead()
        OUT["timeline_overhead"] = {
            "per_event_us": ovh["per_event_us"],
            "events": len(tl_events),
            "share": round(len(tl_events) * ovh["per_event_us"] * 1e-6
                           / max(t_tl1 - t_tl0, 1e-9), 6)}
        # capacity evidence: the same derived signals the node's
        # /teku/v1/admin/capacity serves, measured over this phase's
        # live dispatches (per-shape latency model + occupancy)
        from teku_tpu.infra import capacity
        cap = capacity.snapshot()
        OUT["capacity"] = {
            "derived": cap["derived"],
            "occupancy_ratio": cap["device"]["occupancy_ratio"],
            "shapes": {shape: {path: {k: stats[k] for k in
                                      ("ewma_s", "p50_s", "samples")}
                               for path, stats in paths.items()}
                       for shape, paths in cap["shapes"].items()}}
        # min_bucket is PINNED to 256 above (compile budget): the lane
        # waste in this summary measures the pin + the burst's
        # coalescing, not the production planner — flagged so the
        # bench_diff waste gate skips this phase
        _ledger_phase_summary("latency", led0, pinned_min_bucket=256)
    finally:
        tracing.set_sampler(None)
        bls.reset_implementation()


def _mont_phase(jax, deadline):
    """Kernel-level A/B microbench: mont_muls/sec on the vpu
    (elementwise int64 pad-and-sum) vs mxu (int8 digit-split matmul)
    path at the service's primary batch shapes — so BENCH_*.json shows
    the multiplier-level speedup INDEPENDENT of end-to-end pipeline
    noise (the whole verify pipeline is ~11k mont_muls/signature, so
    this ratio bounds the pipeline win the MXU path can deliver)."""
    import secrets as _secrets

    import jax.numpy as jnp
    from jax import lax

    from teku_tpu.ops import limbs as fp
    from teku_tpu.ops import mxu

    batches = [int(b) for b in os.environ.get(
        "BENCH_MONT_BATCHES", "256,4096").split(",")]
    chain = int(os.environ.get("BENCH_MONT_CHAIN", "16"))
    _beat("mont_phase_start", batches=batches, chain=chain)
    out: dict = {"chain": chain, "unit": "mont_muls/sec"}

    def make_chain(mul):
        # a scan-chained multiply measures steady-state kernel cost,
        # not per-dispatch overhead: chain * batch mont_muls per call
        def run(a, b):
            def step(c, _):
                return mul(c, b), None
            c, _ = lax.scan(step, a, None, length=chain)
            return c
        return jax.jit(run)

    kernels = {"vpu": make_chain(fp.mont_mul_vpu),
               "mxu": make_chain(fp.mont_mul_mxu)}
    for n in batches:
        if time.time() > deadline - 60:
            out[str(n)] = "skipped: budget"
            continue
        a = np.stack([fp.int_to_mont(int.from_bytes(
            _secrets.token_bytes(47), "big")) for _ in range(n)])
        b = np.roll(a, 1, axis=0)
        entry: dict = {}
        for path, fn in kernels.items():
            try:
                WD.arm(max(deadline - time.time(), 60) + 120,
                       f"mont_mul {path} batch {n}")
                jax.block_until_ready(fn(a, b))      # warm/compile
                iters = max(3, min(50, int(2e6 / (n * chain))))
                t0 = time.time()
                for _ in range(iters):
                    r = fn(a, b)
                jax.block_until_ready(r)
                WD.disarm()
                dt = (time.time() - t0) / iters
                entry[path] = {
                    "mont_muls_per_sec": round(n * chain / dt, 1),
                    "dispatch_ms": round(dt * 1e3, 3)}
            except Exception as exc:
                entry[path] = {"error": f"{type(exc).__name__}: {exc}"}
        if all("mont_muls_per_sec" in entry.get(p, {})
               for p in ("vpu", "mxu")):
            entry["mxu_speedup"] = round(
                entry["mxu"]["mont_muls_per_sec"]
                / entry["vpu"]["mont_muls_per_sec"], 3)
        out[str(n)] = entry
        _beat("mont_batch_done", batch=n,
              **{p: entry[p].get("mont_muls_per_sec")
                 for p in ("vpu", "mxu") if p in entry})
    out["active_path"] = mxu.resolve()
    OUT["mont_mul"] = out
    _beat("mont_phase_done")


def _msm_phase(jax, deadline):
    """Scalars-stage A/B microbench: the per-lane windowed ladder
    (stage_scalars + stage_group) vs the GLV+Pippenger bucketed MSM
    (stage_scalars_pippenger, ops/msm.py) on IDENTICAL inputs at the
    committee-duplicated shape the grouped pipeline serves — plus the
    G1 (grouped fold) and G2 (whole-batch signature fold) sides
    measured separately.  The stage-profile `scalars` p50 delta lands
    in OUT["msm"] and tools/bench_diff.py gates pippenger >= 1.3x at
    batch >= 256."""
    import secrets as _secrets

    from teku_tpu.crypto.bls import curve as CC
    from teku_tpu.ops import limbs as fp
    from teku_tpu.ops import msm as MS
    from teku_tpu.ops import points as PTT
    from teku_tpu.ops import verify as VV

    batches = [int(b) for b in os.environ.get(
        "BENCH_MSM_BATCHES", "256,4096").split(",")]
    dup = int(os.environ.get("BENCH_MSM_DUP", "8"))
    iters = int(os.environ.get("BENCH_MSM_ITERS", "9"))
    out: dict = {"window": MS.window_env(), "dup": dup,
                 "unit": "stage p50 ms"}
    OUT["msm"] = out
    _beat("msm_phase_start", batches=batches, dup=dup)

    # 8 distinct subgroup points tiled over lanes (host oracle math —
    # the scalars stage is the only compiled program under test)
    g1aff = [CC.to_affine(CC.FQ_OPS, CC.point_mul(
        CC.FQ_OPS, 0x1111 + 7 * i, CC.G1_GENERATOR)) for i in range(8)]
    g2aff = [CC.to_affine(CC.FQ2_OPS, CC.point_mul(
        CC.FQ2_OPS, 0x2222 + 9 * i, CC.G2_GENERATOR))
        for i in range(8)]
    g1x = np.stack([fp.int_to_mont(a[0]) for a in g1aff])
    g1y = np.stack([fp.int_to_mont(a[1]) for a in g1aff])
    g2x = [np.stack([fp.int_to_mont(a[0][c]) for a in g2aff])
           for c in (0, 1)]
    g2y = [np.stack([fp.int_to_mont(a[1][c]) for a in g2aff])
           for c in (0, 1)]

    def p50(thunk):
        jax.block_until_ready(thunk())       # warm/compile
        ts = []
        for _ in range(iters):
            t0 = time.time()
            jax.block_until_ready(thunk())
            ts.append(time.time() - t0)
        ts.sort()
        return round(ts[len(ts) // 2] * 1e3, 2)

    jits = VV.staged_jits()
    g1_lad = jax.jit(lambda pk, rb, mm, gi, gp: VV.stage_group(
        PTT.scalar_mul_bits(PTT.G1_KIT, rb, pk), mm, gi, gp))
    g1_pip = jax.jit(MS.g1_grouped_msm)
    g2_lad = jax.jit(lambda sig, rb: PTT.point_batch_sum(
        PTT.G2_KIT, PTT.scalar_mul_bits(PTT.G2_KIT, rb, sig)))
    g2_pip = jax.jit(MS.g2_msm)

    for n in batches:
        if time.time() > deadline - 120 and any(
                k.isdigit() for k in out):
            out[str(n)] = "skipped: budget"
            continue
        try:
            WD.arm(max(deadline - time.time(), 60) + 600,
                   f"msm batch {n}")
            rows = max(n // dup, 1)
            idx = np.arange(n) % 8
            one = np.tile(np.asarray(fp.ONE_MONT), (n, 1))
            zero = np.zeros((n, fp.L), dtype=np.int64)
            pk_jac = (g1x[idx], g1y[idx], one)
            sig_jac = ((g2x[0][idx], g2x[1][idx]),
                       (g2y[0][idx], g2y[1][idx]),
                       (one, zero))
            raw = np.frombuffer(_secrets.token_bytes(8 * n),
                                dtype=np.uint64).copy()
            raw[raw == 0] = 1
            r_bits = np.asarray(PTT.scalar_from_uint64(raw))
            digits = MS.glv_digits_np(*MS.glv_sample_from_uint64(raw))
            mm = np.ones(n, dtype=bool)
            gi = np.arange(n, dtype=np.int32).reshape(rows, -1)
            gp = np.ones((rows, n // rows), dtype=bool)

            def lad_stage():
                pk_r, wsig = jits["scalars"](pk_jac, sig_jac, r_bits)
                return jits["group"](pk_r, mm, gi, gp) + (wsig,)

            def pip_stage():
                return jits["scalars_pip"](pk_jac, sig_jac, digits,
                                           gi, gp, mm)

            entry: dict = {}
            for name, lad, pip in (
                    ("g1", lambda: g1_lad(pk_jac, r_bits, mm, gi, gp),
                     lambda: g1_pip(pk_jac, digits, gi, gp, mm)),
                    ("g2", lambda: g2_lad(sig_jac, r_bits),
                     lambda: g2_pip(sig_jac, digits)),
                    ("scalars", lad_stage, pip_stage)):
                lad_ms = p50(lad)
                pip_ms = p50(pip)
                entry[name] = {
                    "ladder_p50_ms": lad_ms,
                    "pippenger_p50_ms": pip_ms,
                    "speedup": round(lad_ms / pip_ms, 3)
                    if pip_ms else None}
            WD.disarm()
            out[str(n)] = entry
            _beat("msm_batch_done", batch=n,
                  scalars_speedup=entry["scalars"]["speedup"],
                  g1=entry["g1"]["speedup"],
                  g2=entry["g2"]["speedup"])
        except Exception as exc:
            out[str(n)] = {"error": f"{type(exc).__name__}: {exc}"}
    out["active_path"] = MS.resolve(lanes=batches[0],
                                    rows=max(batches[0] // dup, 1))
    _beat("msm_phase_done")


def _dedup_phase(jax, deadline):
    """Duplication sweep: fixed batch, dup factor 1x/8x/64x — the
    committee-gossip shape ("Performance of EdDSA and BLS Signatures in
    Committee-Based Consensus" measures exactly this batch mix).  The
    dedup-aware pipeline runs h2c AND the Miller loops at unique-message
    width, so sigs/sec must rise MONOTONICALLY with the duplication
    factor; a final fully-warm pass (same messages again) proves a warm
    H(m) cache makes ZERO h2c dispatches.  Per-factor rates + dedup/
    cache evidence land in OUT["h2c_dedup"]."""
    from teku_tpu.crypto.bls import keygen
    from teku_tpu.ops import provider as pv
    from teku_tpu.ops.provider import JaxBls12381

    batch = int(os.environ.get("BENCH_DEDUP_BATCH", "256"))
    factors = [int(f) for f in os.environ.get(
        "BENCH_DEDUP_FACTORS", "1,8,64").split(",")]
    iters = int(os.environ.get("BENCH_DEDUP_ITERS", "3"))
    led0 = _ledger_mark()
    impl = JaxBls12381(max_batch=batch, min_bucket=batch)
    out: dict = {"batch": batch, "factors": {}}
    OUT["h2c_dedup"] = out
    _beat("dedup_phase_start", batch=batch, factors=factors)
    sks = [keygen(bytes([17 + i]) * 32) for i in range(16)]
    pks = [impl.secret_key_to_public_key(sk) for sk in sks]
    seq = [0]

    def fresh_triples(d):
        """One batch at duplication factor d: batch/d FRESH unique
        messages (cold H(m) path), each signed by d committee members
        cycling over 16 keys."""
        uniq = max(batch // d, 1)
        msgs = [b"dedup-%d-%d" % (seq[0], u) for u in range(uniq)]
        seq[0] += 1
        sig_cache: dict = {}
        triples = []
        for lane in range(batch):
            m = msgs[lane % uniq]
            k = lane % 16
            if (k, m) not in sig_cache:
                sig_cache[(k, m)] = impl.sign(sks[k], m)
            triples.append(([pks[k]], m, sig_cache[(k, m)]))
        return triples

    rate_1x = None
    last_triples = None
    for d in factors:
        remaining = deadline - time.time()
        if remaining < 120 and out["factors"]:
            out["factors"][str(d)] = "skipped: budget"
            continue
        try:
            WD.arm(max(remaining, 60) + 300, f"dedup factor {d}")
            t0 = time.time()
            if not impl.batch_verify(fresh_triples(d)):  # warm/compile
                raise RuntimeError("dedup warmup batch failed")
            warm_s = round(time.time() - t0, 1)
            best = 0.0
            h2c_d0 = impl.h2c_dispatch_count
            for _ in range(iters):
                triples = fresh_triples(d)   # fresh: cold H(m) cache
                t0 = time.time()
                okv = impl.batch_verify(triples)
                dt = time.time() - t0
                if not okv:
                    raise RuntimeError("dedup batch did not verify")
                best = max(best, batch / dt)
            WD.disarm()
            last_triples = triples
            entry = {"sigs_per_sec": round(best, 1),
                     "compile_s": warm_s,
                     "unique_per_batch": max(batch // d, 1),
                     "h2c_dispatches": impl.h2c_dispatch_count - h2c_d0}
            if d == 1:
                rate_1x = best
            elif rate_1x:
                entry["speedup_vs_1x"] = round(best / rate_1x, 3)
            out["factors"][str(d)] = entry
            _beat("dedup_factor_done", factor=d,
                  sigs_per_sec=entry["sigs_per_sec"],
                  speedup=entry.get("speedup_vs_1x"))
        except Exception as exc:
            out["factors"][str(d)] = {
                "error": f"{type(exc).__name__}: {exc}"}
    # fully-warm pass: the SAME messages again — steady-state gossip
    # (every AttestationData already mapped this slot)
    if last_triples is not None and time.time() < deadline:
        try:
            WD.arm(max(deadline - time.time(), 60) + 120, "dedup warm")
            h2c_d0 = impl.h2c_dispatch_count
            t0 = time.time()
            okv = impl.batch_verify(last_triples)
            dt = time.time() - t0
            WD.disarm()
            out["warm"] = {
                "sigs_per_sec": round(batch / dt, 1) if okv else 0.0,
                "h2c_dispatches": impl.h2c_dispatch_count - h2c_d0}
            if rate_1x and okv:
                out["warm"]["speedup_vs_1x"] = round(
                    batch / dt / rate_1x, 3)
        except Exception as exc:
            out["warm"] = {"error": f"{type(exc).__name__}: {exc}"}
    out["dedup_ratio"] = round(pv._dedup_ratio(), 4)
    out["cache"] = impl._h2c_cache.stats()
    _ledger_phase_summary("dedup", led0)
    _beat("dedup_phase_done", **{k: out.get(k) for k in
                                 ("dedup_ratio", "warm")})


def _mesh_phase(jax, deadline):
    """Device-count sweep of the GROUP-ALIGNED sharded verify path
    (ROADMAP item 1): the committee-shaped dup-8 batch dispatched
    through JaxBls12381(mesh=make_mesh(n)) at n = 1/2/4/8 devices,
    per-count sigs/sec + scaling efficiency into OUT["mesh"].

    On virtual CPU devices (xla_force_host_platform_device_count over
    ONE host) the shards execute SERIALIZED, so measured wall rates
    cannot rise with n; the phase additionally reports the per-device
    projection — wall_n/n per-dispatch latency, i.e. what concurrent
    shards would deliver, including the replicated finish and gather
    overhead the mesh really adds (PERF.md "Multi-chip mesh" derives
    why this equals real-mesh scaling up to ICI latency).  The
    monotonicity/efficiency gates in tools/bench_diff.py key on the
    ``series`` field: "measured" on real parallel hardware,
    "projected_serialized_virtual" here."""
    from teku_tpu import parallel
    from teku_tpu.crypto.bls import keygen
    from teku_tpu.ops.provider import JaxBls12381

    batch = int(os.environ.get("BENCH_MESH_BATCH", "256"))
    dup = int(os.environ.get("BENCH_MESH_DUP", "8"))
    iters = int(os.environ.get("BENCH_MESH_ITERS", "2"))
    counts = [int(c) for c in os.environ.get(
        "BENCH_MESH_COUNTS", "1,2,4,8").split(",")]
    avail = len(jax.devices())
    virtual = jax.devices()[0].platform == "cpu"
    led0 = _ledger_mark()
    out: dict = {"batch": batch, "dup": dup,
                 "available_devices": avail,
                 "series": ("projected_serialized_virtual" if virtual
                            else "measured"),
                 "devices": {}}
    OUT["mesh"] = out
    _beat("mesh_phase_start", batch=batch, dup=dup, counts=counts,
          available=avail, virtual=virtual)
    pure_sks = [keygen(bytes([41 + i]) * 32) for i in range(16)]
    seq = [0]

    def fresh_triples(impl, pks):
        """One committee-shaped batch: batch/dup FRESH unique messages
        (cold H(m) path), each signed by dup committee members."""
        uniq = max(batch // dup, 1)
        msgs = [b"mesh-%d-%d" % (seq[0], u) for u in range(uniq)]
        seq[0] += 1
        sig_cache: dict = {}
        triples = []
        for lane in range(batch):
            m = msgs[lane % uniq]
            k = lane % 16
            if (k, m) not in sig_cache:
                sig_cache[(k, m)] = impl.sign(pure_sks[k], m)
            triples.append(([pks[k]], m, sig_cache[(k, m)]))
        return triples

    wall: dict = {}
    for c in counts:
        if c > avail:
            out["devices"][str(c)] = "skipped: devices"
            continue
        remaining = deadline - time.time()
        if remaining < 120 and wall:
            out["devices"][str(c)] = "skipped: budget"
            continue
        try:
            WD.arm(max(remaining, 60) + 600, f"mesh {c} devices")
            mesh = None if c == 1 else parallel.make_mesh(c)
            impl = JaxBls12381(max_batch=batch, min_bucket=batch,
                               mesh=mesh)
            pks = [impl.secret_key_to_public_key(sk)
                   for sk in pure_sks]
            t0 = time.time()
            if not impl.batch_verify(fresh_triples(impl, pks)):
                raise RuntimeError("mesh warmup batch failed")
            compile_s = round(time.time() - t0, 1)
            best_wall = None
            for _ in range(iters):
                triples = fresh_triples(impl, pks)
                t0 = time.time()
                okv = impl.batch_verify(triples)
                dt = time.time() - t0
                if not okv:
                    raise RuntimeError("mesh batch did not verify")
                best_wall = dt if best_wall is None \
                    else min(best_wall, dt)
            WD.disarm()
            wall[c] = best_wall
            entry = {"sigs_per_sec": round(batch / best_wall, 2),
                     "wall_s": round(best_wall, 3),
                     "compile_s": compile_s,
                     "mesh_dispatches":
                         impl.dispatch_count if mesh else 0}
            # the scaling series: measured on real parallel devices,
            # the wall/n per-device projection on serialized virtual
            entry["mesh_sigs_per_sec"] = round(
                batch * c / best_wall if virtual
                else batch / best_wall, 2)
            out["devices"][str(c)] = entry
            _beat("mesh_count_done", devices=c, **{
                k: entry[k] for k in ("sigs_per_sec",
                                      "mesh_sigs_per_sec",
                                      "compile_s")})
        except Exception as exc:
            out["devices"][str(c)] = {
                "error": f"{type(exc).__name__}: {exc}"}
    rates = [(c, out["devices"][str(c)]["mesh_sigs_per_sec"])
             for c in counts
             if isinstance(out["devices"].get(str(c)), dict)
             and "mesh_sigs_per_sec" in out["devices"][str(c)]]
    if len(rates) >= 2:
        out["monotonic"] = all(b[1] >= a[1] for a, b in
                               zip(rates, rates[1:]))
        base_c, base_r = rates[0]
        max_c, max_r = rates[-1]
        out["max_devices"] = max_c
        # efficiency vs linear scaling from the smallest count
        out["scaling_efficiency_at_max"] = round(
            (max_r / base_r) / (max_c / base_c), 4)
    _ledger_phase_summary("mesh", led0)
    _beat("mesh_phase_done",
          monotonic=out.get("monotonic"),
          efficiency=out.get("scaling_efficiency_at_max"))


def _chaos_phase(jax, deadline):
    """Mesh self-healing recovery-time objective (RTO) on the REAL
    8-virtual-device mesh: serve committee batches through a
    breaker-guarded mesh provider with the self-healer wired
    (`parallel/selfheal.py` + `loader.make_mesh_healer`), wedge one
    shard mid-serving via the keyed ``bls.mesh_shard`` fault, and
    measure the full cycle — eject exactly the sick device, reshape
    to the surviving pow-2 subset, AOT-warm, atomic swap, keep
    serving on-device — then clear the fault and measure the readmit
    grow-back.  Every verdict along the way is checked against the
    expected truth (valid batches True, a tampered batch False):
    ``wrong_verdicts`` must be ZERO in every run.

    On virtual (serialized CPU) devices wall recovery time is
    dominated by XLA compiles of the smaller sharded shape and by the
    serialized shards, so ``series="virtual"`` and tools/bench_diff.py
    gates only the correctness properties; real parallel hardware
    reports ``series="measured"`` and must also beat
    ``mesh_recovery_s_max``.  The fault kind defaults to a fast Raise
    on virtual (wall-cheap) and a true Hang (deadline overrun) on
    hardware; BENCH_CHAOS_FAULT={raise,hang} overrides."""
    from teku_tpu import parallel
    from teku_tpu.crypto.bls import keygen
    from teku_tpu.crypto.bls.loader import (GuardedBls12381,
                                            make_mesh_healer)
    import contextlib
    from teku_tpu.infra import faults
    from teku_tpu.infra.env import env_override
    from teku_tpu.infra.supervisor import CircuitBreaker
    from teku_tpu.ops.provider import JaxBls12381

    from teku_tpu.infra.pow2 import floor_pow2
    n_dev = floor_pow2(min(8, len(jax.devices())))
    if n_dev < 4:
        OUT["chaos"] = "skipped: needs >= 4 devices"
        return
    batch = int(os.environ.get("BENCH_CHAOS_BATCH", "64"))
    dup = 8
    virtual = jax.devices()[0].platform == "cpu"
    fault_kind = os.environ.get(
        "BENCH_CHAOS_FAULT", "raise" if virtual else "hang")
    deadline_s = float(os.environ.get("BENCH_CHAOS_DEADLINE_S",
                                      "5" if virtual else "20"))
    led0 = _ledger_mark()
    out: dict = {"devices": n_dev, "batch": batch, "dup": dup,
                 "series": "virtual" if virtual else "measured",
                 "fault": fault_kind}
    OUT["chaos"] = out
    _beat("chaos_phase_start", devices=n_dev, batch=batch,
          fault=fault_kind)
    # reshape warm = the serving shape set: the first post-swap
    # dispatch must hit the jit cache, so recovery time includes the
    # real AOT cost and nothing compiles on the serving path.  The
    # operator's value restores in the finally (env_override owns the
    # None-means-unset dance; the try body is too far from a `with`).
    warm_override = contextlib.ExitStack()
    warm_override.enter_context(
        env_override("TEKU_TPU_MESH_WARM_BATCH", str(batch)))
    healer = None
    try:
        impl = JaxBls12381(max_batch=batch, min_bucket=batch,
                           mesh=parallel.make_mesh(n_dev))
        sick = impl.mesh_info["devices"][n_dev // 2 - 1]
        breaker = CircuitBreaker(
            failure_threshold=3, deadline_s=deadline_s,
            cooldown_s=5.0, name="bench_chaos_device")
        guarded = GuardedBls12381(impl, breaker)
        healer = make_mesh_healer(
            guarded, breaker, max_batch=batch, min_bucket=batch,
            trip_threshold=1, probe_deadline_s=max(deadline_s, 2.0),
            reprobe_s=1.0)
        sks = [keygen(bytes([71 + i]) * 32) for i in range(16)]
        pks = [impl.secret_key_to_public_key(sk) for sk in sks]
        seq = [0]

        def fresh():
            uniq = max(batch // dup, 1)
            seq[0] += 1
            msgs = [b"chaos-%d-%d" % (seq[0], u) for u in range(uniq)]
            sig_cache: dict = {}
            triples = []
            for lane in range(batch):
                m = msgs[lane % uniq]
                k = lane % 16
                if (k, m) not in sig_cache:
                    sig_cache[(k, m)] = impl.sign(sks[k], m)
                triples.append(([pks[k]], m, sig_cache[(k, m)]))
            return triples

        wrong = 0

        def check_serving(tag):
            """One valid + one tampered batch; verdicts must match
            the oracle truth exactly."""
            nonlocal wrong
            good = fresh()
            if guarded.batch_verify(good) is not True:
                wrong += 1
            bad = list(good)
            bad[3] = (bad[3][0], b"chaos-tampered", bad[3][2])
            if guarded.batch_verify(bad) is not False:
                wrong += 1
            _beat("chaos_check", stage_name=tag, wrong=wrong)

        WD.arm(max(deadline - time.time(), 60) + 900, "chaos warmup")
        t0 = time.time()
        if not impl.batch_verify(fresh()):
            raise RuntimeError("chaos warmup batch failed")
        out["warm_s"] = round(time.time() - t0, 1)
        check_serving("before_fault")
        # ---- the wedge: one shard of the live mesh goes sick -------
        # times=None on BOTH kinds: the fault must keep firing for the
        # sick device's ISOLATION PROBE after the collective dispatch
        # consumed a firing — a budgeted fault would make the probe
        # pass and attribution impossible (the probe deadline bounds
        # each hang; the collective stops matching once ejected)
        if fault_kind == "hang":
            faults.inject("bls.mesh_shard", faults.Hang(
                deadline_s + 10, key=sick))
        else:
            faults.inject("bls.mesh_shard", faults.Raise(
                RuntimeError("bench chaos: shard wedged"), key=sick))
        t_fault = time.time()
        # this dispatch fails/overruns; the ORACLE serves it (correct
        # verdict, zero failed in-flight) and the healer starts
        if guarded.batch_verify(fresh()) is not True:
            wrong += 1
        # wait for the eject+reshape swap (includes the m{n/2} kernel
        # compile on a cold cache); bounded by the REMAINING budget so
        # a starved run records chaos_error and moves on instead of
        # eating the phases behind it
        swap_bound = max(120.0, min(900.0, deadline - time.time()))
        while guarded.device is impl \
                and time.time() - t_fault < swap_bound:
            time.sleep(0.2)
        if guarded.device is impl:
            raise RuntimeError("healer never swapped the provider")
        out["recovery_s"] = healer.last_recovery_s
        out["recovery_wall_s"] = round(time.time() - t_fault, 1)
        out["ejected_device"] = sick
        out["live_after_eject"] = len(healer.live_devices)
        faults.clear("bls.mesh_shard")
        check_serving("on_shrunken_mesh")
        out["serving_after_eject"] = guarded.serving
        _beat("chaos_recovered", recovery_s=out["recovery_s"],
              live=out["live_after_eject"])
        # ---- readmit: the device recovered; the mesh grows back ----
        # the grow completes at the INSTALL, not the ledger readmit —
        # wait for the live width, bounded by the remaining budget
        t_clear = time.time()
        grow_bound = max(120.0, min(600.0, deadline - time.time()))
        while (healer.ledger.ejected()
               or len(healer.live_devices) < n_dev) \
                and time.time() - t_clear < grow_bound:
            time.sleep(0.2)
        regrown = (not healer.ledger.ejected()
                   and len(healer.live_devices) == n_dev)
        out["regrow_s"] = (round(time.time() - t_clear, 1)
                           if regrown else None)
        out["live_after_readmit"] = len(healer.live_devices)
        out["recovered"] = regrown
        check_serving("after_readmit")
        out["wrong_verdicts"] = wrong
        out["reshapes"] = dict(healer.reshapes)
        out["mesh"] = healer.snapshot()
        _ledger_phase_summary("chaos", led0)
        _beat("chaos_phase_done", recovery_s=out.get("recovery_s"),
              regrow_s=out.get("regrow_s"), wrong=wrong,
              recovered=out.get("recovered"))
    finally:
        # a raising phase must not leak a live reprobe daemon (it
        # would keep probing/reshaping under the LATER bench phases)
        # or leave the watchdog armed
        if healer is not None:
            healer.close()
        WD.disarm()
        faults.clear("bls.mesh_shard")
        warm_override.close()


def _epoch_transition_phase(deadline):
    """Altair epoch transition on a synthetic large-validator state —
    the reference's EpochTransitionBenchmark surface (eth-benchmark-
    tests/.../EpochTransitionBenchmark.java runs the same measurement
    against generated 300k+ validator states).  Pure host-side state
    math: independent of the accelerator backend."""
    from teku_tpu.spec import perf as P
    from teku_tpu.spec.altair import epoch as AE

    n = int(os.environ.get("BENCH_EPOCH_VALIDATORS", "300000"))
    cfg = P.perf_config()
    _beat("epoch_phase_start", validators=n)
    state = P.make_synthetic_altair_state(cfg, n)
    best = None
    runs = 0
    for _ in range(3):
        if time.time() > deadline:
            break
        t0 = time.time()
        AE.process_epoch(cfg, state)
        dt = (time.time() - t0) * 1e3
        best = dt if best is None else min(best, dt)
        runs += 1
    if best is not None:
        OUT["epoch_transition_ms"] = round(best, 1)
        OUT["epoch_transition_validators"] = n
        OUT["epoch_transition_runs"] = runs
        _beat("epoch_phase_done", ms=round(best, 1))
    # the latest fork's epoch transition (pending queues, compounding
    # credentials) on the same registry size
    if time.time() < deadline:
        from teku_tpu.spec.electra import epoch as EE
        cfg_e = P.perf_config_electra()
        state_e = P.make_synthetic_electra_state(cfg_e, n)
        best_e = None
        for _ in range(2):
            if time.time() > deadline:
                break
            t0 = time.time()
            EE.process_epoch(cfg_e, state_e)
            best_e = ((time.time() - t0) * 1e3 if best_e is None
                      else min(best_e, (time.time() - t0) * 1e3))
        if best_e is not None:
            OUT["epoch_transition_electra_ms"] = round(best_e, 1)
            _beat("epoch_electra_done", ms=round(best_e, 1))


_COLDSTART_BOOT = r"""
import asyncio, json, os, time
from teku_tpu.infra import aotstore, compilecache
compilecache.configure()
from teku_tpu.crypto.bls import loader

async def main():
    t0 = time.monotonic()
    sup = loader.make_supervisor(
        max_batch=int(os.environ["COLDSTART_MAX_BATCH"]),
        min_bucket=int(os.environ["COLDSTART_MIN_BUCKET"]),
        probe_base_delay_s=0.1, round_delay_s=0.1,
        warmup_deadline_s=float(os.environ["COLDSTART_DEADLINE_S"]))
    await sup.start()
    ok = await sup.wait_ready(float(os.environ["COLDSTART_DEADLINE_S"]))
    out = {"ready": bool(ok),
           "ready_s": round(time.monotonic() - t0, 2),
           "warmup_cache": sup.warmup_cache,
           "aot": aotstore.stats(), "cache": compilecache.stats()}
    await sup.stop()
    print("COLDSTART_JSON=" + json.dumps(out), flush=True)

asyncio.run(main())
"""


def _coldstart_phase(deadline):
    """Time-to-READY + fresh-compile count per executable-store state.

    Three SEQUENTIAL fresh-process supervisor boots of the same small
    shape set (fresh process = the only honest compile counter):
    `empty` (no caches — the full compile wall, which also populates
    both stores), `xla_cache` (persistent compile cache only, AOT
    store off), `aot_store` (serialized executables only, FRESH XLA
    cache dir — deserialization is the only thing that can help).
    The acceptance observable: the aot_store boot performs zero
    kernel-grade fresh compiles and beats the empty boot >= 3x."""
    import subprocess
    import tempfile

    mb = int(os.environ.get("BENCH_COLDSTART_MAX_BATCH", "4"))
    mbk = int(os.environ.get("BENCH_COLDSTART_MIN_BUCKET", "4"))
    per_boot_s = float(os.environ.get("BENCH_COLDSTART_TIMEOUT_S",
                                      "5400"))
    base = tempfile.mkdtemp(prefix="teku_coldstart_")
    xla_cold = os.path.join(base, "xla_cold")
    xla_fresh = os.path.join(base, "xla_fresh")
    aot = os.path.join(base, "aot")
    # boot 1 self-populates BOTH stores (aotstore misses save); boots
    # 2 and 3 then isolate one store each
    states = [
        ("empty", {"TEKU_TPU_XLA_CACHE_DIR": xla_cold,
                   "TEKU_TPU_AOT_STORE_DIR": aot}),
        ("xla_cache", {"TEKU_TPU_XLA_CACHE_DIR": xla_cold,
                       "TEKU_TPU_AOT_STORE": "0"}),
        ("aot_store", {"TEKU_TPU_XLA_CACHE_DIR": xla_fresh,
                       "TEKU_TPU_AOT_STORE_DIR": aot}),
    ]
    results = {}
    for name, env_d in states:
        _beat("coldstart_boot", state=name)
        env = dict(os.environ)
        env.update(env_d)
        env.update({"COLDSTART_MAX_BATCH": str(mb),
                    "COLDSTART_MIN_BUCKET": str(mbk),
                    "COLDSTART_DEADLINE_S": str(per_boot_s),
                    "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu")})
        WD.arm(per_boot_s + 300, f"coldstart boot {name}")
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _COLDSTART_BOOT],
                capture_output=True, text=True, timeout=per_boot_s,
                env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
            parsed = None
            for line in proc.stdout.splitlines():
                if line.startswith("COLDSTART_JSON="):
                    parsed = json.loads(line.split("=", 1)[1])
            if parsed is None:
                parsed = {"error": f"rc={proc.returncode}: "
                                   f"{proc.stderr[-400:]}"}
        except subprocess.TimeoutExpired:
            parsed = {"error": f"timeout after {per_boot_s:.0f}s"}
        finally:
            WD.disarm()
        results[name] = parsed
        _beat("coldstart_boot_done", state=name,
              ready_s=parsed.get("ready_s"),
              error=parsed.get("error"))
        if "error" in parsed and name == "empty":
            break  # warm states are meaningless without the cold boot
    out = {
        # honest provenance: sequential fresh-process boots on this
        # 1-core CPU container (the parent bench process sits idle
        # while each boot runs) — wall clocks are NOT comparable to
        # parallel or TPU series
        "series": "1-core-cpu-sequential-subprocess",
        "max_batch": mb, "min_bucket": mbk,
        "states": results,
    }
    cold = results.get("empty", {})
    warm = results.get("aot_store", {})
    if cold.get("ready_s") and warm.get("ready_s"):
        out["speedup_vs_empty"] = round(
            cold["ready_s"] / warm["ready_s"], 2)
        # whole-process count: probe + warmup + verify probe included
        out["warm_store_kernel_compiles"] = (
            warm.get("cache", {}).get("kernel_compiles"))
        out["warm_store_backend_compiles"] = (
            warm.get("cache", {}).get("backend_compiles"))
        out["warm_store_aot_loads"] = warm.get("aot", {}).get("loads")
    OUT["coldstart"] = out


def _kzg_phase(deadline):
    """Blob-verification throughput (deneb DA check): batch of 6 blobs
    (mainnet MAX_BLOBS_PER_BLOCK) verified per dispatch, REAL ceremony
    setup (the vendored public KZG ceremony artifact), device path when
    available (reference surface: CKZG4844.java:104-122
    verifyBlobKzgProofBatch)."""
    import secrets as _secrets

    from teku_tpu.crypto import kzg
    from teku_tpu.ops.kzg import JaxKzg

    kzg.set_backend(JaxKzg())
    setup = kzg.get_setup()   # the real 4096-point ceremony file
    n_blobs = int(os.environ.get("BENCH_KZG_BLOBS", "6"))
    _beat("kzg_phase_start", blobs=n_blobs)
    rng = np.random.default_rng(11)
    blobs = []
    for _ in range(n_blobs):
        fes = [int.from_bytes(_secrets.token_bytes(31), "big")
               for _ in range(kzg.FIELD_ELEMENTS_PER_BLOB)]
        blobs.append(b"".join(v.to_bytes(32, "big") for v in fes))
    t0 = time.time()
    commitments = []
    proofs = []
    for b in blobs:
        # every commitment/proof is one 4096-lane device MSM — gate
        # each on the remaining budget so this phase can't overshoot
        if time.time() > deadline - 60 and commitments:
            break
        commitments.append(kzg.blob_to_kzg_commitment(b, setup))
        proofs.append(kzg.compute_blob_kzg_proof(b, commitments[-1],
                                                 setup))
    blobs = blobs[:len(proofs)]
    commitments = commitments[:len(proofs)]
    if not blobs:
        return
    n_blobs = len(blobs)
    # commit + proof are one MSM each: the recorded figure is the
    # prover-side cost per blob (both MSMs)
    OUT["kzg_commit_proof_s_per_blob"] = round(
        (time.time() - t0) / n_blobs, 2)
    _beat("kzg_proofs_ready", blobs=n_blobs)
    # warm (compiles the verification kernel when the device backend is
    # installed), then measure
    t_warm = time.time()
    assert kzg.verify_blob_kzg_proof_batch(blobs, commitments, proofs,
                                           setup)
    warm_s = time.time() - t_warm
    iters = 0
    t0 = time.time()
    while iters < 5 and time.time() < deadline:
        assert kzg.verify_blob_kzg_proof_batch(blobs, commitments,
                                               proofs, setup)
        iters += 1
    if iters:
        dt = (time.time() - t0) / iters
    else:
        dt = warm_s          # budget-starved: the warm dispatch (incl.
        OUT["kzg_warm_only"] = True   # compile) is still evidence
    OUT["kzg_blobs_per_sec"] = round(n_blobs / dt, 2)
    OUT["kzg_backend"] = kzg.backend_name()
    _beat("kzg_phase_done", blobs_per_sec=OUT["kzg_blobs_per_sec"])


def _overload_phase(deadline):
    """Closed-loop overload control: the REAL service + admission
    controller (priority classes, adaptive pow-2 batching, brownout
    shed-by-class) driven at several offered-load factors on a virtual
    clock (`teku_tpu/services/overload_sim.py`).  The device model is
    nominal (BENCH_OVERLOAD_CAPACITY sigs/sec) because the property
    under test is the CONTROL PLANE — does the node hold the 100 ms
    attestation-verify p50 at 10x sustained offered load by shedding
    OPTIMISTIC/GOSSIP and never BLOCK_IMPORT — which is independent of
    this host's absolute BLS speed (virtual time also makes the phase
    budget-proof: each factor runs in a few wall seconds).  The
    measured per-factor curve + the 10x acceptance evidence land in
    OUT["overload"]; tools/bench_diff.py gates on them."""
    from teku_tpu.services import overload_sim

    cap = float(os.environ.get("BENCH_OVERLOAD_CAPACITY", "2000"))
    duration = float(os.environ.get("BENCH_OVERLOAD_DURATION_S", "4"))
    factors = [float(f) for f in os.environ.get(
        "BENCH_OVERLOAD_FACTORS", "1,2,5,10").split(",")]
    _beat("overload_phase_start", capacity=cap, factors=factors)
    out: dict = {"capacity_sigs_per_sec": cap, "duration_s": duration,
                 "slo_p50_ms": 100.0, "curve": {}}
    OUT["overload"] = out
    for x in factors:
        if time.time() > deadline - 30 and out["curve"]:
            out["curve"][str(x)] = "skipped: budget"
            continue
        try:
            WD.arm(max(deadline - time.time(), 60) + 120,
                   f"overload factor {x}")
            res = overload_sim.run(
                offered_x=x, duration_s=duration,
                capacity_sigs_per_sec=cap)
            WD.disarm()
            out["curve"][str(x)] = {
                "p50_ms": res["p50_ms"], "p95_ms": res["p95_ms"],
                "completed_share": res["completed_share"],
                "shed_total": res["shed_total"],
                "brownout_enters": res["brownout"]["enters"]}
            if x == max(factors):
                # the acceptance point: full shed breakdown + brownout
                # edge evidence for the 10x run
                res.pop("final_inputs", None)
                out["at_max"] = res
            _beat("overload_factor_done", factor=x,
                  p50_ms=res["p50_ms"],
                  sheds=res["sheds"])
        except Exception as exc:
            out["curve"][str(x)] = {
                "error": f"{type(exc).__name__}: {exc}"}
    _beat("overload_phase_done",
          p50_at_max=(out.get("at_max") or {}).get("p50_ms"))


def _mainnet_phase(deadline):
    """Mainnet-shape traffic replay (`teku_tpu/loadgen`): seeded
    gossip-replay scenarios — committee-duplicated subnets, aggregation
    waves, sync committee, blob waves, epoch-boundary storms, and
    adversarial shapes (invalid-sig flood exercising bisect,
    equivocation replay exercising coalescing, dup-collapse starving
    the H(m) cache) — against the REAL signature service + admission
    controller on a virtual clock.  Per-scenario sigs/sec, per-class
    p50/p99, shed counts, dedup ratio and brownout transitions land in
    OUT["mainnet"]; tools/bench_diff.py gates BLOCK_IMPORT sheds == 0
    under every scenario, the critical-class p50 bound, and the
    dedup-ratio floor on committee-shaped mixes."""
    from teku_tpu.loadgen import driver, scenarios

    seed = int(os.environ.get("BENCH_MAINNET_SEED", "1"))
    slots = int(os.environ.get("BENCH_MAINNET_SLOTS", "2"))
    names = [s for s in os.environ.get(
        "BENCH_MAINNET_SCENARIOS",
        ",".join(scenarios.DEFAULT_SWEEP)).split(",") if s]
    _beat("mainnet_phase_start", scenarios=names, seed=seed,
          slots=slots)
    out: dict = {"seed": seed, "slots": slots, "scenarios": {}}
    OUT["mainnet"] = out
    for name in names:
        if time.time() > deadline - 30 and out["scenarios"]:
            out["scenarios"][name] = "skipped: budget"
            continue
        try:
            WD.arm(max(deadline - time.time(), 60) + 120,
                   f"mainnet scenario {name}")
            rep = driver.run_scenario(name, seed=seed, slots=slots)
            WD.disarm()
            out["scenarios"][name] = rep
            _beat("mainnet_scenario_done", scenario=name,
                  sigs_per_sec=rep["sigs_per_sec"],
                  p50_ms=rep["p50_ms"], sheds=rep["shed_total"],
                  dedup_ratio=rep["dedup_ratio"],
                  bisect=rep["bisect_dispatches"],
                  brownout_enters=rep["brownout"]["enters"])
        except Exception as exc:
            out["scenarios"][name] = {
                "error": f"{type(exc).__name__}: {exc}"}
    out["summary"] = driver.summarize(out["scenarios"])
    _beat("mainnet_phase_done", **out["summary"])


_TRAJECTORY_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_TRAJECTORY.json")


def trajectory_entry(out: dict, run_id: str) -> dict:
    """Flatten one bench result into the compact trajectory record
    tools/bench_diff.py and future perf PRs compare against."""
    entry = {"run_id": run_id, "t_wall": round(time.time(), 1),
             "sigs_per_sec": out.get("value"),
             "best_batch": out.get("best_batch"),
             "device": out.get("device"),
             "mont_path": out.get("mont_path"),
             "p50_ms": out.get("p50_ms"), "p99_ms": out.get("p99_ms")}
    stages = out.get("latency_stages") or {}
    entry["stage_p50_ms"] = {s: v.get("p50_ms")
                             for s, v in stages.items()
                             if isinstance(v, dict)}
    compile_s, cache_load_s = 0.0, 0.0
    for v in (out.get("detail") or {}).values():
        if isinstance(v, dict):
            compile_s += v.get("compile_s", 0.0)
            cache_load_s += v.get("cache_load_s", 0.0)
    entry["compile_s"] = round(compile_s, 1)
    entry["cache_load_s"] = round(cache_load_s, 1)
    dedup = out.get("h2c_dedup") or {}
    f8 = (dedup.get("factors") or {}).get("8")
    entry["dedup_speedup_8x"] = (f8.get("speedup_vs_1x")
                                 if isinstance(f8, dict) else None)
    warm = dedup.get("warm")
    entry["warm_h2c_dispatches"] = (warm.get("h2c_dispatches")
                                    if isinstance(warm, dict) else None)
    cap = out.get("capacity") or {}
    entry["occupancy_ratio"] = cap.get("occupancy_ratio")
    entry["overlap_efficiency"] = out.get("overlap_efficiency")
    entry["host_prep_serial_share"] = out.get("host_prep_serial_share")
    at_max = (out.get("overload") or {}).get("at_max") or {}
    entry["overload_p50_ms"] = at_max.get("p50_ms")
    entry["overload_block_import_sheds"] = (
        at_max.get("sheds") or {}).get("block_import")
    mainnet = (out.get("mainnet") or {}).get("summary") or {}
    entry["mainnet_block_import_sheds"] = mainnet.get(
        "block_import_sheds_worst")
    entry["mainnet_critical_p50_ms"] = mainnet.get(
        "critical_p50_ms_worst")
    entry["mainnet_dedup_ratio_min"] = mainnet.get(
        "committee_dedup_ratio_min")
    mesh_block = out.get("mesh") or {}
    entry["mesh_monotonic"] = mesh_block.get("monotonic")
    entry["mesh_series"] = mesh_block.get("series")
    entry["mesh_scaling_efficiency"] = mesh_block.get(
        "scaling_efficiency_at_max")
    chaos = out.get("chaos") or {}
    if isinstance(chaos, dict):
        entry["chaos_recovery_s"] = chaos.get("recovery_s")
        entry["chaos_wrong_verdicts"] = chaos.get("wrong_verdicts")
        entry["chaos_series"] = chaos.get("series")
        entry["chaos_recovered"] = chaos.get("recovered")
    lint = out.get("lint") or {}
    if isinstance(lint, dict) and "error" not in lint:
        entry["lint_unsuppressed"] = lint.get("unsuppressed")
        entry["lint_suppressed"] = lint.get("suppressed")
    return entry


def append_trajectory(out: dict, path: str = _TRAJECTORY_PATH,
                      run_id: str = None, max_entries: int = 50) -> str:
    """Append this run to the rolling BENCH_TRAJECTORY.json.

    REFUSES to overwrite an existing entry for the same run id — a
    re-run under the same id must not silently rewrite the historical
    record a regression gate already cited (re-measure under a fresh
    id instead).  Returns "appended" | "duplicate_run_id" | an error
    string; never raises (bench's result line must always come out)."""
    run_id = run_id or os.environ.get("BENCH_RUN_ID") \
        or f"run_{int(time.time())}"
    try:
        beat = _beat if path == _TRAJECTORY_PATH else (
            lambda *a, **k: None)        # tests use scratch paths
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            doc = {"entries": []}        # first run: fresh history
        except (OSError, ValueError) as exc:
            # an EXISTING but unreadable/corrupt trajectory must abort
            # the append — restarting history here would overwrite the
            # record a regression gate already cited
            beat("trajectory_error", run_id=run_id,
                 why=f"unreadable trajectory: {exc}")
            return f"error: unreadable trajectory: {exc}"
        entries = doc.get("entries") or []
        if any(e.get("run_id") == run_id for e in entries):
            beat("trajectory_skipped", run_id=run_id,
                 why="duplicate run id (entries are append-only)")
            return "duplicate_run_id"
        entries.append(trajectory_entry(out, run_id))
        doc["entries"] = entries[-max_entries:]
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1)
        os.replace(tmp, path)
        beat("trajectory_appended", run_id=run_id,
             entries=len(doc["entries"]))
        return "appended"
    except Exception as exc:  # noqa: BLE001 - evidence, not the result
        return f"error: {type(exc).__name__}: {exc}"


def main():
    t_start = time.time()
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    deadline = t_start + budget_s
    _arm_process_guards()
    try:
        os.unlink(_HEARTBEAT_PATH)   # fresh evidence trail per run
    except OSError:
        pass
    _beat("bench_start", budget_s=budget_s)
    _backend_state("cold")
    # 256 first: it doubles as the latency phase's service bucket.
    # 512 is BASELINE.md measurement config 2's missing size (r4 never
    # measured it); 1/64/512/4096 are the advertised batch points.
    batches = [int(b) for b in
               os.environ.get("BENCH_BATCHES",
                              "256,512,64,4096,1").split(",")]
    try:
        jax = _init_device(deadline)
    except Exception as exc:
        OUT["error"] = f"device init: {type(exc).__name__}: {exc}"
        _emit()
        return
    # Phase order is budget-priority order (round 4 burned the whole
    # budget on big-batch compiles and starved p50/epoch): primary
    # shape -> p50 latency (reuses the warm 256 bucket) -> epoch
    # transition (host-side, cheap) -> the remaining batch shapes.
    detail: dict = {}
    # BENCH_THROUGHPUT=0 skips the kernel-compile phases entirely: the
    # virtual-clock phases (overload, mainnet) need no device kernel,
    # so a control-plane-focused run should not pay minutes of XLA
    run_throughput = os.environ.get("BENCH_THROUGHPUT", "1") != "0"
    try:
        if run_throughput:
            _throughput_phase(jax, deadline, batches[:1], detail)
    except Exception as exc:
        OUT["error"] = f"throughput: {type(exc).__name__}: {exc}"
        OUT["trace"] = traceback.format_exc(limit=3)
    if os.environ.get("BENCH_P50", "1") != "0" and time.time() < deadline:
        try:
            _beat("latency_phase_start")
            WD.arm(max(deadline - time.time(), 60) + 300, "latency phase")
            _latency_phase(jax, deadline)
            WD.disarm()
        except Exception as exc:
            OUT["p50_error"] = f"{type(exc).__name__}: {exc}"
    if os.environ.get("BENCH_MONT", "1") != "0" \
            and time.time() < deadline:
        try:
            WD.arm(max(deadline - time.time(), 60) + 300, "mont phase")
            _mont_phase(jax, deadline)
            WD.disarm()
        except Exception as exc:
            OUT["mont_error"] = f"{type(exc).__name__}: {exc}"
    if os.environ.get("BENCH_MSM", "1") != "0" \
            and time.time() < deadline:
        try:
            WD.arm(max(deadline - time.time(), 60) + 300, "msm phase")
            _msm_phase(jax, deadline)
            WD.disarm()
        except Exception as exc:
            OUT["msm_error"] = f"{type(exc).__name__}: {exc}"
    if os.environ.get("BENCH_DEDUP", "1") != "0" \
            and time.time() < deadline:
        try:
            WD.arm(max(deadline - time.time(), 60) + 300, "dedup phase")
            _dedup_phase(jax, deadline)
            WD.disarm()
        except Exception as exc:
            OUT["dedup_error"] = f"{type(exc).__name__}: {exc}"
    if os.environ.get("BENCH_MESH", "1") != "0" \
            and run_throughput and time.time() < deadline:
        try:
            WD.arm(max(deadline - time.time(), 60) + 600, "mesh phase")
            _mesh_phase(jax, deadline)
            WD.disarm()
        except Exception as exc:
            OUT["mesh_error"] = f"{type(exc).__name__}: {exc}"
    if os.environ.get("BENCH_OVERLOAD", "1") != "0":
        try:
            # virtual-clock phase: a few wall seconds per factor, so
            # it runs even on budget-starved rounds
            WD.arm(max(deadline - time.time(), 60) + 300,
                   "overload phase")
            _overload_phase(deadline)
            WD.disarm()
        except Exception as exc:
            OUT["overload_error"] = f"{type(exc).__name__}: {exc}"
    if os.environ.get("BENCH_MAINNET", "1") != "0":
        try:
            # virtual-clock phase like overload: wall-cheap, so it
            # runs even on budget-starved rounds
            WD.arm(max(deadline - time.time(), 60) + 300,
                   "mainnet phase")
            _mainnet_phase(deadline)
            WD.disarm()
        except Exception as exc:
            OUT["mainnet_error"] = f"{type(exc).__name__}: {exc}"
    if os.environ.get("BENCH_EPOCH", "1") != "0":
        try:
            WD.arm(max(deadline - time.time(), 60) + 300, "epoch phase")
            _epoch_transition_phase(deadline)
            WD.disarm()
        except Exception as exc:
            OUT["epoch_error"] = f"{type(exc).__name__}: {exc}"
    # chaos AFTER the wall-cheap virtual phases: its compiles (the
    # reshaped kernel + warm shapes) must never starve them, and its
    # own floor keeps a budget-tight run recording "skipped" instead
    # of a watchdog kill
    chaos_floor = float(os.environ.get("BENCH_CHAOS_MIN_BUDGET_S",
                                       "600"))
    if os.environ.get("BENCH_CHAOS", "1") != "0" and run_throughput:
        if time.time() < deadline - chaos_floor:
            try:
                WD.arm(max(deadline - time.time(), 60) + 900,
                       "chaos phase")
                _chaos_phase(jax, deadline)
                WD.disarm()
            except Exception as exc:
                OUT["chaos_error"] = f"{type(exc).__name__}: {exc}"
        else:
            OUT["chaos"] = "skipped: budget"
    try:
        if run_throughput:
            _throughput_phase(jax, deadline, batches[1:], detail)
    except Exception as exc:
        OUT["error"] = f"throughput2: {type(exc).__name__}: {exc}"
        OUT["trace"] = traceback.format_exc(limit=3)
    if os.environ.get("BENCH_KZG", "1") != "0" and time.time() < deadline:
        try:
            WD.arm(max(deadline - time.time(), 60) + 300, "kzg phase")
            _kzg_phase(deadline)
            WD.disarm()
        except Exception as exc:
            OUT["kzg_error"] = f"{type(exc).__name__}: {exc}"
    # opt-in (three sequential fresh-process boots, one paying the
    # full compile wall): the AOT-store cold-start evidence
    if os.environ.get("BENCH_COLDSTART", "0") != "0":
        try:
            _coldstart_phase(deadline)
        except Exception as exc:
            OUT["coldstart_error"] = f"{type(exc).__name__}: {exc}"
    try:
        # hit/miss evidence for the whole run: a warm (second) run
        # shows hits>0 and per-shape cache_load_s instead of compile_s
        from teku_tpu.infra import compilecache
        stats = compilecache.stats()
        OUT.setdefault("compile_cache", {}).update(stats)
        from teku_tpu.ops import mxu
        OUT["mont_path"] = mxu.resolve()
    except Exception:
        pass
    try:
        # static-analysis state of the tree this run measured: finding
        # counts per checker (all zero on a clean tree) so the
        # trajectory shows the tree STAYING clean PR over PR.  Pure
        # AST, ~a second; never the reason a bench run fails.
        from teku_tpu.analysis import run_lint
        lint_report = run_lint()
        OUT["lint"] = {
            "files": lint_report.files_scanned,
            "unsuppressed": len(lint_report.unsuppressed),
            "suppressed": (len(lint_report.findings)
                           - len(lint_report.unsuppressed)),
            "unused_suppressions": len(
                lint_report.unused_suppressions),
            "by_checker": lint_report.counts(),
        }
    except Exception as exc:  # noqa: BLE001 - evidence, not the result
        OUT["lint"] = {"error": f"{type(exc).__name__}: {exc}"}
    OUT["total_s"] = round(time.time() - t_start, 1)
    # rolling trajectory: the regression gate (tools/bench_diff.py)
    # compares the latest entries across PRs
    OUT["trajectory"] = append_trajectory(OUT)
    _beat("bench_done", total_s=OUT["total_s"])
    _emit()
    # forced virtual host devices (the CPU-fallback mesh topology) can
    # abort XLA teardown AFTER the result line was emitted, turning a
    # clean run into rc 134 — same guard as `cli devnet`
    try:
        from teku_tpu.cli import _hard_exit_if_virtual_devices
        _hard_exit_if_virtual_devices(0)
    except Exception:
        pass


if __name__ == "__main__":
    main()
