"""North-star benchmark: BLS signatures verified per second per chip.

Measures the batched verification kernel (teku_tpu/ops/verify.py) on the
real device at the BASELINE.md batch sizes (1 / 64 / 512 / 4096), end to
end per dispatch: host arrays in, verdict out, device synchronized; plus
a bursty-arrival latency phase (BASELINE.md measurement config 5)
reporting attestation-verify p50/p99 through the batching service.

Prints ONE JSON line:
  {"metric": "bls_verify_sigs_per_sec", "value": <best>, "unit":
   "sigs/sec/chip", "vs_baseline": <value / 50_000>, "p50_ms": ...,
   ...detail...}

Hardened bring-up (round 2: rc=1, no JSON; round 3: in-process
jax.devices() probes hung ~25 min EACH before the fallback fired):
- backend init is probed in a kill-able SUBPROCESS with a hard deadline
  (BENCH_PROBE_TIMEOUT_S, default 60s); on timeout/failure the process
  falls back to CPU immediately so a JSON line ALWAYS comes out
  (flagged via "device"/"fallback");
- a watchdog thread force-emits the JSON and exits if any armed phase
  wedges inside the TPU runtime where signal handlers cannot run;
- every phase transition appends to BENCH_HEARTBEAT.json and stderr so
  even a SIGKILL leaves evidence of where time went;
- every phase is fenced: a failure records an "error" field for that
  phase instead of crashing the process;
- a wall-clock budget (BENCH_BUDGET_S) gates each extra compile.

vs_baseline is against the project target (>= 50k attestation sigs/sec
on one TPU v5e-1, BASELINE.md; the reference's CPU blst does ~1-2k
verifies/sec/core).  The reference measures the same surface with JMH
(reference: eth-benchmark-tests/src/jmh/java/tech/pegasys/teku/
benchmarks/BLSBenchmark.java:37-80 and ethereum/statetransition/src/jmh/
.../AggregatingSignatureVerificationServiceBenchmark.java).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import traceback

import numpy as np

OUT = {
    "metric": "bls_verify_sigs_per_sec",
    "value": 0.0,
    "unit": "sigs/sec/chip",
    "vs_baseline": 0.0,
}

_HEARTBEAT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_HEARTBEAT.json")

_emitted = False


def _emit():
    global _emitted
    if _emitted:
        return
    _emitted = True
    print(json.dumps(OUT))
    sys.stdout.flush()


def _beat(stage: str, **extra) -> None:
    """Progress evidence that survives ANY exit: a heartbeat file beside
    the repo root plus a stderr JSON line (stdout stays reserved for the
    ONE result line the driver parses).  Round 3 lost 80 minutes of
    wall clock with zero evidence of where; this makes every phase
    transition observable post-mortem."""
    beat = {"stage": stage, "t": round(time.time(), 1), **extra,
            "out_so_far": {k: OUT[k] for k in
                           ("value", "device", "fallback", "error")
                           if k in OUT}}
    line = json.dumps(beat)
    try:
        with open(_HEARTBEAT_PATH, "a") as fh:
            fh.write(line + "\n")
    except OSError:
        pass
    print(line, file=sys.stderr)
    sys.stderr.flush()


def _on_term(signum, frame):  # pragma: no cover - signal path
    """An external timeout (driver harness) must still get the JSON
    line: a TPU-side compile can block past any soft deadline, and
    round 3's first probe died JSON-less exactly this way."""
    OUT["error"] = OUT.get("error", f"killed by signal {signum} "
                                    "(budget exceeded mid-compile)")
    _emit()
    os._exit(1)


class _Watchdog:
    """A hung TPU runtime call blocks the main thread inside C, where
    Python signal handlers cannot run — round 3's jax.devices() probes
    hung ~25 minutes EACH.  This daemon thread force-emits the JSON and
    exits the process when an armed phase overruns its deadline."""

    def __init__(self):
        self._deadline = None
        self._label = ""
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def arm(self, seconds: float, label: str) -> None:
        self._label = label
        self._deadline = time.time() + seconds

    def disarm(self) -> None:
        self._deadline = None

    def _run(self):  # pragma: no cover - failure path
        while True:
            time.sleep(1.0)
            d = self._deadline
            if d is not None and time.time() > d:
                OUT["error"] = (f"watchdog: {self._label} exceeded "
                                "deadline (backend hang)")
                _beat("watchdog_fired", label=self._label)
                _emit()
                os._exit(1)


# initialized by main(): importing this module (tests do) must not
# install process-wide signal handlers or spawn the watchdog thread
WD = None


def _arm_process_guards() -> None:
    global WD
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    if WD is None:
        WD = _Watchdog()


_PROBE_CODE = ("import jax, json, sys\n"
               "d = jax.devices()[0]\n"
               "print(json.dumps({'platform': d.platform, "
               "'device': str(d)}))\n")


def _probe_backend(timeout_s: float, code: str = _PROBE_CODE):
    """Ask a SUBPROCESS what jax.devices() says, with a hard deadline.

    The probe owns the hang risk: if the axon tunnel is wedged the child
    is killed at timeout_s and this process never touches the TPU
    runtime — round 3 lost 3 x ~25 min to in-process probes that could
    not be interrupted.  Returns (platform, device_str) or (None, why)."""
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            start_new_session=True, text=True)
    except OSError as exc:
        return None, f"probe spawn failed: {exc}"
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            proc.kill()
        proc.wait()
        return None, f"probe timeout after {timeout_s:.0f}s"
    if proc.returncode != 0:
        tail = (err or "").strip().splitlines()[-1:] or ["no stderr"]
        return None, f"probe rc={proc.returncode}: {tail[0][:200]}"
    try:
        info = json.loads(out.strip().splitlines()[-1])
        return info["platform"], info["device"]
    except (ValueError, KeyError, IndexError):
        return None, f"probe emitted garbage: {out[:120]!r}"


def _init_device():
    """Bring up a JAX backend without ever letting a wedged TPU tunnel
    eat the budget: subprocess probe with a hard deadline first, CPU
    fallback immediately on probe failure, watchdog on the in-process
    init that follows a successful probe."""
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "60"))
    t0 = time.time()
    _beat("probe_start", timeout_s=probe_timeout)
    platform, detail = _probe_backend(probe_timeout)
    OUT["probe_s"] = round(time.time() - t0, 1)
    if platform is None:
        # fast-fail to CPU: the env var must be set BEFORE jax imports
        os.environ["JAX_PLATFORMS"] = "cpu"
        OUT["fallback"] = f"tpu init failed: {detail}"
        _beat("probe_failed", why=detail)
    else:
        _beat("probe_ok", platform=platform, device=detail)

    # the probe proved (or disproved) the backend in a disposable
    # process; the in-process init after a good probe should be quick,
    # but the tunnel can still wedge between the two — watchdog it
    WD.arm(max(probe_timeout * 2, 120), "in-process backend init")
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    # persistent compile cache: repeat bench invocations skip the
    # 20-40s-per-bucket XLA compiles (one definition, shared with the
    # driver entry hooks)
    from __graft_entry__ import _wire_compile_cache
    _wire_compile_cache()
    devs = jax.devices()
    WD.disarm()
    OUT["device"] = str(devs[0])
    _beat("device_ready", device=OUT["device"])
    return jax


def _throughput_phase(jax, deadline, batches):
    """Batches are tried IN ORDER and each fresh compile is gated on
    the remaining budget: TPU-XLA compiles of the full kernel run tens
    of minutes cold (hash-to-G2 alone is ~8 min), so one measured
    number at the primary shape beats four JSON-less timeouts.  The
    persistent compile cache makes warm reruns cheap."""
    import __graft_entry__ as ge
    from teku_tpu.ops import verify as V

    kernel = V.verify_staged     # five bounded compiles, not one monolith
    detail = {}
    best = 0.0
    best_batch = None
    compiled_once = False
    for n in batches:
        remaining = deadline - time.time()
        # a cold compile needs a wide margin; after one shape compiled
        # (cache siblings share most of the work server-side) be braver
        need = 120 if compiled_once else 600
        if remaining < need and detail:
            detail[str(n)] = "skipped: budget"
            continue
        try:
            args = ge._example_batch(n)
            stage_s = {}

            def _on_stage(nm, s, _n=n, _st=stage_s):
                _st[nm] = round(s, 1)
                _beat("stage_done", batch=_n, stage_name=nm,
                      s=round(s, 1))

            # stage-by-stage warm/compile, watchdogged: each of the five
            # staged programs must land within the phase's own margin
            _beat("compile_start", batch=n)
            WD.arm(max(remaining, need) + 120, f"compile batch {n}")
            t0 = time.time()
            ok, lane_ok = kernel(*args, on_stage=_on_stage)
            ok = bool(np.asarray(ok))
            WD.disarm()
            compile_s = time.time() - t0
            compiled_once = True
            entry = {"compile_s": round(compile_s, 1),
                     "stage_s": stage_s}
            detail[str(n)] = entry
            if not (ok and np.asarray(lane_ok).all()):
                entry["error"] = "batch did not verify"
                continue
            iters = max(1, min(30, int(200 / max(n / 64, 1))))
            WD.arm(max(deadline - time.time(), 60) + 120,
                   f"measure batch {n}")
            t0 = time.time()
            for _ in range(iters):
                ok, lane_ok = kernel(*args)
            jax.block_until_ready((ok, lane_ok))
            WD.disarm()
            dt = (time.time() - t0) / iters
            rate = n / dt
            entry["sigs_per_sec"] = round(rate, 1)
            entry["dispatch_ms"] = round(dt * 1e3, 2)
            _beat("batch_measured", batch=n,
                  sigs_per_sec=entry["sigs_per_sec"])
            if rate > best:
                best, best_batch = rate, n
            # keep the headline current so even a SIGTERM mid-phase
            # reports the best number measured so far
            OUT["detail"] = detail
            OUT["best_batch"] = best_batch
            OUT["value"] = round(best, 1)
            OUT["vs_baseline"] = round(best / 50_000, 4)
        except Exception as exc:
            detail[str(n)] = {"error": f"{type(exc).__name__}: {exc}"}
    OUT["detail"] = detail
    OUT["best_batch"] = best_batch
    OUT["value"] = round(best, 1)
    OUT["vs_baseline"] = round(best / 50_000, 4)


def _latency_phase(jax, deadline):
    """Slot-burst replay through AggregatingSignatureVerificationService:
    Poisson-bursty single-attestation tasks, p50/p99 task latency."""
    import asyncio
    import secrets

    from teku_tpu.crypto import bls
    from teku_tpu.crypto.bls import keygen
    from teku_tpu.ops.provider import JaxBls12381
    from teku_tpu.services.signatures import (
        AggregatingSignatureVerificationService)

    # min_bucket=256 pins EVERY service dispatch to the one 256-lane
    # shape the throughput phase already compiled — no extra kernel
    # compiles in this phase (only the small pubkey-validation program)
    impl = JaxBls12381(max_batch=256, min_bucket=256)
    bls.set_implementation(impl)
    try:
        sks = [keygen(bytes([i + 1]) * 32) for i in range(16)]
        pks = [impl.secret_key_to_public_key(sk) for sk in sks]
        msgs = [b"att-%d" % i for i in range(16)]
        sigs = [impl.sign(sk, m) for sk, m in zip(sks, msgs)]
        # one warm dispatch (256-lane bucket + pk validation compile)
        triples = [([pks[i % 16]], msgs[i % 16], sigs[i % 16])
                   for i in range(256)]
        t0 = time.time()
        if not impl.batch_verify(triples):
            raise RuntimeError("warmup batch failed")
        OUT["warm_compile_s"] = round(time.time() - t0, 1)

        lat: list = []

        async def run():
            svc = AggregatingSignatureVerificationService(
                num_workers=2, max_batch_size=256)
            await svc.start()
            rng = np.random.default_rng(3)
            # one slot-boundary burst: ~500 attestations arriving in
            # ~200ms (BASELINE config 5 scaled to bench budget)
            n_msgs = 500
            pending = []
            for i in range(n_msgs):
                j = i % 16
                t_submit = time.perf_counter()
                fut = svc.verify([pks[j]], msgs[j], sigs[j])
                pending.append((t_submit, fut))
                await asyncio.sleep(float(rng.exponential(0.0004)))
            for t_submit, fut in pending:
                okv = await fut
                assert okv
                lat.append(time.perf_counter() - t_submit)
            await svc.stop()

        asyncio.run(run())
        lat_ms = np.asarray(sorted(lat)) * 1e3
        OUT["p50_ms"] = round(float(np.percentile(lat_ms, 50)), 2)
        OUT["p99_ms"] = round(float(np.percentile(lat_ms, 99)), 2)
        OUT["latency_tasks"] = len(lat_ms)
    finally:
        bls.reset_implementation()


def _epoch_transition_phase(deadline):
    """Altair epoch transition on a synthetic large-validator state —
    the reference's EpochTransitionBenchmark surface (eth-benchmark-
    tests/.../EpochTransitionBenchmark.java runs the same measurement
    against generated 300k+ validator states).  Pure host-side state
    math: independent of the accelerator backend."""
    from teku_tpu.spec import perf as P
    from teku_tpu.spec.altair import epoch as AE

    n = int(os.environ.get("BENCH_EPOCH_VALIDATORS", "100000"))
    cfg = P.perf_config()
    _beat("epoch_phase_start", validators=n)
    state = P.make_synthetic_altair_state(cfg, n)
    best = None
    runs = 0
    for _ in range(3):
        if time.time() > deadline:
            break
        t0 = time.time()
        AE.process_epoch(cfg, state)
        dt = (time.time() - t0) * 1e3
        best = dt if best is None else min(best, dt)
        runs += 1
    if best is not None:
        OUT["epoch_transition_ms"] = round(best, 1)
        OUT["epoch_transition_validators"] = n
        OUT["epoch_transition_runs"] = runs
        _beat("epoch_phase_done", ms=round(best, 1))


def main():
    t_start = time.time()
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    deadline = t_start + budget_s
    _arm_process_guards()
    try:
        os.unlink(_HEARTBEAT_PATH)   # fresh evidence trail per run
    except OSError:
        pass
    _beat("bench_start", budget_s=budget_s)
    # 256 first: it doubles as the latency phase's service bucket
    batches = [int(b) for b in
               os.environ.get("BENCH_BATCHES", "256,4096,64,1").split(",")]
    try:
        jax = _init_device()
    except Exception as exc:
        OUT["error"] = f"device init: {type(exc).__name__}: {exc}"
        _emit()
        return
    try:
        _throughput_phase(jax, deadline, batches)
    except Exception as exc:
        OUT["error"] = f"throughput: {type(exc).__name__}: {exc}"
        OUT["trace"] = traceback.format_exc(limit=3)
    if os.environ.get("BENCH_P50", "1") != "0" and time.time() < deadline:
        try:
            _beat("latency_phase_start")
            WD.arm(max(deadline - time.time(), 60) + 300, "latency phase")
            _latency_phase(jax, deadline)
            WD.disarm()
        except Exception as exc:
            OUT["p50_error"] = f"{type(exc).__name__}: {exc}"
    if os.environ.get("BENCH_EPOCH", "1") != "0":
        try:
            WD.arm(max(deadline - time.time(), 60) + 300, "epoch phase")
            _epoch_transition_phase(deadline)
            WD.disarm()
        except Exception as exc:
            OUT["epoch_error"] = f"{type(exc).__name__}: {exc}"
    OUT["total_s"] = round(time.time() - t_start, 1)
    _beat("bench_done", total_s=OUT["total_s"])
    _emit()


if __name__ == "__main__":
    main()
