"""North-star benchmark: BLS signatures verified per second per chip.

Measures the batched verification kernel (teku_tpu/ops/verify.py) on the
real device at the BASELINE.md batch sizes (1 / 64 / 512 / 4096), end to
end per dispatch: host arrays in, verdict out, device synchronized.

Prints ONE JSON line:
  {"metric": "bls_verify_sigs_per_sec", "value": <best>, "unit":
   "sigs/sec/chip", "vs_baseline": <value / 50_000>, ...detail...}

vs_baseline is against the project target (>= 50k attestation sigs/sec on
one TPU v5e-1, BASELINE.md; the reference's CPU blst does ~1-2k
verifies/sec/core).  The reference measures the same surface with JMH
(reference: eth-benchmark-tests/src/jmh/java/tech/pegasys/teku/
benchmarks/BLSBenchmark.java:37-80).
"""

import json
import os
import sys
import time

import numpy as np


def main():
    t_start = time.time()
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    batches = [int(b) for b in
               os.environ.get("BENCH_BATCHES", "1,64,512,4096").split(",")]

    import jax

    import __graft_entry__ as ge
    from teku_tpu.ops import verify as V

    kernel = jax.jit(V.verify_kernel)
    detail = {}
    best = 0.0
    best_batch = None
    for n in batches:
        if time.time() - t_start > budget_s and detail:
            detail[str(n)] = "skipped: budget"
            continue
        args = ge._example_batch(n)
        # warm-up (compile)
        t0 = time.time()
        ok, sig_ok = kernel(*args)
        ok = bool(np.asarray(ok))
        compile_s = time.time() - t0
        assert ok and np.asarray(sig_ok).all(), f"batch {n} did not verify"
        # timed steady-state dispatches
        iters = max(1, min(30, int(200 / max(n / 64, 1))))
        t0 = time.time()
        for _ in range(iters):
            ok, sig_ok = kernel(*args)
        jax.block_until_ready((ok, sig_ok))
        dt = (time.time() - t0) / iters
        rate = n / dt
        detail[str(n)] = {"sigs_per_sec": round(rate, 1),
                          "dispatch_ms": round(dt * 1e3, 2),
                          "compile_s": round(compile_s, 1)}
        if rate > best:
            best, best_batch = rate, n

    out = {
        "metric": "bls_verify_sigs_per_sec",
        "value": round(best, 1),
        "unit": "sigs/sec/chip",
        "vs_baseline": round(best / 50_000, 4),
        "best_batch": best_batch,
        "device": str(jax.devices()[0]),
        "detail": detail,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
