"""North-star benchmark: BLS signatures verified per second per chip.

Measures the batched verification kernel (teku_tpu/ops/verify.py) on the
real device at the BASELINE.md batch sizes (1 / 64 / 512 / 4096), end to
end per dispatch: host arrays in, verdict out, device synchronized; plus
a bursty-arrival latency phase (BASELINE.md measurement config 5)
reporting attestation-verify p50/p99 through the batching service.

Prints ONE JSON line:
  {"metric": "bls_verify_sigs_per_sec", "value": <best>, "unit":
   "sigs/sec/chip", "vs_baseline": <value / 50_000>, "p50_ms": ...,
   ...detail...}

Hardened bring-up (round 2 failed with rc=1 and no JSON at all):
- device init is retried with backoff, then falls back to CPU so a JSON
  line ALWAYS comes out (flagged via "device"/"fallback");
- every phase is fenced: a failure records an "error" field for that
  phase instead of crashing the process;
- a wall-clock budget (BENCH_BUDGET_S) gates each extra compile.

vs_baseline is against the project target (>= 50k attestation sigs/sec
on one TPU v5e-1, BASELINE.md; the reference's CPU blst does ~1-2k
verifies/sec/core).  The reference measures the same surface with JMH
(reference: eth-benchmark-tests/src/jmh/java/tech/pegasys/teku/
benchmarks/BLSBenchmark.java:37-80 and ethereum/statetransition/src/jmh/
.../AggregatingSignatureVerificationServiceBenchmark.java).
"""

import json
import os
import signal
import sys
import time
import traceback

import numpy as np

OUT = {
    "metric": "bls_verify_sigs_per_sec",
    "value": 0.0,
    "unit": "sigs/sec/chip",
    "vs_baseline": 0.0,
}

_emitted = False


def _emit():
    global _emitted
    if _emitted:
        return
    _emitted = True
    print(json.dumps(OUT))
    sys.stdout.flush()


def _on_term(signum, frame):  # pragma: no cover - signal path
    """An external timeout (driver harness) must still get the JSON
    line: a TPU-side compile can block past any soft deadline, and
    round 3's first probe died JSON-less exactly this way."""
    OUT["error"] = OUT.get("error", f"killed by signal {signum} "
                                    "(budget exceeded mid-compile)")
    _emit()
    os._exit(1)


signal.signal(signal.SIGTERM, _on_term)
signal.signal(signal.SIGINT, _on_term)


def _init_device():
    """Initialize a JAX backend, retrying the TPU tunnel with backoff and
    falling back to CPU rather than dying (round 2's failure mode)."""
    import jax

    # persistent compile cache: repeat bench invocations skip the
    # 20-40s-per-bucket XLA compiles (one definition, shared with the
    # driver entry hooks)
    from __graft_entry__ import _wire_compile_cache
    _wire_compile_cache()

    last = None
    for attempt in range(3):
        try:
            devs = jax.devices()
            OUT["device"] = str(devs[0])
            return jax
        except Exception as exc:  # backend init failure
            last = exc
            time.sleep(15 * (attempt + 1))
    # fall back to CPU so the harness still produces a number
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    devs = jax.devices()
    OUT["device"] = str(devs[0])
    OUT["fallback"] = f"tpu init failed: {type(last).__name__}: {last}"
    return jax


def _throughput_phase(jax, deadline, batches):
    """Batches are tried IN ORDER and each fresh compile is gated on
    the remaining budget: TPU-XLA compiles of the full kernel run tens
    of minutes cold (hash-to-G2 alone is ~8 min), so one measured
    number at the primary shape beats four JSON-less timeouts.  The
    persistent compile cache makes warm reruns cheap."""
    import __graft_entry__ as ge
    from teku_tpu.ops import verify as V

    kernel = V.verify_staged     # five bounded compiles, not one monolith
    detail = {}
    best = 0.0
    best_batch = None
    compiled_once = False
    for n in batches:
        remaining = deadline - time.time()
        # a cold compile needs a wide margin; after one shape compiled
        # (cache siblings share most of the work server-side) be braver
        need = 120 if compiled_once else 600
        if remaining < need and detail:
            detail[str(n)] = "skipped: budget"
            continue
        try:
            args = ge._example_batch(n)
            stage_s = {}
            t0 = time.time()
            ok, lane_ok = kernel(
                *args,
                on_stage=lambda nm, s: stage_s.__setitem__(
                    nm, round(s, 1)))
            ok = bool(np.asarray(ok))
            compile_s = time.time() - t0
            compiled_once = True
            entry = {"compile_s": round(compile_s, 1),
                     "stage_s": stage_s}
            detail[str(n)] = entry
            if not (ok and np.asarray(lane_ok).all()):
                entry["error"] = "batch did not verify"
                continue
            iters = max(1, min(30, int(200 / max(n / 64, 1))))
            t0 = time.time()
            for _ in range(iters):
                ok, lane_ok = kernel(*args)
            jax.block_until_ready((ok, lane_ok))
            dt = (time.time() - t0) / iters
            rate = n / dt
            entry["sigs_per_sec"] = round(rate, 1)
            entry["dispatch_ms"] = round(dt * 1e3, 2)
            if rate > best:
                best, best_batch = rate, n
            # keep the headline current so even a SIGTERM mid-phase
            # reports the best number measured so far
            OUT["detail"] = detail
            OUT["best_batch"] = best_batch
            OUT["value"] = round(best, 1)
            OUT["vs_baseline"] = round(best / 50_000, 4)
        except Exception as exc:
            detail[str(n)] = {"error": f"{type(exc).__name__}: {exc}"}
    OUT["detail"] = detail
    OUT["best_batch"] = best_batch
    OUT["value"] = round(best, 1)
    OUT["vs_baseline"] = round(best / 50_000, 4)


def _latency_phase(jax, deadline):
    """Slot-burst replay through AggregatingSignatureVerificationService:
    Poisson-bursty single-attestation tasks, p50/p99 task latency."""
    import asyncio
    import secrets

    from teku_tpu.crypto import bls
    from teku_tpu.crypto.bls import keygen
    from teku_tpu.ops.provider import JaxBls12381
    from teku_tpu.services.signatures import (
        AggregatingSignatureVerificationService)

    # min_bucket=256 pins EVERY service dispatch to the one 256-lane
    # shape the throughput phase already compiled — no extra kernel
    # compiles in this phase (only the small pubkey-validation program)
    impl = JaxBls12381(max_batch=256, min_bucket=256)
    bls.set_implementation(impl)
    try:
        sks = [keygen(bytes([i + 1]) * 32) for i in range(16)]
        pks = [impl.secret_key_to_public_key(sk) for sk in sks]
        msgs = [b"att-%d" % i for i in range(16)]
        sigs = [impl.sign(sk, m) for sk, m in zip(sks, msgs)]
        # one warm dispatch (256-lane bucket + pk validation compile)
        triples = [([pks[i % 16]], msgs[i % 16], sigs[i % 16])
                   for i in range(256)]
        t0 = time.time()
        if not impl.batch_verify(triples):
            raise RuntimeError("warmup batch failed")
        OUT["warm_compile_s"] = round(time.time() - t0, 1)

        lat: list = []

        async def run():
            svc = AggregatingSignatureVerificationService(
                num_workers=2, max_batch_size=256)
            await svc.start()
            rng = np.random.default_rng(3)
            # one slot-boundary burst: ~500 attestations arriving in
            # ~200ms (BASELINE config 5 scaled to bench budget)
            n_msgs = 500
            pending = []
            for i in range(n_msgs):
                j = i % 16
                t_submit = time.perf_counter()
                fut = svc.verify([pks[j]], msgs[j], sigs[j])
                pending.append((t_submit, fut))
                await asyncio.sleep(float(rng.exponential(0.0004)))
            for t_submit, fut in pending:
                okv = await fut
                assert okv
                lat.append(time.perf_counter() - t_submit)
            await svc.stop()

        asyncio.run(run())
        lat_ms = np.asarray(sorted(lat)) * 1e3
        OUT["p50_ms"] = round(float(np.percentile(lat_ms, 50)), 2)
        OUT["p99_ms"] = round(float(np.percentile(lat_ms, 99)), 2)
        OUT["latency_tasks"] = len(lat_ms)
    finally:
        bls.reset_implementation()


def main():
    t_start = time.time()
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    deadline = t_start + budget_s
    # 256 first: it doubles as the latency phase's service bucket
    batches = [int(b) for b in
               os.environ.get("BENCH_BATCHES", "256,4096,64,1").split(",")]
    try:
        jax = _init_device()
    except Exception as exc:
        OUT["error"] = f"device init: {type(exc).__name__}: {exc}"
        _emit()
        return
    try:
        _throughput_phase(jax, deadline, batches)
    except Exception as exc:
        OUT["error"] = f"throughput: {type(exc).__name__}: {exc}"
        OUT["trace"] = traceback.format_exc(limit=3)
    if os.environ.get("BENCH_P50", "1") != "0" and time.time() < deadline:
        try:
            _latency_phase(jax, deadline)
        except Exception as exc:
            OUT["p50_error"] = f"{type(exc).__name__}: {exc}"
    OUT["total_s"] = round(time.time() - t_start, 1)
    _emit()


if __name__ == "__main__":
    main()
